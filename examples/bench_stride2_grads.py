"""Stride-2 input-grad layout probe (docs/MFU_ANALYSIS.md category 3).

ResNet-50's three stage-transition 3x3/stride-2 convolutions transpose to
fractionally-strided convs in the backward pass — scattered writes with
poor MXU tiling at exactly the layers carrying the most channels.  The
space-to-depth identity that fixed the stem (models/resnet.py:
``s2d_stem_kernel``) generalizes: a 3x3/2 conv with SAME padding equals a
2x2/1 conv over 2x2-packed input with a front-padded [2,2,4C,F] kernel,
whose input-grad is a *dense* stride-1 transpose.

This probe times forward+backward of each downsample conv in both
formulations (including the space-to-depth transform cost on the s2d
side — in the full model it would have to fuse or be materialized), and
checks they compute the same function.  The measured deltas decide
whether a ``downsample_s2d`` model variant is worth building.

Run on the real chip: ``python examples/bench_stride2_grads.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np

from stochastic_gradient_push_tpu.models.resnet import space_to_depth
from stochastic_gradient_push_tpu.utils.profiling import fenced_ms

BATCH = 128
# (spatial, C_in, C_out) of the three bottleneck stage-transition 3x3/2
# convs at ImageNet shapes
SHAPES = [(56, 128, 128), (28, 256, 256), (14, 512, 512)]


def s2d_kernel_3x3(k3: jnp.ndarray) -> jnp.ndarray:
    """[3,3,C,F] stride-2 SAME kernel -> [2,2,4C,F] stride-1 kernel over
    space-to-depth input with block-space padding (1, 0)."""
    c, f = k3.shape[2], k3.shape[3]
    k4 = jnp.pad(k3, ((1, 0), (1, 0), (0, 0), (0, 0)))  # [4,4,C,F]
    k2 = k4.reshape(2, 2, 2, 2, c, f).transpose(0, 2, 1, 3, 4, 5)
    return k2.reshape(2, 2, 4 * c, f)


def conv_orig(x, k):
    # pure-bf16 conv, as the model's flax convs run it; a float32
    # preferred_element_type here breaks the VJP (the transpose conv
    # gets an fp32 cotangent against the bf16 kernel and
    # conv_general_dilated requires matching dtypes)
    return jax.lax.conv_general_dilated(
        x, k, window_strides=(2, 2), padding=[(1, 1), (1, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def conv_s2d(x, k2):
    xs = space_to_depth(x, 2)
    return jax.lax.conv_general_dilated(
        xs, k2, window_strides=(1, 1), padding=[(1, 0), (1, 0)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def timeit(fn, *args, steps=20):
    return fenced_ms(fn, *args, steps=steps)


def main():
    print(f"device: {jax.devices()[0].device_kind}", flush=True)
    rows = []
    for spatial, cin, cout in SHAPES:
        key = jax.random.PRNGKey(0)
        x = jax.random.normal(
            key, (BATCH, spatial, spatial, cin), jnp.bfloat16)
        k3 = (jax.random.normal(key, (3, 3, cin, cout), jnp.float32)
              * 0.05).astype(jnp.bfloat16)
        k2 = s2d_kernel_3x3(k3)

        # equivalence check (bf16 conv outputs compared in fp32)
        y0 = np.asarray(conv_orig(x, k3), np.float32)
        y1 = np.asarray(conv_s2d(x, k2), np.float32)
        err = float(np.max(np.abs(y0 - y1)) / (np.max(np.abs(y0)) + 1e-9))
        assert err < 5e-2, (
            f"s2d formulation diverged (rel_err {err:.3e}) — timings "
            "below would compare different functions")

        def loss_orig(x, k):
            return jnp.sum(jnp.square(conv_orig(x, k)))

        def loss_s2d(x, k):
            return jnp.sum(jnp.square(conv_s2d(x, k)))

        g_orig = jax.jit(jax.grad(loss_orig, argnums=(0, 1)))
        g_s2d = jax.jit(jax.grad(loss_s2d, argnums=(0, 1)))
        f_orig = jax.jit(conv_orig)
        f_s2d = jax.jit(conv_s2d)

        fwd0 = timeit(f_orig, x, k3)
        fwd1 = timeit(f_s2d, x, k2)
        bwd0 = timeit(g_orig, x, k3)
        bwd1 = timeit(g_s2d, x, k2)
        rows.append((spatial, cin, cout, err, fwd0, fwd1, bwd0, bwd1))
        print(f"[{spatial}x{spatial}x{cin}->{cout}] rel_err={err:.2e}  "
              f"fwd {fwd0:.3f} -> {fwd1:.3f} ms  "
              f"fwd+bwd {bwd0:.3f} -> {bwd1:.3f} ms  "
              f"bwd_speedup={bwd0 / bwd1:.2f}x", flush=True)

    tot0 = sum(r[6] for r in rows)
    tot1 = sum(r[7] for r in rows)
    print(f"TOTAL fwd+bwd over downsample convs: {tot0:.2f} -> {tot1:.2f} "
          f"ms/step ({tot0 - tot1:+.2f} ms available)", flush=True)


if __name__ == "__main__":
    main()
