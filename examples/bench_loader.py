"""Host data-loader throughput: native C++ pipeline vs pure PIL.

Generates an ImageNet-shaped synthetic JPEG folder (once, cached in
/tmp), then times train-mode decode+augment batches through both
backends and both output modes.  The native loader's edge per core comes
from the single-session libjpeg decode, windowed resampling, and the
DCT-domain fast path; its edge across cores comes from the GIL-free
std::thread pool (invisible on a 1-core host — recorded for context).

Usage: python examples/bench_loader.py        (no TPU needed)
Env: LOADERBENCH_N (images, default 96), LOADERBENCH_SIZE (output, 224).
"""

import json
import os
import time

import numpy as np

from stochastic_gradient_push_tpu.data.native import NativeDecoder, get_native

N = int(os.environ.get("LOADERBENCH_N", "96"))
SIZE = int(os.environ.get("LOADERBENCH_SIZE", "224"))
ROOT = f"/tmp/sgp_loaderbench_{N}"


def make_dataset():
    from PIL import Image

    d = os.path.join(ROOT, "c0")
    if os.path.isdir(d) and len(os.listdir(d)) >= N:
        return sorted(os.path.join(d, f) for f in os.listdir(d))[:N]
    os.makedirs(d, exist_ok=True)
    rng = np.random.default_rng(0)
    paths = []
    for i in range(N):
        # ImageNet-ish dims ~500x375, smoothed noise so JPEG size is
        # realistic
        w, h = int(rng.integers(400, 600)), int(rng.integers(300, 450))
        arr = (rng.random((h // 4, w // 4, 3)) * 255).astype(np.uint8)
        img = Image.fromarray(arr).resize((w, h), Image.BILINEAR)
        p = os.path.join(d, f"img{i:04d}.jpg")
        img.save(p, quality=90)
        paths.append(p)
    return paths


def timed(fn, reps=3):
    fn()  # warm (dims cache, native build)
    t0 = time.perf_counter()
    for _ in range(reps):
        fn()
    return (time.perf_counter() - t0) / reps


def main():
    paths = make_dataset()
    idx = np.arange(len(paths))
    threads = min(16, os.cpu_count() or 1)
    rows = []
    for backend in ("native", "pil"):
        if backend == "native" and get_native() is None:
            rows.append({"backend": "native",
                         "error": "unavailable (g++/libjpeg)"})
            continue
        for output in ("f32", "uint8"):
            dec = NativeDecoder(paths, SIZE, train=True, seed=0,
                                threads=threads)
            if backend == "pil":
                dec._native = None  # force the pure-PIL path
            dt = timed(lambda: dec.decode(idx, output=output))
            # both paths use `threads` workers (the PIL fallback decodes
            # through a ThreadPoolExecutor; PIL releases the GIL)
            rows.append({"backend": backend, "output": output,
                         "threads": threads,
                         "img_per_sec": round(len(idx) / dt, 1)})
    for r in rows:
        print(json.dumps(r), flush=True)
    nat = next((r for r in rows if r.get("backend") == "native"
                and r.get("output") == "f32"), None)
    pil = next((r for r in rows if r.get("backend") == "pil"
                and r.get("output") == "f32"), None)
    if nat and pil and "img_per_sec" in nat and "img_per_sec" in pil:
        print(json.dumps({
            "metric": "native_vs_pil_speedup",
            "value": round(nat["img_per_sec"] / pil["img_per_sec"], 2),
            "cores": os.cpu_count()}), flush=True)


if __name__ == "__main__":
    main()
