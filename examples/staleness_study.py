"""Quantify the staleness trade: compiled overlap gossip vs the
reference's host-async thread/process model.

The reference gets gossip asynchrony from wall-clock overlap — OSGP
polls a non-blocking collective for up to ``synch_freq`` steps
(distributed.py:349-352, 578), and AD-PSGD runs bilateral averaging in a
separate OS process (ad_psgd.py:120-133) — so its *effective staleness*
is hardware-dependent: roughly ``ceil(T_comm / T_step)`` steps, jittered
by the scheduler.  This framework compiles gossip into the step instead:
OSGP's staleness is an EXACT knob (a FIFO of in-flight shares), and
AD-PSGD is a synchronous perfect matching (staleness 0).  The round-3
verdict asked for data on what that reformulation changes; this study
produces it on the canonical decentralized quadratic (per-rank targets,
constant LR — the setting of the D-PSGD/SGP convergence theorems, and of
tests/test_algorithms.py):

1. **OSGP staleness sweep (real implementation)** — the compiled
   PushSumGossip at staleness δ ∈ {sync, 1, 2, 4, 8} on the 8-rank
   mesh: steady-state replica spread and distance of the consensus mean
   from the optimum.  δ is exact here; the reference's δ is a random
   variable with mean T_comm/T_step.
2. **AD-PSGD partner-staleness simulation (reference semantics)** — a
   numpy replica of bilateral averaging where the partner's parameters
   are δ steps old, δ ~ min(Geometric(p), 8) with mean matched to a
   comm/compute ratio; sweeping the ratio maps the reference's
   hardware-dependent behavior onto measurable spread/optimality
   numbers, with δ≡0 cross-checked against the compiled BilateralGossip.

Wall-clock anchor (BASELINE.md, round-2 on-chip sweep): gossip adds
≤0.7 ms to a 49.1 ms ResNet-50 step on TPU ICI → T_comm/T_step ≈ 0.014,
i.e. the reference's own model predicts δ ≈ 1 there, the regime where
the measured penalty below is negligible.  The large-δ columns model
slow interconnects (the reference's 10 Gbps Ethernet experiments).

Artifacts: docs/STALENESS_STUDY.md + docs/staleness_study.png.

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      PYTHONPATH=. python examples/staleness_study.py
"""

import json
import os

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from stochastic_gradient_push_tpu.algorithms import adpsgd, sgp
from stochastic_gradient_push_tpu.parallel import (
    GOSSIP_AXIS, make_gossip_mesh)
from stochastic_gradient_push_tpu.topology import (
    DynamicBipartiteExponentialGraph,
    NPeerDynamicDirectedExponentialGraph,
    build_pairing_schedule,
    build_schedule,
)

WORLD, DIM, STEPS, LR, TAIL = 8, 16, 500, 0.05, 100

rng = np.random.default_rng(9)
TARGETS = rng.normal(size=(WORLD, DIM)).astype(np.float32)
X0 = rng.normal(size=(WORLD, DIM)).astype(np.float32)
OPT = TARGETS.mean(axis=0)


def quad_grad(x, target):
    return x - target


def run_compiled(alg, steps=STEPS):
    """The real four-slot algorithm step on the 8-device mesh."""
    mesh = make_gossip_mesh(WORLD)

    def step(params, gstate, target):
        params, gstate = alg.pre_step(params, gstate)
        z = alg.eval_params(params, gstate)
        grads = jax.grad(lambda p: 0.5 * jnp.sum((p - target) ** 2))(z)
        grads = alg.reduce_grads(grads)
        params = params - LR * grads
        return alg.post_step(params, gstate)

    f = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS), P(GOSSIP_AXIS)),
        out_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS))))
    params = X0.copy()
    gstate = jax.tree.map(
        lambda a: np.broadcast_to(np.asarray(a),
                                  (WORLD,) + np.shape(a)).copy(),
        alg.init(jnp.zeros((DIM,), jnp.float32)))
    # drained VALIDATION view (alg.val_params): measuring on the raw
    # between-step params would inflate every spread/gap by the
    # not-yet-applied in-flight shares — the exact eval-time artifact
    # that once made OSGP look +3.4 % ppl worse in CONVERGENCE_PARITY.md
    fval = jax.jit(jax.shard_map(
        alg.val_params, mesh=mesh,
        in_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS)),
        out_specs=P(GOSSIP_AXIS)))
    spreads, gaps = [], []
    for _ in range(steps):
        params, gstate = f(params, gstate, TARGETS)
        jax.block_until_ready(params)  # serialize CPU collective dispatch
        z = np.asarray(fval(params, gstate))
        spreads.append(float(np.abs(z - z.mean(0, keepdims=True)).max()))
        gaps.append(float(np.abs(z.mean(0) - OPT).max()))
    return spreads, gaps


def run_bilat_sim(mean_delay: float, steps=STEPS, seed=3):
    """Numpy replica of the reference's AD-PSGD process model: each step
    every rank takes a local SGD step, then averages with its matched
    partner's parameters as they were ``δ`` steps ago,
    δ ~ min(Geometric(p), 8) with mean ≈ mean_delay (δ≡0 reproduces the
    synchronous matching of the compiled BilateralGossip)."""
    g = np.random.default_rng(seed)
    pairing = build_pairing_schedule(
        DynamicBipartiteExponentialGraph(WORLD))
    x = X0.copy()
    hist = []          # end-of-step states of PREVIOUS steps
    spreads, gaps = [], []
    n_phases = pairing.shape[0]
    for t in range(steps):
        x = x - LR * quad_grad(x, TARGETS)
        partners = pairing[t % n_phases]
        if mean_delay > 0:
            # geometric support starts at 1; mean 1/p
            delays = np.minimum(g.geometric(min(1.0, 1.0 / mean_delay),
                                            size=WORLD), 8)
        else:
            delays = np.zeros(WORLD, np.int64)
        # δ=0 mixes the partner's CURRENT post-update params — exactly
        # the compiled BilateralGossip's synchronous matching; δ≥1 takes
        # the partner's end-of-step state from δ steps back
        stale = np.stack([
            x[partners[i]] if d == 0 or not hist
            else hist[max(0, len(hist) - int(d))][partners[i]]
            for i, d in enumerate(delays)])
        x = 0.5 * (x + stale)
        hist.append(x.copy())
        if len(hist) > 16:
            hist.pop(0)
        spreads.append(float(np.abs(x - x.mean(0, keepdims=True)).max()))
        gaps.append(float(np.abs(x.mean(0) - OPT).max()))
    return spreads, gaps


def tail_mean(v):
    return float(np.mean(v[-TAIL:]))


ASYNC_NN_SECTION = """
## AD-PSGD: EXECUTABLE wall-clock asynchrony (round 5, real NN)

`--bilat_async` (train/async_bilat.py) now runs the reference's process
model for real: the compiled step carries no collective, a host thread
continuously computes bilateral displacements from the live params, and
the loop adopts them whenever they're ready — δ set by actual host/device
timing, measured per adoption.  TinyCNN, 8-rank mesh, 4 epochs
(/tmp recipe in tests/test_async_bilat.py + this table's driver):

| Config | mean replica spread | adoptions | measured δ (mean/max) |
|--------|--------------------:|----------:|----------------------:|
| local SGD (no averaging) | 2.46e-3 | — | — |
| sync matchings (compiled AD-PSGD) | 1.71e-4 | — | δ≡0 by construction |
| async, unpaced | 3.10e-4 | 31/32 rounds | 1.0 / 1 |
| async, ≥0.1 s/round | 1.28e-3 | 16 | 1.19 / 2 |
| async, ≥0.4 s/round | 2.43e-3 | 2 | 1.0 / 1 |

Unpaced host averaging holds replicas within ~1.8x of the synchronous
matching's consensus — at a measured staleness of one step, exactly the
δ ≈ 1 regime the wall-clock anchor below predicts for fast interconnects.
Throttling the averaging thread (emulating a slow averaging path) walks
consensus monotonically back toward local SGD, the NN-scale confirmation
of the quadratic sim's dose-response above.

"""


def main():
    schedule = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))

    osgp_rows = []
    curves = {}
    configs = [("SGP (sync, δ=0)", sgp(schedule, GOSSIP_AXIS))]
    for d in (1, 2, 4, 8):
        configs.append((f"OSGP δ={d}",
                        sgp(schedule, GOSSIP_AXIS, overlap=True,
                            staleness=d)))
    for name, alg in configs:
        spreads, gaps = run_compiled(alg)
        osgp_rows.append((name, tail_mean(spreads), tail_mean(gaps)))
        curves[name] = spreads
        print(f"{name}: spread {tail_mean(spreads):.4f} "
              f"opt-gap {tail_mean(gaps):.4f}", flush=True)

    # compiled synchronous AD-PSGD — the product path the sim must match
    sp, gp = run_compiled(adpsgd(
        build_pairing_schedule(DynamicBipartiteExponentialGraph(WORLD)),
        GOSSIP_AXIS))
    bilat_rows = [("AD-PSGD compiled (sync matchings)",
                   tail_mean(sp), tail_mean(gp))]
    for mean_delay in (0, 1, 2, 4):
        spreads, gaps = run_bilat_sim(mean_delay)
        label = ("AD-PSGD sim δ≡0" if mean_delay == 0 else
                 f"AD-PSGD sim E[δ]≈{mean_delay}")
        bilat_rows.append((label, tail_mean(spreads), tail_mean(gaps)))
        print(f"{label}: spread {tail_mean(spreads):.4f} "
              f"opt-gap {tail_mean(gaps):.4f}", flush=True)

    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    palette = ["#2a78d6", "#eb6834", "#1baf7a", "#eda100", "#e87ba4"]
    fig, ax = plt.subplots(figsize=(7.5, 4.5), dpi=150)
    for (name, curve), color in zip(curves.items(), palette):
        ax.plot(curve, color=color, linewidth=1.6, label=name)
    ax.set_yscale("log")
    ax.set_xlabel("step")
    ax.set_ylabel("replica spread (max |zᵢ − z̄|, log)")
    ax.set_title("Spread under exact staleness: compiled push-sum, "
                 "8-rank mesh, constant LR")
    ax.grid(True, color="#eeeeee", linewidth=0.8)
    ax.spines[["top", "right"]].set_visible(False)
    ax.legend(frameon=False, fontsize=8)
    fig.tight_layout()
    fig.savefig("docs/staleness_study.png")

    with open("docs/STALENESS_STUDY.md", "w") as f:
        f.write(
            "# Staleness, measured\n\n"
            "What the synchronous/compiled reformulation of the "
            "reference's host-async gossip actually changes, on the "
            "canonical decentralized quadratic (per-rank targets, "
            f"{WORLD} ranks, constant LR {LR}, steady-state = mean of "
            f"the last {TAIL} of {STEPS} steps; "
            "examples/staleness_study.py — re-run to regenerate).\n\n"
            "## OSGP: exact staleness knob (real implementation)\n\n"
            "The reference's overlap staleness is a hardware random "
            "variable (non-blocking poll, distributed.py:349-352); here "
            "it is an exact FIFO depth.  Cost of each extra step of "
            "staleness:\n\n"
            "| Config | steady-state spread | opt gap |\n"
            "|--------|--------------------:|--------:|\n")
        for name, s, gap in osgp_rows:
            f.write(f"| {name} | {s:.4f} | {gap:.4f} |\n")
        f.write(
            "\nδ=1 is *exactly* free: the incoming share is computed "
            "from same-step peers and merely applied one step-boundary "
            "later, so the drained validation view coincides with sync "
            "SGP (`test_osgp_val_params_drains_to_sync`).  Spreads are "
            "measured on `val_params` — the drained eval view matching "
            "the reference's `model.eval()` gossip drain "
            "(distributed.py:322-327).  An earlier revision measured "
            "the undrained between-step parameters and overstated "
            "every δ's cost 2-3× (δ=1 read 0.2162, δ=8 read 0.9075): "
            "that inflation was the in-flight share validation would "
            "have applied, not a property of staleness.\n"
            "\n![spread curves](staleness_study.png)\n\n"
            "## AD-PSGD: synchronous matchings vs the process model\n\n"
            "The compiled formulation is the δ≡0 row; the sim rows "
            "replay the reference's separate-process semantics "
            "(ad_psgd.py:120-133) with partner parameters "
            "δ ~ min(Geom, 8) steps stale:\n\n"
            "| Config | steady-state spread | opt gap |\n"
            "|--------|--------------------:|--------:|\n")
        for name, s, gap in bilat_rows:
            f.write(f"| {name} | {s:.4f} | {gap:.4f} |\n")
        # recorded by the async_bilat NN driver (round 5), not this
        # script — kept here so regeneration preserves the section
        f.write(ASYNC_NN_SECTION)
        f.write(
            "\n## Reading the numbers\n\n"
            "- Spread grows with staleness (stale mixing is a weaker "
            "contraction), while the consensus mean stays near the "
            "optimum — matching the bounded-staleness theory the "
            "reference's paper leans on.\n"
            "- The sim's δ≡0 row lands on the compiled AD-PSGD's "
            "numbers, validating that the synchronous matching IS the "
            "zero-staleness limit of the reference's process model.\n"
            "- Wall-clock anchor: on TPU ICI the measured gossip cost "
            "is ≤0.7 ms against a 49.1 ms step (BASELINE.md round-2 "
            "sweep), so the reference's own timing model predicts "
            "δ ≈ 1 there — the regime where the table shows the "
            "penalty is small.  Large δ models slow interconnects; if "
            "that regime matters, OSGP's exact-δ FIFO reproduces it "
            "deterministically inside the compiled step.\n")
    print(json.dumps({"osgp": osgp_rows, "bilat": bilat_rows}), flush=True)


if __name__ == "__main__":
    main()
