"""Minimal custom training loop: bring your own model and data, use the
algorithm/collective layers directly (no Trainer).

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      PYTHONPATH=.. python custom_training_loop.py
"""

import os

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import stochastic_gradient_push_tpu as sgp
from stochastic_gradient_push_tpu.algorithms import sgp as make_sgp
from stochastic_gradient_push_tpu.parallel import GOSSIP_AXIS, make_gossip_mesh

world = jax.device_count()
mesh = make_gossip_mesh(world)
schedule = sgp.build_schedule(
    sgp.DynamicDirectedExponentialGraph(world, peers_per_itr=1))
alg = make_sgp(schedule, GOSSIP_AXIS)

# per-rank least-squares problems; the consensus optimum is their average
rng = np.random.default_rng(0)
A = rng.normal(size=(world, 32, 6)).astype(np.float32)
b = rng.normal(size=(world, 32)).astype(np.float32)


def step(params, gstate, a, y):
    a, y = a[0], y[0]
    params, gstate = alg.pre_step(params, gstate)
    z = alg.eval_params(params, gstate)
    grads = jax.grad(
        lambda p: jnp.mean((a @ jnp.reshape(p, (-1,)) - y) ** 2))(z)
    params = params - 0.05 * jnp.reshape(grads, jnp.shape(params))
    return alg.post_step(params, gstate)


train = jax.jit(jax.shard_map(
    step, mesh=mesh, in_specs=(P(GOSSIP_AXIS),) * 4,
    out_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS))))

params = np.zeros((world, 6), np.float32)
gstate = jax.tree.map(
    lambda t: np.broadcast_to(np.asarray(t), (world,) + np.shape(t)).copy(),
    alg.init(jnp.zeros((6,), jnp.float32)))

for i in range(400):
    params, gstate = jax.block_until_ready(train(params, gstate, A, b))

z = np.asarray(params) / np.asarray(gstate.ps_weight).reshape(world, 1)
spread = np.abs(z - z.mean(0)).max()
print(f"trained {world} gossip ranks; cross-rank spread {spread:.2e}")
