"""Distributed averaging without a model (reference README's standalone
Gossiper use case).

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      PYTHONPATH=.. python standalone_averaging.py
"""

import os

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

import stochastic_gradient_push_tpu as sgp
from stochastic_gradient_push_tpu.parallel import (
    consensus_error,
    make_gossip_mesh,
    push_sum_average,
)

world = jax.device_count()
mesh = make_gossip_mesh(world)
schedule = sgp.build_schedule(
    sgp.NPeerDynamicDirectedExponentialGraph(world, peers_per_itr=1))

# each rank holds a different measurement; we want every rank to learn the mean
values = np.random.default_rng(0).normal(size=(world, 10)).astype(np.float32)
print(f"before: consensus error {consensus_error(values):.4f}")

averaged = push_sum_average(values, mesh, schedule, rounds=40)
print(f"after : consensus error {consensus_error(averaged):.2e}")
print(f"true mean recovered: "
      f"{np.allclose(np.asarray(averaged)[0], values.mean(0), atol=1e-4)}")
