"""Convergence-parity study: AR vs SGP vs OSGP(×staleness) vs D-PSGD vs
AD-PSGD through the full Trainer stack on the 8-rank virtual CPU mesh.

Quantifies the staleness trade (SURVEY.md §7 hard parts #3-4): overlap
mode delays gossip consumption by synch_freq+1 steps, and AD-PSGD replaces
host-async bilateral averaging with synchronous perfect matchings.  Each
config trains the same TinyCNN on the same synthetic data; the artifact is
a per-epoch validation-accuracy figure + final-accuracy table
(docs/convergence_parity.png, docs/CONVERGENCE_PARITY.md).

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      PYTHONPATH=. python examples/convergence_parity.py
"""

import os

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

from stochastic_gradient_push_tpu.data import (
    DistributedSampler,
    ShardedLoader,
    synthetic_classification,
)
from stochastic_gradient_push_tpu.models import TinyCNN
from stochastic_gradient_push_tpu.parallel import make_gossip_mesh
from stochastic_gradient_push_tpu.topology import (
    DynamicBipartiteExponentialGraph,
    NPeerDynamicDirectedExponentialGraph,
)
from stochastic_gradient_push_tpu.train.loop import Trainer, TrainerConfig

WORLD, BATCH, CLASSES, IMG = 8, 8, 40, 12
EPOCHS = 24
THRESH = 90.0

# fixed-order categorical palette (validated; see dataviz palette.md)
PALETTE = ["#2a78d6", "#eb6834", "#1baf7a", "#eda100", "#e87ba4",
           "#008300"]

CONFIGS = [
    ("AR", dict(all_reduce=True, graph_class=None)),
    ("SGP", dict(push_sum=True)),
    ("OSGP", dict(push_sum=True, overlap=True)),
    ("OSGP sf=2", dict(push_sum=True, overlap=True, synch_freq=2)),
    ("D-PSGD", dict(push_sum=False,
                    graph_class=DynamicBipartiteExponentialGraph)),
    ("AD-PSGD", dict(bilat=True,
                     graph_class=DynamicBipartiteExponentialGraph)),
]


def run_config(name, overrides, data, out_dir):
    images, labels, val_images, val_labels = data
    kwargs = dict(
        graph_class=NPeerDynamicDirectedExponentialGraph,
        lr=0.15, warmup=False, lr_schedule={20: 0.1},
        num_iterations_per_training_epoch=8,
        batch_size=BATCH, num_epochs=EPOCHS, num_itr_ignore=0,
        checkpoint_dir=os.path.join(out_dir, name.replace(" ", "_")),
        num_classes=CLASSES, verbose=False, heartbeat_timeout=0)
    kwargs.update(overrides)
    cfg = TrainerConfig(**kwargs)
    mesh = make_gossip_mesh(WORLD)
    trainer = Trainer(cfg, TinyCNN(num_classes=CLASSES), mesh,
                      sample_input_shape=(BATCH, IMG, IMG, 3))
    state = trainer.init_state()
    sampler = DistributedSampler(len(images), WORLD)
    loader = ShardedLoader(images, labels, BATCH, sampler)
    val_sampler = DistributedSampler(len(val_images), WORLD)
    val_loader = ShardedLoader(val_images, val_labels, BATCH, val_sampler)

    curve = []
    orig_validate = trainer.validate

    def tracking_validate(state, alg, vl):
        v = orig_validate(state, alg, vl)
        curve.append(v)
        return v

    trainer.validate = tracking_validate
    state, result = trainer.fit(state, loader, sampler, val_loader)
    print(f"{name}: final {curve[-1]:.2f}% best {result['best_prec1']:.2f}%",
          flush=True)
    return curve, result


def main():
    out_dir = "/tmp/convergence_parity"
    os.makedirs(out_dir, exist_ok=True)
    n = WORLD * BATCH * 24
    n_val = WORLD * BATCH * 4
    all_images, all_labels = synthetic_classification(
        n + n_val, num_classes=CLASSES, image_size=IMG, seed=7,
        noise=1.5)
    data = (all_images[:n], all_labels[:n],
            all_images[n:], all_labels[n:])

    curves = {}
    finals = {}
    for name, overrides in CONFIGS:
        curve, result = run_config(name, overrides, data, out_dir)
        curves[name] = curve
        to_thresh = next((i + 1 for i, v in enumerate(curve)
                          if v >= THRESH), None)
        finals[name] = (curve[-1], result["best_prec1"], to_thresh)

    # figure: one line per algorithm, fixed-order palette, direct labels
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(8, 4.8), dpi=150)
    for (name, curve), color in zip(curves.items(), PALETTE):
        xs = np.arange(1, len(curve) + 1)
        ax.plot(xs, curve, color=color, linewidth=2, label=name)
        ax.annotate(name, (xs[-1], curve[-1]), xytext=(4, 0),
                    textcoords="offset points", fontsize=8, color="#333")
    ax.set_xlabel("validation point (every 8 steps)")
    ax.set_ylabel("validation top-1 (%)")
    ax.set_title("Convergence parity: decentralized algorithms, "
                 "8-rank mesh, TinyCNN/synthetic")
    ax.grid(True, color="#eeeeee", linewidth=0.8)
    ax.spines[["top", "right"]].set_visible(False)
    ax.legend(frameon=False, fontsize=8, loc="lower right")
    fig.tight_layout()
    fig.savefig("docs/convergence_parity.png")

    # preserve everything from the first non-toy section onward — those
    # sections are written by other studies (convergence_resnet.py,
    # convergence_lm.py transcriptions) and must survive regeneration
    preserved = ""
    try:
        with open("docs/CONVERGENCE_PARITY.md") as f:
            old = f.read()
        idx = old.find("## Non-toy parity")
        if idx >= 0:
            preserved = old[idx:]
    except OSError:
        pass
    with open("docs/CONVERGENCE_PARITY.md", "w") as f:
        f.write(
            "# Convergence parity across algorithms\n\n"
            "Same model (TinyCNN), data (synthetic, 10 classes), LR and "
            f"epochs ({EPOCHS}) for every algorithm on the 8-rank virtual "
            "CPU mesh, through the full Trainer/CLI stack "
            "(examples/convergence_parity.py; re-run to regenerate).\n\n"
            "| Algorithm | Final val top-1 | Best val top-1 | "
            f"Epochs to {THRESH:.0f}% |\n"
            "|-----------|-----------------|----------------|"
            "----------------|\n")
        for name, (final, best, to_t) in finals.items():
            f.write(f"| {name} | {final:.2f}% | {best:.2f}% | "
                    f"{to_t if to_t is not None else '—'} |\n")
        f.write(
            "\n![curves](convergence_parity.png)\n\n"
            "## Reading the staleness trade\n\n"
            "- **OSGP vs SGP**: at staleness 1 the overlap split is "
            "exact — the incoming share is applied before the next "
            "forward, so the training trajectory and (drained) "
            "validation MATCH sync SGP identically "
            "(test_osgp_val_params_drains_to_sync); the rows above "
            "coincide. The collective still overlaps backprop "
            "(distributed.py:571-588 semantics, compiled).\n"
            "- **OSGP sf=2** (synch_freq=2 → staleness 3): bounded "
            "staleness degrades mixing further; the gap vs SGP is the "
            "quantitative cost of the reference's non-blocking polling "
            "window (distributed.py:127-129).\n"
            "- **AD-PSGD** here is the synchronous perfect-matching "
            "formulation (ARCHITECTURE.md design decision): bilateral "
            "pair averages each step, no host asynchrony. Its curve "
            "bounds the *algorithmic* behavior; the reference's "
            "wall-clock staleness distribution is hardware-dependent "
            "and not reproducible in SPMD.\n")
        if preserved:
            f.write("\n" + preserved)
    print("wrote docs/convergence_parity.png, docs/CONVERGENCE_PARITY.md")


if __name__ == "__main__":
    main()
