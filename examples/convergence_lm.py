"""LM convergence parity: AR vs SGP vs OSGP vs D-PSGD vs AD-PSGD on a
REAL byte corpus through the full gossip_lm CLI stack.

The second task family for the D3 acceptance claim (the ResNet study in
examples/convergence_parity.py / docs/CONVERGENCE_PARITY.md was the
first): every algorithm trains the same byte-level transformer on the
same real text (CPython stdlib sources — ~4 MB, deterministic), same LR
schedule, same fixed token budget, 8-rank virtual CPU mesh, with 10 %
of the corpus tail held out for validation.  Artifacts:

* ``docs/convergence_lm.png`` — val loss vs tokens AND vs wall-clock
  (the error-vs-time view the paper family uses,
  reference visualization/plotting.py:26-52)
* a final table (printed as JSON) with AR-relative final val loss/ppl
  -> transcribed into docs/CONVERGENCE_PARITY.md's LM section.

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      PYTHONPATH=. python examples/convergence_lm.py
"""

import glob
import json
import os
import time

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

WORLD = 8
STEPS = int(os.environ.get("LM_STUDY_STEPS", "2500"))
VAL_EVERY = 100
OUT_DIR = os.environ.get("LM_STUDY_DIR", "/tmp/convergence_lm")
# model scale knobs (defaults = the headline study; LM_STUDY_SCALE=big
# runs the 4x-larger dose point recorded in CONVERGENCE_PARITY.md)
if os.environ.get("LM_STUDY_SCALE") == "big":
    D_MODEL, N_LAYERS, N_HEADS, D_FF, SEQ = 128, 4, 4, 512, 256
    FIG = "docs/convergence_lm_big.png"
else:
    D_MODEL, N_LAYERS, N_HEADS, D_FF, SEQ = 64, 2, 4, 256, 128
    FIG = "docs/convergence_lm.png"

# fixed-order categorical palette (validated; see dataviz palette.md)
PALETTE = ["#2a78d6", "#eb6834", "#1baf7a", "#eda100", "#e87ba4"]

# algorithm -> extra gossip_lm flags.  Everything else (model, data, LR,
# token budget) is IDENTICAL across configs; D-PSGD/AD-PSGD need the
# bipartite graph (doubly-stochastic / perfect matchings).
CONFIGS = [
    ("AR", ["--all_reduce", "True"]),
    ("SGP", []),
    ("OSGP", ["--overlap", "True"]),
    ("D-PSGD", ["--push_sum", "False", "--graph_type", "1"]),
    ("AD-PSGD", ["--bilat", "True", "--graph_type", "1"]),
]

BASE = ["--world_size", str(WORLD), "--seq_len", str(SEQ),
        "--d_model", str(D_MODEL), "--n_heads", str(N_HEADS),
        "--n_layers", str(N_LAYERS),
        "--d_ff", str(D_FF), "--batch_size", "2",
        "--num_steps", str(STEPS), "--warmup", "True",
        "--val_frac", "0.1", "--val_every", str(VAL_EVERY),
        "--val_batches", "8", "--print_freq", str(VAL_EVERY),
        "--seed", "47"]


def build_corpus(path: str) -> str:
    """~4 MB of real text: CPython stdlib sources, sorted, capped."""
    if os.path.exists(path):
        return path
    buf = bytearray()
    import sysconfig
    root = sysconfig.get_paths()["stdlib"]
    for f in sorted(glob.glob(os.path.join(root, "*.py"))):
        with open(f, "rb") as fh:
            buf += fh.read()
        if len(buf) >= 4_000_000:
            break
    with open(path, "wb") as fh:
        fh.write(bytes(buf[:4_000_000]))
    return path


def run_config(name, extra, corpus):
    from stochastic_gradient_push_tpu.run import gossip_lm

    ckpt = os.path.join(OUT_DIR, name.replace(" ", "_"))
    os.makedirs(ckpt, exist_ok=True)
    csv = os.path.join(ckpt, f"lm_out_n{WORLD}.csv")
    if os.environ.get("LM_STUDY_REUSE") == "1" and os.path.exists(csv):
        # reuse a finished arm's CSV (e.g. re-running one arm after a
        # val-semantics change).  Wall-clock is reconstructed from the
        # CSV's OWN final step and the run's seq (not the current
        # STEPS/SEQ globals — a stale CSV from another scale must not be
        # silently rescaled), using its train-throughput column; note
        # the CSV's tokens_per_sec excludes compile/validation wall, so
        # reused arms' wall axis is train-time-only (slightly tighter
        # than fresh arms' perf_counter wall).
        rows = np.atleast_1d(np.genfromtxt(csv, delimiter=",",
                                           names=True))
        csv_steps = float(rows["step"][-1])
        if int(csv_steps) != STEPS:
            raise SystemExit(
                f"{name}: existing CSV has {int(csv_steps)} steps but "
                f"LM_STUDY_STEPS={STEPS}; refusing to mix budgets — "
                "delete the arm's directory to re-run it")
        tps = float(np.mean(rows["tokens_per_sec"]))
        wall = csv_steps * WORLD * 2 * SEQ / max(tps, 1.0)
        print(f"{name}: reusing {csv} (wall reconstructed "
              f"{wall/60:.1f} min, train-time-only)", flush=True)
        return rows, wall
    t0 = time.perf_counter()
    gossip_lm.main(BASE + extra + [
        "--corpus_file", corpus, "--checkpoint_dir", ckpt])
    wall = time.perf_counter() - t0
    # atleast_1d: a single-row CSV genfromtxts to a 0-d structured array
    rows = np.atleast_1d(np.genfromtxt(csv, delimiter=",", names=True))
    return rows, wall


def main():
    os.makedirs(OUT_DIR, exist_ok=True)
    os.makedirs("docs", exist_ok=True)
    corpus = build_corpus(os.path.join(OUT_DIR, "corpus.bin"))

    curves, walls, finals = {}, {}, {}
    for name, extra in CONFIGS:
        rows, wall = run_config(name, extra, corpus)
        curves[name] = rows
        walls[name] = wall
        val = rows["val_loss"][np.isfinite(rows["val_loss"])]
        finals[name] = float(val[-1]) if len(val) else float("nan")
        print(f"{name}: final val_loss {finals[name]:.4f}  "
              f"wall {wall/60:.1f} min", flush=True)

    ar = finals["AR"]
    table = {
        name: {
            "final_val_loss": round(v, 4),
            "final_val_ppl": round(float(np.exp(v)), 3),
            "delta_vs_AR": round(v - ar, 4),
            "ppl_ratio_vs_AR": round(float(np.exp(v - ar)), 4),
            "wall_min": round(walls[name] / 60, 1),
        } for name, v in finals.items()}
    print(json.dumps({"lm_parity": table}), flush=True)

    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(11, 4.4), dpi=150)
    tokens_per_step = WORLD * 2 * SEQ
    for (name, rows), color in zip(curves.items(), PALETTE):
        m = np.isfinite(rows["val_loss"])
        steps = rows["step"][m]
        val = rows["val_loss"][m]
        ax1.plot(steps * tokens_per_step / 1e6, val, color=color,
                 linewidth=1.8, label=name)
        # wall-clock axis: steps are even paced within a run, so scale
        # the step axis by the run's measured wall time
        ax2.plot(steps / rows["step"][-1] * walls[name] / 60, val,
                 color=color, linewidth=1.8, label=name)
    for ax, xl in ((ax1, "tokens (millions)"), (ax2, "wall-clock (min)")):
        ax.set_xlabel(xl)
        ax.set_ylabel("validation loss (nats/byte)")
        ax.grid(True, color="#eeeeee", linewidth=0.8)
        ax.spines[["top", "right"]].set_visible(False)
    ax1.legend(frameon=False, fontsize=8, loc="upper right")
    ax1.set_title("LM convergence parity: same token budget")
    ax2.set_title("error vs wall-clock")
    fig.suptitle(f"Byte-level LM (d{D_MODEL} L{N_LAYERS}), real corpus "
                 "(CPython stdlib), 8-rank mesh", fontsize=10)
    fig.tight_layout()
    fig.savefig(FIG)
    print(f"wrote {FIG}", flush=True)


if __name__ == "__main__":
    main()
