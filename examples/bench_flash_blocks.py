"""Asymmetric (block_q, block_k) sweep for the Pallas flash kernels.

The round-4 capture showed symmetric block 512 beating both block 128 and
XLA for the backward at t in {2048, 4096}; this finer sweep (run on the
real chip) covers asymmetric combinations, t=1024, and the non-causal
case, and is the data source for the auto block-size rule in
ops/flash_attention.py.

Usage: PYTHONPATH=/root/.axon_site:/root/repo python examples/bench_flash_blocks.py
"""

import itertools
import json

import jax
import jax.numpy as jnp
import numpy as np

from stochastic_gradient_push_tpu.ops.flash_attention import flash_attention
from stochastic_gradient_push_tpu.utils.profiling import fenced_ms

STEPS = 10


def timed(fn, *args):
    # fenced_ms, NOT bare block_until_ready: over the tunnel the latter
    # returns at RPC-ack and reported 0.02 ms for a 26 ms kernel
    # (docs/tpu_runs/20260731T062828_mfu/flashblocks.txt is that garbage)
    return fenced_ms(fn, *args, steps=STEPS)


def sweep(b, h, t, d, causal):
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(b, h, t, d)) * 0.5,
                           jnp.bfloat16) for _ in range(3))
    best = {}
    for bq, bk in itertools.product((128, 256, 512), repeat=2):
        if t % bq or t % bk:
            continue

        def loss(q, k, v, bq=bq, bk=bk):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           block_q=bq, block_k=bk)
                           .astype(jnp.float32) ** 2)

        fwd = jax.jit(lambda q, k, v, bq=bq, bk=bk: flash_attention(
            q, k, v, causal=causal, block_q=bq, block_k=bk))
        bwd = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        try:
            r = {"t": t, "causal": causal, "bq": bq, "bk": bk,
                 "fwd_ms": round(timed(fwd, q, k, v), 3),
                 "bwd_ms": round(timed(bwd, q, k, v), 3)}
        except Exception as e:
            r = {"t": t, "causal": causal, "bq": bq, "bk": bk,
                 "error": repr(e)[:160]}
        print(json.dumps(r), flush=True)
        if "fwd_ms" in r:
            for key in ("fwd_ms", "bwd_ms"):
                if key not in best or r[key] < best[key][0]:
                    best[key] = (r[key], bq, bk)
    print(json.dumps({"t": t, "causal": causal, "best": {
        k: {"ms": v[0], "bq": v[1], "bk": v[2]} for k, v in best.items()}}),
        flush=True)


if __name__ == "__main__":
    print(f"backend: {jax.default_backend()} "
          f"({jax.devices()[0].device_kind})", flush=True)
    assert jax.default_backend() == "tpu", "needs the real chip"
    for t in (1024, 2048, 4096):
        sweep(4, 8, t, 64, causal=True)
    sweep(4, 8, 2048, 64, causal=False)
