"""Transformer-step MFU decomposition at the LM bench's flagship config
(d768/L12/h12/t1024/b8, vocab 32k, bf16) — the LM counterpart of
docs/MFU_ANALYSIS.md's ResNet roofline.

Round-4 measured 21.6 % MFU at t1024 vs 35.3 % at t2048 with the SAME
token count — so the attention isn't the bottleneck at t1024; something
that doesn't scale with t² dominates.  This probe attributes the step by
measuring, each as its own jitted program (fwd and fwd+bwd, amortized
over STEPS dispatches):

  full      — the complete train-relevant fwd(+bwd) (model apply + CE)
  embed+head— the same model with n_layers=0 (embed -> LN -> 32k-wide
              head -> lean CE): the vocab path, whose logits tensor
              [b, t, 32k] is the single largest activation in the step
  attn x12  — the flash kernel at the exact per-layer shapes
  ffn  x12  — the two [b*t, d] x [d, 4d] matmul chains

``blocks = full - embed+head`` cross-checks ``12*(attn + ffn)``; the
remainder is QKV/proj matmuls, layernorms and residual traffic.
Roofline predictions from public v5e specs print beside each
measurement.  Run on the real chip:
``PYTHONPATH=/root/.axon_site:. python examples/bench_lm_phases.py``.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from stochastic_gradient_push_tpu.models import (TransformerConfig,
                                                 TransformerLM)
from stochastic_gradient_push_tpu.ops.flash_attention import (
    default_block, flash_attention)
from stochastic_gradient_push_tpu.train.lm import lm_loss
from stochastic_gradient_push_tpu.utils.profiling import fenced_ms

D, L, H, T, B, VOCAB = 768, 12, 12, 1024, 8, 32000
STEPS = int(os.environ.get("LMBENCH_STEPS", "20"))
PEAK_TFLOPS = 197.0  # v5e dense bf16
HBM_GBPS = 819.0


def timeit(fn, *args):
    # fenced (host readback) timing — bare block_until_ready returns at
    # RPC-ack over the tunnel and measures dispatch, not compute
    return fenced_ms(fn, *args, steps=STEPS)


def model_ms(n_layers):
    cfg = TransformerConfig(vocab_size=VOCAB, d_model=D, n_layers=n_layers,
                            n_heads=H, d_ff=4 * D, max_len=T,
                            dtype=jnp.bfloat16, attn_impl="flash")
    model = TransformerLM(cfg)
    tokens = jnp.zeros((B, T), jnp.int32)
    targets = jnp.ones((B, T), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens, train=True)

    def loss_fn(p):
        logits = model.apply(p, tokens, train=True)
        return lm_loss(logits, targets)

    fwd = timeit(jax.jit(loss_fn), params)
    bwd = timeit(jax.jit(jax.grad(loss_fn)), params)
    return fwd, bwd


def attn_ms():
    dh = D // H
    blk = default_block(T)
    q = jax.random.normal(jax.random.PRNGKey(0), (B, H, T, dh),
                          jnp.bfloat16)

    def one(q):
        return flash_attention(q, q, q, causal=True, block_q=blk,
                               block_k=blk)

    def loss(q):
        return jnp.sum(jnp.square(one(q)))

    return timeit(jax.jit(one), q), timeit(jax.jit(jax.grad(loss)), q), blk


def ffn_ms():
    x = jax.random.normal(jax.random.PRNGKey(0), (B * T, D), jnp.bfloat16)
    w1 = jax.random.normal(jax.random.PRNGKey(1), (D, 4 * D),
                           jnp.bfloat16) * 0.02
    w2 = jax.random.normal(jax.random.PRNGKey(2), (4 * D, D),
                           jnp.bfloat16) * 0.02

    def one(x, w1, w2):
        return jax.nn.gelu(x @ w1) @ w2

    def loss(x, w1, w2):
        return jnp.sum(jnp.square(one(x, w1, w2)))

    return (timeit(jax.jit(one), x, w1, w2),
            timeit(jax.jit(jax.grad(loss, argnums=(1, 2))), x, w1, w2))


def main():
    print(f"device: {jax.devices()[0].device_kind}", flush=True)
    tokens = B * T

    # roofline: per-phase FLOPs (fwd; train ~ 3x) and dominant traffic
    ffn_flops = 2 * tokens * D * 4 * D * 2            # two matmuls
    qkvo_flops = 2 * tokens * D * D * 4               # q,k,v,o projections
    attn_flops = 4 * B * T * T * D / 2                # causal: half the pairs
    head_flops = 2 * tokens * D * VOCAB
    logits_bytes = tokens * VOCAB * 2                 # bf16 logits tensor
    print(json.dumps({
        "roofline_fwd_ms": {
            "ffn_x12": round(12 * ffn_flops / PEAK_TFLOPS / 1e9, 3),
            "qkvo_x12": round(12 * qkvo_flops / PEAK_TFLOPS / 1e9, 3),
            "attn_x12": round(12 * attn_flops / PEAK_TFLOPS / 1e9, 3),
            "head": round(head_flops / PEAK_TFLOPS / 1e9, 3),
            "logits_traffic": round(logits_bytes / HBM_GBPS / 1e6, 3),
        }}), flush=True)

    full_f, full_b = model_ms(L)
    eh_f, eh_b = model_ms(0)
    at_f, at_b, blk = attn_ms()
    ff_f, ff_b = ffn_ms()
    print(json.dumps({
        "config": f"d{D} L{L} h{H} t{T} b{B} v{VOCAB} blk{blk}",
        "full_fwd_ms": round(full_f, 3), "full_fwdbwd_ms": round(full_b, 3),
        "embed_head_fwd_ms": round(eh_f, 3),
        "embed_head_fwdbwd_ms": round(eh_b, 3),
        "blocks_fwd_ms": round(full_f - eh_f, 3),
        "blocks_fwdbwd_ms": round(full_b - eh_b, 3),
        "attn_x12_fwd_ms": round(12 * at_f, 3),
        "attn_x12_fwdbwd_ms": round(12 * at_b, 3),
        "ffn_x12_fwd_ms": round(12 * ff_f, 3),
        "ffn_x12_fwdbwd_ms": round(12 * ff_b, 3),
    }), flush=True)


if __name__ == "__main__":
    main()
