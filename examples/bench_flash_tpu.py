"""Real-TPU flash-attention kernel benchmark: Pallas vs XLA attention.

Runs the fused forward+backward Pallas kernels on the TPU (NOT interpret
mode), checks numerics against the pure-JAX blockwise oracle, and times
them against XLA's materialized attention.  Emits one JSON line per config
and writes a summary table to stdout.

Usage (needs the real chip): python examples/bench_flash_tpu.py
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from stochastic_gradient_push_tpu.ops.flash_attention import flash_attention
from stochastic_gradient_push_tpu.parallel.ring_attention import (
    blockwise_attention,
)

STEPS = 20


def xla_attention(q, k, v, causal):
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (d ** -0.5)
    if causal:
        t = q.shape[2]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhvd".replace("v", "q"), p, v)


def timed(fn, *args):
    r = fn(*args)
    _ = np.asarray(jax.device_get(jax.tree.leaves(r)[0]))[..., 0, 0]
    t0 = time.perf_counter()
    for _ in range(STEPS):
        r = fn(*args)
    _ = np.asarray(jax.device_get(jax.tree.leaves(r)[0]))[..., 0, 0]
    return (time.perf_counter() - t0) / STEPS * 1e3  # ms


def run(b, h, t, d, causal=True, dtype=jnp.bfloat16):
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(b, h, t, d)) * 0.5, dtype)
               for _ in range(3))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal)
                       .astype(jnp.float32) ** 2)

    def loss_xla(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal)
                       .astype(jnp.float32) ** 2)

    def loss_oracle(q, k, v):
        return jnp.sum(blockwise_attention(q, k, v, min(128, t),
                                           causal=causal)
                       .astype(jnp.float32) ** 2)

    fwd_flash = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, causal=causal))
    fwd_xla = jax.jit(lambda q, k, v: xla_attention(q, k, v, causal))
    bwd_flash = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))
    bwd_xla = jax.jit(jax.grad(loss_xla, argnums=(0, 1, 2)))
    bwd_oracle = jax.jit(jax.grad(loss_oracle, argnums=(0, 1, 2)))

    # numerics vs oracle (fp32 compare)
    out_f = np.asarray(fwd_flash(q, k, v), np.float32)
    out_o = np.asarray(jax.jit(lambda q, k, v: blockwise_attention(
        q, k, v, min(128, t), causal=causal))(q, k, v), np.float32)
    fwd_err = float(np.max(np.abs(out_f - out_o)))
    gf = bwd_flash(q, k, v)
    go = bwd_oracle(q, k, v)
    bwd_err = max(float(np.max(np.abs(np.asarray(a, np.float32)
                                      - np.asarray(b, np.float32))))
                  for a, b in zip(gf, go))

    r = {
        "shape": f"b{b} h{h} t{t} d{d} causal={causal}",
        "fwd_flash_ms": round(timed(fwd_flash, q, k, v), 3),
        "fwd_xla_ms": round(timed(fwd_xla, q, k, v), 3),
        "bwd_flash_ms": round(timed(bwd_flash, q, k, v), 3),
        "bwd_xla_ms": round(timed(bwd_xla, q, k, v), 3),
        "fwd_max_err": fwd_err,
        "bwd_max_err": bwd_err,
    }
    print(json.dumps(r), flush=True)
    return r


def sweep_blocks(b, h, t, d, causal=True, dtype=jnp.bfloat16):
    """Block-size sweep for the fused kernels at one shape: the 3-D-grid
    schedule keeps VMEM at O(block²), so blocks up to 512 are in play;
    record which (block_q, block_k) wins so the defaults can follow."""
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(b, h, t, d)) * 0.5, dtype)
               for _ in range(3))
    for blk in (128, 256, 512):
        if t % blk:
            continue

        def loss(q, k, v, blk=blk):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           block_q=blk, block_k=blk)
                           .astype(jnp.float32) ** 2)

        fwd = jax.jit(lambda q, k, v, blk=blk: flash_attention(
            q, k, v, causal=causal, block_q=blk, block_k=blk))
        bwd = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        try:
            r = {"sweep": f"t{t} block{blk}",
                 "fwd_ms": round(timed(fwd, q, k, v), 3),
                 "bwd_ms": round(timed(bwd, q, k, v), 3)}
        except Exception as e:  # Mosaic rejection at this block size
            r = {"sweep": f"t{t} block{blk}", "error": repr(e)[:200]}
        print(json.dumps(r), flush=True)


if __name__ == "__main__":
    print(f"backend: {jax.default_backend()} "
          f"({jax.devices()[0].device_kind})", flush=True)
    assert jax.default_backend() == "tpu", "needs the real chip"
    for t in (1024, 2048, 4096):
        run(4, 8, t, 64, causal=True)
    run(4, 8, 2048, 64, causal=False)
    sweep_blocks(4, 8, 4096, 64, causal=True)
    sweep_blocks(4, 8, 2048, 64, causal=True)
