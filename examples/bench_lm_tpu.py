"""Real-TPU transformer-LM benchmark: SGP train-step tokens/sec + MFU.

The image headline bench (bench.py) covers ResNet-50; this drives the
transformer family — the TPU-native extension the reference lacks — on one
chip: full SGP train step (fwd, bwd, torch-semantics SGD, push-sum round)
over a decoder-only LM with the Pallas flash-attention kernels, bf16
compute.  Emits one JSON line per config.

Usage (needs the real chip): PYTHONPATH=. python examples/bench_lm_tpu.py
Env knobs: LMBENCH_STEPS, LMBENCH_CONFIGS ("d_model,layers,heads,seq,batch;..").
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from stochastic_gradient_push_tpu.algorithms import sgp
from stochastic_gradient_push_tpu.models import (TransformerConfig,
                                                 TransformerLM)
from stochastic_gradient_push_tpu.parallel import GOSSIP_AXIS, \
    make_gossip_mesh
from stochastic_gradient_push_tpu.topology import (
    NPeerDynamicDirectedExponentialGraph, build_schedule)
from stochastic_gradient_push_tpu.train import LRSchedule, sgd
from stochastic_gradient_push_tpu.train.lm import (build_lm_train_step,
                                                   init_lm_state,
                                                   shard_lm_train_step,
                                                   shard_scanned_lm_step)

STEPS = int(os.environ.get("LMBENCH_STEPS", "20"))
SCAN = int(os.environ.get("LMBENCH_SCAN", "4"))
# override the flash/blockwise attention block size (None = the
# default_block auto rule) — the t1024 block A/B for docs/LM_MFU.md
BLOCK = int(os.environ.get("LMBENCH_BLOCK", "0")) or None
# flash K/V-side block override (None = symmetric with BLOCK)
BLOCK_K = int(os.environ.get("LMBENCH_BLOCK_K", "0")) or None

# (d_model, n_layers, n_heads, seq_len, batch) — a ~125M GPT-small-shaped
# config and a long-context variant
DEFAULT_CONFIGS = [
    (768, 12, 12, 1024, 8),
    (768, 12, 12, 2048, 4),
    (512, 8, 8, 4096, 2),
]


def parse_configs():
    raw = os.environ.get("LMBENCH_CONFIGS")
    if not raw:
        return DEFAULT_CONFIGS
    out = []
    for part in raw.split(";"):
        d, l, h, t, b = (int(x) for x in part.split(","))
        out.append((d, l, h, t, b))
    return out


def peak_tflops(kind: str) -> float | None:
    import bench
    return bench.peak_tflops(kind)


def run(d_model, n_layers, n_heads, seq, batch, vocab=32000,
        attn="flash", moe_experts=0):
    world = jax.device_count()
    mesh = make_gossip_mesh(world)
    cfg = TransformerConfig(
        vocab_size=vocab, d_model=d_model, n_layers=n_layers,
        n_heads=n_heads, d_ff=4 * d_model, max_len=seq,
        dtype=jnp.bfloat16, attn_impl=attn,
        attn_block_size=BLOCK, attn_block_k=BLOCK_K,
        moe_experts=moe_experts)
    model = TransformerLM(cfg)
    alg = sgp(build_schedule(NPeerDynamicDirectedExponentialGraph(
        world, peers_per_itr=1) if world > 1 else
        NPeerDynamicDirectedExponentialGraph(1)), GOSSIP_AXIS)
    tx = sgd(momentum=0.9, weight_decay=0.0)
    lrs = LRSchedule(ref_lr=3e-2, batch_size=batch, world_size=world,
                     decay_schedule={}, warmup=False)
    step = build_lm_train_step(model, alg, tx, lrs, itr_per_epoch=1000,
                               seq_axis=None)
    state = init_lm_state(model, mesh, alg, tx, dp=world, sp=1,
                          batch_size=batch, block_len=seq, seq_axis=None)
    if SCAN > 1:
        train_fn = shard_scanned_lm_step(step, mesh, n_steps=SCAN,
                                         seq_axis=None)
    else:
        train_fn = shard_lm_train_step(step, mesh, seq_axis=None)

    rng = np.random.default_rng(0)
    shape = (world, batch, seq)
    if SCAN > 1:
        shape = (SCAN,) + shape
    toks = rng.integers(0, vocab, size=shape).astype(np.int32)
    tgts = rng.integers(0, vocab, size=shape).astype(np.int32)
    from jax.sharding import NamedSharding, PartitionSpec as P
    spec = P(None, GOSSIP_AXIS) if SCAN > 1 else P(GOSSIP_AXIS)
    sh = NamedSharding(mesh, spec)
    toks = jax.device_put(toks, sh)
    tgts = jax.device_put(tgts, sh)

    flops = None
    try:
        compiled = train_fn.lower(state, toks, tgts).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        f = ca.get("flops")
        flops = float(f) if f and f > 0 else None
        run_fn = compiled
    except Exception:
        run_fn = train_fn

    def call(st, tk, tg):
        # the AOT executable can reject argument shardings on
        # multi-device CPU meshes (its output state shardings need not
        # match its inputs'); fall back to the jit path permanently —
        # it re-infers shardings per call.  1-chip TPU never hits this.
        nonlocal run_fn
        try:
            return run_fn(st, tk, tg)
        except ValueError:
            if run_fn is train_fn:
                raise
            run_fn = train_fn
            return run_fn(st, tk, tg)

    m = None
    for _ in range(3):
        state, m = call(state, toks, tgts)
    loss = float(np.min(np.asarray(jax.device_get(m["loss"]))))
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, m = call(state, toks, tgts)
    loss = float(np.min(np.asarray(jax.device_get(m["loss"]))))
    # one dispatch runs SCAN fused steps; XLA's cost analysis counts the
    # scan body once, so `flops` is already per-iteration (see bench.py)
    time_per_itr = (time.perf_counter() - t0) / (STEPS * SCAN)
    assert np.isfinite(loss), "non-finite loss"

    n_params = sum(int(np.prod(np.shape(l))) for l in jax.tree.leaves(
        jax.tree.map(lambda a: a[0], state.params)))
    tokens_per_sec = world * batch * seq / time_per_itr
    out = {"config": f"d{d_model} L{n_layers} h{n_heads} t{seq} b{batch}",
           "attn": attn, **({"block": BLOCK} if BLOCK else {}),
           **({"block_k": BLOCK_K} if BLOCK_K else {}),
           "moe_experts": moe_experts,
           "params_m": round(n_params / 1e6, 1), "scan": SCAN,
           "tokens_per_sec_per_chip": round(tokens_per_sec / world),
           "step_ms": round(time_per_itr * 1e3, 2), "loss": round(loss, 3)}
    peak = peak_tflops(jax.devices()[0].device_kind)
    if flops and peak:
        out["mfu"] = round(flops / time_per_itr / (peak * 1e12 * world), 4)
        # 6·N·T rule-of-thumb for comparison with the XLA-counted number
        # (dense only: top-1 routing activates ~1/E of MoE FFN params,
        # so total-N would overstate model FLOPs several-fold)
        if moe_experts == 0:
            out["mfu_6nd"] = round(
                6 * n_params * batch * seq / time_per_itr / (peak * 1e12),
                4)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    backend = jax.default_backend()
    print(f"backend: {backend} ({jax.devices()[0].device_kind})",
          flush=True)
    assert backend == "tpu", "needs the real chip"
    def run_retrying(*args, **kw):
        # the tunnel's compile helper throws transient INTERNAL/HTTP-500s
        # (seen in the round-4 capture); one spaced retry rescues the
        # config instead of losing its numbers
        for attempt in (0, 1):
            try:
                return run(*args, **kw)
            except Exception as e:
                transient = "INTERNAL" in repr(e) or "HTTP 5" in repr(e)
                if attempt == 0 and transient:
                    time.sleep(20)
                    continue
                print(json.dumps({"config": str(args), **kw,
                                  "error": repr(e)[:300]}), flush=True)
                return None

    # Priority order (the tunnel window may close any minute — round 4's
    # 900 s timeout cut t4096 and MoE entirely): every config's flash
    # number first, then MoE, then the redundant blockwise comparisons
    # (bench_flash_tpu.py already isolates flash-vs-XLA at the kernel
    # level, so blockwise full-step numbers are corroboration, not
    # primary evidence).
    configs = parse_configs()
    for cfg in configs:
        run_retrying(*cfg, attn="flash")
    # MoE throughput on one chip: the full switch dispatch (router,
    # capacity slots, dispatch/combine einsums) with all experts local —
    # the ep>1 meshes need multiple devices, but the routing machinery's
    # cost is visible here (VERDICT r3 item 1c, single-chip variant)
    run_retrying(768, 12, 12, 1024, 8, attn="flash", moe_experts=8)
    for cfg in configs:
        run_retrying(*cfg, attn="blockwise")
