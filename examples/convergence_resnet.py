"""Non-toy convergence parity: ResNet-18 through the D3 acceptance
methodology (BASELINE.md).

The reference's paper claim is that SGP reaches all-reduce accuracy to
within ~1.5 % at scale (gossip_sgd.py:508-531 recipe; BASELINE.md D3
derives the acceptance band).  This study runs that methodology at the
largest scale the 8-device virtual CPU mesh affords: ResNet-18 (the
flagship family's block structure and init recipe) on a
translated-patch synthetic task — the class pattern appears at a RANDOM
position per sample, so the label is not linearly separable and the
network must learn convolutional features — and compares SGP, OSGP and
D-PSGD against their own-AR baseline after identical epochs/LR.

Acceptance: final val top-1 within 1.5 % of own-AR (D3 band).

Artifacts (committed):
  docs/convergence_resnet.png      — per-epoch val-accuracy curves
  docs/CONVERGENCE_PARITY.md       — gains a non-toy section + gap table
  docs/error_vs_time_train.png     — regenerated from these runs' CSVs
  docs/error_vs_time_val.png         (the reference's headline figure)

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      PYTHONPATH=. python examples/convergence_resnet.py
"""

import json
import os

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

from stochastic_gradient_push_tpu.data import (
    DistributedSampler,
    ShardedLoader,
    translated_patch_classification,
)
from stochastic_gradient_push_tpu.models import resnet18
from stochastic_gradient_push_tpu.parallel import make_gossip_mesh
from stochastic_gradient_push_tpu.topology import (
    DynamicBipartiteExponentialGraph,
)
from stochastic_gradient_push_tpu.train.loop import Trainer, TrainerConfig

WORLD, BATCH, CLASSES, IMG = 8, 12, 16, 24
ITR_PER_EPOCH = 30
EPOCHS = 12
BAND = 1.5  # D3 acceptance band, percentage points vs own-AR

PALETTE = ["#2a78d6", "#eb6834", "#1baf7a", "#eda100"]

CONFIGS = [
    ("AR", dict(all_reduce=True, graph_class=None)),
    ("SGP", dict(push_sum=True)),
    ("OSGP", dict(push_sum=True, overlap=True)),
    ("D-PSGD", dict(push_sum=False,
                    graph_class=DynamicBipartiteExponentialGraph)),
]

OUT_DIR = os.environ.get("CONV_OUT", "/tmp/convergence_resnet")


def run_config(name, overrides, data):
    images, labels, val_images, val_labels = data
    kwargs = dict(
        lr=0.1, warmup=False, lr_schedule={8: 0.1, 10: 0.1},
        num_iterations_per_training_epoch=ITR_PER_EPOCH,
        batch_size=BATCH, num_epochs=EPOCHS, num_itr_ignore=1,
        checkpoint_dir=os.path.join(OUT_DIR, name.replace(" ", "_")),
        num_classes=CLASSES, verbose=False, heartbeat_timeout=0)
    kwargs.update(overrides)
    cfg = TrainerConfig(**kwargs)
    mesh = make_gossip_mesh(WORLD)
    trainer = Trainer(cfg, resnet18(num_classes=CLASSES), mesh,
                      sample_input_shape=(BATCH, IMG, IMG, 3))
    state = trainer.init_state()
    sampler = DistributedSampler(len(images), WORLD)
    loader = ShardedLoader(images, labels, BATCH, sampler)
    val_sampler = DistributedSampler(len(val_images), WORLD)
    val_loader = ShardedLoader(val_images, val_labels, BATCH, val_sampler)

    curve = []
    orig_validate = trainer.validate

    def tracking_validate(state, alg, vl):
        v = orig_validate(state, alg, vl)
        curve.append(v)
        return v

    trainer.validate = tracking_validate
    state, result = trainer.fit(state, loader, sampler, val_loader)
    print(f"{name}: final {curve[-1]:.2f}% best "
          f"{result['best_prec1']:.2f}%", flush=True)
    return curve, result


def main():
    os.makedirs(OUT_DIR, exist_ok=True)
    n = WORLD * BATCH * ITR_PER_EPOCH
    n_val = WORLD * BATCH * 4
    all_images, all_labels = translated_patch_classification(
        n + n_val, num_classes=CLASSES, image_size=IMG, patch_size=8,
        seed=11, noise=1.0)
    data = (all_images[:n], all_labels[:n],
            all_images[n:], all_labels[n:])

    curves, finals = {}, {}
    for name, overrides in CONFIGS:
        curve, result = run_config(name, overrides, data)
        curves[name] = curve
        finals[name] = (curve[-1], result["best_prec1"])
    ar_final = finals["AR"][0]

    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(8, 4.8), dpi=150)
    for (name, curve), color in zip(curves.items(), PALETTE):
        xs = np.arange(1, len(curve) + 1)
        ax.plot(xs, curve, color=color, linewidth=2, label=name)
    ax.set_xlabel("epoch")
    ax.set_ylabel("validation top-1 (%)")
    ax.set_title("ResNet-18 convergence parity (D3 methodology), "
                 "8-rank mesh, translated-patch task")
    ax.grid(True, color="#eeeeee", linewidth=0.8)
    ax.spines[["top", "right"]].set_visible(False)
    ax.legend(frameon=False, fontsize=9, loc="lower right")
    fig.tight_layout()
    fig.savefig("docs/convergence_resnet.png")

    # the reference's headline error-vs-wall-time figures, from these
    # runs' per-rank CSVs (visualization/plotting.py::plot_error_vs_time)
    from stochastic_gradient_push_tpu.visualization import (
        plot_error_vs_time)
    run_dirs = {name: os.path.join(OUT_DIR, name.replace(" ", "_"))
                for name, _ in CONFIGS}
    plot_error_vs_time(run_dirs, WORLD,
                       out_path="docs/error_vs_time_train.png")
    plot_error_vs_time(run_dirs, WORLD, val=True,
                       out_path="docs/error_vs_time_val.png")

    section = [
        "\n## Non-toy parity: ResNet-18, D3 acceptance methodology\n\n"
        "ResNet-18 (the flagship family at study scale) on the "
        "translated-patch task (class pattern at a random position — "
        "not linearly separable), 8 ranks, "
        f"{EPOCHS} epochs × {ITR_PER_EPOCH} itr, identical LR recipe; "
        "each decentralized algorithm is judged against its own-AR "
        f"baseline with the D3 band (±{BAND} %) from BASELINE.md "
        "(examples/convergence_resnet.py; re-run to regenerate).\n\n"
        "| Algorithm | Final val top-1 | Best val top-1 | Gap vs AR | "
        f"within {BAND}% band |\n"
        "|-----------|-----------------|----------------|-----------|"
        "------------------|\n"]
    gaps = {}
    for name, (final, best) in finals.items():
        gap = final - ar_final
        gaps[name] = gap
        ok = "—" if name == "AR" else (
            "yes" if abs(gap) <= BAND else "**no**")
        section.append(f"| {name} | {final:.2f}% | {best:.2f}% | "
                       f"{gap:+.2f}% | {ok} |\n")
    section.append(
        "\n![resnet curves](convergence_resnet.png)\n\n"
        "The error-vs-wall-time figures in this directory "
        "(`error_vs_time_train.png`, `error_vs_time_val.png`) are "
        "generated from these runs' per-rank CSVs.\n")

    marker = "\n## Non-toy parity"
    doc = open("docs/CONVERGENCE_PARITY.md").read()
    if marker in doc:
        doc = doc[:doc.index(marker)]
    open("docs/CONVERGENCE_PARITY.md", "w").write(doc + "".join(section))
    print(json.dumps({"ar_final": ar_final, "gaps": gaps}), flush=True)


if __name__ == "__main__":
    main()
