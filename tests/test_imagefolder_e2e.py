"""The flagship ImageNet entry path, end-to-end on real JPEGs.

Every other CLI e2e test drives ``--dataset synthetic``;
StreamingImageFolder and the native decoder were only tested in
isolation.  This glues the whole seam together — ``main()`` →
StreamingImageFolder → native/PIL decode → train → validate →
checkpoint → resume — exactly where shape/dtype/sampler-fast-forward
bugs live (≙ the reference's ImageFolder path, gossip_sgd.py:539-583).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CLI_ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
}

WORLD, BATCH, CLASSES, IMG_SRC, IMG = 8, 4, 4, 24, 16

# class -> solid RGB so a TinyCNN separates them within two epochs
COLORS = [(220, 40, 40), (40, 220, 40), (40, 40, 220), (220, 220, 40)]


@pytest.fixture(scope="module")
def jpeg_root(tmp_path_factory):
    from PIL import Image

    root = tmp_path_factory.mktemp("imagefolder")
    rng = np.random.default_rng(0)
    for split, per_class in (("train", 16), ("val", 8)):
        for c, color in enumerate(COLORS):
            d = root / split / f"class_{c}"
            d.mkdir(parents=True)
            for i in range(per_class):
                px = np.clip(
                    np.asarray(color, np.int16)
                    + rng.integers(-30, 30, (IMG_SRC, IMG_SRC, 3)),
                    0, 255).astype(np.uint8)
                Image.fromarray(px).save(d / f"img_{i}.jpg", quality=90)
    return root


def _run(jpeg_root, ckpt_dir, epochs, resume=False, extra=()):
    cmd = [sys.executable, "-m",
           "stochastic_gradient_push_tpu.run.gossip_sgd",
           "--dataset", "imagefolder", "--dataset_dir", str(jpeg_root),
           "--data_backend", "auto", "--world_size", str(WORLD),
           "--model", "tiny_cnn", "--num_classes", str(CLASSES),
           "--image_size", str(IMG), "--batch_size", str(BATCH),
           "--num_epochs", str(epochs), "--num_itr_ignore", "0",
           "--num_dataloader_workers", "2", "--lr", "0.05",
           "--resume", str(resume),
           "--checkpoint_dir", str(ckpt_dir) + "/", *extra]
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=600, env=CLI_ENV)


def _csv_epoch_rows(csv_path):
    """(epoch, itr, top1_val) training rows from the reference-schema CSV."""
    rows = []
    for ln in csv_path.read_text().splitlines():
        parts = ln.split(",")
        if len(parts) > 10 and parts[0].isdigit():
            rows.append((int(parts[0]), int(parts[1]), float(parts[-1])))
    return rows


@pytest.mark.slow
def test_imagefolder_cli_end_to_end_with_resume(jpeg_root, tmp_path):
    r = _run(jpeg_root, tmp_path, epochs=2)
    assert r.returncode == 0, (r.stderr or r.stdout)[-3000:]

    csv = tmp_path / "out_r0_n8.csv"
    assert csv.exists(), "reference-schema CSV missing"
    rows = _csv_epoch_rows(csv)
    train_rows = [r for r in rows if r[1] >= 0]
    val_rows = [r for r in rows if r[1] == -1]  # val rows log itr = -1
    # 64 train images / (8 ranks * batch 4) = 2 iterations per epoch
    assert {e for e, _, _ in train_rows} == {0, 1}
    assert all(i < 2 for _, i, _ in train_rows)

    # validation ran each epoch and produced a sane top-1: the 4
    # solid-color classes are separable, so two epochs beat random (25 %)
    assert [e for e, _, _ in val_rows] == [0, 1]
    assert 25.0 <= val_rows[-1][2] <= 100.0, val_rows

    ckpt = tmp_path / "checkpoint_r0_n8.ckpt"
    assert ckpt.exists()

    # resume for a third epoch: picks up at epoch 2, extends the SAME csv
    # with exactly one epoch's rows (2 train + 1 val)
    r2 = _run(jpeg_root, tmp_path, epochs=3, resume=True)
    assert r2.returncode == 0, (r2.stderr or r2.stdout)[-3000:]
    assert "resumed from epoch 2" in r2.stdout + r2.stderr
    rows2 = _csv_epoch_rows(csv)
    assert {e for e, _, _ in rows2} == {0, 1, 2}
    assert len(rows2) == len(rows) + 3
    assert 25.0 <= [r for r in rows2 if r[1] == -1][-1][2] <= 100.0


@pytest.mark.slow
def test_imagefolder_cli_uint8_output_path(jpeg_root, tmp_path):
    """--data_output uint8 ships raw pixels; the step normalizes on
    device (train/step.py _device_normalize) — same seam, quantized."""
    r = _run(jpeg_root, tmp_path, epochs=1,
             extra=("--data_output", "uint8"))
    assert r.returncode == 0, (r.stderr or r.stdout)[-3000:]
    assert (tmp_path / "out_r0_n8.csv").exists()
    out = r.stdout + r.stderr
    assert "Prec@1" in out and "done:" in out
