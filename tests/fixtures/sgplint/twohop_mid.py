"""Middle hop of the two-hop closure fixture: pure pass-through.

Nothing here is traced by its own decorators; tracedness arrives from
``bad_twohop.step`` through the closure and must continue one hop
further into ``twohop_leaf``.
"""

from twohop_leaf import leaf_helper


def mid_helper(x):
    return leaf_helper(x) * 2.0
