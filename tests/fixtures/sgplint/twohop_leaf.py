"""Leaf of the two-hop closure fixture: the hidden host effect.

Two import hops from the jitted entry point — invisible to the old
one-hop closure, flagged by the full fixpoint.  The marker line below
deliberately does not match the ``# EXPECT:`` harness regex: this file
must stay clean under standalone ``lint_file``.
"""

import time

import jax.numpy as jnp


def leaf_helper(x):
    stamp = time.time()  # EXPECT-TWOHOP: SGPL002 (fixpoint closure only)
    return x + jnp.asarray(stamp, x.dtype)
