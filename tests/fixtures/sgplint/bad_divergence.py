"""SGPL011: collective divergence across structured-control-flow branches.

All ranks of an SPMD program must execute the same collective sequence;
a ``lax.cond``/``lax.switch`` whose branches carry different sequences
hangs the moment one rank takes the other branch.  Engine 3 resolves
each branch callable through the call graph and compares the ordered
collective signatures.  Good shapes below pin the precision rules:
matched sequences, collective-uniformized while predicates, and opaque
branch targets (``self.method``) stay silent by design.
"""

import jax
import jax.numpy as jnp
from jax import lax


def push_half(x):
    return lax.ppermute(x, "gossip", [(0, 1), (1, 0)])


def fold_half(x):
    return lax.psum(x, "gossip")


def quiet_half(x):
    return x * 2.0


@jax.jit
def step_cond(pred, x):
    # one branch ships a ppermute, the other ships nothing
    return lax.cond(pred, push_half, quiet_half, x)  # EXPECT: SGPL011


@jax.jit
def step_switch(idx, x):
    # three branches, three different sequences
    return lax.switch(idx, [push_half, fold_half, quiet_half], x)  # EXPECT: SGPL011


@jax.jit
def drain(x):
    def not_done(carry):
        return carry[1] < 4.0

    def body(carry):
        v, t = carry
        return fold_half(v), t + 1.0

    # the body runs a psum every iteration but nothing makes the exit
    # predicate rank-uniform: ranks can disagree on the trip count
    return lax.while_loop(not_done, body, (x, jnp.float32(0)))  # EXPECT: SGPL011


# -- good shapes: silent by design ------------------------------------------


@jax.jit
def step_matched(pred, x):
    # both branches run the same single ppermute: no divergence
    return lax.cond(pred, push_half, lambda v: push_half(v), x)


@jax.jit
def drain_uniform(x):
    def any_left(carry):
        # the pmax makes the predicate identical on every rank
        return lax.pmax(carry[1], "gossip") > 0

    def body(carry):
        v, t = carry
        return fold_half(v), t - 1

    return lax.while_loop(any_left, body, (x, jnp.int32(3)))


class Mixer:
    """Opaque branch targets silence the site (precision over recall):
    ``self._mix`` cannot be resolved statically."""

    def _mix(self, x):
        return lax.psum(x, "gossip")

    def maybe(self, pred, x):
        return lax.cond(pred, lambda v: self._mix(v), lambda v: v, x)
