"""SGPL013 cross-call start-without-wait: the split transport pair.

``gossip_edge_start`` returns a live transport handle — remote-DMA
payloads landed into buffers the handle owns — and every handle must
reach a ``gossip_edge_wait``: locally, in a resolvable callee at a
separate call site, or by escaping to the caller that owns it.  Three
shapes where none of that happens: a discarded start result, a handle
that dies in scope, and a handle flowing only into a callee that never
waits.  ``ok_split_transport.py`` is the silent twin.
"""

from stochastic_gradient_push_tpu.ops import gossip_kernel as gk


def fire_and_forget(parts, dests, axis, spec):
    gk.gossip_edge_start(parts, dests, axis, spec)  # EXPECT: SGPL013
    return None


def dies_in_scope(parts, dests, axis, spec, acc):
    handle = gk.gossip_edge_start(parts, dests, axis, spec)  # EXPECT: SGPL013
    return acc


def _log_only(handle):
    return str(handle)


def wrong_consumer(parts, dests, axis, spec, acc):
    h = gk.gossip_edge_start(parts, dests, axis, spec)  # EXPECT: SGPL013
    _log_only(h)
    return acc
