"""SGPV101 via the topology protocol: a generator that emits a corrupt
permutation table without noticing (bypasses graphs.py's own build-time
check, which is exactly the hole the verifier closes)."""
# EXPECT-MODULE: SGPV101

from stochastic_gradient_push_tpu.topology.graphs import RingGraph


class BrokenRing(RingGraph):
    def phase_permutation(self, phase):
        perm = super().phase_permutation(phase).copy()
        perm[..., 0] = perm[..., 1]  # sources 0 and 1 share a destination
        return perm


SGPLINT_TOPOLOGIES = [BrokenRing(8)]
