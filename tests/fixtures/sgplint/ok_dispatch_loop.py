"""Good twin of ``bad_dispatch_loop.py``: serialized dispatch.

Identical consensus loops, but every body contains a blocking read —
``jax.block_until_ready`` or a host-side scalar read — so the dispatch
queue drains each iteration.  This is exactly how the PR 8 hang was
fixed in the tier-1 tests.  Zero findings expected.
"""

import jax
from jax import lax


@jax.jit
def gossip_step(x):
    return 0.5 * (x + lax.ppermute(x, "gossip", [(0, 1), (1, 0)]))


def consensus_sweep_serialized(x):
    for _ in range(60):
        x = jax.block_until_ready(gossip_step(x))
    return x


def consensus_sweep_metrics(x):
    total = 0.0
    for _ in range(60):
        x = gossip_step(x)
        total += float(x[0])  # host read: blocks on the result
    return x, total
