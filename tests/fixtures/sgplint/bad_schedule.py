"""SGPV101/SGPV102: malformed schedule tables.

Two schedule-like objects: one whose sub-round sends two sources to the
same destination (ppermute would drop a message), one whose mixing
columns sum to 1.1 (push-sum mass inflates every round).
"""
# EXPECT-MODULE: SGPV101,SGPV102

from types import SimpleNamespace

import numpy as np

_N = 4

_NOT_A_PERMUTATION = np.array([[[2, 2, 3, 0]]], dtype=np.int32)
_RING = np.array([[[1, 2, 3, 0]]], dtype=np.int32)

SGPLINT_SCHEDULES = [
    # ranks 0 and 1 both send to rank 2 -> SGPV101
    SimpleNamespace(
        perms=_NOT_A_PERMUTATION,
        self_weight=np.full((1, _N), 0.5),
        edge_weights=np.full((1, 1, _N), 0.5),
        num_phases=1, world_size=_N, peers_per_itr=1),
    # valid ring, but columns sum to 1.1 -> SGPV102
    SimpleNamespace(
        perms=_RING,
        self_weight=np.full((1, _N), 0.6),
        edge_weights=np.full((1, 1, _N), 0.5),
        num_phases=1, world_size=_N, peers_per_itr=1),
]
