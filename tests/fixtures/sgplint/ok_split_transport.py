"""Silent twin of ``bad_split_transport.py``: legitimate handle flows.

The three ways a ``gossip_edge_start`` handle is allowed to travel —
waited in the same body (the synchronous kernel round), waited in a
resolvable callee at a *separate* call site (the cross-call pairing
Engine 3's closure tracks), and escaped to the caller inside a
returned structure (the overlap FIFO: the consumer that lands the
share owns the wait).  Zero findings expected.
"""

from stochastic_gradient_push_tpu.ops import gossip_kernel as gk


def sync_round(parts, dests, axis, spec, acc):
    h = gk.gossip_edge_start(parts, dests, axis, spec)
    return gk.gossip_edge_wait(h, acc)


def _land(handle, acc):
    return gk.gossip_edge_wait(handle, acc)


def split_round(parts, dests, axis, spec, acc):
    h = gk.gossip_edge_start(parts, dests, axis, spec)
    return _land(h, acc)


def launch_only(parts, dests, axis, spec, inc):
    h = gk.gossip_edge_start(parts, dests, axis, spec)
    return (inc, h)  # the FIFO slot's consumer waits it
