"""SGPL010: raw .astype wire cast on a ppermute payload.

The gossip wire has exactly one encode path — parallel/wire.py's
WireCodec family — so comm pricing, error feedback, and the compiled
cast can never disagree.  An inline ``payload.astype(...)`` handed to
``lax.ppermute`` bypasses all three.
"""

import jax
import jax.numpy as jnp
from jax import lax

PAIRS = [(0, 1), (1, 0)]


@jax.jit
def leaky_send(x):
    # the legacy pre-codec idiom: cast down inline, ship, cast back
    wire = lax.ppermute(x.astype(jnp.bfloat16), "gossip", PAIRS)  # EXPECT: SGPL010
    return wire.astype(x.dtype)


@jax.jit
def nested_cast(x, w):
    # the cast hides inside the payload expression — still a wire cast
    return lax.ppermute((x * w).astype(jnp.float16), "gossip", PAIRS)  # EXPECT: SGPL010


@jax.jit
def clean_send(x):
    # no cast on the wire: the payload ships in its own dtype (codecs
    # would have encoded it upstream, in parallel/wire.py)
    return lax.ppermute(x, "gossip", PAIRS)


def host_side(x):
    # NOT traced: astype here is ordinary host numpy-ish code, no wire
    return x.astype(jnp.bfloat16)
