"""SGPV105: a schedule generator that crashes instead of refusing."""
# EXPECT-MODULE: SGPV105


class _ExplodingGraph:
    world_size = 4
    peers_per_itr = 1

    @property
    def num_phases(self):
        raise RuntimeError("phase table exploded")

    @property
    def all_phase_permutations(self):
        raise RuntimeError("phase table exploded")


SGPLINT_TOPOLOGIES = [_ExplodingGraph()]
