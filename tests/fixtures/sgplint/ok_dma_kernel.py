"""Good twin of ``bad_dma_kernel.py``: the shipped transport idioms.

Mirrors ``ops/gossip_kernel.py``: descriptors collected into a list,
all started, all waited; an entry barrier whose wait amount matches its
signal count; a re-made descriptor waited through the make-again
pattern; and ``collective_id`` derived from the slot pool (one pinned
literal at a single site is also fine — only cross-site reuse fires).
Zero findings expected.
"""

import functools

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

COLLECTIVE_ID_SLOTS = 16


def _edge_kernel(nparts, x_ref, y_ref, send_sem, recv_sem, bsem_unused):
    # entry barrier: both neighbours signalled, both signals awaited
    bsem = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(bsem, inc=1, device_id=0)
    pltpu.semaphore_signal(bsem, inc=1, device_id=1)
    pltpu.semaphore_wait(bsem, 2)

    rdmas = []
    for part in range(nparts):
        rdmas.append(pltpu.make_async_remote_copy(
            src_ref=x_ref, dst_ref=y_ref, send_sem=send_sem,
            recv_sem=recv_sem, device_id=part))
    for r in rdmas:
        r.start()
    for r in rdmas:
        r.wait()


def _local_stage_kernel(x_ref, y_ref, sem):
    # the make-twice pattern: start on one descriptor, wait on a
    # re-made twin with identical arguments
    pltpu.make_async_copy(x_ref, y_ref, sem).start()
    pltpu.make_async_copy(x_ref, y_ref, sem).wait()


def edge_transport(x, leaf_slot):
    staged = pl.pallas_call(_local_stage_kernel, out_shape=x)(x)
    return pl.pallas_call(
        functools.partial(_edge_kernel, 2),
        out_shape=x,
        compiler_params=pltpu.TPUCompilerParams(
            collective_id=leaf_slot % COLLECTIVE_ID_SLOTS),
    )(staged)


def pinned_probe(x):
    # a single pinned literal site is legitimate (tests pin slot
    # semantics this way); only cross-site reuse is a hazard
    return pl.pallas_call(
        _local_stage_kernel,
        out_shape=x,
        compiler_params=pltpu.TPUCompilerParams(collective_id=3),
    )(x)
