"""SGPL014: metric names outside the registered vocabulary.

The fleet exposition namespace is closed: every ``.counter()`` /
``.gauge()`` / ``.histogram()`` name must appear in a module-level
``*METRIC_NAMES`` declaration (``telemetry/metrics.py`` in the real
tree; this fixture carries its own so ``lint_file`` sees a non-empty
vocabulary).  A literal that is not registered forks the namespace —
dashboards and SLO rules key on exact names, so the typo'd series
records forever and nobody watches it.  ``ok_metrics.py`` is the
registered good twin.
"""

FLEET_METRIC_NAMES = frozenset({
    "sgp_steps_total",
    "sgp_step_time_s",
    "sgp_ps_mass_err",
})

# a name routed through a module constant resolves like a literal
ROGUE_SERIES = "sgp_stps_total"  # the classic fat-finger fork


class _Registry:
    def counter(self, name, value=1):
        return (name, value)

    def gauge(self, name, value=0.0):
        return (name, value)

    def histogram(self, name, value=0.0):
        return (name, value)


def record_step(reg: _Registry, dt: float) -> None:
    # registered names are silent
    reg.counter("sgp_steps_total")
    reg.histogram("sgp_step_time_s", dt)
    # literal never declared anywhere: the fork
    reg.counter("sgp_step_total")  # EXPECT: SGPL014
    # same fork, laundered through a module constant
    reg.counter(ROGUE_SERIES)  # EXPECT: SGPL014
    reg.gauge("sgp_mass_err", 0.0)  # EXPECT: SGPL014


def record_dynamic(reg: _Registry, name: str) -> None:
    # an unresolvable argument stays silent: precision over recall —
    # the runtime registry still raises on unregistered names
    reg.gauge(name, 1.0)
