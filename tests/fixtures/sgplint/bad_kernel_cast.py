"""SGPL010 at the fused-kernel wire boundary (ops/gossip_kernel.py).

The gossip wire has exactly one encode path — parallel/wire.py's
WireCodec family — whichever transport moves the bytes.  The fused
Pallas kernel (``gossip_edge_axpy``) ships its ``parts`` tuple exactly
like a ppermute payload, so an inline ``.astype(...)`` in its acc or
parts arguments bypasses pricing and error feedback the same way an
inline ppermute cast does.  The kernel's own IN-KERNEL decode lives in
ops/gossip_kernel.py, which is whitelisted alongside parallel/wire.py.
"""

import jax
import jax.numpy as jnp

from stochastic_gradient_push_tpu.ops.gossip_kernel import gossip_edge_axpy

DESTS = [1, 0]


@jax.jit
def leaky_kernel_send(x, spec):
    # inline down-cast on the kernel's wire parts: the bytes shipped no
    # longer match what the codec priced or the EF residual accounted
    return gossip_edge_axpy(x, (x.astype(jnp.bfloat16),), DESTS,  # EXPECT: SGPL010
                            "gossip", spec)


@jax.jit
def leaky_kernel_acc(x, spec):
    # a cast hidden in the accumulator expression is the same leak
    return gossip_edge_axpy(x.astype(jnp.float32) * 0.5, (x,), DESTS,  # EXPECT: SGPL010
                            "gossip", spec)


@jax.jit
def clean_kernel_send(x, parts, spec):
    # encoded upstream by a WireCodec: the payload arrives cast-free
    return gossip_edge_axpy(x, parts, DESTS, "gossip", spec)
