"""SGPL003: numpy RNG frozen into a traced program."""

import jax
import numpy as np


@jax.jit
def bad_dropout(x):
    mask = np.random.rand(*x.shape) > 0.5  # EXPECT: SGPL003
    noise = np.random.normal(size=x.shape)  # EXPECT: SGPL003
    return x * mask + noise


def host_shuffle(idx):
    # NOT traced: numpy RNG on the host is fine
    return np.random.permutation(idx)
