"""SGPL004: Python control flow on traced values."""

import jax
import jax.numpy as jnp
from jax import lax


@jax.jit
def unstageable(x):
    if jnp.any(x > 0):  # EXPECT: SGPL004
        x = x + 1.0
    while jnp.abs(x).max() > 1.0:  # EXPECT: SGPL004
        x = x * 0.5
    if (lax.psum(x, "gossip") > 0).all():  # EXPECT: SGPL004
        x = -x
    if x.ndim == 2:  # shape is static: silent
        x = x[None]
    return x
