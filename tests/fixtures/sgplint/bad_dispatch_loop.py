"""SGPL012: the PR 8 tier-1 deadlock, reconstructed.

A host loop dispatching a compiled collective step with no blocking
read floods the dispatch queue; with in-process (multi-device CPU)
collectives the runtime deadlocks outright — tier-1 hung exactly this
way until the test loops were serialized.  The rule needs the loop to
be host-side (untraced), the callee to resolve through the closure to
traced code that ships a collective, the trip count to be at least
``DISPATCH_LOOP_MIN_TRIPS``, and the body to contain no blocking read.
``ok_dispatch_loop.py`` is the serialized good twin.
"""

import jax
from jax import lax


@jax.jit
def gossip_step(x):
    # the consensus update: push along the ring and fold in
    return 0.5 * (x + lax.ppermute(x, "gossip", [(0, 1), (1, 0)]))


def raw_step(x):
    return x + lax.psum(x, "gossip")


run_compiled = jax.jit(raw_step)


def consensus_sweep(x):
    # 60 queued compiled collectives, zero reads: the PR 8 shape
    for _ in range(60):  # EXPECT: SGPL012
        x = gossip_step(x)
    return x


def drain_until(x):
    t = 0
    # unbounded while: worse than the counted loop
    while t < 100:  # EXPECT: SGPL012
        x = gossip_step(x)
        t += 1
    return x


def pipeline(x):
    # dispatch through a jit-bound alias resolves the same way
    for _ in range(32):  # EXPECT: SGPL012
        x = run_compiled(x)
    return x


def warmup(x):
    # below DISPATCH_LOOP_MIN_TRIPS: deliberate short pipelining is fine
    for _ in range(3):
        x = gossip_step(x)
    return x
