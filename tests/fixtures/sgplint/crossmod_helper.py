"""Helper half of the cross-module closure fixture.

Standalone (``lint_file``) this module is clean: nothing in it is traced
by its own decorators or wrappers.  Linted as a *set* with
``bad_crossmod.py`` (``lint_paths``), the sibling's jitted step calls
``noisy_scale`` through its import, so the one-hop closure marks it
traced here and the host effect fires.  ``quiet_report`` is never
reached from traced code and must stay silent — the closure is
per-function, not per-module.
"""

import time

import jax.numpy as jnp


def noisy_scale(x):
    t = time.time()  # EXPECT-CROSS: SGPL002 (via lint_paths only)
    return x * jnp.asarray(t, x.dtype)


def quiet_report(x):
    print("host-side summary:", x)  # never called from traced code
    return x


class Reporter:
    """A from-import can only bind a module-top-level name: this method
    shares the imported helper's name but is unreachable through
    ``from crossmod_helper import noisy_scale`` — the cross-module seed
    must not mark it traced."""

    def noisy_scale(self, x):
        return x, time.time()  # untraced namesake: must stay silent
