"""SGPL002: host side effects reachable from jitted code."""

import functools
import time

import jax
import jax.numpy as jnp


@jax.jit
def noisy_step(x):
    print("step!", x)  # EXPECT: SGPL002
    t0 = time.time()  # EXPECT: SGPL002
    y = x * 2.0
    scalar = y.sum().item()  # EXPECT: SGPL002
    jax.debug.print("loss={l}", l=y.sum())  # tracing-safe: silent
    return y + scalar + t0


def helper(x):
    # called from the traced function below -> traced by propagation
    time.sleep(0.1)  # EXPECT: SGPL002
    return x


def outer(x):
    return helper(x) + 1.0


outer_jit = jax.jit(outer)


def host_side(x):
    # NOT traced: effects here are fine
    print("host logging is allowed")
    return time.time()


def configured_step(cfg, x):
    # traced via jax.jit(functools.partial(configured_step, ...))
    print("cfg:", cfg)  # EXPECT: SGPL002
    return x


step_jit = jax.jit(functools.partial(configured_step, {"lr": 0.1}))
