"""SGPV103: a structurally valid schedule that can never reach consensus.

Every phase swaps 0<->1 and 2<->3: each sub-round is a bijection and the
mixing matrix is column-stochastic, but the graph is two disconnected
pairs — the cycle product has |lambda_2| = 1 and the spectral gap is
exactly zero.  This is the failure mode only the semantic engine can see.
"""
# EXPECT-MODULE: SGPV103

from types import SimpleNamespace

import numpy as np

_N = 4
_DISCONNECTED = np.array([[[1, 0, 3, 2]]], dtype=np.int32)

SGPLINT_SCHEDULES = [
    SimpleNamespace(
        perms=_DISCONNECTED,
        self_weight=np.full((1, _N), 0.5),
        edge_weights=np.full((1, 1, _N), 0.5),
        num_phases=1, world_size=_N, peers_per_itr=1),
]
