"""SGPL014 good twin: every emitted name is registered.

Same shape as ``bad_metrics.py`` — a module-level ``*METRIC_NAMES``
declaration plus counter/gauge/histogram emission — but every name
(literal or constant-routed) appears in the vocabulary, so the AST
engine is silent.
"""

FLEET_METRIC_NAMES = frozenset({
    "sgp_steps_total",
    "sgp_step_time_s",
    "sgp_ps_mass_err",
})

MASS_SERIES = "sgp_ps_mass_err"


class _Registry:
    def counter(self, name, value=1):
        return (name, value)

    def gauge(self, name, value=0.0):
        return (name, value)

    def histogram(self, name, value=0.0):
        return (name, value)


def record_step(reg: _Registry, dt: float, err: float) -> None:
    reg.counter("sgp_steps_total")
    reg.histogram("sgp_step_time_s", dt)
    reg.gauge(MASS_SERIES, err)
