"""Entry half of the two-hop closure fixture.

The jitted step calls ``mid_helper`` (one import hop), which calls
``leaf_helper`` in a third module (two hops).  The old one-hop closure
marked ``mid_helper`` traced but never saw the leaf; the full fixpoint
closure keeps propagating and flags the leaf's host effect in the
leaf's own module (see ``test_sgplint.py::
test_two_hop_closure_reaches_the_leaf``).  Standalone, every file in
the trio is clean.
"""

import jax

from twohop_mid import mid_helper


@jax.jit
def step(x):
    return mid_helper(x)
