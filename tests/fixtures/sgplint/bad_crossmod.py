"""Main half of the cross-module closure fixture.

The jitted step calls a helper imported from ``crossmod_helper.py``.
Standalone (``lint_file``) both files are clean; ``lint_paths`` over the
pair resolves the import edge and flags the helper's host effect in the
helper's own module (see ``test_sgplint.py::
test_cross_module_closure_one_import_hop``).
"""

import jax

from crossmod_helper import noisy_scale, quiet_report


@jax.jit
def step(x):
    return noisy_scale(x)


def host_summary(x):
    # untraced caller: reaching quiet_report here must NOT mark it traced
    return quiet_report(x)
