"""SGPL001: collective over an axis name no mesh declares."""

import jax
import jax.numpy as jnp
from jax import lax

PAIRS = [(0, 1), (1, 0)]


@jax.jit
def gossip_step(x):
    sent = lax.ppermute(x, "gosip", PAIRS)  # EXPECT: SGPL001
    total = lax.psum(x, axis_name="gossip_axis")  # EXPECT: SGPL001
    rank = lax.axis_index("gossp")  # EXPECT: SGPL001
    ok = lax.pmean(x, "gossip")  # correctly-spelled axis: silent
    return sent + total + rank + ok


def not_traced(x):
    # axis vocabulary applies outside traced code too: the literal is
    # wrong wherever it is
    return lax.psum(x, "tpp")  # EXPECT: SGPL001
