"""SGPV104: bilateral pairings that would deadlock the exchange."""
# EXPECT-MODULE: SGPV104,SGPV104

import numpy as np

SGPLINT_PAIRINGS = [
    # 3-cycle 0->1->2->0: not an involution
    np.array([[1, 2, 0, 3]], dtype=np.int32),
    # rank 0 paired with itself: fixed point
    np.array([[0, 1, 3, 2]], dtype=np.int32),
]
