"""SGPL005: PRNG key reuse without split/fold_in."""

import jax
import jax.numpy as jnp


def correlated_noise(seed, shape):
    key = jax.random.PRNGKey(seed)
    a = jax.random.normal(key, shape)
    b = jax.random.uniform(key, shape)  # EXPECT: SGPL005
    return a + b


def fresh_keys_ok(seed, shape):
    key = jax.random.PRNGKey(seed)
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, shape)
    b = jax.random.uniform(k2, shape)
    return a + b


def refreshed_ok(seed, shape):
    key = jax.random.PRNGKey(seed)
    a = jax.random.normal(key, shape)
    key = jax.random.fold_in(key, 1)
    b = jax.random.normal(key, shape)
    return a + b
