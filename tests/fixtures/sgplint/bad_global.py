"""SGPL008: global-state mutation inside traced code."""

import jax
import jax.numpy as jnp

_STEP_COUNT = 0


@jax.jit
def counting_step(x):
    global _STEP_COUNT  # EXPECT: SGPL008
    _STEP_COUNT = _STEP_COUNT + 1
    return x * 2.0


def host_counter():
    # NOT traced: host-side global bookkeeping is fine
    global _STEP_COUNT
    _STEP_COUNT += 1
    return _STEP_COUNT
