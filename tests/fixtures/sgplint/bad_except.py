"""SGPL007: broad exception handlers in library code."""


def swallow_everything(path):
    try:
        return open(path).read()
    except Exception:  # EXPECT: SGPL007
        return None


def swallow_harder(path):
    try:
        return open(path).read()
    except:  # noqa: E722  # EXPECT: SGPL007
        return None


def narrow_ok(path):
    try:
        return open(path).read()
    except (OSError, UnicodeDecodeError):
        return None


def tagged_ok(fn):
    try:
        return fn()
    except Exception:  # sgplint: disable=SGPL007 (plugin boundary)
        return None
