"""SGPL013: Pallas DMA/semaphore hygiene violations.

Three kernel-local hazards (a DMA started but never waited, a wait
that only happens on one control path, a barrier-semaphore arity
mismatch) plus the whole-program one: the same ``collective_id``
integer literal at two call sites aliases two logically distinct
collectives onto one hardware slot — the PR 15 review finding.
``ok_dma_kernel.py`` mirrors the shipped ``ops/gossip_kernel.py``
idioms and stays silent.
"""

import functools

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _leaky_kernel(nsteps, x_ref, y_ref, send_sem, recv_sem):
    rdma = pltpu.make_async_remote_copy(  # EXPECT: SGPL013
        src_ref=x_ref, dst_ref=y_ref, send_sem=send_sem,
        recv_sem=recv_sem, device_id=1)
    rdma.start()
    # no rdma.wait(): the copy can still be in flight when the kernel
    # exits and its buffers are reused
    y_ref[...] = y_ref[...] * nsteps


def _conditional_wait_kernel(k, x_ref, y_ref, sem):
    cp = pltpu.make_async_copy(x_ref, y_ref, sem)  # EXPECT: SGPL013
    cp.start()
    if k == 0:
        cp.wait()  # waits on one control path only


def _barrier_arity_kernel(x_ref, y_ref):
    bsem = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(bsem, inc=1, device_id=0)
    pltpu.semaphore_signal(bsem, inc=1, device_id=1)
    pltpu.semaphore_wait(bsem, 3)  # EXPECT: SGPL013
    y_ref[...] = x_ref[...]


def bad_transport(x):
    a = pl.pallas_call(
        functools.partial(_leaky_kernel, 4),
        out_shape=x,
        compiler_params=pltpu.TPUCompilerParams(collective_id=7),  # EXPECT: SGPL013
    )(x)
    b = pl.pallas_call(
        _conditional_wait_kernel,
        out_shape=x,
        compiler_params=pltpu.TPUCompilerParams(collective_id=7),  # EXPECT: SGPL013
    )(a)
    return pl.pallas_call(_barrier_arity_kernel, out_shape=x)(b)
