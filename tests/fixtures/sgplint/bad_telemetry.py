"""SGPL009: telemetry span/event emission reachable from jitted code.

A span opened inside a traced function times *tracing* (once, at
compile), and an event emitted there fires once and never again per
step — both are host-side operations that belong around the compiled
call, not inside it.
"""

import jax
import jax.numpy as jnp


class _FakeTelemetry:
    # stands in for telemetry.RunTelemetry / TelemetryRegistry — the
    # rule matches the emission surface by attribute name, exactly
    # because the real objects arrive as arguments, not imports
    def span(self, name, phase="step", args=None):
        import contextlib

        return contextlib.nullcontext()

    def emit(self, kind, data, step=None, severity="info"):
        return data

    def trace_complete(self, name, phase, start, dur, args=None):
        pass


TEL = _FakeTelemetry()


@jax.jit
def traced_step(x):
    with TEL.span("train_step", "step"):  # EXPECT: SGPL009
        y = x * 2.0
    TEL.emit("step_stats", {"loss": 0.0})  # EXPECT: SGPL009
    TEL.trace_complete("fetch", "data", 0.0, 0.1)  # EXPECT: SGPL009
    return y


def helper(x):
    # called from the traced function below -> traced by propagation
    TEL.emit("comm", {})  # EXPECT: SGPL009
    return x


def outer(x):
    return helper(x) + 1.0


outer_jit = jax.jit(outer)


def host_loop(x):
    # NOT traced: emitting around the compiled call is the whole point
    with TEL.span("train_step", "step"):
        y = jnp.asarray(x) * 2.0
    TEL.emit("step_stats", {"loss": float(y.sum())})
    return y
