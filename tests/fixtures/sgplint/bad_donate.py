"""SGPL006: reading a buffer after donating it to a jitted call."""

import jax
import jax.numpy as jnp


def update(state, batch):
    return state + batch


def train_two_steps(state, batch):
    step = jax.jit(update, donate_argnums=(0,))
    new_state = step(state, batch)
    stale = state.sum()  # EXPECT: SGPL006
    return new_state + stale


def donation_ok(state, batch):
    step = jax.jit(update, donate_argnums=(0,))
    state = step(state, batch)
    return state.sum()  # rebound to the result: silent
