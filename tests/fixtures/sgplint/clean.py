"""Known-clean fixture: every rule's correct counterpart in one module.

Both engines must stay silent here.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from stochastic_gradient_push_tpu.topology.graphs import RingGraph

PAIRS = [(0, 1), (1, 0)]


@jax.jit
def good_step(x, key):
    # collective over a declared axis, tracing-safe logging, staged branch
    y = lax.pmean(x, "gossip")
    jax.debug.print("mean={m}", m=y.sum())
    y = jnp.where(jnp.any(y > 0), y + 1.0, y)
    k1, k2 = jax.random.split(key)
    noise = jax.random.normal(k1, y.shape)
    scale = jax.random.uniform(k2, ())
    return y + noise * scale


def host_loop(path, state, batch):
    # host side: effects, numpy RNG, narrow excepts are all fine here
    print("starting epoch")
    perm = np.random.permutation(len(batch))
    try:
        ckpt = open(path).read()
    except OSError:
        ckpt = None
    step = jax.jit(lambda s, b: s + b, donate_argnums=(0,))
    state = step(state, batch[perm[0]])
    return state, ckpt


# valid schedule material for the semantic engine
SGPLINT_TOPOLOGIES = [RingGraph(8)]
SGPLINT_PAIRINGS = [np.array([[1, 0, 3, 2]], dtype=np.int32)]
