"""resilience/: fault injection, health monitoring, recovery.

Pins the subsystem's three claims: (1) mass-conserving drop semantics
keep push-sum exactly mean-preserving — algebraically (the verifier's
column-stochasticity check on the effective schedule) and dynamically
(the compiled fault path matches the numpy effective-matrix simulator);
(2) the monitor detects what it promises — a mass-LEAKING (naive)
implementation within one health window, NaN corruption the step it
lands; (3) recovery restores consensus below the floor in one
global-average cycle without moving the network mean.
"""

import json
import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from stochastic_gradient_push_tpu.algorithms import dpsgd, sgp
from stochastic_gradient_push_tpu.analysis import verify_schedule
from stochastic_gradient_push_tpu.parallel import GOSSIP_AXIS, make_gossip_mesh
from stochastic_gradient_push_tpu.resilience import (
    HEALTH_KEYS,
    FaultPlan,
    HealthMonitor,
    RecoveryPolicy,
    health_signals,
    make_recovery_fn,
    parse_fault_spec,
)
from stochastic_gradient_push_tpu.topology import (
    NPeerDynamicDirectedExponentialGraph,
    RingGraph,
    build_schedule,
)
from stochastic_gradient_push_tpu.utils import PercentileMeter

WORLD = 8


@pytest.fixture(scope="module")
def mesh():
    return make_gossip_mesh(WORLD)


def _exp_schedule(ppi=1):
    return build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=ppi))


def _world_state(alg, dim=6, seed=0):
    rng = np.random.default_rng(seed)
    params = rng.normal(size=(WORLD, dim)).astype(np.float32)
    gstate = jax.tree.map(
        lambda a: np.broadcast_to(np.asarray(a),
                                  (WORLD,) + np.shape(a)).copy(),
        alg.init(jnp.zeros((dim,), jnp.float32)))
    return params, gstate


def _gossip_fn(alg, mesh, with_health=False):
    def step(params, gstate):
        params, gstate = alg.post_step(params, gstate)
        if not with_health:
            return params, gstate
        sig = health_signals(params, None, gstate.ps_weight, GOSSIP_AXIS)
        return params, gstate, jax.tree.map(lambda a: a[None], sig)

    n_out = 3 if with_health else 2
    return jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(GOSSIP_AXIS),) * 2,
        out_specs=(P(GOSSIP_AXIS),) * n_out))


# -- spec parsing ------------------------------------------------------------

class TestFaultSpec:
    def test_grammar_round_trip(self):
        plan = parse_fault_spec(
            "drop:0->1@10:40;straggler:3@20:30;blackout:2@5:9;"
            "nan:1@50:51;seed:7")
        assert plan.seed == 7
        kinds = [e.kind for e in plan.events]
        assert kinds == ["drop", "straggler", "blackout", "nan"]
        d = json.loads(json.dumps(plan.to_dict()))
        assert d["events"][0] == {"kind": "drop", "start": 10, "end": 40,
                                  "src": 0, "dst": 1}

    def test_open_window_and_horizon(self):
        plan = parse_fault_spec("straggler:3")
        assert plan.events[0].active(0) and plan.events[0].active(10 ** 6)
        # bounded windows get one fault-free row past the last end, so
        # the clamped lookup ends the fault instead of repeating it
        bounded = parse_fault_spec("drop:0->1@2:5")
        assert bounded.horizon() == 6

    @pytest.mark.parametrize("bad", [
        "", "seed:3", "warp:1@0:4", "drop:01@0:4", "drop:0->1@4:2",
        "drop_random:0.5", "drop_random:1.5@0:4", "noise",
    ])
    def test_rejects_malformed(self, bad):
        with pytest.raises(ValueError):
            parse_fault_spec(bad)

    def test_validate_ranks_against_world(self):
        plan = parse_fault_spec("straggler:9@0:4")
        with pytest.raises(ValueError, match="outside"):
            plan.build_masks(_exp_schedule())
        with pytest.raises(ValueError, match="src != dst"):
            FaultPlan.validate(parse_fault_spec("drop:3->3@0:4"), WORLD)

    def test_slice_expands_to_per_rank_blackouts(self):
        # the fleet failure granularity as an in-mesh fault: a whole
        # slice blacks out at once, as sugar over the already-verified
        # blackout machinery
        plan = parse_fault_spec("slice:2-4@10:20")
        assert [(e.kind, e.rank, e.start, e.end) for e in plan.events] \
            == [("blackout", r, 10, 20) for r in (2, 3, 4)]

    def test_slice_fault_is_mass_conserving(self):
        # losing ranks 2-3 for a window must not leak push-sum mass:
        # the effective mixing matrix stays column-stochastic (SGPV102).
        # A zero spectral gap DURING the outage is expected — a dead
        # slice cannot reach consensus until it comes back — so only
        # the mass invariant is pinned here
        from stochastic_gradient_push_tpu.analysis import verify_schedule

        sched = _exp_schedule()
        plan = parse_fault_spec("slice:2-3@0:8")
        plan.build_masks(sched)
        for tick in (0, 3, 7):
            eff = plan.effective_schedule(sched, tick)
            findings, _ = verify_schedule(eff, f"slice-fault@t{tick}",
                                          "<test>", 0)
            mass = [f for f in findings if f.rule == "SGPV102"]
            assert not mass, [f.message for f in mass]
            w = plan.effective_matrix(sched, tick)
            assert np.abs(w.sum(axis=0) - 1.0).max() < 1e-9

    @pytest.mark.parametrize("bad", [
        "slice:2", "slice:3-2@0:4", "slice:-1-2@0:4", "slice:a-b@0:4",
    ])
    def test_slice_rejects_malformed(self, bad):
        with pytest.raises(ValueError):
            parse_fault_spec(bad)

    def test_drop_random_is_seeded_and_windowed(self):
        sched = _exp_schedule()
        a = parse_fault_spec("drop_random:0.5@0:8;seed:3").build_masks(sched)
        b = parse_fault_spec("drop_random:0.5@0:8;seed:3").build_masks(sched)
        c = parse_fault_spec("drop_random:0.5@0:8;seed:4").build_masks(sched)
        assert np.array_equal(a.keep_host(), b.keep_host())
        assert not np.array_equal(a.keep_host(), c.keep_host())
        assert (a.keep_host() == 0).any()
        assert (a.keep_host()[-1] == 1).all()  # past the window: clean


# -- mask semantics ----------------------------------------------------------

class TestMaskSemantics:
    def test_straggler_drops_all_out_edges(self):
        sched = _exp_schedule()
        keep = parse_fault_spec("straggler:3@0:2").build_masks(
            sched).keep_host()
        assert (keep[0:2, :, 3] == 0).all()
        other = np.delete(keep[0:2], 3, axis=2)
        assert (other == 1).all()

    def test_blackout_drops_both_directions(self):
        sched = _exp_schedule()
        keep = parse_fault_spec("blackout:2@0:1").build_masks(
            sched).keep_host()
        assert (keep[0, :, 2] == 0).all()           # sends nothing
        for i in range(sched.peers_per_itr):        # receives nothing
            senders = np.where(sched.perms[0, i] == 2)[0]
            assert (keep[0, i, senders] == 0).all()

    def test_effective_schedule_passes_verifier(self):
        """The ISSUE's acceptance hook: mass-conserving faulted mixing is
        column-stochastic by the ANALYSIS layer's own check (SGPV102),
        not by a private reimplementation."""
        sched = _exp_schedule()
        plan = parse_fault_spec("drop:0->1@0:4;straggler:3@1:3")
        for tick in range(5):
            eff = plan.effective_schedule(sched, tick)
            findings, _ = verify_schedule(eff, f"t{tick}", "<test>", 0)
            # SGPV103 (ergodicity) legitimately fires for a fault state
            # held forever — a transient tick makes no long-run claim;
            # the mass-conservation invariants are SGPV101/102
            hard = [f for f in findings if f.rule in ("SGPV101", "SGPV102")]
            assert not hard, [f.message for f in hard]

    def test_open_ended_drop_tracks_rotation_past_horizon(self):
        """Regression: an open-ended `drop:0->1` on a multi-phase graph
        must keep dropping exactly the 0->1 edge at whichever phases
        carry it — never rank 0's whole out-neighborhood (the one-row
        clamp bug turned a single-edge drop into a full straggler)."""
        sched = _exp_schedule()          # 3 phases: 0 -> 1 / 2 / 4
        assert sched.num_phases > 1
        plan = parse_fault_spec("drop:0->1")
        keep = plan.build_masks(sched).keep_host()
        assert keep.shape[0] == plan.horizon() + sched.num_phases
        for p in range(sched.num_phases):
            row = keep[plan.horizon() + p]
            if sched.perms[p, 0, 0] == 1:
                assert row[0, 0] == 0.0   # the dropped edge, this phase
            else:
                assert row[0, 0] == 1.0   # other out-edges untouched
            assert (np.delete(row, 0, axis=1) == 1.0).all()
        # and the dense matrices agree far past the horizon
        for tick in (0, 5, 7, 100):
            w_eff = plan.effective_matrix(sched, tick)
            p = tick % sched.num_phases
            clean = sched.mixing_matrix(p)
            if sched.perms[p, 0, 0] == 1:
                assert w_eff[1, 0] == 0.0 and w_eff[0, 0] > clean[0, 0]
            else:
                np.testing.assert_allclose(w_eff, clean, atol=1e-12)

    def test_gossip_every_mismatch_rejected_and_alignment(self):
        """Masks are compiled against the thinned rotation: a mismatched
        thinning factor is rejected, and with gossip_every=2 the masks
        resolve phase (t // 2) % num_phases, not t % num_phases."""
        sched = _exp_schedule()
        masks1 = parse_fault_spec("drop:0->1@0:12").build_masks(sched)
        with pytest.raises(ValueError, match="gossip_every"):
            sgp(sched, GOSSIP_AXIS, gossip_every=2, faults=masks1)
        masks2 = parse_fault_spec("drop:0->1@0:12").build_masks(
            sched, gossip_every=2)
        keep = masks2.keep_host()
        for t in range(12):
            p = (t // 2) % sched.num_phases
            expect = 0.0 if sched.perms[p, 0, 0] == 1 else 1.0
            assert keep[t, 0, 0] == expect, t

    def test_naive_masks_leak_mass(self):
        sched = _exp_schedule()
        plan = parse_fault_spec("drop:0->1@0:4")
        w_eff = plan.effective_matrix(sched, 0)
        assert np.allclose(w_eff.sum(axis=0), 1.0, atol=1e-12)
        # strip the reabsorption: the dropped column now sums below 1
        naive = w_eff.copy()
        naive[0, 0] -= sched.edge_weights[0, 0, 0]
        assert naive.sum(axis=0)[0] < 1.0 - 1e-3


# -- dynamics: compiled fault path vs numpy simulator ------------------------

class TestFaultedGossip:
    def test_jit_matches_effective_matrix_sim_and_preserves_mean(self, mesh):
        sched = _exp_schedule()
        plan = parse_fault_spec("drop:0->1@1:4;straggler:3@2:5;seed:7")
        alg = sgp(sched, GOSSIP_AXIS, faults=plan.build_masks(sched))
        step = _gossip_fn(alg, mesh)
        params, gstate = _world_state(alg)
        x0 = params.copy()
        sim_x = x0.astype(np.float64).copy()
        sim_w = np.ones(WORLD)
        for t in range(7):
            params, gstate = jax.block_until_ready(step(params, gstate))
            w_eff = plan.effective_matrix(sched, t)
            sim_x = w_eff @ sim_x
            sim_w = w_eff @ sim_w
            np.testing.assert_allclose(np.asarray(params), sim_x,
                                       rtol=1e-5, atol=1e-5)
            np.testing.assert_allclose(
                np.asarray(gstate.ps_weight).ravel(), sim_w,
                rtol=1e-5, atol=1e-6)
            # the claim: network-wide mean preserved under faults
            np.testing.assert_allclose(np.asarray(params).mean(0),
                                       x0.mean(0), rtol=1e-4, atol=1e-6)

    def test_thinned_faulted_gossip_matches_sim(self, mesh):
        """gossip_every=2 + faults: fired rounds use rotation t//2 while
        fault windows stay on the step clock — the compiled path must
        match the numpy simulator built from the same convention."""
        sched = _exp_schedule()
        plan = parse_fault_spec("drop:0->1@0:8")
        alg = sgp(sched, GOSSIP_AXIS, gossip_every=2,
                  faults=plan.build_masks(sched, gossip_every=2))
        step = _gossip_fn(alg, mesh)
        params, gstate = _world_state(alg, seed=5)
        x0 = params.copy()
        sim_x = x0.astype(np.float64).copy()
        sim_w = np.ones(WORLD)
        for t in range(10):
            params, gstate = jax.block_until_ready(step(params, gstate))
            if t % 2 == 0:  # fired rounds only
                w_eff = plan.effective_matrix(sched, t, gossip_every=2)
                sim_x = w_eff @ sim_x
                sim_w = w_eff @ sim_w
            np.testing.assert_allclose(np.asarray(params), sim_x,
                                       rtol=1e-5, atol=1e-5)
            np.testing.assert_allclose(
                np.asarray(gstate.ps_weight).ravel(), sim_w,
                rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(params).mean(0), x0.mean(0),
                                   rtol=1e-4, atol=1e-6)

    def test_consensus_after_faults_end(self, mesh):
        """Bounded faults heal on their own: once the window closes, the
        de-biased estimates converge to the TRUE initial mean (no
        information was destroyed — only delayed)."""
        sched = _exp_schedule()
        plan = parse_fault_spec("drop:0->1@0:6;seed:1")
        alg = sgp(sched, GOSSIP_AXIS, faults=plan.build_masks(sched))
        step = _gossip_fn(alg, mesh)
        params, gstate = _world_state(alg, seed=1)
        x0 = params.copy()
        for _ in range(50):
            params, gstate = jax.block_until_ready(step(params, gstate))
        z = np.asarray(params) / np.asarray(gstate.ps_weight).reshape(
            WORLD, 1)
        np.testing.assert_allclose(
            z, np.broadcast_to(x0.mean(0), z.shape), rtol=1e-3, atol=1e-4)

    def test_nan_corruption_reaches_receiver_payloads(self, mesh):
        sched = _exp_schedule()
        plan = parse_fault_spec("nan:1@0:1")
        alg = sgp(sched, GOSSIP_AXIS, faults=plan.build_masks(sched))
        step = _gossip_fn(alg, mesh, with_health=True)
        params, gstate = _world_state(alg)
        params, gstate, sig = jax.block_until_ready(step(params, gstate))
        # rank 1's out-payloads are poisoned -> some params are NaN...
        assert float(np.asarray(sig["nonfinite_params"])[0]) > 0
        # ...but the ps-weight lane stays finite (telemetry survives)
        assert np.isfinite(np.asarray(gstate.ps_weight)).all()

    def test_dpsgd_rejects_faults(self):
        sched = _exp_schedule()
        masks = parse_fault_spec("drop:0->1@0:4").build_masks(sched)
        with pytest.raises(ValueError, match="push-sum"):
            dpsgd(sched, GOSSIP_AXIS, faults=masks)

    def test_overlap_composes_with_faults(self):
        # masks are keyed on the LAUNCH tick, so the overlap phase
        # schedule takes fault plans like sync does (mass conservation
        # under overlap+drop is pinned in tests/test_overlap.py)
        sched = _exp_schedule()
        masks = parse_fault_spec("drop:0->1@0:4").build_masks(sched)
        alg = sgp(sched, GOSSIP_AXIS, overlap=True, faults=masks)
        assert alg.overlap and alg.faults is masks
        # the thinning cross-check still applies under overlap
        masks2 = parse_fault_spec("drop:0->1@0:4").build_masks(
            sched, gossip_every=2)
        with pytest.raises(ValueError, match="gossip_every"):
            sgp(sched, GOSSIP_AXIS, overlap=True, faults=masks2)


# -- monitor -----------------------------------------------------------------

class TestMonitor:
    def _signals(self, **over):
        sig = {"consensus_residual": 0.0, "ps_w_min": 1.0, "ps_w_max": 1.0,
               "ps_mass_err": 0.0, "nonfinite_params": 0.0,
               "nonfinite_grads": 0.0}
        sig.update(over)
        return sig

    def test_healthy_line_cadence(self, caplog):
        log = logging.getLogger("t-monitor-cadence")
        mon = HealthMonitor(health_every=3, residual_floor=0.1, log=log)
        with caplog.at_level(logging.INFO, logger=log.name):
            for t in range(1, 7):
                mon.observe(t, self._signals())
        lines = [r.message for r in caplog.records
                 if r.message.startswith("gossip health: ")]
        assert len(lines) == 2  # steps 3 and 6
        payload = json.loads(lines[0][len("gossip health: "):])
        assert set(HEALTH_KEYS) <= set(payload)
        assert "reasons" not in payload

    def test_excursion_logs_immediately_with_reasons(self, caplog):
        log = logging.getLogger("t-monitor-excursion")
        mon = HealthMonitor(health_every=1000, residual_floor=0.1, log=log)
        with caplog.at_level(logging.INFO, logger=log.name):
            report = mon.observe(1, self._signals(consensus_residual=0.5))
        assert report.unhealthy
        assert report.reasons == ("residual-above-floor",)
        assert any("residual-above-floor" in r.message
                   for r in caplog.records)

    def test_mass_leak_detected_within_health_window(self, mesh):
        """Regression: NAIVE dropping (no reabsorption) must be caught by
        the monitor within health_every steps — the exact detection the
        ps_mass_err signal exists for."""
        sched = _exp_schedule()
        plan = parse_fault_spec("drop:0->1@0:64")
        naive = plan.build_masks(sched, reabsorb=False)
        alg = sgp(sched, GOSSIP_AXIS, faults=naive)
        step = _gossip_fn(alg, mesh, with_health=True)
        params, gstate = _world_state(alg)
        health_every = 4
        mon = HealthMonitor(health_every=health_every, residual_floor=1e9)
        flagged_at = None
        for t in range(1, health_every + 1):
            params, gstate, sig = jax.block_until_ready(
                step(params, gstate))
            report = mon.observe(
                t, {k: float(np.asarray(sig[k])[0]) for k in HEALTH_KEYS})
            if "push-sum-mass-leak" in report.reasons:
                flagged_at = t
                break
        assert flagged_at is not None and flagged_at <= health_every
        # and mass-conserving masks DON'T trip it over the same window
        alg2 = sgp(sched, GOSSIP_AXIS, faults=plan.build_masks(sched))
        step2 = _gossip_fn(alg2, mesh, with_health=True)
        params, gstate = _world_state(alg2)
        mon2 = HealthMonitor(health_every=health_every, residual_floor=1e9)
        for t in range(1, health_every + 1):
            params, gstate, sig = jax.block_until_ready(
                step2(params, gstate))
            report = mon2.observe(
                t, {k: float(np.asarray(sig[k])[0]) for k in HEALTH_KEYS})
            assert "push-sum-mass-leak" not in report.reasons

    def test_nan_signals_flag_nonfinite(self):
        mon = HealthMonitor(health_every=1, residual_floor=0.1)
        report = mon.observe(1, self._signals(nonfinite_params=12.0,
                                              consensus_residual=float(
                                                  "nan")))
        assert "nonfinite-params" in report.reasons
        assert "residual-above-floor" in report.reasons

    def test_step_time_percentiles_ride_payload(self):
        mon = HealthMonitor(health_every=1, residual_floor=0.1)
        for v in [0.1] * 99 + [2.0]:
            mon.record_step_time(v)
        report = mon.observe(1, self._signals())
        assert report.payload["step_p50_s"] == pytest.approx(0.1)
        assert report.payload["step_p99_s"] == pytest.approx(2.0)


class TestPercentileMeter:
    def test_percentiles_and_bounded_window(self):
        m = PercentileMeter(maxlen=100)
        for v in range(1000):
            m.update(float(v))
        assert m.count == 1000
        assert len(m._window) == 100          # bounded memory
        assert m.p50 == pytest.approx(950.0, abs=2)
        assert m.p99 == pytest.approx(999.0, abs=1)
        assert m.percentile(0) == 900.0

    def test_empty_and_validation(self):
        m = PercentileMeter()
        assert m.p50 == 0.0
        m.update(1.0)
        with pytest.raises(ValueError):
            m.percentile(101)
        with pytest.raises(ValueError):
            PercentileMeter(maxlen=0)


# -- recovery ----------------------------------------------------------------

class TestRecovery:
    def _report(self, step=5, **over):
        from stochastic_gradient_push_tpu.resilience.monitor import \
            HealthReport
        reasons = over.pop("reasons", ("residual-above-floor",))
        return HealthReport(step=step, payload={"step": step},
                            reasons=tuple(reasons))

    def test_fires_global_average_with_planner_suggestion(self):
        pol = RecoveryPolicy(world=8, topology="ring", cooldown_steps=0)
        event = pol.assess(self._report())
        assert event.action == "global-average"
        assert event.suggestion["topology"] != "ring"
        assert event.suggestion["switch"] is True
        assert 0.0 < event.suggestion["gap"] <= 1.0

    def test_replan_prices_on_the_run_fabric(self):
        # a hierarchical run on a DCN-dominant pod must not be advised
        # to switch to a flat graph just because the re-plan forgot the
        # fabric it was planned on
        from stochastic_gradient_push_tpu.planner import InterconnectModel

        fabric = InterconnectModel(slice_size=8, dcn_cost=16.0)
        pol = RecoveryPolicy(world=64, topology="hierarchical",
                             cooldown_steps=0, interconnect=fabric)
        suggestion = pol.replan()
        assert suggestion["topology"] == "hierarchical"
        assert suggestion["switch"] is False

    def test_replan_honors_fault_injection(self):
        # a fault-injected run cannot relaunch on a hierarchical schedule
        # (per-edge masks don't decompose across the grouped psum), so
        # the suggestion must stay flat even on a DCN-dominant fabric
        from stochastic_gradient_push_tpu.planner import InterconnectModel

        fabric = InterconnectModel(slice_size=8, dcn_cost=16.0)
        pol = RecoveryPolicy(world=64, cooldown_steps=0,
                             interconnect=fabric, faults=True)
        assert pol.replan()["topology"] != "hierarchical"

    def test_cooldown_and_circuit_breaker(self):
        pol = RecoveryPolicy(world=8, cooldown_steps=10, max_recoveries=2)
        assert pol.assess(self._report(step=0)).action == "global-average"
        assert pol.assess(self._report(step=5)).action == "none"
        assert pol.assess(self._report(step=10)).action == "global-average"
        # circuit breaker: third firing refused even off cooldown
        assert pol.assess(self._report(step=50)).action == "none"

    def test_poisoned_state_advises_restore(self):
        pol = RecoveryPolicy(world=8, cooldown_steps=0)
        event = pol.assess(self._report(
            reasons=("nonfinite-params", "residual-above-floor")))
        assert event.action == "advise-restore"
        assert pol.recoveries == 0

    def test_recovery_fn_restores_consensus_and_mean(self, mesh):
        sched = build_schedule(RingGraph(WORLD, peers_per_itr=1))
        plan = parse_fault_spec("drop:0->1@0:64")
        alg = sgp(sched, GOSSIP_AXIS, faults=plan.build_masks(sched))
        step = _gossip_fn(alg, mesh, with_health=True)
        params, gstate = _world_state(alg, dim=16, seed=3)
        x0 = params.copy()
        for _ in range(4):
            params, gstate, sig = jax.block_until_ready(
                step(params, gstate))
        assert float(np.asarray(sig["consensus_residual"])[0]) > 0.01
        recover = make_recovery_fn(alg, mesh)
        params, psw = recover(params, gstate.ps_weight)
        gstate = gstate.replace(ps_weight=psw)
        z = np.asarray(params) / np.asarray(psw).reshape(WORLD, 1)
        np.testing.assert_allclose(
            z, np.broadcast_to(x0.mean(0), z.shape), rtol=1e-5, atol=1e-6)
        assert np.allclose(np.asarray(psw), 1.0)
        # one more faulted round: residual stays below the floor
        params, gstate, sig = jax.block_until_ready(step(params, gstate))
        assert float(np.asarray(sig["consensus_residual"])[0]) < 0.01

    def test_recovery_fn_rejects_algorithms_without_average(self, mesh):
        from stochastic_gradient_push_tpu.algorithms import all_reduce
        with pytest.raises(ValueError, match="global_average"):
            make_recovery_fn(all_reduce(GOSSIP_AXIS), mesh)

    def test_recovery_fn_folds_and_drains_overlap(self, mesh):
        """The reactive average under overlap folds the in-flight FIFO
        into Σx/Σw (each pending share counted exactly once) and drains
        it — the exact mean survives, nothing is double-counted."""
        alg = sgp(_exp_schedule(), GOSSIP_AXIS, overlap=True, staleness=2)
        fn = make_recovery_fn(alg, mesh)
        rng = np.random.default_rng(11)
        params = rng.normal(size=(WORLD, 6)).astype(np.float32)
        in_p = rng.normal(size=(WORLD, 6)).astype(np.float32)
        # a mid-flight state: half the weight mass rides the FIFO
        ps_w = np.full((WORLD,), 0.5, np.float32)
        in_w = np.full((WORLD,), 0.5, np.float32)
        fifo = ((in_p, in_w),
                (np.zeros_like(in_p), np.zeros_like(in_w)))
        new_p, new_w, new_fl = jax.block_until_ready(
            fn(params, ps_w, fifo))
        want = (params.astype(np.float64).sum(0)
                + in_p.astype(np.float64).sum(0)) / WORLD
        np.testing.assert_allclose(np.asarray(new_p),
                                   np.broadcast_to(want, (WORLD, 6)),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(new_w), 1.0, rtol=1e-6)
        for slot_p, slot_w in new_fl:
            np.testing.assert_allclose(np.asarray(slot_p), 0.0)
            np.testing.assert_allclose(np.asarray(slot_w), 0.0)


# -- chaos selftest (the CI gate, run in-process) ----------------------------

def test_chaos_selftest_passes(capsys):
    from stochastic_gradient_push_tpu.resilience.chaos import main
    assert main(["--selftest"]) == 0
    assert "chaos selftest: OK" in capsys.readouterr().out


def test_chaos_describe_reports_mass_conservation(capsys):
    from stochastic_gradient_push_tpu.resilience.chaos import main
    assert main(["--describe", "drop:0->1@0:4", "--topology", "ring",
                 "--world", "8"]) == 0
    out = capsys.readouterr().out
    assert "mass-conserving" in out


# -- CLI wiring --------------------------------------------------------------

class TestCLIWiring:
    def test_sgd_flags_thread_into_config(self):
        from stochastic_gradient_push_tpu.run.gossip_sgd import parse_config
        cfg, _ = parse_config(["--inject_faults", "drop:0->1@0:4",
                               "--health_every", "10",
                               "--residual_floor", "0.05"])
        assert cfg.inject_faults == "drop:0->1@0:4"
        assert cfg.health_every == 10
        assert cfg.residual_floor == 0.05

    def test_sgd_rejects_bad_fault_configs(self):
        from stochastic_gradient_push_tpu.run.gossip_sgd import parse_config
        with pytest.raises(SystemExit, match="push-sum"):
            parse_config(["--inject_faults", "drop:0->1@0:4",
                          "--all_reduce", "True", "--graph_type", "-1"])
        with pytest.raises(SystemExit, match="push-sum"):
            parse_config(["--inject_faults", "drop:0->1@0:4",
                          "--push_sum", "False"])
        # overlap + faults is a supported composition (launch-tick masks)
        cfg, _ = parse_config(["--inject_faults", "drop:0->1@0:4",
                               "--overlap", "True"])
        assert cfg.overlap and cfg.inject_faults == "drop:0->1@0:4"
        with pytest.raises(ValueError, match="unknown fault kind"):
            parse_config(["--inject_faults", "warp:0@0:4"])

    def test_trainer_rejects_faults_outside_gossip(self):
        from stochastic_gradient_push_tpu.train.loop import (
            Trainer, TrainerConfig)
        cfg = TrainerConfig(all_reduce=True, inject_faults="straggler:0",
                            checkpoint_dir="/tmp/x")
        mesh = make_gossip_mesh(WORLD)
        tr = Trainer(cfg, model=None, mesh=mesh,
                     sample_input_shape=(1, 8, 8, 3))
        with pytest.raises(ValueError, match="gossip"):
            tr.make_algorithm(1)

    def test_lm_mixing_alpha_rejections_match_gossip_sgd(self):
        """Satellite: --mixing_alpha lands in the LM CLI with the same
        error text as gossip_sgd."""
        from stochastic_gradient_push_tpu.run.gossip_lm import main as lm
        base = ["--world_size", "8", "--seq_len", "32", "--d_model", "32",
                "--n_layers", "1", "--n_heads", "4", "--d_ff", "32",
                "--vocab_size", "32", "--batch_size", "2",
                "--num_steps", "1"]
        with pytest.raises(SystemExit, match="needs push-sum gossip"):
            lm(base + ["--mixing_alpha", "auto", "--all_reduce", "True"])
        with pytest.raises(SystemExit, match="doubly-stochastic"):
            lm(base + ["--mixing_alpha", "auto", "--push_sum", "False"])
        with pytest.raises(SystemExit, match="do not apply"):
            lm(base + ["--mixing_alpha", "auto", "--bilat", "True"])
        with pytest.raises(SystemExit):
            lm(base + ["--mixing_alpha", "1.5"])

    def test_lm_health_flag_validation(self):
        from stochastic_gradient_push_tpu.run.gossip_lm import main as lm
        base = ["--world_size", "8", "--seq_len", "32", "--d_model", "32",
                "--n_layers", "1", "--n_heads", "4", "--d_ff", "32",
                "--vocab_size", "32", "--batch_size", "2",
                "--num_steps", "1"]
        with pytest.raises(SystemExit, match="multiple of"):
            lm(base + ["--health_every", "7", "--print_freq", "10"])
        with pytest.raises(SystemExit, match="flat dp"):
            lm(base + ["--health_every", "10", "--tp", "2"])
        with pytest.raises(SystemExit, match="push-sum"):
            lm(base + ["--inject_faults", "drop:0->1@0:4",
                       "--all_reduce", "True"])


@pytest.mark.slow
def test_sgd_cli_chaos_end_to_end(tmp_path, capfd):
    """Whole-stack: CLI flags -> faulted compiled step -> health lines ->
    recovery -> checkpoint written.  The project logger writes to stdout
    with propagate=False (utils/logging.py), so capture at the fd."""
    from stochastic_gradient_push_tpu.run.gossip_sgd import main
    from stochastic_gradient_push_tpu.utils import reset_logger

    # make_logger latches its stream at first creation; an earlier test
    # may have created these loggers under ITS captured stdout — rebind
    # via the public hook (utils/logging.py reset_logger)
    for name in ("main", "trainer"):
        reset_logger(name)
    main(["--dataset", "synthetic", "--model", "tiny_cnn",
          "--num_classes", "10", "--image_size", "16",
          "--batch_size", "4", "--world_size", "8",
          "--num_epochs", "1",
          "--num_iterations_per_training_epoch", "4",
          "--num_itr_ignore", "0",
          "--inject_faults", "drop:0->1@0:2",
          "--health_every", "1", "--residual_floor", "0.0000001",
          "--checkpoint_dir", str(tmp_path)])
    out = capfd.readouterr().out
    health = [l for l in out.splitlines() if "gossip health: " in l]
    assert health, "no gossip health: lines emitted"
    payload = json.loads(health[0].split("gossip health: ", 1)[1])
    assert set(HEALTH_KEYS) <= set(payload)
    assert any("gossip recovery: " in l for l in out.splitlines())
    from stochastic_gradient_push_tpu.utils.checkpoint import \
        CheckpointManager
    ckpt = CheckpointManager(str(tmp_path), rank=0, world_size=8)
    assert ckpt.exists()
