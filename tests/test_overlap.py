"""Overlap (OSGP) as a first-class phase schedule: the interaction matrix.

The double-buffered round — launch at the top of the step
(``collectives.overlap_launch``), consume at the bottom — must compose
with everything the synchronous round composes with: fault injection
(masks keyed on the LAUNCH tick), wire codecs + error feedback (the
residual telescopes against the SENT round), communication thinning,
periodic/reactive exact averaging (fold + drain the FIFO), hierarchical
two-level schedules (only the delegate share defers), and the comm
accountant (bytes identical to sync — overlap moves wall-clock, not
volume).  Every compiled check here serializes dispatch per the OSGP
deadlock note (CHANGES.md PR 8): XLA CPU in-process collectives hang
when many executions are in flight concurrently.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from stochastic_gradient_push_tpu.algorithms import sgp
from stochastic_gradient_push_tpu.analysis import verify_schedule
from stochastic_gradient_push_tpu.parallel import (
    GOSSIP_AXIS,
    make_gossip_mesh,
)
from stochastic_gradient_push_tpu.parallel.wire import Int8Codec
from stochastic_gradient_push_tpu.resilience import parse_fault_spec
from stochastic_gradient_push_tpu.topology import (
    GRAPH_TOPOLOGIES,
    HierarchicalGraph,
    NPeerDynamicDirectedExponentialGraph,
    build_schedule,
)

WORLD = 8
DIM = 6

rng = np.random.default_rng(7)
X0 = rng.normal(size=(WORLD, DIM)).astype(np.float32)


@pytest.fixture(scope="module")
def mesh():
    return make_gossip_mesh(WORLD)


def stack_state(state):
    return jax.tree.map(
        lambda a: np.broadcast_to(np.asarray(a),
                                  (WORLD,) + np.shape(a)).copy(),
        state)


def make_avg_runner(alg, mesh):
    """Jitted pure-averaging step (lr=0): pre_step → post_step."""

    def step(params, gstate):
        params, gstate = alg.pre_step(params, gstate)
        return alg.post_step(params, gstate)

    return jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS)),
        out_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS))))


def total_mass(params, gstate, residual=False):
    """Σ over ranks of params + every in-flight slot (+ EF residual)."""
    tot = np.asarray(params, np.float64).sum(axis=0)
    for in_p, _ in gstate.in_flight:
        tot = tot + np.asarray(in_p, np.float64).sum(axis=0)
    if residual and gstate.ef_residual is not None:
        tot = tot + np.asarray(gstate.ef_residual, np.float64).sum(axis=0)
    return tot


def weight_mass(gstate):
    w = np.asarray(gstate.ps_weight, np.float64).sum()
    for _, in_w in gstate.in_flight:
        w += np.asarray(in_w, np.float64).sum()
    return w


def debias(params, gstate):
    w = np.asarray(gstate.ps_weight).reshape(WORLD, 1)
    return np.asarray(params) / w


# -- acceptance: the verifier takes the overlap schedule everywhere ---------

def test_overlap_schedule_verifies_for_all_flat_topologies():
    """``analysis.verify_schedule`` accepts the one-round-stale augmented
    matrix (column-stochastic + contracting) for EVERY registered flat
    topology at world 2–64, staleness 1–3 — the SGPV106 object."""
    classes = sorted({c for c in GRAPH_TOPOLOGIES.values()
                      if c is not None and c is not HierarchicalGraph},
                     key=lambda c: c.__name__)
    checked = 0
    for cls in classes:
        for world in (2, 3, 4, 5, 6, 8, 12, 16, 24, 32, 48, 64):
            for ppi in (1, 2):
                try:
                    graph = cls(world, peers_per_itr=ppi)
                except ValueError:
                    continue  # unsupported cell, same skip as the sweep
                sched = build_schedule(graph)
                for s in (1, 2, 3):
                    ov = sched.overlap_schedule(s)
                    assert ov.world_size == world * s
                    findings, gap = verify_schedule(
                        ov, f"{cls.__name__}(w={world}, ppi={ppi}, "
                            f"staleness={s})", "<test>", 1)
                    assert not findings, [str(f) for f in findings]
                    assert np.isfinite(gap) and (world == 1 or gap > 0)
                    checked += 1
    assert checked > 100  # the sweep actually covered the grid


def test_overlap_schedule_validation():
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    with pytest.raises(ValueError, match="staleness"):
        sched.overlap_schedule(0)
    assert sched.overlap_schedule(1) is sched  # same-step consume = W
    hier = build_schedule(HierarchicalGraph(WORLD))
    with pytest.raises(ValueError, match="hierarchical"):
        hier.overlap_schedule(2)


# -- overlap × fault injection ----------------------------------------------

def test_overlap_drop_mass_conservation(mesh):
    """overlap + ``drop:S->D``: masks are resolved at the LAUNCH tick, the
    sender reabsorbs the undelivered weight when the wire fires, and the
    dropped share rides the FIFO as an exact zero — so total mass
    (params + in-flight, both lanes) is conserved at every step and the
    de-biased consensus still lands on the true initial mean."""
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    plan = parse_fault_spec("drop:0->1@2:6;drop:3->5;seed:3")
    masks = plan.build_masks(sched)
    alg = sgp(sched, GOSSIP_AXIS, overlap=True, staleness=2,
              faults=masks)
    f = make_avg_runner(alg, mesh)

    params = X0.copy()
    gstate = stack_state(alg.init(jnp.zeros((DIM,), jnp.float32)))
    want = X0.astype(np.float64).sum(axis=0)
    for t in range(30):
        params, gstate = jax.block_until_ready(f(params, gstate))
        np.testing.assert_allclose(total_mass(params, gstate), want,
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"step {t}")
        np.testing.assert_allclose(weight_mass(gstate), WORLD,
                                   rtol=1e-5, err_msg=f"step {t}")
    for _ in range(170):
        params, gstate = jax.block_until_ready(f(params, gstate))
    z = debias(params, gstate)
    np.testing.assert_allclose(
        z, np.broadcast_to(X0.mean(axis=0), z.shape), atol=2e-3)


# -- overlap × int8 wire × error feedback -----------------------------------

def test_overlap_int8_ef_telescoping(mesh):
    """overlap + int8 + EF: the residual telescopes against the SENT
    round, so ``Σ(params + in-flight + residual)`` is EXACTLY the
    uncompressed mass at every step (delivered + pending == exact
    mixing), the never-quantized ps-weight lane matches the f32 overlap
    run, and consensus lands within quantization tolerance of the mean."""
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    alg = sgp(sched, GOSSIP_AXIS, overlap=True, staleness=2,
              wire=Int8Codec(block=16), error_feedback=True)
    ref = sgp(sched, GOSSIP_AXIS, overlap=True, staleness=2)
    f = make_avg_runner(alg, mesh)
    f_ref = make_avg_runner(ref, mesh)

    params = X0.copy()
    gstate = stack_state(alg.init(jnp.zeros((DIM,), jnp.float32)))
    assert gstate.ef_residual is not None
    p_ref = X0.copy()
    g_ref = stack_state(ref.init(jnp.zeros((DIM,), jnp.float32)))
    want = X0.astype(np.float64).sum(axis=0)
    for t in range(40):
        params, gstate = jax.block_until_ready(f(params, gstate))
        p_ref, g_ref = jax.block_until_ready(f_ref(p_ref, g_ref))
        # the telescoping identity: quantization error lives in the
        # residual, never in the network mass
        np.testing.assert_allclose(
            total_mass(params, gstate, residual=True), want,
            rtol=1e-4, atol=1e-4, err_msg=f"step {t}")
        # the ps-weight lane never goes through the codec: identical
        # trajectory to the uncompressed overlap run
        np.testing.assert_allclose(np.asarray(gstate.ps_weight),
                                   np.asarray(g_ref.ps_weight),
                                   rtol=1e-6, atol=1e-7,
                                   err_msg=f"step {t}")
    z = debias(params, gstate)
    np.testing.assert_allclose(
        z, np.broadcast_to(X0.mean(axis=0), z.shape), atol=5e-2)
    # the pending residual stays bounded (EF, not a leak)
    assert np.abs(np.asarray(gstate.ef_residual)).max() < 1.0


# -- overlap × thinning ------------------------------------------------------

def test_overlap_thinning_matches_numpy(mesh):
    """overlap + ``gossip_every=2`` at staleness 1: firing steps apply
    the rotation's W exactly (same-step launch+consume), non-firing
    steps are the identity, and the rotation advances only with fired
    rounds — the same clock as the sync thinned path."""
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    alg = sgp(sched, GOSSIP_AXIS, overlap=True, gossip_every=2)
    f = make_avg_runner(alg, mesh)

    params = X0.copy()
    gstate = stack_state(alg.init(jnp.zeros((DIM,), jnp.float32)))
    sim = X0.astype(np.float64).copy()
    for t in range(9):
        params, gstate = jax.block_until_ready(f(params, gstate))
        if t % 2 == 0:
            sim = sched.mixing_matrix(t // 2) @ sim
        np.testing.assert_allclose(np.asarray(params), sim,
                                   rtol=1e-5, atol=1e-5,
                                   err_msg=f"step {t}")


# -- overlap × periodic exact averaging --------------------------------------

def test_overlap_global_avg_folds_and_drains(mesh):
    """overlap + ``global_avg_every``: the fired average folds the
    in-flight FIFO into Σx/Σw and drains it — at lr=0 every rank snaps
    to EXACTLY the initial mean (in-flight mass included), ps-weight
    resets to 1, and the FIFO is empty."""
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    alg = sgp(sched, GOSSIP_AXIS, overlap=True, staleness=2,
              global_avg_every=3)
    f = make_avg_runner(alg, mesh)

    params = X0.copy()
    gstate = stack_state(alg.init(jnp.zeros((DIM,), jnp.float32)))
    for t in range(3):  # steps 0,1,2; the average fires at tick_next=3
        params, gstate = jax.block_until_ready(f(params, gstate))
    np.testing.assert_allclose(
        np.asarray(params),
        np.broadcast_to(X0.mean(axis=0), (WORLD, DIM)),
        rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gstate.ps_weight),
                               np.ones(WORLD), rtol=1e-6)
    for in_p, in_w in gstate.in_flight:
        np.testing.assert_allclose(np.asarray(in_p), 0.0, atol=1e-7)
        np.testing.assert_allclose(np.asarray(in_w), 0.0, atol=1e-7)


# -- overlap × hierarchical two-level schedule -------------------------------

def test_hierarchical_overlap_mass_and_consensus(mesh):
    """overlap on the two-level schedule: only the delegate (DCN) share
    defers; the ICI-local intra-slice psum runs at consume time.  Mass
    (params + in-flight, both lanes) is conserved every step and the
    de-biased consensus reaches the initial mean — the invariant the
    augmented-table form cannot express is pinned numerically here."""
    sched = build_schedule(HierarchicalGraph(WORLD))
    alg = sgp(sched, GOSSIP_AXIS, overlap=True, staleness=2)
    f = make_avg_runner(alg, mesh)

    params = X0.copy()
    gstate = stack_state(alg.init(jnp.zeros((DIM,), jnp.float32)))
    want = X0.astype(np.float64).sum(axis=0)
    for t in range(20):
        params, gstate = jax.block_until_ready(f(params, gstate))
        np.testing.assert_allclose(total_mass(params, gstate), want,
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"step {t}")
        np.testing.assert_allclose(weight_mass(gstate), WORLD,
                                   rtol=1e-5, err_msg=f"step {t}")
    for _ in range(60):
        params, gstate = jax.block_until_ready(f(params, gstate))
    z = debias(params, gstate)
    np.testing.assert_allclose(
        z, np.broadcast_to(X0.mean(axis=0), z.shape), atol=2e-3)


# -- checkpoint: drain at save, reshard like sync ----------------------------

def test_overlap_checkpoint_drains_and_reshards(tmp_path, mesh):
    """A formerly-overlap Trainer checkpoint: the save barrier drains the
    in-flight FIFO into params (satellite: supervise/reshard.py used to
    reject these), so the on-disk state carries zero slots and reshards
    to a smaller world with the mean preserved."""
    from stochastic_gradient_push_tpu.data import (
        DistributedSampler, ShardedLoader, synthetic_classification)
    from stochastic_gradient_push_tpu.models import TinyMLP
    from stochastic_gradient_push_tpu.supervise import (
        consensus_mean, load_world_checkpoint, reshard_state)
    from stochastic_gradient_push_tpu.train.loop import (
        Trainer, TrainerConfig)
    from stochastic_gradient_push_tpu.utils.checkpoint import (
        CheckpointManager, ClusterManager)

    batch, classes, img = 4, 4, 8
    images, labels = synthetic_classification(
        WORLD * batch * 2, num_classes=classes, image_size=img, seed=5)
    cfg = TrainerConfig(
        graph_class=NPeerDynamicDirectedExponentialGraph,
        overlap=True, staleness=2, lr=0.1, batch_size=batch,
        num_epochs=1, num_itr_ignore=0, checkpoint_dir=str(tmp_path),
        num_classes=classes, verbose=False)
    ckpt = CheckpointManager(str(tmp_path), world_size=WORLD)
    trainer = Trainer(cfg, TinyMLP(num_classes=classes), mesh,
                      sample_input_shape=(batch, img, img, 3),
                      cluster_manager=ClusterManager(
                          ckpt, install_handlers=False))
    state = trainer.init_state()
    sampler = DistributedSampler(len(images), WORLD)
    loader = ShardedLoader(images, labels, batch, sampler)
    live, _ = trainer.fit(state, loader, sampler, val_loader=None)

    # the live state was drained at the save barrier too (the continuing
    # run and a resumed run share one trajectory)
    for in_p, in_w in live.gossip.in_flight:
        for leaf in jax.tree.leaves(in_p):
            np.testing.assert_allclose(np.asarray(leaf), 0.0, atol=1e-7)
        np.testing.assert_allclose(np.asarray(in_w), 0.0, atol=1e-7)

    saved, _, _ = load_world_checkpoint(str(tmp_path), "", WORLD)
    fifo = saved["gossip"]["in_flight"]
    assert fifo and all(
        not np.asarray(leaf).any()
        for slot in fifo.values()
        for _, leaf in _walk_leaves(slot))
    before = consensus_mean(saved)
    new = reshard_state(saved, WORLD, 4)
    after = consensus_mean(new)
    for k in before:
        np.testing.assert_allclose(after[k], before[k], atol=1e-6,
                                   err_msg=k)


def _walk_leaves(tree, path=()):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _walk_leaves(v, path + (k,))
    else:
        yield path, tree


# -- health monitoring sees the drained view ---------------------------------

def test_overlap_health_signals_use_drained_view(mesh):
    """At staleness ≥ 2, weight mass legitimately rides the FIFO across
    the step boundary; the in-step health signals must fold it back in
    or every overlap run reads as a push-sum mass leak (and
    false-triggers reactive recovery).  Pin: ps_mass_err stays at float
    noise through real overlap training steps."""
    from stochastic_gradient_push_tpu.data import synthetic_classification
    from stochastic_gradient_push_tpu.models import TinyMLP
    from stochastic_gradient_push_tpu.train import (
        LRSchedule, build_train_step, init_train_state, replicate_state,
        sgd, shard_train_step)

    batch, classes, img = 2, 4, 8
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    alg = sgp(sched, GOSSIP_AXIS, overlap=True, staleness=2)
    model = TinyMLP(num_classes=classes)
    tx = sgd(momentum=0.9)
    step = build_train_step(
        model, alg, tx,
        LRSchedule(ref_lr=0.1, batch_size=batch, world_size=WORLD),
        itr_per_epoch=10, num_classes=classes, health_axis=GOSSIP_AXIS)
    fn = shard_train_step(step, mesh)
    state = replicate_state(
        init_train_state(model, jax.random.PRNGKey(0),
                         jnp.zeros((batch, img, img, 3)), tx, alg),
        WORLD)
    images, labels = synthetic_classification(
        WORLD * batch, num_classes=classes, image_size=img, seed=2)
    x = images.reshape(WORLD, batch, img, img, 3)
    y = labels.reshape(WORLD, batch)
    for t in range(4):
        state, metrics = fn(state, x, y)
        jax.block_until_ready(state)
        assert float(np.asarray(metrics["ps_mass_err"])[0]) < 1e-5, \
            f"step {t}: in-flight weight mass read as a leak"
        # the drained per-rank weights stay in a sane band too (no
        # ps-weight-collapse false positive from the launch rescale)
        assert float(np.asarray(metrics["ps_w_min"])[0]) > 0.2


# -- comm accounting: bytes identical to sync --------------------------------

def test_comm_model_overlap_prices_identically_to_sync():
    from stochastic_gradient_push_tpu.telemetry import CommModel

    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=2))
    payload = 4096
    sync = CommModel.from_schedule(sched, payload, global_avg_every=4)
    over = CommModel.from_schedule(sched, payload, global_avg_every=4,
                                   overlap=True, staleness=3)
    assert over.totals(50) == sync.totals(50)  # bytes don't change
    d = over.to_dict()
    assert d["overlap"] is True and d["staleness"] == 3
    assert sync.to_dict()["overlap"] is False


# -- CLI surface -------------------------------------------------------------

class TestStalenessCLI:
    def test_sgd_staleness_threads_and_validates(self):
        from stochastic_gradient_push_tpu.run.gossip_sgd import (
            parse_config)

        cfg, _ = parse_config(["--dataset", "synthetic",
                               "--overlap", "True", "--staleness", "3"])
        assert cfg.overlap and cfg.staleness == 3
        with pytest.raises(SystemExit, match="overlap-mode knob"):
            parse_config(["--dataset", "synthetic", "--staleness", "2"])
        with pytest.raises(SystemExit, match="must be >= 0"):
            parse_config(["--dataset", "synthetic", "--overlap", "True",
                          "--staleness", "-1"])
        with pytest.raises(SystemExit, match="conflicts"):
            parse_config(["--dataset", "synthetic", "--overlap", "True",
                          "--staleness", "3", "--synch_freq", "3"])
        # the synch_freq alias still resolves (staleness = synch_freq+1)
        cfg, _ = parse_config(["--dataset", "synthetic",
                               "--overlap", "True",
                               "--staleness", "3", "--synch_freq", "2"])
        assert cfg.staleness == 3

    def test_lm_staleness_same_rejection_text(self, tmp_path):
        """The LM CLI exposes --staleness with the SAME validation and
        rejection text as the SGD harness (shared resolver)."""
        from stochastic_gradient_push_tpu.run.gossip_lm import main

        common = ["--world_size", str(WORLD), "--num_steps", "1",
                  "--d_model", "16", "--n_layers", "1", "--n_heads", "2",
                  "--d_ff", "32", "--seq_len", "16", "--batch_size", "2",
                  "--checkpoint_dir", str(tmp_path)]
        with pytest.raises(SystemExit, match="overlap-mode knob"):
            main(common + ["--staleness", "2"])
        with pytest.raises(SystemExit, match="must be >= 0"):
            main(common + ["--overlap", "True", "--staleness", "-1"])

    def test_trainer_resolves_staleness(self):
        from stochastic_gradient_push_tpu.train.loop import (
            Trainer, TrainerConfig)

        mesh = make_gossip_mesh(WORLD)

        def trainer(**over):
            cfg = TrainerConfig(
                graph_class=NPeerDynamicDirectedExponentialGraph,
                checkpoint_dir="/tmp/x", verbose=False, **over)
            return Trainer(cfg, model=None, mesh=mesh,
                           sample_input_shape=(2, 8, 8, 3))

        alg = trainer(overlap=True, staleness=3).make_algorithm(1)
        assert alg.staleness == 3
        alg = trainer(overlap=True, synch_freq=2).make_algorithm(1)
        assert alg.staleness == 3  # alias: synch_freq + 1
        with pytest.raises(ValueError, match="conflicts"):
            trainer(overlap=True, staleness=2,
                    synch_freq=3).make_algorithm(1)
        # without overlap the knob is ignored with a warning (flag
        # compatibility with reference launch scripts)
        alg = trainer(staleness=3).make_algorithm(1)
        assert alg.staleness == 1
