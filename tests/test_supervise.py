"""supervise/: the elastic run supervisor.

Pins the subsystem's contracts: (1) the events.jsonl tailer survives
everything a live JSONL file does (partial trailing lines, truncation,
rotation, torn writes) without losing or double-reading events; (2) the
reshard's restart-boundary invariant — the network parameter mean is
preserved across any n -> n' resize — against an independent numpy
oracle; (3) the policy debounce — one transient or flapping re-plan
suggestion triggers nothing, a sustained one triggers exactly one
relaunch cycle; (4) the supervisor lifecycle end to end (fast with a
fake child, the full chaos selftest as a slow test).
"""

import json
import os
import signal
import subprocess
import sys
import textwrap

import flax.serialization
import numpy as np
import pytest

from stochastic_gradient_push_tpu.supervise import (
    EventTailer,
    SupervisorPolicy,
    TornCheckpointError,
    consensus_mean,
    load_world_checkpoint,
    maybe_cross_world_reshard,
    reshard_checkpoints,
    reshard_state,
)
from stochastic_gradient_push_tpu.supervise.supervisor import (
    ChildSpec,
    Supervisor,
)
from stochastic_gradient_push_tpu.utils.checkpoint import (
    REQUEUE_EXIT_CODE,
    CheckpointManager,
    ClusterManager,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORLD = 8


# -- events.jsonl tailer ------------------------------------------------------


def _ev(kind="step_stats", **data):
    return {"v": 1, "kind": kind, "t": 0.0, "rank": 0,
            "severity": "info", "step": 0, "data": data}


class TestEventTailer:
    def test_missing_file_yields_nothing(self, tmp_path):
        t = EventTailer(str(tmp_path / "events.jsonl"))
        assert t.poll() == []

    def test_incremental_reads_no_double_delivery(self, tmp_path):
        path = tmp_path / "events.jsonl"
        t = EventTailer(str(path))
        with open(path, "a") as f:
            f.write(json.dumps(_ev(step=1)) + "\n")
        assert [e["data"] for e in t.poll()] == [{"step": 1}]
        assert t.poll() == []  # nothing new
        with open(path, "a") as f:
            f.write(json.dumps(_ev(step=2)) + "\n")
            f.write(json.dumps(_ev(step=3)) + "\n")
        assert [e["data"]["step"] for e in t.poll()] == [2, 3]
        assert t.events_seen == 3

    def test_partial_trailing_line_buffered_until_newline(self, tmp_path):
        path = tmp_path / "events.jsonl"
        t = EventTailer(str(path))
        line = json.dumps(_ev(step=7))
        with open(path, "w") as f:
            f.write(line[:10])  # the OS exposed a write mid-line
        assert t.poll() == []   # incomplete tail never parsed
        with open(path, "a") as f:
            f.write(line[10:] + "\n")
        out = t.poll()
        assert len(out) == 1 and out[0]["data"]["step"] == 7
        assert t.skipped == 0  # buffered, not dropped

    def test_malformed_and_non_dict_lines_skipped(self, tmp_path):
        path = tmp_path / "events.jsonl"
        with open(path, "w") as f:
            f.write('{"torn": \n')            # torn write at a crash
            f.write('[1, 2, 3]\n')            # valid JSON, not an event
            f.write(json.dumps(_ev()) + "\n")  # the stream continues
        t = EventTailer(str(path))
        assert len(t.poll()) == 1  # one corrupt line doesn't blind us
        assert t.skipped == 2

    def test_truncation_resets_to_start(self, tmp_path):
        path = tmp_path / "events.jsonl"
        with open(path, "w") as f:
            for s in range(5):
                f.write(json.dumps(_ev(step=s)) + "\n")
        t = EventTailer(str(path))
        assert len(t.poll()) == 5
        with open(path, "w") as f:  # truncate-in-place rewrite
            f.write(json.dumps(_ev(step=99)) + "\n")
        assert [e["data"]["step"] for e in t.poll()] == [99]

    def test_rotation_new_inode_resets(self, tmp_path):
        path = tmp_path / "events.jsonl"
        with open(path, "w") as f:
            f.write(json.dumps(_ev(step=1)) + "\n")
        t = EventTailer(str(path))
        assert len(t.poll()) == 1
        os.rename(path, tmp_path / "events.jsonl.1")
        # a relaunched child recreates the file: new inode, same name;
        # padding makes the new file LONGER than the old read offset so
        # only the inode check can catch it
        with open(path, "w") as f:
            f.write(json.dumps(_ev(step=2, pad="x" * 200)) + "\n")
        out = t.poll()
        assert [e["data"]["step"] for e in out] == [2]

    def test_unknown_kinds_pass_through(self, tmp_path):
        # the registry vocabulary may be newer than this supervisor:
        # unknown kinds must reach the policy (which ignores them), not
        # be filtered at the tailer
        path = tmp_path / "events.jsonl"
        with open(path, "w") as f:
            f.write(json.dumps(_ev(kind="hologram")) + "\n")
        t = EventTailer(str(path))
        out = t.poll()
        assert len(out) == 1 and out[0]["kind"] == "hologram"
        assert SupervisorPolicy(world=4).observe(out[0]) is None


# -- reshard: the restart-boundary invariant ---------------------------------


def _world_state(n=WORLD, seed=0):
    """A synthetic world-stacked gossip TrainState shaped like what
    CheckpointManager serializes (multi-leaf params, momentum, push-sum
    lane, int step)."""
    rng = np.random.default_rng(seed)
    return {
        "params": {
            "conv": {"kernel": rng.normal(size=(n, 3, 3, 2)
                                          ).astype(np.float32)},
            "dense": {"kernel": rng.normal(size=(n, 4, 5)
                                           ).astype(np.float32),
                      "bias": rng.normal(size=(n, 5)).astype(np.float32)},
        },
        "opt_state": {"momentum": rng.normal(size=(n, 4, 5)
                                             ).astype(np.float32)},
        "gossip": {
            # realistic push-sum weights: positive, mean ~1
            "ps_weight": rng.uniform(0.5, 1.5, size=n).astype(np.float32),
            "phase": (np.arange(n) % 3).astype(np.int32),
            "in_flight": None,
        },
        "step": np.full((n,), 17, np.int32),
    }


def _map_like(tree, fn):
    """Leaf-map over a nested-dict params tree."""
    return {k: _map_like(v, fn) if isinstance(v, dict) else fn(v)
            for k, v in tree.items()}


def _oracle_mean(state):
    """Independent numpy oracle: per-leaf Σ rank rows / Σ ps_weight."""
    w = np.asarray(state["gossip"]["ps_weight"], np.float64).sum()
    out = {}
    for name, sub in state["params"].items():
        for leaf, arr in sub.items():
            out[f"{name}/{leaf}"] = (
                np.asarray(arr, np.float64).sum(0) / w)
    return out


class TestReshardState:
    @pytest.mark.parametrize("new_world", [1, WORLD // 2, WORLD - 1])
    def test_mean_preserved_against_numpy_oracle(self, new_world):
        state = _world_state()
        oracle = _oracle_mean(state)
        new = reshard_state(state, WORLD, new_world)
        # every new rank row is the consensus, so the new network mean
        # (uniform: ps_weight is reset to 1) equals the old network mean
        w = np.asarray(new["gossip"]["ps_weight"], np.float64)
        np.testing.assert_array_equal(w, np.ones(new_world))
        for name, sub in new["params"].items():
            for leaf, arr in sub.items():
                assert arr.shape == (new_world,) + arr.shape[1:]
                got = np.asarray(arr, np.float64).sum(0) / w.sum()
                np.testing.assert_allclose(
                    got, oracle[f"{name}/{leaf}"], atol=1e-6)
                # and the rows are identical replicas (exact consensus)
                for r in range(1, new_world):
                    np.testing.assert_array_equal(arr[r], arr[0])

    def test_leaf_rules(self):
        state = _world_state()
        new = reshard_state(state, WORLD, 4)
        assert np.all(new["gossip"]["phase"] == 0)   # new schedule
        assert np.all(new["step"] == 17)             # int: row 0
        assert new["gossip"]["in_flight"] is None
        # float non-param leaves: plain rank mean, replicated
        np.testing.assert_allclose(
            new["opt_state"]["momentum"][0],
            np.asarray(state["opt_state"]["momentum"],
                       np.float64).mean(0).astype(np.float32), atol=1e-6)
        # dtypes survive the float64 round trip
        assert new["params"]["dense"]["kernel"].dtype == np.float32

    def test_grow_world_also_works(self):
        # elasticity is not only shrinking: a recovered rank can rejoin
        state = _world_state()
        before = consensus_mean(state)
        after = consensus_mean(reshard_state(state, WORLD, WORLD + 4))
        for k in before:
            np.testing.assert_allclose(after[k], before[k], atol=1e-9)

    def test_overlap_in_flight_folded_into_consensus(self):
        """A formerly-overlap checkpoint (undrained FIFO) reshards: each
        pending share is network mass counted exactly once in Σx/Σw, and
        the new world starts with zero slots.  Verified against an
        independent numpy oracle over the folded state."""
        state = _world_state()
        rng = np.random.default_rng(3)
        slot_p = {
            name: {leaf: rng.normal(size=arr.shape).astype(np.float32)
                   for leaf, arr in sub.items()}
            for name, sub in state["params"].items()}
        slot_w = rng.uniform(0.1, 0.5, size=WORLD).astype(np.float32)
        zero_p = _map_like(slot_p, np.zeros_like)
        state["gossip"]["in_flight"] = {
            "0": {"0": slot_p, "1": slot_w},
            "1": {"0": zero_p, "1": np.zeros(WORLD, np.float32)},
        }
        w_sum = (np.asarray(state["gossip"]["ps_weight"],
                            np.float64).sum() + slot_w.sum())
        new = reshard_state(state, WORLD, 4)
        for name, sub in new["params"].items():
            for leaf, arr in sub.items():
                want = (np.asarray(state["params"][name][leaf],
                                   np.float64).sum(0)
                        + np.asarray(slot_p[name][leaf],
                                     np.float64).sum(0)) / w_sum
                np.testing.assert_allclose(
                    np.asarray(arr, np.float64).sum(0) / 4.0, want,
                    atol=1e-6, err_msg=f"{name}/{leaf}")
        # the resharded FIFO is empty slots at the new world
        for slot in new["gossip"]["in_flight"].values():
            for sub in slot["0"].values():
                for arr in sub.values():
                    assert arr.shape[0] == 4
                    np.testing.assert_array_equal(arr, 0.0)
            np.testing.assert_array_equal(slot["1"], 0.0)
        # consensus_mean folds identically (the drift check's oracle)
        before = consensus_mean(state)
        after = consensus_mean(new)
        for k in before:
            np.testing.assert_allclose(after[k], before[k], atol=1e-6)

    def test_unrecognizable_in_flight_rejected(self):
        # a FIFO that is not (params, ps_weight) slots cannot be drained
        state = _world_state()
        state["gossip"]["in_flight"] = {"params": np.zeros((WORLD, 2))}
        with pytest.raises(ValueError, match="in_flight|in-flight"):
            reshard_state(state, WORLD, 4)
        state["gossip"]["in_flight"] = {"0": {"x": 1}}
        with pytest.raises(ValueError, match="slot"):
            reshard_state(state, WORLD, 4)

    def test_bad_ps_weight_rejected(self):
        state = _world_state()
        state["gossip"]["ps_weight"] = np.zeros(WORLD, np.float32)
        with pytest.raises(ValueError, match="finite and positive"):
            reshard_state(state, WORLD, 4)

    def test_world_mismatch_rejected(self):
        with pytest.raises(ValueError, match="rank rows"):
            reshard_state(_world_state(), WORLD + 1, 4)


def _write_rank_file(directory, tag, rank, world, state, meta=None):
    payload = {"state": state, "meta": meta or {"epoch": 2, "itr": 0}}
    path = os.path.join(directory,
                        f"{tag}checkpoint_r{rank}_n{world}.ckpt")
    with open(path, "wb") as f:
        f.write(flax.serialization.msgpack_serialize(payload))
    return path


def _slice_rows(state, lo, hi):
    def rec(t):
        if isinstance(t, dict):
            return {k: rec(v) for k, v in t.items()}
        return None if t is None else np.asarray(t)[lo:hi]
    return rec(state)


class TestCheckpointSets:
    def test_multi_process_set_assembles_in_rank_order(self, tmp_path):
        state = _world_state()
        _write_rank_file(tmp_path, "", 0, WORLD, _slice_rows(state, 0, 4))
        _write_rank_file(tmp_path, "", 1, WORLD, _slice_rows(state, 4, 8))
        got, meta, paths = load_world_checkpoint(str(tmp_path), "", WORLD)
        np.testing.assert_array_equal(
            got["params"]["dense"]["kernel"],
            state["params"]["dense"]["kernel"])
        assert len(paths) == 2

    def test_identical_mtimes_do_not_crash_meta_pick(self, tmp_path):
        # per-process saves land near-simultaneously: an mtime tie must
        # not fall through to dict-vs-dict comparison
        state = _world_state()
        a = _write_rank_file(tmp_path, "", 0, WORLD,
                             _slice_rows(state, 0, 4), {"epoch": 1})
        b = _write_rank_file(tmp_path, "", 1, WORLD,
                             _slice_rows(state, 4, 8), {"epoch": 2})
        os.utime(a, (100, 100))
        os.utime(b, (100, 100))
        _, meta, _ = load_world_checkpoint(str(tmp_path), "", WORLD)
        assert meta["epoch"] in (1, 2)

    def test_torn_set_rejected(self, tmp_path):
        # half the per-process files of a preempted save: rows don't
        # sum to the world — must raise, never assemble a short world
        state = _world_state()
        _write_rank_file(tmp_path, "", 0, WORLD, _slice_rows(state, 0, 4))
        with pytest.raises(TornCheckpointError, match="torn"):
            load_world_checkpoint(str(tmp_path), "", WORLD)

    def test_missing_set_rejected(self, tmp_path):
        with pytest.raises(TornCheckpointError, match="no "):
            load_world_checkpoint(str(tmp_path), "", WORLD)

    def test_reshard_checkpoints_on_disk(self, tmp_path):
        state = _world_state()
        _write_rank_file(tmp_path, "", 0, WORLD, state)
        before = consensus_mean(state)
        plan = {"world": 4, "topology": "ring"}
        report = reshard_checkpoints(str(tmp_path), "", WORLD, 4,
                                     plan=plan)
        assert report.mean_drift < 1e-6
        new, meta, _ = load_world_checkpoint(str(tmp_path), "", 4)
        after = consensus_mean(new)
        for k in before:
            np.testing.assert_allclose(after[k], before[k], atol=1e-6)
        # provenance + the fresh plan are stamped into the new meta
        assert meta["reshard"]["old_world"] == WORLD
        assert meta["reshard"]["new_world"] == 4
        assert meta["plan"] == plan
        # the old-world files stay in place — they are the rollback path
        assert os.path.isfile(
            tmp_path / f"checkpoint_r0_n{WORLD}.ckpt")

    def test_discover_worlds_newest_compatible_first(self, tmp_path):
        _write_rank_file(tmp_path, "", 0, 8, _world_state(8))
        old = _write_rank_file(tmp_path, "", 0, 2, _world_state(2))
        os.utime(old, (1, 1))  # the world-2 set is ancient
        cm = CheckpointManager(str(tmp_path), world_size=4)
        assert cm.discover_worlds() == [8, 2]
        # the current world is excluded (exists()/restore handle it)
        cm8 = CheckpointManager(str(tmp_path), world_size=8)
        assert cm8.discover_worlds() == [2]

    def test_maybe_cross_world_reshard_prefers_exact_set(self, tmp_path):
        _write_rank_file(tmp_path, "", 0, 4, _world_state(4))
        assert maybe_cross_world_reshard(str(tmp_path), "", 4) is None

    def test_maybe_cross_world_reshard_resizes_newest(self, tmp_path):
        state = _world_state()
        _write_rank_file(tmp_path, "", 0, WORLD, state)
        report = maybe_cross_world_reshard(str(tmp_path), "", 4)
        assert report is not None and report.old_world == WORLD
        assert os.path.isfile(tmp_path / "checkpoint_r0_n4.ckpt")

    def test_maybe_cross_world_reshard_skips_torn_set(self, tmp_path):
        # newest set is torn -> fall through to the older good one
        state = _world_state()
        good = _write_rank_file(tmp_path, "", 0, WORLD, state)
        os.utime(good, (1, 1))
        _write_rank_file(tmp_path, "", 0, 16, _slice_rows(state, 0, 4))
        report = maybe_cross_world_reshard(str(tmp_path), "", 4)
        assert report is not None and report.old_world == WORLD


# -- policy: debounce / cooldown / budget ------------------------------------


def _suggest(step, switch=True):
    return {"kind": "recovery", "severity": "warning",
            "data": {"step": step, "suggestion": {"switch": switch}}}


class TestSupervisorPolicy:
    def test_single_transient_suggestion_triggers_nothing(self):
        p = SupervisorPolicy(world=8, replan_count=3,
                             replan_cooldown_steps=20)
        assert p.observe(_suggest(10)) is None

    def test_flapping_suggestion_resets_the_streak(self):
        p = SupervisorPolicy(world=8, replan_count=2,
                             replan_cooldown_steps=10)
        assert p.observe(_suggest(10)) is None
        assert p.observe(_suggest(15, switch=False)) is None  # flap
        assert p.observe(_suggest(30)) is None   # streak restarted
        assert p.observe(_suggest(35)) is None   # span 5 < cooldown 10
        act = p.observe(_suggest(45))            # span 15: sustained
        assert act is not None and act.kind == "drain-restart"

    def test_count_without_span_is_not_sustained(self):
        # many events in a burst (same recovery cycle) are one signal
        p = SupervisorPolicy(world=8, replan_count=3,
                             replan_cooldown_steps=20)
        for _ in range(5):
            assert p.observe(_suggest(100)) is None

    def test_sustained_suggestion_fires_exactly_once(self):
        p = SupervisorPolicy(world=8, replan_count=2,
                             replan_cooldown_steps=5)
        p.observe(_suggest(0))
        act = p.observe(_suggest(10))
        assert act is not None and act.kind == "drain-restart" \
            and not act.shrink
        # the relaunch cycle completes: the pre-restart backlog is gone
        p.mark_relaunched(8)
        assert p.observe(_suggest(20)) is None
        assert p.generation == 1 and p.restarts == 1

    def test_watchdog_stall_means_rank_loss(self):
        p = SupervisorPolicy(world=8)
        act = p.observe({"kind": "heartbeat", "severity": "error",
                         "data": {"stalled_for_s": 120.0}})
        assert act is not None and act.kind == "restart" and act.shrink
        # info heartbeats (liveness) are not stalls
        assert SupervisorPolicy(world=8).observe(
            {"kind": "heartbeat", "severity": "info", "data": {}}) is None

    def test_event_silence_means_rank_loss(self):
        act = SupervisorPolicy(world=8).on_stale(61.0)
        assert act.kind == "restart" and act.shrink

    def test_child_exit_mapping(self):
        p = SupervisorPolicy(world=8)
        assert p.on_child_exit(0).kind == "complete"
        assert p.on_child_exit(REQUEUE_EXIT_CODE).kind == "relaunch"
        crash = p.on_child_exit(-9)
        assert crash.kind == "restart" and crash.shrink

    def test_target_world_shrink_floor(self):
        p = SupervisorPolicy(world=8, shrink_factor=2, min_world=4)
        assert p.target_world(shrink=False) == 8
        assert p.target_world(shrink=True) == 4
        p.mark_relaunched(4)
        assert p.target_world(shrink=True) == 4  # never below min_world

    def test_restart_budget_gives_up(self):
        p = SupervisorPolicy(world=8, max_restarts=1)
        assert p.on_child_exit(-9).kind == "restart"
        p.mark_relaunched(4)
        assert p.on_child_exit(-9).kind == "give-up"

    def test_unlimited_budget(self):
        p = SupervisorPolicy(world=8, max_restarts=0)
        for _ in range(5):
            assert p.on_child_exit(-9).kind == "restart"
            p.mark_relaunched(p.world)


# -- child spec / argv handling ----------------------------------------------


class TestRelaunchBackoffAndRefill:
    def test_healthy_relaunch_has_no_backoff(self):
        p = SupervisorPolicy(world=4)
        assert p.next_backoff_s() == 0.0
        p.mark_relaunched(4, failure=False)   # requeue / replan drain
        assert p.next_backoff_s() == 0.0

    def test_failure_backoff_is_exponential_capped_and_deterministic(self):
        def policy():
            return SupervisorPolicy(world=4, backoff_base_s=2.0,
                                    backoff_max_s=30.0,
                                    backoff_jitter=0.5)

        a, b = policy(), policy()
        seen = []
        for _ in range(6):
            a.mark_relaunched(4, failure=True)
            b.mark_relaunched(4, failure=True)
            # deterministic: two identical policies pace identically
            assert a.next_backoff_s() == b.next_backoff_s()
            seen.append(a.next_backoff_s())
        # exponential ramp with jitter in [1, 1.5), capped at the max
        for k, s in enumerate(seen):
            raw = 2.0 * 2.0 ** k
            assert min(30.0, raw) <= s <= min(30.0, raw * 1.5)
        assert seen[-1] == 30.0  # the cap
        assert seen == sorted(seen)

    def test_jitter_desynchronizes_generations(self):
        p = SupervisorPolicy(world=4, backoff_base_s=1.0,
                             backoff_max_s=1e9, backoff_jitter=0.5)
        fracs = []
        for _ in range(4):
            p.mark_relaunched(4, failure=True)
            k = p.consecutive_failures
            fracs.append(p.next_backoff_s() / (2.0 ** (k - 1)))
        assert len(set(fracs)) == len(fracs)  # no lockstep

    def test_jitter_salt_desynchronizes_hosts(self):
        # a pod-wide transient crashes every host at the SAME
        # generation; the per-host salt must spread their backoffs
        # (identical salts still pace identically — determinism holds)
        def policy(salt):
            p = SupervisorPolicy(world=4, backoff_base_s=1.0,
                                 backoff_max_s=1e9, backoff_jitter=0.5,
                                 jitter_salt=salt)
            p.mark_relaunched(4, failure=True)
            return p.next_backoff_s()

        backoffs = [policy(h) for h in range(4)]
        assert len(set(backoffs)) == 4
        # MEANINGFULLY spread, not micro-distinct floats: with jitter
        # 0.5 the factor spans [1, 1.5) — hosts must use a real chunk
        # of that range or the herd still lands together
        assert max(backoffs) - min(backoffs) > 0.05
        assert policy(2) == policy(2)

    def test_healthy_relaunch_resets_the_failure_streak(self):
        p = SupervisorPolicy(world=4, backoff_base_s=1.0,
                             backoff_jitter=0.0)
        p.mark_relaunched(4, failure=True)
        p.mark_relaunched(4, failure=True)
        assert p.next_backoff_s() == 2.0
        p.mark_relaunched(4, failure=False)
        assert p.next_backoff_s() == 0.0

    def test_progress_refills_the_restart_budget(self):
        p = SupervisorPolicy(world=4, max_restarts=2, refill_steps=10)
        p.mark_relaunched(4, failure=True)
        p.mark_relaunched(4, failure=True)
        # budget spent: the next incident would give up...
        assert p.on_child_exit(1).kind == "give-up"
        # ...but sustained healthy progress refills it: 10 observed
        # steps since the relaunch restore the full budget
        p.observe(_ev(step=3))
        assert p.restarts == 2              # baseline only, no credit
        p.observe(_ev(step=8))
        assert p.restarts == 2              # window not yet spanned
        p.observe(_ev(step=13))
        assert p.restarts == 0
        assert p.consecutive_failures == 0
        assert p.on_child_exit(1).kind == "restart"

    def test_refill_window_restarts_after_each_relaunch(self):
        p = SupervisorPolicy(world=4, max_restarts=1, refill_steps=10)
        p.observe(_ev(step=100))            # pre-crash progress
        p.mark_relaunched(4, failure=True)
        # the relaunched child resumes at a LOWER step; the old
        # baseline must not credit the jump backwards
        p.observe(_ev(step=50))
        p.observe(_ev(step=59))
        assert p.restarts == 1
        p.observe(_ev(step=60))
        assert p.restarts == 0

    def test_refill_disabled_keeps_hard_cap(self):
        p = SupervisorPolicy(world=4, max_restarts=1, refill_steps=0)
        p.mark_relaunched(4, failure=True)
        p.observe(_ev(step=10 ** 6))
        assert p.restarts == 1
        assert p.on_child_exit(1).kind == "give-up"


class TestChildSpec:
    ARGV = ["python", "-m", "stochastic_gradient_push_tpu.run.gossip_sgd",
            "--world_size", "8", "--trace_dir", "/runs/t",
            "--checkpoint_dir", "/ck", "--topology", "ring"]

    def test_flags_parsed(self):
        spec = ChildSpec(self.ARGV)
        assert spec.world == 8 and spec.trace_dir == "/runs/t"
        assert spec.checkpoint_dir == "/ck" and spec.tag == ""
        assert spec.gossip and spec.algorithm == "sgp"

    def test_lm_child_gets_lm_tag(self):
        argv = ["python", "-m",
                "stochastic_gradient_push_tpu.run.gossip_lm",
                "--world_size", "4", "--trace_dir", "/t"]
        assert ChildSpec(argv).tag == "lm_"

    def test_trace_dir_and_world_required(self):
        with pytest.raises(ValueError, match="trace_dir"):
            ChildSpec(["python", "x.py", "--world_size", "8"])
        with pytest.raises(ValueError, match="world size"):
            ChildSpec(["python", "x.py", "--trace_dir", "/t"])

    def test_build_argv_rewrites_managed_flags(self):
        spec = ChildSpec(self.ARGV)
        plan = {"topology": "bipartite-exponential", "world": 4,
                "global_avg_every": 10, "slice_size": None, "alpha": 0.7}
        argv = spec.build_argv(4, plan, resume=True)
        joined = " ".join(argv)
        assert "--world_size 4" in joined
        assert "--topology bipartite-exponential" in joined
        assert "--global_avg_every 10" in joined
        assert "--mixing_alpha 0.7" in joined
        assert "--slice_size" not in joined
        assert "--resume True" in joined
        assert joined.count("--topology") == 1  # the old ring is gone
        # operator flags the supervisor doesn't manage stay verbatim
        assert "--checkpoint_dir /ck" in joined

    def test_build_argv_without_plan_keeps_operator_flags(self):
        argv = ChildSpec(self.ARGV).build_argv(8, None, resume=False)
        assert "--topology ring" in " ".join(argv)
        assert "--resume" not in " ".join(argv)


# -- supervisor lifecycle (fast, fake child) ---------------------------------


FAKE_CHILD = textwrap.dedent("""
    import json, os, sys, time
    args = dict(zip(sys.argv[1::2], sys.argv[2::2]))
    td = args["--trace_dir"]
    mode_path = os.path.join(td, "mode")
    mode = open(mode_path).read() if os.path.exists(mode_path) else "done"
    with open(os.path.join(td, "events.jsonl"), "a") as f:
        f.write(json.dumps({"v": 1, "kind": "step_stats",
                            "t": time.time(), "rank": 0,
                            "severity": "info", "step": 1,
                            "data": {}}) + "\\n")
    if mode == "requeue-once":
        os.remove(mode_path)
        sys.exit(75)
    if mode == "crash-once":
        os.remove(mode_path)
        sys.exit(1)
    sys.exit(0)
""")


def _fake_spec(tmp_path, mode):
    script = tmp_path / "fake_child.py"
    script.write_text(FAKE_CHILD)
    (tmp_path / "mode").write_text(mode)
    return ChildSpec([sys.executable, str(script),
                      "--trace_dir", str(tmp_path),
                      "--checkpoint_dir", str(tmp_path),
                      "--world_size", "4"])


def _sup_events(tmp_path):
    path = tmp_path / "supervisor.jsonl"
    if not path.exists():
        return []
    return [json.loads(l) for l in path.read_text().splitlines() if l]


class TestSupervisorLifecycle:
    def test_requeue_exit_relaunches_same_world(self, tmp_path):
        spec = _fake_spec(tmp_path, "requeue-once")
        sup = Supervisor(spec, SupervisorPolicy(world=4, max_restarts=3),
                         poll_interval_s=0.05,
                         install_signal_handlers=False)
        assert sup.run() == 0
        rel = [e for e in _sup_events(tmp_path)
               if e["kind"] == "relaunch"]
        assert len(rel) == 1
        d = rel[0]["data"]
        # a voluntary requeue keeps the world; a fresh plan still rides
        assert d["world"] == 4 and d["prev_world"] == 4
        assert d["topology"]  # replanned even without a checkpoint
        assert d["resharded"] is False  # no checkpoint set to reshard

    def test_crash_shrinks_the_world(self, tmp_path):
        spec = _fake_spec(tmp_path, "crash-once")
        sup = Supervisor(spec, SupervisorPolicy(world=4, max_restarts=3,
                                                shrink_factor=2),
                         poll_interval_s=0.05,
                         install_signal_handlers=False)
        assert sup.run() == 0
        rel = [e for e in _sup_events(tmp_path)
               if e["kind"] == "relaunch"]
        assert len(rel) == 1
        assert rel[0]["data"]["world"] == 2
        assert rel[0]["data"]["prev_world"] == 4

    def test_budget_spent_gives_up(self, tmp_path):
        script = tmp_path / "fake_child.py"
        script.write_text("import sys; sys.exit(1)\n")
        spec = ChildSpec([sys.executable, str(script),
                          "--trace_dir", str(tmp_path),
                          "--checkpoint_dir", str(tmp_path),
                          "--world_size", "4"])
        sup = Supervisor(spec, SupervisorPolicy(world=4, max_restarts=1),
                         poll_interval_s=0.05,
                         install_signal_handlers=False)
        assert sup.run() == 1
        evs = _sup_events(tmp_path)
        assert any(e["data"].get("action") == "gave-up" for e in evs
                   if e["kind"] == "supervisor")

    def test_drain_tail_does_not_leak_into_next_generation(self, tmp_path):
        # a draining child keeps emitting until its save lands; those
        # stale recovery suggestions must not seed the next generation's
        # debounce streak (one fresh suggestion would then relaunch)
        script = tmp_path / "fake_child.py"
        script.write_text(textwrap.dedent("""
            import json, os, signal, sys, time
            args = dict(zip(sys.argv[1::2], sys.argv[2::2]))
            td = args["--trace_dir"]

            def emit(step):
                with open(os.path.join(td, "events.jsonl"), "a") as f:
                    f.write(json.dumps({
                        "v": 1, "kind": "recovery", "t": time.time(),
                        "rank": 0, "severity": "warning", "step": step,
                        "data": {"step": step,
                                 "suggestion": {"switch": True}},
                    }) + "\\n")

            if os.path.exists(os.path.join(td, "gen1")):
                sys.exit(0)  # the relaunched generation is healthy
            open(os.path.join(td, "gen1"), "w").close()

            def drain(signum, frame):
                # two more suggestions flushed during the drain window
                emit(100)
                emit(101)
                sys.exit(75)
            signal.signal(signal.SIGUSR1, drain)
            emit(1)
            emit(2)  # span 1 >= cooldown 0: sustained -> drain-restart
            for _ in range(200):
                time.sleep(0.1)
            sys.exit(3)  # supervisor never drained us: fail loudly
        """))
        spec = ChildSpec([sys.executable, str(script),
                          "--trace_dir", str(tmp_path),
                          "--checkpoint_dir", str(tmp_path),
                          "--world_size", "4"])
        sup = Supervisor(
            spec, SupervisorPolicy(world=4, replan_count=2,
                                   replan_cooldown_steps=0,
                                   max_restarts=3),
            poll_interval_s=0.05, drain_timeout_s=30.0,
            install_signal_handlers=False)
        assert sup.run() == 0
        rel = [e for e in _sup_events(tmp_path)
               if e["kind"] == "relaunch"]
        # exactly one cycle: the drain-window backlog died with gen 0
        assert len(rel) == 1
        assert rel[0]["data"]["reason"].startswith("replan-suggestion")

    def test_crash_reshards_an_existing_checkpoint_set(self, tmp_path):
        state = _world_state(4, seed=3)
        _write_rank_file(tmp_path, "", 0, 4, state)
        before = consensus_mean(state)
        spec = _fake_spec(tmp_path, "crash-once")
        sup = Supervisor(spec, SupervisorPolicy(world=4, max_restarts=2,
                                                shrink_factor=2),
                         poll_interval_s=0.05,
                         install_signal_handlers=False)
        assert sup.run() == 0
        rel = [e for e in _sup_events(tmp_path)
               if e["kind"] == "relaunch"][0]["data"]
        assert rel["resharded"] is True and rel["world"] == 2
        after = consensus_mean(
            load_world_checkpoint(str(tmp_path), "", 2)[0])
        for k in before:
            np.testing.assert_allclose(after[k], before[k], atol=1e-6)


# -- run-layer wiring ---------------------------------------------------------


class TestRequeueExitCode:
    def test_cluster_manager_exits_with_requeue_code(self, tmp_path):
        cm = CheckpointManager(str(tmp_path), world_size=2)
        cluster = ClusterManager(cm, rank=0, install_handlers=False)
        cluster._sigusr1(signal.SIGUSR1, None)
        with pytest.raises(SystemExit) as exc:
            cluster.save_checkpoint({"x": np.zeros(2)}, {"epoch": 1})
        assert exc.value.code == REQUEUE_EXIT_CODE
        assert cluster.last_signal == "SIGUSR1"

    def test_sigterm_also_drains(self, tmp_path):
        # schedulers that send only SIGTERM (k8s, plain kill) must still
        # drain through a checkpoint
        cm = CheckpointManager(str(tmp_path), world_size=2)
        cluster = ClusterManager(cm, rank=0, install_handlers=False)
        cluster._sigterm(signal.SIGTERM, None)
        assert cluster.any_rank_signalled()
        assert cluster.last_signal == "SIGTERM"

    def test_supervised_child_never_self_requeues(self, monkeypatch):
        from stochastic_gradient_push_tpu.run.gossip_sgd import (
            _default_requeue)
        monkeypatch.setenv("SLURM_JOB_ID", "123")
        assert _default_requeue() == "scontrol requeue 123"
        monkeypatch.setenv("SGP_SUPERVISED", "1")
        assert _default_requeue() is None


# -- telemetry kinds ----------------------------------------------------------


class TestSupervisorTelemetry:
    def test_new_kinds_accepted_and_closed(self):
        from stochastic_gradient_push_tpu.telemetry import (
            MemorySink, TelemetryRegistry)
        reg = TelemetryRegistry(rank=0, sinks=[MemorySink()])
        reg.emit("supervisor", {"action": "launch"})
        reg.emit("relaunch", {"generation": 1})
        with pytest.raises(ValueError):
            reg.emit("resize", {})  # still a closed vocabulary

    def test_compat_sink_renders_legacy_supervisor_line(self, caplog):
        import logging

        from stochastic_gradient_push_tpu.telemetry import (
            LoggerCompatSink, TelemetryRegistry)
        log = logging.getLogger("test_supervise_compat")
        reg = TelemetryRegistry(rank=0, sinks=[LoggerCompatSink(log)])
        data = {"action": "launch", "world": 8, "generation": 0}
        with caplog.at_level(logging.INFO, log.name):
            reg.emit("supervisor", data)
            reg.emit("relaunch", {"generation": 1})  # no legacy line
        lines = [r.message for r in caplog.records]
        assert lines == ["gossip supervisor: "
                         + json.dumps(data, sort_keys=True)]


# -- the chaos e2e (the CI gate) ---------------------------------------------


@pytest.mark.slow
def test_supervise_selftest_kill_reshard_relaunch(tmp_path, capsys):
    """World-8 CPU child SIGKILLed after its first checkpoint -> the
    supervisor detects the rank loss, reshards 8->4, replans, relaunches,
    and the run completes at world 4 with the parameter mean preserved
    across the restart boundary."""
    from stochastic_gradient_push_tpu.supervise.cli import selftest

    env = {**os.environ,
           "PYTHONPATH": REPO + os.pathsep
           + os.environ.get("PYTHONPATH", "")}
    assert selftest(keep_dir=str(tmp_path), child_env=env) == 0
    assert "supervise selftest: OK" in capsys.readouterr().out
