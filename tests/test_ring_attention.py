"""Ring attention == full attention, sharded over a sequence mesh axis."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from stochastic_gradient_push_tpu.parallel.ring_attention import (
    blockwise_attention,
    ring_attention,
)

WORLD = 8
B, H, T, D = 2, 4, 64, 16  # T across all ranks; block = T // WORLD


def full_attention(q, k, v, causal=False):
    s = np.einsum("bhqd,bhkd->bhqk", q.astype(np.float64),
                  k.astype(np.float64)) * (D ** -0.5)
    if causal:
        mask = np.tril(np.ones((T, T), bool))
        s = np.where(mask[None, None], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v.astype(np.float64))


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(0)
    return [rng.normal(size=(B, H, T, D)).astype(np.float32)
            for _ in range(3)]


@pytest.fixture(scope="module")
def mesh():
    from stochastic_gradient_push_tpu.parallel import make_gossip_mesh
    return make_gossip_mesh(WORLD)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(mesh, qkv, causal):
    q, k, v = qkv
    block = T // WORLD

    def shard_seq(x):
        # [B,H,T,D] → [WORLD, B, H, block, D] (contiguous block layout)
        return np.moveaxis(
            x.reshape(B, H, WORLD, block, D), 2, 0).copy()

    def f(qb, kb, vb):
        return ring_attention(qb[0], kb[0], vb[0], "gossip",
                              causal=causal)[None]

    sharded = jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=(P("gossip"), P("gossip"), P("gossip")),
        out_specs=P("gossip")))
    out_blocks = np.asarray(sharded(shard_seq(q), shard_seq(k),
                                    shard_seq(v)))
    # [WORLD, B, H, block, D] → [B, H, T, D]
    got = np.moveaxis(out_blocks, 0, 2).reshape(B, H, T, D)
    want = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block", [8, 16, 64])
def test_blockwise_attention_matches_full(qkv, causal, block):
    q, k, v = qkv
    got = np.asarray(jax.jit(
        lambda q, k, v: blockwise_attention(q, k, v, block, causal=causal)
    )(q, k, v))
    want = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_ring_attention_gradients_flow(mesh, qkv):
    """Differentiability: ring attention participates in backprop."""
    q, k, v = qkv
    block = T // WORLD

    def shard_seq(x):
        return np.moveaxis(x.reshape(B, H, WORLD, block, D), 2, 0).copy()

    def loss_fn(qb, kb, vb):
        out = ring_attention(qb[0], kb[0], vb[0], "gossip", causal=True)
        return jnp.sum(out ** 2)

    def f(qb, kb, vb):
        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
            qb, kb, vb)
        return loss[None], grads

    sharded = jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=(P("gossip"), P("gossip"), P("gossip")),
        out_specs=(P("gossip"), (P("gossip"), P("gossip"), P("gossip")))))
    loss, grads = sharded(shard_seq(q), shard_seq(k), shard_seq(v))
    for g in grads:
        g = np.asarray(g)
        assert np.all(np.isfinite(g))
        assert np.abs(g).max() > 0
