"""Ring attention == full attention, sharded over a sequence mesh axis."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from stochastic_gradient_push_tpu.parallel.ring_attention import (
    blockwise_attention,
    ring_attention,
)

WORLD = 8
B, H, T, D = 2, 4, 64, 16  # T across all ranks; block = T // WORLD


def full_attention(q, k, v, causal=False):
    s = np.einsum("bhqd,bhkd->bhqk", q.astype(np.float64),
                  k.astype(np.float64)) * (D ** -0.5)
    if causal:
        mask = np.tril(np.ones((T, T), bool))
        s = np.where(mask[None, None], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v.astype(np.float64))


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(0)
    return [rng.normal(size=(B, H, T, D)).astype(np.float32)
            for _ in range(3)]


@pytest.fixture(scope="module")
def mesh():
    from stochastic_gradient_push_tpu.parallel import make_gossip_mesh
    return make_gossip_mesh(WORLD)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(mesh, qkv, causal):
    q, k, v = qkv
    block = T // WORLD

    def shard_seq(x):
        # [B,H,T,D] → [WORLD, B, H, block, D] (contiguous block layout)
        return np.moveaxis(
            x.reshape(B, H, WORLD, block, D), 2, 0).copy()

    def f(qb, kb, vb):
        return ring_attention(qb[0], kb[0], vb[0], "gossip",
                              causal=causal)[None]

    sharded = jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=(P("gossip"), P("gossip"), P("gossip")),
        out_specs=P("gossip")))
    out_blocks = np.asarray(sharded(shard_seq(q), shard_seq(k),
                                    shard_seq(v)))
    # [WORLD, B, H, block, D] → [B, H, T, D]
    got = np.moveaxis(out_blocks, 0, 2).reshape(B, H, T, D)
    want = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block", [8, 16, 64])
def test_blockwise_attention_matches_full(qkv, causal, block):
    q, k, v = qkv
    got = np.asarray(jax.jit(
        lambda q, k, v: blockwise_attention(q, k, v, block, causal=causal)
    )(q, k, v))
    want = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_ring_attention_gradients_flow(mesh, qkv):
    """Differentiability: ring attention participates in backprop."""
    q, k, v = qkv
    block = T // WORLD

    def shard_seq(x):
        return np.moveaxis(x.reshape(B, H, WORLD, block, D), 2, 0).copy()

    def loss_fn(qb, kb, vb):
        out = ring_attention(qb[0], kb[0], vb[0], "gossip", causal=True)
        return jnp.sum(out ** 2)

    def f(qb, kb, vb):
        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
            qb, kb, vb)
        return loss[None], grads

    sharded = jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=(P("gossip"), P("gossip"), P("gossip")),
        out_specs=(P("gossip"), (P("gossip"), P("gossip"), P("gossip")))))
    loss, grads = sharded(shard_seq(q), shard_seq(k), shard_seq(v))
    for g in grads:
        g = np.asarray(g)
        assert np.all(np.isfinite(g))
        assert np.abs(g).max() > 0


class TestRingFlash:
    """ring_flash_attention (ops/ring_flash.py): the flash-kernel-tick
    ring — values AND analytic custom-vjp gradients must match full
    attention / autodiff through the reference ring."""

    @staticmethod
    def _shard_seq(x, world=4):
        b, h, t, d = x.shape
        block = t // world
        return np.moveaxis(x.reshape(b, h, world, block, d), 2, 0).copy()

    @staticmethod
    def _unshard(blocks):
        w, b, h, blk, d = blocks.shape
        return np.moveaxis(blocks, 0, 2).reshape(b, h, w * blk, d)

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("use_pallas", [False, True])
    def test_matches_full_attention(self, qkv, causal, use_pallas):
        from stochastic_gradient_push_tpu.ops.ring_flash import (
            ring_flash_attention)
        from stochastic_gradient_push_tpu.parallel import make_gossip_mesh

        if not causal and jax.__version_info__ < (0, 5):
            pytest.skip(
                "non-causal ring flash: every tick's mode is the constant "
                "FULL, and the resulting program shape makes jax<0.5's "
                "SPMD partitioner emit an unsupported PartitionId op on "
                "the CPU mesh; the causal variants exercise the same "
                "merge/ppermute machinery and pass")

        world = 4
        mesh = make_gossip_mesh(world)
        q, k, v = qkv

        def f(qb, kb, vb):
            return ring_flash_attention(
                qb[0], kb[0], vb[0], "gossip", causal=causal, block=8,
                interpret=use_pallas, use_pallas=use_pallas)[None]

        sharded = jax.jit(jax.shard_map(
            f, mesh=mesh,
            in_specs=(P("gossip"),) * 3, out_specs=P("gossip")))
        got = self._unshard(np.asarray(sharded(
            self._shard_seq(q), self._shard_seq(k), self._shard_seq(v))))
        want = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("use_pallas", [False, True])
    def test_gradients_match_reference_ring(self, qkv, causal,
                                            use_pallas):
        """The custom-vjp ring backward (global-lse per-tick kernels +
        homeward dk/dv rotation) equals autodiff through the reference
        ring implementation."""
        from stochastic_gradient_push_tpu.ops.ring_flash import (
            ring_flash_attention)
        from stochastic_gradient_push_tpu.parallel import make_gossip_mesh

        world = 4
        mesh = make_gossip_mesh(world)
        q, k, v = qkv

        def loss_flash(qb, kb, vb):
            out = ring_flash_attention(
                qb, kb, vb, "gossip", causal=causal, block=8,
                interpret=use_pallas, use_pallas=use_pallas)
            return jnp.sum(out.astype(jnp.float32) ** 2)

        def loss_ref(qb, kb, vb):
            out = ring_attention(qb, kb, vb, "gossip", causal=causal)
            return jnp.sum(out.astype(jnp.float32) ** 2)

        def make(loss_fn):
            def f(qb, kb, vb):
                g = jax.grad(loss_fn, argnums=(0, 1, 2))(
                    qb[0], kb[0], vb[0])
                return tuple(x[None] for x in g)
            return jax.jit(jax.shard_map(
                f, mesh=mesh, in_specs=(P("gossip"),) * 3,
                out_specs=(P("gossip"),) * 3))

        args = (self._shard_seq(q), self._shard_seq(k),
                self._shard_seq(v))
        got = make(loss_flash)(*args)
        want = make(loss_ref)(*args)
        for name, a, b in zip("dq dk dv".split(), got, want):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4,
                err_msg=name)


@pytest.mark.slow
def test_long_context_16x_blocks_trains(tmp_path):
    """Long-context evidence: 8192 tokens over the sp=8 ring_flash mesh
    train end-to-end through the CLI (peak attention memory per device is
    O(block²) in the 1024-token shard, not O(seq²))."""
    import subprocess
    import sys

    from tests.test_run_layer import CLI_ENV

    cmd = [sys.executable, "-m",
           "stochastic_gradient_push_tpu.run.gossip_lm",
           "--world_size", "8", "--sp", "8", "--attn", "ring_flash",
           "--seq_len", "8192", "--d_model", "32", "--n_layers", "1",
           "--n_heads", "4", "--d_ff", "64", "--batch_size", "1",
           "--num_steps", "2", "--corpus_tokens", "100000",
           "--checkpoint_dir", str(tmp_path)]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=420,
                       env=CLI_ENV)
    assert r.returncode == 0, r.stderr[-2000:]
    assert '"final_loss"' in r.stdout + r.stderr
