"""Per-bucket pipelined gossip transport (parallel/collectives).

The kernel lane now partitions the payload leaves into contiguous,
byte-bounded transport buckets (``_transport_plan``) and launches one
split start/wait kernel program per bucket.  Bucketing is a transport
*pipelining* knob: it must never change the round's mathematics, its
wire volume, or the schedule object SGPV106 verifies.  Pinned here:

* plan invariants — contiguity, byte bounding, scalar exclusion, int8
  whole-block padding, clamping, dtype boundaries;
* the scalar/ppermute fallback — a tree with no payload leaf never
  builds a plan, a handle, or a kernel call;
* the FIFO lifecycle seams (``empty_incoming`` / ``land_shares`` /
  ``settle_share``) and their structural cond-branch contract;
* the jit trajectory against a numpy push-sum oracle at staleness
  1–3 × buckets {1, 3} on the world-8 mesh;
* buckets {1, 3} produce BIT-identical trajectories (packing is a
  partition, never a re-quantization);
* ``verify_schedule`` (SGPV106) sees the same object regardless of
  bucket count — the plan is schedule-free by construction.

Compiled mesh dispatch is serialized per the PR-8 deadlock note.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from stochastic_gradient_push_tpu.algorithms import sgp
from stochastic_gradient_push_tpu.analysis import verify_schedule
from stochastic_gradient_push_tpu.ops.gossip_kernel import KernelLane
from stochastic_gradient_push_tpu.parallel import wire
from stochastic_gradient_push_tpu.parallel.collectives import (
    PendingShares,
    _transport_plan,
    empty_incoming,
    land_shares,
    settle_share,
)
from stochastic_gradient_push_tpu.parallel.mesh import (
    GOSSIP_AXIS,
    make_gossip_mesh,
)
from stochastic_gradient_push_tpu.topology import RingGraph, build_schedule

WORLD = 8
ROUNDS = 4

F32_SPEC = wire.F32.kernel_spec()
I8_SPEC = wire.Int8Codec(64).kernel_spec()


# -- the static plan (host-only, no mesh) -----------------------------------


class TestTransportPlan:
    def test_partition_is_contiguous_and_skips_scalars(self):
        leaves = [np.zeros(10, np.float32), np.zeros((), np.float32),
                  np.zeros(33, np.float32), np.zeros(5, np.float32),
                  np.zeros(1, np.float32)]
        plan = _transport_plan(leaves, F32_SPEC, 2)
        slots = [j for bucket in plan for j, _, _ in bucket]
        assert slots == [0, 2, 3]  # contiguous slot order, scalars out
        assert all(n == p for b in plan for _, n, p in b)  # f32: no pad
        assert 1 <= len(plan) <= 2

    def test_byte_bounded_split(self):
        leaves = [np.zeros(100, np.float32) for _ in range(4)]
        plan = _transport_plan(leaves, F32_SPEC, 2)
        assert len(plan) == 2
        sizes = [sum(p for _, _, p in b) for b in plan]
        assert sizes == [200, 200]  # greedy cumulative close balances

    def test_bucket_count_clamps_to_payload_leaves(self):
        leaves = [np.zeros(8, np.float32) for _ in range(3)]
        assert len(_transport_plan(leaves, F32_SPEC, 10)) == 3
        assert len(_transport_plan(leaves, F32_SPEC, 1)) == 1
        with_scalar = leaves + [np.zeros((), np.float32)]
        assert len(_transport_plan(with_scalar, F32_SPEC, 10)) == 3

    def test_int8_leaves_pad_to_whole_blocks(self):
        leaves = [np.zeros(100, np.float32), np.zeros(64, np.float32)]
        plan = _transport_plan(leaves, I8_SPEC, 1)
        assert plan == (((0, 100, 128), (1, 64, 64)),)

    def test_dtype_change_forces_a_boundary(self):
        # one bucket ships ONE packed accumulator, so a mixed-dtype tree
        # may exceed the requested bucket count
        leaves = [np.zeros(8, np.float32), np.zeros(8, np.float16),
                  np.zeros(8, np.float32)]
        plan = _transport_plan(leaves, F32_SPEC, 1)
        assert [tuple(j for j, _, _ in b) for b in plan] == \
            [(0,), (1,), (2,)]

    def test_no_payload_leaf_means_no_plan(self):
        scalars = [np.zeros((), np.float32), np.zeros(1, np.float32)]
        assert _transport_plan(scalars, F32_SPEC, 4) == ()

    def test_bucketing_partitions_but_never_repads(self):
        # comm-volume invariant: any bucket count yields the SAME
        # (slot, n, padded) triples — bucketing moves boundaries, it
        # never changes what goes on the wire
        leaves = [np.zeros(n, np.float32) for n in (100, 7, 65, 3, 200)]
        for spec in (F32_SPEC, I8_SPEC):
            flat = {b: [t for bucket in
                        _transport_plan(leaves, spec, b)
                        for t in bucket]
                    for b in (1, 2, 3, 4)}
            for b in (2, 3, 4):
                assert flat[b] == flat[1]


# -- FIFO lifecycle seams ---------------------------------------------------


class TestPendingLifecycle:
    sched = build_schedule(RingGraph(WORLD, peers_per_itr=1))
    lane = KernelLane(interpret=True)

    def test_empty_incoming_matches_launch_structure(self):
        tree = {"w": jnp.zeros(96), "b": jnp.zeros(5),
                "s": jnp.zeros(())}
        inc = empty_incoming(tree, self.sched, kernel=self.lane,
                             buckets=3)
        assert isinstance(inc, PendingShares)
        assert len(inc.handles) == len(inc.plan) == 2
        assert inc.plan == _transport_plan(
            jax.tree.leaves(tree), F32_SPEC, 3)
        # without a kernel the slot is plain zeros
        plain = empty_incoming(tree, self.sched)
        assert not isinstance(plain, PendingShares)

    def test_scalar_only_tree_stays_on_the_ppermute_lane(self):
        # the push-sum weight (and any size<=1 leaf) must never build a
        # transport handle — the skip branch hands lax.cond plain zeros
        tree = {"w": jnp.zeros(()), "n": jnp.zeros(1)}
        inc = empty_incoming(tree, self.sched, kernel=self.lane,
                             buckets=4)
        assert not isinstance(inc, PendingShares)
        assert all(np.all(np.asarray(v) == 0)
                   for v in jax.tree.leaves(inc))

    def test_settling_a_zero_pending_lands_zero(self):
        # waiting an empty handle contributes decode(0) == 0 — the
        # structural zero the thinning skip branch relies on
        tree = {"w": jnp.ones(96), "b": jnp.ones(5)}
        inc = empty_incoming(tree, self.sched, kernel=self.lane,
                             buckets=2)
        assert isinstance(inc, PendingShares)
        settled = settle_share(inc)
        assert not isinstance(settled, PendingShares)
        for leaf in jax.tree.leaves(settled):
            np.testing.assert_array_equal(np.asarray(leaf), 0.0)
        landed = land_shares(tree, inc)
        for a, b in zip(jax.tree.leaves(landed), jax.tree.leaves(tree)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_land_rejects_mismatched_tree(self):
        tree = {"w": jnp.ones(96), "b": jnp.ones(5)}
        inc = empty_incoming(tree, self.sched, kernel=self.lane)
        with pytest.raises(ValueError, match="mirror"):
            land_shares({"w": jnp.ones(96)}, inc)


# -- trajectory oracle on the world-8 mesh ----------------------------------


def _run(sched, staleness, buckets, rounds=ROUNDS, overlap=True,
         codec=None, ef=False):
    """ROUNDS kernel-lane gossip steps; returns (params [W, D],
    ps-weight trajectory [rounds, W])."""
    alg = sgp(sched, GOSSIP_AXIS, wire=codec, error_feedback=ef,
              overlap=overlap, staleness=staleness,
              gossip_kernel=KernelLane(interpret=True),
              gossip_buckets=buckets)

    def step(p, g):
        p, g = alg.pre_step(p, g)
        return alg.post_step(p, g)

    mesh = make_gossip_mesh(WORLD)
    fn = jax.jit(jax.shard_map(step, mesh=mesh,
                               in_specs=(P(GOSSIP_AXIS),) * 2,
                               out_specs=(P(GOSSIP_AXIS),) * 2))
    rng = np.random.default_rng(3)
    params = {"w": rng.normal(size=(WORLD, 24)).astype(np.float32),
              "b": rng.normal(size=(WORLD, 5)).astype(np.float32)}
    gstate = jax.tree.map(
        lambda a: np.broadcast_to(np.asarray(a),
                                  (WORLD,) + np.shape(a)).copy(),
        alg.init(jax.tree.map(lambda a: jnp.zeros(a.shape[1:], a.dtype),
                              params)))
    traj = []
    for _ in range(rounds):
        params, gstate = jax.block_until_ready(fn(params, gstate))
        traj.append(np.asarray(gstate.ps_weight).reshape(WORLD).copy())
    return jax.tree.map(np.asarray, params), np.stack(traj)


def _numpy_overlap(sched, trees, w0, rounds, staleness):
    """Float64 push-sum overlap reference: launch ``(W_t − L_t)x_t`` at
    step ``t``, keep ``L_t x_t``, consume the share launched
    ``staleness − 1`` steps earlier (zero before warm-up)."""
    xs = [t.astype(np.float64).copy() for t in trees]
    wv = w0.astype(np.float64).copy()
    lag = staleness - 1
    shares, traj = [], []
    for t in range(rounds):
        W = sched.mixing_matrix(t)
        lo = np.diag(W)
        E = W - np.diag(lo)
        shares.append(([E @ x for x in xs], E @ wv))
        xs = [lo[:, None] * x for x in xs]
        wv = lo * wv
        if t - lag >= 0:
            sp, sw = shares[t - lag]
            xs = [x + s for x, s in zip(xs, sp)]
            wv = wv + sw
        traj.append(wv.copy())
    return xs, np.stack(traj)


def test_trajectory_matches_numpy_oracle_across_staleness_and_buckets():
    """The compiled kernel-lane round equals the dense-matrix push-sum
    reference at every (staleness, buckets) cell — bucketing and the
    split transport change HOW bytes move, never what arrives when."""
    sched = build_schedule(RingGraph(WORLD, peers_per_itr=1))
    for staleness in (1, 2, 3):
        for buckets in (1, 3):
            p, w = _run(sched, staleness, buckets)
            rng = np.random.default_rng(3)
            x0 = [rng.normal(size=(WORLD, 24)).astype(np.float32),
                  rng.normal(size=(WORLD, 5)).astype(np.float32)]
            # dict flatten order is sorted keys: "b" then "w"
            (rb, rw), wref = _numpy_overlap(
                sched, [x0[1], x0[0]], np.ones(WORLD), ROUNDS, staleness)
            label = f"staleness={staleness} buckets={buckets}"
            np.testing.assert_allclose(
                w, wref, atol=1e-6,
                err_msg=f"[{label}] ps-weight trajectory")
            np.testing.assert_allclose(
                p["w"], rw, atol=1e-5,
                err_msg=f"[{label}] params leaf 'w'")
            np.testing.assert_allclose(
                p["b"], rb, atol=1e-5,
                err_msg=f"[{label}] params leaf 'b'")


def test_bucket_count_is_bitwise_invisible():
    """buckets ∈ {1, 3} produce BIT-identical params and ps-weight on
    the same lane — packing concatenates and slices, it never reorders
    a leaf's arithmetic (int8 + EF + overlap is the harshest packing:
    block scales and the telescoping residual both cross the seam)."""
    sched = build_schedule(RingGraph(WORLD, peers_per_itr=1))
    i8 = wire.Int8Codec(64)
    for codec, ef, overlap, s in [(None, False, False, 1),
                                  (i8, True, True, 2)]:
        p1, w1 = _run(sched, s, 1, overlap=overlap, codec=codec, ef=ef)
        p3, w3 = _run(sched, s, 3, overlap=overlap, codec=codec, ef=ef)
        np.testing.assert_array_equal(w1, w3)
        for leaf in p1:
            np.testing.assert_array_equal(p1[leaf], p3[leaf])


def test_sgpv106_object_is_bucket_free():
    """SGPV106 verifies the augmented overlap schedule — an object the
    transport plan never touches (``_transport_plan`` takes leaves and a
    wire spec, no schedule), so bucketing cannot perturb the verified
    contraction.  Pin both halves: the verifier stays green on the
    schedule this file runs, and the plan is a pure function of the
    payload."""
    sched = build_schedule(RingGraph(WORLD, peers_per_itr=1))
    for s in (1, 2, 3):
        ov = sched.overlap_schedule(s)
        findings, gap = verify_schedule(ov, f"ring8-s{s}", "<test>", 1)
        assert not findings, [str(f) for f in findings]
        assert np.isfinite(gap) and gap > 0
    leaves = [np.zeros(96, np.float32), np.zeros(5, np.float32)]
    assert _transport_plan(leaves, F32_SPEC, 3) == \
        _transport_plan(list(leaves), F32_SPEC, 3)
