"""Expert parallelism: sharded switch MoE == single-shard reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from stochastic_gradient_push_tpu.models.moe import (
    moe_capacity,
    switch_moe_ffn,
)

EP = 4
T, D, F, E = 32, 8, 16, 8  # tokens per shard, dims, total experts
E_LOCAL = E // EP


@pytest.fixture(scope="module")
def mesh():
    import numpy as _np
    from jax.sharding import Mesh

    return Mesh(_np.array(jax.devices()[:EP]), ("ep",))


@pytest.fixture(scope="module")
def weights():
    rng = np.random.default_rng(0)
    return (rng.normal(size=(D, E)).astype(np.float32) * 0.5,
            rng.normal(size=(E, D, F)).astype(np.float32) * 0.3,
            rng.normal(size=(E, F, D)).astype(np.float32) * 0.3)


def test_ep_sharded_matches_single_shard(mesh, weights):
    router_w, w1, w2 = weights
    rng = np.random.default_rng(1)
    x = rng.normal(size=(EP, T, D)).astype(np.float32)

    def sharded(xs, w1s, w2s):
        y, aux = switch_moe_ffn(xs[0], router_w, w1s, w2s, ep_axis="ep")
        return y[None], jax.tree.map(lambda a: a[None], aux)

    f = jax.jit(jax.shard_map(
        sharded, mesh=mesh, in_specs=(P("ep"), P("ep"), P("ep")),
        out_specs=(P("ep"), P("ep"))))
    y_ep, aux_ep = f(x, w1, w2)
    y_ep = np.asarray(y_ep)
    # guard against vacuous equivalence: outputs must be nontrivial
    assert np.abs(y_ep).max() > 0.01, "MoE produced (near-)zero outputs"

    # single-shard reference processes each shard's tokens with all experts
    for shard in range(EP):
        y_ref, aux_ref = switch_moe_ffn(
            jnp.asarray(x[shard]), router_w, jnp.asarray(w1),
            jnp.asarray(w2), ep_axis=None)
        np.testing.assert_allclose(y_ep[shard], np.asarray(y_ref),
                                   rtol=2e-4, atol=2e-5)


def test_moe_routing_and_capacity():
    router_w, w1, w2 = (np.zeros((D, E), np.float32),
                        np.ones((E, D, F), np.float32),
                        np.ones((E, F, D), np.float32))
    # force all tokens to expert 0 via a biased router
    router_w[:, 0] = 0
    router_w[0, 0] = 100.0
    x = np.ones((T, D), np.float32)
    y, aux = switch_moe_ffn(jnp.asarray(x), jnp.asarray(router_w),
                            jnp.asarray(w1), jnp.asarray(w2), ep_axis=None)
    cap = moe_capacity(T, E)
    # only `cap` tokens fit in expert 0; the rest are dropped
    np.testing.assert_allclose(float(aux["dropped_fraction"]),
                               (T - cap) / T, atol=1e-6)
    # dropped tokens contribute zero output
    nonzero_rows = np.abs(np.asarray(y)).sum(axis=-1) > 0
    assert nonzero_rows.sum() == cap


def test_moe_gradients_flow(mesh, weights):
    router_w, w1, w2 = weights
    rng = np.random.default_rng(2)
    x = rng.normal(size=(EP, T, D)).astype(np.float32)

    def loss_fn(xs, rw, w1s, w2s):
        y, aux = switch_moe_ffn(xs[0], rw, w1s, w2s, ep_axis="ep")
        return (jnp.sum(y ** 2)
                + 0.01 * aux["load_balance_loss"])[None]

    def step(xs, rw, w1s, w2s):
        g = jax.grad(lambda *a: loss_fn(*a).sum(),
                     argnums=(1, 2, 3))(xs, rw, w1s, w2s)
        return g

    f = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P("ep"), P(), P("ep"), P("ep")),
        out_specs=(P(), P("ep"), P("ep"))))
    g_rw, g_w1, g_w2 = f(x, router_w, w1, w2)
    for g in (g_rw, g_w1, g_w2):
        g = np.asarray(g)
        assert np.all(np.isfinite(g))
        assert np.abs(g).max() > 0


def test_moe_router_size_validation():
    with pytest.raises(ValueError, match="router"):
        switch_moe_ffn(jnp.ones((4, D)), jnp.ones((D, 4)),
                       jnp.ones((E, D, F)), jnp.ones((E, F, D)),
                       ep_axis=None)


def test_moe_ring_per_block_routing_parity():
    """MoE × ring sequence parallelism: routing is per-token, so with
    enough capacity the sequence-sharded model (per-block routing) must
    match the single-shard full-attention model exactly."""
    from jax.sharding import Mesh

    from stochastic_gradient_push_tpu.models import (
        TransformerConfig, TransformerLM)

    B, T, V, sp = 2, 32, 64, 2
    mesh = Mesh(np.array(jax.devices()[:sp]), ("seq",))
    base = dict(vocab_size=V, d_model=16, n_layers=2, n_heads=2, d_ff=32,
                max_len=T, moe_experts=4, moe_every=2,
                moe_capacity_factor=8.0)
    m_full = TransformerLM(TransformerConfig(**base, attn_impl="full"))
    m_ring = TransformerLM(TransformerConfig(**base, attn_impl="ring",
                                             seq_axis="seq"))
    rng = np.random.default_rng(11)
    toks = jnp.asarray(rng.integers(0, V, size=(B, T)), jnp.int32)
    params = m_full.init(jax.random.PRNGKey(0), toks)["params"]

    logits_full, _ = m_full.apply(
        {"params": params}, toks, mutable=["losses", "moe_metrics"])

    def ring_fwd(p, blocks):
        out, _ = m_ring.apply({"params": p}, blocks[0],
                              mutable=["losses", "moe_metrics"])
        return out[None]

    blocks = jnp.asarray(toks).reshape(B, sp, T // sp).transpose(1, 0, 2)
    f = jax.jit(jax.shard_map(
        ring_fwd, mesh=mesh, in_specs=(P(), P("seq")),
        out_specs=P("seq")))
    lr = f(params, blocks)                       # [sp, B, block, V]
    logits_ring = np.asarray(lr).transpose(1, 0, 2, 3).reshape(B, T, V)
    np.testing.assert_allclose(np.asarray(logits_full), logits_ring,
                               rtol=2e-4, atol=2e-4)
