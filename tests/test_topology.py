"""Unit tests for graph topologies, mixing, and compiled schedules.

Pure-function tests (no devices needed): permutation property, regularity,
rotation periodicity, column-stochasticity, involution of bilat pairings —
the properties push-sum correctness rests on (SURVEY.md §4).
"""

import numpy as np
import pytest

from stochastic_gradient_push_tpu.topology import (
    GRAPH_TOPOLOGIES,
    DynamicBipartiteExponentialGraph,
    DynamicBipartiteLinearGraph,
    DynamicDirectedExponentialGraph,
    DynamicDirectedLinearGraph,
    NPeerDynamicDirectedExponentialGraph,
    RingGraph,
    UniformMixing,
    build_pairing_schedule,
    build_schedule,
)

ALL_GRAPHS = [
    DynamicDirectedExponentialGraph,
    NPeerDynamicDirectedExponentialGraph,
    DynamicBipartiteExponentialGraph,
    DynamicDirectedLinearGraph,
    DynamicBipartiteLinearGraph,
    RingGraph,
]


@pytest.mark.parametrize("cls", ALL_GRAPHS)
@pytest.mark.parametrize("world", [2, 4, 8, 16])
def test_phase_rows_are_permutations(cls, world):
    g = cls(world_size=world, peers_per_itr=1)
    perms = g.all_phase_permutations
    assert perms.shape == (g.num_phases, 1, world)
    for p in range(g.num_phases):
        assert sorted(perms[p, 0].tolist()) == list(range(world))
        # no self-sends
        assert not np.any(perms[p, 0] == np.arange(world))


@pytest.mark.parametrize("world,ppi", [(8, 2), (16, 2), (16, 3), (8, 1)])
def test_npdde_multi_peer(world, ppi):
    g = NPeerDynamicDirectedExponentialGraph(world_size=world,
                                             peers_per_itr=ppi)
    perms = g.all_phase_permutations
    assert perms.shape[1] == ppi
    for p in range(g.num_phases):
        dsts = set()
        for i in range(ppi):
            row = perms[p, i].tolist()
            assert sorted(row) == list(range(world))
            # distinct peers across sub-rounds for any given src
            for src in range(world):
                assert (i, row[src]) not in dsts
                dsts.add((i, row[src]))


@pytest.mark.parametrize("cls", ALL_GRAPHS)
def test_in_out_degree_regular(cls):
    world = 8
    g = cls(world_size=world, peers_per_itr=1)
    assert g.is_regular_graph()
    for phase in range(g.num_phases):
        for r in range(world):
            assert len(g.out_peers(r, phase)) == 1
            assert len(g.in_peers(r, phase)) == 1


def test_rotation_periodicity():
    g = DynamicDirectedExponentialGraph(world_size=8, peers_per_itr=1)
    # phone book: +-1, +-2, +-4 → 6 entries (4 == -4 mod 8 dedup → 5 entries)
    L = g.phone_book_len
    assert g.num_phases == L
    for r in range(8):
        assert g.out_peers(r, 0) == g.out_peers(r, g.num_phases)


def test_static_ring_never_rotates():
    g = RingGraph(world_size=8, peers_per_itr=1)
    assert g.num_phases == 1
    for phase in range(4):
        assert g.out_peers(3, phase) == (4,)
        assert g.in_peers(3, phase) == (2,)


def test_dde_peers_match_reference_structure():
    # world 8, rank 0: forward/backward powers of two: 1, 7, 2, 6, 4
    g = DynamicDirectedExponentialGraph(world_size=8)
    assert g.phone_book[0] == [1, 7, 2, 6, 4]


def test_npdde_peers_match_reference_structure():
    # world 16 ppi 1: distances 2^i → 1, 2, 4, 8
    g = NPeerDynamicDirectedExponentialGraph(world_size=16, peers_per_itr=1)
    assert g.phone_book[0] == [1, 2, 4, 8]
    # world 16 ppi 2: j*(3^i) for j in {1,2}, i in {0,1,2} → 1,2,3,6,9,18%16=2?
    g2 = NPeerDynamicDirectedExponentialGraph(world_size=16, peers_per_itr=2)
    assert g2.phone_book[0][:4] == [1, 2, 3, 6]


def test_bipartite_active_passive_split():
    g = DynamicBipartiteExponentialGraph(world_size=8)
    for r in range(8):
        assert g.is_passive(r) == (r % 2 == 0)
        for phase in range(g.num_phases):
            for peer in g.out_peers(r, phase):
                assert g.is_passive(peer) != g.is_passive(r)


@pytest.mark.parametrize("cls", ALL_GRAPHS)
@pytest.mark.parametrize("world", [4, 8])
def test_schedule_column_stochastic(cls, world):
    g = cls(world_size=world, peers_per_itr=1)
    sched = build_schedule(g, UniformMixing())
    for p in range(sched.num_phases):
        W = sched.mixing_matrix(p)
        np.testing.assert_allclose(W.sum(axis=0), np.ones(world), atol=1e-12)


@pytest.mark.parametrize("cls", ALL_GRAPHS)
def test_schedule_doubly_stochastic_when_regular(cls):
    # uniform mixing on a regular graph → rows also sum to 1
    g = cls(world_size=8, peers_per_itr=1)
    sched = build_schedule(g, UniformMixing())
    assert sched.regular
    for p in range(sched.num_phases):
        W = sched.mixing_matrix(p)
        np.testing.assert_allclose(W.sum(axis=1), np.ones(8), atol=1e-12)


def test_mixing_matrix_products_converge_to_consensus():
    # repeated application of the phase-cycled mixing matrices must drive
    # any vector to its mean (ergodicity of the time-varying graph)
    g = NPeerDynamicDirectedExponentialGraph(world_size=8, peers_per_itr=1)
    sched = build_schedule(g)
    x = np.random.default_rng(0).normal(size=(8,))
    mean = x.mean()
    for step in range(60):
        x = sched.mixing_matrix(step) @ x
    np.testing.assert_allclose(x, np.full(8, mean), atol=1e-9)


@pytest.mark.parametrize("cls", [DynamicBipartiteExponentialGraph,
                                 DynamicBipartiteLinearGraph, RingGraph,
                                 DynamicDirectedExponentialGraph])
@pytest.mark.parametrize("world", [4, 8, 16])
def test_pairing_schedule_involution(cls, world):
    g = cls(world_size=world)
    pairing = build_pairing_schedule(g)
    n_phases, n = pairing.shape
    assert n == world
    for p in range(n_phases):
        row = pairing[p]
        assert np.array_equal(row[row], np.arange(world))
        assert not np.any(row == np.arange(world))  # nobody self-paired


def test_pairing_covers_multiple_partners():
    g = DynamicBipartiteExponentialGraph(world_size=8)
    pairing = build_pairing_schedule(g)
    partners_of_1 = set(pairing[:, 1].tolist())
    assert len(partners_of_1) > 1


def test_registry_ids_match_reference():
    # gossip_sgd.py:54-67
    assert GRAPH_TOPOLOGIES[0] is DynamicDirectedExponentialGraph
    assert GRAPH_TOPOLOGIES[1] is DynamicBipartiteExponentialGraph
    assert GRAPH_TOPOLOGIES[2] is DynamicDirectedLinearGraph
    assert GRAPH_TOPOLOGIES[3] is DynamicBipartiteLinearGraph
    assert GRAPH_TOPOLOGIES[4] is RingGraph
    assert GRAPH_TOPOLOGIES[5] is NPeerDynamicDirectedExponentialGraph
    assert GRAPH_TOPOLOGIES[-1] is None


def test_world_size_one_is_trivial():
    g = NPeerDynamicDirectedExponentialGraph(world_size=1)
    assert g.out_peers(0, 0) == ()
    sched = build_schedule(g)
    np.testing.assert_allclose(sched.mixing_matrix(0), np.ones((1, 1)))
