"""Native C++ image pipeline vs the PIL reference path.

The native loader (data/native_src/loader.cc via data/native.py) must be a
drop-in replacement for the PIL decode in data/imagefolder.py: identical
augmentation stream (the crop/flip rng is sampled in Python either way)
and resampling within Pillow's fixed-point rounding (~1 uint8 LSB).  The
reference gets this layer from torch's C++ DataLoader + torchvision
(gossip_sgd.py:546-583); here it is the framework's own native component.
"""

import os

import numpy as np
import pytest

from stochastic_gradient_push_tpu.data.imagefolder import ImageFolderDataset
from stochastic_gradient_push_tpu.data.native import (NativeDecoder,
                                                      get_native)
from stochastic_gradient_push_tpu.data.streaming import StreamingImageFolder

native = get_native()
pytestmark = pytest.mark.skipif(
    native is None, reason="native loader unavailable (g++/libjpeg)")

# ~1 uint8 LSB in normalized units: 1/255/std_min = 1/255/0.225
LSB = 1.0 / 255.0 / 0.225


@pytest.fixture(scope="module")
def image_root(tmp_path_factory):
    """Two-class folder of JPEGs (plus one PNG to exercise the fallback).

    Sizes stay below 2x the resample targets used in the tests so the
    DCT-domain downscale never triggers at max_denom=1 parity checks.
    """
    from PIL import Image

    root = tmp_path_factory.mktemp("imgs")
    rng = np.random.default_rng(0)
    sizes = [(200, 150), (97, 131), (128, 128), (240, 180), (150, 220)]
    for cls in ("a", "b"):
        d = root / "train" / cls
        d.mkdir(parents=True)
        for i, (w, h) in enumerate(sizes):
            arr = (rng.random((h, w, 3)) * 255).astype(np.uint8)
            arr = np.asarray(Image.fromarray(arr).resize(
                (w, h), Image.BILINEAR))  # smooth: limits JPEG noise
            Image.fromarray(arr).save(d / f"img{i}.jpg", quality=95)
        # one PNG: libjpeg rejects it, the PIL fallback must cover it
        png = (rng.random((64, 80, 3)) * 255).astype(np.uint8)
        Image.fromarray(png).save(d / "zz_extra.png")
    return str(root / "train")


def _decoders(root, train, image_size=64, seed=7, max_denom=1):
    ds = ImageFolderDataset(root, image_size=image_size, train=train,
                            seed=seed)
    dec = NativeDecoder(ds.paths, image_size, train, seed=seed,
                        threads=2, max_denom=max_denom)
    return ds, dec


@pytest.mark.parametrize("train", [True, False], ids=["train", "eval"])
def test_parity_with_pil(image_root, train):
    ds, dec = _decoders(image_root, train)
    for epoch in (0, 3):
        ds.set_epoch(epoch)
        dec.set_epoch(epoch)
        idx = np.arange(len(ds))
        out = dec.decode(idx)
        ref = np.stack([ds[int(i)][0] for i in idx])
        assert out.shape == ref.shape
        d = np.abs(out - ref)
        # JPEGs: within ~2 LSB of the PIL path; PNGs go through the PIL
        # fallback and must be exact
        assert float(d.max()) < 2.5 * LSB
        for j, i in enumerate(idx):
            if ds.paths[int(i)].endswith(".png"):
                np.testing.assert_array_equal(out[j], ref[j])


def test_augmentation_stream_changes_with_epoch(image_root):
    _, dec = _decoders(image_root, train=True)
    jpeg_idx = np.array([0, 1, 2])
    a = dec.decode(jpeg_idx)
    dec.set_epoch(1)
    b = dec.decode(jpeg_idx)
    assert np.abs(a - b).max() > 10 * LSB  # fresh crops every epoch


def test_eval_is_deterministic(image_root):
    _, dec = _decoders(image_root, train=False)
    a = dec.decode(np.array([0, 4]))
    b = dec.decode(np.array([0, 4]))
    np.testing.assert_array_equal(a, b)


def test_eval_resize_rounds_half_to_even(tmp_path):
    """Exact-.5 short-side targets: Python round() is half-to-even, and the
    C++ path must agree (nearbyint), or the resize dimension differs by a
    row and every pixel shifts."""
    from PIL import Image

    d = tmp_path / "half" / "c"
    d.mkdir(parents=True)
    rng = np.random.default_rng(5)
    # 256x257 at S=112: short_target=128, nh = round(128*257/256) =
    # round(128.5) -> 128 under banker's rounding (lround would say 129)
    arr = (rng.random((257, 256, 3)) * 255).astype(np.uint8)
    arr = np.asarray(Image.fromarray(arr).resize((256, 257),
                                                 Image.BILINEAR))
    Image.fromarray(arr).save(d / "half.jpg", quality=95)
    ds, dec = _decoders(str(tmp_path / "half"), train=False, image_size=112)
    out = dec.decode(np.array([0]))
    ref = ds[0][0]
    assert float(np.abs(out[0] - ref).max()) < 2.5 * LSB


def test_dct_downscale_stays_close(image_root, tmp_path):
    """max_denom=8 may decode at 1/2+ resolution; the result must stay a
    faithful (antialiased) downscale, not an aliased or shifted one."""
    from PIL import Image

    d = tmp_path / "big" / "c"
    d.mkdir(parents=True)
    # smooth gradient at odd dims: a correct antialiased downscale
    # preserves it nearly exactly regardless of decode resolution, while
    # any output-grid misalignment (e.g. reconstructing original dims as
    # scaled_dims * denom, which overshoots because libjpeg ceils) shows
    # up as a systematic shift.  Odd dims pin the full_w/full_h
    # bookkeeping.
    h, w = 401, 521
    yy, xx = np.mgrid[0:h, 0:w]
    arr = np.stack([xx * 255 / w, yy * 255 / h,
                    (xx + yy) * 255 / (w + h)], -1).astype(np.uint8)
    Image.fromarray(arr).save(d / "big.jpg", quality=98)
    root = str(tmp_path / "big")

    ds, fast = _decoders(root, train=False, image_size=64, max_denom=8)
    _, exact = _decoders(root, train=False, image_size=64, max_denom=1)
    out_fast = fast.decode(np.array([0]))
    out_exact = exact.decode(np.array([0]))
    diff = np.abs(out_fast - out_exact)
    assert float(diff.mean()) < 2 * LSB
    assert float(diff.max()) < 6 * LSB


@pytest.mark.parametrize("train", [True, False], ids=["train", "eval"])
def test_streaming_backend_native_matches_pil(image_root, train):
    # image_size 96: large enough that the default max_denom=8 DCT
    # downscale never triggers on the fixture's <=240px images, so the
    # two backends differ only by resampling rounding
    kw = dict(split="", world_size=2, batch_size=2, image_size=96,
              train=train, num_workers=2, prefetch=2, seed=1)
    nat = StreamingImageFolder(image_root, backend="native", **kw)
    pil = StreamingImageFolder(image_root, backend="pil", **kw)
    assert nat.decoder is not None and pil.decoder is None
    nat.set_epoch(2)
    pil.set_epoch(2)
    for (xi, yi), (xp, yp) in zip(nat, pil):
        np.testing.assert_array_equal(yi, yp)
        assert xi.shape == xp.shape
        assert float(np.abs(xi - xp).max()) < 2.5 * LSB


def test_uint8_output_matches_f32_after_normalize(image_root):
    """output='uint8' must carry the SAME pixels as the f32 path pre-
    normalization: normalizing the uint8 batch reproduces the f32 batch
    bit-exactly (both quantize to the uint8 grid before normalize)."""
    from stochastic_gradient_push_tpu.data.imagefolder import (
        IMAGENET_MEAN, IMAGENET_STD)

    ds, dec = _decoders(image_root, train=True, image_size=64)
    idx = np.arange(len(ds))
    u8 = dec.decode(idx, output="uint8")
    f32 = dec.decode(idx, output="f32")
    assert u8.dtype == np.uint8
    renorm = (u8.astype(np.float32) / 255.0 - IMAGENET_MEAN) / IMAGENET_STD
    np.testing.assert_allclose(renorm, f32, atol=1e-6)


def test_uint8_streaming_and_device_normalize(image_root):
    """End to end: a uint8-streamed batch through the jitted train step
    equals the f32-streamed batch (device normalize == host normalize)."""
    import jax
    import jax.numpy as jnp

    from stochastic_gradient_push_tpu.train.step import _device_normalize

    kw = dict(split="", world_size=2, batch_size=2, image_size=96,
              train=True, num_workers=2, prefetch=2, seed=1)
    u8 = StreamingImageFolder(image_root, output="uint8", **kw)
    f32 = StreamingImageFolder(image_root, output="f32", **kw)
    (xu, yu), (xf, yf) = next(iter(u8)), next(iter(f32))
    assert xu.dtype == np.uint8 and xf.dtype == np.float32
    np.testing.assert_array_equal(yu, yf)
    normed = jax.jit(_device_normalize)(jnp.asarray(xu))
    np.testing.assert_allclose(np.asarray(normed), xf, atol=1e-6)
    # float batches pass through untouched
    np.testing.assert_array_equal(
        np.asarray(jax.jit(_device_normalize)(jnp.asarray(xf))), xf)


def test_uint8_pil_backend(image_root):
    """The uint8 contract holds on the pure-PIL backend too (fallback
    parity: PNGs and toolchain-less hosts)."""
    kw = dict(split="", world_size=2, batch_size=2, image_size=96,
              train=False, num_workers=2, prefetch=2, seed=1)
    nat = StreamingImageFolder(image_root, backend="native",
                               output="uint8", **kw)
    pil = StreamingImageFolder(image_root, backend="pil",
                               output="uint8", **kw)
    for (xi, yi), (xp, yp) in zip(nat, pil):
        assert xi.dtype == xp.dtype == np.uint8
        np.testing.assert_array_equal(yi, yp)
        assert int(np.abs(xi.astype(int) - xp.astype(int)).max()) <= 2


def test_bad_file_falls_back(image_root, tmp_path):
    d = tmp_path / "bad" / "c"
    d.mkdir(parents=True)
    # valid magic, truncated body: native decode fails -> PIL also fails
    # -> but a real PNG decodes through the fallback
    from PIL import Image

    png = (np.random.default_rng(0).random((32, 40, 3)) * 255
           ).astype(np.uint8)
    Image.fromarray(png).save(d / "ok.png")
    ds, dec = _decoders(str(tmp_path / "bad"), train=False, image_size=16)
    out = dec.decode(np.array([0]))
    ref = ds[0][0]
    np.testing.assert_array_equal(out[0], ref)


def test_resampler_fuzz_vs_pil(tmp_path):
    """Seeded fuzz of the C++ resampler against PIL across random sizes,
    crops (incl. 1-2 pixel boxes), upscales, and flips: every case must
    stay within ~1 uint8 LSB of PIL.  (A 120-case sweep recorded a worst
    deviation of exactly 1 LSB.)"""
    from PIL import Image

    from stochastic_gradient_push_tpu.data.imagefolder import (
        IMAGENET_MEAN, IMAGENET_STD)

    rng = np.random.default_rng(0)
    for trial in range(25):
        w, h = int(rng.integers(8, 200)), int(rng.integers(8, 200))
        arr = (rng.random((h, w, 3)) * 255).astype(np.uint8)
        p = str(tmp_path / f"t{trial}.jpg")
        Image.fromarray(arr).save(p, quality=95)
        S = int(rng.integers(8, 96))
        cw = int(rng.integers(1, w + 1))
        ch = int(rng.integers(1, h + 1))
        left = int(rng.integers(0, w - cw + 1))
        top = int(rng.integers(0, h - ch + 1))
        flip = int(rng.integers(0, 2))
        raw = native.decode_one(p.encode(), (left, top, cw, ch, flip),
                                S, 0, 1)
        assert raw is not None, (trial, w, h, S, left, top, cw, ch)
        got = np.frombuffer(raw, np.float32).reshape(S, S, 3)
        with Image.open(p) as img:
            ref = img.convert("RGB").resize(
                (S, S), Image.BILINEAR,
                box=(left, top, left + cw, top + ch))
            if flip:
                ref = ref.transpose(Image.FLIP_LEFT_RIGHT)
            ref = (np.asarray(ref, np.float32) / 255.0
                   - IMAGENET_MEAN) / IMAGENET_STD
        assert float(np.abs(got - ref).max()) < 1.5 * LSB, \
            (trial, w, h, S, left, top, cw, ch, flip)


def test_decode_batch_validates_buffers(image_root):
    ds, dec = _decoders(image_root, train=False, image_size=32)
    paths = [os.fsencode(ds.paths[0])]
    boxes = np.zeros((1, 5), np.int32)
    small = np.zeros((1, 8, 8, 3), np.float32)
    with pytest.raises(ValueError):
        native.decode_batch(paths, boxes, small, 32, 1, 0)
    with pytest.raises(ValueError):
        native.decode_batch(paths, np.zeros((1, 2), np.int32),
                            np.zeros((1, 32, 32, 3), np.float32), 32, 1, 0)
    with pytest.raises(ValueError):
        native.decode_batch(paths, boxes,
                            np.zeros((1, 32, 32, 3), np.float32), 32, 1, 7)
    # element types are pinned, not just byte lengths: int64 boxes of
    # sufficient byte size must raise, not be reinterpreted as int32
    with pytest.raises(TypeError):
        native.decode_batch(paths, boxes.astype(np.int64),
                            np.zeros((1, 32, 32, 3), np.float32), 32, 1, 0)
    # float32 out for the uint8 mode (and vice versa) is a type error
    with pytest.raises(TypeError):
        native.decode_batch(paths, boxes,
                            np.zeros((1, 32, 32, 3), np.float32), 32, 1, 2)
    with pytest.raises(TypeError):
        native.decode_batch(paths, boxes,
                            np.zeros((1, 32, 32, 3), np.uint8), 32, 1, 0)
