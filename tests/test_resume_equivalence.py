"""Checkpoint-resume exactness: training N epochs straight must equal
training k epochs, saving, restoring into a fresh Trainer, and training the
remaining N-k — same parameters, same sampler order, same LR trajectory.
The reference could never test this (no tests, no fake backend)."""

import jax
import numpy as np
import pytest

from stochastic_gradient_push_tpu.data import (
    DistributedSampler,
    ShardedLoader,
    synthetic_classification,
)
from stochastic_gradient_push_tpu.models import TinyMLP
from stochastic_gradient_push_tpu.parallel import make_gossip_mesh
from stochastic_gradient_push_tpu.topology import (
    NPeerDynamicDirectedExponentialGraph,
)
from stochastic_gradient_push_tpu.train.loop import Trainer, TrainerConfig
from stochastic_gradient_push_tpu.utils.checkpoint import (
    CheckpointManager,
    ClusterManager,
)

WORLD, BATCH, CLASSES, IMG = 8, 4, 4, 8


@pytest.fixture(scope="module")
def mesh():
    return make_gossip_mesh(WORLD)


def make_cfg(tmp_path, num_epochs, resume=False):
    return TrainerConfig(
        graph_class=NPeerDynamicDirectedExponentialGraph,
        lr=0.2, warmup=False, lr_schedule={2: 0.5},
        batch_size=BATCH, num_epochs=num_epochs, num_itr_ignore=0,
        checkpoint_dir=str(tmp_path), num_classes=CLASSES,
        verbose=False, resume=resume, train_fast=False)


def run(tmp_path, mesh, data, num_epochs, resume=False, state=None):
    images, labels = data
    cfg = make_cfg(tmp_path, num_epochs, resume)
    ckpt = CheckpointManager(str(tmp_path), world_size=WORLD)
    cluster = ClusterManager(ckpt, install_handlers=False)
    trainer = Trainer(cfg, TinyMLP(num_classes=CLASSES), mesh,
                      sample_input_shape=(BATCH, IMG, IMG, 3),
                      cluster_manager=cluster)
    if state is None:
        state = trainer.init_state()
    sampler = DistributedSampler(len(images), WORLD)
    loader = ShardedLoader(images, labels, BATCH, sampler)
    state, _ = trainer.fit(state, loader, sampler, val_loader=loader)
    return state


def test_resume_matches_straight_run(tmp_path, mesh):
    data = synthetic_classification(WORLD * BATCH * 3, num_classes=CLASSES,
                                    image_size=IMG, seed=0)
    # straight: 4 epochs in one go
    straight = run(tmp_path / "a", mesh, data, num_epochs=4)
    # split: 2 epochs, checkpoint (the Trainer saves every epoch), then a
    # FRESH trainer restores and finishes epochs 2-3
    run(tmp_path / "b", mesh, data, num_epochs=2)
    resumed = run(tmp_path / "b", mesh, data, num_epochs=4, resume=True)

    for a, b in zip(jax.tree.leaves(straight.params),
                    jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    # optimizer momentum and gossip state continue exactly too
    for a, b in zip(jax.tree.leaves(straight.opt_state),
                    jax.tree.leaves(resumed.opt_state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(straight.step),
                                  np.asarray(resumed.step))
    np.testing.assert_allclose(np.asarray(straight.gossip.phase),
                               np.asarray(resumed.gossip.phase))


def test_resume_matches_straight_run_stale_overlap(tmp_path, mesh):
    """Resume exactness with OSGP bounded staleness: the in-flight FIFO
    (a tuple of slots) round-trips through the checkpoint and the resumed
    trajectory matches the straight run exactly."""
    import dataclasses

    data = synthetic_classification(WORLD * BATCH * 3, num_classes=CLASSES,
                                    image_size=IMG, seed=1)

    def run_o(path, num_epochs, resume=False):
        images, labels = data
        cfg = dataclasses.replace(make_cfg(path, num_epochs, resume),
                                  overlap=True, synch_freq=1)
        ckpt = CheckpointManager(str(path), world_size=WORLD)
        cluster = ClusterManager(ckpt, install_handlers=False)
        trainer = Trainer(cfg, TinyMLP(num_classes=CLASSES), mesh,
                          sample_input_shape=(BATCH, IMG, IMG, 3),
                          cluster_manager=cluster)
        state = trainer.init_state()
        sampler = DistributedSampler(len(images), WORLD)
        loader = ShardedLoader(images, labels, BATCH, sampler)
        state, _ = trainer.fit(state, loader, sampler, val_loader=loader)
        return state

    straight = run_o(tmp_path / "a", 4)
    run_o(tmp_path / "b", 2)
    resumed = run_o(tmp_path / "b", 4, resume=True)

    # the FIFO structure survived the round-trip on the RESUMED state
    assert len(resumed.gossip.in_flight) == 2  # staleness = synch_freq+1
    for a, b in zip(jax.tree.leaves(straight), jax.tree.leaves(resumed),
                    strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
