"""Run-layer tests: checkpointing, preemption manager, CLI, visualization."""

import json
import os
import signal
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from stochastic_gradient_push_tpu.utils.checkpoint import (
    CheckpointManager,
    ClusterManager,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _state():
    return {"params": {"w": jnp.arange(6.0).reshape(2, 3)},
            "ps_weight": jnp.ones((4, 1))}


def test_checkpoint_roundtrip(tmp_path):
    cm = CheckpointManager(str(tmp_path), tag="t_", rank=0, world_size=4)
    state = _state()
    cm.save(state, {"epoch": 3, "itr": 7}, is_best=True)
    assert cm.exists()
    template = {"params": {"w": jnp.zeros((2, 3))},
                "ps_weight": jnp.zeros((4, 1))}
    restored, meta = cm.restore(template)
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                  np.asarray(state["params"]["w"]))
    assert meta == {"epoch": 3, "itr": 7}
    assert os.path.isfile(cm.best_path)


def test_checkpoint_epoch_files_update_canonical(tmp_path):
    cm = CheckpointManager(str(tmp_path), world_size=2)
    cm.save(_state(), {"epoch": 1}, epoch_id=0)
    cm.save(_state(), {"epoch": 2}, epoch_id=1)
    # unique per-epoch files exist AND the canonical resume path tracks them
    assert os.path.isfile(cm.path_for_epoch(0))
    assert os.path.isfile(cm.path_for_epoch(1))
    _, meta = cm.restore(_state())
    assert meta["epoch"] == 2


def test_cluster_manager_preemption_flow(tmp_path):
    cm = CheckpointManager(str(tmp_path), world_size=2)
    marker = tmp_path / "requeued"
    cluster = ClusterManager(cm, rank=0,
                             requeue_command=f"touch {marker}",
                             install_handlers=False)
    # no signal → normal save
    cluster.save_checkpoint(_state(), {"epoch": 0})
    assert not marker.exists()
    # simulate SIGUSR1 → checkpoint, requeue, exit
    cluster._sigusr1(signal.SIGUSR1, None)
    with pytest.raises(SystemExit):
        cluster.save_checkpoint(_state(), {"epoch": 1})
    assert marker.exists()
    # the flag survives exit (peer processes must still see it) and is
    # cleared by the requeued job's ClusterManager init
    assert os.path.isfile(cluster._flag_path)
    ClusterManager(cm, rank=0, install_handlers=False)
    assert not os.path.isfile(cluster._flag_path)


def test_cluster_manager_flag_is_shared_via_fs(tmp_path):
    cm1 = CheckpointManager(str(tmp_path), world_size=2)
    a = ClusterManager(cm1, rank=0, install_handlers=False)
    b = ClusterManager(cm1, rank=1, install_handlers=False)
    assert not b.any_rank_signalled()
    a._sigusr1(signal.SIGUSR1, None)
    # the other "rank" observes the preemption via the shared flag file
    assert b.any_rank_signalled()


CLI_ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
}


def _run_cli(module, tmp_path, extra=(), timeout=420):
    cmd = [sys.executable, "-m", module,
           "--dataset", "synthetic", "--world_size", "8",
           "--model", "tiny_cnn", "--num_classes", "4",
           "--image_size", "8", "--batch_size", "4",
           "--num_epochs", "1", "--num_itr_ignore", "0",
           "--num_iterations_per_training_epoch", "3",
           "--checkpoint_dir", str(tmp_path) + "/", *extra]
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, env=CLI_ENV)


@pytest.mark.slow
def test_cli_end_to_end_produces_csv_and_checkpoint(tmp_path):
    r = _run_cli("stochastic_gradient_push_tpu.run.gossip_sgd", tmp_path)
    assert r.returncode == 0, r.stderr[-2000:]
    csv = tmp_path / "out_r0_n8.csv"
    assert csv.exists()
    lines = csv.read_text().splitlines()
    assert lines[0] == "BEGIN-TRAINING"
    assert lines[4].startswith("Epoch,itr,BT(s),avg:BT(s),std:BT(s),")
    assert any(line.split(",")[1] == "-1" for line in lines[5:])  # val row
    assert (tmp_path / "checkpoint_r0_n8.ckpt").exists()
    # state and meta live in one atomic msgpack payload
    import flax.serialization

    raw = flax.serialization.msgpack_restore(
        (tmp_path / "checkpoint_r0_n8.ckpt").read_bytes())
    assert set(raw) == {"state", "meta"}
    assert raw["meta"]["epoch"] == 1


@pytest.mark.slow
def test_cli_all_reduce_baseline(tmp_path):
    r = _run_cli("stochastic_gradient_push_tpu.run.gossip_sgd", tmp_path,
                 extra=("--all_reduce", "True", "--graph_type", "-1"))
    assert r.returncode == 0, r.stderr[-2000:]


@pytest.mark.slow
def test_cli_hierarchical_and_bf16(tmp_path):
    r = _run_cli("stochastic_gradient_push_tpu.run.gossip_sgd", tmp_path,
                 extra=("--nprocs_per_node", "2", "--precision", "bf16"))
    assert r.returncode == 0, r.stderr[-2000:]
    assert (tmp_path / "out_r0_n8.csv").exists()


@pytest.mark.slow
def test_cli_adpsgd(tmp_path):
    r = _run_cli("stochastic_gradient_push_tpu.run.gossip_sgd_adpsgd",
                 tmp_path)
    assert r.returncode == 0, r.stderr[-2000:]


@pytest.mark.slow
def test_cli_lm_ring_sp(tmp_path):
    cmd = [sys.executable, "-m",
           "stochastic_gradient_push_tpu.run.gossip_lm",
           "--world_size", "8", "--sp", "2", "--seq_len", "32",
           "--d_model", "32", "--n_layers", "1", "--n_heads", "4",
           "--d_ff", "32", "--vocab_size", "32", "--batch_size", "2",
           "--num_steps", "4", "--corpus_tokens", "20000",
           "--checkpoint_dir", str(tmp_path)]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=420,
                      env=CLI_ENV)
    assert r.returncode == 0, r.stderr[-2000:]
    csv = tmp_path / "lm_out_n8.csv"
    assert csv.exists()
    assert csv.read_text().splitlines()[0] == \
        "step,loss,ppl,lr,tokens_per_sec,grad_norm"
    # the grad_norm column carries real values on every training row
    assert all(float(l.split(",")[5]) > 0
               for l in csv.read_text().splitlines()[1:])


def test_cli_rejects_inconsistent_flags(tmp_path):
    from stochastic_gradient_push_tpu.run.gossip_sgd import parse_config
    with pytest.raises(SystemExit):
        parse_config(["--all_reduce", "True", "--graph_type", "5"])
    with pytest.raises(SystemExit):
        parse_config(["--peers_per_itr_schedule", "5", "2"])


def test_parse_pair_schedules():
    from stochastic_gradient_push_tpu.run.gossip_sgd import parse_config
    cfg, _ = parse_config([
        "--schedule", "30", "0.1", "60", "0.1", "80", "0.1",
        "--peers_per_itr_schedule", "0", "1", "10", "2"])
    assert cfg.lr_schedule == {30: 0.1, 60: 0.1, 80: 0.1}
    assert cfg.ppi_schedule == {0: 1, 10: 2}


def test_visualization_parses_trainer_csv(tmp_path):
    from stochastic_gradient_push_tpu.visualization import (
        parse_csv, plot_itrs)
    f = tmp_path / "out_r0_n8.csv"
    f.write_text(
        "BEGIN-TRAINING\nWorld-Size,8\nNum-DLWorkers,0\nBatch-Size,8\n"
        "Epoch,itr,BT(s),avg:BT(s),std:BT(s),NT(s),avg:NT(s),std:NT(s),"
        "DT(s),avg:DT(s),std:DT(s),Loss,avg:Loss,Prec@1,avg:Prec@1,"
        "Prec@5,avg:Prec@5,val\n"
        "0,0,0.1,0.1,0.0,0.08,0.08,0.0,0.01,0.01,0.0,"
        "2.0,2.0,10.0,10.0,50.0,50.0,-1\n"
        "0,10,0.1,0.1,0.0,0.08,0.08,0.0,0.01,0.01,0.0,"
        "1.5,1.7,20.0,15.0,60.0,55.0,-1\n"
        "0,-1,0.1,0.1,0.0,0.08,0.08,0.0,0.01,0.01,0.0,"
        "-1,-1,-1,-1,-1,-1,42.5\n")
    train, val = parse_csv(str(f))
    assert len(train) == 2 and len(val) == 1
    assert float(val["val"].iloc[0]) == 42.5
    fig = plot_itrs(str(tmp_path), world_size=8, out_path=str(
        tmp_path / "fig.png"))
    assert (tmp_path / "fig.png").exists()


def test_error_vs_time_figure(tmp_path):
    """The paper's headline error-vs-wall-time figure (reference
    plotting.py:255-292, x='time'): per-epoch cross-rank means with the
    elapsed-seconds estimate, train and val variants."""
    from stochastic_gradient_push_tpu.visualization import (
        parse_epochs, plot_error_vs_time)

    header = (
        "BEGIN-TRAINING\nWorld-Size,2\nNum-DLWorkers,0\nBatch-Size,8\n"
        "Epoch,itr,BT(s),avg:BT(s),std:BT(s),NT(s),avg:NT(s),std:NT(s),"
        "DT(s),avg:DT(s),std:DT(s),Loss,avg:Loss,Prec@1,avg:Prec@1,"
        "Prec@5,avg:Prec@5,val\n")
    for rank, (p1_a, p1_b, v_a, v_b) in enumerate(
            [(10.0, 30.0, 25.0, 45.0), (20.0, 40.0, 25.0, 45.0)]):
        (tmp_path / f"out_r{rank}_n2.csv").write_text(
            header
            + f"0,9,0.1,0.2,0.0,0.1,0.1,0.0,0.0,0.0,0.0,"
              f"2.0,2.0,{p1_a},{p1_a},50.0,50.0,-1\n"
            + f"0,-1,0.1,0.2,0.0,0.1,0.1,0.0,0.0,0.0,0.0,"
              f"-1,-1,-1,-1,-1,-1,{v_a}\n"
            + f"1,9,0.1,0.2,0.0,0.1,0.1,0.0,0.0,0.0,0.0,"
              f"1.5,1.5,{p1_b},{p1_b},60.0,60.0,-1\n"
            + f"1,-1,0.1,0.2,0.0,0.1,0.1,0.0,0.0,0.0,0.0,"
              f"-1,-1,-1,-1,-1,-1,{v_b}\n")

    pdf = parse_epochs(str(tmp_path), world_size=2)
    assert len(pdf) == 2
    # cross-rank mean train error: 100 - mean(10, 20) = 85, then 65
    assert pdf["train_mean"].tolist() == [85.0, 65.0]
    assert pdf["val_mean"].tolist() == [75.0, 55.0]
    # elapsed: epoch-end itr × final mean avg:BT = (10, 20) × 0.2
    assert pdf["time"].tolist() == [2.0, 4.0]

    # a rank killed mid-epoch has an epoch-end train row without a val
    # row: alignment is by Epoch, and means skip the missing entries
    (tmp_path / "out_r1_n2.csv").write_text(
        header
        + "0,9,0.1,0.2,0.0,0.1,0.1,0.0,0.0,0.0,0.0,"
          "2.0,2.0,20.0,20.0,50.0,50.0,-1\n"
        + "0,-1,0.1,0.2,0.0,0.1,0.1,0.0,0.0,0.0,0.0,"
          "-1,-1,-1,-1,-1,-1,45.0\n"
        + "1,5,0.1,0.2,0.0,0.1,0.1,0.0,0.0,0.0,0.0,"
          "1.5,1.5,40.0,40.0,60.0,60.0,-1\n")
    pdf = parse_epochs(str(tmp_path), world_size=2)
    assert pdf["train_mean"].tolist() == [85.0, 65.0]
    # epoch 0: mean(75, 55); epoch 1: only rank 0's val row exists
    assert pdf["val_mean"].tolist() == [65.0, 55.0]

    plot_error_vs_time({"SGP": str(tmp_path)}, 2,
                       out_path=str(tmp_path / "evt.png"))
    plot_error_vs_time({"SGP": str(tmp_path)}, 2, val=True,
                       out_path=str(tmp_path / "evt_val.png"))
    assert (tmp_path / "evt.png").exists()
    assert (tmp_path / "evt_val.png").exists()


@pytest.mark.slow
def test_cli_lm_resume_migrates_stale_csv_header(tmp_path):
    """Resuming a run whose CSV predates a schema change re-seats every
    old value under its original column name (a stale header must not
    leave val_loss parsing as grad_norm)."""
    base = [sys.executable, "-m",
            "stochastic_gradient_push_tpu.run.gossip_lm",
            "--world_size", "2", "--seq_len", "32", "--d_model", "32",
            "--n_layers", "1", "--n_heads", "4", "--d_ff", "32",
            "--vocab_size", "32", "--batch_size", "2",
            "--corpus_tokens", "20000", "--checkpoint_dir", str(tmp_path)]
    r = subprocess.run(base + ["--num_steps", "4"], capture_output=True,
                       text=True, timeout=420, env=CLI_ENV)
    assert r.returncode == 0, r.stderr[-2000:]
    csv = tmp_path / "lm_out_n2.csv"
    lines = csv.read_text().splitlines()
    assert lines[0] == "step,loss,ppl,lr,tokens_per_sec,grad_norm"
    # forge a pre-grad_norm file: drop the grad_norm column entirely
    old_rows = [",".join(l.split(",")[:5]) for l in lines[1:]]
    csv.write_text("step,loss,ppl,lr,tokens_per_sec\n"
                   + "\n".join(old_rows) + "\n")
    r = subprocess.run(base + ["--num_steps", "8", "--resume", "True"],
                       capture_output=True, text=True, timeout=420,
                       env=CLI_ENV)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = csv.read_text().splitlines()
    assert lines[0] == "step,loss,ppl,lr,tokens_per_sec,grad_norm"
    # old rows were padded with an empty grad_norm slot, new rows carry
    # real values — and every loss still sits in the loss column
    for line in lines[1:]:
        cells = line.split(",")
        assert len(cells) == 6
        assert float(cells[1]) > 0  # loss
    assert any(cells == "" for cells in
               (l.split(",")[5] for l in lines[1:]))
    assert any(c not in ("",) and float(c) > 0 for c in
               (l.split(",")[5] for l in lines[1:]))


def test_plot_scaling_and_transformer_parse(tmp_path):
    from stochastic_gradient_push_tpu.visualization import (
        parse_transformer_out,
        plot_scaling,
        plot_transformer,
    )

    fig = plot_scaling({4: 0.4, 8: 0.45, 16: 0.5},
                       baseline={4: 0.5, 8: 0.7, 16: 1.1},
                       out_path=str(tmp_path / "scaling.png"))
    assert (tmp_path / "scaling.png").exists()

    log = tmp_path / "transformer.log"
    log.write_text(
        "| epoch 001 | loss 7.123 | wall 120.5 |\n"
        "garbage line\n"
        "| epoch 002 | loss 6.050 | wall 260.0 |\n")
    df = parse_transformer_out(str(log))
    assert len(df) == 2
    assert df["loss"].tolist() == [7.123, 6.05]
    plot_transformer({"SGP": str(log)},
                     out_path=str(tmp_path / "nll.png"))
    assert (tmp_path / "nll.png").exists()


@pytest.mark.slow
def test_cli_orbax_backend_save_and_resume(tmp_path):
    """--ckpt_backend orbax through the full CLI path: save, then resume."""
    r = _run_cli("stochastic_gradient_push_tpu.run.gossip_sgd", tmp_path,
                 extra=("--ckpt_backend", "orbax"))
    assert r.returncode == 0, r.stderr[-2000:]
    root = tmp_path / "orbax_r0_n8"
    assert root.is_dir() and any(root.iterdir())
    r2 = _run_cli("stochastic_gradient_push_tpu.run.gossip_sgd", tmp_path,
                  extra=("--ckpt_backend", "orbax", "--resume", "True",
                         "--num_epochs", "2"))
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert "resumed from epoch 1" in r2.stdout + r2.stderr


def test_trainer_watchdog_fires_on_slow_step(tmp_path):
    """The heartbeat is wired into the Trainer's blocking step (≙ the
    reference's 300s gossip-flag timeout, distributed.py:36,349-352)."""
    import time as _time

    from stochastic_gradient_push_tpu.data import (
        DistributedSampler, ShardedLoader, synthetic_classification)
    from stochastic_gradient_push_tpu.models import TinyMLP
    from stochastic_gradient_push_tpu.parallel import make_gossip_mesh
    from stochastic_gradient_push_tpu.topology import (
        NPeerDynamicDirectedExponentialGraph)
    from stochastic_gradient_push_tpu.train.loop import (
        Trainer, TrainerConfig)

    mesh = make_gossip_mesh(8)
    # 4 batches: the heartbeat only arms on warm steps (the first two calls
    # of a variant may compile), so the slow 3rd/4th steps must trip it
    images, labels = synthetic_classification(
        n=8 * 4 * 4, num_classes=4, image_size=8, seed=0)
    cfg = TrainerConfig(
        graph_class=NPeerDynamicDirectedExponentialGraph,
        lr=0.1, warmup=False, lr_schedule={}, batch_size=4, num_epochs=1,
        num_itr_ignore=0, checkpoint_dir=str(tmp_path), num_classes=4,
        verbose=False, train_fast=True, heartbeat_timeout=1)
    trainer = Trainer(cfg, TinyMLP(num_classes=4), mesh,
                      sample_input_shape=(4, 8, 8, 3))
    assert trainer.watchdog is not None

    orig = trainer._train_fn

    def slow(ppi, ipe, scan=1):
        alg, fn = orig(ppi, ipe, scan)

        def delayed(s, x, y):
            _time.sleep(1.3)  # exceed the 1s heartbeat
            return fn(s, x, y)

        return alg, delayed

    trainer._train_fn = slow
    state = trainer.init_state()
    sampler = DistributedSampler(len(images), 8)
    loader = ShardedLoader(images, labels, 4, sampler)
    trainer.fit(state, loader, sampler)
    assert trainer.watchdog.timed_out

    # timeout 0 disables the watchdog entirely
    cfg0 = TrainerConfig(
        graph_class=NPeerDynamicDirectedExponentialGraph,
        heartbeat_timeout=0, checkpoint_dir=str(tmp_path), num_classes=4,
        verbose=False)
    assert Trainer(cfg0, TinyMLP(num_classes=4), mesh,
                   sample_input_shape=(4, 8, 8, 3)).watchdog is None


def test_parse_and_plot_lm_csv(tmp_path):
    """LM CSVs (with and without validation columns) parse and plot."""
    from stochastic_gradient_push_tpu.visualization.plotting import (
        parse_lm_csv, plot_lm)

    plain = tmp_path / "lm_out_n8.csv"
    plain.write_text("step,loss,ppl,lr,tokens_per_sec\n"
                     "2,4.5,90.0,0.1,1000\n4,4.2,66.7,0.1,1200\n")
    withval = tmp_path / "lm_val_out_n8.csv"
    withval.write_text(
        "step,loss,ppl,lr,tokens_per_sec,val_loss,val_ppl\n"
        "2,4.5,90.0,0.1,1000,,\n4,4.2,66.7,0.1,1200,4.3,73.7\n")

    df = parse_lm_csv(str(plain))
    assert list(df["step"]) == [2, 4]
    dfv = parse_lm_csv(str(withval))
    assert dfv["val_loss"].notna().sum() == 1

    fig = plot_lm({"SGP": str(plain), "SGP+val": str(withval)},
                  out_path=str(tmp_path / "lm.png"))
    assert (tmp_path / "lm.png").exists()
    import matplotlib.pyplot
    matplotlib.pyplot.close(fig)


def test_load_corpus_variants(tmp_path):
    """--corpus_file: .npy token arrays validated against vocab; other
    files read as byte-level corpora (vocab >= 256 enforced)."""
    import numpy as np
    import pytest

    from stochastic_gradient_push_tpu.data.lm import load_corpus

    npy = tmp_path / "toks.npy"
    np.save(npy, np.arange(100) % 30)
    arr = load_corpus(str(npy), 256)
    assert arr.dtype == np.int32 and arr.shape == (100,)
    with pytest.raises(ValueError, match="outside vocab_size"):
        load_corpus(str(npy), 16)
    bad = tmp_path / "f.npy"
    np.save(bad, np.linspace(0, 1, 10))
    with pytest.raises(ValueError, match="integer"):
        load_corpus(str(bad), 256)
    txt = tmp_path / "c.txt"
    txt.write_bytes(b"abc" * 50)
    b = load_corpus(str(txt), 256)
    assert b.shape == (150,) and int(b.max()) < 256
    with pytest.raises(ValueError, match="vocab_size >= 256"):
        load_corpus(str(txt), 100)


# -- planner wiring (--topology / --gap_floor / --global_avg_every) ----------

def test_topology_flag_forces_named_graph():
    from stochastic_gradient_push_tpu.run.gossip_sgd import parse_config
    from stochastic_gradient_push_tpu.topology import (
        DynamicBipartiteLinearGraph, RingGraph)

    cfg, args = parse_config(["--topology", "ring"])
    assert cfg.graph_class is RingGraph
    # the name overrides the integer registry
    cfg, _ = parse_config(["--topology", "bipartite-linear",
                           "--graph_type", "4"])
    assert cfg.graph_class is DynamicBipartiteLinearGraph


def test_topology_flag_validation():
    from stochastic_gradient_push_tpu.run.gossip_sgd import parse_config

    with pytest.raises(SystemExit):
        parse_config(["--topology", "auto", "--all_reduce", "True",
                      "--graph_type", "-1"])
    with pytest.raises(SystemExit):
        parse_config(["--mixing_alpha", "bogus"])
    with pytest.raises(SystemExit):
        parse_config(["--mixing_alpha", "1.5"])
    with pytest.raises(SystemExit):  # D-PSGD needs a regular schedule
        parse_config(["--mixing_alpha", "auto", "--push_sum", "False"])
    with pytest.raises(SystemExit):  # AllReduce doesn't mix at all
        parse_config(["--mixing_alpha", "auto", "--all_reduce", "True",
                      "--graph_type", "-1"])


def test_global_avg_every_threads_into_config():
    from stochastic_gradient_push_tpu.run.gossip_sgd import parse_config

    cfg, _ = parse_config(["--global_avg_every", "5"])
    assert cfg.global_avg_every == 5


def test_resolve_plan_auto_configures_trainer_config():
    """_resolve_plan mutates the TrainerConfig exactly as main() would:
    planned graph class, stamped plan dict, averaging period."""
    from stochastic_gradient_push_tpu.run.gossip_sgd import (
        _resolve_plan, parse_config)
    from stochastic_gradient_push_tpu.topology import RingGraph
    from stochastic_gradient_push_tpu.utils import make_logger

    log = make_logger("test-plan", verbose=False)
    cfg, args = parse_config(["--topology", "auto"])
    _resolve_plan(cfg, args, 64, log)
    assert cfg.graph_class is not RingGraph
    assert cfg.plan and cfg.plan["auto"] and cfg.plan["gap"] >= 0.01
    assert cfg.global_avg_every == 0

    # forced ring at 64: warned (log) + periodic averaging enabled
    cfg, args = parse_config(["--topology", "ring"])
    _resolve_plan(cfg, args, 64, log)
    assert cfg.graph_class is RingGraph
    assert cfg.plan["warnings"] and cfg.global_avg_every == 100

    # alpha co-optimization rides the plan into mixing_class
    cfg, args = parse_config(["--topology", "auto",
                              "--mixing_alpha", "auto",
                              "--peers_per_itr_schedule", "0", "4"])
    _resolve_plan(cfg, args, 64, log)
    mixing = cfg.mixing_class()
    assert float(mixing.alpha[0]) == pytest.approx(cfg.plan["alpha"])


def test_lm_rejects_topology_outside_gossip_family():
    """A forced --topology must never be silently dropped: all_reduce and
    bilat modes reject it instead of falling back to --graph_type."""
    from stochastic_gradient_push_tpu.run.gossip_lm import main as lm_main

    base = ["--world_size", "8", "--seq_len", "32", "--d_model", "32",
            "--n_layers", "1", "--n_heads", "4", "--d_ff", "32",
            "--vocab_size", "32", "--batch_size", "2", "--num_steps", "1"]
    with pytest.raises(SystemExit, match="does not apply"):
        lm_main(base + ["--topology", "ring", "--all_reduce", "True"])
    with pytest.raises(SystemExit, match="does not apply"):
        lm_main(base + ["--topology", "auto", "--bilat", "True"])


@pytest.mark.slow
def test_cli_topology_auto_end_to_end(tmp_path):
    """--topology auto through the full CLI: plan logged, training runs,
    plan stamped into checkpoint metadata."""
    r = _run_cli("stochastic_gradient_push_tpu.run.gossip_sgd", tmp_path,
                 extra=("--topology", "auto"))
    assert r.returncode == 0, r.stderr[-2000:]
    assert "gossip plan: " in r.stdout + r.stderr
    import flax.serialization

    raw = flax.serialization.msgpack_restore(
        (tmp_path / "checkpoint_r0_n8.ckpt").read_bytes())
    plan = raw["meta"]["plan"]
    assert plan["auto"] and plan["topology"] in (
        "bipartite-exponential", "bipartite-linear", "linear",
        "npeer-exponential", "exponential")
    assert plan["gap"] >= 0.01
