"""Gossip data parallelism × tensor parallelism (Megatron-style, GSPMD).

The gossip collective runs as manual SPMD over the ``gossip`` axis while the
``tp`` axis stays auto: each rank's transformer compute is partitioned by
GSPMD according to the kernel shardings from ``apply_tp_sharding``.  The
pinning test: tp=2 must produce the SAME training trajectory as tp=1 —
tensor parallelism is an implementation detail, not an algorithm change."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stochastic_gradient_push_tpu.algorithms import sgp
from stochastic_gradient_push_tpu.data.lm import (
    lm_batches,
    synthetic_lm_corpus,
)
from stochastic_gradient_push_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
)
from stochastic_gradient_push_tpu.parallel import GOSSIP_AXIS, make_gossip_mesh
from stochastic_gradient_push_tpu.topology import (
    DynamicDirectedExponentialGraph,
    build_schedule,
)
from stochastic_gradient_push_tpu.train import LRSchedule, sgd
from stochastic_gradient_push_tpu.train.lm import (
    build_lm_train_step,
    init_lm_state_tp,
    make_dp_tp_mesh,
    shard_lm_train_step,
)
from stochastic_gradient_push_tpu.train.state import TrainState

DP, TP = 4, 2
VOCAB, D, LAYERS, HEADS, FF = 64, 32, 2, 4, 64
BATCH, SEQ = 2, 32


def build(model, alg, tx, mesh, tp):
    lrs = LRSchedule(ref_lr=0.5, batch_size=BATCH, world_size=DP,
                     decay_schedule={}, warmup=False)
    step = build_lm_train_step(model, alg, tx, lrs, itr_per_epoch=100,
                               seq_axis=None)
    return shard_lm_train_step(step, mesh, seq_axis=None, tp=tp)


def init_state(model, alg, tx, dp):
    tokens = jnp.zeros((BATCH, SEQ), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    from stochastic_gradient_push_tpu.train.step import replicate_state

    params = replicate_state(variables["params"], dp)
    one = lambda t: jax.tree.map(lambda a: a[0], t)
    return TrainState(
        step=jnp.zeros((dp,), jnp.int32), params=params, batch_stats={},
        opt_state=replicate_state(tx.init(one(params)), dp),
        gossip=replicate_state(alg.init(one(params)), dp))


def run_steps(train_fn, state, n=6):
    corpus = synthetic_lm_corpus(20_000, vocab_size=VOCAB, seed=1)
    losses = []
    for tokens, targets in lm_batches(corpus, DP, 1, BATCH, SEQ, seed=0):
        tokens = tokens.reshape(DP, BATCH, SEQ)
        targets = targets.reshape(DP, BATCH, SEQ)
        state, metrics = train_fn(state, tokens, targets)
        jax.block_until_ready(state)
        losses.append(np.mean(np.asarray(metrics["loss"])))
        if len(losses) >= n:
            break
    return state, losses


@pytest.mark.slow
def test_tp_matches_tp1_trajectory():
    cfg = TransformerConfig(vocab_size=VOCAB, d_model=D, n_layers=LAYERS,
                            n_heads=HEADS, d_ff=FF, max_len=SEQ,
                            attn_impl="full")
    model = TransformerLM(cfg)
    sched = build_schedule(DynamicDirectedExponentialGraph(DP))
    tx = sgd(momentum=0.9, weight_decay=0.0)

    # tp=1 baseline on a flat 4-device mesh
    alg = sgp(sched, GOSSIP_AXIS)
    mesh1 = make_gossip_mesh(DP)
    fn1 = build(model, alg, tx, mesh1, tp=False)
    st1 = init_state(model, alg, tx, DP)
    st1, losses1 = run_steps(fn1, st1)

    # tp=2 on a (4, 2) mesh with Megatron shardings, sharded from init
    mesh2 = make_dp_tp_mesh(DP, TP)
    fn2 = build(model, alg, tx, mesh2, tp=True)
    st2 = init_lm_state_tp(model, mesh2, alg, tx, dp=DP,
                           batch_size=BATCH, seq_len=SEQ)
    st2, losses2 = run_steps(fn2, st2)

    np.testing.assert_allclose(losses1, losses2, rtol=2e-4, atol=2e-4)
    for a, b in zip(jax.tree.leaves(st1.params),
                    jax.tree.leaves(st2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-3, atol=3e-4)


def test_tp_kernels_are_actually_sharded():
    cfg = TransformerConfig(vocab_size=VOCAB, d_model=D, n_layers=1,
                            n_heads=HEADS, d_ff=FF, max_len=SEQ)
    model = TransformerLM(cfg)
    sched = build_schedule(DynamicDirectedExponentialGraph(DP))
    tx = sgd()
    alg = sgp(sched, GOSSIP_AXIS)
    mesh = make_dp_tp_mesh(DP, TP)
    state = init_lm_state_tp(model, mesh, alg, tx, dp=DP,
                             batch_size=BATCH, seq_len=SEQ)

    flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
    tp_sharded = 0
    for path, leaf in flat:
        names = [getattr(p, "key", str(p)) for p in path]
        spec = leaf.sharding.spec
        if names[-1] == "kernel" and names[-2] in ("q", "k", "v", "up",
                                                   "lm_head"):
            assert spec[-1] == "tp", (names, spec)
            tp_sharded += 1
        elif names[-1] == "kernel" and names[-2] in ("o", "down"):
            assert spec[-2] == "tp", (names, spec)
            tp_sharded += 1
        else:
            assert "tp" not in str(spec), (names, spec)
    assert tp_sharded == 7  # q,k,v,o,up,down,lm_head for 1 layer
    # momentum buffers mirror the param shardings by path
    mom = jax.tree_util.tree_flatten_with_path(state.opt_state)[0]
    assert any("tp" in str(leaf.sharding.spec) for _, leaf in mom)


@pytest.mark.slow
def test_three_way_dp_sp_tp_trains():
    """Full composition: 2 gossip replicas x 2 sequence shards x 2 tensor
    shards on 8 devices — ring attention over the manual seq axis while
    GSPMD partitions kernels over the auto tp axis."""
    from stochastic_gradient_push_tpu.train.lm import (
        SEQ_AXIS,
        init_lm_state,
        make_dp_sp_tp_mesh,
    )

    dp, sp, tp = 2, 2, 2
    block = SEQ // sp
    mesh = make_dp_sp_tp_mesh(dp, sp, tp)
    cfg = TransformerConfig(vocab_size=VOCAB, d_model=D, n_layers=LAYERS,
                            n_heads=HEADS, d_ff=FF, max_len=SEQ,
                            attn_impl="ring", seq_axis=SEQ_AXIS)
    model = TransformerLM(cfg)
    sched = build_schedule(DynamicDirectedExponentialGraph(dp))
    alg = sgp(sched, GOSSIP_AXIS)
    tx = sgd(momentum=0.9, weight_decay=0.0)
    lrs = LRSchedule(ref_lr=0.5, batch_size=BATCH, world_size=dp,
                     decay_schedule={}, warmup=False)
    step = build_lm_train_step(model, alg, tx, lrs, itr_per_epoch=100)
    train_fn = shard_lm_train_step(step, mesh, tp=True)
    state = init_lm_state(model, mesh, alg, tx, dp=dp, sp=sp,
                          batch_size=BATCH, block_len=block)
    # tp kernels actually sharded over the 3-D mesh
    assert any("tp" in str(l.sharding.spec)
               for l in jax.tree.leaves(state.params))

    corpus = synthetic_lm_corpus(30_000, vocab_size=VOCAB, seed=2)
    losses = []
    for epoch in range(3):
        for tokens, targets in lm_batches(corpus, dp, sp, BATCH, SEQ,
                                          seed=epoch):
            state, metrics = train_fn(state, tokens, targets)
            jax.block_until_ready(state)
            losses.append(float(np.mean(np.asarray(metrics["loss"]))))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.95


@pytest.mark.slow
def test_moe_with_tp_matches_tp1():
    """MoE + tensor parallelism: expert FF dims shard over the auto tp
    axis; the trajectory must match tp=1 exactly."""
    from stochastic_gradient_push_tpu.train.lm import init_lm_state_tp

    cfg = TransformerConfig(vocab_size=VOCAB, d_model=D, n_layers=2,
                            n_heads=HEADS, d_ff=FF, max_len=SEQ,
                            attn_impl="full", moe_experts=4, moe_every=2)
    model = TransformerLM(cfg)
    sched = build_schedule(DynamicDirectedExponentialGraph(DP))
    tx = sgd(momentum=0.9, weight_decay=0.0)

    alg = sgp(sched, GOSSIP_AXIS)
    mesh1 = make_gossip_mesh(DP)
    fn1 = build(model, alg, tx, mesh1, tp=False)
    st1 = init_state(model, alg, tx, DP)
    st1, losses1 = run_steps(fn1, st1)

    mesh2 = make_dp_tp_mesh(DP, TP)
    fn2 = build(model, alg, tx, mesh2, tp=True)
    st2 = init_lm_state_tp(model, mesh2, alg, tx, dp=DP,
                           batch_size=BATCH, seq_len=SEQ)
    # expert stacks actually tp-sharded on their FF dim
    flat = jax.tree_util.tree_flatten_with_path(st2.params)[0]
    expert_specs = [str(l.sharding.spec) for p, l in flat
                    if any("experts" in str(k) for k in p)]
    assert expert_specs and all("tp" in sp for sp in expert_specs)
    st2, losses2 = run_steps(fn2, st2)
    np.testing.assert_allclose(losses1, losses2, rtol=3e-4, atol=3e-4)
