"""Property tests for gossip collectives on 8 virtual CPU devices.

Push-sum invariants (SURVEY.md §4): mass conservation, consensus on static
inputs, agreement with the numpy mixing-matrix simulator — the fake-backend
test capability the reference lacks entirely.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from stochastic_gradient_push_tpu.parallel import (
    GOSSIP_AXIS,
    allreduce_mean,
    gossip_round,
    mix_bilat,
    mix_push_pull,
    mix_push_sum,
    make_gossip_mesh,
)
from stochastic_gradient_push_tpu.topology import (
    DynamicBipartiteExponentialGraph,
    DynamicDirectedExponentialGraph,
    NPeerDynamicDirectedExponentialGraph,
    RingGraph,
    build_pairing_schedule,
    build_schedule,
)

WORLD = 8


def shard_gossip(fn, mesh, in_specs, out_specs):
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs)


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() >= WORLD, "conftest must fake 8 devices"
    return make_gossip_mesh(WORLD)


def _per_rank_values(seed=0, shape=(4, 3)):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(WORLD,) + shape).astype(np.float32)


@pytest.mark.parametrize("graph_cls,ppi", [
    (NPeerDynamicDirectedExponentialGraph, 1),
    (NPeerDynamicDirectedExponentialGraph, 2),
    (DynamicDirectedExponentialGraph, 1),
    (RingGraph, 1),
])
def test_gossip_round_matches_mixing_matrix(mesh, graph_cls, ppi):
    sched = build_schedule(graph_cls(WORLD, peers_per_itr=ppi))
    x = _per_rank_values(seed=1)

    def step(phase, xs):
        return gossip_round(xs, phase, sched, GOSSIP_AXIS)

    f = jax.jit(shard_gossip(step, mesh,
                             (P(), P(GOSSIP_AXIS)), P(GOSSIP_AXIS)))
    for phase in range(sched.num_phases + 1):
        got = np.asarray(f(jnp.int32(phase), x))
        W = sched.mixing_matrix(phase)
        want = np.einsum("rs,s...->r...", W, x.astype(np.float64))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_mass_conservation(mesh):
    """Σ_r x_r is invariant under any gossip round (column stochasticity)."""
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    x = _per_rank_values(seed=2)

    def step(phase, xs):
        return gossip_round(xs, phase, sched, GOSSIP_AXIS)

    f = jax.jit(shard_gossip(step, mesh,
                             (P(), P(GOSSIP_AXIS)), P(GOSSIP_AXIS)))
    total = x.sum(axis=0)
    for phase in range(sched.num_phases):
        x = np.asarray(f(jnp.int32(phase), x))
        np.testing.assert_allclose(x.sum(axis=0), total, rtol=1e-4, atol=1e-4)


def test_push_sum_consensus_on_static_input(mesh):
    """Iterated push-sum drives de-biased values to the global mean."""
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    x = _per_rank_values(seed=3, shape=(5,))
    w = np.ones((WORLD, 1), dtype=np.float32)

    def step(phase, xs, ws):
        return mix_push_sum(xs, ws, phase, sched, GOSSIP_AXIS)

    f = jax.jit(shard_gossip(
        step, mesh, (P(), P(GOSSIP_AXIS), P(GOSSIP_AXIS)),
        (P(GOSSIP_AXIS), P(GOSSIP_AXIS))))

    mean = x.mean(axis=0)
    for phase in range(50):
        x, w = map(np.asarray, f(jnp.int32(phase), x, w))
    debiased = x / w
    np.testing.assert_allclose(debiased,
                               np.broadcast_to(mean, debiased.shape),
                               rtol=1e-4, atol=1e-4)


def test_push_sum_weight_stays_one_for_regular_schedule(mesh):
    sched = build_schedule(
        DynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    assert sched.regular
    x = _per_rank_values(seed=4, shape=(2,))
    w = np.ones((WORLD, 1), dtype=np.float32)

    def step(phase, xs, ws):
        return mix_push_sum(xs, ws, phase, sched, GOSSIP_AXIS)

    f = jax.jit(shard_gossip(
        step, mesh, (P(), P(GOSSIP_AXIS), P(GOSSIP_AXIS)),
        (P(GOSSIP_AXIS), P(GOSSIP_AXIS))))
    for phase in range(sched.num_phases):
        x, w = map(np.asarray, f(jnp.int32(phase), x, w))
        np.testing.assert_allclose(w, np.ones_like(w), rtol=1e-5)


def test_bilat_round_pairwise_average(mesh):
    graph = DynamicBipartiteExponentialGraph(WORLD)
    pairing = build_pairing_schedule(graph)
    x = _per_rank_values(seed=5, shape=(3,))

    def step(phase, xs):
        return mix_bilat(xs, phase, pairing, GOSSIP_AXIS)

    f = jax.jit(shard_gossip(step, mesh,
                             (P(), P(GOSSIP_AXIS)), P(GOSSIP_AXIS)))
    got = np.asarray(f(jnp.int32(0), x))
    for r in range(WORLD):
        partner = pairing[0, r]
        np.testing.assert_allclose(got[r], 0.5 * (x[r] + x[partner]),
                                   rtol=1e-6)

    # iterating pairwise averaging over rotating matchings → consensus
    y = x
    for phase in range(40):
        y = np.asarray(f(jnp.int32(phase), y))
    np.testing.assert_allclose(
        y, np.broadcast_to(x.mean(axis=0), y.shape), rtol=1e-3, atol=1e-3)


def test_push_pull_doubly_stochastic_consensus(mesh):
    """D-PSGD primitive: mean preserved every round, consensus at the end."""
    import dataclasses

    sched = build_schedule(DynamicBipartiteExponentialGraph(WORLD))
    assert sched.regular
    x = _per_rank_values(seed=11, shape=(3,))
    mean = x.mean(axis=0)

    def step(phase, xs):
        return mix_push_pull(xs, phase, sched, GOSSIP_AXIS)

    f = jax.jit(shard_gossip(step, mesh,
                             (P(), P(GOSSIP_AXIS)), P(GOSSIP_AXIS)))
    for phase in range(40):
        x = np.asarray(f(jnp.int32(phase), x))
        # doubly-stochastic mixing preserves the *mean* exactly
        np.testing.assert_allclose(x.mean(axis=0), mean, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(x, np.broadcast_to(mean, x.shape),
                               rtol=1e-3, atol=1e-4)

    # the regular-schedule gate: push-pull must reject irregular mixing
    irregular = dataclasses.replace(sched, regular=False)
    with pytest.raises(ValueError, match="regular"):
        mix_push_pull(x[0], 0, irregular, GOSSIP_AXIS)


def test_gossip_round_pytree(mesh):
    """Gossip mixes arbitrary pytrees (the flatten/unflatten of helpers.py
    :21-57 is unnecessary — XLA fuses per-leaf collectives)."""
    sched = build_schedule(RingGraph(WORLD, peers_per_itr=1))
    tree = {"a": _per_rank_values(seed=6, shape=(2, 2)),
            "b": [_per_rank_values(seed=7, shape=(3,))]}

    def step(phase, t):
        return gossip_round(t, phase, sched, GOSSIP_AXIS)

    f = jax.jit(shard_gossip(step, mesh,
                             (P(), P(GOSSIP_AXIS)), P(GOSSIP_AXIS)))
    out = f(jnp.int32(0), tree)
    W = sched.mixing_matrix(0)
    for key, leaf in (("a", tree["a"]), ("b", tree["b"][0])):
        got = np.asarray(out[key] if key == "a" else out["b"][0])
        want = np.einsum("rs,s...->r...", W, leaf.astype(np.float64))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_allreduce_mean(mesh):
    x = _per_rank_values(seed=8)

    def step(xs):
        return allreduce_mean(xs, GOSSIP_AXIS)

    f = jax.jit(shard_gossip(step, mesh, (P(GOSSIP_AXIS),), P(GOSSIP_AXIS)))
    got = np.asarray(f(x))
    want = np.broadcast_to(x.mean(axis=0), x.shape)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_no_recompilation_across_phases(mesh):
    """Phase is traced: stepping through the rotation must not retrace."""
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    x = _per_rank_values(seed=9, shape=(2,))
    traces = 0

    def step(phase, xs):
        nonlocal traces
        traces += 1
        return gossip_round(xs, phase, sched, GOSSIP_AXIS)

    f = jax.jit(shard_gossip(step, mesh,
                             (P(), P(GOSSIP_AXIS)), P(GOSSIP_AXIS)))
    for phase in range(6):
        f(jnp.int32(phase), x)
    assert traces == 1
