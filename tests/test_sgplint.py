"""sgplint: both engines run in tier-1 on CPU.

* the repo itself must be clean against the checked-in baseline (empty:
  no grandfathered semantic findings, no unsuppressed lint findings);
* every rule id fires exactly where its known-bad fixture says
  (``# EXPECT: RULE`` line comments / ``# EXPECT-MODULE:`` headers) and
  nowhere in the known-clean fixture;
* the spectral-gap report covers the full topology grid with strictly
  positive gaps.
"""

import glob
import importlib.util
import os
import re

import pytest

from stochastic_gradient_push_tpu.analysis import (
    RULES,
    lint_file,
    lint_paths,
    lint_program,
    load_baseline,
    render_rules_markdown,
    save_baseline,
    stale_baseline_entries,
    verify_module,
    verify_package,
)
from stochastic_gradient_push_tpu.analysis.astlint import (
    collect_axis_vocabulary,
)
from stochastic_gradient_push_tpu.analysis.findings import (
    Finding,
    partition_against_baseline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "stochastic_gradient_push_tpu")
FIXDIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "fixtures", "sgplint")
BASELINE = os.path.join(REPO, "sgplint.baseline.json")

AXES = collect_axis_vocabulary([PKG])

_EXPECT_LINE = re.compile(r"#\s*EXPECT:\s*([A-Z0-9, ]+)")
_EXPECT_MODULE = re.compile(r"#\s*EXPECT-MODULE:\s*([A-Z0-9, ]+)")

FIXTURES = sorted(glob.glob(os.path.join(FIXDIR, "*.py")))


def _read(path):
    with open(path) as f:
        return f.read()


def _expected_line_rules(path):
    out = set()
    for i, line in enumerate(_read(path).splitlines(), start=1):
        m = _EXPECT_LINE.search(line)
        if m and "EXPECT-MODULE" not in line:
            for rule in m.group(1).split(","):
                out.add((i, rule.strip()))
    return out


def _expected_module_rules(path):
    m = _EXPECT_MODULE.search(_read(path))
    if not m:
        return []
    return sorted(r.strip() for r in m.group(1).split(","))


def _import_fixture(path):
    name = "sgplint_fixture_" + os.path.basename(path)[:-3]
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- the repo gate ---------------------------------------------------------


def test_mesh_axis_vocabulary_is_discovered():
    # the axes every engine-1 rule keys on; a regression here would let
    # SGPL001 pass vacuously
    assert {"gossip", "node", "local", "seq", "tp", "ep",
            "pipe"} <= AXES


def test_repo_ast_lint_clean_vs_baseline():
    # the CI sweep: package + scripts/ + tests/ (fixtures excluded),
    # Engine 1 under the fixpoint closure plus Engine 3 — and the
    # ratchet: no stale grandfathered entries either
    from stochastic_gradient_push_tpu.analysis.cli import lint_targets

    findings, graph = lint_program(lint_targets(), relto=REPO)
    baseline = load_baseline(BASELINE)
    new, _ = partition_against_baseline(findings, baseline)
    assert new == [], "\n".join(f.render() for f in new)
    assert stale_baseline_entries(findings, baseline) == []
    # the call-graph artifact is real: the whole package is in it
    report = graph.to_report(relto=REPO)
    assert report["modules"] > 100
    assert report["traced_functions"] > 50
    assert report["cross_module_edges"] > 10


def test_repo_schedule_verifier_clean_with_empty_baseline():
    findings, gaps = verify_package(relto=REPO)
    # acceptance: zero grandfathered semantic findings, ever
    assert findings == [], "\n".join(f.render() for f in findings)
    assert len(gaps) > 300  # the full topology x world x ppi x mixing grid
    assert all(g.gap > 0 for g in gaps)


def test_spectral_gap_report_flags_slow_ring():
    # documents the ROADMAP open item: the static ring's gap collapses
    # quadratically with world size while exponential graphs stay flat
    _, gaps = verify_package(world_sizes=(64,), peer_counts=(1,))
    by_topo = {g.topology: g.gap for g in gaps if g.mixing == "uniform"}
    assert by_topo["RingGraph"] < 0.01
    assert by_topo["DynamicDirectedExponentialGraph"] > 0.05


# -- fixture suite ---------------------------------------------------------


@pytest.mark.parametrize(
    "path", FIXTURES, ids=[os.path.basename(p)[:-3] for p in FIXTURES])
def test_fixture_rules_fire_exactly_where_expected(path):
    expected = _expected_line_rules(path)
    got = {(f.line, f.rule)
           for f in lint_file(path, AXES, relto=FIXDIR)}
    assert got == expected, (
        f"AST engine mismatch in {os.path.basename(path)}:\n"
        f"  unexpected: {sorted(got - expected)}\n"
        f"  missing:    {sorted(expected - got)}")

    expected_mod = _expected_module_rules(path)
    has_material = bool(re.search(r"^SGPLINT_", _read(path), re.M))
    if expected_mod or has_material:
        mod = _import_fixture(path)
        sem = verify_module(mod, relto=FIXDIR)
        assert sorted(f.rule for f in sem) == expected_mod, (
            f"verifier mismatch in {os.path.basename(path)}:\n"
            + "\n".join(f.render() for f in sem))


def test_clean_fixture_is_silent_in_both_engines():
    path = os.path.join(FIXDIR, "clean.py")
    assert lint_file(path, AXES, relto=FIXDIR) == []
    assert verify_module(_import_fixture(path), relto=FIXDIR) == []


def test_every_fired_rule_is_cataloged_and_coverage_is_broad():
    fired = set()
    for p in FIXTURES:
        fired |= {r for _, r in _expected_line_rules(p)}
        fired |= set(_expected_module_rules(p))
    assert fired <= set(RULES)
    # acceptance: >= 8 distinct rule ids demonstrated by fixtures
    assert len(fired) >= 8, sorted(fired)
    # both engines represented
    assert any(r.startswith("SGPL") for r in fired)
    assert any(r.startswith("SGPV") for r in fired)


def test_cross_module_closure_single_hop():
    """A traced function calling a helper imported from a sibling
    module marks the helper traced in its own module — but only when
    the files are linted as a set (lint_paths), and only along
    actually-called edges.  (The single-hop slice of the fixpoint
    closure; the two-hop test below proves the rest.)"""
    main = os.path.join(FIXDIR, "bad_crossmod.py")
    helper = os.path.join(FIXDIR, "crossmod_helper.py")

    # standalone, neither half fires: the import edge is invisible
    assert lint_file(main, AXES, relto=FIXDIR) == []
    assert lint_file(helper, AXES, relto=FIXDIR) == []

    findings = lint_paths([main, helper], axes=AXES, relto=FIXDIR)
    assert [(f.file, f.rule) for f in findings] == [
        ("crossmod_helper.py", "SGPL002")]
    # the finding lands on the helper's time.time() line, per its
    # EXPECT-CROSS marker
    marked = [i for i, l in enumerate(_read(helper).splitlines(), 1)
              if "EXPECT-CROSS" in l]
    assert [f.line for f in findings] == marked
    # quiet_report is only reached from an UNTRACED caller: its print()
    # must not fire (the closure is per-function, not per-module), and
    # Reporter.noisy_scale — a class-method namesake of the imported
    # helper — must not be seeded (a from-import binds only module
    # top-level names); the exact-match assertion above pins both


def test_two_hop_closure_reaches_the_leaf():
    """Tentpole: the full transitive fixpoint closure.  The leaf's host
    effect sits two import hops from the jitted entry point — the old
    one-hop seeding marked the middle module traced and stopped; the
    fixpoint keeps going and flags the leaf in its own module."""
    trio = [os.path.join(FIXDIR, n + ".py")
            for n in ("bad_twohop", "twohop_mid", "twohop_leaf")]

    # standalone, every file is clean (also pinned by the per-fixture
    # exact-match test, which parses no EXPECT markers in any of them)
    for p in trio:
        assert lint_file(p, AXES, relto=FIXDIR) == []

    findings = lint_paths(trio, axes=AXES, relto=FIXDIR)
    assert [(f.file, f.rule) for f in findings] == [
        ("twohop_leaf.py", "SGPL002")]
    marked = [i for i, l in enumerate(
        _read(trio[2]).splitlines(), 1) if "EXPECT-TWOHOP" in l]
    assert [f.line for f in findings] == marked

    # without the traced root, nothing propagates: mid + leaf alone are
    # silent (tracedness flows from roots, not from mere imports)
    assert lint_paths(trio[1:], axes=AXES, relto=FIXDIR) == []


def test_pr8_deadlock_shape_regression():
    """Satellite: SGPL012 fires on the reconstructed PR 8 deadlock loop
    (unsynchronized dispatch of compiled collectives) and stays silent
    on the serialized good twin — the exact fix tier-1 shipped."""
    bad = os.path.join(FIXDIR, "bad_dispatch_loop.py")
    ok = os.path.join(FIXDIR, "ok_dispatch_loop.py")
    bad_rules = [f.rule for f in lint_file(bad, AXES, relto=FIXDIR)]
    assert bad_rules == ["SGPL012"] * 3  # for-range, while, jit-bound
    assert lint_file(ok, AXES, relto=FIXDIR) == []


def test_dma_hygiene_fires_on_waitless_kernel():
    """Satellite: SGPL013 on the wait-less/conditional/mismatched-
    barrier kernels plus collective_id literal reuse; the good twin
    mirrors ops/gossip_kernel.py and is silent."""
    bad = os.path.join(FIXDIR, "bad_dma_kernel.py")
    ok = os.path.join(FIXDIR, "ok_dma_kernel.py")
    bad_rules = [f.rule for f in lint_file(bad, AXES, relto=FIXDIR)]
    assert bad_rules == ["SGPL013"] * 5
    assert lint_file(ok, AXES, relto=FIXDIR) == []


def test_metric_vocabulary_is_closed():
    """Satellite: SGPL014 — the exposition namespace is closed.  The
    bad fixture forks it three ways (raw literal, constant-routed
    literal, typo'd gauge); the registered good twin is silent; and the
    repo-level vocabulary discovery actually finds the registry's
    declarations (a regression here would let the rule pass
    vacuously, like the axis-vocabulary pin above)."""
    from stochastic_gradient_push_tpu.analysis.astlint import (
        collect_metric_vocabulary,
    )

    bad = os.path.join(FIXDIR, "bad_metrics.py")
    ok = os.path.join(FIXDIR, "ok_metrics.py")
    bad_rules = [f.rule for f in lint_file(bad, AXES, relto=FIXDIR)]
    assert bad_rules == ["SGPL014"] * 3  # literal, constant, typo
    assert lint_file(ok, AXES, relto=FIXDIR) == []

    vocab = collect_metric_vocabulary([PKG])
    assert {"sgp_step_time_seconds", "sgp_ps_mass_err",
            "sgp_alerts_total", "sgp_heartbeat_age_seconds"} <= vocab


# -- baseline ratchet ------------------------------------------------------


def test_baseline_writer_is_deterministic_and_content_addressed(tmp_path):
    f1 = Finding("b.py", 9, "SGPL002", "msg two")
    f2 = Finding("a.py", 3, "SGPL001", "msg one")
    p1, p2 = tmp_path / "bl1.json", tmp_path / "bl2.json"
    save_baseline(str(p1), [f1, f2])
    save_baseline(str(p2), [f2, f1, f1])  # order/dupes must not matter
    assert p1.read_bytes() == p2.read_bytes()
    import json
    data = json.loads(p1.read_text())
    assert [e["file"] for e in data["findings"]] == ["a.py", "b.py"]
    ids = [e["id"] for e in data["findings"]]
    assert len(set(ids)) == 2 and all(len(i) == 16 for i in ids)
    # round-trips through the loader
    assert load_baseline(str(p1)) == {f1.key(), f2.key()}


def test_stale_baseline_entries_ratchet():
    live = [Finding("a.py", 1, "SGPL001", "still fires")]
    baseline = {("a.py", "SGPL001", "still fires"),
                ("gone.py", "SGPL002", "was fixed")}
    assert stale_baseline_entries(live, baseline) == [
        ("gone.py", "SGPL002", "was fixed")]
    assert stale_baseline_entries(live, {live[0].key()}) == []


# -- lint cache ------------------------------------------------------------


def test_lint_cache_roundtrip_and_invalidation(tmp_path):
    from stochastic_gradient_push_tpu.analysis.cache import LintCache

    src = tmp_path / "mod.py"
    src.write_text(
        "import time\nimport jax\n\n\n"
        "@jax.jit\ndef step(x):\n    t = time.time()\n    return x + t\n")
    cache_path = str(tmp_path / "cache.json")

    cache = LintCache(cache_path, enabled=True)
    first = lint_paths([str(src)], axes=AXES, relto=str(tmp_path),
                       cache=cache)
    assert [f.rule for f in first] == ["SGPL002"]
    assert os.path.exists(cache_path)

    # warm run: same findings from the cache (interface + engine 1)
    warm = LintCache(cache_path, enabled=True)
    second = lint_paths([str(src)], axes=AXES, relto=str(tmp_path),
                        cache=warm)
    assert second == first

    # content change invalidates: the fixed file lints clean
    src.write_text(
        "import jax\n\n\n@jax.jit\ndef step(x):\n    return x + 1\n")
    third = lint_paths([str(src)], axes=AXES, relto=str(tmp_path),
                       cache=LintCache(cache_path, enabled=True))
    assert third == []

    # a corrupt cache file is discarded, never fatal
    with open(cache_path, "w") as f:
        f.write("{not json")
    fourth = lint_paths([str(src)], axes=AXES, relto=str(tmp_path),
                        cache=LintCache(cache_path, enabled=True))
    assert fourth == []


# -- generated docs --------------------------------------------------------


def test_rules_markdown_is_fresh():
    """docs/sgplint_rules.md is generated from the catalog; a rule edit
    without regenerating the doc fails here (regenerate with
    `python scripts/sgplint.py --rules-md docs/sgplint_rules.md`)."""
    doc = os.path.join(REPO, "docs", "sgplint_rules.md")
    assert os.path.exists(doc)
    assert _read(doc) == render_rules_markdown() + "\n"
    # every rule id appears in the doc
    text = _read(doc)
    assert all(rid in text for rid in RULES)


def test_rule_catalog_has_severities_and_new_families():
    for rid, rule in RULES.items():
        assert rule.severity in ("error", "warning"), rid
        assert rule.summary and rule.hint, rid
    assert {"SGPL011", "SGPL012", "SGPL013"} <= set(RULES)
    # tuple-compat: older call sites index the hint
    assert RULES["SGPL001"][1] == RULES["SGPL001"].hint


def test_suppression_comment_is_honored():
    # the tagged_ok handler in bad_except.py carries a disable tag and
    # must NOT appear among findings (already covered by the exact-match
    # test; this pins the mechanism explicitly)
    path = os.path.join(FIXDIR, "bad_except.py")
    lines = {f.line for f in lint_file(path, AXES, relto=FIXDIR)}
    src = _read(path).splitlines()
    tagged = [i for i, l in enumerate(src, 1) if "sgplint: disable" in l]
    assert tagged and not (lines & set(tagged))


# -- CLI -------------------------------------------------------------------


def test_cli_files_mode_and_rule_catalog(tmp_path, capsys):
    from stochastic_gradient_push_tpu.analysis.cli import main

    bad = tmp_path / "staged_bad.py"
    bad.write_text(
        "import time\nimport jax\n\n"
        "@jax.jit\ndef step(x):\n    return x * time.time()\n")
    assert main(["--files", str(bad)]) == 1
    out = capsys.readouterr().out
    assert "SGPL002" in out
    assert main(["--rules"]) == 0


def test_cli_files_mode_skips_fixture_files(capsys):
    # staged deliberately-bad fixtures (this very suite's test data) must
    # not fail the pre-commit hook — the full gate excludes fixtures/ and
    # --files honors the same policy
    from stochastic_gradient_push_tpu.analysis.cli import main

    assert main(["--files", os.path.join(FIXDIR, "clean.py"),
                 os.path.join(FIXDIR, "bad_axis.py")]) == 0
    assert "SGPL" not in capsys.readouterr().out
