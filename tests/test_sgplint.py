"""sgplint: both engines run in tier-1 on CPU.

* the repo itself must be clean against the checked-in baseline (empty:
  no grandfathered semantic findings, no unsuppressed lint findings);
* every rule id fires exactly where its known-bad fixture says
  (``# EXPECT: RULE`` line comments / ``# EXPECT-MODULE:`` headers) and
  nowhere in the known-clean fixture;
* the spectral-gap report covers the full topology grid with strictly
  positive gaps.
"""

import glob
import importlib.util
import os
import re

import pytest

from stochastic_gradient_push_tpu.analysis import (
    RULES,
    lint_file,
    lint_paths,
    load_baseline,
    verify_module,
    verify_package,
)
from stochastic_gradient_push_tpu.analysis.astlint import (
    collect_axis_vocabulary,
)
from stochastic_gradient_push_tpu.analysis.findings import (
    partition_against_baseline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "stochastic_gradient_push_tpu")
FIXDIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "fixtures", "sgplint")
BASELINE = os.path.join(REPO, "sgplint.baseline.json")

AXES = collect_axis_vocabulary([PKG])

_EXPECT_LINE = re.compile(r"#\s*EXPECT:\s*([A-Z0-9, ]+)")
_EXPECT_MODULE = re.compile(r"#\s*EXPECT-MODULE:\s*([A-Z0-9, ]+)")

FIXTURES = sorted(glob.glob(os.path.join(FIXDIR, "*.py")))


def _read(path):
    with open(path) as f:
        return f.read()


def _expected_line_rules(path):
    out = set()
    for i, line in enumerate(_read(path).splitlines(), start=1):
        m = _EXPECT_LINE.search(line)
        if m and "EXPECT-MODULE" not in line:
            for rule in m.group(1).split(","):
                out.add((i, rule.strip()))
    return out


def _expected_module_rules(path):
    m = _EXPECT_MODULE.search(_read(path))
    if not m:
        return []
    return sorted(r.strip() for r in m.group(1).split(","))


def _import_fixture(path):
    name = "sgplint_fixture_" + os.path.basename(path)[:-3]
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- the repo gate ---------------------------------------------------------


def test_mesh_axis_vocabulary_is_discovered():
    # the axes every engine-1 rule keys on; a regression here would let
    # SGPL001 pass vacuously
    assert {"gossip", "node", "local", "seq", "tp", "ep",
            "pipe"} <= AXES


def test_repo_ast_lint_clean_vs_baseline():
    findings = lint_paths([PKG], relto=REPO)
    new, _ = partition_against_baseline(findings, load_baseline(BASELINE))
    assert new == [], "\n".join(f.render() for f in new)


def test_repo_schedule_verifier_clean_with_empty_baseline():
    findings, gaps = verify_package(relto=REPO)
    # acceptance: zero grandfathered semantic findings, ever
    assert findings == [], "\n".join(f.render() for f in findings)
    assert len(gaps) > 300  # the full topology x world x ppi x mixing grid
    assert all(g.gap > 0 for g in gaps)


def test_spectral_gap_report_flags_slow_ring():
    # documents the ROADMAP open item: the static ring's gap collapses
    # quadratically with world size while exponential graphs stay flat
    _, gaps = verify_package(world_sizes=(64,), peer_counts=(1,))
    by_topo = {g.topology: g.gap for g in gaps if g.mixing == "uniform"}
    assert by_topo["RingGraph"] < 0.01
    assert by_topo["DynamicDirectedExponentialGraph"] > 0.05


# -- fixture suite ---------------------------------------------------------


@pytest.mark.parametrize(
    "path", FIXTURES, ids=[os.path.basename(p)[:-3] for p in FIXTURES])
def test_fixture_rules_fire_exactly_where_expected(path):
    expected = _expected_line_rules(path)
    got = {(f.line, f.rule)
           for f in lint_file(path, AXES, relto=FIXDIR)}
    assert got == expected, (
        f"AST engine mismatch in {os.path.basename(path)}:\n"
        f"  unexpected: {sorted(got - expected)}\n"
        f"  missing:    {sorted(expected - got)}")

    expected_mod = _expected_module_rules(path)
    has_material = bool(re.search(r"^SGPLINT_", _read(path), re.M))
    if expected_mod or has_material:
        mod = _import_fixture(path)
        sem = verify_module(mod, relto=FIXDIR)
        assert sorted(f.rule for f in sem) == expected_mod, (
            f"verifier mismatch in {os.path.basename(path)}:\n"
            + "\n".join(f.render() for f in sem))


def test_clean_fixture_is_silent_in_both_engines():
    path = os.path.join(FIXDIR, "clean.py")
    assert lint_file(path, AXES, relto=FIXDIR) == []
    assert verify_module(_import_fixture(path), relto=FIXDIR) == []


def test_every_fired_rule_is_cataloged_and_coverage_is_broad():
    fired = set()
    for p in FIXTURES:
        fired |= {r for _, r in _expected_line_rules(p)}
        fired |= set(_expected_module_rules(p))
    assert fired <= set(RULES)
    # acceptance: >= 8 distinct rule ids demonstrated by fixtures
    assert len(fired) >= 8, sorted(fired)
    # both engines represented
    assert any(r.startswith("SGPL") for r in fired)
    assert any(r.startswith("SGPV") for r in fired)


def test_cross_module_closure_one_import_hop():
    """Satellite: a traced function calling a helper imported from a
    sibling module marks the helper traced in its own module — but only
    when the files are linted as a set (lint_paths), and only along
    actually-called edges."""
    main = os.path.join(FIXDIR, "bad_crossmod.py")
    helper = os.path.join(FIXDIR, "crossmod_helper.py")

    # standalone, neither half fires: the import edge is invisible
    assert lint_file(main, AXES, relto=FIXDIR) == []
    assert lint_file(helper, AXES, relto=FIXDIR) == []

    findings = lint_paths([main, helper], axes=AXES, relto=FIXDIR)
    assert [(f.file, f.rule) for f in findings] == [
        ("crossmod_helper.py", "SGPL002")]
    # the finding lands on the helper's time.time() line, per its
    # EXPECT-CROSS marker
    marked = [i for i, l in enumerate(_read(helper).splitlines(), 1)
              if "EXPECT-CROSS" in l]
    assert [f.line for f in findings] == marked
    # quiet_report is only reached from an UNTRACED caller: its print()
    # must not fire (the closure is per-function, not per-module), and
    # Reporter.noisy_scale — a class-method namesake of the imported
    # helper — must not be seeded (a from-import binds only module
    # top-level names); the exact-match assertion above pins both


def test_suppression_comment_is_honored():
    # the tagged_ok handler in bad_except.py carries a disable tag and
    # must NOT appear among findings (already covered by the exact-match
    # test; this pins the mechanism explicitly)
    path = os.path.join(FIXDIR, "bad_except.py")
    lines = {f.line for f in lint_file(path, AXES, relto=FIXDIR)}
    src = _read(path).splitlines()
    tagged = [i for i, l in enumerate(src, 1) if "sgplint: disable" in l]
    assert tagged and not (lines & set(tagged))


# -- CLI -------------------------------------------------------------------


def test_cli_files_mode_and_rule_catalog(capsys):
    from stochastic_gradient_push_tpu.analysis.cli import main

    assert main(["--files", os.path.join(FIXDIR, "clean.py")]) == 0
    assert main(["--files", os.path.join(FIXDIR, "bad_axis.py")]) == 1
    out = capsys.readouterr().out
    assert "SGPL001" in out
    assert main(["--rules"]) == 0
