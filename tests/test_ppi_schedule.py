"""Peers-per-iteration schedule changes mid-training (SURVEY.md §7 hard
part #2): each ppi value is its own compiled step variant; switching must
preserve training state and keep the gossip math sound."""

import numpy as np
import pytest

from stochastic_gradient_push_tpu.data import (
    DistributedSampler,
    ShardedLoader,
    synthetic_classification,
)
from stochastic_gradient_push_tpu.models import TinyMLP
from stochastic_gradient_push_tpu.parallel import make_gossip_mesh
from stochastic_gradient_push_tpu.topology import (
    NPeerDynamicDirectedExponentialGraph,
)
from stochastic_gradient_push_tpu.train.loop import Trainer, TrainerConfig

WORLD = 8
BATCH = 4


@pytest.fixture(scope="module")
def mesh():
    return make_gossip_mesh(WORLD)


def test_training_across_ppi_switch(mesh, tmp_path):
    """Epoch 0 gossips with 1 peer, epoch 1+ with 2: the trainer must
    rebuild the compiled step at the boundary and keep converging."""
    images, labels = synthetic_classification(
        n=WORLD * BATCH * 4, num_classes=4, image_size=8, seed=0)
    cfg = TrainerConfig(
        graph_class=NPeerDynamicDirectedExponentialGraph,
        ppi_schedule={0: 1, 1: 2},
        lr=0.5, warmup=False, lr_schedule={},
        batch_size=BATCH, num_epochs=3, num_itr_ignore=0,
        checkpoint_dir=str(tmp_path), num_classes=4, verbose=False)
    trainer = Trainer(cfg, TinyMLP(num_classes=4), mesh,
                      sample_input_shape=(BATCH, 8, 8, 3))
    state = trainer.init_state()

    sampler = DistributedSampler(len(images), WORLD)
    loader = ShardedLoader(images, labels, BATCH, sampler)
    state, result = trainer.fit(state, loader, sampler, val_loader=loader)

    # two distinct compiled variants were built (ppi 1 and ppi 2)
    ppis = {key[0] for key in trainer._step_cache}
    assert ppis == {1, 2}
    assert result["best_prec1"] > 50.0
    # gossip state stays sound across the switch
    w = np.asarray(state.gossip.ps_weight)
    np.testing.assert_allclose(w, np.ones_like(w), atol=1e-3)


def test_ppi_2_schedule_has_more_edges(mesh):
    g1 = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1)
    g2 = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=2)
    assert g2.all_phase_permutations.shape[1] == 2
    assert g1.all_phase_permutations.shape[1] == 1
