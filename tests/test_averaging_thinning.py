"""Standalone averaging API and gossip_every communication thinning."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from stochastic_gradient_push_tpu.algorithms import sgp
from stochastic_gradient_push_tpu.parallel import (
    GOSSIP_AXIS,
    consensus_error,
    make_gossip_mesh,
    push_sum_average,
)
from stochastic_gradient_push_tpu.topology import (
    NPeerDynamicDirectedExponentialGraph,
    SelfWeightedMixing,
    build_schedule,
)

WORLD = 8


@pytest.fixture(scope="module")
def mesh():
    return make_gossip_mesh(WORLD)


def test_push_sum_average_reaches_exact_mean(mesh):
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    rng = np.random.default_rng(0)
    tree = {"a": rng.normal(size=(WORLD, 3, 2)).astype(np.float32),
            "b": rng.normal(size=(WORLD, 5)).astype(np.float32)}
    assert consensus_error(tree) > 0.5
    out = push_sum_average(tree, mesh, sched, rounds=50)
    assert consensus_error(out) < 1e-5
    for k in tree:
        want = tree[k].mean(axis=0)
        np.testing.assert_allclose(np.asarray(out[k])[0], want,
                                   rtol=1e-4, atol=1e-5)


def test_push_sum_average_irregular_mixing(mesh):
    alphas = 0.3 + 0.5 * np.arange(WORLD) / (WORLD - 1)
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1),
        SelfWeightedMixing(alpha=alphas))
    rng = np.random.default_rng(1)
    tree = rng.normal(size=(WORLD, 4)).astype(np.float32)
    out = push_sum_average(tree, mesh, sched, rounds=120)
    np.testing.assert_allclose(
        np.asarray(out), np.broadcast_to(tree.mean(0), tree.shape),
        rtol=1e-4, atol=1e-4)


def test_gossip_every_thinned_sgp_matches_manual(mesh):
    """gossip_every=2: odd steps are SGD-only, even steps gossip with the
    rotation advancing once per fired round."""
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    alg = sgp(sched, GOSSIP_AXIS, gossip_every=2)
    rng = np.random.default_rng(2)
    x0 = rng.normal(size=(WORLD, 4)).astype(np.float32)
    targets = rng.normal(size=(WORLD, 4)).astype(np.float32)
    lr = 0.1

    def step(params, gstate, target):
        params, gstate = alg.pre_step(params, gstate)
        z = alg.eval_params(params, gstate)
        g = jax.grad(lambda p: 0.5 * jnp.sum((p - target) ** 2))(z)
        return alg.post_step(params - lr * g, gstate)

    f = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(GOSSIP_AXIS),) * 3,
        out_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS))))

    params = x0.copy()
    gstate = jax.tree.map(
        lambda a: np.broadcast_to(np.asarray(a),
                                  (WORLD,) + np.shape(a)).copy(),
        alg.init(jnp.zeros((4,), jnp.float32)))

    sim = x0.astype(np.float64).copy()
    for t in range(8):
        params, gstate = jax.block_until_ready(f(params, gstate, targets))
        sim = sim - lr * (sim - targets)
        if t % 2 == 0:  # fired rounds: rotation t//2
            sim = sched.mixing_matrix(t // 2) @ sim
        np.testing.assert_allclose(np.asarray(params), sim,
                                   rtol=1e-5, atol=1e-5, err_msg=str(t))


def test_gossip_every_still_converges(mesh):
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    alg = sgp(sched, GOSSIP_AXIS, gossip_every=3)
    rng = np.random.default_rng(3)
    targets = rng.normal(size=(WORLD, 4)).astype(np.float32)
    lr = 0.02

    def step(params, gstate, target):
        params, gstate = alg.pre_step(params, gstate)
        z = alg.eval_params(params, gstate)
        g = jax.grad(lambda p: 0.5 * jnp.sum((p - target) ** 2))(z)
        return alg.post_step(params - lr * g, gstate)

    f = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(GOSSIP_AXIS),) * 3,
        out_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS))))
    params = rng.normal(size=(WORLD, 4)).astype(np.float32)
    gstate = jax.tree.map(
        lambda a: np.broadcast_to(np.asarray(a),
                                  (WORLD,) + np.shape(a)).copy(),
        alg.init(jnp.zeros((4,), jnp.float32)))
    for _ in range(600):
        params, gstate = jax.block_until_ready(f(params, gstate, targets))
    z = np.asarray(params) / np.asarray(gstate.ps_weight).reshape(WORLD, 1)
    np.testing.assert_allclose(z.mean(0), targets.mean(0), atol=5e-3)


def test_gossip_every_validation():
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    with pytest.raises(ValueError):
        sgp(sched, GOSSIP_AXIS, gossip_every=0)
    # thinning composes with the overlap phase schedule (non-firing
    # steps launch nothing; tests/test_overlap.py pins the behavior)
    alg = sgp(sched, GOSSIP_AXIS, overlap=True, gossip_every=2)
    assert alg.overlap and alg.gossip_every == 2


def test_bf16_comm_compression_bounded_error(mesh):
    """Gossip with bf16 wire payloads: consensus still reached, with error
    bounded by bf16 quantization, and mass approximately conserved."""
    import jax.numpy as jnp
    from stochastic_gradient_push_tpu.parallel import mix_push_sum

    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    rng = np.random.default_rng(4)
    x = rng.normal(size=(WORLD, 6)).astype(np.float32)
    w = np.ones((WORLD, 1), np.float32)
    mean = x.mean(axis=0)

    def step(phase, xs, ws):
        return mix_push_sum(xs, ws, phase, sched, GOSSIP_AXIS,
                            comm_dtype=jnp.bfloat16)

    f = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(GOSSIP_AXIS), P(GOSSIP_AXIS)),
        out_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS))))
    for phase in range(60):
        x, w = map(np.asarray, f(jnp.int32(phase), x, w))

    z = x / w
    # consensus within bf16 quantization noise (~3e-3 relative)
    np.testing.assert_allclose(z, np.broadcast_to(mean, z.shape),
                               rtol=0, atol=2e-2)
    spread = np.abs(z - z.mean(0)).max()
    assert spread < 1e-2, spread


def test_sgp_with_comm_compression_trains(mesh):
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    import jax.numpy as jnp
    alg = sgp(sched, GOSSIP_AXIS, comm_dtype=jnp.bfloat16)
    rng = np.random.default_rng(5)
    targets = rng.normal(size=(WORLD, 4)).astype(np.float32)
    lr = 0.05

    def step(params, gstate, target):
        params, gstate = alg.pre_step(params, gstate)
        z = alg.eval_params(params, gstate)
        g = jax.grad(lambda p: 0.5 * jnp.sum((p - target) ** 2))(z)
        return alg.post_step(params - lr * g, gstate)

    f = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(GOSSIP_AXIS),) * 3,
        out_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS))))
    params = rng.normal(size=(WORLD, 4)).astype(np.float32)
    gstate = jax.tree.map(
        lambda a: np.broadcast_to(np.asarray(a),
                                  (WORLD,) + np.shape(a)).copy(),
        alg.init(jnp.zeros((4,), jnp.float32)))
    for _ in range(400):
        params, gstate = jax.block_until_ready(f(params, gstate, targets))
    z = np.asarray(params) / np.asarray(gstate.ps_weight).reshape(WORLD, 1)
    np.testing.assert_allclose(z.mean(0), targets.mean(0), atol=2e-2)


# -- periodic global averaging (global_avg_every, planner recovery) ----------

def _stacked_init(alg, dim=4):
    return jax.tree.map(
        lambda a: np.broadcast_to(np.asarray(a),
                                  (WORLD,) + np.shape(a)).copy(),
        alg.init(jnp.zeros((dim,), jnp.float32)))


def _sgd_gossip_step(alg, mesh, lr):
    def step(params, gstate, target):
        params, gstate = alg.pre_step(params, gstate)
        z = alg.eval_params(params, gstate)
        g = jax.grad(lambda p: 0.5 * jnp.sum((p - target) ** 2))(z)
        return alg.post_step(params - lr * g, gstate)

    return jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(GOSSIP_AXIS),) * 3,
        out_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS))))


def test_global_avg_every_matches_manual_sim(mesh):
    """Ring gossip + SGD with an exact global average every 3rd step
    matches the numpy reference trajectory exactly: gossip rounds mix,
    and on fire steps every rank snaps to the cross-rank mean."""
    from stochastic_gradient_push_tpu.topology import RingGraph

    sched = build_schedule(RingGraph(WORLD, peers_per_itr=1))
    k = 3
    alg = sgp(sched, GOSSIP_AXIS, global_avg_every=k)
    rng = np.random.default_rng(6)
    x0 = rng.normal(size=(WORLD, 4)).astype(np.float32)
    targets = rng.normal(size=(WORLD, 4)).astype(np.float32)
    lr = 0.1
    f = _sgd_gossip_step(alg, mesh, lr)

    params, gstate = x0.copy(), _stacked_init(alg)
    sim = x0.astype(np.float64).copy()
    for t in range(1, 9):
        params, gstate = jax.block_until_ready(f(params, gstate, targets))
        sim = sched.mixing_matrix(t - 1) @ (sim - lr * (sim - targets))
        if t % k == 0:
            sim = np.broadcast_to(sim.mean(0), sim.shape).copy()
        np.testing.assert_allclose(np.asarray(params), sim,
                                   rtol=1e-5, atol=1e-5, err_msg=str(t))
        # ps-weight is 1 after an average (regular mixing keeps it 1)
        np.testing.assert_allclose(
            np.asarray(gstate.ps_weight).reshape(WORLD), np.ones(WORLD),
            atol=1e-6)


def test_global_avg_exact_consensus_under_irregular_mixing(mesh):
    """With per-rank irregular mixing the push-sum weight deviates from 1;
    the every-k average must still land every rank exactly on the true
    mean (Σ numerators / Σ weights) and reset the weight to 1."""
    from stochastic_gradient_push_tpu.topology import SelfWeightedMixing

    alphas = 0.2 + 0.6 * np.arange(WORLD) / (WORLD - 1)
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1),
        SelfWeightedMixing(alpha=alphas))
    k = 4
    alg = sgp(sched, GOSSIP_AXIS, global_avg_every=k)
    rng = np.random.default_rng(7)
    x0 = rng.normal(size=(WORLD, 4)).astype(np.float32)
    f = _sgd_gossip_step(alg, mesh, lr=0.0)  # pure averaging dynamics

    params, gstate = x0.copy(), _stacked_init(alg)
    for _ in range(k):
        params, gstate = jax.block_until_ready(
            f(params, gstate, jnp.zeros_like(params)))
    # mass conservation makes the consensus value the exact initial mean
    np.testing.assert_allclose(
        np.asarray(params),
        np.broadcast_to(x0.mean(0), x0.shape), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(gstate.ps_weight).reshape(WORLD), np.ones(WORLD),
        atol=1e-6)


def test_global_avg_composes_with_gossip_thinning(mesh):
    """gossip_every=2 + global_avg_every=3: thinned rounds fire on their
    own cadence, the exact average on its own; the numpy sim agrees."""
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    alg = sgp(sched, GOSSIP_AXIS, gossip_every=2, global_avg_every=3)
    rng = np.random.default_rng(8)
    x0 = rng.normal(size=(WORLD, 4)).astype(np.float32)
    targets = rng.normal(size=(WORLD, 4)).astype(np.float32)
    lr = 0.1
    f = _sgd_gossip_step(alg, mesh, lr)

    params, gstate = x0.copy(), _stacked_init(alg)
    sim = x0.astype(np.float64).copy()
    for t in range(12):
        params, gstate = jax.block_until_ready(f(params, gstate, targets))
        sim = sim - lr * (sim - targets)
        if t % 2 == 0:          # thinned gossip fires, rotation t//2
            sim = sched.mixing_matrix(t // 2) @ sim
        if (t + 1) % 3 == 0:    # exact average fires after the round
            sim = np.broadcast_to(sim.mean(0), sim.shape).copy()
        np.testing.assert_allclose(np.asarray(params), sim,
                                   rtol=1e-5, atol=1e-5, err_msg=str(t))


def test_global_avg_validation():
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    with pytest.raises(ValueError, match="global_avg_every"):
        sgp(sched, GOSSIP_AXIS, global_avg_every=-1)
    # periodic exact averaging composes with overlap: the fired average
    # folds + drains the in-flight FIFO (pinned in tests/test_overlap.py)
    alg = sgp(sched, GOSSIP_AXIS, overlap=True, global_avg_every=2)
    assert alg.overlap and alg.global_avg_every == 2
