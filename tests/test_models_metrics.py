"""Model structure, loss/accuracy parity, LR schedule, Meter, SGD parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stochastic_gradient_push_tpu.models import TinyCNN, resnet50
from stochastic_gradient_push_tpu.train import (
    LRSchedule,
    accuracy_topk,
    kl_div_loss,
    one_hot,
    ppi_at_epoch,
    sgd,
)
from stochastic_gradient_push_tpu.utils import Meter


def test_resnet50_structure_and_init():
    model = resnet50(num_classes=1000)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 224, 224, 3)), train=True))
    params = variables["params"]
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(params))
    # torchvision resnet50 has 25.557M params
    assert abs(n_params - 25_557_032) / 25_557_032 < 0.01, n_params
    assert "batch_stats" in variables


@pytest.mark.slow
def test_resnet_zero_gamma_and_fc_init():
    model = resnet50(num_classes=10)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 32, 32, 3)), train=True)
    params = variables["params"]
    # every bottleneck's final norm scale starts at zero
    zero_scales = [
        k2 for k, v in params.items() if k.startswith("Bottleneck")
        for k2, v2 in v.items()
        if k2 == "BatchNorm_2" and float(np.abs(v2["scale"]).max()) == 0.0]
    assert len(zero_scales) == 16  # 3+4+6+3 blocks
    # fc ~ N(0, 0.01)
    fc = np.asarray(params["fc"]["kernel"])
    assert 0.005 < fc.std() < 0.02
    # forward pass at init: residual blocks are identity-like, logits finite
    out = model.apply(variables, jnp.ones((2, 32, 32, 3)), train=False)
    assert np.all(np.isfinite(np.asarray(out)))


def test_kl_div_loss_equals_cross_entropy_for_one_hot():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(8, 10)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, size=(8,)))
    got = kl_div_loss(logits, one_hot(labels, 10))
    # cross entropy
    logp = jax.nn.log_softmax(logits)
    want = -jnp.mean(logp[jnp.arange(8), labels])
    np.testing.assert_allclose(float(got), float(want), rtol=1e-6)


def test_kl_div_loss_soft_targets_matches_torch_formula():
    rng = np.random.default_rng(1)
    logits = rng.normal(size=(4, 6)).astype(np.float32)
    target = rng.dirichlet(np.ones(6), size=4).astype(np.float32)
    got = float(kl_div_loss(jnp.asarray(logits), jnp.asarray(target)))
    # torch KLDivLoss(batchmean): sum(t * (log t - log q)) / N
    logq = np.asarray(jax.nn.log_softmax(jnp.asarray(logits)))
    want = float(np.sum(target * (np.log(target) - logq)) / 4)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_accuracy_topk():
    logits = jnp.asarray([[0.1, 0.9, 0.0, 0.0],
                          [0.9, 0.1, 0.0, 0.0],
                          [0.0, 0.1, 0.2, 0.7],
                          [0.5, 0.4, 0.05, 0.05]], jnp.float32)
    labels = jnp.asarray([1, 3, 2, 0])
    top1, top2 = accuracy_topk(logits, labels, topk=(1, 2))
    assert float(top1) == 50.0   # rows 0 and 3 correct
    assert float(top2) == 75.0   # row 2 recovered at k=2; row 1 still missed


def test_lr_schedule_matches_reference_rule():
    # 32 ranks x 256-per-node batch = the paper's flagship config
    s = LRSchedule(ref_lr=0.1, batch_size=256, world_size=32,
                   decay_schedule={30: 0.1, 60: 0.1, 80: 0.1}, warmup=True)
    target = 0.1 * 256 * 32 / 256
    itr_per_epoch = 156
    # warmup: epoch 0 itr 0 → ref_lr + (target-ref)/(5*ipe)
    lr0 = float(s(0, 0, itr_per_epoch))
    np.testing.assert_allclose(
        lr0, 0.1 + (target - 0.1) / (5 * itr_per_epoch), rtol=1e-5)
    # end of warmup → target
    np.testing.assert_allclose(float(s(4, 155, itr_per_epoch)), target,
                               rtol=1e-3)
    # piecewise decays
    np.testing.assert_allclose(float(s(30, 0, itr_per_epoch)), target * 0.1,
                               rtol=1e-5)
    np.testing.assert_allclose(float(s(60, 0, itr_per_epoch)), target * 0.01,
                               rtol=1e-5)
    np.testing.assert_allclose(float(s(85, 0, itr_per_epoch)), target * 1e-3,
                               rtol=1e-5)


def test_lr_schedule_no_warmup_small_world():
    # target <= ref_lr → warmup clamps to target (gossip_sgd.py:519-521)
    s = LRSchedule(ref_lr=0.1, batch_size=32, world_size=1, warmup=True)
    assert float(s(0, 0, 100)) == pytest.approx(0.1 * 32 / 256)


def test_ppi_schedule_lookup():
    sched = {0: 1, 10: 2, 50: 4}
    assert ppi_at_epoch(sched, 0) == 1
    assert ppi_at_epoch(sched, 9) == 1
    assert ppi_at_epoch(sched, 10) == 2
    assert ppi_at_epoch(sched, 49) == 2
    assert ppi_at_epoch(sched, 89) == 4
    with pytest.raises(ValueError):
        ppi_at_epoch({5: 2}, 0)


def test_meter_stats_and_format():
    m = Meter(ptag="Time")
    for v in (1.0, 2.0, 3.0):
        m.update(v)
    assert m.avg == pytest.approx(2.0)
    assert m.std == pytest.approx(1.0)
    assert str(m) == "3.000,2.000,1.000"
    m2 = Meter(init_dict=m.state_dict())
    assert m2.avg == pytest.approx(2.0)
    stateful = Meter(ptag="Gossip", stateful=True, csv_format=False)
    stateful.update(1.0)
    stateful.update(3.0)
    assert "Gossip: 3.000 (2.000 +- 1.000)" == str(stateful)


def test_sgd_matches_torch_semantics():
    """Verify against torch.optim.SGD on a tiny problem."""
    import torch

    w0 = np.array([1.0, -2.0, 3.0], dtype=np.float32)
    grads_seq = [np.array([0.5, -1.0, 0.25], dtype=np.float32),
                 np.array([-0.3, 0.2, 0.8], dtype=np.float32),
                 np.array([0.1, 0.1, -0.1], dtype=np.float32)]
    lr, mu, wd = 0.1, 0.9, 1e-2

    for nesterov in (False, True):
        tw = torch.tensor(w0.copy(), requires_grad=True)
        topt = torch.optim.SGD([tw], lr=lr, momentum=mu, weight_decay=wd,
                               nesterov=nesterov)
        tx = sgd(momentum=mu, weight_decay=wd, nesterov=nesterov)
        jw = jnp.asarray(w0)
        jstate = tx.init(jw)
        for g in grads_seq:
            topt.zero_grad()
            tw.grad = torch.tensor(g)
            topt.step()
            updates, jstate = tx.update(jnp.asarray(g), jstate, jw)
            jw = jw - lr * updates
        np.testing.assert_allclose(np.asarray(jw), tw.detach().numpy(),
                                   rtol=1e-5, atol=1e-6)


def test_tiny_cnn_forward():
    model = TinyCNN(num_classes=10)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, 16, 16, 3)), train=True)
    out, mutated = model.apply(variables, jnp.ones((2, 16, 16, 3)),
                               train=True, mutable=["batch_stats"])
    assert out.shape == (2, 10)
    assert "batch_stats" in mutated


def test_s2d_stem_is_equivalent():
    """space-to-depth stem (MLPerf TPU trick): transforming the standard
    7x7/2 stem kernel with s2d_stem_kernel must reproduce the standard
    model's logits exactly (fp32 rounding)."""
    from stochastic_gradient_push_tpu.models.resnet import (
        resnet18, s2d_stem_kernel, space_to_depth)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 64, 64, 3)), jnp.float32)
    std = resnet18(num_classes=10)
    s2d = resnet18(num_classes=10, stem_s2d=True)
    vs = std.init(jax.random.PRNGKey(0), x, train=False)
    grafted = dict(vs["params"])
    grafted["conv_init"] = {
        "kernel": s2d_stem_kernel(vs["params"]["conv_init"]["kernel"])}
    out_std = std.apply(vs, x, train=False)
    out_s2d = s2d.apply({"params": grafted,
                         "batch_stats": vs["batch_stats"]}, x, train=False)
    np.testing.assert_allclose(np.asarray(out_s2d), np.asarray(out_std),
                               atol=2e-6)
    # the packing helper itself round-trips pixels
    blocks = space_to_depth(x, 2)
    assert blocks.shape == (2, 32, 32, 12)
    np.testing.assert_array_equal(
        np.asarray(blocks[0, 0, 0, :3]), np.asarray(x[0, 0, 0]))
    # init distribution: the s2d kernel is a transformed 7x7 draw, so its
    # nonzero mass equals a 7x7 kernel's (one zero-padded row/col)
    vd = s2d.init(jax.random.PRNGKey(1), x, train=False)
    kd = np.asarray(vd["params"]["conv_init"]["kernel"])
    assert kd.shape == (4, 4, 12, 64)
    assert np.count_nonzero(kd) == 7 * 7 * 3 * 64


def test_probe_batch_norm_variants():
    """ProbeBatchNorm (models/resnet.py): the MFU-experiment norm
    variants must keep nn.BatchNorm's exact variable structure and, with
    float32 stats, its exact math — so bench variants differ ONLY in the
    lever under test (docs/MFU_ANALYSIS.md)."""
    import flax.linen as nn

    from stochastic_gradient_push_tpu.models.resnet import ProbeBatchNorm

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 6, 6, 8)), jnp.float32)

    ref = nn.BatchNorm(use_running_average=False, momentum=0.9,
                       epsilon=1e-5)
    probe = ProbeBatchNorm(use_running_average=False, momentum=0.9,
                           epsilon=1e-5)
    v_ref = ref.init(jax.random.PRNGKey(0), x)
    v_probe = probe.init(jax.random.PRNGKey(0), x)
    assert jax.tree.structure(v_ref) == jax.tree.structure(v_probe)

    y_ref, m_ref = ref.apply(v_ref, x, mutable=["batch_stats"])
    y_probe, m_probe = probe.apply(v_probe, x, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(y_probe), np.asarray(y_ref),
                               atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(m_probe["batch_stats"]["mean"]),
        np.asarray(m_ref["batch_stats"]["mean"]), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(m_probe["batch_stats"]["var"]),
        np.asarray(m_ref["batch_stats"]["var"]), atol=1e-5)

    # bf16 stats: same function within bf16 tolerance
    p16 = ProbeBatchNorm(use_running_average=False, momentum=0.9,
                         epsilon=1e-5, dtype=jnp.bfloat16,
                         stats_dtype=jnp.bfloat16)
    y16, _ = p16.apply(v_probe, x, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(y16, np.float32),
                               np.asarray(y_ref), atol=0.1)

    # folded: running stats used in train mode, collection still mutated
    # (structure preserved for the train step) with values unchanged
    frozen = ProbeBatchNorm(use_running_average=False, frozen=True)
    y_frozen, m_frozen = frozen.apply(v_probe, x, mutable=["batch_stats"])
    assert jax.tree.structure(m_frozen) == jax.tree.structure(m_ref)
    np.testing.assert_array_equal(
        np.asarray(m_frozen["batch_stats"]["mean"]),
        np.asarray(v_probe["batch_stats"]["mean"]))
    # running stats at init are mean 0 / var 1 -> y = scale*x/sqrt(1+eps)+bias
    np.testing.assert_allclose(
        np.asarray(y_frozen), np.asarray(x) / np.sqrt(1 + 1e-5), atol=1e-6)


def test_resnet_norm_variant_state_structure():
    """All three norm variants build the same train-state *shapes* (same
    parameters, same batch_stats, mutated every step), so BENCH_NORM
    sweeps the lever without touching any other plumbing.  Flax's
    auto-names embed the module class (BatchNorm_0 vs ProbeBatchNorm_0),
    so checkpoints do not interchange across the flag — same caveat as
    stem_s2d, and irrelevant to the bench, which builds its own state."""
    from stochastic_gradient_push_tpu.models.resnet import resnet18

    x = jnp.zeros((2, 16, 16, 3), jnp.float32)
    shapes = {}
    for nv in ("bn", "bn16", "folded"):
        model = resnet18(num_classes=10, small_images=True,
                         norm_variant=nv)
        v = model.init(jax.random.PRNGKey(0), x, train=True)
        out, mutated = model.apply(v, x, train=True,
                                   mutable=["batch_stats"])
        assert np.all(np.isfinite(np.asarray(out))), nv
        assert "batch_stats" in mutated, nv
        shapes[nv] = {
            coll: sorted(jnp.shape(l) for l in jax.tree.leaves(v[coll]))
            for coll in ("params", "batch_stats")}
        shapes[nv]["mutated"] = sorted(
            jnp.shape(l) for l in jax.tree.leaves(mutated["batch_stats"]))
    assert shapes["bn"] == shapes["bn16"] == shapes["folded"]
