"""supervise/coordinator.py: two-level fleet supervision.

Pins the fleet contracts: (1) the rendezvous barrier — every expected
host joins within the deadline or is excluded and the barrier RE-RUNS
at the smaller membership (never a hang); (2) the two-phase commit —
survivors reshard their disjoint ``out_rank``/``out_rows`` shards
concurrently, ack, and relaunch only on ``go``, so exactly one
coordinated cycle happens per cause; (3) the host-side supervisor fleet
mode — faults are reported, not locally acted on, and the relaunch
adopts the coordinator's assignment; (4) the host-sim trainer's
checkpoint/drain/resume contracts that the fleet chaos selftest rides
on; (5) the reshard tmp-file hygiene and concurrent-writer composition
the coordinated reshard depends on.  The full kill-a-slice chaos e2e
runs as a slow test (and as the ``scripts/fleet.py --selftest`` CI
gate).
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import flax.serialization
import numpy as np
import pytest

from stochastic_gradient_push_tpu.supervise import (
    EXCLUDED_EXIT_CODE,
    Coordinator,
    FleetMember,
    SupervisorPolicy,
    TornCheckpointError,
    consensus_mean,
    gc_stale_tmp,
    host_dir,
    load_world_checkpoint,
    maybe_cross_world_reshard,
    reshard_checkpoints,
)
from stochastic_gradient_push_tpu.supervise.supervisor import (
    ChildSpec,
    Supervisor,
)
from stochastic_gradient_push_tpu.telemetry import (
    COORDINATOR_EVENTS_FILE,
    SUPERVISOR_EVENTS_FILE,
    JsonlSink,
    TelemetryRegistry,
)
from stochastic_gradient_push_tpu.utils.checkpoint import (
    REQUEUE_EXIT_CODE,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _events(path):
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _host_registry(fleet_dir, host):
    d = host_dir(fleet_dir, host)
    os.makedirs(d, exist_ok=True)
    return TelemetryRegistry(rank=0, sinks=[
        JsonlSink(os.path.join(d, SUPERVISOR_EVENTS_FILE))])


# -- protocol plumbing -------------------------------------------------------


class TestFleetMember:
    def test_emit_requires_bind(self, tmp_path):
        m = FleetMember(str(tmp_path), 0, 2)
        with pytest.raises(RuntimeError):
            m.hello(world=4, generation=0, child_pid=1)

    def test_emits_land_in_host_stream_and_polls_broadcast(self, tmp_path):
        d = str(tmp_path)
        m = FleetMember(d, 1, 2, alive_interval_s=0.0)
        reg = _host_registry(d, 1)
        m.bind(reg)
        m.hello(world=4, generation=0, child_pid=42)
        m.fault(reason="boom", action="restart")
        m.join(3)
        evs = _events(os.path.join(host_dir(d, 1),
                                   SUPERVISOR_EVENTS_FILE))
        assert [e["kind"] for e in evs] == ["rendezvous"] * 3
        assert [e["data"]["phase"] for e in evs] == [
            "hello", "fault", "join"]
        assert all(e["data"]["host"] == 1 for e in evs)
        # broadcast direction: a coordinator write shows up in poll()
        coord = TelemetryRegistry(rank=0, sinks=[JsonlSink(
            os.path.join(d, COORDINATOR_EVENTS_FILE))])
        coord.emit("rendezvous", {"phase": "call", "round": 1,
                                  "hosts": [1]})
        coord.emit("run_meta", {"noise": True})  # filtered out
        polled = m.poll()
        assert len(polled) == 1
        assert polled[0]["data"]["phase"] == "call"

    def test_rows_validated(self, tmp_path):
        with pytest.raises(ValueError):
            FleetMember(str(tmp_path), 0, 0)


# -- coordinator rendezvous / cycle ------------------------------------------


class _FakeHost(threading.Thread):
    """A scripted host supervisor: answers calls, acks assigns."""

    def __init__(self, fleet_dir, host, rows, *, joins=True,
                 ack_ok=False):
        super().__init__(daemon=True)
        self.member = FleetMember(fleet_dir, host, rows,
                                  alive_interval_s=0.0)
        self.member.bind(_host_registry(fleet_dir, host))
        self.joins = joins
        self.ack_ok = ack_ok
        self.saw_go = threading.Event()
        self.stop = threading.Event()

    def run(self):
        while not self.stop.is_set():
            for ev in self.member.poll():
                data = ev.get("data") or {}
                phase = data.get("phase")
                if ev["kind"] == "rendezvous" and phase == "call" \
                        and self.joins:
                    self.member.join(data["round"])
                elif ev["kind"] == "fleet" and phase == "assign":
                    shard = (data.get("shards") or {}).get(
                        str(self.member.host))
                    if shard is not None:
                        self.member.ack(data["round"], ok=self.ack_ok,
                                        out_rank=shard["out_rank"],
                                        out_rows=shard["out_rows"])
                elif ev["kind"] == "fleet" and phase == "go":
                    self.saw_go.set()
            time.sleep(0.02)


def _coordinator(tmp_path, hosts, **kw):
    kw.setdefault("deadline_s", 0.8)
    kw.setdefault("ack_timeout_s", 5.0)
    kw.setdefault("poll_interval_s", 0.03)
    kw.setdefault("install_signal_handlers", False)
    return Coordinator(str(tmp_path), hosts, gossip=False, **kw)


class TestCoordinatorCycle:
    def test_all_join_one_round_one_cycle(self, tmp_path):
        fakes = [_FakeHost(str(tmp_path), h, 2) for h in (0, 1)]
        for f in fakes:
            f.start()
        coord = _coordinator(tmp_path, {0: 2, 1: 2})
        try:
            assert coord._cycle("test-cause") is None
            # the committed go reaches every survivor's broadcast tailer
            assert all(f.saw_go.wait(2) for f in fakes)
        finally:
            for f in fakes:
                f.stop.set()
                f.join(timeout=2)
        assert coord.world == 4 and coord.cycle == 1
        assert coord.generation == 1 and coord.excluded == []
        evs = _events(os.path.join(str(tmp_path),
                                   COORDINATOR_EVENTS_FILE))
        calls = [e for e in evs if e["kind"] == "rendezvous"
                 and e["data"]["phase"] == "call"]
        gos = [e for e in evs if e["kind"] == "fleet"
               and e["data"]["phase"] == "go"]
        assert len(calls) == 1 and len(gos) == 1

    def test_deadline_miss_excludes_and_reruns(self, tmp_path):
        # host 2 never joins: round 1 times out, host 2 is excluded,
        # and the rendezvous RE-RUNS at the smaller membership — the
        # acceptance criterion "re-rendezvous, not a hang"
        fakes = [_FakeHost(str(tmp_path), h, 2) for h in (0, 1)]
        fakes.append(_FakeHost(str(tmp_path), 2, 2, joins=False))
        for f in fakes:
            f.start()
        coord = _coordinator(tmp_path, {0: 2, 1: 2, 2: 2})
        try:
            assert coord._cycle("host-silence: host 2") is None
        finally:
            for f in fakes:
                f.stop.set()
                f.join(timeout=2)
        assert coord.excluded == [2]
        assert sorted(coord.live) == [0, 1] and coord.world == 4
        evs = _events(os.path.join(str(tmp_path),
                                   COORDINATOR_EVENTS_FILE))
        calls = [e["data"] for e in evs if e["kind"] == "rendezvous"
                 and e["data"]["phase"] == "call"]
        assert len(calls) == 2
        assert calls[0]["hosts"] == [0, 1, 2]
        assert calls[1]["hosts"] == [0, 1]
        assigns = [e["data"] for e in evs if e["kind"] == "fleet"
                   and e["data"]["phase"] == "assign"]
        assert len(assigns) == 1 and assigns[0]["excluded"] == [2]
        shards = assigns[0]["shards"]
        assert shards["0"] == {"out_rank": 0, "out_rows": 2,
                               "host_index": 0, "num_hosts": 2,
                               "rank_offset": 0}
        assert shards["1"]["out_rank"] == 1
        assert shards["1"]["rank_offset"] == 2

    def test_nobody_joins_gives_up(self, tmp_path):
        coord = _coordinator(tmp_path, {0: 2, 1: 2}, deadline_s=0.3)
        assert coord._cycle("test") == 1
        evs = _events(os.path.join(str(tmp_path),
                                   COORDINATOR_EVENTS_FILE))
        assert any(e["kind"] == "fleet"
                   and e["data"]["phase"] == "give-up" for e in evs)

    def test_min_hosts_floor_gives_up(self, tmp_path):
        fakes = [_FakeHost(str(tmp_path), 0, 2)]
        fakes[0].start()
        coord = _coordinator(tmp_path, {0: 2, 1: 2}, min_hosts=2,
                             deadline_s=0.4)
        try:
            assert coord._cycle("test") == 1
        finally:
            fakes[0].stop.set()
            fakes[0].join(timeout=2)

    def test_cycle_budget_spent_gives_up(self, tmp_path):
        coord = _coordinator(tmp_path, {0: 2}, max_cycles=0)
        assert coord._cycle("test") == 1

    def test_hosts_validated(self, tmp_path):
        with pytest.raises(ValueError):
            Coordinator(str(tmp_path), {})
        with pytest.raises(ValueError):
            Coordinator(str(tmp_path), {0: 0})

    def test_cli_host_rows_validation(self):
        import argparse

        from stochastic_gradient_push_tpu.supervise.fleetcli import (
            _parse_host_rows)

        def ns(**kw):
            base = {"hosts": None, "rows": None, "host_rows": None}
            base.update(kw)
            return argparse.Namespace(**base)

        assert _parse_host_rows(
            argparse.Namespace(hosts=2, rows=3, host_rows=None)) \
            == {0: 3, 1: 3}
        assert _parse_host_rows(ns(host_rows="2,4")) == {0: 2, 1: 4}
        with pytest.raises(ValueError, match="--hosts"):
            _parse_host_rows(ns())
        with pytest.raises(ValueError, match="--rows"):
            # --hosts without --rows must be a config error, not a
            # TypeError deep inside Coordinator.__init__
            _parse_host_rows(ns(hosts=4))
        with pytest.raises(ValueError, match=">= 1"):
            _parse_host_rows(ns(host_rows="2,0"))


# -- child argv rewriting ----------------------------------------------------


class TestChildSpecFleetArgv:
    def test_extra_flags_rewrite(self, tmp_path):
        spec = ChildSpec([sys.executable, "train.py",
                          "--world_size", "6",
                          "--num_processes", "3", "--process_id", "2",
                          "--trace_dir", str(tmp_path),
                          "--rows", "2", "--rank_offset", "4"])
        argv = spec.build_argv(4, None, resume=True,
                               extra={"--num_processes": 2,
                                      "--process_id": 1,
                                      "--rows": 2,
                                      "--rank_offset": 2})
        flat = " ".join(argv)
        assert "--world_size 4" in flat
        assert "--num_processes 2" in flat and "--process_id 1" in flat
        assert "--rank_offset 2" in flat
        assert flat.count("--num_processes") == 1  # old value stripped
        assert "--resume True" in flat


# -- supervisor fleet mode ---------------------------------------------------


FLEET_CHILD = textwrap.dedent("""
    import json, os, sys, time
    args = dict(zip(sys.argv[1::2], sys.argv[2::2]))
    td = args["--trace_dir"]
    mode_path = os.path.join(td, "mode")
    mode = open(mode_path).read() if os.path.exists(mode_path) else "done"
    with open(os.path.join(td, "events.jsonl"), "a") as f:
        f.write(json.dumps({"v": 1, "kind": "step_stats",
                            "t": time.time(), "rank": 0,
                            "severity": "info", "step": 1,
                            "data": {}}) + "\\n")
    if mode == "crash-once":
        os.remove(mode_path)
        sys.exit(1)
    sys.exit(0)
""")


class _FakeCoordinator(threading.Thread):
    """Scripted coordinator for a one-host fleet: on the host's fault
    report, run call → assign → go (or exclude the host)."""

    def __init__(self, fleet_dir, *, exclude=False, world=4):
        super().__init__(daemon=True)
        self.registry = TelemetryRegistry(rank=0, sinks=[JsonlSink(
            os.path.join(fleet_dir, COORDINATOR_EVENTS_FILE))])
        from stochastic_gradient_push_tpu.supervise import EventTailer

        self.tailer = EventTailer(os.path.join(
            host_dir(fleet_dir, 0), SUPERVISOR_EVENTS_FILE))
        self.exclude = exclude
        self.world = world
        self.acked = threading.Event()
        self.stop = threading.Event()

    def run(self):
        state = "watch"
        while not self.stop.is_set():
            for ev in self.tailer.poll():
                if ev.get("kind") != "rendezvous":
                    continue
                phase = (ev.get("data") or {}).get("phase")
                if phase == "fault" and state == "watch":
                    state = "called"
                    self.registry.emit("rendezvous", {
                        "phase": "call", "round": 1, "cause": "test",
                        "deadline_s": 5.0, "hosts": [0]})
                elif phase == "join" and state == "called":
                    state = "assigned"
                    shards = {} if self.exclude else {
                        "0": {"out_rank": 0, "out_rows": self.world,
                              "host_index": 0, "num_hosts": 1,
                              "rank_offset": 0}}
                    self.registry.emit("fleet", {
                        "phase": "assign", "round": 1, "cycle": 1,
                        "cause": "test", "world": self.world,
                        "prev_world": self.world, "plan": None,
                        "shards": shards,
                        "excluded": [0] if self.exclude else []})
                elif phase == "ack" and state == "assigned":
                    state = "done"
                    self.acked.set()
                    self.registry.emit("fleet", {
                        "phase": "go", "round": 1, "cycle": 1,
                        "world": self.world, "prev_world": self.world,
                        "generation": 1, "acks": {"0": None}})
            time.sleep(0.02)
        self.registry.close()


def _fleet_supervisor(tmp_path, mode, **fake_kw):
    d = str(tmp_path)
    hdir = host_dir(d, 0)
    os.makedirs(hdir, exist_ok=True)
    script = tmp_path / "fleet_child.py"
    script.write_text(FLEET_CHILD)
    (tmp_path / f"host0/mode").write_text(mode)
    spec = ChildSpec([sys.executable, str(script),
                      "--trace_dir", hdir,
                      "--checkpoint_dir", d,
                      "--world_size", "4"])
    member = FleetMember(d, 0, 4, alive_interval_s=0.1)
    sup = Supervisor(spec, SupervisorPolicy(world=4, max_restarts=0),
                     poll_interval_s=0.05, fleet=member,
                     fleet_timeout_s=10.0,
                     install_signal_handlers=False)
    fake = _FakeCoordinator(d, **fake_kw)
    fake.start()
    return sup, fake


class TestSupervisorFleetMode:
    def test_crash_reports_fault_and_relaunches_on_go(self, tmp_path):
        sup, fake = _fleet_supervisor(tmp_path, "crash-once")
        try:
            assert sup.run() == 0
        finally:
            fake.stop.set()
            fake.join(timeout=2)
        assert fake.acked.is_set()
        evs = _events(os.path.join(host_dir(str(tmp_path), 0),
                                   SUPERVISOR_EVENTS_FILE))
        phases = [e["data"].get("phase") for e in evs
                  if e["kind"] == "rendezvous"]
        # hello (gen 0) -> fault -> join -> ack -> hello (gen 1) -> done
        assert phases.count("fault") == 1
        assert phases.count("join") == 1
        assert phases.count("ack") == 1
        assert phases.count("done") == 1
        assert phases.count("hello") == 2
        rel = [e for e in evs if e["kind"] == "relaunch"]
        assert len(rel) == 1
        assert rel[0]["data"]["reason"].startswith("fleet-assign")
        assert rel[0]["data"]["out_rank"] == 0
        # no local reshard/replan happened: the fleet path never calls
        # the single-host reshard (there was no checkpoint anyway) and
        # the plan comes from the assignment (None here)
        assert rel[0]["data"]["topology"] is None

    def test_excluded_host_exits_with_excluded_code(self, tmp_path):
        sup, fake = _fleet_supervisor(tmp_path, "crash-once",
                                      exclude=True)
        try:
            assert sup.run() == EXCLUDED_EXIT_CODE
        finally:
            fake.stop.set()
            fake.join(timeout=2)
        evs = _events(os.path.join(host_dir(str(tmp_path), 0),
                                   SUPERVISOR_EVENTS_FILE))
        assert any(e["data"].get("action") == "excluded" for e in evs
                   if e["kind"] == "supervisor")

    def test_healthy_host_answers_rendezvous_call(self, tmp_path):
        # another host died: the coordinator calls a rendezvous while
        # THIS host's child is healthy — the supervisor must drain the
        # child (checkpoint barrier) and join, not ignore the call
        d = str(tmp_path)
        hdir = host_dir(d, 0)
        os.makedirs(hdir, exist_ok=True)
        script = tmp_path / "fleet_child.py"
        # a child that runs until drained (SIGUSR1 -> exit 75)
        script.write_text(textwrap.dedent("""
            import os, signal, sys, time
            signal.signal(signal.SIGUSR1,
                          lambda s, f: sys.exit(75))
            time.sleep(30)
            sys.exit(0)
        """))
        spec = ChildSpec([sys.executable, str(script),
                          "--trace_dir", hdir,
                          "--checkpoint_dir", d,
                          "--world_size", "4"])
        member = FleetMember(d, 0, 4, alive_interval_s=0.1)
        sup = Supervisor(spec, SupervisorPolicy(world=4,
                                                max_restarts=0),
                         poll_interval_s=0.05, fleet=member,
                         fleet_timeout_s=10.0, drain_timeout_s=10.0,
                         install_signal_handlers=False)
        coord = TelemetryRegistry(rank=0, sinks=[JsonlSink(
            os.path.join(d, COORDINATOR_EVENTS_FILE))])

        def conduct():
            from stochastic_gradient_push_tpu.supervise import (
                EventTailer)
            tailer = EventTailer(os.path.join(hdir,
                                              SUPERVISOR_EVENTS_FILE))
            deadline = time.time() + 10
            called = False
            while time.time() < deadline:
                for ev in tailer.poll():
                    data = ev.get("data") or {}
                    if ev.get("kind") != "rendezvous":
                        continue
                    if data.get("phase") == "hello" and not called:
                        called = True
                        coord.emit("rendezvous", {
                            "phase": "call", "round": 1,
                            "cause": "host 1 lost", "deadline_s": 5.0,
                            "hosts": [0]})
                    elif data.get("phase") == "join":
                        coord.emit("fleet", {
                            "phase": "assign", "round": 1, "cycle": 1,
                            "cause": "host 1 lost", "world": 2,
                            "prev_world": 4, "plan": None,
                            "shards": {"0": {
                                "out_rank": 0, "out_rows": 2,
                                "host_index": 0, "num_hosts": 1,
                                "rank_offset": 0}},
                            "excluded": [1]})
                    elif data.get("phase") == "ack":
                        coord.emit("fleet", {
                            "phase": "go", "round": 1, "cycle": 1,
                            "world": 2, "prev_world": 4,
                            "generation": 1, "acks": {"0": None}})
                        return
                time.sleep(0.02)

        t = threading.Thread(target=conduct, daemon=True)
        t.start()
        # after the go, the relaunched child sleeps 30s; drain the
        # supervisor itself once the relaunch landed
        deadline = time.time() + 15
        rel_path = os.path.join(hdir, SUPERVISOR_EVENTS_FILE)
        result = {}

        def run_sup():
            result["rc"] = sup.run()

        st = threading.Thread(target=run_sup, daemon=True)
        st.start()
        while time.time() < deadline:
            if any(e["kind"] == "relaunch" for e in _events(rel_path)):
                break
            time.sleep(0.05)
        sup._preempted = True   # what the SIGTERM handler would set
        st.join(timeout=15)
        t.join(timeout=2)
        assert result.get("rc") == REQUEUE_EXIT_CODE
        evs = _events(rel_path)
        rel = [e for e in evs if e["kind"] == "relaunch"]
        assert len(rel) == 1
        assert rel[0]["data"]["world"] == 2
        assert rel[0]["data"]["prev_world"] == 4
        phases = [e["data"].get("phase") for e in evs
                  if e["kind"] == "rendezvous"]
        assert "join" in phases and "fault" not in phases


# -- host-sim trainer --------------------------------------------------------


class TestHostSim:
    def _run(self, tmp_path, extra=()):
        from stochastic_gradient_push_tpu.supervise import hostsim

        argv = ["--checkpoint_dir", str(tmp_path),
                "--trace_dir", str(tmp_path / "host0"),
                "--world_size", "4", "--num_processes", "2",
                "--process_id", "0", "--rows", "2",
                "--step_s", "0.001", "--save_every", "2",
                *extra]
        return hostsim.main(argv)

    def test_runs_and_writes_reshardable_checkpoint(self, tmp_path):
        assert self._run(tmp_path, ["--steps", "4"]) == 0
        path = tmp_path / "checkpoint_r0_n4.ckpt"
        raw = flax.serialization.msgpack_restore(path.read_bytes())
        assert raw["meta"]["step"] == 4
        assert np.asarray(raw["state"]["gossip"]["ps_weight"]).shape \
            == (2,)
        assert np.asarray(raw["state"]["params"]["w"]).shape[0] == 2
        evs = _events(str(tmp_path / "host0" / "events.jsonl"))
        kinds = [e["kind"] for e in evs]
        assert kinds[0] == "run_meta" and "step_stats" in kinds

    def test_resume_continues_step_counter(self, tmp_path):
        assert self._run(tmp_path, ["--steps", "3"]) == 0
        assert self._run(tmp_path, ["--steps", "6",
                                    "--resume", "True"]) == 0
        raw = flax.serialization.msgpack_restore(
            (tmp_path / "checkpoint_r0_n4.ckpt").read_bytes())
        assert raw["meta"]["step"] == 6

    def test_wrong_rows_rejected_on_resume(self, tmp_path):
        assert self._run(tmp_path, ["--steps", "2"]) == 0
        from stochastic_gradient_push_tpu.supervise import hostsim

        rc = hostsim.main([
            "--checkpoint_dir", str(tmp_path),
            "--trace_dir", str(tmp_path / "host0"),
            "--world_size", "4", "--num_processes", "2",
            "--process_id", "0", "--rows", "3",
            "--steps", "4", "--resume", "True", "--step_s", "0.001"])
        assert rc == 2

    def test_sigusr1_drains_to_requeue_exit(self, tmp_path):
        env = {**os.environ,
               "PYTHONPATH": REPO + os.pathsep
               + os.environ.get("PYTHONPATH", "")}
        child = subprocess.Popen(
            [sys.executable, "-m",
             "stochastic_gradient_push_tpu.supervise.hostsim",
             "--checkpoint_dir", str(tmp_path),
             "--trace_dir", str(tmp_path / "host0"),
             "--world_size", "4", "--num_processes", "2",
             "--process_id", "0", "--rows", "2",
             "--steps", "500", "--step_s", "0.02"], env=env)
        # wait until the trainer is actually running (its run_meta
        # event landed) — the package import dominates startup, and a
        # SIGUSR1 before the handler is installed would just kill it
        deadline = time.time() + 60
        ev_path = str(tmp_path / "host0" / "events.jsonl")
        while time.time() < deadline and not _events(ev_path):
            time.sleep(0.1)
        time.sleep(0.3)
        child.send_signal(signal.SIGUSR1)
        assert child.wait(timeout=30) == REQUEUE_EXIT_CODE
        raw = flax.serialization.msgpack_restore(
            (tmp_path / "checkpoint_r0_n4.ckpt").read_bytes())
        assert 0 < raw["meta"]["step"] < 500


# -- reshard hygiene (stale tmp files) ---------------------------------------


def _world_state(n, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "params": {"w": rng.normal(size=(n, 8)).astype(np.float32)},
        "gossip": {"ps_weight": np.ones(n, np.float32),
                   "phase": np.zeros(n, np.int32)},
    }


def _write_rank_file(directory, tag, rank, world, state, rows):
    lo = rank * rows
    sliced = {
        "params": {"w": state["params"]["w"][lo:lo + rows]},
        "gossip": {
            "ps_weight": state["gossip"]["ps_weight"][lo:lo + rows],
            "phase": state["gossip"]["phase"][lo:lo + rows]},
    }
    path = os.path.join(directory,
                        f"{tag}checkpoint_r{rank}_n{world}.ckpt")
    with open(path, "wb") as f:
        f.write(flax.serialization.msgpack_serialize(
            {"state": sliced, "meta": {"epoch": 1, "itr": 0,
                                       "step": 7}}))
    return path


class TestStaleTmpHygiene:
    def test_fresh_tmp_ignored_but_kept(self, tmp_path):
        d = str(tmp_path)
        state = _world_state(4)
        for r in range(2):
            _write_rank_file(d, "", r, 4, state, 2)
        tmp = tmp_path / "checkpoint_r0_n4.ckpt.tmp.r0"
        tmp.write_bytes(b"half-written garbage")
        # never considered part of the set...
        st, _, files = load_world_checkpoint(d, "", 4)
        assert len(files) == 2
        assert np.asarray(st["gossip"]["ps_weight"]).shape == (4,)
        # ...and a FRESH tmp (live concurrent writer) is not GC'd
        assert tmp.exists()

    def test_stale_tmp_garbage_collected(self, tmp_path):
        d = str(tmp_path)
        state = _world_state(4)
        for r in range(2):
            _write_rank_file(d, "", r, 4, state, 2)
        tmp = tmp_path / "checkpoint_r1_n4.ckpt.tmp.r1"
        tmp.write_bytes(b"dead writer droppings")
        past = time.time() - 3600
        os.utime(tmp, (past, past))
        load_world_checkpoint(d, "", 4)
        assert not tmp.exists()

    def test_maybe_cross_world_reshard_also_collects(self, tmp_path):
        d = str(tmp_path)
        state = _world_state(4)
        for r in range(2):
            _write_rank_file(d, "", r, 4, state, 2)
        tmp = tmp_path / "checkpoint_r0_n4.ckpt.tmp.r9"
        tmp.write_bytes(b"x")
        past = time.time() - 3600
        os.utime(tmp, (past, past))
        report = maybe_cross_world_reshard(d, "", 2)
        assert report is not None and report.new_world == 2
        assert not tmp.exists()

    def test_gc_respects_tag_and_age(self, tmp_path):
        d = str(tmp_path)
        mine = tmp_path / "lm_checkpoint_r0_n4.ckpt.tmp.r0"
        other = tmp_path / "checkpoint_r0_n4.ckpt.tmp.r0"
        fresh = tmp_path / "lm_checkpoint_r1_n4.ckpt.tmp.r1"
        for p in (mine, other, fresh):
            p.write_bytes(b"x")
        past = time.time() - 3600
        for p in (mine, other):
            os.utime(p, (past, past))
        removed = gc_stale_tmp(d, "lm_")
        assert [os.path.basename(p) for p in removed] == [mine.name]
        assert other.exists() and fresh.exists()


# -- concurrent shard writers ------------------------------------------------


# run reshard_checkpoints in a FRESH python process without importing
# the package (reshard.py is deliberately standalone: numpy at module
# level, flax inside functions) — real concurrent writers, no jax in
# the children, and no os.fork() of this multithreaded test process
_RESHARD_WORKER = textwrap.dedent("""
    import importlib.util, sys
    path, d, old_w, new_w, rank, rows = sys.argv[1:]
    spec = importlib.util.spec_from_file_location("reshard_alone", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["reshard_alone"] = mod   # dataclasses resolve via this
    spec.loader.exec_module(mod)
    mod.reshard_checkpoints(d, "", int(old_w), int(new_w),
                            out_rank=int(rank), out_rows=int(rows))
""")


def _reshard_subprocess(d, old_world, new_world, out_rank, out_rows):
    reshard_py = os.path.join(
        REPO, "stochastic_gradient_push_tpu", "supervise", "reshard.py")
    return subprocess.Popen(
        [sys.executable, "-c", _RESHARD_WORKER, reshard_py, d,
         str(old_world), str(new_world), str(out_rank), str(out_rows)])


class TestConcurrentShardWriters:
    def test_disjoint_out_ranks_compose_untorn(self, tmp_path):
        d = str(tmp_path)
        state = _world_state(6, seed=3)
        for r in range(3):
            _write_rank_file(d, "", r, 6, state, 2)
        before = consensus_mean({
            "params": state["params"],
            "gossip": state["gossip"]})
        procs = [_reshard_subprocess(d, 6, 4, rank, 2)
                 for rank in (0, 1)]
        for p in procs:
            assert p.wait(timeout=60) == 0
        new, meta, files = load_world_checkpoint(d, "", 4)
        assert len(files) == 2
        after = consensus_mean(new)
        drift = max(float(np.abs(before[k] - after[k]).max())
                    for k in before)
        assert drift < 1e-6
        assert np.allclose(np.asarray(new["gossip"]["ps_weight"]), 1.0)
        assert meta["reshard"]["old_world"] == 6

    def test_duplicate_out_rank_detected_as_torn(self, tmp_path):
        # a racing duplicate write: two hosts both claim out_rank 1 with
        # different row splits — the assembled rows no longer sum to the
        # world, and the torn-set check refuses the set instead of
        # silently merging it
        d = str(tmp_path)
        state = _world_state(6, seed=4)
        for r in range(3):
            _write_rank_file(d, "", r, 6, state, 2)
        reshard_checkpoints(d, "", 6, 4, out_rank=0, out_rows=2)
        reshard_checkpoints(d, "", 6, 4, out_rank=1, out_rows=3)
        with pytest.raises(TornCheckpointError, match="torn"):
            load_world_checkpoint(d, "", 4)


class TestFleetBacklog:
    def test_check_fleet_stream_consumes_backlog_and_keeps_tail(
            self, tmp_path):
        # the tailer never re-delivers: whatever a poll batch carries
        # beyond the event we act on must survive — both directions
        # (backlog in, tail out)
        d = str(tmp_path)
        hdir = host_dir(d, 0)
        os.makedirs(hdir, exist_ok=True)
        spec = ChildSpec([sys.executable, "x.py", "--trace_dir", hdir,
                          "--checkpoint_dir", d, "--world_size", "4"])
        member = FleetMember(d, 0, 4)
        sup = Supervisor(spec, SupervisorPolicy(world=4), fleet=member,
                         install_signal_handlers=False)
        call = {"kind": "rendezvous", "data": {"phase": "call",
                                               "round": 7,
                                               "cause": "x"}}
        assign = {"kind": "fleet", "data": {"phase": "assign",
                                            "round": 7, "shards": {}}}
        sup._fleet_backlog = [call, assign]
        act = sup._check_fleet_stream()
        assert act is not None and act.kind == "fleet-rendezvous"
        assert sup._fleet_call["round"] == 7
        # the assign that followed the call in the same batch is NOT
        # lost — it is queued for the fleet-cycle loop
        assert sup._fleet_backlog == [assign]


# -- run CLI fleet knobs -----------------------------------------------------


class TestRunCLIFleetKnobs:
    def test_sgd_rejects_host_id_without_fleet(self):
        from stochastic_gradient_push_tpu.run.gossip_sgd import (
            parse_config)
        with pytest.raises(SystemExit, match="needs --fleet True"):
            parse_config(["--dataset", "synthetic", "--host_id", "2"])

    def test_sgd_rejects_fleet_without_trace_dir(self):
        from stochastic_gradient_push_tpu.run.gossip_sgd import (
            parse_config)
        with pytest.raises(SystemExit, match="needs --trace_dir"):
            parse_config(["--dataset", "synthetic", "--fleet", "True"])

    def test_sgd_fleet_lands_in_config(self, tmp_path):
        from stochastic_gradient_push_tpu.run.gossip_sgd import (
            parse_config)
        cfg, args = parse_config([
            "--dataset", "synthetic", "--fleet", "True",
            "--host_id", "1", "--trace_dir", str(tmp_path)])
        assert cfg.fleet is True and cfg.host_id == 1

    def test_lm_rejects_fleet_knob_misuse(self):
        from stochastic_gradient_push_tpu.run.gossip_lm import (
            main as lm_main)
        base = ["--world_size", "8", "--seq_len", "32", "--d_model",
                "32", "--n_layers", "1", "--n_heads", "4", "--d_ff",
                "32", "--vocab_size", "32", "--batch_size", "2",
                "--num_steps", "1"]
        with pytest.raises(SystemExit, match="needs --fleet True"):
            lm_main(base + ["--host_id", "1"])
        with pytest.raises(SystemExit, match="needs --trace_dir"):
            lm_main(base + ["--fleet", "True"])

    def test_trainer_fleet_mode_skips_auto_reshard(self, tmp_path):
        # under fleet supervision the coordinator owns the restart
        # boundary: the Trainer must never race it with a local
        # cross-world reshard (out_rank-0 writes from every host would
        # collide).  Pinned at the config gate the Trainer checks.
        from stochastic_gradient_push_tpu.train.loop import (
            TrainerConfig)
        cfg = TrainerConfig(fleet=True)
        assert cfg.fleet is True   # the gate _try_cross_world_resume
        # reads; the fleet selftest covers the live path end to end


# -- telemetry kinds ---------------------------------------------------------


class TestFleetTelemetry:
    def test_new_kinds_accepted_and_closed(self):
        from stochastic_gradient_push_tpu.telemetry import MemorySink
        reg = TelemetryRegistry(rank=0, sinks=[MemorySink()])
        reg.emit("rendezvous", {"phase": "join", "host": 0, "round": 1})
        reg.emit("fleet", {"phase": "go", "world": 4})
        with pytest.raises(ValueError):
            reg.emit("gossip", {})  # still a closed vocabulary

    def test_compat_sink_renders_legacy_lines_byte_stably(self, caplog):
        import logging

        from stochastic_gradient_push_tpu.telemetry import (
            LoggerCompatSink)
        log = logging.getLogger("test_fleet_compat")
        reg = TelemetryRegistry(rank=0, sinks=[LoggerCompatSink(log)])
        rdv = {"phase": "call", "round": 2, "hosts": [0, 1]}
        flt = {"phase": "assign", "world": 4, "excluded": [2]}
        with caplog.at_level(logging.INFO, log.name):
            reg.emit("rendezvous", rdv)
            reg.emit("fleet", flt)
        lines = [r.message for r in caplog.records]
        assert lines == [
            "gossip rendezvous: " + json.dumps(rdv, sort_keys=True),
            "gossip fleet: " + json.dumps(flt, sort_keys=True)]


# -- the kill-a-slice chaos e2e (the CI gate) --------------------------------


@pytest.mark.slow
def test_fleet_selftest_kill_slice_coordinated_reshard(tmp_path, capsys):
    """A 3-host x 2-rank simulated fleet loses an entire slice (host 2's
    supervisor AND child SIGKILLed) -> the coordinator's rendezvous
    excludes it after the deadline and re-runs -> both survivors reshard
    their disjoint shards of the 6->4 collapse concurrently (mean
    preserved, un-torn set) -> exactly one coordinated relaunch -> the
    run completes at the shrunken world."""
    from stochastic_gradient_push_tpu.supervise.fleetcli import selftest

    assert selftest(keep_dir=str(tmp_path)) == 0
    assert "fleet selftest: OK" in capsys.readouterr().out
