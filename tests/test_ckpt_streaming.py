"""Orbax checkpoint backend and streaming ImageFolder loader."""

import os

import jax.numpy as jnp
import numpy as np
import pytest
from PIL import Image

from stochastic_gradient_push_tpu.data.streaming import StreamingImageFolder
from stochastic_gradient_push_tpu.utils.orbax_ckpt import (
    OrbaxCheckpointManager,
)

WORLD = 4


def _state():
    return {"params": {"w": jnp.arange(6.0).reshape(2, 3)},
            "ps_weight": jnp.ones((WORLD, 1))}


def test_orbax_roundtrip(tmp_path):
    cm = OrbaxCheckpointManager(str(tmp_path), tag="t_", world_size=WORLD,
                                async_save=False)
    assert not cm.exists()
    state = _state()
    cm.save(state, {"epoch": 3, "itr": 7}, is_best=True)
    cm.wait()
    assert cm.exists()
    template = {"params": {"w": jnp.zeros((2, 3))},
                "ps_weight": jnp.zeros((WORLD, 1))}
    restored, meta = cm.restore(template)
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                  np.asarray(state["params"]["w"]))
    assert meta["epoch"] == 3 and meta["itr"] == 7
    cm.close()


def test_orbax_retention_and_latest(tmp_path):
    cm = OrbaxCheckpointManager(str(tmp_path), world_size=WORLD,
                                max_to_keep=2, async_save=False)
    for epoch in range(4):
        cm.save(_state(), {"epoch": epoch}, epoch_id=epoch)
    cm.wait()
    _, meta = cm.restore(_state())
    assert meta["epoch"] == 3  # latest wins
    kept = sorted(d for d in os.listdir(cm.checkpoint_path)
                  if d.isdigit())
    assert len(kept) <= 2  # retention GC
    cm.close()


@pytest.fixture(scope="module")
def image_folder(tmp_path_factory):
    """Tiny 2-class ImageFolder on disk."""
    root = tmp_path_factory.mktemp("imgs")
    rng = np.random.default_rng(0)
    for split in ("train",):
        for cls in ("cat", "dog"):
            d = root / split / cls
            d.mkdir(parents=True)
            for i in range(24):
                arr = rng.integers(0, 255, size=(20, 20, 3), dtype=np.uint8)
                Image.fromarray(arr).save(d / f"{i}.png")
    return str(root)


def test_streaming_imagefolder_shapes_and_epochs(image_folder):
    loader = StreamingImageFolder(image_folder, "train", world_size=WORLD,
                                  batch_size=2, image_size=16,
                                  num_workers=1)
    assert len(loader) == 48 // WORLD // 2
    loader.set_epoch(1)
    batches = list(loader)
    assert len(batches) == len(loader)
    x, y = batches[0]
    assert x.shape == (WORLD, 2, 16, 16, 3)
    assert y.shape == (WORLD, 2)
    assert x.dtype == np.float32 and y.dtype == np.int32

    # different epoch → different batch composition
    loader.set_epoch(2)
    x2, _ = next(iter(loader))
    assert not np.allclose(x, x2)

    # determinism within an epoch
    loader.set_epoch(1)
    x3, y3 = next(iter(loader))
    np.testing.assert_allclose(x, x3)
    np.testing.assert_array_equal(y, y3)


def test_streaming_fast_forward(image_folder):
    loader = StreamingImageFolder(image_folder, "train", world_size=WORLD,
                                  batch_size=2, image_size=16,
                                  num_workers=1)
    loader.set_epoch(5)
    full = list(loader)
    loader.fast_forward(2)
    resumed = list(loader)
    assert len(resumed) == len(full) - 2
    np.testing.assert_array_equal(resumed[0][1], full[2][1])


def test_orbax_best_survives_retention(tmp_path):
    cm = OrbaxCheckpointManager(str(tmp_path), world_size=WORLD,
                                max_to_keep=2, async_save=False)
    best_state = {"params": {"w": jnp.full((2, 3), 7.0)},
                  "ps_weight": jnp.ones((WORLD, 1))}
    cm.save(best_state, {"epoch": 0}, epoch_id=0, is_best=True)
    for epoch in range(1, 5):
        cm.save(_state(), {"epoch": epoch}, epoch_id=epoch)
    cm.wait()
    restored, meta = cm.restore_best(_state())
    assert meta["epoch"] == 0
    np.testing.assert_allclose(np.asarray(restored["params"]["w"]), 7.0)
    cm.close()
