"""Two-level hierarchical gossip (topology/hierarchical.py).

Covers the PR-8 tentpole end to end on CPU:

* slice decomposition rules and constructor refusals;
* schedule invariants through ``analysis.verify_schedule`` (the
  two-level effective matrix is column-stochastic and mean-preserving,
  including non-power-of-two slice counts and self-weighted mixing);
* the pinned gap regression table at world 8/16/32/64;
* compiled-round parity: the leader-``ppermute`` + grouped-``psum``
  round equals the dense ``W_intra @ W_inter`` product the verifier
  checks, on a real 8-device mesh;
* the acceptance pin: at world 64 with DCN-dominant edge pricing the
  planner selects the hierarchical topology, its schedule verifies, and
  its inter-slice (DCN) bytes/step are strictly below the flat-gossip
  winner's at the same gap floor.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from stochastic_gradient_push_tpu.analysis import (
    spectral_gap,
    verify_schedule,
)
from stochastic_gradient_push_tpu.parallel import (
    GOSSIP_AXIS,
    gossip_round,
    make_gossip_mesh,
    mix_push_sum,
)
from stochastic_gradient_push_tpu.planner import (
    InterconnectModel,
    PlanConstraints,
    plan_for,
)
from stochastic_gradient_push_tpu.telemetry import CommModel
from stochastic_gradient_push_tpu.topology import (
    TOPOLOGY_NAMES,
    HierarchicalGraph,
    HierarchicalSchedule,
    SelfWeightedMixing,
    build_pairing_schedule,
    build_schedule,
    default_slice_size,
)

WORLD = 8


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() >= WORLD, "conftest must fake 8 devices"
    return make_gossip_mesh(WORLD)


def _per_rank_values(seed=0, shape=(4, 3)):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(WORLD,) + shape).astype(np.float32)


# -- slice decomposition ----------------------------------------------------


class TestSliceDecomposition:
    def test_default_slice_sizes(self):
        # few, large slices — the shape of real multi-slice pods
        assert {w: default_slice_size(w)
                for w in (4, 8, 12, 16, 24, 32, 48, 64)} == {
                    4: 2, 8: 4, 12: 4, 16: 4, 24: 6, 32: 8, 48: 8, 64: 8}

    @pytest.mark.parametrize("world", [1, 2, 3])
    def test_worlds_below_two_slices_of_two_are_unsupported(self, world):
        with pytest.raises(ValueError, match="unsupported|must be >="):
            HierarchicalGraph(world)

    def test_indivisible_slice_size_refused(self):
        with pytest.raises(ValueError, match="unsupported"):
            HierarchicalGraph(8, slice_size=3)
        with pytest.raises(ValueError, match="unsupported"):
            HierarchicalGraph(8, slice_size=8)   # needs >= 2 slices

    def test_dcn_fanout_bounds(self):
        with pytest.raises(ValueError, match="dcn_fanout"):
            HierarchicalGraph(16, dcn_fanout=0)
        with pytest.raises(ValueError, match="dcn_fanout"):
            HierarchicalGraph(16, slice_size=4, dcn_fanout=5)

    def test_ppi_beyond_slice_phone_book_is_unsupported(self):
        # 2 slices → the slice-level exponential graph has 1 peer max
        with pytest.raises(ValueError):
            HierarchicalGraph(8, peers_per_itr=3)

    def test_pairing_refused(self):
        # delegates are not interchangeable partners: bilateral pairing
        # (AD-PSGD) has no meaning on a two-level schedule
        assert HierarchicalGraph.supports_pairing is False
        with pytest.raises(ValueError, match="unsupported"):
            build_pairing_schedule(HierarchicalGraph(8))


# -- schedule invariants ----------------------------------------------------


class TestScheduleInvariants:
    @pytest.mark.parametrize("world,slice_size,ppi", [
        (8, None, 1), (16, None, 1), (32, None, 1), (64, None, 1),
        (64, None, 2), (48, 8, 1), (24, 6, 1), (12, 4, 1), (64, 4, 1),
        (64, 16, 2),
    ])
    def test_verifier_clean_over_grid(self, world, slice_size, ppi):
        g = HierarchicalGraph(world, peers_per_itr=ppi,
                              slice_size=slice_size)
        sched = build_schedule(g)
        findings, gap = verify_schedule(sched, f"hier-{world}", "<t>", 0)
        assert findings == []
        assert gap > 0.01  # every cell clears the planner's floor

    def test_schedule_structure(self):
        g = HierarchicalGraph(64)  # 8 slices of 8, fanout 2, 3 rounds
        sched = build_schedule(g)
        assert isinstance(sched, HierarchicalSchedule)
        assert sched.rounds_per_cycle == 3
        assert sched.num_phases == 6  # inter+intra table phases per round
        assert sched.phase_kinds == ("inter", "intra") * 3
        assert sched.slice_groups == tuple(
            tuple(range(j * 8, (j + 1) * 8)) for j in range(8))
        # the compact inter tables are what the compiled ppermute runs
        inter = sched.inter_schedule
        assert inter.num_phases == 3 and inter.peers_per_itr == 1

    def test_mean_preserved_by_full_cycle_product(self):
        # column-stochasticity per phase ⇒ the uniform-weight consensus
        # value is the true mean (push-sum's core invariant)
        sched = build_schedule(HierarchicalGraph(24, slice_size=6))
        rng = np.random.default_rng(0)
        x = rng.normal(size=(24,))
        prod = np.eye(24)
        for p in range(sched.num_phases):
            prod = sched.mixing_matrix(p) @ prod
        assert np.allclose(prod.sum(axis=0), 1.0, atol=1e-12)
        assert (prod @ x).mean() == pytest.approx(x.mean(), abs=1e-12)

    def test_self_weighted_mixing_verifies(self):
        g = HierarchicalGraph(16)
        sched = build_schedule(g, SelfWeightedMixing(0.3))
        findings, gap = verify_schedule(sched, "hier-sw", "<t>", 0)
        assert findings == [] and 0.0 < gap <= 1.0

    def test_out_peers_inter_and_intra(self):
        g = HierarchicalGraph(8)  # 2 slices of 4, fanout 1
        # phase 0 (inter): delegate 0 sends to the peer slice's delegate
        assert g.out_peers(0, 0) == (4,)
        assert g.out_peers(1, 0) == ()       # non-delegate: silent
        # phase 1 (intra): everyone sends to its whole slice
        assert set(g.out_peers(1, 1)) == {0, 2, 3}

    def test_registered_in_both_registries(self):
        from stochastic_gradient_push_tpu.topology import GRAPH_TOPOLOGIES
        assert TOPOLOGY_NAMES["hierarchical"] is HierarchicalGraph
        assert GRAPH_TOPOLOGIES[6] is HierarchicalGraph


# -- pinned gap regression table --------------------------------------------


class TestGapRegression:
    """Future edits to the two-level schedule must not silently change
    mixing behavior — same contract as the flat-graph table in
    test_planner.py."""

    @pytest.mark.parametrize("world,want", [
        (8, 0.375), (16, 0.375), (32, 0.4375), (64, 0.4375),
    ])
    def test_default_decomposition(self, world, want):
        sched = build_schedule(HierarchicalGraph(world))
        assert spectral_gap(sched) == pytest.approx(want, rel=1e-6)

    @pytest.mark.parametrize("world,slice_size,want", [
        (48, 8, 0.4375),      # 6 slices — non-power-of-two slice count
        (24, 6, 0.277778),    # 4 slices of 6
        (12, 4, 0.457031),    # 3 slices of 4
        (64, 4, 0.375),       # 16 small slices
        (64, 16, 0.46875),    # 4 large slices
    ])
    def test_explicit_decompositions(self, world, slice_size, want):
        sched = build_schedule(HierarchicalGraph(world,
                                                 slice_size=slice_size))
        assert spectral_gap(sched) == pytest.approx(want, rel=1e-4)

    def test_gap_flat_across_slice_count_at_fixed_slice_size(self):
        # slice-level rotation is exponential: adding slices at the same
        # slice size does not collapse the gap (48 = 6 slices matches 64
        # = 8 slices) — the property RingGraph lacks at pod scale
        g48 = spectral_gap(build_schedule(HierarchicalGraph(48, slice_size=8)))
        g64 = spectral_gap(build_schedule(HierarchicalGraph(64, slice_size=8)))
        assert g48 == pytest.approx(g64, rel=1e-6)


# -- compiled round parity --------------------------------------------------


class TestCompiledRound:
    def _round_fn(self, mesh, sched):
        def step(phase, xs):
            return gossip_round(xs, phase, sched, GOSSIP_AXIS)
        return jax.jit(jax.shard_map(
            step, mesh=mesh, in_specs=(P(), P(GOSSIP_AXIS)),
            out_specs=P(GOSSIP_AXIS)))

    def test_round_matches_two_level_matrices(self, mesh):
        """One compiled round (leader ppermute + grouped psum) applies
        exactly W_intra @ W_inter — the matrices the verifier checks."""
        sched = build_schedule(HierarchicalGraph(WORLD, slice_size=4))
        f = self._round_fn(mesh, sched)
        x = _per_rank_values(seed=1)
        for rnd in range(sched.rounds_per_cycle + 1):
            got = np.asarray(f(jnp.int32(rnd), x))
            q = rnd % sched.rounds_per_cycle
            W = sched.mixing_matrix(2 * q + 1) @ sched.mixing_matrix(2 * q)
            want = np.einsum("rs,s...->r...", W, x.astype(np.float64))
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_round_matches_with_two_by_two_slices(self, mesh):
        sched = build_schedule(HierarchicalGraph(WORLD, slice_size=2))
        f = self._round_fn(mesh, sched)
        x = _per_rank_values(seed=2, shape=(3,))
        got = np.asarray(f(jnp.int32(0), x))
        W = sched.mixing_matrix(1) @ sched.mixing_matrix(0)
        np.testing.assert_allclose(
            got, np.einsum("rs,s...->r...", W, x.astype(np.float64)),
            rtol=1e-5, atol=1e-5)

    def test_mass_conservation_and_push_sum_consensus(self, mesh):
        sched = build_schedule(HierarchicalGraph(WORLD))
        x = _per_rank_values(seed=3, shape=(5,))
        w = np.ones((WORLD, 1), dtype=np.float32)
        total, mean = x.sum(axis=0), x.mean(axis=0)

        def step(phase, xs, ws):
            return mix_push_sum(xs, ws, phase, sched, GOSSIP_AXIS)

        f = jax.jit(jax.shard_map(
            step, mesh=mesh,
            in_specs=(P(), P(GOSSIP_AXIS), P(GOSSIP_AXIS)),
            out_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS))))
        for rnd in range(40):
            x, w = map(np.asarray, f(jnp.int32(rnd), x, w))
            np.testing.assert_allclose(x.sum(axis=0), total,
                                       rtol=1e-4, atol=1e-4)
        debiased = x / w
        np.testing.assert_allclose(
            debiased, np.broadcast_to(mean, debiased.shape),
            rtol=1e-4, atol=1e-4)

    def test_no_recompilation_across_rounds(self, mesh):
        sched = build_schedule(HierarchicalGraph(WORLD, slice_size=2))
        assert sched.rounds_per_cycle > 1
        x = _per_rank_values(seed=4, shape=(2,))
        traces = 0

        def step(phase, xs):
            nonlocal traces
            traces += 1
            return gossip_round(xs, phase, sched, GOSSIP_AXIS)

        f = jax.jit(jax.shard_map(
            step, mesh=mesh, in_specs=(P(), P(GOSSIP_AXIS)),
            out_specs=P(GOSSIP_AXIS)))
        for rnd in range(2 * sched.rounds_per_cycle):
            f(jnp.int32(rnd), x)
        assert traces == 1

    def test_faults_rejected_overlap_composes(self):
        from stochastic_gradient_push_tpu.algorithms import sgp
        from stochastic_gradient_push_tpu.resilience import \
            parse_fault_spec

        sched = build_schedule(HierarchicalGraph(WORLD))
        # overlap composes with the two-level round: the delegate (DCN)
        # share defers, the intra-slice psum runs at consume time
        # (behavior pinned in tests/test_overlap.py)
        alg = sgp(sched, GOSSIP_AXIS, overlap=True)
        assert alg.overlap
        # fault injection remains a flat-schedule feature: the grouped
        # psum has no per-edge mask
        flat = build_schedule(
            TOPOLOGY_NAMES["ring"](WORLD, peers_per_itr=1))
        masks = parse_fault_spec("drop:0->1@0:4;seed:1").build_masks(flat)
        with pytest.raises(ValueError, match="hierarchical"):
            sgp(sched, GOSSIP_AXIS, faults=masks)
        with pytest.raises(ValueError, match="hierarchical"):
            gossip_round((np.zeros(2),), 0, sched, GOSSIP_AXIS,
                         faults=masks)

    def test_dpsgd_rejects_irregular_hierarchical(self):
        from stochastic_gradient_push_tpu.algorithms import dpsgd

        sched = build_schedule(HierarchicalGraph(WORLD))
        with pytest.raises(ValueError, match="regular"):
            dpsgd(sched, GOSSIP_AXIS)


# -- world-64 acceptance pin ------------------------------------------------


class TestWorld64Acceptance:
    FABRIC = InterconnectModel(slice_size=8, dcn_cost=16.0)

    def test_dcn_dominant_pricing_selects_hierarchical(self):
        plan = plan_for(64, ppi=1, constraints=PlanConstraints(
            interconnect=self.FABRIC))
        assert plan.topology == "hierarchical"
        assert plan.slice_size == 8 and not plan.below_floor()
        assert plan.interconnect == self.FABRIC.to_dict()
        # the planned graph class carries the slice decomposition
        g = plan.graph_class(64, peers_per_itr=plan.ppi)
        assert isinstance(g, HierarchicalGraph) and g.slice_size == 8

    def test_selected_schedule_verifies(self):
        plan = plan_for(64, ppi=1, constraints=PlanConstraints(
            interconnect=self.FABRIC))
        sched = build_schedule(plan.graph_class(64, peers_per_itr=1),
                               plan.mixing_strategy())
        findings, gap = verify_schedule(sched, "hier-acc", "<t>", 0)
        assert findings == [] and gap >= plan.floor

    def test_uniform_fabric_keeps_flat_winner(self):
        assert plan_for(64, ppi=1).topology != "hierarchical"

    def test_inter_slice_bytes_strictly_below_flat_at_same_floor(self):
        """The measurable payoff: per-step DCN bytes drop by the gossip
        sparsity factor versus the flat winner at the same gap floor."""
        flat_plan = plan_for(64, ppi=1)   # uniform-fabric flat winner
        flat = build_schedule(
            TOPOLOGY_NAMES[flat_plan.topology](64, peers_per_itr=1))
        hier = build_schedule(HierarchicalGraph(64, slice_size=8))
        assert spectral_gap(flat) >= 0.01 and spectral_gap(hier) >= 0.01

        payload = 100_000
        steps = 96  # covers both rotation cycles (32 and 3) evenly
        flat_b = CommModel.from_schedule(
            flat, payload, interconnect=self.FABRIC).totals(steps)
        hier_b = CommModel.from_schedule(
            hier, payload, interconnect=self.FABRIC).totals(steps)
        assert hier_b["gossip_dcn"] < flat_b["gossip_dcn"]
        # the sparsity factor: only num_slices × fanout × ppi messages
        # cross DCN per round vs (almost) world for the flat graph
        assert hier_b["gossip_dcn"] < flat_b["gossip_dcn"] / 2
        # both models account every wire byte into exactly two lanes
        for b in (flat_b, hier_b):
            assert b["gossip_ici"] + b["gossip_dcn"] == b["gossip_wire"]
