"""Planner subsystem: gap regression table, scoring, policy, alpha search.

The regression table pins the known rotation-cycle spectral gaps (ROADMAP
open items / PR 1 verifier report) so future topology edits cannot
silently change mixing behavior: ring collapse at pod scale, exponential
graphs' perfect gap at powers of two and ~17% degradation at 12/24/48,
and the irregular-mixing alpha cost the planner's co-optimizer recovers.
"""

import json

import numpy as np
import pytest

from stochastic_gradient_push_tpu.analysis import GapEntry, spectral_gap
from stochastic_gradient_push_tpu.planner import (
    Plan,
    PlanConstraints,
    check_topology,
    consensus_cost,
    optimize_alpha,
    plan_for,
    resolve_topology,
    score_candidates,
)
from stochastic_gradient_push_tpu.planner.alpha import alpha_gap
from stochastic_gradient_push_tpu.planner.cli import main as plan_cli
from stochastic_gradient_push_tpu.topology import (
    TOPOLOGY_NAMES,
    DynamicDirectedExponentialGraph,
    NPeerDynamicDirectedExponentialGraph,
    RingGraph,
    UniformMixing,
    build_schedule,
    topology_name,
)


def _gap(cls, world, ppi=1, mixing=None):
    return spectral_gap(build_schedule(cls(world, peers_per_itr=ppi),
                                       mixing or UniformMixing()))


# -- satellite: pinned gap regression table ---------------------------------

class TestGapRegression:
    def test_ring_gap_collapses_with_world_size(self):
        # the quadratic collapse that motivates the whole subsystem
        assert _gap(RingGraph, 8) == pytest.approx(0.07612, rel=1e-3)
        assert _gap(RingGraph, 32) == pytest.approx(0.0048153, rel=1e-3)
        assert _gap(RingGraph, 64) == pytest.approx(0.0012045, rel=1e-3)

    @pytest.mark.parametrize("world", [8, 16, 32, 64])
    def test_exponential_exact_at_powers_of_two(self, world):
        assert _gap(DynamicDirectedExponentialGraph, world) == \
            pytest.approx(1.0, abs=1e-9)

    @pytest.mark.parametrize("world", [12, 24, 48])
    def test_exponential_degrades_at_non_powers_of_two(self, world):
        # supported-but-degraded (v5e-48-style worlds): ~17% of the gap
        # is lost off the power-of-two lattice (ARCHITECTURE.md "Planner")
        assert _gap(DynamicDirectedExponentialGraph, world) == \
            pytest.approx(0.83, abs=0.01)
        assert _gap(NPeerDynamicDirectedExponentialGraph, world) == \
            pytest.approx(0.79, abs=0.01)

    def test_spectral_gap_is_public_analysis_api(self):
        # the planner consumes these as stable exports — importability is
        # the contract (no duplicated power iteration or skip rules)
        from stochastic_gradient_push_tpu.analysis import \
            is_unsupported_config

        row = GapEntry("RingGraph", 8, 1, "uniform", 0.076)
        assert row.topology == "RingGraph" and row.gap == 0.076
        assert is_unsupported_config(
            ValueError("bipartite graphs require an even world size"))
        assert not is_unsupported_config(ValueError("index out of range"))


# -- scorer -----------------------------------------------------------------

class TestScorer:
    def test_world64_ranking_avoids_ring(self):
        cands = score_candidates(64, peer_counts=(1,))
        assert cands, "no candidates at world 64"
        best = cands[0]
        assert best.topology != "ring"
        assert best.gap >= 0.01
        # ring is present but ranked last (below the floor)
        ring = [c for c in cands if c.topology == "ring"]
        assert ring and cands[-1].topology == "ring"
        assert not ring[0].meets(0.01)

    def test_consensus_cost_model(self):
        # exact consensus = one full cycle; contraction = phases / rate
        rounds, cost = consensus_cost(1.0, num_phases=6, ppi=2)
        assert rounds == 6.0 and cost == 12.0
        rounds, _ = consensus_cost(0.5, num_phases=4, ppi=1)
        assert rounds == pytest.approx(4 / -np.log(0.5))
        rounds, _ = consensus_cost(0.0, num_phases=1, ppi=1)
        assert rounds == np.inf

    def test_odd_world_skips_bipartite(self):
        cands = score_candidates(5, peer_counts=(1,))
        names = {c.topology for c in cands}
        assert "bipartite-exponential" not in names
        assert "bipartite-linear" not in names
        assert "ring" in names

    def test_unknown_allowed_name_rejected(self):
        with pytest.raises(ValueError, match="unknown topology"):
            score_candidates(8, allowed=("hypercube",))


# -- policy -----------------------------------------------------------------

class TestPolicy:
    def test_plan_for_world64_clears_floor(self):
        plan = plan_for(64, ppi=1)
        assert plan.auto and plan.topology != "ring"
        assert plan.gap >= plan.floor
        assert plan.global_avg_every == 0
        assert plan.ranking  # stamped for the report

    def test_ring_only_constraint_emits_averaging_schedule(self):
        plan = plan_for(64, ppi=1,
                        constraints=PlanConstraints(allowed=("ring",)))
        assert plan.topology == "ring" and plan.below_floor()
        assert plan.global_avg_every > 0
        assert "periodic global averaging" in plan.rationale
        # the period: capped at 1/floor steps even though 1/gap ~ 830
        assert plan.global_avg_every == 100

    def test_forced_ring_world64_warns_with_gap_and_alternative(self):
        plan = check_topology(64, RingGraph, ppi=1)
        assert not plan.auto and plan.below_floor()
        assert plan.global_avg_every > 0
        assert len(plan.warnings) == 1
        msg = plan.warnings[0]
        assert msg.startswith("topology-below-floor: ")
        payload = json.loads(msg.split(": ", 1)[1].split(" — ")[0])
        assert payload["topology"] == "ring" and payload["world"] == 64
        assert payload["gap"] == pytest.approx(0.0012, abs=1e-4)
        assert payload["suggested_topology"] != "ring"
        assert payload["suggested_gap"] >= 0.01

    def test_forced_healthy_topology_is_silent(self):
        plan = check_topology(64, NPeerDynamicDirectedExponentialGraph)
        assert not plan.warnings and plan.global_avg_every == 0

    def test_plan_dict_json_round_trips(self):
        plan = plan_for(12, ppi=1)
        d = json.loads(json.dumps(plan.to_dict()))
        assert d["topology"] == plan.topology
        assert d["world"] == 12 and "rationale" in d
        assert TOPOLOGY_NAMES[d["topology"]] is plan.graph_class

    def test_dpsgd_rejects_self_weighted(self):
        with pytest.raises(ValueError, match="regular"):
            plan_for(8, algorithm="dpsgd",
                     constraints=PlanConstraints(self_weighted=True))

    def test_world_one_is_trivial(self):
        plan = plan_for(1)
        assert plan.gap == 1.0 and plan.global_avg_every == 0


# -- alpha co-optimization (acceptance criterion) ---------------------------

class TestAlphaCoOptimization:
    def test_recovers_gap_where_default_loses_20pct_at_world64(self):
        """NPeerExponential(64, ppi=4): the free-knob default alpha 0.5
        costs >20% of the gap; the planner's scalar search recovers it
        to within 5% of uniform mixing (the ROADMAP irregular-mixing
        open item, closed)."""
        g = NPeerDynamicDirectedExponentialGraph(64, peers_per_itr=4)
        uniform = _gap(NPeerDynamicDirectedExponentialGraph, 64, ppi=4)
        default = alpha_gap(g, 0.5)
        tuned_alpha, tuned = optimize_alpha(g)
        assert default <= 0.8 * tuned          # default loses >= 20%
        assert tuned >= 0.95 * uniform         # search recovers the gap
        assert 0.0 < tuned_alpha < 0.5         # multi-peer wants less self-mass

    def test_plan_carries_co_optimized_alpha(self):
        plan = plan_for(64, ppi=4,
                        constraints=PlanConstraints(self_weighted=True))
        assert plan.alpha is not None
        assert plan.mixing.startswith("self-weighted(")
        assert plan.gap >= plan.floor
        strat = plan.mixing_strategy()
        assert float(strat.alpha[0]) == pytest.approx(plan.alpha)

    def test_forced_suboptimal_alpha_warns_with_suggestion(self):
        plan = check_topology(64, NPeerDynamicDirectedExponentialGraph,
                              ppi=4, self_weighted=0.9)
        assert any(w.startswith("alpha-suboptimal: ")
                   for w in plan.warnings)
        payload = json.loads(
            [w for w in plan.warnings
             if w.startswith("alpha-suboptimal")][0].split(": ", 1)[1])
        assert payload["suggested_gap"] > payload["gap"]

    def test_optimize_alpha_never_below_default(self):
        for world, ppi in ((8, 1), (16, 2), (12, 1)):
            g = NPeerDynamicDirectedExponentialGraph(world,
                                                     peers_per_itr=ppi)
            _, tuned = optimize_alpha(g)
            assert tuned + 1e-9 >= alpha_gap(g, 0.5)


# -- run-layer entry point --------------------------------------------------

class _FakeLog:
    def __init__(self):
        self.infos, self.warnings = [], []

    def info(self, msg, *a):
        self.infos.append(msg % a if a else msg)

    def warning(self, msg, *a):
        self.warnings.append(msg % a if a else msg)


class TestResolveTopology:
    def test_auto_logs_plan_stamp(self):
        log = _FakeLog()
        plan = resolve_topology(64, ppi=1, topology="auto", log=log)
        assert plan.auto and plan.topology != "ring"
        stamp = [m for m in log.infos if m.startswith("gossip plan: ")]
        assert len(stamp) == 1
        assert json.loads(stamp[0].split(": ", 1)[1])["topology"] \
            == plan.topology
        assert not log.warnings

    def test_forced_ring_warns_loudly(self):
        log = _FakeLog()
        plan = resolve_topology(64, ppi=1, graph_class=RingGraph, log=log)
        assert plan.below_floor()
        assert any("topology-below-floor" in w for w in log.warnings)

    def test_user_override_of_averaging_period(self):
        plan = resolve_topology(64, ppi=1, graph_class=RingGraph,
                                global_avg_every=7)
        assert plan.global_avg_every == 7
        # the warning names the period actually in effect, not the
        # policy default
        assert '"global_avg_every": 7' in plan.warnings[0]

    def test_explicit_zero_disables_plan_imposed_averaging(self):
        # benchmarking pure ring gossip below the floor must be possible:
        # 0 means off, with the warning saying so
        plan = resolve_topology(64, ppi=1, graph_class=RingGraph,
                                global_avg_every=0)
        assert plan.global_avg_every == 0
        assert "explicitly disabled" in plan.warnings[0]

    def test_override_applies_to_healthy_auto_plan(self):
        plan = resolve_topology(64, ppi=1, topology="auto",
                                global_avg_every=50)
        assert plan.gap >= plan.floor and plan.global_avg_every == 50
        assert "user request" in plan.rationale

    def test_requires_a_selection(self):
        with pytest.raises(ValueError, match="topology name or a"):
            resolve_topology(8)


# -- CLI (scripts/plan.py drives planner.cli.main) --------------------------

class TestPlanCLI:
    def test_recommend_world64(self, capsys):
        rc = plan_cli(["--world", "64", "--ppi", "1"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "topology=" in out and "topology=ring" not in out
        assert "rationale:" in out

    def test_report_table(self, capsys):
        rc = plan_cli(["--world", "64", "--ppi", "1", "--report"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "BELOW" in out          # the ring row is flagged
        assert "msgs/efold" in out

    def test_forced_ring_exits_3_with_warning(self, capsys):
        rc = plan_cli(["--world", "64", "--topology", "ring"])
        out = capsys.readouterr().out
        assert rc == 3
        assert "topology-below-floor" in out

    def test_json_dump(self, tmp_path, capsys):
        path = tmp_path / "plan.json"
        rc = plan_cli(["--world", "8", "--json", str(path)])
        assert rc == 0
        d = json.loads(path.read_text())
        assert d["world"] == 8 and d["topology"] in TOPOLOGY_NAMES

    def test_selftest(self, capsys):
        assert plan_cli(["--world", "8", "--selftest"]) == 0
        assert "planner selftest: OK" in capsys.readouterr().out


def test_topology_name_round_trip():
    for name, cls in TOPOLOGY_NAMES.items():
        assert topology_name(cls) == name
    with pytest.raises(KeyError):
        topology_name(Plan)


# -- satellite: mesh-distance (ring hop) comm-cost model ---------------------

class _StrideRingGraph(RingGraph):
    """A "ring" that hops 3 ranks per edge: graph-isomorphic to the
    neighbor ring whenever gcd(3, n) == 1 (relabel ranks by r -> 3r mod
    n), so its spectral gap and message count are IDENTICAL — only the
    physical ICI distance of each message differs."""

    STRIDE = 3

    def _make_graph(self) -> None:
        for rank in range(self.world_size):
            self._add_peers(rank, [
                self._rotate_forward(rank, self.STRIDE),
                self._rotate_backward(rank, self.STRIDE)])


class TestHopCostModel:
    def test_ring_hop_distance_wraps(self):
        from stochastic_gradient_push_tpu.planner.scorer import \
            ring_hop_distance
        assert ring_hop_distance(0, 1, 8) == 1
        assert ring_hop_distance(0, 7, 8) == 1   # wrap-around link
        assert ring_hop_distance(0, 4, 8) == 4
        assert ring_hop_distance(5, 2, 8) == 3

    def test_neighbor_ring_beats_same_gap_stride_ring(self):
        """Equal gap, equal message count, 3x hop distance: the comm
        model must prefer the topology hugging the physical mesh."""
        from stochastic_gradient_push_tpu.planner.scorer import \
            evaluate_candidate, hops_per_round
        world = 8  # gcd(3, 8) == 1 -> stride ring is isomorphic
        near = evaluate_candidate(RingGraph, world, 1)
        far = evaluate_candidate(_StrideRingGraph, world, 1)
        assert near is not None and far is not None
        assert far.gap == pytest.approx(near.gap, abs=1e-9)
        assert far.comm_cost == pytest.approx(near.comm_cost, rel=1e-9)
        near_hops = hops_per_round(
            build_schedule(RingGraph(world, peers_per_itr=1)))
        far_hops = hops_per_round(
            build_schedule(_StrideRingGraph(world, peers_per_itr=1)))
        assert near_hops == pytest.approx(1.0)
        assert far_hops == pytest.approx(3.0)
        assert far.hop_cost == pytest.approx(3.0 * near.hop_cost, rel=1e-9)
        assert near.hop_cost < far.hop_cost

    def test_exponential_hops_priced_in(self):
        """An exponential graph's long edges cost what they cost: more
        hops per round than the ring, fewer rounds per e-fold — the
        model weighs both instead of calling every message equal."""
        from stochastic_gradient_push_tpu.planner.scorer import \
            hops_per_round
        ring_sched = build_schedule(RingGraph(64, peers_per_itr=1))
        exp_sched = build_schedule(
            DynamicDirectedExponentialGraph(64, peers_per_itr=1))
        assert hops_per_round(ring_sched) == pytest.approx(1.0)
        assert hops_per_round(exp_sched) > 5.0
        # ...and the ranking still never prefers the non-mixing ring
        cands = score_candidates(64, peer_counts=(1,))
        assert cands[0].topology != "ring"
        assert cands[0].hop_cost < float("inf")

    def test_candidate_dict_carries_hop_cost(self):
        c = score_candidates(8, peer_counts=(1,))[0]
        d = json.loads(json.dumps(c.to_dict()))
        assert isinstance(d["hop_cost"], float)


# -- tentpole: torus-aware DCN/ICI interconnect pricing ----------------------

class TestInterconnectModel:
    def test_edge_cost_semantics(self):
        from stochastic_gradient_push_tpu.planner import InterconnectModel

        m = InterconnectModel(slice_size=8, ici_cost=1.0, dcn_cost=16.0)
        assert m.edge_cost(3, 3, 64) == 0.0            # loopback free
        assert m.edge_cost(0, 1, 64) == 1.0            # 1 ICI hop
        assert m.edge_cost(0, 7, 64) == 1.0            # ring wrap inside
        assert m.edge_cost(0, 4, 64) == 4.0            # 4 hops on 1-D
        assert m.edge_cost(7, 8, 64) == 16.0           # crosses DCN
        assert m.edge_cost(0, 63, 64) == 16.0
        assert m.is_cross_slice(7, 8) and not m.is_cross_slice(0, 7)

    def test_torus_dims_shorten_intra_slice_paths(self):
        from stochastic_gradient_push_tpu.planner import InterconnectModel

        ring = InterconnectModel(slice_size=16)
        torus = InterconnectModel(slice_size=16, torus=(4, 4))
        # rank 0 -> 10 = (row 2, col 2) on the 4x4 torus: 2+2 hops,
        # vs min(10, 6) on the 1-D ring
        assert ring.edge_cost(0, 10, 16) == 6.0
        assert torus.edge_cost(0, 10, 16) == 4.0
        with pytest.raises(ValueError, match="do not tile"):
            InterconnectModel(slice_size=16, torus=(4, 3))

    def test_uniform_model_reproduces_ring_hop_ranking(self):
        """With no fabric structure the priced cost IS the old hop cost:
        rankings on a uniform fabric are unchanged by construction."""
        for c in score_candidates(16, peer_counts=(1, 2)):
            assert c.priced_cost == pytest.approx(c.hop_cost)
            assert c.dcn_per_efold == 0.0

    def test_make_interconnect_resolves_defaults(self):
        from stochastic_gradient_push_tpu.planner import (
            DEFAULT_DCN_COST, make_interconnect)

        assert make_interconnect() is None     # no fabric flags: uniform
        m = make_interconnect(slice_size=4)
        assert m.slice_size == 4 and m.dcn_cost == DEFAULT_DCN_COST
        assert make_interconnect(slice_size=4, dcn_cost=32.0).dcn_cost \
            == 32.0
        # a DCN weight with no slice structure could never apply — reject
        # rather than silently price a uniform fabric
        with pytest.raises(ValueError, match="slice_size"):
            make_interconnect(dcn_cost=32.0)

    def test_uniform_fabric_torus_must_tile_the_world(self):
        from stochastic_gradient_push_tpu.planner import InterconnectModel

        m = InterconnectModel(torus=(4, 4))   # legal: world checked later
        assert m.torus_hops(0, 10, 16) == 4   # (2, 2) on the 4x4 torus
        with pytest.raises(ValueError, match="do not tile"):
            m.edge_cost(0, 16, 64)            # 4*4 != 64: no silent 0-hop


class TestHierarchicalRanking:
    def _fabric(self, dcn=16.0):
        from stochastic_gradient_push_tpu.planner import InterconnectModel
        return InterconnectModel(slice_size=8, dcn_cost=dcn)

    def test_dcn_dominant_fabric_flips_the_world64_winner(self):
        cons = PlanConstraints(interconnect=self._fabric())
        plan = plan_for(64, ppi=1, constraints=cons)
        assert plan.topology == "hierarchical" and plan.slice_size == 8
        assert "DCN" in plan.rationale
        # the stamped ranking shows flat candidates priced higher
        flat = [r for r in plan.ranking if r["topology"] != "hierarchical"]
        assert flat and all(r["priced_cost"] > plan.ranking[0]["priced_cost"]
                            for r in flat)

    def test_uniform_fabric_keeps_flat_winner(self):
        plan = plan_for(64, ppi=1)
        assert plan.topology != "hierarchical"
        # hierarchical is scored (present) but loses without DCN weight
        names = {r["topology"] for r in plan.ranking}
        assert "hierarchical" in names

    def test_mildly_priced_dcn_does_not_flip(self):
        # at DCN == ICI the hierarchical intra-slice allreduce is pure
        # overhead; the flip threshold is what the model exists to find
        plan = plan_for(64, ppi=1, constraints=PlanConstraints(
            interconnect=self._fabric(dcn=1.0)))
        assert plan.topology != "hierarchical"

    def test_forced_hierarchical_checks_and_stamps_slice(self):
        from stochastic_gradient_push_tpu.topology import HierarchicalGraph

        plan = check_topology(64, HierarchicalGraph, ppi=1,
                              interconnect=self._fabric())
        assert not plan.auto and plan.topology == "hierarchical"
        assert plan.slice_size == 8 and not plan.below_floor()
        assert not plan.warnings

    def test_fabric_slice_size_overrides_default_decomposition(self):
        from stochastic_gradient_push_tpu.planner import InterconnectModel
        from stochastic_gradient_push_tpu.topology import HierarchicalGraph

        plan = check_topology(
            64, HierarchicalGraph, ppi=1,
            interconnect=InterconnectModel(slice_size=16, dcn_cost=16.0))
        assert plan.slice_size == 16
        g = plan.graph_class(64, peers_per_itr=1)
        assert g.slice_size == 16

    def test_plan_dict_roundtrips_with_interconnect(self):
        plan = plan_for(64, ppi=1, constraints=PlanConstraints(
            interconnect=self._fabric()))
        d = json.loads(json.dumps(plan.to_dict()))
        assert d["slice_size"] == 8
        assert d["interconnect"]["dcn_cost"] == 16.0

    def test_resolve_topology_threads_interconnect(self):
        log = _FakeLog()
        plan = resolve_topology(64, topology="auto",
                                interconnect=self._fabric(), log=log)
        assert plan.topology == "hierarchical"
        assert any("hierarchical" in m for m in log.infos)

    def test_dpsgd_auto_plan_never_selects_irregular_hierarchical(self):
        # D-PSGD needs doubly-stochastic mixing; the hierarchical
        # schedule is irregular, so even on a DCN-dominant fabric the
        # planner must rank it out rather than recommend a topology the
        # algorithm would reject at launch
        plan = plan_for(64, ppi=1, algorithm="dpsgd",
                        constraints=PlanConstraints(
                            interconnect=self._fabric()))
        assert plan.topology != "hierarchical"

    def test_dpsgd_forced_hierarchical_rejected_at_plan_time(self):
        from stochastic_gradient_push_tpu.topology import HierarchicalGraph

        with pytest.raises(ValueError, match="regular"):
            check_topology(64, HierarchicalGraph, ppi=1, algorithm="dpsgd",
                           interconnect=self._fabric())

    def test_faulted_runs_never_plan_hierarchical(self):
        # PushSumGossip rejects hierarchical schedules under fault
        # injection (the grouped psum has no per-edge mask); even on a
        # DCN-dominant fabric the planner must rank hierarchical out
        # instead of crashing the launch
        cons = PlanConstraints(interconnect=self._fabric(), faults=True)
        plan = plan_for(64, ppi=1, constraints=cons)
        assert plan.topology != "hierarchical"

    def test_overlap_runs_may_plan_hierarchical(self):
        # overlap composes with the hierarchical round now (the delegate
        # share defers; the intra psum runs at consume), so the overlap
        # constraint no longer filters the ranking: on a DCN-dominant
        # fabric an overlap run gets the same winner as a sync run
        cons = PlanConstraints(interconnect=self._fabric(), overlap=True)
        plan = plan_for(64, ppi=1, constraints=cons)
        sync = plan_for(64, ppi=1, constraints=PlanConstraints(
            interconnect=self._fabric()))
        assert plan.topology == sync.topology == "hierarchical"

    def test_forced_hierarchical_rejected_for_faults_only(self):
        from stochastic_gradient_push_tpu.topology import HierarchicalGraph

        with pytest.raises(ValueError, match="flat-schedule|flat "
                                             "topology"):
            check_topology(64, HierarchicalGraph, ppi=1,
                           interconnect=self._fabric(), faults=True)
        # forced hierarchical under overlap is accepted (and stays
        # hierarchical)
        plan = check_topology(64, HierarchicalGraph, ppi=1,
                              interconnect=self._fabric(), overlap=True)
        assert plan.topology == "hierarchical"

    def test_hierarchical_plan_graph_class_keeps_its_name(self):
        # Plan.graph_class binds slice_size via functools.partial; the
        # recovery policy resolves it back through topology_name
        plan = plan_for(64, ppi=1, constraints=PlanConstraints(
            interconnect=self._fabric()))
        assert topology_name(plan.graph_class) == "hierarchical"


# -- satellite: spectral-gap memoization ------------------------------------

class TestSpectralGapCache:
    def test_identical_tables_hit_the_cache(self):
        from stochastic_gradient_push_tpu.analysis import (
            spectral_gap_cache_clear, spectral_gap_cache_info)

        spectral_gap_cache_clear()
        s1 = build_schedule(RingGraph(16, peers_per_itr=1))
        s2 = build_schedule(RingGraph(16, peers_per_itr=1))  # fresh object
        g1, g2 = spectral_gap(s1), spectral_gap(s2)
        assert g1 == g2
        info = spectral_gap_cache_info()
        # the LRU bound (PR 12) grew the info payload: evict counter +
        # configured max ride alongside the original hit/miss/size
        assert (info["hits"], info["misses"], info["size"],
                info["evictions"]) == (1, 1, 1, 0)
        assert info["max"] >= 1

    def test_different_tables_miss(self):
        from stochastic_gradient_push_tpu.analysis import (
            schedule_fingerprint, spectral_gap_cache_clear,
            spectral_gap_cache_info)

        spectral_gap_cache_clear()
        a = build_schedule(RingGraph(8, peers_per_itr=1))
        b = build_schedule(DynamicDirectedExponentialGraph(8))
        assert schedule_fingerprint(a) != schedule_fingerprint(b)
        spectral_gap(a), spectral_gap(b)
        assert spectral_gap_cache_info()["misses"] == 2

    def test_repeated_plan_for_stops_recomputing_eigenvalues(self):
        """The satellite's pin: a second identical plan_for call in the
        same process does zero new eigenvalue solves."""
        from stochastic_gradient_push_tpu.analysis import (
            spectral_gap_cache_clear, spectral_gap_cache_info)

        spectral_gap_cache_clear()
        plan_for(32)
        first = spectral_gap_cache_info()
        assert first["misses"] > 0
        plan_for(32)
        second = spectral_gap_cache_info()
        assert second["misses"] == first["misses"]   # all cache hits
        assert second["hits"] > first["hits"]
