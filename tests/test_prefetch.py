"""DevicePrefetcher (data/prefetch.py): overlap H2D with compute."""

import os

import jax
import numpy as np

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from jax.sharding import PartitionSpec as P

from stochastic_gradient_push_tpu.data import (DistributedSampler,
                                               ShardedLoader)
from stochastic_gradient_push_tpu.data.prefetch import DevicePrefetcher
from stochastic_gradient_push_tpu.parallel import GOSSIP_AXIS, \
    make_gossip_mesh


def _loader(world=8, batch=2, n=64):
    rng = np.random.default_rng(0)
    images = rng.normal(size=(n, 4, 4, 3)).astype(np.float32)
    labels = rng.integers(0, 4, n).astype(np.int32)
    sampler = DistributedSampler(n, world)
    return ShardedLoader(images, labels, batch, sampler), sampler


def test_prefetch_yields_same_batches_sharded():
    world = 8
    mesh = make_gossip_mesh(world)
    loader, sampler = _loader(world)
    sampler.set_epoch(0)
    plain = [(np.asarray(x), np.asarray(y)) for x, y in loader]
    sampler.set_epoch(0)
    pf = DevicePrefetcher(loader, mesh, P(GOSSIP_AXIS))
    assert len(pf) == len(loader)
    fetched = list(pf)
    assert len(fetched) == len(plain)
    for (x0, y0), (x1, y1) in zip(plain, fetched):
        # already on device with the gossip sharding
        assert isinstance(x1, jax.Array) and len(x1.sharding.device_set) \
            == world
        np.testing.assert_array_equal(x0, np.asarray(x1))
        np.testing.assert_array_equal(y0, np.asarray(y1))


def test_prefetch_early_abandon_does_not_deadlock():
    world = 8
    mesh = make_gossip_mesh(world)
    loader, sampler = _loader(world, n=128)
    sampler.set_epoch(0)
    pf = iter(DevicePrefetcher(loader, mesh, P(GOSSIP_AXIS), depth=1))
    next(pf)
    pf.close()  # the generator's finally stops the worker thread
    # a second pass works fine after abandonment
    sampler.set_epoch(0)
    n = sum(1 for _ in DevicePrefetcher(loader, mesh, P(GOSSIP_AXIS)))
    assert n == len(loader)


def test_prefetch_propagates_loader_errors():
    import pytest

    mesh = make_gossip_mesh(8)

    class Boom:
        def __iter__(self):
            yield (np.zeros((8, 1, 4, 4, 3), np.float32),
                   np.zeros((8, 1), np.int32))
            raise RuntimeError("loader died")

        def __len__(self):
            return 2

    pf = DevicePrefetcher(Boom(), mesh, P(GOSSIP_AXIS))
    it = iter(pf)
    next(it)
    with pytest.raises(RuntimeError, match="loader died"):
        next(it)
