"""Utility-layer tests: flatten/communicate, watchdog, discovery parsing."""

import time

import jax
import jax.numpy as jnp
import numpy as np

from stochastic_gradient_push_tpu.parallel.discovery import (
    ClusterInfo,
    _first_slurm_host,
    discover,
)
from stochastic_gradient_push_tpu.utils import (
    StepWatchdog,
    communicate,
    flatten_tensors,
    global_norm,
    group_by_dtype,
    unflatten_tensors,
)


def _tree():
    return {"a": jnp.arange(6.0).reshape(2, 3),
            "b": [jnp.ones((4,), jnp.float32),
                  jnp.asarray([1, 2, 3], jnp.int32)]}


def test_flatten_roundtrip():
    tree = _tree()
    flat, unravel = flatten_tensors(tree)
    assert flat.ndim == 1
    restored = unflatten_tensors(flat, unravel)
    for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_group_by_dtype():
    groups = group_by_dtype(_tree())
    assert set(groups) == {np.dtype(np.float32), np.dtype(np.int32)}
    assert len(groups[np.dtype(np.float32)]) == 2
    assert len(groups[np.dtype(np.int32)]) == 1


def test_communicate_applies_op_per_dtype():
    tree = {"x": jnp.ones((3,)), "y": jnp.full((2, 2), 2.0)}
    out = communicate(tree, lambda flat: flat * 10)
    np.testing.assert_allclose(np.asarray(out["x"]), 10 * np.ones(3))
    np.testing.assert_allclose(np.asarray(out["y"]), 20 * np.ones((2, 2)))
    # structure preserved
    assert jax.tree.structure(out) == jax.tree.structure(tree)


def test_global_norm():
    tree = {"a": jnp.asarray([3.0]), "b": jnp.asarray([4.0])}
    np.testing.assert_allclose(float(global_norm(tree)), 5.0)


def test_watchdog_fires_on_slow_step_and_not_on_fast():
    wd = StepWatchdog(timeout=0.2)
    with wd.step():
        pass
    time.sleep(0.3)
    assert not wd.timed_out

    wd2 = StepWatchdog(timeout=0.1)
    with wd2.step():
        time.sleep(0.35)
    assert wd2.timed_out


def test_discover_reports_cpu_mesh():
    info = discover()
    assert isinstance(info, ClusterInfo)
    assert info.platform == "cpu"
    assert info.global_device_count >= 8
    assert not info.is_multihost


def test_slurm_nodelist_first_host():
    assert _first_slurm_host("tpu-pod-[003-007,010]") == "tpu-pod-003"
    assert _first_slurm_host("a-1,b-2") == "a-1"
    assert _first_slurm_host("node[001-004]") == "node001"
    assert _first_slurm_host("single") == "single"


def _captured_initialize(monkeypatch):
    """Stub jax.distributed.initialize and return the capture dict."""
    got = {}

    def fake_init(coordinator_address=None, num_processes=None,
                  process_id=None):
        got.update(coordinator_address=coordinator_address,
                   num_processes=num_processes, process_id=process_id)

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    return got


def test_mpi_env_bootstrap(monkeypatch):
    """OpenMPI launcher env (reference --backend mpi, gossip_sgd.py:600-602)
    derives rank/size; COORDINATOR_ADDRESS wins over HOSTNAME."""
    from stochastic_gradient_push_tpu.parallel.discovery import (
        initialize_multihost)

    for var in ("SLURM_PROCID", "SLURM_NTASKS"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "3")
    monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "4")
    monkeypatch.setenv("COORDINATOR_ADDRESS", "head-node:40123")
    got = _captured_initialize(monkeypatch)
    initialize_multihost()
    assert got == {"coordinator_address": "head-node:40123",
                   "num_processes": 4, "process_id": 3}

    # reference fallbacks: OMPI_UNIVERSE_SIZE for world, HOSTNAME for the
    # coordinator, default port appended to a bare host
    monkeypatch.delenv("OMPI_COMM_WORLD_SIZE")
    monkeypatch.delenv("COORDINATOR_ADDRESS")
    monkeypatch.setenv("OMPI_UNIVERSE_SIZE", "8")
    monkeypatch.setenv("HOSTNAME", "mpi-head")
    got = _captured_initialize(monkeypatch)
    initialize_multihost()
    assert got == {"coordinator_address": "mpi-head:40100",
                   "num_processes": 8, "process_id": 3}


def test_mpi_multinode_without_coordinator_fails_fast(monkeypatch):
    """A multi-node mpirun with no COORDINATOR_ADDRESS must raise, not
    let every rank dial its own hostname and hang in initialize."""
    import pytest

    from stochastic_gradient_push_tpu.parallel.discovery import (
        initialize_multihost)

    import socket

    for var in ("SLURM_PROCID", "SLURM_NTASKS", "COORDINATOR_ADDRESS"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.delenv("HOSTNAME", raising=False)
    monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "2")
    monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "4")
    monkeypatch.setenv("OMPI_COMM_WORLD_LOCAL_SIZE", "2")
    _captured_initialize(monkeypatch)
    with pytest.raises(RuntimeError, match="COORDINATOR_ADDRESS"):
        initialize_multihost()

    # env HOSTNAME == this machine's own name: still a self-dial → raise
    monkeypatch.setenv("HOSTNAME", socket.gethostname())
    with pytest.raises(RuntimeError, match="COORDINATOR_ADDRESS"):
        initialize_multihost()

    # mpirun -x HOSTNAME: rank 0's hostname propagated to a remote node
    # differs from the machine's own name → trusted as the coordinator
    monkeypatch.setenv("HOSTNAME", "head-node-from-rank0")
    got = _captured_initialize(monkeypatch)
    initialize_multihost()
    assert got["coordinator_address"] == "head-node-from-rank0:40100"

    # single-node (local size == world size): HOSTNAME fallback is fine
    monkeypatch.setenv("OMPI_COMM_WORLD_LOCAL_SIZE", "4")
    monkeypatch.setenv("HOSTNAME", "onebox")
    got = _captured_initialize(monkeypatch)
    initialize_multihost()
    assert got["coordinator_address"] == "onebox:40100"


def test_slurm_env_wins_over_mpi(monkeypatch):
    """When both schedulers' vars are present, SLURM keeps priority (the
    reference selects by --backend; auto-detection must be deterministic)."""
    from stochastic_gradient_push_tpu.parallel.discovery import (
        initialize_multihost)

    monkeypatch.setenv("SLURM_PROCID", "1")
    monkeypatch.setenv("SLURM_NTASKS", "2")
    monkeypatch.setenv("SLURM_JOB_NODELIST", "single")
    monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "7")
    monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "9")
    got = _captured_initialize(monkeypatch)
    initialize_multihost()
    assert got["process_id"] == 1
    assert got["num_processes"] == 2


def test_mpi_env_multihost_autodetect(monkeypatch):
    from stochastic_gradient_push_tpu.run.gossip_sgd import _multihost_env

    for var in ("JAX_COORDINATOR_ADDRESS", "TPU_WORKER_HOSTNAMES",
                "SLURM_NTASKS", "OMPI_COMM_WORLD_SIZE",
                "OMPI_UNIVERSE_SIZE"):
        monkeypatch.delenv(var, raising=False)
    assert not _multihost_env()
    monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "2")
    assert _multihost_env()


def test_profiler_guard_times_out_without_hanging():
    """The tunnel-safe profiler guard (utils/profiling.py): a hung
    profiler call must return False within the timeout instead of
    stalling the run (round-4's capture lost 600s to exactly this)."""
    import time

    from stochastic_gradient_push_tpu.utils.profiling import (
        _call_with_timeout)

    t0 = time.monotonic()
    ok = _call_with_timeout(lambda: time.sleep(30), timeout=0.2,
                            what="test")
    assert not ok
    assert time.monotonic() - t0 < 5

    # a fast call passes through, and its exception surfaces
    assert _call_with_timeout(lambda: None, timeout=5, what="test")
    import pytest

    with pytest.raises(RuntimeError):
        _call_with_timeout(
            lambda: (_ for _ in ()).throw(RuntimeError("x")),
            timeout=5, what="test")


def test_profiler_guard_late_completion_callback():
    """A call declared hung that later completes must trigger the
    compensating callback (e.g. stopping a late-started trace)."""
    import threading
    import time

    from stochastic_gradient_push_tpu.utils.profiling import (
        _call_with_timeout)

    compensated = threading.Event()
    ok = _call_with_timeout(lambda: time.sleep(0.5), timeout=0.1,
                            what="test",
                            on_late_completion=compensated.set)
    assert not ok
    assert compensated.wait(5), "late completion never compensated"
