"""Fleet observability plane: multi-stream merge semantics.

The supervisor's single-stream :class:`EventTailer` contract is pinned
in ``test_supervise.py``; this module covers what the fleet aggregator
layers on top — N tailers merged through a per-stream watermark:

* **concurrent writers** — interleaved appends across skewed host
  streams release in event-time order, with the frontier withholding
  events a slower stream could still precede;
* **same-mtime rotation** — a rotated stream (new inode, same size,
  same mtime) restarts from byte 0: the reset is inode-keyed, never
  mtime- or size-keyed;
* **straggler silent mid-merge** — a host that stops emitting is
  excluded from the frontier after ``silence_s`` of *event time*, so a
  dead host cannot stall the fleet view, and its late backfill is
  counted and consumed rather than dropped.
"""

import json
import os

from stochastic_gradient_push_tpu.supervise import EventTailer
from stochastic_gradient_push_tpu.telemetry import (
    COORDINATOR_EVENTS_FILE,
    EVENTS_FILE,
)
from stochastic_gradient_push_tpu.telemetry.aggregate import (
    FleetAggregator,
    SloThresholds,
)


def _append(path, *events):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "a") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")


def _ev(t, kind="health", host=None, **data):
    ev = {"v": 1, "t": round(t, 6), "kind": kind, "data": data}
    if host is not None:
        ev["host"] = host
    return ev


def _host_stream(run_dir, host):
    return os.path.join(run_dir, f"host{host}", EVENTS_FILE)


def _agg(run_dir, **kw):
    kw.setdefault("write_alerts", False)
    return FleetAggregator(str(run_dir), **kw)


class TestSameMtimeRotation:
    def test_rotation_detected_by_inode_not_mtime_or_size(self, tmp_path):
        path = tmp_path / EVENTS_FILE
        _append(str(path), _ev(0.1, step=1))
        tailer = EventTailer(str(path))
        assert [e["data"]["step"] for e in tailer.poll()] == [1]
        old = os.stat(path)

        # rotate: a NEW file takes the name with byte-identical size and
        # the same mtime — only the inode differs.  (A relaunched run
        # recreating events.jsonl within the filesystem's mtime
        # granularity looks exactly like this.)
        repl = tmp_path / "rotated.jsonl"
        _append(str(repl), _ev(0.2, step=2))
        assert os.stat(repl).st_size == old.st_size
        os.utime(repl, ns=(old.st_atime_ns, old.st_mtime_ns))
        os.replace(repl, path)
        st = os.stat(path)
        assert st.st_ino != old.st_ino
        assert (st.st_size, st.st_mtime_ns) == (old.st_size,
                                                old.st_mtime_ns)

        # a position-keyed or mtime-keyed reader would see "no change"
        # and deliver nothing; the inode-keyed reset re-reads from 0
        assert [e["data"]["step"] for e in tailer.poll()] == [2]
        assert tailer.skipped == 0

    def test_rotation_mid_merge_rewinds_one_stream_only(self, tmp_path):
        h0, h1 = _host_stream(tmp_path, 0), _host_stream(tmp_path, 1)
        _append(h0, _ev(0.1, step=1), _ev(0.2, step=2))
        _append(h1, _ev(0.2, step=1))
        agg = _agg(tmp_path)
        agg.poll()

        # host0 rotates in place with the same size + mtime
        old = os.stat(h0)
        repl = os.path.join(os.path.dirname(h0), "repl.jsonl")
        _append(repl, _ev(0.3, step=3))
        pad = old.st_size - os.stat(repl).st_size
        assert pad > 0
        with open(repl, "a") as f:   # newline padding: size-identical
            f.write("\n" * pad)
        os.utime(repl, ns=(old.st_atime_ns, old.st_mtime_ns))
        os.replace(repl, h0)
        assert os.stat(h0).st_ino != old.st_ino

        _append(h1, _ev(0.4, step=2))
        total = agg.drain()
        agg.close()
        # every event from both generations of host0 plus host1's two:
        # the rewind re-read only the rotated stream, dropped nothing
        assert agg.emitted == 5
        assert total == 2


class TestConcurrentWriters:
    def test_interleaved_appends_release_in_event_time_order(
            self, tmp_path):
        h0, h1 = _host_stream(tmp_path, 0), _host_stream(tmp_path, 1)
        coord = os.path.join(str(tmp_path), COORDINATOR_EVENTS_FILE)
        agg = _agg(tmp_path)

        released_t = []
        orig = agg._consume

        def record(ev):
            released_t.append(float(ev["t"]))
            orig(ev)

        agg._consume = record

        # round 1: skewed appends — host1 runs ahead of host0
        _append(h0, _ev(0.10, host=0), _ev(0.30, host=0))
        _append(h1, _ev(0.25, host=1), _ev(0.50, host=1))
        _append(coord, _ev(0.40, kind="rendezvous", phase="call"))
        agg.poll()
        # frontier = min watermark = host0 @ 0.30: the 0.40 and 0.50
        # events stay buffered — host0 could still emit before them
        assert released_t == [0.10, 0.25, 0.30]

        # round 2: host0 catches up, but the coordinator (quiet since
        # 0.40, still within silence_s) now gates the frontier — 0.50
        # stays buffered behind a stream that could yet precede it
        _append(h0, _ev(0.60, host=0))
        _append(h1, _ev(0.55, host=1))
        agg.poll()
        assert released_t == [0.10, 0.25, 0.30, 0.40]

        agg.drain()
        agg.close()
        assert released_t == sorted(released_t)
        assert agg.emitted == 7
        assert agg.late_events == 0
        assert agg.streams == [
            COORDINATOR_EVENTS_FILE,
            os.path.join("host0", EVENTS_FILE),
            os.path.join("host1", EVENTS_FILE)]

    def test_partial_line_from_live_writer_never_splits_an_event(
            self, tmp_path):
        # one writer flushes mid-line while the merge polls: the torn
        # tail must neither parse nor poison later reads
        h0, h1 = _host_stream(tmp_path, 0), _host_stream(tmp_path, 1)
        _append(h1, _ev(0.1, host=1))
        line = json.dumps(_ev(0.15, host=0))
        os.makedirs(os.path.dirname(h0), exist_ok=True)
        with open(h0, "w") as f:
            f.write(line[:12])
        agg = _agg(tmp_path)
        agg.poll()
        # the torn line is buffered unparsed; host0 has produced no
        # complete event yet, so it has no watermark and cannot gate —
        # h1's event releases
        assert agg.emitted == 1
        with open(h0, "a") as f:
            f.write(line[12:] + "\n")
        agg.drain()
        agg.close()
        assert agg.emitted == 2
        assert agg.late_events == 0  # the joined event arrived whole
        tailers = [s.tailer for s in agg._streams.values()]
        assert sum(t.skipped for t in tailers) == 0


class TestStragglerSilence:
    def test_silent_stream_leaves_frontier_and_backfill_is_late(
            self, tmp_path):
        h0, h1 = _host_stream(tmp_path, 0), _host_stream(tmp_path, 1)
        thr = SloThresholds(heartbeat_silence_s=10.0)  # isolate merge
        agg = _agg(tmp_path, silence_s=0.5, thresholds=thr)

        _append(h0, _ev(0.1, host=0))
        _append(h1, _ev(0.1, host=1))
        agg.poll()
        assert agg.emitted == 2

        # host1 dies mid-merge; host0 keeps emitting well past
        # silence_s of event time
        _append(h0, _ev(0.4, host=0), _ev(0.9, host=0))
        agg.poll()
        # host1's watermark (0.1) lags the fleet max (0.9) by more than
        # silence_s: it is dropped from the frontier and host0's whole
        # tail releases — the dead host did not stall the merge
        assert agg.emitted == 4
        assert agg.late_events == 0

        # the straggler backfills BEHIND the released frontier: counted
        # as late, still consumed — totals stay exact
        _append(h1, _ev(0.5, host=1))
        agg.poll()
        agg.close()
        assert agg.emitted == 5
        assert agg.late_events == 1

    def test_slow_but_live_stream_still_gates_the_frontier(
            self, tmp_path):
        # the dual: within silence_s, a slow host DOES hold events back
        # (withholding, not reordering, is the merge's failure mode)
        h0, h1 = _host_stream(tmp_path, 0), _host_stream(tmp_path, 1)
        agg = _agg(tmp_path, silence_s=5.0)
        _append(h0, _ev(0.1, host=0))
        _append(h1, _ev(0.1, host=1), _ev(2.0, host=1))
        agg.poll()
        assert agg.emitted == 2          # 2.0 buffered, not released
        _append(h0, _ev(2.5, host=0))
        agg.poll()
        assert agg.emitted == 3          # 2.5 now gated by h1 @ 2.0
        agg.drain()
        agg.close()
        assert agg.emitted == 4
        assert agg.late_events == 0
