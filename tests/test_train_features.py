"""Label smoothing, cosine LR, and gradient accumulation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stochastic_gradient_push_tpu.algorithms import sgp
from stochastic_gradient_push_tpu.models import TinyMLP
from stochastic_gradient_push_tpu.parallel import GOSSIP_AXIS, make_gossip_mesh
from stochastic_gradient_push_tpu.topology import (
    NPeerDynamicDirectedExponentialGraph,
    build_schedule,
)
from stochastic_gradient_push_tpu.train import (
    CosineLRSchedule,
    LRSchedule,
    build_train_step,
    init_train_state,
    one_hot,
    replicate_state,
    sgd,
    shard_train_step,
)

WORLD, BATCH, CLASSES, IMG = 8, 8, 4, 8


def test_label_smoothing_targets():
    t = one_hot(jnp.asarray([1]), 4, label_smoothing=0.1)
    np.testing.assert_allclose(
        np.asarray(t)[0], [0.025, 0.925, 0.025, 0.025], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(t).sum(), 1.0, rtol=1e-6)


def test_cosine_schedule_shape():
    s = CosineLRSchedule(ref_lr=0.1, batch_size=256, world_size=32,
                         total_epochs=90, warmup=True)
    target = 0.1 * 256 * 32 / 256
    ipe = 100
    # warmup ramps from ref_lr
    assert float(s(0, 0, ipe)) < target / 2
    # mid-training is between 0 and target, decreasing
    mid = float(s(45, 0, ipe))
    late = float(s(80, 0, ipe))
    assert 0 < late < mid < target
    # end decays to ~0
    assert float(s(89, 99, ipe)) < 0.01 * target


@pytest.fixture(scope="module")
def mesh():
    return make_gossip_mesh(WORLD)


def test_grad_accum_matches_full_batch(mesh):
    """grad_accum=4 must produce the same update as the full batch (modulo
    BN statistics, absent in TinyMLP)."""
    model = TinyMLP(num_classes=CLASSES)
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    tx = sgd(momentum=0.9, weight_decay=1e-4)
    lrs = LRSchedule(ref_lr=0.1, batch_size=BATCH, world_size=WORLD,
                     decay_schedule={}, warmup=False)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(WORLD, BATCH, IMG, IMG, 3)).astype(np.float32)
    y = rng.integers(0, CLASSES, size=(WORLD, BATCH)).astype(np.int32)

    states = []
    for accum in (1, 4):
        alg = sgp(sched, GOSSIP_AXIS)
        step = build_train_step(model, alg, tx, lrs, itr_per_epoch=10,
                                num_classes=CLASSES, grad_accum=accum)
        fn = shard_train_step(step, mesh)
        st = replicate_state(
            init_train_state(model, jax.random.PRNGKey(0),
                             jnp.zeros((BATCH, IMG, IMG, 3)), tx, alg),
            WORLD)
        st, metrics = fn(st, x, y)
        jax.block_until_ready(st)
        states.append((st, float(np.mean(np.asarray(metrics["loss"])))))

    (s1, l1), (s4, l4) = states
    np.testing.assert_allclose(l1, l4, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s4.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_grad_accum_divisibility_error(mesh):
    model = TinyMLP(num_classes=CLASSES)
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    tx = sgd()
    lrs = LRSchedule(ref_lr=0.1, batch_size=BATCH, world_size=WORLD,
                     decay_schedule={}, warmup=False)
    alg = sgp(sched, GOSSIP_AXIS)
    step = build_train_step(model, alg, tx, lrs, itr_per_epoch=10,
                            num_classes=CLASSES, grad_accum=3)
    fn = shard_train_step(step, mesh)
    st = replicate_state(
        init_train_state(model, jax.random.PRNGKey(0),
                         jnp.zeros((BATCH, IMG, IMG, 3)), tx, alg), WORLD)
    x = np.zeros((WORLD, BATCH, IMG, IMG, 3), np.float32)
    y = np.zeros((WORLD, BATCH), np.int32)
    with pytest.raises(ValueError, match="divisible"):
        fn(st, x, y)
