"""Transformer LM: attention-backend equivalence and gossip-DP × ring-SP
end-to-end training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stochastic_gradient_push_tpu.algorithms import sgp
from stochastic_gradient_push_tpu.data.lm import (
    lm_batches,
    synthetic_lm_corpus,
)
from stochastic_gradient_push_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
)
from stochastic_gradient_push_tpu.parallel import GOSSIP_AXIS
from stochastic_gradient_push_tpu.topology import (
    DynamicDirectedExponentialGraph,
    build_schedule,
)
from stochastic_gradient_push_tpu.train import LRSchedule, sgd
from stochastic_gradient_push_tpu.train.lm import (
    SEQ_AXIS,
    build_lm_train_step,
    lm_loss,
    make_dp_sp_mesh,
    shard_lm_train_step,
)
from stochastic_gradient_push_tpu.train.state import TrainState

VOCAB, D, LAYERS, HEADS = 64, 32, 2, 4
DP, SP = 4, 2
BATCH, SEQ = 2, 32


def small_cfg(attn_impl="full", seq_axis=None):
    return TransformerConfig(
        vocab_size=VOCAB, d_model=D, n_layers=LAYERS, n_heads=HEADS,
        d_ff=64, max_len=SEQ, attn_impl=attn_impl, attn_block_size=8,
        seq_axis=seq_axis)


def test_attention_backends_agree():
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, VOCAB, size=(2, SEQ)).astype(np.int32)
    full = TransformerLM(small_cfg("full"))
    variables = full.init(jax.random.PRNGKey(0), tokens)
    out_full = full.apply(variables, tokens)
    for impl in ("blockwise", "flash"):
        other = TransformerLM(small_cfg(impl))
        out = other.apply(variables, tokens)
        np.testing.assert_allclose(np.asarray(out_full), np.asarray(out),
                                   rtol=2e-4, atol=2e-4, err_msg=impl)
    # asymmetric flash blocks (block_k != block_q) are the same function
    asym_cfg = TransformerConfig(
        vocab_size=VOCAB, d_model=D, n_layers=LAYERS, n_heads=HEADS,
        d_ff=64, max_len=SEQ, attn_impl="flash", attn_block_size=16,
        attn_block_k=8)
    out = TransformerLM(asym_cfg).apply(variables, tokens)
    np.testing.assert_allclose(np.asarray(out_full), np.asarray(out),
                               rtol=2e-4, atol=2e-4, err_msg="flash asym")


def test_ring_sequence_parallel_forward_matches_single_device():
    """The seq-sharded ring forward must equal the single-device full
    forward on the same weights and tokens."""
    from jax.sharding import PartitionSpec as P

    mesh = make_dp_sp_mesh(1, 8)
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, VOCAB, size=(BATCH, SEQ)).astype(np.int32)

    full = TransformerLM(small_cfg("full"))
    variables = full.init(jax.random.PRNGKey(0), tokens)
    want = np.asarray(full.apply(variables, tokens))

    ring = TransformerLM(small_cfg("ring", seq_axis=SEQ_AXIS))
    block = SEQ // 8
    # [B, T] → [1, 8, B, block]
    sharded_tokens = tokens.reshape(BATCH, 8, block).transpose(1, 0, 2)
    sharded_tokens = sharded_tokens[None]

    def fwd(params, toks):
        return ring.apply({"params": params}, toks[0, 0])[None, None]

    f = jax.jit(jax.shard_map(
        fwd, mesh=mesh,
        in_specs=(P(), P(GOSSIP_AXIS, SEQ_AXIS)),
        out_specs=P(GOSSIP_AXIS, SEQ_AXIS)))
    out = np.asarray(f(variables["params"], sharded_tokens))
    # [1, 8, B, block, V] → [B, T, V]
    got = out[0].transpose(1, 0, 2, 3).reshape(BATCH, SEQ, VOCAB)
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)


def test_ring_flash_forward_matches_single_device():
    """attn_impl='ring_flash' (flash-kernel ticks, ops/ring_flash.py) is
    the same function as the single-device full forward."""
    from jax.sharding import PartitionSpec as P

    mesh = make_dp_sp_mesh(1, 8)
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, VOCAB, size=(BATCH, SEQ)).astype(np.int32)

    full = TransformerLM(small_cfg("full"))
    variables = full.init(jax.random.PRNGKey(0), tokens)
    want = np.asarray(full.apply(variables, tokens))

    rf = TransformerLM(small_cfg("ring_flash", seq_axis=SEQ_AXIS))
    block = SEQ // 8
    sharded_tokens = tokens.reshape(BATCH, 8, block).transpose(1, 0, 2)
    sharded_tokens = sharded_tokens[None]

    def fwd(params, toks):
        return rf.apply({"params": params}, toks[0, 0])[None, None]

    f = jax.jit(jax.shard_map(
        fwd, mesh=mesh,
        in_specs=(P(), P(GOSSIP_AXIS, SEQ_AXIS)),
        out_specs=P(GOSSIP_AXIS, SEQ_AXIS)))
    out = np.asarray(f(variables["params"], sharded_tokens))
    got = out[0].transpose(1, 0, 2, 3).reshape(BATCH, SEQ, VOCAB)
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)


@pytest.mark.slow
def test_gossip_dp_with_ring_sp_trains():
    """4 gossip replicas × 2 sequence shards: loss decreases well below the
    unigram entropy on a Markov corpus."""
    mesh = make_dp_sp_mesh(DP, SP)
    cfg = small_cfg("ring", seq_axis=SEQ_AXIS)
    model = TransformerLM(cfg)
    sched = build_schedule(DynamicDirectedExponentialGraph(DP,
                                                           peers_per_itr=1))
    alg = sgp(sched, GOSSIP_AXIS)
    tx = sgd(momentum=0.9, weight_decay=0.0)
    lrs = LRSchedule(ref_lr=0.5, batch_size=BATCH, world_size=DP * SP,
                     decay_schedule={}, warmup=False)
    step = build_lm_train_step(model, alg, tx, lrs, itr_per_epoch=100)
    train_fn = shard_lm_train_step(step, mesh)

    block = SEQ // SP
    # ring models reference the mesh axis, so init runs under shard_map
    from jax.sharding import PartitionSpec as P

    def init_fn(toks):
        variables = model.init(jax.random.PRNGKey(0), toks[0, 0])
        return jax.tree.map(lambda a: a[None], variables["params"])

    init_sharded = jax.jit(jax.shard_map(
        init_fn, mesh=mesh,
        in_specs=(P(GOSSIP_AXIS, SEQ_AXIS),),
        out_specs=P(GOSSIP_AXIS)))
    dummy = np.zeros((DP, SP, BATCH, block), np.int32)
    params = init_sharded(dummy)
    state = TrainState(
        step=jnp.zeros((DP,), jnp.int32),
        params=params,
        batch_stats={},
        opt_state=jax.tree.map(
            lambda a: jnp.broadcast_to(jnp.asarray(a)[None],
                                       (DP,) + jnp.shape(a)).copy(),
            tx.init(jax.tree.map(lambda a: a[0], params))),
        gossip=jax.tree.map(
            lambda a: jnp.broadcast_to(jnp.asarray(a)[None],
                                       (DP,) + jnp.shape(a)).copy(),
            alg.init(jax.tree.map(lambda a: a[0], params))))

    corpus = synthetic_lm_corpus(40_000, vocab_size=VOCAB, seed=2)
    losses = []
    for epoch in range(6):
        for tokens, targets in lm_batches(corpus, DP, SP, BATCH, SEQ,
                                          seed=epoch):
            state, metrics = train_fn(state, tokens, targets)
            jax.block_until_ready(state)
            losses.append(float(np.mean(np.asarray(metrics["loss"]))))

    first, last = np.mean(losses[:5]), np.mean(losses[-5:])
    assert last < first * 0.75, (first, last)
    # unigram entropy of a 64-symbol near-uniform marginal is ~4.1 nats;
    # learning the Markov structure must beat it
    assert last < 3.5, last


def test_lm_loss_matches_manual_ce():
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(2, 8, VOCAB)).astype(np.float32)
    targets = rng.integers(0, VOCAB, size=(2, 8)).astype(np.int32)
    got = float(lm_loss(jnp.asarray(logits), jnp.asarray(targets)))
    logp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits)))
    want = -np.mean([logp[b, t, targets[b, t]]
                     for b in range(2) for t in range(8)])
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_lm_cli_checkpoint_and_resume(tmp_path):
    """LM CLI saves its state+step atomically and resumes from it."""
    import numpy as np

    from stochastic_gradient_push_tpu.run.gossip_lm import main

    base = ["--world_size", "8", "--seq_len", "32", "--d_model", "32",
            "--n_layers", "1", "--n_heads", "4", "--d_ff", "32",
            "--vocab_size", "32", "--batch_size", "2",
            "--corpus_tokens", "20000", "--print_freq", "2",
            "--checkpoint_dir", str(tmp_path)]
    r1 = main(base + ["--num_steps", "4"])
    assert np.isfinite(r1["final_loss"])
    assert (tmp_path / "lm_checkpoint_r0_n8.ckpt").exists()

    r2 = main(base + ["--num_steps", "8", "--resume", "True"])
    assert np.isfinite(r2["final_loss"])
    csv = (tmp_path / "lm_out_n8.csv").read_text().splitlines()
    steps = [int(l.split(",")[0]) for l in csv[1:]]
    # rows from both runs, continuing past the first run's horizon
    assert 4 in steps and 8 in steps


@pytest.mark.slow
def test_lm_cli_orbax_backend_save_and_resume(tmp_path):
    """--ckpt_backend orbax through the LM CLI: per-step orbax saves with
    retention, then resume from the latest step."""
    import os

    import numpy as np

    from stochastic_gradient_push_tpu.run.gossip_lm import main

    base = ["--world_size", "8", "--seq_len", "32", "--d_model", "32",
            "--n_layers", "1", "--n_heads", "4", "--d_ff", "32",
            "--vocab_size", "32", "--batch_size", "2",
            "--corpus_tokens", "20000", "--print_freq", "2",
            "--ckpt_backend", "orbax", "--checkpoint_dir", str(tmp_path)]
    r1 = main(base + ["--num_steps", "4"])
    assert np.isfinite(r1["final_loss"])
    root = tmp_path / "lm_orbax_r0_n8"
    assert root.is_dir(), f"missing orbax root under {os.listdir(tmp_path)}"
    assert any(d.name == "4" for d in root.iterdir()), \
        "no step-4 orbax checkpoint"

    r2 = main(base + ["--num_steps", "8", "--resume", "True"])
    assert np.isfinite(r2["final_loss"])
    csv = (tmp_path / "lm_out_n8.csv").read_text().splitlines()
    steps = [int(l.split(",")[0]) for l in csv[1:]]
    assert 4 in steps and 8 in steps


@pytest.mark.slow
def test_scanned_lm_step_matches_sequential():
    """shard_scanned_lm_step(n) produces the same state and per-step losses
    as n individual dispatches, for plain dp and dp x sp (ring) layouts."""
    from stochastic_gradient_push_tpu.train.lm import (init_lm_state,
                                                       shard_scanned_lm_step)

    for ring in (False, True):
        sp = SP if ring else 1
        mesh = make_dp_sp_mesh(DP, SP) if ring else make_dp_sp_mesh(DP * SP,
                                                                    1)
        dp = DP if ring else DP * SP
        cfg = small_cfg("ring" if ring else "full",
                        seq_axis=SEQ_AXIS if ring else None)
        model = TransformerLM(cfg)
        alg = sgp(build_schedule(DynamicDirectedExponentialGraph(dp)),
                  GOSSIP_AXIS)
        tx = sgd(momentum=0.9, weight_decay=0.0)
        lrs = LRSchedule(ref_lr=0.1, batch_size=BATCH, world_size=dp,
                         decay_schedule={}, warmup=False)
        step = build_lm_train_step(
            model, alg, tx, lrs, itr_per_epoch=100,
            seq_axis=SEQ_AXIS if ring else None)
        seq_axis = SEQ_AXIS if ring else None
        train_fn = shard_lm_train_step(step, mesh, seq_axis=seq_axis)
        scan_fn = shard_scanned_lm_step(step, mesh, n_steps=3,
                                        seq_axis=seq_axis)
        block = SEQ // sp

        state = init_lm_state(model, mesh, alg, tx, dp=dp, sp=sp,
                              batch_size=BATCH, block_len=block,
                              seq_axis=seq_axis)
        state2 = jax.tree.map(jnp.copy, state)

        rng = np.random.default_rng(0)
        shape = ((dp, sp, BATCH, block) if ring
                 else (dp, BATCH, block))
        toks = rng.integers(0, VOCAB, size=(3,) + shape).astype(np.int32)
        tgts = rng.integers(0, VOCAB, size=(3,) + shape).astype(np.int32)

        seq_losses = []
        for i in range(3):
            state, m = train_fn(state, toks[i], tgts[i])
            jax.block_until_ready(state)
            seq_losses.append(np.asarray(m["loss"]))
        state2, ms = scan_fn(state2, toks, tgts)
        jax.block_until_ready(state2)

        np.testing.assert_allclose(
            np.stack(seq_losses, axis=1), np.asarray(ms["loss"]),
            rtol=1e-5, atol=1e-6)
        for a, b in zip(jax.tree.leaves(state.params),
                        jax.tree.leaves(state2.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=1e-6)


@pytest.mark.slow
def test_lm_cli_validation(tmp_path):
    """--val_frac holds out corpus tail; val_loss/val_ppl columns appear at
    --val_every steps and at the end, for both plain and ring layouts."""
    import numpy as np

    from stochastic_gradient_push_tpu.run.gossip_lm import main

    for extra in ([], ["--sp", "2"]):
        d = tmp_path / ("ring" if extra else "plain")
        r = main(["--world_size", "8", "--seq_len", "32", "--d_model",
                  "32", "--n_layers", "1", "--n_heads", "4", "--d_ff",
                  "32", "--vocab_size", "32", "--batch_size", "2",
                  "--corpus_tokens", "30000", "--print_freq", "2",
                  "--num_steps", "4", "--val_frac", "0.1",
                  "--val_every", "2", "--val_batches", "2",
                  "--checkpoint_dir", str(d)] + extra)
        assert np.isfinite(r["val_loss"])
        csv = (d / "lm_out_n8.csv").read_text().splitlines()
        assert csv[0].endswith("val_loss,val_ppl")
        val_rows = [l for l in csv[1:] if l.split(",")[5]]
        assert val_rows, csv
        for l in val_rows:
            assert np.isfinite(float(l.split(",")[5]))


def test_grad_accum_matches_full_batch():
    """grad_accum splits the batch into scanned microbatches; the LM has
    no BatchNorm, so one accumulated step must equal the full-batch step
    EXACTLY (params, loss, grad_norm)."""
    from stochastic_gradient_push_tpu.algorithms import all_reduce
    from stochastic_gradient_push_tpu.train.lm import (
        init_lm_state, shard_lm_train_step)

    dp = 2
    mesh = make_dp_sp_mesh(dp, 1)
    cfg = small_cfg("full")
    model = TransformerLM(cfg)
    alg = all_reduce(GOSSIP_AXIS)
    tx = sgd(momentum=0.0, weight_decay=0.0)
    lrs = LRSchedule(ref_lr=0.1, batch_size=4, world_size=dp,
                     decay_schedule={}, warmup=False)
    rng = np.random.default_rng(5)
    toks = rng.integers(0, VOCAB, size=(dp, 4, SEQ)).astype(np.int32)
    tgts = rng.integers(0, VOCAB, size=(dp, 4, SEQ)).astype(np.int32)

    results = {}
    for ga in (1, 2, 4):
        step = build_lm_train_step(model, alg, tx, lrs,
                                   itr_per_epoch=100, seq_axis=None,
                                   grad_accum=ga)
        state = init_lm_state(model, mesh, alg, tx, dp=dp, sp=1,
                              batch_size=4, block_len=SEQ, seq_axis=None)
        fn = shard_lm_train_step(step, mesh, seq_axis=None)
        new_state, metrics = fn(state, toks, tgts)
        results[ga] = (jax.tree.map(np.asarray, new_state.params),
                       float(np.asarray(metrics["loss"])[0]),
                       float(np.asarray(metrics["grad_norm"])[0]))

    for ga in (2, 4):
        np.testing.assert_allclose(results[ga][1], results[1][1],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(results[ga][2], results[1][2],
                                   rtol=1e-4, atol=1e-6)
        for a, b in zip(jax.tree.leaves(results[ga][0]),
                        jax.tree.leaves(results[1][0])):
            np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)


def test_grad_accum_matches_full_batch_on_sp_mesh():
    """Ring-attention collectives inside the accumulation scan: one
    grad_accum=2 step on the (gossip, seq) mesh equals the full-batch
    step exactly."""
    from jax.sharding import PartitionSpec as P

    from stochastic_gradient_push_tpu.algorithms import all_reduce
    from stochastic_gradient_push_tpu.train.lm import (
        init_lm_state, shard_lm_train_step)

    dp, sp = 2, 2
    block = SEQ // sp
    mesh = make_dp_sp_mesh(dp, sp)
    cfg = small_cfg("ring", seq_axis=SEQ_AXIS)
    model = TransformerLM(cfg)
    alg = all_reduce(GOSSIP_AXIS)
    tx = sgd(momentum=0.0, weight_decay=0.0)
    lrs = LRSchedule(ref_lr=0.1, batch_size=4, world_size=dp * sp,
                     decay_schedule={}, warmup=False)
    rng = np.random.default_rng(6)
    toks = rng.integers(0, VOCAB, size=(dp, sp, 4, block)).astype(np.int32)
    tgts = rng.integers(0, VOCAB, size=(dp, sp, 4, block)).astype(np.int32)

    results = {}
    for ga in (1, 2):
        step = build_lm_train_step(model, alg, tx, lrs,
                                   itr_per_epoch=100, seq_axis=SEQ_AXIS,
                                   grad_accum=ga)
        state = init_lm_state(model, mesh, alg, tx, dp=dp, sp=sp,
                              batch_size=4, block_len=block)
        fn = shard_lm_train_step(step, mesh, seq_axis=SEQ_AXIS)
        new_state, metrics = fn(state, toks, tgts)
        results[ga] = (jax.tree.map(np.asarray, new_state.params),
                       float(np.asarray(metrics["loss"])[0]))

    np.testing.assert_allclose(results[2][1], results[1][1],
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(results[2][0]),
                    jax.tree.leaves(results[1][0])):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)


def test_grad_accum_matches_full_batch_on_ep_mesh():
    """MoE all_to_all dispatch inside the accumulation scan: with
    no-drop capacity and moe_loss_coef=0 (the LB loss is nonlinear in
    the batch split), grad_accum=2 on the (gossip, ep) mesh equals the
    full-batch step exactly."""
    from stochastic_gradient_push_tpu.algorithms import all_reduce
    from stochastic_gradient_push_tpu.train.lm import (
        EP_AXIS, ep_state_specs, init_lm_state_ep, shard_lm_train_step)

    dp, ep = 1, 2
    cfg = TransformerConfig(
        vocab_size=VOCAB, d_model=D, n_layers=LAYERS, n_heads=HEADS,
        d_ff=64, max_len=SEQ, attn_impl="full", moe_experts=4,
        moe_every=2, moe_capacity_factor=8.0, ep_axis=EP_AXIS)
    model = TransformerLM(cfg)
    from stochastic_gradient_push_tpu.train.lm import make_dp_ep_mesh
    mesh = make_dp_ep_mesh(dp, ep)
    alg = all_reduce(GOSSIP_AXIS)
    tx = sgd(momentum=0.0, weight_decay=0.0)
    lrs = LRSchedule(ref_lr=0.1, batch_size=4, world_size=dp * ep,
                     decay_schedule={}, warmup=False)
    rng = np.random.default_rng(8)
    toks = rng.integers(0, VOCAB, size=(dp, ep, 4, SEQ)).astype(np.int32)
    tgts = rng.integers(0, VOCAB, size=(dp, ep, 4, SEQ)).astype(np.int32)

    results = {}
    for ga in (1, 2):
        step = build_lm_train_step(model, alg, tx, lrs,
                                   itr_per_epoch=100, seq_axis=None,
                                   ep_axis=EP_AXIS, moe_loss_coef=0.0,
                                   grad_accum=ga)
        state = init_lm_state_ep(model, mesh, alg, tx, dp=dp, ep=ep,
                                 batch_size=4, seq_len=SEQ)
        fn = shard_lm_train_step(step, mesh, seq_axis=None,
                                 state_specs=ep_state_specs(state),
                                 ep_axis=EP_AXIS)
        new_state, metrics = fn(state, toks, tgts)
        assert float(np.asarray(metrics["moe_dropped"])[0]) == 0.0
        results[ga] = (jax.tree.map(np.asarray, new_state.params),
                       float(np.asarray(metrics["loss"])[0]))

    np.testing.assert_allclose(results[2][1], results[1][1],
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(results[2][0]),
                    jax.tree.leaves(results[1][0])):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)
