"""Irregular (non-uniform) mixing: the regime where the push-sum weight
genuinely matters.

With SelfWeightedMixing the mixing matrix is column- but not
row-stochastic: plain averaging of the raw values would converge to a
*weighted* (wrong) average, while push-sum's de-biased estimate provably
recovers the true mean.  These tests pin down exactly that distinction —
the core mathematical claim of the SGP paper that none of the regular
built-in graphs can exhibit."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from stochastic_gradient_push_tpu.algorithms import dpsgd, sgp
from stochastic_gradient_push_tpu.parallel import (
    GOSSIP_AXIS,
    make_gossip_mesh,
    mix_push_sum,
)
from stochastic_gradient_push_tpu.topology import (
    NPeerDynamicDirectedExponentialGraph,
    SelfWeightedMixing,
    build_schedule,
)

WORLD = 8


@pytest.fixture(scope="module")
def mesh():
    return make_gossip_mesh(WORLD)


ALPHAS = 0.3 + 0.5 * np.arange(WORLD) / (WORLD - 1)  # rank-dependent


def test_selfweighted_schedule_is_irregular_but_column_stochastic():
    g = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1)
    sched = build_schedule(g, SelfWeightedMixing(alpha=ALPHAS))
    assert not sched.regular
    for p in range(sched.num_phases):
        W = sched.mixing_matrix(p)
        np.testing.assert_allclose(W.sum(axis=0), np.ones(WORLD),
                                   atol=1e-12)
        # row sums deviate → non-uniform stationary distribution
        assert np.abs(W.sum(axis=1) - 1.0).max() > 0.05


def test_push_sum_recovers_true_mean_under_irregular_mixing(mesh):
    g = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1)
    sched = build_schedule(g, SelfWeightedMixing(alpha=ALPHAS))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(WORLD, 5)).astype(np.float32)
    w = np.ones((WORLD, 1), np.float32)
    true_mean = x.mean(axis=0)

    def step(phase, xs, ws):
        return mix_push_sum(xs, ws, phase, sched, GOSSIP_AXIS)

    f = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(GOSSIP_AXIS), P(GOSSIP_AXIS)),
        out_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS))))

    for phase in range(120):
        x, w = map(np.asarray, f(jnp.int32(phase), x, w))

    # the ps-weights genuinely deviate from 1 (irregular regime)
    assert np.abs(w - 1.0).max() > 1e-3, w.ravel()
    # raw values converge to the (biased) weighted average, NOT the mean:
    # exactly the error push-sum's division corrects
    assert np.abs(x - true_mean).max() > 1e-3
    # de-biased estimates recover the true mean on every rank
    np.testing.assert_allclose(x / w, np.broadcast_to(true_mean, x.shape),
                               rtol=1e-4, atol=1e-4)


def test_sgp_trains_under_irregular_mixing(mesh):
    """SGP with irregular mixing still solves the consensus optimization."""
    g = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1)
    sched = build_schedule(g, SelfWeightedMixing(alpha=ALPHAS))
    alg = sgp(sched, GOSSIP_AXIS)
    rng = np.random.default_rng(1)
    targets = rng.normal(size=(WORLD, 4)).astype(np.float32)
    lr = 0.05

    def step(params, gstate, target):
        params, gstate = alg.pre_step(params, gstate)
        z = alg.eval_params(params, gstate)
        grads = jax.grad(lambda p: 0.5 * jnp.sum((p - target) ** 2))(z)
        params = params - lr * grads
        return alg.post_step(params, gstate)

    f = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(GOSSIP_AXIS),) * 3,
        out_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS))))

    params = rng.normal(size=(WORLD, 4)).astype(np.float32)
    gstate = jax.tree.map(
        lambda a: np.broadcast_to(np.asarray(a),
                                  (WORLD,) + np.shape(a)).copy(),
        alg.init(jnp.zeros((4,), jnp.float32)))
    for _ in range(500):
        params, gstate = jax.block_until_ready(f(params, gstate, targets))

    w = np.asarray(gstate.ps_weight).reshape(WORLD, 1)
    z = np.asarray(params) / w
    np.testing.assert_allclose(z.mean(axis=0), targets.mean(axis=0),
                               atol=5e-3)


def test_dpsgd_rejects_irregular_mixing():
    g = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1)
    sched = build_schedule(g, SelfWeightedMixing(alpha=ALPHAS))
    with pytest.raises(ValueError, match="regular"):
        dpsgd(sched, GOSSIP_AXIS)


def test_selfweighted_alpha_validation():
    with pytest.raises(ValueError):
        SelfWeightedMixing(alpha=0.0)
    with pytest.raises(ValueError):
        SelfWeightedMixing(alpha=1.0)
    with pytest.raises(ValueError, match="entries"):
        build_schedule(NPeerDynamicDirectedExponentialGraph(WORLD),
                       SelfWeightedMixing(alpha=[0.5, 0.5, 0.5]))


def test_osgp_overlap_under_irregular_mixing(mesh):
    """Overlap mode with per-rank self weights: the split-round bookkeeping
    must use each rank's own lo, so de-biased consensus still lands on the
    true mean (lr=0 pure averaging)."""
    from stochastic_gradient_push_tpu.algorithms import osgp

    g = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1)
    sched = build_schedule(g, SelfWeightedMixing(alpha=ALPHAS))
    alg = osgp(sched, GOSSIP_AXIS)
    rng = np.random.default_rng(3)
    x0 = rng.normal(size=(WORLD, 4)).astype(np.float32)
    true_mean = x0.mean(axis=0)

    def step(params, gstate):
        params, gstate = alg.pre_step(params, gstate)
        return alg.post_step(params, gstate)

    f = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS)),
        out_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS))))
    params = x0.copy()
    gstate = jax.tree.map(
        lambda a: np.broadcast_to(np.asarray(a),
                                  (WORLD,) + np.shape(a)).copy(),
        alg.init(jnp.zeros((4,), jnp.float32)))
    for _ in range(200):
        params, gstate = jax.block_until_ready(f(params, gstate))

    w = np.asarray(gstate.ps_weight).reshape(WORLD, 1)
    in_p, in_w = gstate.in_flight[0]
    # total mass conservation including in-flight shares
    np.testing.assert_allclose(
        np.asarray(params).sum(0) + np.asarray(in_p).sum(0),
        x0.sum(0), rtol=1e-4, atol=1e-4)
    # irregular: weights deviate from 1, de-biased values hit the true mean
    assert np.abs(w - 1.0).max() > 1e-3
    np.testing.assert_allclose(
        np.asarray(params) / w, np.broadcast_to(true_mean, x0.shape),
        rtol=2e-4, atol=2e-4)
