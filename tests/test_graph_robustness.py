"""Robustness matrix: every graph family x world size either builds a valid
schedule or raises a clean ValueError — never a crash or a silently broken
permutation (the reference assumed power-of-two worlds and could IndexError
or deadlock otherwise)."""

import numpy as np
import pytest

from stochastic_gradient_push_tpu.topology import (
    DynamicBipartiteExponentialGraph,
    DynamicBipartiteLinearGraph,
    DynamicDirectedExponentialGraph,
    DynamicDirectedLinearGraph,
    NPeerDynamicDirectedExponentialGraph,
    RingGraph,
    build_schedule,
)

ALL = [DynamicDirectedExponentialGraph,
       NPeerDynamicDirectedExponentialGraph,
       DynamicBipartiteExponentialGraph,
       DynamicDirectedLinearGraph,
       DynamicBipartiteLinearGraph,
       RingGraph]


# sizes every family must support (power-of-two worlds, the reference's
# deployment shape) — rejection here is a regression, not robustness
MUST_BUILD = {4, 8, 16}


@pytest.mark.parametrize("cls", ALL)
@pytest.mark.parametrize("world", list(range(2, 17)))
def test_build_or_clean_error(cls, world):
    try:
        g = cls(world_size=world, peers_per_itr=1)
        sched = build_schedule(g)
    except ValueError:
        assert world not in MUST_BUILD, \
            f"{cls.__name__} must support world_size={world}"
        return  # clean rejection is acceptable for odd sizes
    # if it builds, it must be mathematically sound
    for p in range(sched.num_phases):
        W = sched.mixing_matrix(p)
        np.testing.assert_allclose(W.sum(axis=0), np.ones(world),
                                   atol=1e-12)
        # every row of the permutation table is a permutation
        for i in range(sched.peers_per_itr):
            assert sorted(sched.perms[p, i].tolist()) == list(range(world))


@pytest.mark.parametrize("world,ppi", [(9, 2), (12, 3), (16, 5), (10, 2)])
def test_npdde_nonstandard_ppi_world(world, ppi):
    try:
        g = NPeerDynamicDirectedExponentialGraph(world, peers_per_itr=ppi)
        sched = build_schedule(g)
    except ValueError:
        return
    for p in range(sched.num_phases):
        for i in range(sched.peers_per_itr):
            assert sorted(sched.perms[p, i].tolist()) == list(range(world))
