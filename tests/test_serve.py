"""serve/: page-table oracle, paged-decode parity, engine-vs-model
parity, continuous-batching invariants, consensus ingest, and the
decode-fleet child's supervisor contracts.

The two load-bearing equalities are pinned here:

* the ingested serving params are BIT-equal to the reshard collapse
  (``reshard_state(state, world, 1)`` row 0) — serving deploys exactly
  the consensus the restart boundary would compute;
* the paged decode path (Pallas interpret kernel, sharded or not, and
  the whole greedy engine) matches the dense ``TransformerLM`` oracle.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from stochastic_gradient_push_tpu.serve.pages import (
    PageCapacityError,
    PageTable,
    pages_for,
)
from stochastic_gradient_push_tpu.serve.scheduler import (
    AdmissionError,
    ContinuousBatcher,
    Request,
)

# -- page table (pure python: no jax anywhere in this section) --------------


class TestPageTable:
    def test_pages_for_is_ceil_div(self):
        assert pages_for(1, 8) == 1
        assert pages_for(8, 8) == 1
        assert pages_for(9, 8) == 2
        assert pages_for(0, 8) == 0

    def test_open_reserves_full_budget_up_front(self):
        t = PageTable(num_pages=8, page_size=4, max_seqs=4)
        slot = t.open(budget_tokens=10)        # 3 pages reserved
        assert t.reserved_pages == 3 and t.used_pages == 0
        assert t.available_pages == 5
        t.append(slot, 10)
        # the reservation converted into real pages, none left over
        assert t.used_pages == 3 and t.reserved_pages == 0
        t.close(slot)
        assert t.free_pages == 8

    def test_pages_hand_out_ascending_and_recycle(self):
        t = PageTable(num_pages=4, page_size=2, max_seqs=4)
        a = t.open(4)
        t.append(a, 4)
        assert t.pages_of(a) == (0, 1)
        b = t.open(4)
        t.append(b, 4)
        assert t.pages_of(b) == (2, 3)
        t.close(a)                     # frees 0, 1
        c = t.open(3)
        t.append(c, 3)
        assert set(t.pages_of(c)) <= {0, 1}   # freed pages reused
        t.close(b)
        t.close(c)
        t.assert_quiescent()

    def test_capacity_errors_are_typed(self):
        t = PageTable(num_pages=2, page_size=4, max_seqs=1)
        with pytest.raises(PageCapacityError):
            t.open(9)                  # 3 pages > 2 in the pool
        slot = t.open(8)
        with pytest.raises(PageCapacityError):
            t.open(1)                  # max_seqs exhausted
        t.append(slot, 8)
        with pytest.raises(PageCapacityError):
            t.append(slot, 1)          # past the reserved budget
        t.close(slot)

    def test_reservation_blocks_other_admissions(self):
        # an admitted-but-short sequence still owns its whole budget:
        # available_pages is free minus reserved, so a second open that
        # would overlap the reservation is refused
        t = PageTable(num_pages=4, page_size=4, max_seqs=4)
        s = t.open(16)                 # reserves all 4 pages
        t.append(s, 2)                 # only 1 page materialized
        assert t.used_pages == 1 and t.available_pages == 0
        assert not t.can_fit(1)
        with pytest.raises(PageCapacityError):
            t.open(1)
        t.close(s)
        assert t.can_fit(16)

    def test_last_position_and_page_index_array(self):
        t = PageTable(num_pages=4, page_size=4, max_seqs=2)
        s = t.open(10)
        t.append(s, 5)
        assert t.length(s) == 5
        assert t.last_position(s) == (t.pages_of(s)[1], 0)
        rows = t.page_index_array([s], max_pages=3)
        assert rows.shape == (1, 3) and rows.dtype == np.int32
        assert tuple(rows[0, :2]) == t.pages_of(s)
        t.close(s)

    def test_quiescence_names_leaks(self):
        t = PageTable(num_pages=4, page_size=4, max_seqs=2)
        t.open(4)
        with pytest.raises(AssertionError, match="live sequences"):
            t.assert_quiescent()


# -- continuous batching (synthetic engine: still no accelerator) -----------


def _synthetic_engine(num_pages=32, max_seqs=4, page_size=4,
                      max_pages_per_seq=8):
    from stochastic_gradient_push_tpu.serve.bench import SyntheticEngine
    from stochastic_gradient_push_tpu.serve.engine import ServeConfig

    return SyntheticEngine(ServeConfig(
        n_heads=1, page_size=page_size, num_pages=num_pages,
        max_seqs=max_seqs, max_pages_per_seq=max_pages_per_seq))


class TestContinuousBatching:
    def test_no_slot_leak_over_200_requests(self):
        from stochastic_gradient_push_tpu.serve.bench import (
            run_bench, synthetic_requests)

        engine = _synthetic_engine()
        requests = synthetic_requests(200, seed=3)
        metrics, completions = run_bench(engine, requests)
        assert metrics["requests"] == 200
        assert len(completions) == 200
        # run_bench already asserted quiescence; re-assert for the test
        engine.pages.assert_quiescent()
        assert engine.pages.free_pages == engine.pages.num_pages
        # every request got exactly its token budget
        by_rid = {r.rid: r for r in requests}
        for c in completions:
            assert len(c.tokens) == by_rid[c.rid].max_new_tokens

    def test_permanent_rejection_is_typed_and_counted(self):
        from stochastic_gradient_push_tpu.telemetry import (
            MemorySink, TelemetryRegistry)

        mem = MemorySink()
        batcher = ContinuousBatcher(
            _synthetic_engine(max_pages_per_seq=2),
            registry=TelemetryRegistry(sinks=[mem]))
        with pytest.raises(AdmissionError):
            batcher.submit(Request(rid=0, prompt=(1,) * 10,
                                   max_new_tokens=5))   # 15 > 8 window
        assert batcher.rejected == 1 and batcher.pending == 0
        [ev] = mem.by_kind("serve")
        assert ev["data"]["phase"] == "reject"
        assert ev["severity"] == "warning"

    def test_backpressure_queues_fifo_and_drains(self):
        from stochastic_gradient_push_tpu.telemetry import (
            MemorySink, TelemetryRegistry)

        mem = MemorySink()
        # one slot, tiny pool: everything must serialize through it
        engine = _synthetic_engine(num_pages=4, max_seqs=1,
                                   max_pages_per_seq=4)
        batcher = ContinuousBatcher(
            engine, registry=TelemetryRegistry(sinks=[mem]))
        for rid in range(6):
            batcher.submit(Request(rid=rid, prompt=(1, 2, 3),
                                   max_new_tokens=3))
        completions = batcher.drain()
        assert [c.rid for c in completions] == list(range(6))  # FIFO
        assert len(mem.by_kind("request")) == 6
        assert batcher.peak_occupancy > 0

    def test_max_new_one_completes_at_prefill(self):
        batcher = ContinuousBatcher(_synthetic_engine())
        batcher.submit(Request(rid=7, prompt=(4, 5), max_new_tokens=1))
        [done] = batcher.step()
        assert done.rid == 7 and len(done.tokens) == 1
        batcher.engine.pages.assert_quiescent()


# -- paged attention parity -------------------------------------------------


def _paged_case(seed=0, b=4, h=8, kv_pages=9, page_size=4, d=8, np_=6):
    r = np.random.default_rng(seed)
    q = r.standard_normal((b, h, d)).astype(np.float32)
    kp = r.standard_normal((h, kv_pages, page_size, d)).astype(np.float32)
    vp = r.standard_normal((h, kv_pages, page_size, d)).astype(np.float32)
    pi = r.integers(0, kv_pages, size=(b, np_)).astype(np.int32)
    lengths = r.integers(1, np_ * page_size + 1, size=b).astype(np.int32)
    return q, kp, vp, pi, lengths


class TestPagedAttention:
    def test_interpret_kernel_matches_dense_reference(self):
        from stochastic_gradient_push_tpu.serve.paged_attention import (
            paged_attention_decode, paged_attention_reference)

        q, kp, vp, pi, lengths = _paged_case(seed=1)
        out = paged_attention_decode(q, kp, vp, pi, lengths,
                                     use_pallas=True, interpret=True)
        ref = paged_attention_reference(q, kp, vp, pi, lengths)
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-6)

    def test_jnp_fallback_matches_dense_reference(self):
        from stochastic_gradient_push_tpu.serve.paged_attention import (
            paged_attention_decode, paged_attention_reference)

        q, kp, vp, pi, lengths = _paged_case(seed=2)
        out = paged_attention_decode(q, kp, vp, pi, lengths,
                                     use_pallas=False)
        ref = paged_attention_reference(q, kp, vp, pi, lengths)
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-6)

    def test_length_one_attends_to_exactly_one_token(self):
        # q_len == 1, kv length == 1: the output IS v at the first slot
        from stochastic_gradient_push_tpu.serve.paged_attention import (
            paged_attention_decode)

        q, kp, vp, pi, _ = _paged_case(seed=3, b=2, np_=2)
        lengths = np.ones(2, np.int32)
        out = np.asarray(paged_attention_decode(
            q, kp, vp, pi, lengths, use_pallas=True, interpret=True))
        want = np.stack([vp[:, pi[i, 0], 0] for i in range(2)])
        np.testing.assert_allclose(out, want, atol=2e-6)

    def test_sharded_decode_matches_reference_on_model_mesh(self):
        import jax
        from jax.sharding import Mesh

        from stochastic_gradient_push_tpu.serve.paged_attention import (
            paged_attention_reference, sharded_paged_decode)

        mesh = Mesh(np.array(jax.devices()[:2]), ("model",))
        q, kp, vp, pi, lengths = _paged_case(seed=4)
        out = sharded_paged_decode(mesh, q, kp, vp, pi, lengths,
                                   use_pallas=True, interpret=True)
        ref = paged_attention_reference(q, kp, vp, pi, lengths)
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-6)


# -- engine vs the dense model ----------------------------------------------


def _tiny_lm(seed=0):
    import jax

    from stochastic_gradient_push_tpu.models.transformer import (
        TransformerConfig, TransformerLM)

    model = TransformerLM(TransformerConfig(
        vocab_size=48, d_model=16, n_layers=2, n_heads=2, d_ff=32,
        max_len=32, attn_impl="full"))
    variables = model.init(jax.random.PRNGKey(seed),
                           np.zeros((1, 8), np.int32))
    return model, variables["params"]


def _dense_greedy(model, params, prompt, n_new):
    import jax
    import jax.numpy as jnp

    pjax = jax.tree.map(jnp.asarray, params)
    seq, out = list(prompt), []
    for _ in range(n_new):
        logits = model.apply({"params": pjax},
                             jnp.asarray([seq], jnp.int32))
        tok = int(jnp.argmax(logits[0, -1]))
        out.append(tok)
        seq.append(tok)
    return out


class TestLMEngine:
    def test_greedy_decode_matches_dense_model(self):
        from stochastic_gradient_push_tpu.serve.engine import (
            LMEngine, ServeConfig)

        model, params = _tiny_lm()
        engine = LMEngine(params, ServeConfig(
            n_heads=2, page_size=4, num_pages=16, max_seqs=2,
            max_pages_per_seq=4))
        prompt, n_new = [5, 11, 3], 5
        slot, tok = engine.start(list(prompt), len(prompt) + n_new)
        got = [tok]
        while len(got) < n_new:
            got.append(engine.step([slot])[slot])
        engine.finish(slot)
        engine.pages.assert_quiescent()
        assert got == _dense_greedy(model, params, prompt, n_new)

    def test_concurrent_slots_do_not_cross_talk(self):
        # two interleaved sequences decode exactly what each would
        # decode alone — the page table isolates their KV
        from stochastic_gradient_push_tpu.serve.engine import (
            LMEngine, ServeConfig)

        model, params = _tiny_lm(seed=1)
        engine = LMEngine(params, ServeConfig(
            n_heads=2, page_size=4, num_pages=16, max_seqs=2,
            max_pages_per_seq=4))
        pa, pb, n_new = [7, 2, 9, 4], [30, 1], 4
        sa, ta = engine.start(list(pa), len(pa) + n_new)
        sb, tb = engine.start(list(pb), len(pb) + n_new)
        ga, gb = [ta], [tb]
        while len(ga) < n_new:
            step = engine.step([sa, sb])
            ga.append(step[sa])
            gb.append(step[sb])
        engine.finish(sa)
        engine.finish(sb)
        engine.pages.assert_quiescent()
        assert ga == _dense_greedy(model, params, pa, n_new)
        assert gb == _dense_greedy(model, params, pb, n_new)

    def test_kv_bytes_per_token_is_modeled(self):
        from stochastic_gradient_push_tpu.serve.engine import (
            LMEngine, ServeConfig)

        _, params = _tiny_lm()
        engine = LMEngine(params, ServeConfig(n_heads=2))
        # 2 layers * 2 heads * head_dim 8 * 4 bytes, k and v
        assert engine.kv_bytes_per_token() == 2 * 2 * 2 * 8 * 4


# -- consensus ingest -------------------------------------------------------


def _save_ckpt(path, state, meta, raw_meta=False):
    import flax.serialization

    if not raw_meta:
        meta = json.loads(json.dumps(meta, default=float))
    with open(path, "wb") as f:
        f.write(flax.serialization.msgpack_serialize(
            {"state": state, "meta": meta}))


def _world_state(world, rows, seed):
    r = np.random.default_rng(seed)
    return {
        "params": {"w": r.standard_normal((rows, 6)).astype(np.float32)},
        "gossip": {
            "ps_weight": r.uniform(0.5, 2.0, rows).astype(np.float32),
            "phase": np.full(rows, 3, np.int32)},
    }


class TestConsensusIngest:
    def _write_world(self, d, world=4, procs=2, tag=""):
        rows = world // procs
        for p in range(procs):
            _save_ckpt(
                os.path.join(d, f"{tag}checkpoint_r{p}_n{world}.ckpt"),
                _world_state(world, rows, seed=p),
                {"step": 5, "rows": rows, "process_id": p,
                 "num_processes": procs, "world": world})

    def test_ingest_bit_equals_reshard_collapse(self, tmp_path):
        from stochastic_gradient_push_tpu.serve.load import (
            load_consensus)
        from stochastic_gradient_push_tpu.supervise.reshard import (
            load_world_checkpoint, reshard_state)

        d = str(tmp_path)
        self._write_world(d)
        params, _, info = load_consensus(d)
        state, _, _ = load_world_checkpoint(d, "", 4)
        want = reshard_state(state, 4, 1)["params"]["w"][0]
        assert np.array_equal(params["w"], want)   # BIT equality
        assert info.world == 4 and info.step == 5
        assert len(info.files) == 2

    def test_newest_world_wins(self, tmp_path):
        from stochastic_gradient_push_tpu.serve.load import (
            available_worlds, load_consensus)

        d = str(tmp_path)
        self._write_world(d, world=8, procs=2)
        time.sleep(0.02)
        self._write_world(d, world=4, procs=2)
        os.utime(os.path.join(d, "checkpoint_r0_n4.ckpt"))
        assert available_worlds(d)[0] == 4
        assert load_consensus(d)[2].world == 4
        assert load_consensus(d, world=8)[2].world == 8

    def test_torn_set_rejected(self, tmp_path):
        from stochastic_gradient_push_tpu.serve.load import (
            load_consensus)
        from stochastic_gradient_push_tpu.supervise.reshard import (
            TornCheckpointError)

        d = str(tmp_path)
        self._write_world(d)
        os.unlink(os.path.join(d, "checkpoint_r1_n4.ckpt"))
        with pytest.raises(TornCheckpointError):
            load_consensus(d)

    def test_empty_directory_is_typed(self, tmp_path):
        from stochastic_gradient_push_tpu.serve.load import (
            ConsensusIngestError, load_consensus)

        with pytest.raises(ConsensusIngestError):
            load_consensus(str(tmp_path))

    def test_partition_rules_cover_the_lm_tree(self):
        from stochastic_gradient_push_tpu.serve.load import (
            decode_partition_rules, match_partition_rules)

        _, params = _tiny_lm()
        params = {k: v for k, v in params.items()}
        specs = match_partition_rules(decode_partition_rules(), params)
        qspec = specs["block_0"]["attn"]["q"]["kernel"]
        ospec = specs["block_0"]["attn"]["o"]["kernel"]
        assert qspec == (None, "model") and ospec == ("model", None)
        assert specs["embed"]["embedding"] == ()      # replicated


class TestMetaBugfix:
    """Checkpoints whose meta lacks plan/health (or is None) must
    reshard and ingest; malformed meta fails typed, not as KeyError."""

    def test_none_meta_tolerated(self, tmp_path):
        from stochastic_gradient_push_tpu.serve.load import (
            load_consensus)
        from stochastic_gradient_push_tpu.supervise.reshard import (
            load_world_checkpoint)

        d = str(tmp_path)
        _save_ckpt(os.path.join(d, "checkpoint_r0_n2.ckpt"),
                   _world_state(2, 2, seed=0), None, raw_meta=True)
        _, meta, _ = load_world_checkpoint(d, "", 2)
        assert meta == {}
        params, _, info = load_consensus(d)
        assert info.step is None and params["w"].shape == (6,)

    def test_non_dict_meta_is_typed(self, tmp_path):
        from stochastic_gradient_push_tpu.supervise.reshard import (
            CheckpointMetaError, load_world_checkpoint)

        d = str(tmp_path)
        _save_ckpt(os.path.join(d, "checkpoint_r0_n2.ckpt"),
                   _world_state(2, 2, seed=0), ["not", "a", "dict"],
                   raw_meta=True)
        with pytest.raises(CheckpointMetaError, match="mapping"):
            load_world_checkpoint(d, "", 2)

    def test_meta_key_names_whats_missing(self):
        from stochastic_gradient_push_tpu.supervise.reshard import (
            CheckpointMetaError, meta_key)

        assert meta_key({"plan": 1}, "plan") == 1
        with pytest.raises(CheckpointMetaError, match="'plan'") as ei:
            meta_key({"step": 3}, "plan", context="resume")
        assert ei.value.key == "plan"
        with pytest.raises(CheckpointMetaError):
            meta_key("nope", "plan")

    def test_stripped_meta_reshards(self, tmp_path):
        # the serve-time shape: no plan, no health, no counters — the
        # cross-world reshard must still go through
        from stochastic_gradient_push_tpu.supervise.reshard import (
            reshard_checkpoints)

        d = str(tmp_path)
        _save_ckpt(os.path.join(d, "checkpoint_r0_n2.ckpt"),
                   _world_state(2, 2, seed=0), {"serve": True})
        report = reshard_checkpoints(d, "", 2, 1)
        assert report.new_world == 1


# -- the decode-fleet child -------------------------------------------------


class TestDecodeChild:
    def _spawn(self, ck, tr, steps=400, extra=()):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        return subprocess.Popen(
            [sys.executable, "-m",
             "stochastic_gradient_push_tpu.serve.child",
             "--checkpoint_dir", ck, "--trace_dir", tr,
             "--world_size", "4", "--num_processes", "2",
             "--process_id", "0", "--rows", "2",
             "--steps", str(steps), "--step_s", "0.02",
             "--save_every", "5", "--seed", "3", *extra],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

    def _wait_for_steps(self, events_path, timeout=60.0):
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            if os.path.exists(events_path):
                with open(events_path) as f:
                    if any('"step_stats"' in ln for ln in f):
                        return
            time.sleep(0.05)
        raise AssertionError("child produced no step_stats heartbeat")

    def test_drain_contract_sigusr1_saves_and_exits_75(self, tmp_path):
        ck, tr = str(tmp_path / "ck"), str(tmp_path / "tr")
        child = self._spawn(ck, tr)
        try:
            self._wait_for_steps(os.path.join(tr, "events.jsonl"))
            child.send_signal(signal.SIGUSR1)
            out, _ = child.communicate(timeout=60)
        finally:
            if child.poll() is None:
                child.kill()
        assert child.returncode == 75, out
        # the reshardable checkpoint landed (this host's 2 rows of 4)
        assert os.path.exists(os.path.join(ck, "checkpoint_r0_n4.ckpt"))
        events = [json.loads(ln)
                  for ln in open(os.path.join(tr, "events.jsonl"))]
        kinds = {e["kind"] for e in events}
        assert {"run_meta", "step_stats", "serve"} <= kinds
        last_meta = [e for e in events if e["kind"] == "run_meta"][-1]
        assert last_meta["data"]["exit_reason"] == "preempted"
        assert last_meta["data"]["exit_code"] == 75
        # the drain finished every in-flight request before exit
        summary = [e for e in events if e["kind"] == "serve"][-1]
        assert summary["data"]["phase"] == "summary"
        assert summary["data"]["requests"] > 0

    def test_clean_run_ingests_consensus_and_exits_zero(self, tmp_path):
        from stochastic_gradient_push_tpu.serve.child import PARAM_DIM

        ck, tr = str(tmp_path / "ck"), str(tmp_path / "tr")
        os.makedirs(ck)
        # a training world-4 set for the child to ingest
        r = np.random.default_rng(0)
        for p in range(2):
            _save_ckpt(
                os.path.join(ck, f"checkpoint_r{p}_n4.ckpt"),
                {"params": {"w": r.standard_normal(
                    (2, PARAM_DIM)).astype(np.float32)},
                 "gossip": {"ps_weight": np.ones(2, np.float32),
                            "phase": np.zeros(2, np.int32)}},
                {"step": 1, "rows": 2, "process_id": p,
                 "num_processes": 2})
        child = self._spawn(ck, tr, steps=3)
        out, _ = child.communicate(timeout=120)
        assert child.returncode == 0, out
        events = [json.loads(ln)
                  for ln in open(os.path.join(tr, "events.jsonl"))]
        meta0 = [e for e in events if e["kind"] == "run_meta"][0]
        assert meta0["data"]["model_source"] == "consensus_n4"
        assert [e for e in events if e["kind"] == "request"]
