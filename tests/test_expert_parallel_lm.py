"""MoE transformer with expert parallelism, end to end with gossip DP."""

import jax
import numpy as np
import pytest

from stochastic_gradient_push_tpu.algorithms import sgp
from stochastic_gradient_push_tpu.data.lm import (
    lm_batches,
    synthetic_lm_corpus,
)
from stochastic_gradient_push_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
)
from stochastic_gradient_push_tpu.parallel import GOSSIP_AXIS
from stochastic_gradient_push_tpu.topology import (
    DynamicDirectedExponentialGraph,
    build_schedule,
)
from stochastic_gradient_push_tpu.train import LRSchedule, sgd
from stochastic_gradient_push_tpu.train.lm import (
    EP_AXIS,
    build_lm_train_step,
    ep_state_specs,
    init_lm_state_ep,
    make_dp_ep_mesh,
    shard_lm_train_step,
)

DP, EP = 2, 4
VOCAB, D, LAYERS, HEADS, FF, EXPERTS = 64, 32, 2, 4, 32, 8
BATCH, SEQ = 2, 32


def test_moe_lm_trains_with_gossip_and_ep():
    mesh = make_dp_ep_mesh(DP, EP)
    cfg = TransformerConfig(
        vocab_size=VOCAB, d_model=D, n_layers=LAYERS, n_heads=HEADS,
        d_ff=FF, max_len=SEQ, attn_impl="full",
        moe_experts=EXPERTS, moe_every=2, ep_axis=EP_AXIS)
    model = TransformerLM(cfg)
    alg = sgp(build_schedule(DynamicDirectedExponentialGraph(DP)),
              GOSSIP_AXIS)
    tx = sgd(momentum=0.9, weight_decay=0.0)
    lrs = LRSchedule(ref_lr=0.5, batch_size=BATCH, world_size=DP * EP,
                     decay_schedule={}, warmup=False)
    step = build_lm_train_step(model, alg, tx, lrs, itr_per_epoch=100,
                               seq_axis=None, ep_axis=EP_AXIS)
    state = init_lm_state_ep(model, mesh, alg, tx, dp=DP, ep=EP,
                             batch_size=BATCH, seq_len=SEQ)
    train_fn = shard_lm_train_step(step, mesh, seq_axis=None,
                                   state_specs=ep_state_specs(state),
                                   ep_axis=EP_AXIS)

    # expert leaves really shard over ep; router/attention replicate
    flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
    expert_shapes = [(p, l.shape, str(l.sharding.spec)) for p, l in flat
                     if any("experts" in str(k) for k in p)]
    assert expert_shapes, "no expert leaves found"
    for p, shape, spec in expert_shapes:
        assert "ep" in spec, (p, spec)
        assert shape[1] == EXPERTS  # global expert dim intact
    # distinct expert initializations across ep shards
    up = [l for pth, l in flat
          if any("experts_up" in str(k) for k in pth)][0]
    up = np.asarray(up)[0]  # [E, D, F] for gossip rank 0
    for a in range(EXPERTS):
        for b in range(a + 1, EXPERTS):
            assert not np.allclose(up[a], up[b]), (a, b)

    corpus = synthetic_lm_corpus(30_000, vocab_size=VOCAB, seed=3)
    losses = []
    for epoch in range(3):
        for tokens, targets in lm_batches(corpus, DP * EP, 1, BATCH, SEQ,
                                          seed=epoch):
            # [dp*ep, 1, B, T] → [dp, ep, B, T]
            tokens = tokens.reshape(DP, EP, BATCH, SEQ)
            targets = targets.reshape(DP, EP, BATCH, SEQ)
            state, metrics = train_fn(state, tokens, targets)
            jax.block_until_ready(state)
            losses.append(float(np.mean(np.asarray(metrics["loss"]))))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.95, (
        losses[:5], losses[-5:])

    # the trained router (from the FINAL state — earlier buffers were
    # donated) is finite and nonzero
    final_flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
    router = [l for p, l in final_flat
              if any("router" in str(k) for k in p)][0]
    r = np.asarray(router)
    assert np.all(np.isfinite(r)) and np.abs(r).max() > 0


@pytest.mark.slow
def test_ep_train_step_matches_full_expert_model():
    """One momentum-free SGD step on the (gossip=1, ep=2) mesh moves every
    param — expert slices included — by exactly ``-lr * grad`` of the
    stacked full-expert model under the mean-over-ep-shards CE.

    Pins the uniform ``/n_ep`` gradient scaling: expert grads arrive as
    the SUM over shards via the all_to_all transpose (each expert
    processes slots from every shard), so exempting them from the
    division — as round 3 did — trains experts with an effective
    ``n_ep``× learning rate while every loss/eval metric looks fine.
    """
    import jax.numpy as jnp

    from stochastic_gradient_push_tpu.algorithms import all_reduce
    from stochastic_gradient_push_tpu.train.lm import lm_loss

    dp, ep = 1, 2
    cfg = TransformerConfig(
        vocab_size=VOCAB, d_model=D, n_layers=LAYERS, n_heads=HEADS,
        d_ff=FF, max_len=SEQ, attn_impl="full",
        moe_experts=4, moe_every=2, moe_capacity_factor=8.0,
        ep_axis=EP_AXIS)
    model = TransformerLM(cfg)
    mesh = make_dp_ep_mesh(dp, ep)
    alg = all_reduce(GOSSIP_AXIS)
    tx = sgd(momentum=0.0, weight_decay=0.0)
    lrs = LRSchedule(ref_lr=0.1, batch_size=BATCH, world_size=dp * ep,
                     decay_schedule={}, warmup=False)
    step = build_lm_train_step(model, alg, tx, lrs, itr_per_epoch=100,
                               seq_axis=None, ep_axis=EP_AXIS,
                               moe_loss_coef=0.0)
    state = init_lm_state_ep(model, mesh, alg, tx, dp=dp, ep=ep,
                             batch_size=BATCH, seq_len=SEQ)
    train_fn = shard_lm_train_step(step, mesh, seq_axis=None,
                                   state_specs=ep_state_specs(state),
                                   ep_axis=EP_AXIS)
    rng = np.random.default_rng(7)
    toks = rng.integers(0, VOCAB,
                        size=(dp, ep, BATCH, SEQ)).astype(np.int32)
    tgts = rng.integers(0, VOCAB,
                        size=(dp, ep, BATCH, SEQ)).astype(np.int32)

    # rank-0 slice of the global state: expert dims are already global
    ref_params = jax.tree.map(lambda a: np.asarray(a)[0], state.params)
    ref_model = TransformerLM(cfg._replace(ep_axis=None))

    def ref_loss(p):
        ces = []
        for j in range(ep):
            logits = ref_model.apply({"params": p}, toks[0, j])
            ces.append(lm_loss(logits, tgts[0, j]))
        return jnp.mean(jnp.stack(ces))

    ref_grads = jax.grad(ref_loss)(ref_params)
    new_state, metrics = train_fn(state, toks, tgts)
    assert float(np.asarray(metrics["moe_dropped"])[0]) == 0.0
    lr = float(np.asarray(metrics["lr"])[0])
    new_ref = jax.tree.map(lambda a: np.asarray(a)[0], new_state.params)
    expect = jax.tree.map(lambda p, g: p - lr * np.asarray(g),
                          ref_params, ref_grads)
    flat_e, _ = jax.tree_util.tree_flatten_with_path(expect)
    flat_n, _ = jax.tree_util.tree_flatten_with_path(new_ref)
    for (path_e, e), (_, n) in zip(flat_e, flat_n):
        np.testing.assert_allclose(
            np.asarray(n), np.asarray(e), rtol=5e-4, atol=1e-5,
            err_msg=jax.tree_util.keystr(path_e))


def test_composition_fences_raise_clean_errors():
    """Unsupported parallelism compositions fail at the CLI boundary with
    actionable messages (ARCHITECTURE.md composition matrix)."""
    import pytest

    from stochastic_gradient_push_tpu.run.gossip_lm import main

    base = ["--world_size", "8", "--moe_experts", "4", "--num_steps", "1"]
    with pytest.raises(SystemExit, match="requires --moe_experts"):
        main(["--world_size", "8", "--ep", "2", "--num_steps", "1"])
    with pytest.raises(SystemExit, match="needs --sp"):
        main(base + ["--ep", "2", "--attn", "ring"])


@pytest.mark.slow
def test_ring_flash_composes_with_pp_sp(tmp_path):
    """attn=ring_flash inside pipeline ticks (custom-vjp ppermutes in a
    lax.cond branch of the tick scan) trains end-to-end on the 3-D
    gossip × pipe × seq mesh."""
    import subprocess
    import sys

    from tests.test_run_layer import CLI_ENV

    cmd = [sys.executable, "-m",
           "stochastic_gradient_push_tpu.run.gossip_lm",
           "--world_size", "8", "--pp", "2", "--sp", "2",
           "--attn", "ring_flash", "--seq_len", "64", "--d_model", "32",
           "--n_layers", "2", "--n_heads", "4", "--d_ff", "32",
           "--batch_size", "4", "--n_micro", "2", "--num_steps", "4",
           "--checkpoint_dir", str(tmp_path)]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=420,
                       env=CLI_ENV)
    assert r.returncode == 0, r.stderr[-2000:]
    assert '"final_loss"' in r.stdout + r.stderr


@pytest.mark.slow
def test_moe_ep_sp_tp_4d_trains(tmp_path):
    """All four axes at once: gossip × ep × seq × tp on one 4-D mesh,
    with held-out validation through the same composed forward."""
    import numpy as np

    from stochastic_gradient_push_tpu.run.gossip_lm import main

    r = main(["--world_size", "8", "--ep", "2", "--sp", "2", "--tp", "2",
              "--moe_experts", "4", "--moe_every", "2",
              "--seq_len", "32", "--d_model", "32", "--n_layers", "2",
              "--n_heads", "4", "--d_ff", "64", "--vocab_size", "64",
              "--batch_size", "4", "--num_steps", "4",
              "--corpus_tokens", "40000", "--print_freq", "2",
              "--val_frac", "0.1", "--val_every", "2",
              "--val_batches", "2", "--checkpoint_dir", str(tmp_path)])
    assert np.isfinite(r["final_loss"])
    assert np.isfinite(r["val_loss"])


@pytest.mark.slow
def test_moe_with_ring_sp_trains(tmp_path):
    """MoE composed with ring sequence parallelism (per-block routing)
    trains end-to-end through the CLI."""
    import numpy as np

    from stochastic_gradient_push_tpu.run.gossip_lm import main

    r = main(["--world_size", "8", "--sp", "2", "--moe_experts", "2",
              "--moe_every", "2", "--seq_len", "32", "--d_model", "32",
              "--n_layers", "2", "--n_heads", "4", "--d_ff", "32",
              "--vocab_size", "32", "--batch_size", "2", "--num_steps", "4",
              "--corpus_tokens", "20000",
              "--checkpoint_dir", str(tmp_path)])
    assert np.isfinite(r["final_loss"])


@pytest.mark.slow
def test_moe_ep_with_tp_matches_ep_only(tmp_path):
    """ep × tp: expert parallelism (manual all_to_all dispatch over ep)
    composed with GSPMD tensor parallelism on the 3-D (gossip, ep, tp)
    mesh — same tokens, same routing ⇒ same losses as the ep-only run,
    and the expert/projection kernels really shard over tp."""
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from stochastic_gradient_push_tpu.run.gossip_lm import main
    from stochastic_gradient_push_tpu.train.lm import (
        EP_AXIS, TP_AXIS, ep_tp_sharding_tree, make_dp_ep_tp_mesh)

    common = ["--moe_experts", "4", "--moe_every", "1", "--seq_len", "32",
              "--d_model", "32", "--n_layers", "2", "--n_heads", "4",
              "--d_ff", "64", "--vocab_size", "64", "--batch_size", "4",
              "--num_steps", "4", "--corpus_tokens", "20000",
              "--print_freq", "2"]
    r_tp = main(["--world_size", "8", "--ep", "2", "--tp", "2",
                 "--checkpoint_dir", str(tmp_path / "tp")] + common)
    r_ep = main(["--world_size", "4", "--ep", "2",
                 "--checkpoint_dir", str(tmp_path / "ep")] + common)
    assert np.isfinite(r_tp["final_loss"])
    np.testing.assert_allclose(r_tp["final_loss"], r_ep["final_loss"],
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(r_tp["avg_loss"], r_ep["avg_loss"],
                               rtol=2e-5, atol=2e-5)

    # the sharding tree really puts tp on expert FFN dims and ep on the
    # expert dim (a replicated layout would make the parity vacuous)
    import jax
    import jax.numpy as jnp

    mesh = make_dp_ep_tp_mesh(2, 2, 2)
    probe = {"block_0": {"moe": {"experts_up": jnp.zeros((2, 4, 8, 16)),
                                 "experts_down": jnp.zeros((2, 4, 16, 8)),
                                 "router": {"kernel": jnp.zeros((2, 8, 4))}}}}
    shard = ep_tp_sharding_tree(probe, mesh)
    assert shard["block_0"]["moe"]["experts_up"].spec == \
        P("gossip", EP_AXIS, None, TP_AXIS)
    assert shard["block_0"]["moe"]["experts_down"].spec == \
        P("gossip", EP_AXIS, TP_AXIS, None)
    assert shard["block_0"]["moe"]["router"]["kernel"].spec == \
        P("gossip", None, None)


@pytest.mark.slow
def test_moe_pp_trains(tmp_path):
    """MoE × pipeline through the CLI: replicated expert blocks routed per
    microbatch inside the tick schedule (moe_every=1)."""
    import numpy as np

    from stochastic_gradient_push_tpu.run.gossip_lm import main

    r = main(["--world_size", "8", "--pp", "2", "--n_micro", "2",
              "--moe_experts", "4", "--moe_every", "1",
              "--seq_len", "32", "--d_model", "32", "--n_layers", "2",
              "--n_heads", "4", "--d_ff", "32", "--vocab_size", "32",
              "--batch_size", "4", "--num_steps", "4",
              "--corpus_tokens", "40000", "--print_freq", "2",
              "--val_frac", "0.1", "--val_every", "2", "--val_batches",
              "2", "--checkpoint_dir", str(tmp_path)])
    assert np.isfinite(r["final_loss"])
    # the pipelined eval path (stage-gated head) produced a real value
    assert np.isfinite(r["val_loss"])


@pytest.mark.slow
def test_moe_pp_ep_trains(tmp_path):
    """pp × ep through the CLI: expert-sharded dispatch (all_to_all over
    ep) inside the pipeline tick schedule, with held-out validation."""
    import numpy as np

    from stochastic_gradient_push_tpu.run.gossip_lm import main

    r = main(["--world_size", "8", "--pp", "2", "--ep", "2",
              "--n_micro", "2", "--moe_experts", "4", "--moe_every", "1",
              "--seq_len", "32", "--d_model", "32", "--n_layers", "2",
              "--n_heads", "4", "--d_ff", "32", "--vocab_size", "32",
              "--batch_size", "4", "--num_steps", "4",
              "--corpus_tokens", "40000", "--print_freq", "2",
              "--val_frac", "0.1", "--val_every", "2", "--val_batches",
              "2", "--checkpoint_dir", str(tmp_path)])
    assert np.isfinite(r["final_loss"])
    assert np.isfinite(r["val_loss"])


@pytest.mark.slow
def test_moe_pp_sp_trains(tmp_path):
    """MoE × pp × sp through the CLI: per-block expert routing inside the
    ring-attention pipeline ticks."""
    import numpy as np

    from stochastic_gradient_push_tpu.run.gossip_lm import main

    r = main(["--world_size", "8", "--pp", "2", "--sp", "2",
              "--n_micro", "2", "--moe_experts", "4", "--moe_every", "1",
              "--seq_len", "32", "--d_model", "32", "--n_layers", "2",
              "--n_heads", "4", "--d_ff", "32", "--vocab_size", "32",
              "--batch_size", "4", "--num_steps", "3",
              "--corpus_tokens", "20000", "--print_freq", "3",
              "--checkpoint_dir", str(tmp_path)])
    assert np.isfinite(r["final_loss"])


@pytest.mark.slow
def test_moe_pp_ep_sp_4d_trains(tmp_path):
    """The 4-D pipeline mesh through the CLI: gossip × pipe × ep × seq
    with validation through the same composed forward."""
    import numpy as np

    from stochastic_gradient_push_tpu.run.gossip_lm import main

    r = main(["--world_size", "8", "--pp", "2", "--ep", "2", "--sp", "2",
              "--n_micro", "2", "--moe_experts", "4", "--moe_every", "1",
              "--seq_len", "32", "--d_model", "32", "--n_layers", "2",
              "--n_heads", "4", "--d_ff", "32", "--vocab_size", "64",
              "--batch_size", "4", "--num_steps", "3",
              "--corpus_tokens", "40000", "--print_freq", "3",
              "--val_frac", "0.1", "--val_every", "3",
              "--val_batches", "2", "--checkpoint_dir", str(tmp_path)])
    assert np.isfinite(r["final_loss"])
    assert np.isfinite(r["val_loss"])


@pytest.mark.skipif(
    jax.__version_info__ < (0, 5),
    reason="marginal 6-step convergence threshold (3.6) calibrated on "
           "newer jax; under the jax<0.5 compat shim the ep x sp run "
           "still trains (finite, decreasing loss) but lands ~0.07 above "
           "it")
def test_moe_ep_with_ring_sp_trains(tmp_path):
    """ep x sp: expert parallelism (all_to_all over ep) composed with
    ring sequence parallelism on the 3-D (gossip, ep, seq) mesh."""
    import numpy as np

    from stochastic_gradient_push_tpu.run.gossip_lm import main

    r = main(["--world_size", "8", "--ep", "2", "--sp", "2",
              "--moe_experts", "4", "--moe_every", "2",
              "--seq_len", "32", "--d_model", "32", "--n_layers", "2",
              "--n_heads", "4", "--d_ff", "32", "--vocab_size", "32",
              "--batch_size", "2", "--num_steps", "6",
              "--corpus_tokens", "40000", "--print_freq", "2",
              "--val_frac", "0.1", "--val_every", "2", "--val_batches",
              "2", "--checkpoint_dir", str(tmp_path)])
    assert np.isfinite(r["final_loss"])
    # the expert-dispatched eval path (ep × sp) produced a real value
    assert np.isfinite(r["val_loss"])
    # divergence guard: stay at or below the uniform-prediction loss
    # (log 32 ≈ 3.47 + small MoE aux term) after 6 steps
    assert r["final_loss"] < 3.6
