"""Test configuration: force an 8-device virtual CPU platform.

The reference had no fake/loopback backend and therefore no tests
(SURVEY.md §4).  Here every distributed code path runs on
``xla_force_host_platform_device_count=8`` CPU devices, so the full mesh /
ppermute machinery is exercised without TPU hardware.
"""

import os

# force CPU even when the session has a TPU platform configured
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# the container's sitecustomize registers a TPU platform plugin and pins
# jax_platforms before this file runs; override it back to CPU
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)
