"""Multi-host execution: 2 processes × 4 CPU devices over jax.distributed.

The reference's raison d'être is multi-node training (one process per GPU,
``dist.init_process_group``, gossip_sgd.py:586-690).  The TPU counterpart
is JAX's multi-controller model: every process runs the same program, owns
a slice of every global array, feeds its local ranks'
batches, and writes its own CSV/checkpoint files.  This test proves that
path end-to-end on localhost: rendezvous, cross-process gossip ppermute,
per-process feeding (``jax.make_array_from_process_local_data``),
per-process checkpoint save — then a second launch that *resumes* from the
per-process files.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

# every test here launches 2 OS processes that rendezvous over
# jax.distributed and compile their own programs — minutes each
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _launch(port: int, proc_id: int, ckpt_dir: str, epochs: int,
            resume: str, extra: tuple = ()) -> subprocess.Popen:
    args = [
        sys.executable, "-m", "stochastic_gradient_push_tpu.run.gossip_sgd",
        "--multihost", "True",
        "--coordinator_address", f"127.0.0.1:{port}",
        "--num_processes", "2", "--process_id", str(proc_id),
        "--dataset", "synthetic", "--world_size", "8",
        "--model", "tiny_cnn", "--image_size", "12", "--num_classes", "10",
        "--batch_size", "4", "--num_epochs", str(epochs),
        "--num_iterations_per_training_epoch", "4",
        "--num_itr_ignore", "0", "--print_freq", "1",
        "--checkpoint_dir", ckpt_dir, "--per_rank_csv", "True",
        "--resume", resume, "--verbose", "True", *extra,
    ]
    return subprocess.Popen(args, cwd=REPO, env=_worker_env(),
                            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                            text=True)


def _run_pair(port: int, ckpt_dir: str, epochs: int, resume: str,
              extra: tuple = ()) -> list[str]:
    procs = [_launch(port, i, ckpt_dir, epochs, resume, extra)
             for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out[-4000:]}"
    return outs


def test_two_process_train_and_resume(tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    port = _free_port()
    outs = _run_pair(port, ckpt_dir, epochs=1, resume="False")

    # each process reported its rank ownership
    assert "feeding batch rows [0, 1, 2, 3]" in outs[0]
    assert "feeding batch rows [4, 5, 6, 7]" in outs[1]

    # per-process checkpoints: r0 from process 0, r1 from process 1
    assert os.path.isfile(os.path.join(ckpt_dir, "checkpoint_r0_n8.ckpt"))
    assert os.path.isfile(os.path.join(ckpt_dir, "checkpoint_r1_n8.ckpt"))

    # per-rank CSVs from both processes, with training rows
    for r in range(8):
        f = os.path.join(ckpt_dir, f"out_r{r}_n8.csv")
        assert os.path.isfile(f), f"missing per-rank csv for rank {r}"
        rows = [l for l in open(f).read().splitlines()
                if l and l[0].isdigit()]
        assert rows, f"no data rows in {f}"
        # loss column (index 5) is finite on every row
        losses = [float(row.split(",")[5]) for row in rows
                  if row.split(",")[1] != "-1"]
        assert losses and all(np.isfinite(losses))

    # resume: a fresh 2-epoch launch continues from the epoch-1 checkpoint
    port2 = _free_port()
    outs2 = _run_pair(port2, ckpt_dir, epochs=2, resume="True")
    assert any("resumed from epoch 1" in o for o in outs2[:1]), \
        outs2[0][-2000:]


def test_two_process_hierarchical_mesh(tmp_path):
    """Hierarchical (node, local) gossip across 2 processes: exact psum
    averaging inside each node, gossip between nodes, with node boundaries
    aligned to hosts (4 nodes x 2 local devices over 2 processes)."""
    ckpt_dir = str(tmp_path / "ckpt")
    port = _free_port()
    outs = _run_pair(port, ckpt_dir, epochs=1, resume="False",
                     extra=("--nprocs_per_node", "2"))
    # batch rows are device rows; node ranks 0-1 on proc 0, 2-3 on proc 1
    assert "feeding batch rows [0, 1, 2, 3]" in outs[0]
    assert "feeding batch rows [4, 5, 6, 7]" in outs[1]
    # per-rank CSVs are per NODE rank (4 nodes), split across processes
    for r in range(4):
        f = os.path.join(ckpt_dir, f"out_r{r}_n8.csv")
        assert os.path.isfile(f), f"missing node-rank csv {r}"
        rows = [l for l in open(f).read().splitlines()
                if l and l[0].isdigit()]
        assert rows
    assert os.path.isfile(os.path.join(ckpt_dir, "checkpoint_r0_n8.ckpt"))
    assert os.path.isfile(os.path.join(ckpt_dir, "checkpoint_r1_n8.ckpt"))


def test_two_process_orbax_checkpointing(tmp_path):
    """Orbax backend on a 2-process cluster: jax.Array-native global-state
    mode — ONE shared root, every process writes its own shards of the
    global arrays (orbax's numpy handlers only ever write on host 0, so
    host-local trees would silently save empty on process 1), and a fresh
    launch restores the sharded state directly."""
    ckpt_dir = str(tmp_path / "ckpt")
    port = _free_port()
    outs = _run_pair(port, ckpt_dir, epochs=1, resume="False",
                     extra=("--ckpt_backend", "orbax"))
    assert "feeding batch rows [0, 1, 2, 3]" in outs[0]
    assert "feeding batch rows [4, 5, 6, 7]" in outs[1]
    # one shared global root with at least one landed step
    root = os.path.join(ckpt_dir, "orbax_global_n8")
    assert os.path.isdir(root), "missing shared orbax root"
    steps = [d for d in os.listdir(root)
             if d.isdigit() and os.path.isdir(os.path.join(root, d))]
    assert steps, f"no orbax steps under {root}"

    port2 = _free_port()
    outs2 = _run_pair(port2, ckpt_dir, epochs=2, resume="True",
                      extra=("--ckpt_backend", "orbax"))
    assert all("resumed from epoch 1" in o for o in outs2), \
        outs2[0][-2000:]
