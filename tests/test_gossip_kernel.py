"""Split Pallas gossip transport (ops/gossip_kernel.py — paired
start/wait ops plus the fused axpy composition): kernel-vs-XLA
bit-parity across sync, overlap and bucketed rounds, chunking,
resolver contracts, and flag plumbing.

The parity sweep runs both transport lanes of the SAME algorithm
configuration on the world-8 CPU mesh — the kernel through the Pallas
interpreter (the real remote-DMA kernel path, discharged over the mesh
axis), the fallback through ``lax.ppermute`` + ``WireCodec.decode`` —
and requires the push-sum weight trajectory BIT-IDENTICAL (the scalar
lane never enters the kernel) and params within f32 tolerance (the only
permitted difference is XLA fusing the receive axpy into an FMA on the
fallback lane).

Dispatch is serialized (every call drains before the next, per the PR-8
CPU-collective deadlock note), and the sweep lives in ONE test so two
compiled mesh programs never run concurrently.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from stochastic_gradient_push_tpu.algorithms import sgp
from stochastic_gradient_push_tpu.ops.gossip_kernel import (
    DEFAULT_CHUNK_ELEMS,
    KernelBackendError,
    KernelLane,
    gossip_edge_axpy,
    resolve_gossip_kernel,
    resolve_use_pallas,
)
from stochastic_gradient_push_tpu.parallel import wire
from stochastic_gradient_push_tpu.parallel.mesh import (
    GOSSIP_AXIS,
    make_gossip_mesh,
)
from stochastic_gradient_push_tpu.resilience import parse_fault_spec
from stochastic_gradient_push_tpu.topology import (
    HierarchicalGraph,
    RingGraph,
    build_schedule,
)
from stochastic_gradient_push_tpu.topology.synthesized import (
    SynthesizedGraph,
)

WORLD = 8
ROUNDS = 4
FAULT_SPEC = "drop:0->1@0:64;seed:7"


def _world_stack(tree):
    return jax.tree.map(
        lambda a: np.broadcast_to(np.asarray(a),
                                  (WORLD,) + np.shape(a)).copy(), tree)


# -- resolver contracts (host-only, no mesh) --------------------------------


class TestResolvers:
    def test_shared_auto_rule(self):
        # on the CPU test backend: auto = interpret only
        assert resolve_use_pallas(None, interpret=True) is True
        assert resolve_use_pallas(None, interpret=False) is \
            (jax.default_backend() == "tpu")
        # an explicit flag always wins
        assert resolve_use_pallas(True, interpret=False) is True
        assert resolve_use_pallas(False, interpret=True) is False

    def test_flag_resolution(self):
        assert resolve_gossip_kernel(None) is None
        assert resolve_gossip_kernel("xla") is None
        lane = resolve_gossip_kernel("auto", interpret=True)
        assert isinstance(lane, KernelLane) and lane.interpret
        assert lane.name == "pallas"
        assert lane.chunk_elems == DEFAULT_CHUNK_ELEMS
        if jax.default_backend() != "tpu":
            assert resolve_gossip_kernel("auto") is None

    def test_pallas_on_cpu_is_a_typed_error(self):
        if jax.default_backend() == "tpu":
            pytest.skip("rejection is the non-TPU contract")
        with pytest.raises(KernelBackendError, match="TPU backend"):
            resolve_gossip_kernel("pallas")
        # interpret mode IS a valid pallas carrier (the test lane)
        assert resolve_gossip_kernel("pallas", interpret=True) is not None

    def test_unknown_flag(self):
        with pytest.raises(ValueError, match="unknown gossip_kernel"):
            resolve_gossip_kernel("mosaic")

    def test_algorithm_resolves_flag_strings(self):
        sched = build_schedule(RingGraph(WORLD, peers_per_itr=1))
        assert sgp(sched, GOSSIP_AXIS, gossip_kernel="xla") \
            .gossip_kernel is None
        if jax.default_backend() != "tpu":
            assert sgp(sched, GOSSIP_AXIS, gossip_kernel="auto") \
                .gossip_kernel is None
            with pytest.raises(KernelBackendError):
                sgp(sched, GOSSIP_AXIS, gossip_kernel="pallas")
        lane = KernelLane(interpret=True)
        assert sgp(sched, GOSSIP_AXIS,
                   gossip_kernel=lane).gossip_kernel is lane

    def test_overlap_keeps_the_kernel_lane(self):
        # the split start/wait kernel issues its remote DMA at launch
        # and lands it at consume, so overlap rounds ride the pallas
        # lane first-class — the old forced-xla downgrade is gone and
        # telemetry must stamp the lane that actually runs
        sched = build_schedule(RingGraph(WORLD, peers_per_itr=1))
        lane = KernelLane(interpret=True)
        sync_alg = sgp(sched, GOSSIP_AXIS, gossip_kernel=lane)
        over_alg = sgp(sched, GOSSIP_AXIS, gossip_kernel=lane,
                       overlap=True, staleness=2)
        assert sync_alg.transport_kernel_name == "pallas"
        assert over_alg.transport_kernel_name == "pallas"
        assert over_alg.gossip_kernel is lane
        assert sgp(sched, GOSSIP_AXIS).transport_kernel_name == "xla"
        assert sgp(sched, GOSSIP_AXIS,
                   overlap=True, staleness=2).transport_kernel_name \
            == "xla"

    def test_specless_codec_resolves_to_xla_lane(self):
        # a lossy codec with no in-kernel decode spec pins the XLA path
        # at _edge_transport — telemetry must stamp what actually runs,
        # not the requested lane
        class Opaque(wire.WireCodec):
            name = "opaque"
            lossy = True

        sched = build_schedule(RingGraph(WORLD, peers_per_itr=1))
        lane = KernelLane(interpret=True)
        alg = sgp(sched, GOSSIP_AXIS, gossip_kernel=lane, wire=Opaque())
        assert alg.transport_kernel_name == "xla"
        # a lossy codec WITH a spec (and the lossless exact wire, which
        # the kernel carries as the f32 passthrough) keep the lane
        assert sgp(sched, GOSSIP_AXIS, gossip_kernel=lane,
                   wire=wire.Int8Codec(64)).transport_kernel_name \
            == "pallas"


class TestDecodeSpecs:
    def test_codecs_expose_specs(self):
        assert wire.F32.kernel_spec() == wire.DecodeSpec("f32")
        assert wire.BF16.kernel_spec() == wire.DecodeSpec("bf16")
        assert wire.Int8Codec(32).kernel_spec() == \
            wire.DecodeSpec("int8", block=32)

    def test_unknown_codec_has_no_spec(self):
        class Opaque(wire.WireCodec):
            name = "opaque"
            lossy = True

        # base default: no in-kernel decode — the collective layer must
        # keep such a codec on the XLA path
        assert Opaque().kernel_spec() is None

    def test_kernel_rejects_missing_spec(self):
        with pytest.raises(ValueError, match="no in-kernel decode"):
            gossip_edge_axpy(jnp.zeros(4), (jnp.zeros(4),),
                             [1, 0], GOSSIP_AXIS, None)


# -- flag plumbing ----------------------------------------------------------


class TestFlagPlumbing:
    def test_trainer_config_default(self):
        from stochastic_gradient_push_tpu.train.loop import TrainerConfig

        # conservative default until the kernel's live-TPU capture
        # lands: pallas/auto are explicit opt-ins
        assert TrainerConfig().gossip_kernel == "xla"

    def test_cli_default_and_rejection(self):
        from stochastic_gradient_push_tpu.run.gossip_sgd import (
            parse_config)

        cfg, args = parse_config(["--dataset", "synthetic"])
        assert cfg.gossip_kernel == "xla"
        if jax.default_backend() != "tpu":
            with pytest.raises(SystemExit, match="TPU backend"):
                parse_config(["--dataset", "synthetic",
                              "--gossip_kernel", "pallas"])
        cfg, _ = parse_config(["--dataset", "synthetic",
                               "--gossip_kernel", "xla"])
        assert cfg.gossip_kernel == "xla"

    def test_lm_cli_has_the_flag(self):
        from stochastic_gradient_push_tpu.run.gossip_lm import (
            build_parser)

        args = build_parser().parse_args([])
        assert args.gossip_kernel == "xla"

    def test_comm_model_stamps_the_lane(self):
        from stochastic_gradient_push_tpu.telemetry import CommModel

        sched = build_schedule(RingGraph(WORLD, peers_per_itr=1))
        d = CommModel.from_schedule(sched, 1024,
                                    gossip_kernel="pallas").to_dict()
        assert d["gossip_kernel"] == "pallas"
        # the lane re-times the wire, never re-prices it
        x = CommModel.from_schedule(sched, 1024, gossip_kernel="xla")
        p = CommModel.from_schedule(sched, 1024, gossip_kernel="pallas")
        assert x.totals(6) == p.totals(6)
        assert CommModel.from_schedule(sched, 1024).to_dict()[
            "gossip_kernel"] == "xla"


# -- chunk layout edge cases (the split path computes layouts per
# transport bucket, so every ragged shape below now also reaches the
# kernel through bucketed rounds) -------------------------------------------


class TestChunkLayout:
    def _layout(self, *a):
        from stochastic_gradient_push_tpu.ops.gossip_kernel import (
            _chunk_layout)

        return _chunk_layout(*a)

    def test_ragged_tail(self):
        # 300 elems over 128-elem chunks: 2 full + 1 ragged; the pad is
        # bounded by one chunk's tail
        assert self._layout(300, None, 128) == (128, 128, 3)

    def test_payload_smaller_than_one_chunk(self):
        # the chunk shrinks to the payload — a huge chunk target must
        # never allocate (or pad to) more than the payload itself
        # (companion of the 4 GB-pad pin in the axpy parametrization)
        assert self._layout(33, None, 1 << 30) == (33, 33, 1)

    def test_int8_block7_chunks_are_whole_blocks(self):
        # 300 elems in 7-wide blocks: 43 scale rows; a 64-elem chunk
        # target holds 9 whole blocks — scales stay chunk-local, the
        # ragged row count never splits a block across chunks
        rows, c, nb = self._layout(300, 7, 64)
        assert (rows, c, nb) == (9, 63, 5)
        assert rows * nb >= 43

    def test_payload_smaller_than_one_block(self):
        assert self._layout(3, 7, 64) == (1, 7, 1)

    def test_scalar_leaf_is_rejected(self):
        # the transport plan must route scalar leaves (the ps-weight
        # lane) to the exact-f32 ppermute — reaching the kernel with
        # one is a plan bug, not a layout to accommodate
        from stochastic_gradient_push_tpu.ops.gossip_kernel import (
            _chunk_layout)

        for bad in (0, -1):
            with pytest.raises(ValueError, match="ppermute lane"):
                _chunk_layout(bad, None, 128)

    def test_chunk_elems_validated(self):
        with pytest.raises(ValueError, match="chunk_elems"):
            self._layout(16, None, 0)


# -- the kernel itself ------------------------------------------------------


@pytest.mark.parametrize("n,chunk", [(33, 1 << 30),   # single ragged chunk
                                     (300, 128),      # 3 chunks, ragged tail
                                     (256, 64)])      # exact chunking
def test_edge_axpy_matches_ppermute_decode(n, chunk):
    """Direct kernel call vs the XLA seam it replaces, per codec, across
    chunk layouts (padding must never leak into the axpy)."""
    mesh = make_gossip_mesh(WORLD)
    dests = np.asarray([(r + 3) % WORLD for r in range(WORLD)])
    pairs = [(s, int(dests[s])) for s in range(WORLD)]
    codecs = [None, wire.BF16, wire.Int8Codec(64), wire.Int8Codec(7)]

    def f(xr):
        xr = xr.reshape(-1)
        acc = xr * 0.25
        outs = []
        for codec in codecs:
            if codec is None:
                parts, spec = (xr,), wire.F32.kernel_spec()
                ref = acc + jax.lax.ppermute(xr, GOSSIP_AXIS, pairs)
            else:
                parts, spec = codec.encode(xr), codec.kernel_spec()
                ref = acc + codec.decode(
                    tuple(jax.lax.ppermute(p, GOSSIP_AXIS, pairs)
                          for p in parts), xr)
            out = gossip_edge_axpy(acc, parts, dests, GOSSIP_AXIS, spec,
                                   interpret=True, chunk_elems=chunk)
            outs += [out[None], ref[None]]
        return tuple(outs)

    fn = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P(GOSSIP_AXIS),
                               out_specs=(P(GOSSIP_AXIS),) * 8))
    x = np.random.default_rng(n).normal(
        size=(WORLD, n)).astype(np.float32)
    res = [np.asarray(a) for a in jax.block_until_ready(fn(x))]
    for i, codec in enumerate(codecs):
        kern, ref = res[2 * i], res[2 * i + 1]
        name = codec.name if codec else "f32"
        if codec is None or name == "bf16":
            # pure transport (and the bf16 widen) has no arithmetic for
            # XLA to re-fuse: bit-identical
            np.testing.assert_array_equal(
                kern, ref, err_msg=f"codec {name}, n={n}, chunk={chunk}")
        else:
            # int8 dequant: XLA may fuse the reference's decode+add into
            # an FMA; the kernel's round-to-nearest product is the f32
            # tolerance the acceptance bound allows
            np.testing.assert_allclose(
                kern, ref, rtol=0, atol=1e-6,
                err_msg=f"codec {name}, n={n}, chunk={chunk}")


def test_compiled_mode_kernel_carries_the_entry_barrier():
    """The compiled (non-interpret) kernel must run the inter-device
    entry barrier before its first remote copy — signal dst AND src on
    the collective_id-keyed barrier semaphore, wait both back down.
    Mosaic lowering needs a real TPU, but the kernel body is traced at
    pallas_call time, so abstract eval catches a broken barrier (wrong
    primitive signature, mismatched SMEM spec) here: trace the
    interpret=False path and pin the barrier ops in the jaxpr.  The
    interpret path must stay barrier-free (jax's discharge rules are
    synchronous and cannot signal remote semaphores)."""
    mesh = make_gossip_mesh(WORLD)
    dests = np.asarray([(r + 1) % WORLD for r in range(WORLD)])
    codec = wire.Int8Codec(64)

    def f(interpret):
        def inner(xr):
            xr = xr.reshape(-1)
            return gossip_edge_axpy(
                xr * 0.25, codec.encode(xr), dests, GOSSIP_AXIS,
                codec.kernel_spec(), interpret=interpret,
                chunk_elems=128, collective_id=5)[None]
        return inner

    x = np.zeros((WORLD, 300), np.float32)
    traced = jax.make_jaxpr(jax.shard_map(
        f(False), mesh=mesh, in_specs=P(GOSSIP_AXIS),
        out_specs=P(GOSSIP_AXIS)))(x)
    s = str(traced)
    for op in ("get_barrier_semaphore", "semaphore_signal",
               "semaphore_wait"):
        assert op in s, f"compiled-mode kernel jaxpr lost {op}"
    interp = str(jax.make_jaxpr(jax.shard_map(
        f(True), mesh=mesh, in_specs=P(GOSSIP_AXIS),
        out_specs=P(GOSSIP_AXIS)))(x))
    assert "get_barrier_semaphore" not in interp, (
        "interpret-mode kernel must not emit the barrier (remote "
        "semaphore signals have no discharge rule)")


def test_dests_must_be_a_permutation():
    # the barrier handshakes with the permutation's inverse at this
    # rank, which only exists for a bijection — reject garbage early
    with pytest.raises(ValueError, match="permutation"):
        gossip_edge_axpy(jnp.zeros(4), (jnp.zeros(4),), [1, 1],
                         GOSSIP_AXIS, wire.F32.kernel_spec(),
                         interpret=True)


def _run_rounds(schedule, kernel, codec=None, ef=False, faults=None,
                thin=1, overlap=False, staleness=1, buckets=1, leaf=96):
    """ROUNDS gossip steps of one configured PushSumGossip on one
    transport lane; returns (params tree, ps-weight trajectory)."""
    alg = sgp(schedule, GOSSIP_AXIS, wire=codec, error_feedback=ef,
              faults=faults, gossip_every=thin, overlap=overlap,
              staleness=staleness, gossip_kernel=kernel,
              gossip_buckets=buckets)

    def step(p, g):
        p, g = alg.pre_step(p, g)
        return alg.post_step(p, g)

    mesh = make_gossip_mesh(WORLD)
    fn = jax.jit(jax.shard_map(step, mesh=mesh,
                               in_specs=(P(GOSSIP_AXIS),) * 2,
                               out_specs=(P(GOSSIP_AXIS),) * 2))
    rng = np.random.default_rng(0)
    params = {"w": rng.normal(size=(WORLD, leaf)).astype(np.float32),
              "b": rng.normal(size=(WORLD, 5)).astype(np.float32)}
    gstate = _world_stack(alg.init(
        jax.tree.map(lambda a: jnp.zeros(a.shape[1:], a.dtype), params)))
    traj = []
    for _ in range(ROUNDS):
        params, gstate = jax.block_until_ready(fn(params, gstate))
        traj.append(np.asarray(gstate.ps_weight).copy())
    return (jax.tree.map(np.asarray, params), np.stack(traj))


def test_parity_sweep_kernel_vs_xla():
    """The acceptance sweep: {f32, bf16, int8} × {EF on/off} × {plain,
    drop fault, thinning} × {sync, overlap staleness 2} × {1, 3
    transport buckets}, kernel lane vs XLA lane.  ps-weight
    trajectories bit-identical; params within f32 tolerance (FMA fusion
    on the fallback lane is the only slack).  The overlap rows now run
    the REAL kernel lane — the split start/wait transport launches its
    per-bucket remote DMA at the top of the step and lands it at the
    bottom (no forced-xla downgrade); the bucketed rows pin that the
    pipelining granularity never changes the round.

    One test on purpose: the sweep serializes its world-8 compiled
    programs (PR-8 deadlock note) and pairs each config's two lanes
    back to back.
    """
    sched = build_schedule(RingGraph(WORLD, peers_per_itr=1))
    i8 = wire.Int8Codec(64)
    # (label, codec, ef, fault, thin, overlap, buckets)
    sweep = [
        ("f32/sync", None, False, False, 1, False, 1),
        ("f32/sync/fault", None, False, True, 1, False, 1),
        ("f32/overlap2/thin", None, False, False, 2, True, 1),
        ("f32/overlap2/thin/b3", None, False, False, 2, True, 3),
        ("bf16/overlap2", wire.BF16, False, False, 1, True, 1),
        ("bf16+ef/sync/fault", wire.BF16, True, True, 1, False, 1),
        ("bf16+ef/sync/thin", wire.BF16, True, False, 2, False, 1),
        ("int8/sync", i8, False, False, 1, False, 1),
        ("int8/sync/b3", i8, False, False, 1, False, 3),
        ("int8+ef/overlap2/fault", i8, True, True, 1, True, 1),
        ("int8+ef/overlap2/fault/b3", i8, True, True, 1, True, 3),
        ("int8+ef/overlap2/thin", i8, True, False, 2, True, 1),
        ("int8+ef/sync", i8, True, False, 1, False, 1),
    ]
    for label, codec, ef, fault, thin, overlap, buckets in sweep:
        faults = (parse_fault_spec(FAULT_SPEC)
                  .build_masks(sched, gossip_every=thin)
                  if fault else None)
        kw = dict(codec=codec, ef=ef, faults=faults, thin=thin,
                  overlap=overlap, staleness=2 if overlap else 1,
                  buckets=buckets)
        p_x, w_x = _run_rounds(sched, None, **kw)
        p_k, w_k = _run_rounds(sched, KernelLane(interpret=True), **kw)
        np.testing.assert_array_equal(
            w_x, w_k,
            err_msg=f"[{label}] ps-weight trajectory must be "
                    "bit-identical across transport lanes")
        for leaf in p_x:
            d = np.abs(p_x[leaf] - p_k[leaf]).max()
            assert d <= 1e-6, (
                f"[{label}] leaf {leaf!r} diverged {d:.2e} across "
                "transport lanes (beyond f32/FMA tolerance)")


def test_hierarchical_delegate_rides_the_kernel():
    """Hierarchical rounds: the delegate (inter) edge phase takes the
    fused transport, the grouped intra-slice psum stays lax.psum — the
    two lanes must still agree."""
    sched = build_schedule(HierarchicalGraph(WORLD, slice_size=4))
    for codec, ef in [(None, False), (wire.Int8Codec(64), True)]:
        p_x, w_x = _run_rounds(sched, None, codec=codec, ef=ef)
        p_k, w_k = _run_rounds(sched, KernelLane(interpret=True),
                               codec=codec, ef=ef)
        np.testing.assert_array_equal(w_x, w_k)
        for leaf in p_x:
            assert np.abs(p_x[leaf] - p_k[leaf]).max() <= 1e-6


def test_synthesized_edge_phase_rides_the_kernel():
    """Synthesized compositions: edge phases take the fused transport,
    grouped psum phases stay exact collectives."""
    spec = {"v": 1, "world": WORLD, "phases": [
        {"kind": "edge",
         "perm": [(r + 1) % WORLD for r in range(WORLD)],
         "send": [0.5] * WORLD},
        {"kind": "psum", "group_size": 4},
    ]}
    sched = build_schedule(SynthesizedGraph(WORLD, spec=spec))
    p_x, w_x = _run_rounds(sched, None, codec=wire.Int8Codec(64),
                           ef=True)
    p_k, w_k = _run_rounds(sched, KernelLane(interpret=True),
                           codec=wire.Int8Codec(64), ef=True)
    np.testing.assert_array_equal(w_x, w_k)
    for leaf in p_x:
        assert np.abs(p_x[leaf] - p_k[leaf]).max() <= 1e-6
