"""telemetry/: tracer round-trip, event schema, sinks, comm accounting,
producer wiring, and the --trace_dir end-to-end acceptance pins.

The two invariants the train loop depends on are pinned here:

* disabled telemetry is free — the null tracer returns one shared span
  object and never reads a clock (poisoned-clock test), and a fit with
  telemetry enabled performs exactly the same number of device syncs as
  one without (counted-sync test);
* the comm-bytes accounting a real CLI run reports equals the analytic
  model built independently for the same plan (acceptance criterion).
"""

import importlib.util
import io
import json
import logging
import os
import sys
import time

import jax
import numpy as np
import pytest

from stochastic_gradient_push_tpu.telemetry import (
    COMM_CATEGORIES,
    EVENTS_FILE,
    NULL_TELEMETRY,
    TRACE_FILE,
    CommAccountant,
    CommModel,
    JsonlSink,
    LoggerCompatSink,
    MemorySink,
    SpanTracer,
    TelemetryRegistry,
    allreduce_bytes,
    make_run_telemetry,
    tree_payload_bytes,
)
from stochastic_gradient_push_tpu.topology import RingGraph, build_schedule

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORLD = 8


def _load_script(filename, modname):
    spec = importlib.util.spec_from_file_location(
        modname, os.path.join(REPO, filename))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def obsreport():
    return _load_script(os.path.join("scripts", "obsreport.py"),
                        "obsreport_under_test")


class _ListHandler(logging.Handler):
    def __init__(self):
        super().__init__()
        self.lines: list[tuple[str, str]] = []  # (levelname, message)

    def emit(self, record):
        self.lines.append((record.levelname, record.getMessage()))


def _list_logger(name="telemetry-test-log"):
    log = logging.getLogger(name)
    for h in list(log.handlers):
        log.removeHandler(h)
    h = _ListHandler()
    log.addHandler(h)
    log.setLevel(logging.DEBUG)
    log.propagate = False
    return log, h


# -- tracer ----------------------------------------------------------------


class TestTracer:
    def test_chrome_roundtrip_schema(self, tmp_path, obsreport):
        tracer = SpanTracer(rank=3)
        with tracer.span("checkpoint_save", "checkpoint", {"epoch": 0}):
            pass
        t0 = tracer.now()
        # deliberately recorded out of order: export must sort
        tracer.complete("train_step", "step", t0 + 0.10, 0.01,
                        {"steps": 1, "gossip": 1})
        tracer.complete("data_fetch", "data", t0 + 0.05, 0.02)
        tracer.instant("excursion", "step")
        path = str(tmp_path / TRACE_FILE)
        tracer.write(path)

        events = obsreport.load_trace(tmp_path)
        assert obsreport.check_trace(events) == []
        xs = [e for e in events if e["ph"] == "X"]
        assert {e["name"] for e in xs} == {
            "checkpoint_save", "train_step", "data_fetch", "excursion"}
        # rank label: every event carries the tracer's rank as pid
        assert {e["pid"] for e in xs} == {3}
        # phase labels: thread-name metadata names each used track
        meta = [e for e in events if e["ph"] == "M"
                and e["name"] == "thread_name"]
        assert {m["args"]["name"] for m in meta} == {
            "checkpoint", "step", "data"}
        # monotone timestamps despite insertion order
        ts = [e["ts"] for e in xs]
        assert ts == sorted(ts)

    def test_durations_accessor(self):
        tracer = SpanTracer()
        tracer.complete("bench", "bench", 0.0, 1.5)
        tracer.complete("bench", "bench", 2.0, 0.5)
        assert tracer.durations("bench") == [1.5, 0.5]
        assert tracer.durations("missing") == []

    def test_disabled_tracer_no_clock_no_allocation(self):
        """The null path must not read a clock or mint objects: span()
        returns one shared instance, and the null tracer holds no clock
        at all — while the enabled path demonstrably reads it."""
        calls = {"n": 0}

        def counting_clock():
            calls["n"] += 1
            return float(calls["n"])

        live = SpanTracer(clock=counting_clock)
        before = calls["n"]
        with live.span("train_step", "step"):
            pass
        assert calls["n"] == before + 2  # enabled: enter + exit reads
        # the disabled tracer has no clock to read, per-step or ever
        assert not hasattr(NULL_TELEMETRY.tracer, "_clock")
        s1 = NULL_TELEMETRY.span("train_step", "step")
        s2 = NULL_TELEMETRY.span("data_fetch", "data")
        assert s1 is s2  # the shared singleton: no per-call allocation
        with s1:
            pass
        NULL_TELEMETRY.trace_complete("x", "step", 0.0, 1.0)
        NULL_TELEMETRY.emit_comm()
        NULL_TELEMETRY.finish()


# -- registry + sinks ------------------------------------------------------


class TestRegistry:
    def test_envelope_and_sinks(self, tmp_path):
        mem = MemorySink()
        jsonl = JsonlSink(str(tmp_path / EVENTS_FILE))
        reg = TelemetryRegistry(rank=2, sinks=[mem, jsonl])
        ev = reg.emit("health", {"step": 5, "consensus_residual": 0.1},
                      step=5, severity="warning")
        assert ev["v"] == 1 and ev["kind"] == "health"
        assert ev["rank"] == 2 and ev["step"] == 5
        assert ev["severity"] == "warning"
        jsonl.close()
        lines = (tmp_path / EVENTS_FILE).read_text().splitlines()
        assert json.loads(lines[0]) == ev
        assert mem.by_kind("health") == [ev]
        assert reg.counts == {"health": 1}

    def test_schema_is_enforced(self):
        reg = TelemetryRegistry()
        with pytest.raises(ValueError, match="unknown event kind"):
            reg.emit("made-up-kind", {})
        with pytest.raises(ValueError, match="severity"):
            reg.emit("health", {}, severity="loud")
        with pytest.raises(TypeError):
            reg.emit("health", "not a dict")

    def test_compat_sink_reproduces_legacy_lines_exactly(self):
        log, h = _list_logger()
        reg = TelemetryRegistry(sinks=[LoggerCompatSink(log)])
        payload = {"step": 7, "consensus_residual": 0.5,
                   "reasons": ["residual-above-floor"]}
        reg.emit("health", payload, step=7, severity="warning")
        reg.emit("plan", {"topology": "ring"}, severity="info")
        reg.emit("recovery", {"action": "global-average"},
                 severity="warning")
        reg.emit("step_stats", {"loss": 1.0})  # new kind: no legacy line
        assert h.lines == [
            ("WARNING", "gossip health: "
             + json.dumps(payload, sort_keys=True)),
            ("INFO", 'gossip plan: {"topology": "ring"}'),
            ("WARNING", 'gossip recovery: {"action": "global-average"}'),
        ]

    def test_serve_kinds_in_closed_vocabulary(self):
        """The serving stack's kinds are declared: `serve` renders a
        legacy-style line, `request` is typed-only, and both have a
        span-phase track for the tracer."""
        from stochastic_gradient_push_tpu.telemetry import (
            EVENT_KINDS, LEGACY_PREFIXES, SPAN_PHASES)

        assert {"serve", "request"} <= EVENT_KINDS
        assert LEGACY_PREFIXES["serve"] == "gossip serve"
        assert "request" not in LEGACY_PREFIXES
        assert "serve" in SPAN_PHASES and "request" in SPAN_PHASES
        reg = TelemetryRegistry()
        assert reg.emit("serve", {"phase": "summary"})["kind"] == "serve"
        assert reg.emit("request", {"id": 1})["kind"] == "request"

    def test_serve_compat_line_is_byte_stable(self):
        """`gossip serve: {sorted json}` — the exact legacy line shape,
        so grep pipelines keyed on the other `gossip <kind>:` prefixes
        extend to serving unchanged; `request` events emit no line."""
        log, h = _list_logger()
        reg = TelemetryRegistry(sinks=[LoggerCompatSink(log)])
        summary = {"tokens_per_sec": 12.5, "requests": 3,
                   "phase": "summary"}
        reg.emit("serve", summary)
        reg.emit("request", {"id": 0, "latency_s": 0.25})  # typed-only
        reg.emit("serve", {"phase": "reject", "id": 9},
                 severity="warning")
        assert h.lines == [
            ("INFO", "gossip serve: "
             + json.dumps(summary, sort_keys=True)),
            ("WARNING", 'gossip serve: {"id": 9, "phase": "reject"}'),
        ]


# -- producer wiring -------------------------------------------------------


class TestProducers:
    def _reg(self):
        log, h = _list_logger()
        mem = MemorySink()
        return TelemetryRegistry(sinks=[mem, LoggerCompatSink(log)]), \
            mem, h

    def test_monitor_publishes_typed_events_once(self):
        from stochastic_gradient_push_tpu.resilience import HealthMonitor
        from stochastic_gradient_push_tpu.resilience.monitor import (
            HEALTH_KEYS)

        reg, mem, h = self._reg()
        direct_log, direct_h = _list_logger("telemetry-test-direct")
        mon = HealthMonitor(health_every=2, residual_floor=0.01,
                            log=direct_log, registry=reg)
        healthy = dict.fromkeys(HEALTH_KEYS, 0.0)
        healthy.update(ps_w_min=1.0, ps_w_max=1.0)
        mon.observe(0, healthy)                 # due -> info event
        mon.observe(1, healthy)                 # not due -> nothing
        sick = dict(healthy, consensus_residual=0.5)
        report = mon.observe(3, sick)           # excursion -> warning
        assert report.unhealthy
        events = mem.by_kind("health")
        assert [e["severity"] for e in events] == ["info", "warning"]
        assert events[1]["data"]["reasons"] == ["residual-above-floor"]
        # exactly one legacy line per emitted event, all via the compat
        # sink — the monitor's direct logger stayed silent (no doubles)
        assert len(h.lines) == 2
        assert direct_h.lines == []
        assert mon.reports == 2 and mon.excursions == 1

    def test_recovery_policy_publishes_event(self):
        from stochastic_gradient_push_tpu.resilience import RecoveryPolicy
        from stochastic_gradient_push_tpu.resilience.monitor import (
            HealthReport)

        reg, mem, h = self._reg()
        policy = RecoveryPolicy(world=WORLD, registry=reg)
        event = policy.assess(HealthReport(
            step=9, payload={}, reasons=("residual-above-floor",)))
        assert event.action == "global-average"
        [ev] = mem.by_kind("recovery")
        assert ev["step"] == 9 and ev["severity"] == "warning"
        assert ev["data"]["action"] == "global-average"
        assert "suggestion" in ev["data"]
        [(lvl, line)] = h.lines
        assert lvl == "WARNING" and line.startswith("gossip recovery: ")

    def test_watchdog_stall_becomes_heartbeat_event(self):
        from stochastic_gradient_push_tpu.utils import StepWatchdog

        reg, mem, _ = self._reg()
        wd = StepWatchdog(timeout=0.05, rank=4, registry=reg)
        with wd.step():
            time.sleep(0.3)
        deadline = time.time() + 2.0
        while not mem.by_kind("heartbeat") and time.time() < deadline:
            time.sleep(0.01)
        [ev] = mem.by_kind("heartbeat")
        assert ev["severity"] == "error"
        assert ev["data"]["timeout_s"] == 0.05
        assert ev["data"]["rank"] == 4
        assert wd.timed_out


# -- comm model ------------------------------------------------------------


class TestCommModel:
    def test_ring_hand_count(self):
        sched = build_schedule(RingGraph(WORLD, peers_per_itr=1))
        model = CommModel.from_schedule(sched, 1000, global_avg_every=4)
        totals = model.totals(8)
        # 8 rounds x 1 msg x (payload + 4B ps-weight)
        assert totals["gossip_wire"] == 8 * 1004
        # every ring edge is hop distance 1 -> hop bytes == wire bytes
        assert totals["gossip_hop_bytes"] == 8 * 1004
        # scheduled exact averages at tick_next % 4 == 0: t = 3 and 7
        assert totals["global_avg"] == 2 * allreduce_bytes(1000, WORLD)
        assert totals["gossip_delivered"] == totals["gossip_wire"]

    def test_thinning_and_dpsgd_weightless(self):
        sched = build_schedule(RingGraph(WORLD, peers_per_itr=1))
        model = CommModel.from_schedule(sched, 1000, gossip_every=2,
                                        ps_weight=False)
        totals = model.totals(8)
        # gossip fires on ticks 0,2,4,6 only; no ps-weight lane
        assert totals["gossip_wire"] == 4 * 1000
        assert totals["global_avg"] == 0

    def test_allreduce_and_bilat_modes(self):
        ar = CommModel.for_allreduce(WORLD, 1000)
        assert ar.totals(5)["allreduce"] == 5 * allreduce_bytes(1000,
                                                                WORLD)
        assert ar.totals(5)["gossip_wire"] == 0
        bi = CommModel.for_bilat(WORLD, 1000)
        assert bi.totals(5)["gossip_wire"] == 5 * 1000  # no weight lane

    def test_accountant_matches_model_and_recovery(self):
        sched = build_schedule(RingGraph(WORLD, peers_per_itr=1))
        model = CommModel.from_schedule(sched, 512, global_avg_every=3)
        acc = CommAccountant(model)
        for t in range(10):
            acc.on_step(t)
        acc.on_recovery()
        want = model.totals(10)
        want["recovery"] = model.recovery_bytes()
        snap = acc.snapshot()
        assert snap["bytes"] == want
        assert snap["steps"] == 10 and snap["recoveries"] == 1
        assert set(snap["bytes"]) == set(COMM_CATEGORIES)

    def test_fault_plan_prices_dropped_edges(self):
        from stochastic_gradient_push_tpu.resilience import (
            parse_fault_spec)

        sched = build_schedule(RingGraph(WORLD, peers_per_itr=1))
        masks = parse_fault_spec("drop:0->1").build_masks(sched)
        model = CommModel.from_schedule(sched, 1000, faults=masks)
        keep = masks.keep_host()
        for t in range(6):
            row = t if t < masks.horizon else (
                masks.horizon + model.phase_at(t))
            assert model.delivered_fraction(t) == pytest.approx(
                float(keep[row].mean()))
        totals = model.totals(6)
        # the dropped edge shaves delivered bytes below the wire bytes
        assert totals["gossip_delivered"] < totals["gossip_wire"]
        # wire traffic itself is fault-independent (dense ppermute)
        assert totals["gossip_wire"] == 6 * 1004


# -- reset_logger (satellite) ----------------------------------------------


def test_reset_logger_rebinds_to_current_stdout():
    from stochastic_gradient_push_tpu.utils import (make_logger,
                                                    reset_logger)

    buf1, buf2 = io.StringIO(), io.StringIO()
    old = sys.stdout
    try:
        sys.stdout = buf1
        reset_logger("telemetry-reset-test")
        make_logger("telemetry-reset-test").info("first")
        sys.stdout = buf2
        # without the reset, the handler stays latched to buf1
        make_logger("telemetry-reset-test").info("latched")
        reset_logger("telemetry-reset-test")
        make_logger("telemetry-reset-test").info("second")
    finally:
        sys.stdout = old
        reset_logger("telemetry-reset-test")
    assert "first" in buf1.getvalue()
    assert "latched" in buf1.getvalue()
    assert "second" not in buf1.getvalue()
    assert "second" in buf2.getvalue()


# -- trainer integration ---------------------------------------------------


@pytest.fixture(scope="module")
def mesh():
    from stochastic_gradient_push_tpu.parallel import make_gossip_mesh

    return make_gossip_mesh(WORLD)


def _tiny_fit(mesh, tmp_dir, trace_dir):
    from stochastic_gradient_push_tpu.data import (
        DistributedSampler, ShardedLoader, synthetic_classification)
    from stochastic_gradient_push_tpu.models import TinyMLP
    from stochastic_gradient_push_tpu.topology import (
        NPeerDynamicDirectedExponentialGraph)
    from stochastic_gradient_push_tpu.train.loop import (
        Trainer, TrainerConfig)

    batch = 8
    images, labels = synthetic_classification(
        n=WORLD * batch * 4, num_classes=4, image_size=8, seed=0)
    cfg = TrainerConfig(
        graph_class=NPeerDynamicDirectedExponentialGraph,
        lr=0.1, warmup=False, lr_schedule={}, batch_size=batch,
        num_epochs=1, num_itr_ignore=0, checkpoint_dir=tmp_dir,
        num_classes=4, verbose=False, heartbeat_timeout=0,
        trace_dir=trace_dir, metrics_every=2 if trace_dir else 0)
    trainer = Trainer(cfg, TinyMLP(num_classes=4), mesh,
                      sample_input_shape=(batch, 8, 8, 3))
    state = trainer.init_state()
    sampler = DistributedSampler(len(images), WORLD)
    loader = ShardedLoader(images, labels, batch, sampler)
    trainer.fit(state, loader, sampler, val_loader=None)
    return trainer


def test_telemetry_adds_zero_device_syncs(tmp_path, mesh, monkeypatch):
    """Acceptance pin: with telemetry enabled the loop performs exactly
    the same number of device syncs per step as with it disabled (and
    the disabled path, being the null object, cannot add any)."""
    counts = {"block": 0, "get": 0}
    real_block = jax.block_until_ready
    real_get = jax.device_get

    def counting_block(x):
        counts["block"] += 1
        return real_block(x)

    def counting_get(x):
        counts["get"] += 1
        return real_get(x)

    monkeypatch.setattr(jax, "block_until_ready", counting_block)
    monkeypatch.setattr(jax, "device_get", counting_get)

    _tiny_fit(mesh, str(tmp_path / "off"), trace_dir=None)
    off = dict(counts)
    counts["block"] = counts["get"] = 0
    _tiny_fit(mesh, str(tmp_path / "on"),
              trace_dir=str(tmp_path / "on" / "telemetry"))
    on = dict(counts)
    assert on == off, (off, on)
    # and the enabled run actually produced its artifacts
    assert (tmp_path / "on" / "telemetry" / TRACE_FILE).is_file()
    assert (tmp_path / "on" / "telemetry" / EVENTS_FILE).is_file()


def test_sgd_cli_trace_dir_end_to_end(tmp_path, capfd, obsreport):
    """Acceptance: a world-8 CPU smoke run with --trace_dir produces a
    loadable trace.json + events.jsonl whose comm accounting matches the
    analytic model for the active plan, with the legacy `gossip *:`
    lines intact on stdout (compatibility view)."""
    import jax.numpy as jnp

    from stochastic_gradient_push_tpu.models import TinyCNN
    from stochastic_gradient_push_tpu.run.gossip_sgd import main
    from stochastic_gradient_push_tpu.utils import reset_logger

    # make_logger latches its stream at first creation; an earlier test
    # may have created these loggers under ITS captured stdout
    for name in ("main", "trainer"):
        reset_logger(name)

    run_dir = str(tmp_path / "run")
    steps, gossip_every = 6, 2
    main(["--dataset", "synthetic", "--model", "tiny_cnn",
          "--num_classes", "10", "--image_size", "16",
          "--batch_size", "4", "--world_size", str(WORLD),
          "--num_epochs", "1",
          "--num_iterations_per_training_epoch", str(steps),
          "--num_itr_ignore", "0", "--topology", "ring",
          "--gossip_every", str(gossip_every),
          "--health_every", "2", "--metrics_every", "2",
          "--trace_dir", run_dir, "--checkpoint_dir", run_dir])
    out = capfd.readouterr().out

    # compatibility view: the legacy line formats still flow to stdout
    assert any("gossip plan: " in l for l in out.splitlines())
    health_lines = [l for l in out.splitlines() if "gossip health: " in l]
    assert health_lines
    json.loads(health_lines[0].split("gossip health: ", 1)[1])

    # events.jsonl: schema-clean, expected kinds present
    events = obsreport.load_events(run_dir)
    assert obsreport.check_events(events) == []
    kinds = {e["kind"] for e in events}
    assert {"plan", "run_meta", "health", "comm",
            "step_stats"} <= kinds

    # trace.json: loadable, monotone, labelled train_step spans
    trace = obsreport.load_trace(run_dir)
    assert obsreport.check_trace(trace) == []
    step_spans = [e for e in trace if e.get("ph") == "X"
                  and e["name"] == "train_step"]
    assert len(step_spans) == steps
    assert {e["args"]["gossip"] for e in step_spans} == {0, 1}

    # comm acceptance: the run's reported bytes equal the analytic model
    # built independently for the active plan (forced ring, ppi 1)
    run_meta = next(e for e in events if e["kind"] == "run_meta")["data"]
    payload = run_meta["comm_model"]["payload_bytes"]
    # the payload itself must match an independently initialized model
    params = TinyCNN(num_classes=10).init(
        jax.random.PRNGKey(0), jnp.zeros((4, 16, 16, 3)))["params"]
    assert payload == tree_payload_bytes(params, 1)
    model = CommModel.from_schedule(
        build_schedule(RingGraph(WORLD, peers_per_itr=1)), payload,
        gossip_every=gossip_every, global_avg_every=0)
    final_comm = [e for e in events if e["kind"] == "comm"][-1]["data"]
    assert final_comm["steps"] == steps
    assert final_comm["bytes"] == model.totals(steps)
    assert final_comm["gossip_rounds"] == sum(
        model.gossip_fires(t) for t in range(steps))

    # the report pipeline digests the run end to end
    report = obsreport.build_report(run_dir)
    assert report["schema_problems"] == []
    assert report["step_time"]["timed_steps"] > 0
    assert report["comm"]["bytes"] == model.totals(steps)
    assert report["ckpt_meta"] is not None  # plan/health rode the ckpt
    assert "plan" in report["ckpt_meta"]


# -- obsreport + bench mode ------------------------------------------------


def test_obsreport_selftest_in_process(obsreport, capsys):
    assert obsreport.selftest() == 0
    assert "obsreport selftest: OK" in capsys.readouterr().out


def test_bench_gossip_vs_ar_mode(tmp_path, monkeypatch):
    """The --gossip-vs-ar bench mode (ROADMAP --global_avg_every item):
    run in-process at a tiny size; the artifact carries measured ms next
    to the modeled per-rank bytes, timed through the span tracer."""
    bench = _load_script("bench.py", "bench_gva_under_test")
    out_path = str(tmp_path / "bench_gva.json")
    monkeypatch.setenv("BENCH_GVA_STEPS", "2")
    monkeypatch.setenv("BENCH_GVA_WARMUP", "1")
    monkeypatch.setenv("BENCH_GVA_BATCH", "2")
    monkeypatch.setenv("BENCH_GVA_GA", "8")
    monkeypatch.setenv("BENCH_GVA_OUT", out_path)
    out = bench.run_gossip_vs_ar()
    assert out["metric"] == "sgp_ga_vs_allreduce_step_ms"
    assert out["value"] > 0 and out["ar_step_ms"] > 0
    assert out["world"] == WORLD and out["global_avg_every"] == 8
    doc = json.load(open(out_path))
    assert doc["bench"]["payload_bytes"] > 0
    names = {e.get("name") for e in doc["trace"]["traceEvents"]}
    assert {"sgp_ga_steps", "allreduce_steps"} <= names
    # modeled comm: gossip+GA moves fewer bytes than AR-every-step
    mb = doc["bench"]["modeled_bytes_per_rank"]
    assert mb["sgp_ga"] < mb["allreduce"]


def test_bench_gva_topology_arg_both_spellings():
    """The parent must honor --topology NAME and --topology=NAME alike —
    a silently ignored '=' spelling would stamp flat-ring numbers into a
    hierarchical calibration run."""
    bench = _load_script("bench.py", "bench_gva_argparse_under_test")
    argv = ["bench.py", "--gossip-vs-ar"]
    assert bench._gva_topology_arg(argv) is None
    assert bench._gva_topology_arg(
        argv + ["--topology", "hierarchical"]) == "hierarchical"
    assert bench._gva_topology_arg(
        argv + ["--topology=hierarchical"]) == "hierarchical"
    with pytest.raises(SystemExit):
        bench._gva_topology_arg(argv + ["--topology"])
