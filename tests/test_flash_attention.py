"""Pallas flash-attention kernel vs the pure-JAX oracle (interpret mode on
CPU; the same kernel compiles for real on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stochastic_gradient_push_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_forward,
)
from stochastic_gradient_push_tpu.parallel.ring_attention import (
    blockwise_attention,
)

B, H, T, D = 2, 2, 64, 16


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(7)
    return [jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
            for _ in range(3)]


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block", [16, 32, 64])
def test_flash_kernel_matches_blockwise(qkv, causal, block):
    q, k, v = qkv
    got = flash_attention_forward(q, k, v, causal=causal, block_q=block,
                                  block_k=block, interpret=True)
    want = blockwise_attention(q, k, v, block, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_kernel_wide_head_dim():
    """head_dim 128 (v5e lane width) through forward AND backward: the
    production LM shapes use d=64; this pins the d=128 layouts the
    kernels' scratch/accumulators must also support."""
    rng = np.random.default_rng(3)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 2, 64, 128)), jnp.float32)
               for _ in range(3))
    got = flash_attention_forward(q, k, v, causal=True, block_q=32,
                                  block_k=32, interpret=True)
    want = blockwise_attention(q, k, v, 32, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    from stochastic_gradient_push_tpu.ops.flash_attention import (
        flash_attention_backward)

    out, lse = flash_attention_forward(q, k, v, causal=True, block_q=32,
                                       block_k=32, interpret=True,
                                       return_lse=True)
    do = jnp.asarray(rng.normal(size=out.shape), jnp.float32)
    dq, dk, dv = flash_attention_backward(q, k, v, out, lse, do,
                                          causal=True, block_q=32,
                                          block_k=32, interpret=True)

    def loss(q, k, v):
        return jnp.sum(blockwise_attention(q, k, v, 32, causal=True) * do)

    wq, wk, wv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g, w in ((dq, wq), (dk, wk), (dv, wv)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-4)


def test_default_block_rule():
    from stochastic_gradient_push_tpu.ops.flash_attention import (
        default_block)

    # largest tiling block wins at every measured length (the round-5
    # step-level A/B: t1024 block 512 is 2.0x block 128)
    assert default_block(64) == 64
    assert default_block(1024) == 512
    assert default_block(2048) == 512
    assert default_block(4096) == 512
    assert default_block(1024 + 256) == 256  # not divisible by 512
    assert default_block(2048 + 128) == 128  # only 128 tiles it


@pytest.mark.parametrize("block_q,block_k", [(16, 32), (32, 16)])
def test_flash_kernel_mixed_block_sizes(qkv, block_q, block_k):
    """Both aspect ratios exercise the causal index-map clamps (a
    wrong floor in either direction reads the wrong streamed block)."""
    q, k, v = qkv
    got = flash_attention_forward(q, k, v, causal=True, block_q=block_q,
                                  block_k=block_k, interpret=True)
    want = blockwise_attention(q, k, v, 16, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_gradient_matches_blockwise(qkv):
    q, k, v = qkv

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(blockwise_attention(q, k, v, 16, causal=True) ** 2)

    # on CPU flash_attention falls back to blockwise; gradients must agree
    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_flash_kernel_bf16(qkv):
    q, k, v = (x.astype(jnp.bfloat16) for x in qkv)
    got = flash_attention_forward(q, k, v, causal=True, block_q=32,
                                  block_k=32, interpret=True)
    want = blockwise_attention(q, k, v, 32, causal=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=3e-2, atol=3e-2)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block", [16, 32])
def test_flash_backward_kernels_match_oracle(qkv, causal, block):
    from stochastic_gradient_push_tpu.ops.flash_attention import (
        flash_attention_backward)

    q, k, v = qkv
    out, lse = flash_attention_forward(q, k, v, causal=causal,
                                       block_q=block, block_k=block,
                                       interpret=True, return_lse=True)
    rng = np.random.default_rng(3)
    do = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    dq, dk, dv = flash_attention_backward(
        q, k, v, out, lse, do, causal=causal, block_q=block,
        block_k=block, interpret=True)
    _, vjp = jax.vjp(
        lambda q, k, v: blockwise_attention(q, k, v, block, causal=causal),
        q, k, v)
    for got, want in zip((dq, dk, dv), vjp(do)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("block_q,block_k", [(16, 32), (32, 16)])
def test_flash_backward_mixed_block_sizes(qkv, block_q, block_k):
    from stochastic_gradient_push_tpu.ops.flash_attention import (
        flash_attention_backward)

    q, k, v = qkv
    out, lse = flash_attention_forward(q, k, v, causal=True,
                                       block_q=block_q, block_k=block_k,
                                       interpret=True, return_lse=True)
    rng = np.random.default_rng(4)
    do = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    dq, dk, dv = flash_attention_backward(
        q, k, v, out, lse, do, causal=True, block_q=block_q,
        block_k=block_k, interpret=True)
    _, vjp = jax.vjp(
        lambda q, k, v: blockwise_attention(q, k, v, 16, causal=True),
        q, k, v)
    for got, want in zip((dq, dk, dv), vjp(do)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)


def test_forward_lse_matches_reference(qkv):
    q, k, v = qkv
    _, lse = flash_attention_forward(q, k, v, causal=False, block_q=32,
                                     block_k=32, interpret=True,
                                     return_lse=True)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (D ** -0.5)
    want = jax.scipy.special.logsumexp(s, axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
