"""Unit tests for the multi-host helpers (single-process semantics and
mesh-geometry logic; the cross-process paths are covered by
tests/test_multihost.py's subprocess integration tests)."""

import jax
import numpy as np
import pytest

from stochastic_gradient_push_tpu.parallel import (
    consensus_resume_point,
    make_global_batch,
    make_gossip_mesh,
    make_hierarchical_mesh,
    owned_batch_rows,
    owned_ranks,
    to_host,
)
from stochastic_gradient_push_tpu.parallel.mesh import GOSSIP_AXIS, NODE_AXIS


def test_single_process_owns_everything():
    mesh = make_gossip_mesh(8)
    assert owned_ranks(mesh, GOSSIP_AXIS) == list(range(8))
    assert owned_batch_rows(mesh) == list(range(8))


def test_hierarchical_ranks_are_node_indices():
    mesh = make_hierarchical_mesh(2, 8)      # (node=4, local=2)
    assert owned_ranks(mesh, NODE_AXIS) == [0, 1, 2, 3]
    # batch rows are per-device (8), ranks are per-node (4)
    assert owned_batch_rows(mesh) == list(range(8))


def test_owned_ranks_rejects_straddling_ranks():
    """A node whose devices belong to different processes must be caught,
    not silently mis-fed."""

    class FakeDev:
        def __init__(self, pi):
            self.process_index = pi

    mesh = make_hierarchical_mesh(2, 8)
    fake = np.array([[FakeDev(0), FakeDev(1)]] * 4, dtype=object)

    class FakeMesh:
        axis_names = mesh.axis_names
        devices = fake

    with pytest.raises(ValueError, match="spans processes"):
        owned_ranks(FakeMesh(), NODE_AXIS)


def test_single_process_passthroughs():
    mesh = make_gossip_mesh(8)
    x = np.arange(16.0).reshape(8, 2)
    from jax.sharding import PartitionSpec as P

    assert make_global_batch(mesh, P(GOSSIP_AXIS), x) is x
    out = to_host({"a": x}, mesh)
    np.testing.assert_array_equal(out["a"], x)
    assert consensus_resume_point(3, 7) == (3, 7)
