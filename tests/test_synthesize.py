"""Schedule synthesizer (planner/synthesize.py, topology/synthesized.py).

Covers the PR-12 tentpole end to end on CPU:

* spec validation, normalization, JSON round-trip, and fingerprinting;
* table compilation: synthesized psum/edge phases build exactly the
  dense matrices the verifier checks, and the compact per-phase edge
  tables the compiled path executes;
* search soundness (the property sweep): every schedule the search
  emits — across seeds and worlds 4–48, non-powers-of-two included —
  passes ``analysis.verify_schedule``, and equal config reproduces the
  spec bit-exactly;
* compiled parity: one jitted round (edge ``ppermute`` / grouped
  ``psum``) equals the numpy mixing matrix on the world-8 CPU mesh
  (serialized dispatch per the PR-8 deadlock note);
* plan policy: beats every registry entry at world 12 under 16:1 DCN
  pricing, falls back to the registry when unbeaten, round-trips
  through ``Plan.to_dict``/checkpoint meta, and is rejected for
  overlap/faults/D-PSGD/self-weighted mixing;
* wiring: both run CLIs, the recovery policy's replan, the
  supervisor's relaunch argv, telemetry comm lanes, and the bounded
  spectral-gap LRU (satellite).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from stochastic_gradient_push_tpu.analysis import (
    is_unsupported_config,
    spectral_gap_cache_info,
    spectral_gap_cache_limit,
    verify_schedule,
)
from stochastic_gradient_push_tpu.parallel import (
    GOSSIP_AXIS,
    gossip_round,
    make_gossip_mesh,
    mix_push_sum,
)
from stochastic_gradient_push_tpu.planner import (
    InterconnectModel,
    SynthesisConfig,
    plan_for,
    PlanConstraints,
    plan_synthesized,
    synthesize,
)
from stochastic_gradient_push_tpu.planner.scorer import (
    evaluate_candidate,
    score_candidates,
)
from stochastic_gradient_push_tpu.topology import (
    SynthesizedGraph,
    SynthesizedSchedule,
    build_schedule,
    spec_fingerprint,
    topology_name,
    validate_spec,
)

WORLD = 8

DCN_FABRIC = InterconnectModel(slice_size=4, dcn_cost=16.0)

# small, fast search: plenty to beat the registry at world 12 on a
# DCN-dominant fabric while keeping tier-1 runtime bounded
FAST = SynthesisConfig(budget=300, max_phases=4)


def _spec(world=WORLD, phases=None):
    return {"v": 1, "world": world, "phases": phases or [
        {"kind": "edge", "perm": [(r + 1) % world for r in range(world)],
         "send": [0.75] * world},
        {"kind": "psum", "group_size": 4},
    ]}


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() >= WORLD, "conftest must fake 8 devices"
    return make_gossip_mesh(WORLD)


# -- spec layer --------------------------------------------------------------


class TestSpec:
    def test_normalize_and_json_round_trip(self):
        spec = validate_spec(_spec())
        again = validate_spec(json.loads(json.dumps(spec)))
        assert again == spec
        assert spec_fingerprint(again) == spec_fingerprint(spec)

    def test_self_edges_normalized_to_zero_send(self):
        spec = validate_spec(_spec(phases=[
            {"kind": "edge", "perm": [4, 1, 2, 3, 0, 5, 6, 7],
             "send": [0.9] * 8}]))
        send = spec["phases"][0]["send"]
        assert send[0] == send[4] == 0.9
        assert all(s == 0.0 for i, s in enumerate(send)
                   if i not in (0, 4))

    @pytest.mark.parametrize("mutate, needle", [
        (lambda s: s.update(v=99), "version"),
        (lambda s: s.update(world=1), "need >= 2"),
        (lambda s: s.update(phases=[]), "no phases"),
        (lambda s: s["phases"].append({"kind": "edge",
                                       "perm": [0] * WORLD,
                                       "send": [0.5] * WORLD}),
         "not a permutation"),
        (lambda s: s["phases"].append({"kind": "edge",
                                       "perm": list(range(WORLD)),
                                       "send": [1.5] * WORLD}),
         "in [0, 1]"),
        (lambda s: s["phases"].append({"kind": "edge",
                                       "perm": list(range(WORLD)),
                                       "send": [0.0] * WORLD}),
         "sends nothing"),
        (lambda s: s["phases"].append({"kind": "psum", "group_size": 3}),
         "group_size"),
        (lambda s: s["phases"].append({"kind": "butterfly"}),
         "unsupported"),
    ])
    def test_malformed_specs_refused_as_unsupported(self, mutate, needle):
        spec = _spec()
        mutate(spec)
        with pytest.raises(ValueError, match="(?s)" + needle.replace(
                "[", r"\[").replace("]", r"\]")) as ei:
            validate_spec(spec)
        assert is_unsupported_config(ei.value)

    def test_world_mismatch_refused(self):
        with pytest.raises(ValueError, match="re-synthesize"):
            SynthesizedGraph(12, spec=_spec(world=8))

    def test_specless_constructor_is_unsupported_config(self):
        """The registry scan must skip 'synth' the way it skips odd-world
        bipartite graphs — via the shared unsupported predicate."""
        with pytest.raises(ValueError) as ei:
            SynthesizedGraph(WORLD)
        assert is_unsupported_config(ei.value)
        assert all(c.topology != "synth" for c in score_candidates(WORLD))

    def test_registered_name_round_trips(self):
        assert topology_name(SynthesizedGraph) == "synth"


# -- table compilation -------------------------------------------------------


class TestScheduleTables:
    def test_tables_match_dense_matrices(self):
        sched = build_schedule(SynthesizedGraph(WORLD, spec=_spec()))
        assert isinstance(sched, SynthesizedSchedule)
        assert sched.phase_kinds == ("edge", "psum")
        assert sched.rounds_per_cycle == sched.num_phases == 2
        # psum phase = exact block average within contiguous groups
        W = sched.mixing_matrix(1)
        want = np.zeros((WORLD, WORLD))
        for j in range(WORLD // 4):
            want[j * 4:(j + 1) * 4, j * 4:(j + 1) * 4] = 0.25
        np.testing.assert_allclose(W, want, atol=1e-12)
        # edge phase columns: keep 0.25, send 0.75 to r+1
        W0 = sched.mixing_matrix(0)
        np.testing.assert_allclose(np.diag(W0), 0.25, atol=1e-12)
        np.testing.assert_allclose(W0.sum(axis=0), 1.0, atol=1e-12)

    def test_edge_phase_schedule_is_compact(self):
        sched = build_schedule(SynthesizedGraph(WORLD, spec=_spec()))
        flat = sched.edge_phase_schedule(0)
        assert flat.num_phases == 1 and flat.peers_per_itr == 1
        np.testing.assert_array_equal(flat.perms[0, 0],
                                      sched.perms[0, 0])
        with pytest.raises(ValueError, match="not an edge phase"):
            sched.edge_phase_schedule(1)

    def test_verifies_through_sgpv(self):
        sched = build_schedule(SynthesizedGraph(WORLD, spec=_spec()))
        findings, gap = verify_schedule(sched, "synth", "<test>", 0)
        assert findings == [] and gap > 0.01

    def test_overlap_schedule_refused(self):
        sched = build_schedule(SynthesizedGraph(WORLD, spec=_spec()))
        with pytest.raises(ValueError, match="augmented table form"):
            sched.overlap_schedule(2)

    def test_self_weighted_mixing_refused(self):
        from stochastic_gradient_push_tpu.topology import \
            SelfWeightedMixing

        with pytest.raises(ValueError, match="searched per-rank"):
            build_schedule(SynthesizedGraph(WORLD, spec=_spec()),
                           SelfWeightedMixing(0.5))


# -- search soundness (property sweep) ---------------------------------------


class TestSearchSoundness:
    SWEEP = SynthesisConfig(budget=90, max_phases=3, beam_width=3,
                            stall_width=2)

    @pytest.mark.parametrize("world", [4, 6, 8, 12, 16, 24, 48])
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_every_emitted_schedule_verifies(self, world, seed):
        """Satellite pin: whatever the search emits — any seed, any
        world 4–48 (non-powers-of-two included), sliced or uniform
        fabric — passes verify_schedule and round-trips its spec."""
        fabrics = [None]
        for s in (4, 8):
            if world % s == 0 and world // s >= 2:
                fabrics.append(InterconnectModel(slice_size=s,
                                                 dcn_cost=16.0))
        cfg = SynthesisConfig(budget=self.SWEEP.budget,
                              max_phases=self.SWEEP.max_phases,
                              beam_width=self.SWEEP.beam_width,
                              stall_width=self.SWEEP.stall_width,
                              seed=seed)
        for fabric in fabrics:
            res = synthesize(world, interconnect=fabric, config=cfg)
            if res is None:
                continue
            spec = validate_spec(res.spec, world)
            sched = build_schedule(SynthesizedGraph(world, spec=spec))
            findings, gap = verify_schedule(
                sched, f"synth-{world}-{seed}", "<sweep>", 0)
            assert findings == []
            assert gap >= 0.01 and gap == pytest.approx(res.gap)
            rebuilt = json.loads(json.dumps(spec))
            assert spec_fingerprint(rebuilt) == spec_fingerprint(spec)

    def test_equal_config_reproduces_spec(self):
        a = synthesize(12, interconnect=DCN_FABRIC, config=self.SWEEP)
        b = synthesize(12, interconnect=DCN_FABRIC, config=self.SWEEP)
        assert a is not None and a.spec == b.spec

    def test_stamped_spec_is_reused_at_same_world(self):
        first = synthesize(12, interconnect=DCN_FABRIC, config=self.SWEEP)
        again = synthesize(12, interconnect=DCN_FABRIC,
                           config=SynthesisConfig(budget=2),
                           seed_specs=(first.spec,))
        # with no budget to beat it, the stamped spec must win as-is
        assert again.from_seed_spec and again.spec == first.spec

    def test_zero_gap_prefixes_enter_the_stall_frontier(self):
        """A lone psum (or delegate) phase has spectral gap zero —
        SGPV103 — but is one move from the best schedules: _evaluate
        must score it as a not-yet-contracting prefix (infinite priced
        cost), not refuse it, or the stall_width beam slots are dead."""
        import math

        from stochastic_gradient_push_tpu.planner.synthesize import \
            _evaluate

        ev = _evaluate(WORLD, ({"kind": "psum", "group_size": 4},),
                       DCN_FABRIC, 1.0)
        assert ev is not None and math.isinf(ev.priced)
        assert ev.cycle_ici > 0.0   # stall ranking key: cycle cost
        # a structurally broken cycle still refuses (bijection violated
        # is unreachable from the library; world mismatch stands in)
        assert _evaluate(12, ({"kind": "psum", "group_size": 8},),
                         DCN_FABRIC, 1.0) is None


# -- compiled parity ---------------------------------------------------------


class TestCompiledRound:
    def _round_fn(self, mesh, sched):
        def step(phase, xs):
            return gossip_round(xs, phase, sched, GOSSIP_AXIS)
        return jax.jit(jax.shard_map(
            step, mesh=mesh, in_specs=(P(), P(GOSSIP_AXIS)),
            out_specs=P(GOSSIP_AXIS)))

    def test_jit_matches_numpy_mixing_matrices(self, mesh):
        """One compiled round per phase — delegate-style sparse edge,
        grouped psum, dense rotation — applies exactly the dense matrix
        the verifier checks (serialized dispatch: every call drains
        before the next, per the PR-8 CPU-collective deadlock note)."""
        spec = _spec(phases=[
            {"kind": "edge",
             "perm": [4, 1, 2, 3, 0, 5, 6, 7],
             "send": [0.9, 0, 0, 0, 0.9, 0, 0, 0]},
            {"kind": "psum", "group_size": 4},
            {"kind": "edge",
             "perm": [(r + 2) % WORLD for r in range(WORLD)],
             "send": [0.5] * WORLD},
        ])
        sched = build_schedule(SynthesizedGraph(WORLD, spec=spec))
        f = self._round_fn(mesh, sched)
        rng = np.random.default_rng(3)
        x = rng.normal(size=(WORLD, 4, 3)).astype(np.float32)
        for rnd in range(sched.num_phases + 1):
            got = np.asarray(jax.block_until_ready(f(jnp.int32(rnd), x)))
            W = sched.mixing_matrix(rnd % sched.num_phases)
            want = np.einsum("rs,s...->r...", W, x.astype(np.float64))
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_push_sum_mass_conserved_to_consensus(self, mesh):
        sched = build_schedule(SynthesizedGraph(WORLD, spec=_spec()))
        rng = np.random.default_rng(4)
        x = rng.normal(size=(WORLD, 5)).astype(np.float32)
        w = np.ones((WORLD, 1), dtype=np.float32)
        total, mean = x.sum(axis=0), x.mean(axis=0)

        def step(phase, xs, ws):
            return mix_push_sum(xs, ws, phase, sched, GOSSIP_AXIS)

        f = jax.jit(jax.shard_map(
            step, mesh=mesh,
            in_specs=(P(), P(GOSSIP_AXIS), P(GOSSIP_AXIS)),
            out_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS))))
        for rnd in range(40):
            x, w = map(np.asarray,
                       map(jax.block_until_ready,
                           f(jnp.int32(rnd), x, w)))
            np.testing.assert_allclose(x.sum(axis=0), total,
                                       rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(x / w,
                                   np.broadcast_to(mean, x.shape),
                                   rtol=1e-3, atol=1e-3)

    def test_overlap_and_faults_rejected(self):
        from stochastic_gradient_push_tpu.algorithms import PushSumGossip
        from stochastic_gradient_push_tpu.parallel.collectives import \
            overlap_launch

        sched = build_schedule(SynthesizedGraph(WORLD, spec=_spec()))
        x = np.ones((WORLD, 2), np.float32)
        # static configuration errors: raised before any mesh context
        with pytest.raises(ValueError, match="overlap is not supported"):
            overlap_launch((x,), 0, sched, GOSSIP_AXIS)
        with pytest.raises(ValueError, match="fault injection"):
            gossip_round((x,), 0, sched, GOSSIP_AXIS, faults=object())
        with pytest.raises(ValueError, match="overlap is not supported"):
            PushSumGossip(sched, GOSSIP_AXIS, overlap=True)
        with pytest.raises(ValueError, match="inject_faults"):
            PushSumGossip(sched, GOSSIP_AXIS, faults=object())
        with pytest.raises(ValueError, match="regular schedule"):
            from stochastic_gradient_push_tpu.parallel.collectives import \
                mix_push_pull
            mix_push_pull(x, 0, sched, GOSSIP_AXIS)


# -- plan policy -------------------------------------------------------------


class TestPlanPolicy:
    def test_beats_every_registry_entry_on_dcn_fabric(self):
        """The acceptance pin at world 12 (world 48 rides the plan.py
        selftest in check.sh — same code path, bigger search)."""
        plan = plan_synthesized(12, interconnect=DCN_FABRIC, config=FAST)
        assert plan.topology == "synth" and plan.gap >= plan.floor
        cand = evaluate_candidate(
            plan.graph_class, 12, 1, interconnect=DCN_FABRIC)
        regs = score_candidates(12, interconnect=DCN_FABRIC)
        assert all(cand.priced_cost < c.priced_cost for c in regs)
        # the winner's ranking row leads the stamped ranking
        assert plan.ranking[0]["topology"] == "synth"

    def test_plan_round_trips_through_json_meta(self):
        plan = plan_synthesized(12, interconnect=DCN_FABRIC, config=FAST)
        d = json.loads(json.dumps(plan.to_dict()))
        assert d["topology"] == "synth" and d["mixing"] == "synthesized"
        rebuilt = SynthesizedGraph(12, spec=d["synth"]["spec"])
        assert spec_fingerprint(rebuilt.spec) == d["synth"]["fingerprint"]
        sched = build_schedule(rebuilt)
        findings, gap = verify_schedule(sched, "resumed", "<test>", 0)
        assert findings == [] and gap == pytest.approx(d["gap"], abs=1e-6)

    def test_falls_back_to_registry_when_unbeaten(self):
        """One evaluation cannot beat the registry winner; the plan must
        keep the registry choice and say why."""
        plan = plan_synthesized(WORLD, config=SynthesisConfig(budget=1))
        registry = plan_for(WORLD)
        assert plan.topology == registry.topology
        assert plan.synth is None
        assert "did not beat the registry" in plan.rationale

    def test_plan_for_delegates_on_synth_constraint(self):
        plan = plan_for(12, constraints=PlanConstraints(
            interconnect=DCN_FABRIC,
            synth={"budget": FAST.budget, "max_phases": FAST.max_phases}))
        assert plan.topology == "synth"

    def test_rejections(self):
        with pytest.raises(ValueError, match="overlap"):
            plan_synthesized(12, overlap=True, config=FAST)
        with pytest.raises(ValueError, match="fault injection"):
            plan_synthesized(12, faults=True, config=FAST)
        with pytest.raises(ValueError, match="push-sum only"):
            plan_synthesized(12, algorithm="dpsgd", config=FAST)
        with pytest.raises(ValueError, match="mixing_alpha"):
            plan_synthesized(12, self_weighted=0.5, config=FAST)

    def test_recovery_policy_replan_reuses_stamp(self):
        from stochastic_gradient_push_tpu.resilience import RecoveryPolicy

        plan = plan_synthesized(12, interconnect=DCN_FABRIC, config=FAST)
        policy = RecoveryPolicy(world=12, topology="synth",
                                interconnect=DCN_FABRIC,
                                synth={**plan.synth, "budget": 2})
        suggestion = policy.replan()
        assert suggestion["topology"] == "synth"
        assert suggestion["switch"] is False


# -- run-layer + supervisor wiring -------------------------------------------


class TestRunLayerWiring:
    def test_resolve_plan_configures_trainer_config(self):
        from stochastic_gradient_push_tpu.run.gossip_sgd import (
            _resolve_plan, parse_config)
        from stochastic_gradient_push_tpu.utils import make_logger

        log = make_logger("test-synth-plan", verbose=False)
        cfg, args = parse_config([
            "--topology", "synth", "--slice_size", "4",
            "--dcn_cost", "16", "--synth_budget", str(FAST.budget),
            "--synth_phases", str(FAST.max_phases)])
        _resolve_plan(cfg, args, 12, log)
        assert cfg.plan["topology"] == "synth"
        graph = cfg.graph_class(12, peers_per_itr=1)
        assert isinstance(graph, SynthesizedGraph)
        assert (spec_fingerprint(graph.spec)
                == cfg.plan["synth"]["fingerprint"])

    def test_stray_synth_knobs_rejected(self):
        from stochastic_gradient_push_tpu.run.gossip_sgd import (
            _resolve_plan, parse_config)
        from stochastic_gradient_push_tpu.utils import make_logger

        cfg, args = parse_config(["--topology", "auto",
                                  "--synth_budget", "50"])
        with pytest.raises(SystemExit, match="--topology synth"):
            _resolve_plan(cfg, args, 8,
                          make_logger("test-synth-knobs", verbose=False))

    def test_synth_rejected_on_single_rank_world(self):
        from stochastic_gradient_push_tpu.run.gossip_sgd import (
            _resolve_plan, parse_config)
        from stochastic_gradient_push_tpu.utils import make_logger

        cfg, args = parse_config(["--topology", "synth"])
        with pytest.raises(SystemExit, match="auto/synth"):
            _resolve_plan(cfg, args, 1,
                          make_logger("test-synth-w1", verbose=False))

    def test_lm_parser_accepts_synth(self):
        from stochastic_gradient_push_tpu.run.gossip_lm import \
            build_parser

        args = build_parser().parse_args(
            ["--topology", "synth", "--synth_seed", "3"])
        assert args.topology == "synth" and args.synth_seed == 3

    def test_supervisor_argv_carries_synth_knobs(self):
        from stochastic_gradient_push_tpu.supervise.supervisor import \
            ChildSpec

        spec = ChildSpec(argv=[
            "python", "-m",
            "stochastic_gradient_push_tpu.run.gossip_sgd",
            "--world_size", "12", "--topology", "synth",
            "--checkpoint_dir", "/tmp/x",
            "--trace_dir", "/tmp/x-trace"])
        plan = {"topology": "synth", "global_avg_every": 0,
                "slice_size": None, "alpha": None,
                "interconnect": {"slice_size": 4, "dcn_cost": 16.0,
                                 "ici_cost": 1.0, "torus": None},
                "synth": {"seed": 5, "budget": 400, "beam_width": 6,
                          "max_phases": 4, "spec": _spec(12, [
                              {"kind": "psum", "group_size": 4}])}}
        argv = spec.build_argv(6, plan, resume=True)
        assert argv[argv.index("--topology") + 1] == "synth"
        for flag, val in (("--synth_seed", "5"), ("--synth_budget",
                                                  "400"),
                          ("--synth_beam", "6"), ("--synth_phases",
                                                  "4")):
            assert argv[argv.index(flag) + 1] == val
        # a synth plan stamps slice_size=None (no hierarchical
        # decomposition) but was priced on a sliced fabric: the child
        # must get --slice_size back or its surviving --dcn_cost is
        # rejected at launch (make_interconnect needs slice structure)
        assert argv[argv.index("--slice_size") + 1] == "4"


# -- telemetry comm lanes ----------------------------------------------------


class TestCommLanes:
    def test_lane_split_matches_hand_count(self):
        from stochastic_gradient_push_tpu.telemetry import CommModel

        spec = _spec(phases=[
            {"kind": "edge",
             "perm": [4, 1, 2, 3, 0, 5, 6, 7],
             "send": [0.9, 0, 0, 0, 0.9, 0, 0, 0]},
            {"kind": "psum", "group_size": 4},
        ])
        sched = build_schedule(SynthesizedGraph(WORLD, spec=spec))
        payload = 1000
        m = CommModel.from_schedule(sched, payload,
                                    interconnect=DCN_FABRIC)
        msg = payload + 4   # ps-weight lane rides each edge message
        assert m.synthesized and m.num_phases == 2
        # phase 0: two cross-slice delegate messages over 8 ranks
        assert m.dcn_bytes_per_phase == (round(2 * msg / WORLD), 0)
        # phase 1: grouped ring-allreduce 2·(g−1)/g of the EXACT payload
        assert m.ici_bytes_per_phase == (0, 1500)
        assert m.wire_bytes_per_phase == (m.dcn_bytes_per_phase[0], 1500)
        with pytest.raises(ValueError, match="fault pricing"):
            CommModel.from_schedule(sched, payload, faults=object())

    def test_cross_slice_psum_prices_on_dcn_lane(self):
        from stochastic_gradient_push_tpu.telemetry import CommModel

        # groups of 4 on a slice-2 fabric span slices: DCN lane
        sched = build_schedule(SynthesizedGraph(WORLD, spec=_spec()))
        m = CommModel.from_schedule(
            sched, 1000,
            interconnect=InterconnectModel(slice_size=2, dcn_cost=16.0))
        assert m.dcn_bytes_per_phase[1] == 1500
        assert m.ici_bytes_per_phase[1] == 0


# -- satellite: bounded spectral-gap LRU -------------------------------------


class TestGapCacheLRU:
    @pytest.fixture(autouse=True)
    def _restore_limit(self):
        old = spectral_gap_cache_limit()
        yield
        spectral_gap_cache_limit(old)

    def test_cache_is_bounded_and_counts_evictions(self):
        from stochastic_gradient_push_tpu.analysis import spectral_gap
        from stochastic_gradient_push_tpu.topology import RingGraph

        spectral_gap_cache_limit(4)
        before = spectral_gap_cache_info()["evictions"]
        for world in (5, 6, 7, 8, 9, 10, 11, 12):
            spectral_gap(build_schedule(RingGraph(world)))
        info = spectral_gap_cache_info()
        assert info["size"] <= 4 and info["max"] == 4
        assert info["evictions"] >= before + 4
        # a hit still registers after evictions (the survivor is fresh)
        hits = info["hits"]
        spectral_gap(build_schedule(RingGraph(12)))
        assert spectral_gap_cache_info()["hits"] == hits + 1

    def test_limit_validates(self):
        with pytest.raises(ValueError, match=">= 1"):
            spectral_gap_cache_limit(0)
