"""End-to-end training smoke tests (SURVEY.md §7 minimum slice).

TinyCNN on learnable synthetic data, 8 ranks on the virtual CPU mesh,
through the full stack: data sharding → jitted shard_map step →
algorithm → gossip collectives.  Asserts (a) loss decreases, (b) de-biased
params converge toward consensus, (c) eval runs, (d) resume fast-forward
works — the capabilities the reference only exposes as manual flags
(--num_iterations_per_training_epoch, --train_fast)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stochastic_gradient_push_tpu.algorithms import all_reduce, dpsgd, osgp, sgp
from stochastic_gradient_push_tpu.data import (
    DistributedSampler,
    ShardedLoader,
    synthetic_classification,
)
from stochastic_gradient_push_tpu.models import TinyMLP
from stochastic_gradient_push_tpu.parallel import GOSSIP_AXIS, make_gossip_mesh
from stochastic_gradient_push_tpu.topology import (
    NPeerDynamicDirectedExponentialGraph,
    build_schedule,
)
from stochastic_gradient_push_tpu.train import (
    LRSchedule,
    build_eval_step,
    build_train_step,
    init_train_state,
    replicate_state,
    sgd,
    shard_eval_step,
    shard_train_step,
)

WORLD = 8
BATCH = 8
NUM_CLASSES = 4
IMG = 8


@pytest.fixture(scope="module")
def mesh():
    return make_gossip_mesh(WORLD)


@pytest.fixture(scope="module")
def data():
    return synthetic_classification(
        n=WORLD * BATCH * 6, num_classes=NUM_CLASSES, image_size=IMG, seed=3)


def build_everything(algorithm_factory, mesh, itr_per_epoch=6):
    model = TinyMLP(num_classes=NUM_CLASSES)
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    alg = algorithm_factory(sched)
    tx = sgd(momentum=0.9, weight_decay=1e-4, nesterov=True)
    lr_sched = LRSchedule(ref_lr=0.1, batch_size=BATCH, world_size=WORLD,
                          decay_schedule={3: 0.1}, warmup=False)
    step = build_train_step(model, alg, tx, lr_sched,
                            itr_per_epoch=itr_per_epoch,
                            num_classes=NUM_CLASSES)
    sharded = shard_train_step(step, mesh)
    state0 = init_train_state(
        model, jax.random.PRNGKey(47),
        jnp.zeros((BATCH, IMG, IMG, 3)), tx, alg)
    return model, alg, sharded, replicate_state(state0, WORLD), step


def run_epochs(sharded, state, images, labels, epochs=2, seed=47):
    sampler = DistributedSampler(len(images), WORLD)
    loader = ShardedLoader(images, labels, BATCH, sampler)
    losses = []
    for epoch in range(epochs):
        sampler.set_epoch(epoch + seed * 90)  # ≙ gossip_sgd.py:289
        for x, y in loader:
            state, metrics = sharded(state, x, y)
            jax.block_until_ready(state)
            losses.append(float(np.asarray(metrics["loss"]).mean()))
    return state, losses


@pytest.mark.parametrize("factory", [
    lambda s: sgp(s, GOSSIP_AXIS),
    lambda s: osgp(s, GOSSIP_AXIS),
    lambda s: dpsgd(s, GOSSIP_AXIS),
    lambda s: all_reduce(GOSSIP_AXIS),
])
def test_training_reduces_loss_and_reaches_consensus(mesh, data, factory):
    images, labels = data
    model, alg, sharded, state, _ = build_everything(factory, mesh)
    state, losses = run_epochs(sharded, state, images, labels, epochs=4)

    first = np.mean(losses[:4])
    last = np.mean(losses[-4:])
    assert last < 0.75 * first, (first, last)

    # de-biased replicas are in near-consensus (vmap over the world dim —
    # eval_params is a per-rank function)
    z = jax.vmap(alg.eval_params)(state.params, state.gossip)
    flat = np.concatenate([np.asarray(l).reshape(WORLD, -1)
                           for l in jax.tree.leaves(z)], axis=1)
    spread = np.abs(flat - flat.mean(axis=0, keepdims=True)).max()
    scale = np.abs(flat).max()
    assert spread < 0.05 * max(scale, 1.0), (spread, scale)


def test_eval_step_runs_and_scores_above_chance(mesh, data):
    images, labels = data
    model, alg, sharded, state, _ = build_everything(
        lambda s: sgp(s, GOSSIP_AXIS), mesh)
    state, _ = run_epochs(sharded, state, images, labels, epochs=4)

    eval_step = build_eval_step(model, alg, NUM_CLASSES)
    sharded_eval = shard_eval_step(eval_step, mesh)

    sampler = DistributedSampler(len(images), WORLD)
    loader = ShardedLoader(images, labels, BATCH, sampler)
    top1s = []
    for x, y in loader:
        m = sharded_eval(state, x, y)
        top1s.append(np.asarray(m["top1"]).mean())
    assert np.mean(top1s) > 100.0 / NUM_CLASSES + 10  # well above chance


def test_loader_fast_forward_resume(data):
    images, labels = data
    sampler = DistributedSampler(len(images), WORLD)
    sampler.set_epoch(7)
    loader = ShardedLoader(images, labels, BATCH, sampler)
    full = list(loader)
    loader.fast_forward(3)
    resumed = list(loader)
    assert len(resumed) == len(full) - 3
    np.testing.assert_array_equal(resumed[0][1], full[3][1])
    # fast-forward resets after one epoch
    assert len(list(loader)) == len(full)


def test_sampler_epoch_determinism_and_coverage(data):
    images, labels = data
    sampler = DistributedSampler(len(images), WORLD)
    sampler.set_epoch(5)
    a = sampler.all_indices()
    sampler.set_epoch(5)
    np.testing.assert_array_equal(a, sampler.all_indices())
    sampler.set_epoch(6)
    assert not np.array_equal(a, sampler.all_indices())
    # coverage: every sample appears at least once across ranks
    assert set(a.ravel().tolist()) == set(range(len(images)))


def test_early_exit_iteration_cap(mesh, data):
    """≙ --num_iterations_per_training_epoch (gossip_sgd.py:83-88)."""
    images, labels = data
    _, _, sharded, state, _ = build_everything(
        lambda s: sgp(s, GOSSIP_AXIS), mesh)
    sampler = DistributedSampler(len(images), WORLD)
    loader = ShardedLoader(images, labels, BATCH, sampler)
    cap = 2
    steps = 0
    for i, (x, y) in enumerate(loader):
        state, _ = sharded(state, x, y)
        steps += 1
        if i + 1 == cap:
            break
    assert steps == cap
    assert int(np.asarray(state.step)[0]) == cap


@pytest.mark.parametrize("make_alg, staleness", [
    (lambda s: sgp(s, GOSSIP_AXIS), 0),
    (lambda s: sgp(s, GOSSIP_AXIS, overlap=True, staleness=2), 2),
])
def test_scanned_steps_equal_sequential_steps(mesh, data, make_alg,
                                              staleness):
    """k scanned steps == k sequential dispatches, bit-for-bit-ish —
    for sync SGP and for stale-overlap OSGP (whose in-flight FIFO, a
    tuple of slots, must thread correctly through the lax.scan carry)."""
    from stochastic_gradient_push_tpu.train import shard_scanned_train_step

    images, labels = data
    k = 4
    model, alg, sharded, state_a, step = build_everything(make_alg, mesh)
    state_b = jax.tree.map(jnp.copy, state_a)

    sampler = DistributedSampler(len(images), WORLD)
    loader = ShardedLoader(images, labels, BATCH, sampler)
    xs, ys = [], []
    it = iter(loader)
    for _ in range(k):
        x, y = next(it)
        xs.append(x)
        ys.append(y)

    # sequential
    for x, y in zip(xs, ys):
        state_a, _ = sharded(state_a, x, y)
        jax.block_until_ready(state_a)

    # scanned: the SAME per-rank step fused over k iterations
    scanned = shard_scanned_train_step(step, mesh, n_steps=k)
    state_b, metrics = scanned(state_b, np.stack(xs), np.stack(ys))
    jax.block_until_ready(state_b)

    assert np.asarray(metrics["loss"]).shape == (WORLD, k)
    for a, b in zip(jax.tree.leaves(state_a), jax.tree.leaves(state_b),
                    strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    assert int(np.asarray(state_b.step)[0]) == k
    if staleness:
        # both FIFO slots present; between steps the newest real share
        # sits at the head and the tail slot is the freed one (the next
        # pre_step's launch target)
        assert len(state_b.gossip.in_flight) == staleness
        newest = np.asarray(
            jax.tree.leaves(state_b.gossip.in_flight[0][0])[0])
        assert np.abs(newest).max() > 0
        tail = np.asarray(
            jax.tree.leaves(state_b.gossip.in_flight[-1][0])[0])
        assert np.abs(tail).max() == 0
