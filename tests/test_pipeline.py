"""Pipeline parallelism: exact parity with the non-pipelined transformer,
gradient correctness, and composition with gossip data parallelism.

The reference has no pipeline parallelism (SURVEY.md §2) — these tests hold
the TPU-native extension to the same standard as MoE × ring: the pipelined
program must be numerically the *same function* as the plain stacked model.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stochastic_gradient_push_tpu.algorithms import all_reduce, dpsgd, sgp
from stochastic_gradient_push_tpu.models import (
    PipelineStageLM, TransformerConfig, TransformerLM)
from stochastic_gradient_push_tpu.parallel import GOSSIP_AXIS
from stochastic_gradient_push_tpu.topology import (
    DynamicDirectedExponentialGraph, build_schedule)
from stochastic_gradient_push_tpu.train import LRSchedule, sgd
from stochastic_gradient_push_tpu.train.lm import lm_loss
from stochastic_gradient_push_tpu.train.pp import (
    build_pp_train_step, init_pp_state, make_dp_pp_mesh, pp_state_specs,
    shard_pp_train_step)

VOCAB, D, HEADS, FF, SEQ = 64, 32, 4, 64, 16


def _cfg(n_layers, **kw):
    kw.setdefault("attn_impl", "full")
    return TransformerConfig(vocab_size=VOCAB, d_model=D, n_layers=n_layers,
                             n_heads=HEADS, d_ff=FF, max_len=SEQ, **kw)


def _setup(dp, pp, n_layers, n_micro, micro_batch=2, algorithm=None,
           momentum=0.0, remat=False, moe=False, moe_loss_coef=0.01):
    kw = dict(remat=remat)
    if moe:
        # capacity high enough that no token ever drops: per-microbatch
        # routing then equals full-batch routing token-for-token
        kw.update(moe_experts=4, moe_every=1, moe_capacity_factor=8.0)
    cfg = _cfg(n_layers, **kw)
    model = PipelineStageLM(cfg, n_local_layers=n_layers // pp)
    mesh = make_dp_pp_mesh(dp, pp)
    alg = algorithm or all_reduce(GOSSIP_AXIS)
    tx = sgd(momentum=momentum, weight_decay=0.0)
    lrs = LRSchedule(ref_lr=0.1, batch_size=micro_batch * n_micro,
                     world_size=dp, decay_schedule={}, warmup=False)
    step = build_pp_train_step(model, alg, tx, lrs, itr_per_epoch=100,
                               moe_loss_coef=moe_loss_coef)
    state = init_pp_state(model, mesh, alg, tx, dp=dp, pp=pp,
                          n_micro=n_micro, micro_batch=micro_batch,
                          seq_len=SEQ)
    train_fn = shard_pp_train_step(step, mesh, pp_state_specs(state))
    rng = np.random.default_rng(0)
    toks = rng.integers(0, VOCAB, size=(dp, n_micro, micro_batch, SEQ)
                        ).astype(np.int32)
    tgts = rng.integers(0, VOCAB, size=(dp, n_micro, micro_batch, SEQ)
                        ).astype(np.int32)
    return model, cfg, state, train_fn, toks, tgts


def _assemble_reference_params(state, rank, n_layers):
    """Full TransformerLM param tree for one gossip rank, gathered from the
    pipe-sharded global state (stack leaves are [dp, L, ...] globally)."""
    host = jax.tree.map(np.asarray, state.params)
    ref = {"embed": jax.tree.map(lambda a: a[rank], host["embed"]),
           "ln_f": jax.tree.map(lambda a: a[rank], host["ln_f"]),
           "lm_head": jax.tree.map(lambda a: a[rank], host["lm_head"])}
    for i in range(n_layers):
        ref[f"block_{i}"] = jax.tree.map(lambda a: a[rank, i],
                                         host["stack"]["block"])
    return ref


def _reference_loss_and_grads(cfg, ref_params, toks, tgts):
    ref_model = TransformerLM(cfg._replace(remat=False))
    flat_t = toks.reshape(-1, toks.shape[-1])
    flat_y = tgts.reshape(-1, tgts.shape[-1])

    def loss_fn(p):
        return lm_loss(ref_model.apply({"params": p}, flat_t), flat_y)

    return jax.value_and_grad(loss_fn)(ref_params)


class TestPipelineParity:
    @pytest.mark.slow
    def test_forward_loss_matches_stacked_model(self):
        n_layers, pp, n_micro = 4, 4, 4
        model, cfg, state, train_fn, toks, tgts = _setup(
            1, pp, n_layers, n_micro)
        ref_params = _assemble_reference_params(state, 0, n_layers)
        ref_loss, _ = _reference_loss_and_grads(cfg, ref_params,
                                                toks[0], tgts[0])
        _, metrics = train_fn(state, toks, tgts)
        loss = float(np.asarray(metrics["loss"])[0])
        assert np.isfinite(loss)
        np.testing.assert_allclose(loss, float(ref_loss), rtol=2e-5,
                                   atol=2e-5)

    @pytest.mark.slow
    def test_grads_match_stacked_model(self):
        """One momentum-free SGD step: params move by exactly -lr * grad of
        the stacked model, for stage-local AND pipe-replicated leaves."""
        n_layers, pp, n_micro = 4, 2, 4
        model, cfg, state, train_fn, toks, tgts = _setup(
            1, pp, n_layers, n_micro)
        ref_params = _assemble_reference_params(state, 0, n_layers)
        _, ref_grads = _reference_loss_and_grads(cfg, ref_params,
                                                 toks[0], tgts[0])
        new_state, metrics = train_fn(state, toks, tgts)
        lr = float(np.asarray(metrics["lr"])[0])
        new_ref = _assemble_reference_params(new_state, 0, n_layers)

        expect = jax.tree.map(lambda p, g: p - lr * np.asarray(g),
                              ref_params, ref_grads)
        flat_e, _ = jax.tree_util.tree_flatten_with_path(expect)
        flat_n, _ = jax.tree_util.tree_flatten_with_path(new_ref)
        for (path_e, e), (_, n) in zip(flat_e, flat_n):
            np.testing.assert_allclose(
                np.asarray(n), np.asarray(e), rtol=5e-4, atol=1e-5,
                err_msg=jax.tree_util.keystr(path_e))

    @pytest.mark.slow
    def test_more_microbatches_than_stages(self):
        n_layers, pp, n_micro = 2, 2, 3
        model, cfg, state, train_fn, toks, tgts = _setup(
            1, pp, n_layers, n_micro)
        ref_params = _assemble_reference_params(state, 0, n_layers)
        ref_loss, _ = _reference_loss_and_grads(cfg, ref_params,
                                                toks[0], tgts[0])
        _, metrics = train_fn(state, toks, tgts)
        np.testing.assert_allclose(float(np.asarray(metrics["loss"])[0]),
                                   float(ref_loss), rtol=2e-5, atol=2e-5)

    def test_pipeline_forward_logits_match_stacked_model(self):
        """pipeline_forward (the exported inference path) produces the
        stacked model's logits on the last stage and exact zeros elsewhere
        — psum over pipe recovers the full logits."""
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from stochastic_gradient_push_tpu.train.pp import (
            PIPE_AXIS, pipeline_forward, pp_state_specs)

        n_layers, pp, n_micro = 2, 2, 2
        model, cfg, state, _, toks, _ = _setup(1, pp, n_layers, n_micro)
        ref_params = _assemble_reference_params(state, 0, n_layers)
        ref_model = TransformerLM(cfg._replace(remat=False))
        flat_t = toks[0].reshape(-1, toks.shape[-1])
        ref_logits = np.asarray(
            ref_model.apply({"params": ref_params}, flat_t))

        mesh = make_dp_pp_mesh(1, pp)
        specs = pp_state_specs(state.params)

        def fwd(params, tokens):
            p = jax.tree.map(lambda a: a[0], params)
            logits = pipeline_forward(model, p, tokens[0])
            return lax.psum(logits, PIPE_AXIS)[None]

        sm = jax.shard_map(fwd, mesh=mesh,
                           in_specs=(specs, P(GOSSIP_AXIS)),
                           out_specs=P(GOSSIP_AXIS))
        got = np.asarray(jax.jit(sm)(state.params, toks))[0]
        np.testing.assert_allclose(got.reshape(ref_logits.shape),
                                   ref_logits, rtol=2e-5, atol=2e-5)

    @pytest.mark.slow
    def test_moe_pp_matches_stacked_model(self):
        """MoE × pipeline (every layer an expert block, routed per
        microbatch inside the ticks): with no-drop capacity, routing is
        per-token, so CE and a momentum-free SGD step match the stacked
        full-batch MoE model exactly (moe_loss_coef=0 isolates CE)."""
        n_layers, pp, n_micro = 2, 2, 2
        model, cfg, state, train_fn, toks, tgts = _setup(
            1, pp, n_layers, n_micro, moe=True, moe_loss_coef=0.0)
        ref_params = _assemble_reference_params(state, 0, n_layers)
        ref_loss, ref_grads = _reference_loss_and_grads(
            cfg, ref_params, toks[0], tgts[0])
        new_state, metrics = train_fn(state, toks, tgts)
        np.testing.assert_allclose(
            float(np.asarray(metrics["loss"])[0]), float(ref_loss),
            rtol=2e-5, atol=2e-5)
        assert float(np.asarray(metrics["moe_dropped"])[0]) == 0.0

        lr = float(np.asarray(metrics["lr"])[0])
        new_ref = _assemble_reference_params(new_state, 0, n_layers)
        expect = jax.tree.map(lambda p, g: p - lr * np.asarray(g),
                              ref_params, ref_grads)
        flat_e, _ = jax.tree_util.tree_flatten_with_path(expect)
        flat_n, _ = jax.tree_util.tree_flatten_with_path(new_ref)
        for (path_e, e), (_, n) in zip(flat_e, flat_n):
            np.testing.assert_allclose(
                np.asarray(n), np.asarray(e), rtol=5e-4, atol=1e-5,
                err_msg=jax.tree_util.keystr(path_e))

    @pytest.mark.slow
    def test_remat_matches(self):
        n_layers, pp, n_micro = 2, 2, 2
        _, _, state, train_fn, toks, tgts = _setup(1, pp, n_layers, n_micro)
        _, m_plain = train_fn(state, toks, tgts)
        _, _, state_r, train_r, _, _ = _setup(1, pp, n_layers, n_micro,
                                              remat=True)
        _, m_remat = train_r(state_r, toks, tgts)
        np.testing.assert_allclose(np.asarray(m_plain["loss"]),
                                   np.asarray(m_remat["loss"]),
                                   rtol=1e-5, atol=1e-5)


class TestPipelineExpert:
    @pytest.mark.slow
    def test_pp_ep_eval_matches_assembled_model(self):
        """pp × ep: the MoE all_to_all dispatches token slots over ep
        inside each tick.  Under no-drop capacity routing is per-token,
        so the pipelined+dispatched eval CE equals a stacked full-expert
        model run on each ep shard's tokens (assembled from the
        (gossip, pipe, ep)-sharded global state)."""
        from stochastic_gradient_push_tpu.train.lm import EP_AXIS, lm_loss
        from stochastic_gradient_push_tpu.train.pp import (
            build_pp_eval_step, init_pp_state, make_dp_pp_ep_mesh,
            pp_state_specs, shard_pp_eval_step)

        dp, pp, ep, n_layers, n_micro, mb = 2, 2, 2, 2, 2, 2
        cfg = _cfg(n_layers, moe_experts=4, moe_every=1,
                   moe_capacity_factor=8.0, ep_axis=EP_AXIS)
        model = PipelineStageLM(cfg, n_local_layers=n_layers // pp)
        mesh = make_dp_pp_ep_mesh(dp, pp, ep)
        alg = all_reduce(GOSSIP_AXIS)
        tx = sgd(momentum=0.0, weight_decay=0.0)
        state = init_pp_state(model, mesh, alg, tx, dp=dp, pp=pp,
                              n_micro=n_micro, micro_batch=mb,
                              seq_len=SEQ, ep=ep)
        eval_fn = shard_pp_eval_step(
            build_pp_eval_step(model, alg), mesh,
            pp_state_specs(state, ep_axis=EP_AXIS), ep_axis=EP_AXIS)
        rng = np.random.default_rng(3)
        shape = (dp, ep, n_micro, mb, SEQ)
        toks = rng.integers(0, VOCAB, size=shape).astype(np.int32)
        tgts = rng.integers(0, VOCAB, size=shape).astype(np.int32)
        got = np.asarray(eval_fn(state, toks, tgts)["loss"])

        # reference: stacked TransformerLM holding ALL experts (the
        # global stack leaf is [dp, L_total, E_total, ...]), applied to
        # each ep shard's tokens independently, CE averaged over shards
        ref_model = TransformerLM(cfg._replace(ep_axis=None, remat=False))
        for r in range(dp):
            ref_params = _assemble_reference_params(state, r, n_layers)
            ces = []
            for j in range(ep):
                flat_t = toks[r, j].reshape(-1, SEQ)
                flat_y = tgts[r, j].reshape(-1, SEQ)
                ces.append(float(lm_loss(
                    ref_model.apply({"params": ref_params}, flat_t),
                    flat_y)))
            np.testing.assert_allclose(float(got[r]), np.mean(ces),
                                       rtol=2e-5, atol=2e-5)


    @pytest.mark.slow
    def test_pp_ep_train_matches_assembled_model(self):
        """pp × ep one momentum-free SGD step: every param — expert
        slices included — moves by exactly ``-lr * grad`` of the stacked
        full-expert model under the mean-over-ep-shards CE
        (moe_loss_coef=0 isolates CE; no-drop capacity makes routing
        per-token).  Pins the uniform ``/n_ep`` grad scaling on the
        pipeline mesh — eval parity alone cannot catch a wrong expert
        grad scale (round-3 lesson)."""
        from stochastic_gradient_push_tpu.train.lm import EP_AXIS, lm_loss
        from stochastic_gradient_push_tpu.train.pp import (
            make_dp_pp_ep_mesh)

        dp, pp, ep, n_layers, n_micro, mb = 1, 2, 2, 2, 2, 2
        cfg = _cfg(n_layers, moe_experts=4, moe_every=1,
                   moe_capacity_factor=8.0, ep_axis=EP_AXIS)
        model = PipelineStageLM(cfg, n_local_layers=n_layers // pp)
        mesh = make_dp_pp_ep_mesh(dp, pp, ep)
        alg = all_reduce(GOSSIP_AXIS)
        tx = sgd(momentum=0.0, weight_decay=0.0)
        lrs = LRSchedule(ref_lr=0.1, batch_size=mb * n_micro,
                         world_size=dp, decay_schedule={}, warmup=False)
        step = build_pp_train_step(model, alg, tx, lrs, itr_per_epoch=100,
                                   moe_loss_coef=0.0)
        state = init_pp_state(model, mesh, alg, tx, dp=dp, pp=pp,
                              n_micro=n_micro, micro_batch=mb,
                              seq_len=SEQ, ep=ep)
        train_fn = shard_pp_train_step(
            step, mesh, pp_state_specs(state, ep_axis=EP_AXIS),
            ep_axis=EP_AXIS)
        rng = np.random.default_rng(11)
        shape = (dp, ep, n_micro, mb, SEQ)
        toks = rng.integers(0, VOCAB, size=shape).astype(np.int32)
        tgts = rng.integers(0, VOCAB, size=shape).astype(np.int32)

        ref_params = _assemble_reference_params(state, 0, n_layers)
        ref_model = TransformerLM(cfg._replace(ep_axis=None, remat=False))

        def ref_loss(p):
            ces = []
            for j in range(ep):
                flat_t = toks[0, j].reshape(-1, SEQ)
                flat_y = tgts[0, j].reshape(-1, SEQ)
                ces.append(lm_loss(
                    ref_model.apply({"params": p}, flat_t), flat_y))
            return jnp.mean(jnp.stack(ces))

        ref_grads = jax.grad(ref_loss)(ref_params)
        new_state, metrics = train_fn(state, toks, tgts)
        assert float(np.asarray(metrics["moe_dropped"])[0]) == 0.0
        lr = float(np.asarray(metrics["lr"])[0])
        new_ref = _assemble_reference_params(new_state, 0, n_layers)
        expect = jax.tree.map(lambda p, g: p - lr * np.asarray(g),
                              ref_params, ref_grads)
        flat_e, _ = jax.tree_util.tree_flatten_with_path(expect)
        flat_n, _ = jax.tree_util.tree_flatten_with_path(new_ref)
        for (path_e, e), (_, n) in zip(flat_e, flat_n):
            np.testing.assert_allclose(
                np.asarray(n), np.asarray(e), rtol=5e-4, atol=1e-5,
                err_msg=jax.tree_util.keystr(path_e))

    @pytest.mark.slow
    def test_pp_sp_moe_eval_matches_assembled_model(self):
        """MoE × pp × sp: per-block expert routing (no collectives when
        ep is off) inside the ring-attention pipeline ticks.  Under
        no-drop capacity, per-(tick, block) routing equals full-batch
        routing and ring equals full attention, so the composed eval CE
        matches a stacked full-attention full-batch MoE model."""
        from stochastic_gradient_push_tpu.train.lm import lm_loss
        from stochastic_gradient_push_tpu.train.pp import (
            build_pp_eval_step, init_pp_state, make_dp_pp_sp_mesh,
            pp_state_specs, shard_pp_eval_step)

        dp, pp, sp, n_layers, n_micro, mb = 2, 2, 2, 2, 2, 2
        block = SEQ // sp
        cfg = _cfg(n_layers, moe_experts=4, moe_every=1,
                   moe_capacity_factor=8.0, attn_impl="ring",
                   seq_axis="seq")
        model = PipelineStageLM(cfg, n_local_layers=n_layers // pp)
        mesh = make_dp_pp_sp_mesh(dp, pp, sp)
        alg = all_reduce(GOSSIP_AXIS)
        tx = sgd(momentum=0.0, weight_decay=0.0)
        state = init_pp_state(model, mesh, alg, tx, dp=dp, pp=pp,
                              n_micro=n_micro, micro_batch=mb,
                              seq_len=SEQ, sp=sp)
        eval_fn = shard_pp_eval_step(
            build_pp_eval_step(model, alg), mesh,
            pp_state_specs(state), seq_axis="seq")
        rng = np.random.default_rng(4)
        shape = (dp, sp, n_micro, mb, block)
        toks = rng.integers(0, VOCAB, size=shape).astype(np.int32)
        tgts = rng.integers(0, VOCAB, size=shape).astype(np.int32)
        got = np.asarray(eval_fn(state, toks, tgts)["loss"])

        ref_model = TransformerLM(cfg._replace(
            attn_impl="full", seq_axis=None, remat=False))
        for r in range(dp):
            ref_params = _assemble_reference_params(state, r, n_layers)
            # reassemble full sequences from the contiguous seq blocks
            full_t = np.concatenate(
                [toks[r, j] for j in range(sp)], axis=-1
            ).reshape(-1, SEQ)
            full_y = np.concatenate(
                [tgts[r, j] for j in range(sp)], axis=-1
            ).reshape(-1, SEQ)
            ref_ce = float(lm_loss(
                ref_model.apply({"params": ref_params}, full_t), full_y))
            np.testing.assert_allclose(float(got[r]), ref_ce,
                                       rtol=2e-5, atol=2e-5)


    def test_pp_ep_sp_4d_eval_matches_assembled_model(self):
        """The full 4-D pipeline mesh (gossip × pipe × ep × seq): expert
        all_to_all within each seq shard inside ring-attention ticks.
        Under no-drop capacity the composed eval CE equals a stacked
        full-expert full-attention model run on each ep shard's
        reassembled sequences, averaged over ep."""
        from stochastic_gradient_push_tpu.train.lm import EP_AXIS, lm_loss
        from stochastic_gradient_push_tpu.train.pp import (
            build_pp_eval_step, init_pp_state, make_dp_pp_ep_sp_mesh,
            pp_state_specs, shard_pp_eval_step)

        dp, pp, ep, sp, n_layers, n_micro, mb = 1, 2, 2, 2, 2, 2, 2
        block = SEQ // sp
        cfg = _cfg(n_layers, moe_experts=4, moe_every=1,
                   moe_capacity_factor=8.0, attn_impl="ring",
                   seq_axis="seq", ep_axis=EP_AXIS)
        model = PipelineStageLM(cfg, n_local_layers=n_layers // pp)
        mesh = make_dp_pp_ep_sp_mesh(dp, pp, ep, sp)
        alg = all_reduce(GOSSIP_AXIS)
        tx = sgd(momentum=0.0, weight_decay=0.0)
        state = init_pp_state(model, mesh, alg, tx, dp=dp, pp=pp,
                              n_micro=n_micro, micro_batch=mb,
                              seq_len=SEQ, sp=sp, ep=ep)
        eval_fn = shard_pp_eval_step(
            build_pp_eval_step(model, alg), mesh,
            pp_state_specs(state, ep_axis=EP_AXIS),
            seq_axis="seq", ep_axis=EP_AXIS)
        rng = np.random.default_rng(5)
        shape = (dp, ep, sp, n_micro, mb, block)
        toks = rng.integers(0, VOCAB, size=shape).astype(np.int32)
        tgts = rng.integers(0, VOCAB, size=shape).astype(np.int32)
        got = float(np.asarray(eval_fn(state, toks, tgts)["loss"])[0])

        ref_model = TransformerLM(cfg._replace(
            attn_impl="full", seq_axis=None, ep_axis=None, remat=False))
        ref_params = _assemble_reference_params(state, 0, n_layers)
        ces = []
        for j in range(ep):
            full_t = np.concatenate(
                [toks[0, j, s] for s in range(sp)], axis=-1
            ).reshape(-1, SEQ)
            full_y = np.concatenate(
                [tgts[0, j, s] for s in range(sp)], axis=-1
            ).reshape(-1, SEQ)
            ces.append(float(lm_loss(
                ref_model.apply({"params": ref_params}, full_t), full_y)))
        np.testing.assert_allclose(got, np.mean(ces), rtol=2e-5,
                                   atol=2e-5)


class TestPipelineGossip:
    @pytest.mark.parametrize("make_alg", [
        lambda dp: sgp(build_schedule(
            DynamicDirectedExponentialGraph(dp)), GOSSIP_AXIS,
            overlap=True),
        lambda dp: dpsgd(build_schedule(
            DynamicDirectedExponentialGraph(dp)), GOSSIP_AXIS),
    ], ids=["osgp", "dpsgd"])
    def test_other_algorithms_compose_with_pipeline(self, make_alg):
        """OSGP (overlap, in-flight gossip buffer in the carried state) and
        D-PSGD both slot into the pipelined step unchanged."""
        dp, pp, n_layers, n_micro = 4, 2, 2, 2
        alg = make_alg(dp)
        _, _, state, train_fn, toks, tgts = _setup(
            dp, pp, n_layers, n_micro, algorithm=alg, momentum=0.9)
        rng = np.random.default_rng(2)
        for _ in range(4):
            toks = rng.integers(0, VOCAB, size=toks.shape).astype(np.int32)
            tgts = rng.integers(0, VOCAB, size=tgts.shape).astype(np.int32)
            state, metrics = train_fn(state, toks, tgts)
        assert np.all(np.isfinite(np.asarray(metrics["loss"])))

    def test_sgp_composes_with_pipeline(self):
        """dp=4 gossip replicas × pp=2 stages: SGP trains, push-sum weight
        stays 1 (regular mixing), and replicas drift toward consensus."""
        dp, pp, n_layers, n_micro = 4, 2, 2, 2
        alg = sgp(build_schedule(DynamicDirectedExponentialGraph(dp)),
                  GOSSIP_AXIS)
        model, cfg, state, train_fn, toks, tgts = _setup(
            dp, pp, n_layers, n_micro, algorithm=alg, momentum=0.9)

        def spread(st):
            emb = np.asarray(st.params["embed"]["embedding"])
            return float(np.mean(np.var(emb, axis=0)))

        rng = np.random.default_rng(1)
        losses = []
        for _ in range(8):
            toks = rng.integers(0, VOCAB, size=toks.shape).astype(np.int32)
            tgts = rng.integers(0, VOCAB, size=tgts.shape).astype(np.int32)
            state, metrics = train_fn(state, toks, tgts)
            losses.append(float(np.mean(np.asarray(metrics["loss"]))))
        assert all(np.isfinite(l) for l in losses)
        w = np.asarray(state.gossip.ps_weight)
        np.testing.assert_allclose(w, 1.0, atol=1e-4)
        # gossip keeps replicas' shared leaves within consensus reach:
        # spread stays bounded (pure SGD with per-replica data would grow)
        assert spread(state) < 1.0

    def test_fences(self):
        """The one remaining pipeline constraint: the scanned stage stack
        is uniform, so MoE requires moe_every=1 (every axis composition —
        ring, MoE, ep, and the 4-D pp × ep × sp — was lifted in
        round 3)."""
        cfg = _cfg(2, moe_experts=4, moe_every=2)
        with pytest.raises(ValueError, match="moe_every=1"):
            PipelineStageLM(cfg, n_local_layers=1).init(
                jax.random.PRNGKey(0), jnp.zeros((1, 2, SEQ), jnp.int32))


class TestPipelineRing:
    @pytest.mark.slow
    def test_pp_sp_matches_pp_only(self, tmp_path):
        """pp × sp through the CLI: ring attention inside the pipeline
        tick body (KV rotation over seq, activations over pipe) produces
        the same losses as the pp-only full-attention run on the same
        global batch."""
        from stochastic_gradient_push_tpu.run.gossip_lm import main

        common = ["--seq_len", "32", "--d_model", "32", "--n_layers", "2",
                  "--n_heads", "4", "--d_ff", "64", "--vocab_size", "64",
                  "--batch_size", "4", "--n_micro", "2", "--num_steps",
                  "4", "--corpus_tokens", "20000", "--print_freq", "2"]
        r_sp = main(["--world_size", "8", "--pp", "2", "--sp", "2",
                     "--checkpoint_dir", str(tmp_path / "sp")] + common)
        r_pp = main(["--world_size", "4", "--pp", "2",
                     "--checkpoint_dir", str(tmp_path / "pp")] + common)
        assert np.isfinite(r_sp["final_loss"])
        np.testing.assert_allclose(r_sp["final_loss"], r_pp["final_loss"],
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(r_sp["avg_loss"], r_pp["avg_loss"],
                                   rtol=2e-5, atol=2e-5)
