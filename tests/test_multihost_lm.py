"""Multi-host LM training: 2 processes × 4 CPU devices over
jax.distributed, through the real gossip_lm CLI.

Extends the image-harness multi-host proof (tests/test_multihost.py) to
the transformer path: per-process batch contribution via
``jax.make_array_from_callback``, cross-process ring-attention sequence
parallelism on a (gossip, seq) mesh, per-process CSVs, and per-process
checkpoint save + consensus resume.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

# every test here launches 2 OS processes that rendezvous over
# jax.distributed and compile their own programs — minutes each
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _launch(port: int, proc_id: int, ckpt_dir: str, num_steps: int,
            resume: str, extra: tuple = ()) -> subprocess.Popen:
    args = [
        sys.executable, "-m", "stochastic_gradient_push_tpu.run.gossip_lm",
        "--multihost", "True",
        "--coordinator_address", f"127.0.0.1:{port}",
        "--num_processes", "2", "--process_id", str(proc_id),
        "--world_size", "8", "--vocab_size", "64", "--d_model", "32",
        "--n_layers", "2", "--n_heads", "4", "--d_ff", "64",
        "--seq_len", "32", "--batch_size", "4",
        "--num_steps", str(num_steps), "--print_freq", "2",
        "--checkpoint_dir", ckpt_dir, "--resume", resume, *extra,
    ]
    return subprocess.Popen(args, cwd=REPO, env=_worker_env(),
                            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                            text=True)


def _run_pair(port: int, ckpt_dir: str, num_steps: int, resume: str,
              extra: tuple = ()) -> list[str]:
    procs = [_launch(port, i, ckpt_dir, num_steps, resume, extra)
             for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out[-4000:]}"
    return outs


def _csv_losses(path):
    rows = [l for l in open(path).read().splitlines() if l[:1].isdigit()]
    return [float(r.split(",")[1]) for r in rows]


def test_two_process_lm_train_and_resume(tmp_path):
    """Plain gossip-DP LM across 2 processes: trains, writes per-process
    CSVs with finite decreasing-ish loss, then resumes from per-process
    checkpoints at the consensus step."""
    ckpt_dir = str(tmp_path / "lm")
    port = _free_port()
    outs = _run_pair(port, ckpt_dir, num_steps=8, resume="False")
    for p in range(2):
        f = os.path.join(ckpt_dir, f"lm_out_p{p}_n8.csv")
        assert os.path.isfile(f), f"missing per-process csv {f}"
        losses = _csv_losses(f)
        assert losses and all(np.isfinite(losses))
    # the two processes see identical (replicated) metrics
    assert _csv_losses(os.path.join(ckpt_dir, "lm_out_p0_n8.csv")) == \
        _csv_losses(os.path.join(ckpt_dir, "lm_out_p1_n8.csv"))
    for r in (0, 1):
        assert os.path.isfile(
            os.path.join(ckpt_dir, f"lm_checkpoint_r{r}_n8.ckpt"))

    port2 = _free_port()
    outs2 = _run_pair(port2, ckpt_dir, num_steps=12, resume="True")
    assert all("resumed from step 8" in o for o in outs2), outs2[0][-2000:]


def test_two_process_lm_ring_attention(tmp_path):
    """dp×sp across processes: ring attention's KV rotation crosses the
    host boundary (4 replicas × 2 sequence shards over 2 processes)."""
    ckpt_dir = str(tmp_path / "lm_sp")
    port = _free_port()
    outs = _run_pair(port, ckpt_dir, num_steps=6, resume="False",
                     extra=("--sp", "2"))
    assert all("multihost LM" in o for o in outs)
    losses = _csv_losses(os.path.join(ckpt_dir, "lm_out_p0_n8.csv"))
    assert losses and all(np.isfinite(losses))


def test_two_process_lm_ep_tp_orbax(tmp_path):
    """ep×tp across processes: expert all_to_all dispatch and GSPMD
    tensor parallelism crossing the host boundary, with the orbax
    global-state checkpoint — the rank-row msgpack layout cannot slice
    states sharded on non-leading dims, so ep/tp meshes save ONE shared
    logical checkpoint with every process writing its own shards."""
    ckpt_dir = str(tmp_path / "lm_eptp")
    extra = ("--ep", "2", "--tp", "2", "--moe_experts", "4",
             "--moe_every", "2")
    port = _free_port()
    outs = _run_pair(port, ckpt_dir, num_steps=6, resume="False",
                     extra=extra)
    assert all("multihost LM" in o for o in outs)
    p0 = _csv_losses(os.path.join(ckpt_dir, "lm_out_p0_n8.csv"))
    p1 = _csv_losses(os.path.join(ckpt_dir, "lm_out_p1_n8.csv"))
    assert p0 and all(np.isfinite(p0)) and p0 == p1
    root = os.path.join(ckpt_dir, "lm_orbax_global_n8")
    assert os.path.isdir(root), "missing shared orbax root"
    steps = [d for d in os.listdir(root)
             if d.isdigit() and os.path.isdir(os.path.join(root, d))]
    assert steps, f"no orbax steps under {root}"

    port2 = _free_port()
    outs2 = _run_pair(port2, ckpt_dir, num_steps=10, resume="True",
                      extra=extra)
    assert all("resumed from step 6" in o for o in outs2), \
        outs2[0][-2000:]


def test_two_process_lm_pp_orbax(tmp_path):
    """dp×pp across processes: the pipeline tick ppermute crosses the
    host boundary, with the orbax global-state checkpoint (stage stacks
    shard on the pipe axis, which rank-row msgpack cannot slice)."""
    ckpt_dir = str(tmp_path / "lm_pp")
    extra = ("--pp", "2", "--n_micro", "2")
    port = _free_port()
    outs = _run_pair(port, ckpt_dir, num_steps=6, resume="False",
                     extra=extra)
    assert all("multihost LM" in o for o in outs)
    p0 = _csv_losses(os.path.join(ckpt_dir, "lm_out_p0_n8.csv"))
    p1 = _csv_losses(os.path.join(ckpt_dir, "lm_out_p1_n8.csv"))
    assert p0 and all(np.isfinite(p0)) and p0 == p1
    root = os.path.join(ckpt_dir, "lm_orbax_global_n8")
    assert os.path.isdir(root), "missing shared orbax root"

    port2 = _free_port()
    outs2 = _run_pair(port2, ckpt_dir, num_steps=10, resume="True",
                      extra=extra)
    assert all("resumed from step 6" in o for o in outs2), \
        outs2[0][-2000:]
