"""Algorithm-level tests: AR / SGP / OSGP / D-PSGD / AD-PSGD.

Each algorithm drives a toy distributed optimization — per-rank quadratic
losses with different optima — through the same four-slot step structure the
real train harness uses.  Checks: consensus of de-biased parameters,
equivalence of AR to large-batch SGD, OSGP mass conservation including the
in-flight buffer, and exact agreement of sync SGP with a numpy
mixing-matrix simulator.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from stochastic_gradient_push_tpu.algorithms import (
    BilateralGossip,
    adpsgd,
    all_reduce,
    dpsgd,
    osgp,
    sgp,
)
from stochastic_gradient_push_tpu.parallel import GOSSIP_AXIS, make_gossip_mesh
from stochastic_gradient_push_tpu.topology import (
    DynamicBipartiteExponentialGraph,
    NPeerDynamicDirectedExponentialGraph,
    build_pairing_schedule,
    build_schedule,
)

WORLD = 8
DIM = 4


@pytest.fixture(scope="module")
def mesh():
    return make_gossip_mesh(WORLD)


def quad_loss(params, target):
    return 0.5 * jnp.sum((params - target) ** 2)


def stack_state(state):
    """Replicate a single-rank GossipState across the world dimension."""
    return jax.tree.map(
        lambda a: np.broadcast_to(np.asarray(a), (WORLD,) + np.shape(a)).copy(),
        state)


def make_runner(alg, mesh, lr):
    """Jitted (params, gstate, targets) -> (params, gstate) train step."""

    def step(params, gstate, target):
        params, gstate = alg.pre_step(params, gstate)
        z = alg.eval_params(params, gstate)
        grads = jax.grad(quad_loss)(z, target)
        grads = alg.reduce_grads(grads)
        params = params - lr * grads
        params, gstate = alg.post_step(params, gstate)
        return params, gstate

    return jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS), P(GOSSIP_AXIS)),
        out_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS))))


def debias(alg, params, gstate):
    w = np.asarray(gstate.ps_weight).reshape(WORLD, *([1] * (params.ndim - 1)))
    return params / w


rng = np.random.default_rng(42)
TARGETS = rng.normal(size=(WORLD, DIM)).astype(np.float32)
X0 = rng.normal(size=(WORLD, DIM)).astype(np.float32)


def run_alg(alg, mesh, steps=300, lr=0.05, x0=X0):
    f = make_runner(alg, mesh, lr)
    params = x0.copy()
    gstate = stack_state(alg.init(jnp.zeros((DIM,), jnp.float32)))
    for _ in range(steps):
        params, gstate = f(params, gstate, TARGETS)
        # XLA CPU in-process collectives deadlock when many executions are
        # in flight concurrently; serialize dispatch in tests
        jax.block_until_ready(params)
    return np.asarray(params), jax.tree.map(np.asarray, gstate)


def test_allreduce_matches_centralized_sgd(mesh):
    # DDP semantics: all ranks start from identical params (the reference
    # broadcasts rank 0's init)
    x0 = np.broadcast_to(X0[0], X0.shape).copy()
    alg = all_reduce(GOSSIP_AXIS)
    params, _ = run_alg(alg, mesh, steps=200, lr=0.1, x0=x0)
    # AR-SGD on Σ quadratics converges to the mean target on every rank
    want = TARGETS.mean(axis=0)
    for r in range(WORLD):
        np.testing.assert_allclose(params[r], want, atol=1e-4)

    # one AR step == SGD on the mean gradient, exactly
    f = make_runner(alg, mesh, lr=0.1)
    gstate = stack_state(alg.init(jnp.zeros((DIM,), jnp.float32)))
    p1, _ = f(x0, gstate, TARGETS)
    mean_grad = (x0 - TARGETS).mean(axis=0)
    np.testing.assert_allclose(np.asarray(p1), x0 - 0.1 * mean_grad,
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("make_alg", [
    lambda s: sgp(s, GOSSIP_AXIS),
    lambda s: osgp(s, GOSSIP_AXIS),
    lambda s: dpsgd(s, GOSSIP_AXIS),
    lambda s: dpsgd(s, GOSSIP_AXIS, overlap=True),
])
def test_gossip_algorithms_reach_consensus_optimum(mesh, make_alg):
    graph = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1)
    sched = build_schedule(graph)
    alg = make_alg(sched)
    lr = 0.05
    params, gstate = run_alg(alg, mesh, steps=400, lr=lr)
    z = debias(alg, params, gstate)
    want = TARGETS.mean(axis=0)
    # the rank-average converges to the consensus optimum exactly
    np.testing.assert_allclose(z.mean(axis=0), want, atol=2e-3)
    # individual ranks keep only the O(lr) steady-state disagreement
    # characteristic of decentralized SGD with a constant step size
    spread = np.abs(z - z.mean(axis=0, keepdims=True)).max()
    assert spread < 4 * lr, f"spread {spread} too large for lr={lr}"

    # shrinking the step size shrinks the disagreement proportionally
    params, gstate = run_alg(alg, mesh, steps=400, lr=lr / 10)
    z_small = debias(alg, params, gstate)
    small_spread = np.abs(z_small - z_small.mean(axis=0, keepdims=True)).max()
    assert small_spread < spread / 4, (small_spread, spread)


def test_adpsgd_reaches_consensus_optimum(mesh):
    graph = DynamicBipartiteExponentialGraph(WORLD)
    pairing = build_pairing_schedule(graph)
    alg = adpsgd(pairing, GOSSIP_AXIS)
    lr = 0.05
    params, _ = run_alg(alg, mesh, steps=400, lr=lr)
    want = TARGETS.mean(axis=0)
    np.testing.assert_allclose(params.mean(axis=0), want, atol=2e-3)
    spread = np.abs(params - params.mean(axis=0, keepdims=True)).max()
    assert spread < 4 * lr, spread


def test_sync_sgp_matches_numpy_simulator(mesh):
    """Bit-level check: the sharded SGP step equals the mixing-matrix model
    x ← W(phase) @ (x - lr * ∇f(x))  (regular graph ⇒ w ≡ 1)."""
    graph = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1)
    sched = build_schedule(graph)
    alg = sgp(sched, GOSSIP_AXIS)
    lr = 0.05
    f = make_runner(alg, mesh, lr)

    params = X0.copy()
    gstate = stack_state(alg.init(jnp.zeros((DIM,), jnp.float32)))
    sim = X0.astype(np.float64).copy()
    for step_i in range(10):
        params, gstate = f(params, gstate, TARGETS)
        W = sched.mixing_matrix(step_i)
        sim = W @ (sim - lr * (sim - TARGETS))
        np.testing.assert_allclose(np.asarray(params), sim,
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gstate.ps_weight),
                                   np.ones(WORLD), rtol=1e-5)


def test_osgp_mass_conservation_with_in_flight(mesh):
    """Total mass (params + in-flight residuals) is conserved when lr=0."""
    graph = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1)
    sched = build_schedule(graph)
    alg = osgp(sched, GOSSIP_AXIS)
    f = make_runner(alg, mesh, lr=0.0)

    params = X0.copy()
    gstate = stack_state(alg.init(jnp.zeros((DIM,), jnp.float32)))
    total0 = X0.sum(axis=0)
    for _ in range(17):
        params, gstate = f(params, gstate, TARGETS)
        # in-flight is a FIFO of (params, weight) slots — sum all slots
        in_p_total = sum(np.asarray(p).sum(axis=0)
                         for p, _ in gstate.in_flight)
        total = np.asarray(params).sum(axis=0) + in_p_total
        np.testing.assert_allclose(total, total0, rtol=1e-4, atol=1e-4)
        # ps-weight mass likewise: Σ(w + in_w) == WORLD
        w_total = np.asarray(gstate.ps_weight).sum() + sum(
            np.asarray(w).sum() for _, w in gstate.in_flight)
        np.testing.assert_allclose(w_total, WORLD, rtol=1e-5)

    # with lr=0 the de-biased estimates converge to the initial mean
    for _ in range(60):
        params, gstate = f(params, gstate, TARGETS)
        # serialize dispatch: XLA CPU in-process collectives deadlock
        # when many executions are in flight (see run_alg)
        jax.block_until_ready(params)
    z = debias(alg, np.asarray(params), gstate)
    np.testing.assert_allclose(
        z, np.broadcast_to(X0.mean(axis=0), z.shape), atol=1e-3)


def test_osgp_one_step_staleness_vs_sync(mesh):
    """After one step, overlap mode holds back exactly the incoming share:
    params_osgp + in_flight == params_sync."""
    graph = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1)
    sched = build_schedule(graph)
    lr = 0.05
    f_sync = make_runner(sgp(sched, GOSSIP_AXIS), mesh, lr)
    f_over = make_runner(osgp(sched, GOSSIP_AXIS), mesh, lr)

    gs_sync = stack_state(sgp(sched, GOSSIP_AXIS).init(
        jnp.zeros((DIM,), jnp.float32)))
    gs_over = stack_state(osgp(sched, GOSSIP_AXIS).init(
        jnp.zeros((DIM,), jnp.float32)))

    p_sync, _ = f_sync(X0, gs_sync, TARGETS)
    p_over, gs_over = f_over(X0, gs_over, TARGETS)
    in_p, _ = gs_over.in_flight[0]
    np.testing.assert_allclose(np.asarray(p_over) + np.asarray(in_p),
                               np.asarray(p_sync), rtol=1e-5, atol=1e-6)


def test_osgp_val_params_drains_to_sync(mesh):
    """Validation parity with the reference's ``model.eval()`` drain
    (distributed.py:322-327): at staleness 1 the local+incoming split is
    exact, so OSGP's TRAINING trajectory as seen by the forward is
    identical to sync SGP's — and ``val_params`` (which drains the
    in-flight share before de-biasing) must therefore equal sync SGP's
    eval view at every step.  ``eval_params`` alone (undrained) must
    NOT, or the overlap buffer would be vacuous."""
    graph = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1)
    sched = build_schedule(graph)
    lr = 0.05
    alg_s = sgp(sched, GOSSIP_AXIS)
    alg_o = osgp(sched, GOSSIP_AXIS)
    f_sync = make_runner(alg_s, mesh, lr)
    f_over = make_runner(alg_o, mesh, lr)

    def val_view(alg):
        return jax.jit(jax.shard_map(
            alg.val_params, mesh=mesh,
            in_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS)),
            out_specs=P(GOSSIP_AXIS)))

    vs, vo = val_view(alg_s), val_view(alg_o)  # jit once, not per step
    p_s = X0.copy()
    p_o = X0.copy()
    gs_s = stack_state(alg_s.init(jnp.zeros((DIM,), jnp.float32)))
    gs_o = stack_state(alg_o.init(jnp.zeros((DIM,), jnp.float32)))
    for k in range(7):
        p_s, gs_s = f_sync(p_s, gs_s, TARGETS)
        jax.block_until_ready(p_s)
        p_o, gs_o = f_over(p_o, gs_o, TARGETS)
        jax.block_until_ready(p_o)
        z_sync = np.asarray(vs(p_s, gs_s))
        z_oval = np.asarray(vo(p_o, gs_o))
        np.testing.assert_allclose(z_oval, z_sync, rtol=1e-5, atol=1e-6,
                                   err_msg=f"step {k}")
    # undrained eval differs (the buffer holds a real share)
    z_oeval = np.asarray(jax.jit(jax.shard_map(
        alg_o.eval_params, mesh=mesh,
        in_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS)),
        out_specs=P(GOSSIP_AXIS)))(p_o, gs_o))
    assert np.max(np.abs(z_oeval - z_sync)) > 1e-4


@pytest.mark.parametrize("staleness", [2, 3])
def test_osgp_bounded_staleness(mesh, staleness):
    """synch_freq analogue: incoming shares ride `staleness` steps in a
    FIFO.  Mass stays conserved for any staleness, consensus still holds,
    and the slot actually consumed is the oldest one."""
    graph = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1)
    sched = build_schedule(graph)
    alg = osgp(sched, GOSSIP_AXIS, staleness=staleness)
    f = make_runner(alg, mesh, lr=0.0)

    params = X0.copy()
    gstate = stack_state(alg.init(jnp.zeros((DIM,), jnp.float32)))
    assert len(gstate.in_flight) == staleness
    total0 = X0.sum(axis=0)
    for _ in range(11):
        params, gstate = f(params, gstate, TARGETS)
        in_p_total = sum(np.asarray(p).sum(axis=0)
                         for p, _ in gstate.in_flight)
        total = np.asarray(params).sum(axis=0) + in_p_total
        np.testing.assert_allclose(total, total0, rtol=1e-4, atol=1e-4)
        w_total = np.asarray(gstate.ps_weight).sum() + sum(
            np.asarray(w).sum() for _, w in gstate.in_flight)
        np.testing.assert_allclose(w_total, WORLD, rtol=1e-5)

    # consensus with lr=0: de-biased params converge to the initial mean
    # (staler mixing converges slower, so give it more rounds)
    for _ in range(120 * staleness):
        params, gstate = f(params, gstate, TARGETS)
        # serialize dispatch: XLA CPU in-process collectives deadlock
        # when many executions are in flight (see run_alg)
        jax.block_until_ready(params)
    z = debias(alg, np.asarray(params), gstate)
    np.testing.assert_allclose(
        z, np.broadcast_to(X0.mean(axis=0), z.shape), atol=2e-3)


def test_osgp_staleness_consumes_oldest_first(mesh):
    """With staleness=2, after exactly two steps the round launched at
    step 0 (and only it) has been folded back in."""
    graph = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1)
    sched = build_schedule(graph)
    alg = osgp(sched, GOSSIP_AXIS, staleness=2)
    f = make_runner(alg, mesh, lr=0.0)
    gstate = stack_state(alg.init(jnp.zeros((DIM,), jnp.float32)))

    p, gs = f(X0, gstate, TARGETS)
    # slot 0 empty (nothing old enough yet), slot 1 = round 0's incoming
    np.testing.assert_allclose(np.asarray(gs.in_flight[0][0]), 0.0,
                               atol=1e-7)
    assert np.abs(np.asarray(gs.in_flight[1][0])).max() > 0

    # step 2 consumes slot 0 (still empty) and shifts round 0's share to
    # the front; round 1's share takes the freed last slot
    p2, gs2 = f(p, gs, TARGETS)
    assert np.abs(np.asarray(gs2.in_flight[0][0])).max() > 0
    assert np.abs(np.asarray(gs2.in_flight[1][0])).max() > 0

    # step 3 folds round 0's share (launched at step 0) back into params:
    # the round trip took exactly `staleness` = 2 steps
    mass_before = (np.asarray(p2).sum(axis=0)
                   + sum(np.asarray(b).sum(axis=0)
                         for b, _ in gs2.in_flight))
    p3, gs3 = f(p2, gs2, TARGETS)
    mass_after = (np.asarray(p3).sum(axis=0)
                  + sum(np.asarray(b).sum(axis=0)
                        for b, _ in gs3.in_flight))
    np.testing.assert_allclose(mass_after, mass_before, rtol=1e-4)


def test_bilat_step_is_exact_pair_average(mesh):
    graph = DynamicBipartiteExponentialGraph(WORLD)
    pairing = build_pairing_schedule(graph)
    alg = BilateralGossip(pairing, GOSSIP_AXIS)
    f = make_runner(alg, mesh, lr=0.0)
    gstate = stack_state(alg.init(jnp.zeros((DIM,), jnp.float32)))
    p1, _ = f(X0, gstate, TARGETS)
    p1 = np.asarray(p1)
    for r in range(WORLD):
        np.testing.assert_allclose(p1[r], 0.5 * (X0[r] + X0[pairing[0, r]]),
                                   rtol=1e-6)
