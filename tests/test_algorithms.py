"""Algorithm-level tests: AR / SGP / OSGP / D-PSGD / AD-PSGD.

Each algorithm drives a toy distributed optimization — per-rank quadratic
losses with different optima — through the same four-slot step structure the
real train harness uses.  Checks: consensus of de-biased parameters,
equivalence of AR to large-batch SGD, OSGP mass conservation including the
in-flight buffer, and exact agreement of sync SGP with a numpy
mixing-matrix simulator.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from stochastic_gradient_push_tpu.algorithms import (
    BilateralGossip,
    adpsgd,
    all_reduce,
    dpsgd,
    osgp,
    sgp,
)
from stochastic_gradient_push_tpu.parallel import GOSSIP_AXIS, make_gossip_mesh
from stochastic_gradient_push_tpu.topology import (
    DynamicBipartiteExponentialGraph,
    NPeerDynamicDirectedExponentialGraph,
    build_pairing_schedule,
    build_schedule,
)

WORLD = 8
DIM = 4


@pytest.fixture(scope="module")
def mesh():
    return make_gossip_mesh(WORLD)


def quad_loss(params, target):
    return 0.5 * jnp.sum((params - target) ** 2)


def stack_state(state):
    """Replicate a single-rank GossipState across the world dimension."""
    return jax.tree.map(
        lambda a: np.broadcast_to(np.asarray(a), (WORLD,) + np.shape(a)).copy(),
        state)


def make_runner(alg, mesh, lr):
    """Jitted (params, gstate, targets) -> (params, gstate) train step."""

    def step(params, gstate, target):
        params, gstate = alg.pre_step(params, gstate)
        z = alg.eval_params(params, gstate)
        grads = jax.grad(quad_loss)(z, target)
        grads = alg.reduce_grads(grads)
        params = params - lr * grads
        params, gstate = alg.post_step(params, gstate)
        return params, gstate

    return jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS), P(GOSSIP_AXIS)),
        out_specs=(P(GOSSIP_AXIS), P(GOSSIP_AXIS))))


def debias(alg, params, gstate):
    w = np.asarray(gstate.ps_weight).reshape(WORLD, *([1] * (params.ndim - 1)))
    return params / w


rng = np.random.default_rng(42)
TARGETS = rng.normal(size=(WORLD, DIM)).astype(np.float32)
X0 = rng.normal(size=(WORLD, DIM)).astype(np.float32)


def run_alg(alg, mesh, steps=300, lr=0.05, x0=X0):
    f = make_runner(alg, mesh, lr)
    params = x0.copy()
    gstate = stack_state(alg.init(jnp.zeros((DIM,), jnp.float32)))
    for _ in range(steps):
        params, gstate = f(params, gstate, TARGETS)
        # XLA CPU in-process collectives deadlock when many executions are
        # in flight concurrently; serialize dispatch in tests
        jax.block_until_ready(params)
    return np.asarray(params), jax.tree.map(np.asarray, gstate)


def test_allreduce_matches_centralized_sgd(mesh):
    # DDP semantics: all ranks start from identical params (the reference
    # broadcasts rank 0's init)
    x0 = np.broadcast_to(X0[0], X0.shape).copy()
    alg = all_reduce(GOSSIP_AXIS)
    params, _ = run_alg(alg, mesh, steps=200, lr=0.1, x0=x0)
    # AR-SGD on Σ quadratics converges to the mean target on every rank
    want = TARGETS.mean(axis=0)
    for r in range(WORLD):
        np.testing.assert_allclose(params[r], want, atol=1e-4)

    # one AR step == SGD on the mean gradient, exactly
    f = make_runner(alg, mesh, lr=0.1)
    gstate = stack_state(alg.init(jnp.zeros((DIM,), jnp.float32)))
    p1, _ = f(x0, gstate, TARGETS)
    mean_grad = (x0 - TARGETS).mean(axis=0)
    np.testing.assert_allclose(np.asarray(p1), x0 - 0.1 * mean_grad,
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("make_alg", [
    lambda s: sgp(s, GOSSIP_AXIS),
    lambda s: osgp(s, GOSSIP_AXIS),
    lambda s: dpsgd(s, GOSSIP_AXIS),
    lambda s: dpsgd(s, GOSSIP_AXIS, overlap=True),
])
def test_gossip_algorithms_reach_consensus_optimum(mesh, make_alg):
    graph = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1)
    sched = build_schedule(graph)
    alg = make_alg(sched)
    lr = 0.05
    params, gstate = run_alg(alg, mesh, steps=400, lr=lr)
    z = debias(alg, params, gstate)
    want = TARGETS.mean(axis=0)
    # the rank-average converges to the consensus optimum exactly
    np.testing.assert_allclose(z.mean(axis=0), want, atol=2e-3)
    # individual ranks keep only the O(lr) steady-state disagreement
    # characteristic of decentralized SGD with a constant step size
    spread = np.abs(z - z.mean(axis=0, keepdims=True)).max()
    assert spread < 4 * lr, f"spread {spread} too large for lr={lr}"

    # shrinking the step size shrinks the disagreement proportionally
    params, gstate = run_alg(alg, mesh, steps=400, lr=lr / 10)
    z_small = debias(alg, params, gstate)
    small_spread = np.abs(z_small - z_small.mean(axis=0, keepdims=True)).max()
    assert small_spread < spread / 4, (small_spread, spread)


def test_adpsgd_reaches_consensus_optimum(mesh):
    graph = DynamicBipartiteExponentialGraph(WORLD)
    pairing = build_pairing_schedule(graph)
    alg = adpsgd(pairing, GOSSIP_AXIS)
    lr = 0.05
    params, _ = run_alg(alg, mesh, steps=400, lr=lr)
    want = TARGETS.mean(axis=0)
    np.testing.assert_allclose(params.mean(axis=0), want, atol=2e-3)
    spread = np.abs(params - params.mean(axis=0, keepdims=True)).max()
    assert spread < 4 * lr, spread


def test_sync_sgp_matches_numpy_simulator(mesh):
    """Bit-level check: the sharded SGP step equals the mixing-matrix model
    x ← W(phase) @ (x - lr * ∇f(x))  (regular graph ⇒ w ≡ 1)."""
    graph = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1)
    sched = build_schedule(graph)
    alg = sgp(sched, GOSSIP_AXIS)
    lr = 0.05
    f = make_runner(alg, mesh, lr)

    params = X0.copy()
    gstate = stack_state(alg.init(jnp.zeros((DIM,), jnp.float32)))
    sim = X0.astype(np.float64).copy()
    for step_i in range(10):
        params, gstate = f(params, gstate, TARGETS)
        W = sched.mixing_matrix(step_i)
        sim = W @ (sim - lr * (sim - TARGETS))
        np.testing.assert_allclose(np.asarray(params), sim,
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gstate.ps_weight),
                                   np.ones(WORLD), rtol=1e-5)


def test_osgp_mass_conservation_with_in_flight(mesh):
    """Total mass (params + in-flight residuals) is conserved when lr=0."""
    graph = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1)
    sched = build_schedule(graph)
    alg = osgp(sched, GOSSIP_AXIS)
    f = make_runner(alg, mesh, lr=0.0)

    params = X0.copy()
    gstate = stack_state(alg.init(jnp.zeros((DIM,), jnp.float32)))
    total0 = X0.sum(axis=0)
    for _ in range(17):
        params, gstate = f(params, gstate, TARGETS)
        # in-flight is a FIFO of (params, weight) slots — sum all slots
        in_p_total = sum(np.asarray(p).sum(axis=0)
                         for p, _ in gstate.in_flight)
        total = np.asarray(params).sum(axis=0) + in_p_total
        np.testing.assert_allclose(total, total0, rtol=1e-4, atol=1e-4)
        # ps-weight mass likewise: Σ(w + in_w) == WORLD
        w_total = np.asarray(gstate.ps_weight).sum() + sum(
            np.asarray(w).sum() for _, w in gstate.in_flight)
        np.testing.assert_allclose(w_total, WORLD, rtol=1e-5)

    # with lr=0 the de-biased estimates converge to the initial mean
    for _ in range(60):
        params, gstate = f(params, gstate, TARGETS)
        # serialize dispatch: XLA CPU in-process collectives deadlock
        # when many executions are in flight (see run_alg)
        jax.block_until_ready(params)
    z = debias(alg, np.asarray(params), gstate)
    np.testing.assert_allclose(
        z, np.broadcast_to(X0.mean(axis=0), z.shape), atol=1e-3)


def test_osgp_one_round_stale_vs_sync(mesh):
    """The double-buffered round's one-round staleness, exactly: at
    staleness 1 the launch (pre_step) ships w_i·x_t BEFORE the gradient
    update and the consume (post_step) lands after it, so
    x_{t+1} = W·x_t − lr·∇f(x_t)  — the gradient rides OUTSIDE the
    mixing, vs sync's W·(x_t − lr·∇f).  The FIFO is fully drained at
    every step boundary (nothing stays in flight across steps)."""
    graph = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1)
    sched = build_schedule(graph)
    lr = 0.05
    f_sync = make_runner(sgp(sched, GOSSIP_AXIS), mesh, lr)
    f_over = make_runner(osgp(sched, GOSSIP_AXIS), mesh, lr)

    gs_sync = stack_state(sgp(sched, GOSSIP_AXIS).init(
        jnp.zeros((DIM,), jnp.float32)))
    gs_over = stack_state(osgp(sched, GOSSIP_AXIS).init(
        jnp.zeros((DIM,), jnp.float32)))

    p_sync, _ = f_sync(X0, gs_sync, TARGETS)
    p_over, gs_over = f_over(X0, gs_over, TARGETS)
    W = sched.mixing_matrix(0)
    grad = X0 - TARGETS
    np.testing.assert_allclose(np.asarray(p_over),
                               W @ X0 - lr * grad, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(p_sync),
                               W @ (X0 - lr * grad), rtol=1e-5, atol=1e-6)
    # staleness 1 consumes the same-step launch: FIFO empty between steps
    np.testing.assert_allclose(np.asarray(gs_over.in_flight[0][0]), 0.0,
                               atol=1e-7)
    np.testing.assert_allclose(np.asarray(gs_over.ps_weight),
                               np.ones(WORLD), rtol=1e-5)


def test_osgp_matches_augmented_numpy_simulator(mesh):
    """Bit-level pin of the phase schedule at staleness 1–3: the compiled
    overlap trajectory equals the AUGMENTED one-round-stale matrix model
    (GossipSchedule.overlap_schedule — the SGPV106 object) applied to the
    stacked state (x, f₁ … f_s), with the gradient entering the x block
    only.  This is the jit-vs-numpy equality for the double-buffered
    round."""
    graph = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1)
    sched = build_schedule(graph)
    lr = 0.05
    for staleness in (1, 2, 3):
        alg = osgp(sched, GOSSIP_AXIS, staleness=staleness)
        f = make_runner(alg, mesh, lr)
        aug = sched.overlap_schedule(staleness)
        assert aug.world_size == WORLD * staleness
        params = X0.copy()
        gstate = stack_state(alg.init(jnp.zeros((DIM,), jnp.float32)))
        # augmented state: block 0 = params, block k = in-flight slot k;
        # the push-sum weight lane follows the SAME augmented recursion
        # (at staleness > 1 in-flight mass keeps w != 1 between steps)
        y = np.zeros((WORLD * staleness, DIM))
        y[:WORLD] = X0.astype(np.float64)
        yw = np.zeros(WORLD * staleness)
        yw[:WORLD] = 1.0
        for step_i in range(2 * staleness + 3):
            params, gstate = f(params, gstate, TARGETS)
            jax.block_until_ready(params)
            # the gradient is taken at the de-biased x_t/w_t (the
            # launch's local rescale cancels in x/w) and applied to the
            # live numerator block only — outside the mixing, one round
            # stale
            grad = y[:WORLD] / yw[:WORLD, None] - TARGETS
            A = aug.mixing_matrix(step_i)
            y = A @ y
            yw = A @ yw
            y[:WORLD] -= lr * grad
            np.testing.assert_allclose(
                np.asarray(params), y[:WORLD], rtol=1e-5, atol=1e-5,
                err_msg=f"staleness {staleness} step {step_i}")
            # FIFO slots 0..s-2 mirror augmented blocks 1..s-1; the
            # tail slot is always empty between steps (freed for the
            # next launch)
            for k in range(staleness - 1):
                np.testing.assert_allclose(
                    np.asarray(gstate.in_flight[k][0]),
                    y[(k + 1) * WORLD:(k + 2) * WORLD],
                    rtol=1e-5, atol=1e-5,
                    err_msg=f"staleness {staleness} slot {k} "
                            f"step {step_i}")
            np.testing.assert_allclose(
                np.asarray(gstate.in_flight[-1][0]), 0.0, atol=1e-7)


def test_osgp_val_params_drains_in_flight(mesh):
    """Validation view ≙ the reference's ``model.eval()`` drain
    (distributed.py:322-327): ``val_params`` folds every in-flight share
    into the de-bias.  At staleness 1 the FIFO is empty between steps, so
    ``val_params == eval_params``; at staleness 2 a real share is in
    flight — the drained view must equal the hand-drained
    ``(x + Σ slots) / (w + Σ slot_w)``, differ from the undrained eval,
    and (lr=0) its mass-weighted mean must equal the initial mean
    exactly (nothing in flight is lost or double-counted)."""
    graph = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1)
    sched = build_schedule(graph)

    def views(alg):
        spec = (P(GOSSIP_AXIS), P(GOSSIP_AXIS))
        val = jax.jit(jax.shard_map(alg.val_params, mesh=mesh,
                                    in_specs=spec,
                                    out_specs=P(GOSSIP_AXIS)))
        ev = jax.jit(jax.shard_map(alg.eval_params, mesh=mesh,
                                   in_specs=spec,
                                   out_specs=P(GOSSIP_AXIS)))
        return val, ev

    # staleness 1: nothing in flight between steps — val == eval
    alg1 = osgp(sched, GOSSIP_AXIS)
    f1 = make_runner(alg1, mesh, lr=0.05)
    val1, ev1 = views(alg1)
    p = X0.copy()
    gs = stack_state(alg1.init(jnp.zeros((DIM,), jnp.float32)))
    for k in range(3):
        p, gs = f1(p, gs, TARGETS)
        jax.block_until_ready(p)
        np.testing.assert_allclose(np.asarray(val1(p, gs)),
                                   np.asarray(ev1(p, gs)),
                                   rtol=1e-6, atol=1e-7,
                                   err_msg=f"step {k}")

    # staleness 2: one share is genuinely in flight across the boundary
    alg2 = osgp(sched, GOSSIP_AXIS, staleness=2)
    f2 = make_runner(alg2, mesh, lr=0.0)
    val2, ev2 = views(alg2)
    p = X0.copy()
    gs = stack_state(alg2.init(jnp.zeros((DIM,), jnp.float32)))
    for _ in range(5):
        p, gs = f2(p, gs, TARGETS)
        jax.block_until_ready(p)
    drained_p = np.asarray(p).astype(np.float64)
    drained_w = np.asarray(gs.ps_weight).astype(np.float64)
    for in_p, in_w in gs.in_flight:
        drained_p = drained_p + np.asarray(in_p)
        drained_w = drained_w + np.asarray(in_w).reshape(drained_w.shape)
    want = drained_p / drained_w.reshape(WORLD, 1)
    z_val = np.asarray(val2(p, gs))
    np.testing.assert_allclose(z_val, want, rtol=1e-5, atol=1e-6)
    # the undrained eval differs (the buffer holds a real share)
    assert np.max(np.abs(np.asarray(ev2(p, gs)) - z_val)) > 1e-4
    # and total mass (numerator and weight lanes) is exactly conserved
    np.testing.assert_allclose(drained_p.sum(axis=0), X0.sum(axis=0),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(drained_w.sum(), WORLD, rtol=1e-6)


@pytest.mark.parametrize("staleness", [2, 3])
def test_osgp_bounded_staleness(mesh, staleness):
    """synch_freq analogue: incoming shares ride `staleness` steps in a
    FIFO.  Mass stays conserved for any staleness, consensus still holds,
    and the slot actually consumed is the oldest one."""
    graph = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1)
    sched = build_schedule(graph)
    alg = osgp(sched, GOSSIP_AXIS, staleness=staleness)
    f = make_runner(alg, mesh, lr=0.0)

    params = X0.copy()
    gstate = stack_state(alg.init(jnp.zeros((DIM,), jnp.float32)))
    assert len(gstate.in_flight) == staleness
    total0 = X0.sum(axis=0)
    for _ in range(11):
        params, gstate = f(params, gstate, TARGETS)
        in_p_total = sum(np.asarray(p).sum(axis=0)
                         for p, _ in gstate.in_flight)
        total = np.asarray(params).sum(axis=0) + in_p_total
        np.testing.assert_allclose(total, total0, rtol=1e-4, atol=1e-4)
        w_total = np.asarray(gstate.ps_weight).sum() + sum(
            np.asarray(w).sum() for _, w in gstate.in_flight)
        np.testing.assert_allclose(w_total, WORLD, rtol=1e-5)

    # consensus with lr=0: de-biased params converge to the initial mean
    # (staler mixing converges slower, so give it more rounds)
    for _ in range(120 * staleness):
        params, gstate = f(params, gstate, TARGETS)
        # serialize dispatch: XLA CPU in-process collectives deadlock
        # when many executions are in flight (see run_alg)
        jax.block_until_ready(params)
    z = debias(alg, np.asarray(params), gstate)
    np.testing.assert_allclose(
        z, np.broadcast_to(X0.mean(axis=0), z.shape), atol=2e-3)


def test_osgp_staleness_consumes_oldest_first(mesh):
    """With staleness=2, the share launched at the top of step t is
    consumed at the bottom of step t+1 — "round t−1's payload mixed in
    at the bottom" — and the FIFO tail is always the freed slot."""
    graph = NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1)
    sched = build_schedule(graph)
    alg = osgp(sched, GOSSIP_AXIS, staleness=2)
    f = make_runner(alg, mesh, lr=0.0)
    gstate = stack_state(alg.init(jnp.zeros((DIM,), jnp.float32)))

    def off_diag(phase):
        W = sched.mixing_matrix(phase)
        return W - np.diag(np.diag(W))

    # step 1: pre launches round 0 (head slot), post pops the empty
    # tail's predecessor — nothing old enough yet, round 0 stays
    p, gs = f(X0, gstate, TARGETS)
    np.testing.assert_allclose(np.asarray(gs.in_flight[0][0]),
                               off_diag(0) @ X0, rtol=1e-5, atol=1e-6,
                               err_msg="round 0's share should be the "
                                       "oldest in-flight slot")
    np.testing.assert_allclose(np.asarray(gs.in_flight[1][0]), 0.0,
                               atol=1e-7)

    # step 2: pre launches round 1 into the freed tail, post consumes
    # round 0's share (launched exactly staleness−1 = 1 step ago)
    x1 = np.asarray(p).astype(np.float64)
    p2, gs2 = f(p, gs, TARGETS)
    lo1 = np.diag(sched.mixing_matrix(1))
    want = lo1[:, None] * x1 + off_diag(0) @ X0
    np.testing.assert_allclose(np.asarray(p2), want, rtol=1e-5,
                               atol=1e-6,
                               err_msg="step 2 must fold round 0's "
                                       "share back in")
    np.testing.assert_allclose(np.asarray(gs2.in_flight[0][0]),
                               off_diag(1) @ x1, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gs2.in_flight[1][0]), 0.0,
                               atol=1e-7)


def test_bilat_step_is_exact_pair_average(mesh):
    graph = DynamicBipartiteExponentialGraph(WORLD)
    pairing = build_pairing_schedule(graph)
    alg = BilateralGossip(pairing, GOSSIP_AXIS)
    f = make_runner(alg, mesh, lr=0.0)
    gstate = stack_state(alg.init(jnp.zeros((DIM,), jnp.float32)))
    p1, _ = f(X0, gstate, TARGETS)
    p1 = np.asarray(p1)
    for r in range(WORLD):
        np.testing.assert_allclose(p1[r], 0.5 * (X0[r] + X0[pairing[0, r]]),
                                   rtol=1e-6)
