"""Quantized gossip wire format + per-rank error feedback (ISSUE 10).

Covers the codec layer (parallel/wire.py) against numpy oracles, the
error-feedback telescoping identity, int8+EF vs f32 consensus parity on
the world-8 CPU mesh, ps-weight-lane exactness under faults plus
compression, reshard residual zeroing, encoded-payload pricing pinned
against hand counts, planner wire-fraction pricing, and the CLI flag
surface of both run harnesses.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from stochastic_gradient_push_tpu.algorithms import sgp
from stochastic_gradient_push_tpu.parallel import (
    GOSSIP_AXIS,
    gossip_round,
    make_gossip_mesh,
    mix_push_sum,
)
from stochastic_gradient_push_tpu.parallel import wire
from stochastic_gradient_push_tpu.telemetry import (
    CommModel,
    encoded_payload_bytes,
    tree_payload_bytes,
)
from stochastic_gradient_push_tpu.topology import (
    HierarchicalGraph,
    NPeerDynamicDirectedExponentialGraph,
    RingGraph,
    build_schedule,
)

WORLD = 8


@pytest.fixture(scope="module")
def mesh():
    return make_gossip_mesh(WORLD)


# -- codec oracles ---------------------------------------------------------


def _int8_oracle(x: np.ndarray, block: int) -> np.ndarray:
    """Independent numpy reference for Int8Codec's roundtrip."""
    n = x.size
    nb = -(-n // block)
    flat = np.zeros(nb * block, np.float32)
    flat[:n] = x.reshape(-1).astype(np.float32)
    blocks = flat.reshape(nb, block)
    scale = np.abs(blocks).max(axis=1) / 127.0
    safe = np.where(scale > 0, scale, 1.0)
    q = np.clip(np.round(blocks / safe[:, None]), -127, 127)
    return (q * scale[:, None]).reshape(-1)[:n].reshape(x.shape).astype(
        x.dtype)


@pytest.mark.parametrize("shape", [(7,), (64,), (130,), (3, 5, 11)])
def test_int8_roundtrip_matches_numpy_oracle(shape):
    rng = np.random.default_rng(0)
    x = (rng.normal(size=shape) * rng.uniform(0.01, 10)).astype(
        np.float32)
    codec = wire.Int8Codec(64)
    got = np.asarray(jax.jit(
        lambda a: codec.decode(codec.encode(a), a))(x))
    np.testing.assert_array_equal(got, _int8_oracle(x, 64))


def test_int8_handles_zero_blocks_and_q_of_zero():
    codec = wire.Int8Codec(4)
    x = np.zeros(10, np.float32)
    out = np.asarray(codec.decode(codec.encode(jnp.asarray(x)), x))
    np.testing.assert_array_equal(out, x)  # Q(0) == 0: drop semantics
    q, scale = codec.encode(jnp.asarray(x))
    assert q.dtype == jnp.int8 and scale.dtype == jnp.float32


def test_bf16_codec_matches_plain_cast_and_f32_is_identity():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(33,)).astype(np.float32)
    got = np.asarray(wire.BF16.decode(wire.BF16.encode(jnp.asarray(x)),
                                      x))
    np.testing.assert_array_equal(
        got, np.asarray(jnp.asarray(x).astype(jnp.bfloat16)
                        .astype(jnp.float32)))
    f32 = np.asarray(wire.F32.decode(wire.F32.encode(jnp.asarray(x)), x))
    np.testing.assert_array_equal(f32, x)


def test_codec_registry_and_pricing():
    assert wire.get_codec(None) is None
    assert wire.get_codec("f32") is wire.F32
    assert wire.get_codec("bf16") is wire.BF16
    int8 = wire.get_codec("int8", 32)
    assert isinstance(int8, wire.Int8Codec) and int8.block == 32
    with pytest.raises(ValueError, match="unknown wire_dtype"):
        wire.get_codec("fp4")
    with pytest.raises(ValueError, match="wire_block"):
        wire.Int8Codec(0)
    # element_bytes hand counts
    assert wire.F32.element_bytes(100) == 400
    assert wire.BF16.element_bytes(100) == 200
    assert wire.Int8Codec(64).element_bytes(100) == 100 + 4 * 2
    # asymptotic fractions drive the planner pricing
    assert wire.F32.wire_fraction() == 1.0
    assert wire.BF16.wire_fraction() == 0.5
    assert wire.Int8Codec(64).wire_fraction() == pytest.approx(
        (1 + 4 / 64) / 4)
    # deprecated alias maps exactly onto the bf16 codec
    assert wire.from_comm_dtype(jnp.bfloat16) is wire.BF16
    assert wire.from_comm_dtype(None) is None


def test_ef_telescoping_identity_single_sender():
    """The error-feedback invariant in isolation: over T rounds,
    sum(delivered) == sum(intended) - final_residual exactly (the
    initial residual is zero) — quantization error never accumulates
    into a bias, it only rides as bounded pending correction."""
    codec = wire.Int8Codec(16)
    rng = np.random.default_rng(2)
    msgs = rng.normal(size=(20, 48)).astype(np.float32)

    def body(r, m):
        v = m + r
        d = codec.decode(codec.encode(v), v)
        return v - d, d

    r = jnp.zeros(48, jnp.float32)
    delivered = np.zeros(48, np.float64)
    step = jax.jit(body)
    for m in msgs:
        r, d = step(r, jnp.asarray(m))
        delivered += np.asarray(d, np.float64)
    want = msgs.astype(np.float64).sum(0) - np.asarray(r, np.float64)
    np.testing.assert_allclose(delivered, want, atol=5e-5)


# -- compiled mesh behavior ------------------------------------------------


def _stacked_init(alg, dim):
    return jax.tree.map(
        lambda a: np.broadcast_to(np.asarray(a),
                                  (WORLD,) + np.shape(a)).copy(),
        alg.init(jnp.zeros((dim,), jnp.float32)))


def test_int8_ef_mean_telescopes_on_mesh(mesh):
    """Pure averaging under int8+EF: delivered mass plus pending
    residuals preserves the exact mean; the raw mean drifts by at most
    the residual mass."""
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    codec = wire.Int8Codec(64)
    rng = np.random.default_rng(3)
    x = rng.normal(size=(WORLD, 130)).astype(np.float32)
    w = np.ones((WORLD, 1), np.float32)
    r = np.zeros_like(x)
    mean = x.mean(0)

    def step(phase, xs, ws, rs):
        return mix_push_sum(xs, ws, phase, sched, GOSSIP_AXIS,
                            codec=codec, ef_residual=rs)

    f = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(GOSSIP_AXIS), P(GOSSIP_AXIS), P(GOSSIP_AXIS)),
        out_specs=(P(GOSSIP_AXIS),) * 3))
    for phase in range(40):
        x, w, r = map(np.asarray,
                      jax.block_until_ready(f(jnp.int32(phase), x, w, r)))
    assert np.abs((x.sum(0) + r.sum(0)) / WORLD - mean).max() < 1e-5
    assert np.abs((x / w).mean(0) - mean).max() < 5e-3
    # and the wire really quantizes: consensus is approximate, not exact
    assert np.abs(r).max() > 0


def test_int8_ef_consensus_parity_with_f32(mesh):
    """Acceptance: an SGD consensus run at int8+EF reaches consensus
    error within 2x of the exact f32 wire after the same step budget,
    and lands at the same optimum."""
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    rng = np.random.default_rng(4)
    targets = rng.normal(size=(WORLD, 48)).astype(np.float32)
    p0 = rng.normal(size=(WORLD, 48)).astype(np.float32)
    lr = 0.05

    def run(codec, ef):
        alg = sgp(sched, GOSSIP_AXIS, wire=codec, error_feedback=ef)

        def step(p, g, t):
            p, g = alg.pre_step(p, g)
            z = alg.eval_params(p, g)
            grad = jax.grad(lambda q: 0.5 * jnp.sum((q - t) ** 2))(z)
            return alg.post_step(p - lr * grad, g)

        f = jax.jit(jax.shard_map(
            step, mesh=mesh, in_specs=(P(GOSSIP_AXIS),) * 3,
            out_specs=(P(GOSSIP_AXIS),) * 2))
        p, g = p0.copy(), _stacked_init(alg, 48)
        for _ in range(150):
            p, g = jax.block_until_ready(f(p, g, targets))
        z = np.asarray(p) / np.asarray(g.ps_weight).reshape(WORLD, 1)
        return (float(np.abs(z - z.mean(0)).max()),
                float(np.abs(z.mean(0) - targets.mean(0)).max()))

    f32_spread, f32_err = run(None, False)
    i8_spread, i8_err = run(wire.Int8Codec(64), True)
    assert i8_spread <= 2.0 * max(f32_spread, 1e-4), \
        (i8_spread, f32_spread)
    assert i8_err <= 2.0 * max(f32_err, 1e-3), (i8_err, f32_err)


def test_ps_weight_lane_exact_under_faults_and_compression(mesh):
    """The push-sum weight trajectory under faults is bit-identical with
    and without wire compression: the scalar lane never touches the
    codec, so mass accounting is exactly the faulted-f32 one."""
    from stochastic_gradient_push_tpu.resilience import parse_fault_spec

    sched = build_schedule(RingGraph(WORLD, peers_per_itr=1))

    def run(codec, ef):
        masks = parse_fault_spec("drop:0->1@0:64;seed:7").build_masks(
            sched)
        alg = sgp(sched, GOSSIP_AXIS, faults=masks, wire=codec,
                  error_feedback=ef)

        def step(p, g):
            return alg.post_step(p, g)

        f = jax.jit(jax.shard_map(
            step, mesh=mesh, in_specs=(P(GOSSIP_AXIS),) * 2,
            out_specs=(P(GOSSIP_AXIS),) * 2))
        rng = np.random.default_rng(5)
        p = rng.normal(size=(WORLD, 64)).astype(np.float32)
        g = _stacked_init(alg, 64)
        ws = []
        for _ in range(10):
            p, g = jax.block_until_ready(f(p, g))
            ws.append(np.asarray(g.ps_weight).copy())
        return np.stack(ws)

    w_exact = run(None, False)
    w_int8 = run(wire.Int8Codec(64), True)
    np.testing.assert_array_equal(w_exact, w_int8)
    assert np.abs(np.asarray(w_int8[-1]).mean() - 1.0) < 1e-5


def test_thinned_gossip_carries_residual_through_idle_steps(mesh):
    """gossip_every=2: non-firing steps pass the residual through
    unchanged; firing steps update it."""
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    alg = sgp(sched, GOSSIP_AXIS, gossip_every=2,
              wire=wire.Int8Codec(64), error_feedback=True)

    def step(p, g):
        return alg.post_step(p, g)

    f = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(GOSSIP_AXIS),) * 2,
        out_specs=(P(GOSSIP_AXIS),) * 2))
    rng = np.random.default_rng(6)
    p = rng.normal(size=(WORLD, 32)).astype(np.float32)
    g = _stacked_init(alg, 32)
    # tick 0 fires: residual becomes nonzero
    p, g = jax.block_until_ready(f(p, g))
    r_fire = np.asarray(g.ef_residual).copy()
    assert np.abs(r_fire).max() > 0
    # tick 1 does not fire: residual identical
    p, g = jax.block_until_ready(f(p, g))
    np.testing.assert_array_equal(np.asarray(g.ef_residual), r_fire)
    # tick 2 fires again: residual moves
    p, g = jax.block_until_ready(f(p, g))
    assert np.abs(np.asarray(g.ef_residual) - r_fire).max() > 0


def test_hierarchical_delegate_lane_compression(mesh):
    """A hierarchical round with an int8 codec: the wire codec rides the
    delegate (inter) lane while the intra-slice psum stays exact — the
    round still mean-preserves to within the residual bound."""
    g = HierarchicalGraph(WORLD, slice_size=4)
    sched = build_schedule(g)
    codec = wire.Int8Codec(64)
    rng = np.random.default_rng(7)
    x = rng.normal(size=(WORLD, 96)).astype(np.float32)
    w = np.ones((WORLD, 1), np.float32)
    r = np.zeros_like(x)
    mean = x.mean(0)

    def step(phase, xs, ws, rs):
        (p, ww), rr = gossip_round(
            (xs, ws), phase, sched, GOSSIP_AXIS, codec=codec,
            ef_residual=(rs, jnp.zeros_like(ws)))
        return p, ww, rr[0]

    f = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(GOSSIP_AXIS), P(GOSSIP_AXIS), P(GOSSIP_AXIS)),
        out_specs=(P(GOSSIP_AXIS),) * 3))
    for phase in range(12):
        x, w, r = map(np.asarray,
                      jax.block_until_ready(f(jnp.int32(phase), x, w, r)))
    z = x / w
    assert np.abs(z.mean(0) - mean).max() < 5e-3
    assert np.abs(z - z.mean(0)).max() < 5e-2  # two-level mixing works


def test_ef_requires_lossy_codec():
    sched = build_schedule(
        NPeerDynamicDirectedExponentialGraph(WORLD, peers_per_itr=1))
    with pytest.raises(ValueError, match="lossy wire codec"):
        sgp(sched, GOSSIP_AXIS, error_feedback=True)
    with pytest.raises(ValueError, match="lossy wire codec"):
        sgp(sched, GOSSIP_AXIS, wire=wire.F32, error_feedback=True)
    # EF composes with overlap now: the residual telescopes against the
    # round being SENT at launch (tests/test_overlap.py pins the
    # telescoping identity on the compiled mesh)
    alg = sgp(sched, GOSSIP_AXIS, overlap=True, wire=wire.Int8Codec(),
              error_feedback=True)
    assert alg.overlap and alg.error_feedback
    with pytest.raises(ValueError, match="not both"):
        sgp(sched, GOSSIP_AXIS, wire=wire.BF16,
            comm_dtype=jnp.bfloat16)
    # push-pull carries no residual state: EF must be rejected up front
    # (a silently-None residual would change the carried pytree
    # structure mid-run)
    from stochastic_gradient_push_tpu.algorithms import PushSumGossip
    with pytest.raises(ValueError, match="track_weight"):
        PushSumGossip(sched, GOSSIP_AXIS, track_weight=False,
                      wire=wire.Int8Codec(), error_feedback=True)


# -- pricing ---------------------------------------------------------------


def test_encoded_payload_bytes_hand_counts():
    params = {"w": np.zeros((WORLD, 1000), np.float32),
              "b": np.zeros((WORLD, 24), np.float32),
              "s": np.zeros((WORLD,), np.float32)}  # scalar per rank
    # f32 / no codec: plain storage bytes
    assert encoded_payload_bytes(params, WORLD) == (1000 + 24 + 1) * 4
    assert encoded_payload_bytes(params, WORLD, wire.F32) \
        == (1000 + 24 + 1) * 4
    # bf16 halves payload lanes; the scalar leaf stays at 4 B (the
    # collective's size>1 guard keeps it off the codec)
    assert encoded_payload_bytes(params, WORLD, wire.BF16) \
        == (1000 + 24) * 2 + 4
    # int8: 1 B/element + one f32 scale per 64-block, scalar exempt
    hand = (1000 + 4 * 16) + (24 + 4 * 1) + 4
    assert encoded_payload_bytes(params, WORLD, wire.Int8Codec(64)) \
        == hand
    # >= 3.5x reduction on the payload lanes (the acceptance ratio)
    full = tree_payload_bytes(params, WORLD)
    assert full / encoded_payload_bytes(params, WORLD,
                                        wire.Int8Codec(64)) >= 3.5


def test_comm_model_prices_encoded_wire_and_stamps_codec():
    sched = build_schedule(RingGraph(WORLD, peers_per_itr=1))
    codec = wire.Int8Codec(64)
    params = {"w": np.zeros((WORLD, 1000), np.float32)}
    enc = encoded_payload_bytes(params, WORLD, codec)
    exact = tree_payload_bytes(params, WORLD)
    model = CommModel.from_schedule(sched, enc, exact_bytes=exact,
                                    global_avg_every=4, codec=codec,
                                    error_feedback=True)
    totals = model.totals(8)
    # wire = encoded payload + the exact 4B ps-weight lane per message
    assert totals["gossip_wire"] == 8 * (enc + 4)
    # exact lanes (scheduled averages) price the FULL precision payload
    from stochastic_gradient_push_tpu.telemetry import allreduce_bytes
    assert totals["global_avg"] == 2 * allreduce_bytes(exact, WORLD)
    d = model.to_dict()
    assert d["wire_dtype"] == "int8" and d["wire_block"] == 64
    assert d["error_feedback"] is True
    assert d["payload_bytes"] == enc and d["exact_bytes"] == exact


def test_hierarchical_comm_model_compresses_delegate_lane_only():
    g = HierarchicalGraph(WORLD, slice_size=4)
    sched = build_schedule(g)
    params = {"w": np.zeros((WORLD, 4096), np.float32)}
    codec = wire.Int8Codec(64)
    enc = encoded_payload_bytes(params, WORLD, codec)
    exact = tree_payload_bytes(params, WORLD)
    m_enc = CommModel.from_schedule(sched, enc, exact_bytes=exact,
                                    codec=codec)
    m_exact = CommModel.from_schedule(sched, exact, exact_bytes=exact)
    t_enc, t_exact = m_enc.totals(4), m_exact.totals(4)
    # DCN (delegate) lane shrinks by ~the codec ratio...
    assert t_enc["gossip_dcn"] < t_exact["gossip_dcn"] / 3
    # ...while the intra-slice exact average keeps the ICI lane's
    # ring-allreduce term at full precision (strictly above the pure
    # codec ratio)
    assert t_enc["gossip_ici"] > t_exact["gossip_ici"] / 3


def test_planner_prices_wire_fraction():
    from stochastic_gradient_push_tpu.planner import (
        check_topology, plan_for, PlanConstraints)
    from stochastic_gradient_push_tpu.planner.scorer import (
        evaluate_candidate)

    frac = wire.Int8Codec(64).wire_fraction()
    base = evaluate_candidate(RingGraph, 8, 1)
    comp = evaluate_candidate(RingGraph, 8, 1, wire_fraction=frac)
    assert comp.comm_cost == pytest.approx(base.comm_cost * frac)
    assert comp.priced_cost == pytest.approx(base.priced_cost * frac)
    # hierarchical: only the delegate lane compresses — the intra-slice
    # exact average is priced at full precision even on the uniform
    # fabric (where it is priced as-written, not as a fused psum), so
    # the candidate's cost shrinks by LESS than the pure codec ratio
    hb = evaluate_candidate(HierarchicalGraph, 8, 1)
    hc = evaluate_candidate(HierarchicalGraph, 8, 1, wire_fraction=frac)
    assert hc.priced_cost > hb.priced_cost * frac * 1.5
    assert hc.priced_cost < hb.priced_cost
    # the plan stamps the codec config it was priced on
    wire_cfg = {"dtype": "int8", "block": 64, "error_feedback": True}
    plan = plan_for(8, ppi=1, constraints=PlanConstraints(wire=wire_cfg))
    assert plan.wire == wire_cfg
    assert plan.to_dict()["wire"] == wire_cfg
    forced = check_topology(8, RingGraph, ppi=1, wire=wire_cfg)
    assert forced.wire == wire_cfg
    # an f32/absent wire keeps rankings and costs exactly as before
    assert plan_for(8, ppi=1).wire is None


# -- reshard ---------------------------------------------------------------


def test_reshard_zeros_ef_residual_and_preserves_mean():
    from stochastic_gradient_push_tpu.supervise.reshard import (
        consensus_mean, reshard_state)

    rng = np.random.default_rng(8)
    state = {
        "params": {"w": rng.normal(size=(4, 6)).astype(np.float32)},
        "gossip": {
            "phase": np.full((4,), 3, np.int32),
            "ps_weight": np.full((4,), 1.0, np.float32),
            "in_flight": None,
            "ef_residual": {
                "w": rng.normal(size=(4, 6)).astype(np.float32) * 1e-3},
        },
        "step": np.full((4,), 17, np.int32),
    }
    before = consensus_mean(state)
    out = reshard_state(state, 4, 2)
    after = consensus_mean(out)
    for k in before:
        np.testing.assert_allclose(after[k], before[k], atol=1e-7)
    # residuals are dropped (zeroed) at the new world — pending
    # correction is bounded, stale, and schedule-bound
    assert out["gossip"]["ef_residual"]["w"].shape == (2, 6)
    assert np.all(out["gossip"]["ef_residual"]["w"] == 0)


# -- monitor ---------------------------------------------------------------


def test_monitor_reports_and_flags_ef_residual():
    from stochastic_gradient_push_tpu.resilience.monitor import (
        EF_HEALTH_KEY, HealthMonitor)

    base = {"consensus_residual": 0.0, "ps_w_min": 1.0, "ps_w_max": 1.0,
            "ps_mass_err": 0.0, "nonfinite_params": 0.0,
            "nonfinite_grads": 0.0}
    mon = HealthMonitor(health_every=1)
    rep = mon.observe(0, {**base, EF_HEALTH_KEY: 1e-4})
    assert not rep.unhealthy
    assert rep.payload[EF_HEALTH_KEY] == pytest.approx(1e-4)
    rep = mon.observe(1, {**base, EF_HEALTH_KEY: 0.5})
    assert "ef-residual-blowup" in rep.reasons
    rep = mon.observe(2, {**base, EF_HEALTH_KEY: float("nan")})
    assert "ef-residual-blowup" in rep.reasons
    # runs without EF never emit (or diagnose) the key
    rep = mon.observe(3, base)
    assert EF_HEALTH_KEY not in rep.payload and not rep.unhealthy


# -- CLI surface -----------------------------------------------------------


def test_sgd_cli_wire_flags_thread_into_config():
    from stochastic_gradient_push_tpu.run.gossip_sgd import parse_config

    cfg, args = parse_config(
        ["--dataset", "synthetic", "--wire_dtype", "int8",
         "--wire_block", "32", "--error_feedback", "True"])
    assert cfg.wire_dtype == "int8" and cfg.wire_block == 32
    assert cfg.error_feedback is True


def test_sgd_cli_gossip_comm_dtype_is_deprecated_alias(capsys):
    from stochastic_gradient_push_tpu.run.gossip_sgd import parse_config

    cfg, args = parse_config(
        ["--dataset", "synthetic", "--gossip_comm_dtype", "bf16"])
    assert cfg.wire_dtype == "bf16"
    assert "deprecated" in capsys.readouterr().err
    with pytest.raises(SystemExit, match="deprecated alias"):
        parse_config(["--dataset", "synthetic",
                      "--gossip_comm_dtype", "bf16",
                      "--wire_dtype", "int8"])


def test_sgd_cli_rejects_wire_knobs_outside_push_sum():
    from stochastic_gradient_push_tpu.run.gossip_sgd import parse_config

    for flags in (["--all_reduce", "True", "--graph_type", "-1"],
                  ["--push_sum", "False"]):
        with pytest.raises(SystemExit, match="push-sum knobs"):
            parse_config(["--dataset", "synthetic",
                          "--wire_dtype", "int8"] + flags)
    with pytest.raises(SystemExit, match="lossy --wire_dtype"):
        parse_config(["--dataset", "synthetic",
                      "--error_feedback", "True"])
    # overlap + lossy wire + EF is a supported composition now
    cfg, _ = parse_config(["--dataset", "synthetic", "--overlap", "True",
                           "--wire_dtype", "int8",
                           "--error_feedback", "True"])
    assert cfg.overlap and cfg.error_feedback and cfg.wire_dtype == "int8"


def test_lm_cli_rejects_wire_knobs_outside_push_sum(tmp_path):
    from stochastic_gradient_push_tpu.run.gossip_lm import main

    common = ["--world_size", str(WORLD), "--num_steps", "1",
              "--d_model", "16", "--n_layers", "1", "--n_heads", "2",
              "--d_ff", "32", "--seq_len", "16", "--batch_size", "2",
              "--checkpoint_dir", str(tmp_path),
              "--wire_dtype", "int8"]
    for mode in (["--all_reduce", "True"], ["--bilat", "True"],
                 ["--push_sum", "False"]):
        with pytest.raises(SystemExit, match="push-sum knobs"):
            main(common + mode)


def test_trainer_config_wire_codec_resolution():
    from stochastic_gradient_push_tpu.train.loop import (Trainer,
                                                         TrainerConfig)

    cfg = TrainerConfig(wire_dtype="int8", wire_block=32,
                        error_feedback=True)
    codec = Trainer._wire_codec(
        type("T", (), {"cfg": cfg})())  # resolve without a mesh
    assert isinstance(codec, wire.Int8Codec) and codec.block == 32
    # deprecated library-user spelling still resolves
    cfg2 = TrainerConfig(gossip_comm_dtype="bf16")
    assert Trainer._wire_codec(
        type("T", (), {"cfg": cfg2})()) is wire.BF16
    with pytest.raises(ValueError, match="deprecated alias"):
        Trainer._wire_codec(type("T", (), {
            "cfg": TrainerConfig(wire_dtype="int8",
                                 gossip_comm_dtype="bf16")})())


def test_sgd_cli_int8_ef_end_to_end(tmp_path):
    """Acceptance e2e: a world-8 CPU run with --wire_dtype int8
    --error_feedback reports comm bytes equal to an independently built
    CommModel over the ENCODED payload — and the health stream carries
    the residual signal."""
    from stochastic_gradient_push_tpu.models import TinyCNN
    from stochastic_gradient_push_tpu.run.gossip_sgd import main

    run_dir = str(tmp_path / "run")
    steps = 4
    main(["--dataset", "synthetic", "--model", "tiny_cnn",
          "--num_classes", "10", "--image_size", "16",
          "--batch_size", "4", "--world_size", str(WORLD),
          "--num_epochs", "1",
          "--num_iterations_per_training_epoch", str(steps),
          "--num_itr_ignore", "0", "--topology", "ring",
          "--wire_dtype", "int8", "--error_feedback", "True",
          "--health_every", "2", "--trace_dir", run_dir,
          "--checkpoint_dir", run_dir])

    events = []
    with open(os.path.join(run_dir, "events.jsonl")) as f:
        for line in f:
            events.append(json.loads(line))
    # plan stamped with the wire config
    plan = next(e for e in events if e["kind"] == "plan")["data"]
    assert plan["wire"] == {"dtype": "int8", "block": 64,
                            "error_feedback": True}
    # health events carry the residual signal, below the blowup floor
    health = [e["data"] for e in events if e["kind"] == "health"]
    assert health and all("ef_residual_rms" in h for h in health)
    assert all(0 <= h["ef_residual_rms"] < 0.1 for h in health)
    # comm totals == independent model over the ENCODED payload
    params = TinyCNN(num_classes=10).init(
        jax.random.PRNGKey(0), jnp.zeros((4, 16, 16, 3)))["params"]
    codec = wire.Int8Codec(64)
    enc = encoded_payload_bytes(params, 1, codec)
    exact = tree_payload_bytes(params, 1)
    model = CommModel.from_schedule(
        build_schedule(RingGraph(WORLD, peers_per_itr=1)), enc,
        exact_bytes=exact, codec=codec, error_feedback=True)
    final_comm = [e for e in events if e["kind"] == "comm"][-1]["data"]
    assert final_comm["bytes"] == model.totals(steps)
    assert final_comm["model"]["wire_dtype"] == "int8"
    # >= 3.5x payload reduction vs the exact wire, as reported
    assert exact / final_comm["model"]["payload_bytes"] >= 3.5


def test_bench_wire_sweep_artifact_schema(tmp_path, monkeypatch):
    """The --gossip-vs-ar wire sweep: artifact entries carry measured ms
    next to modeled encoded bytes, with the int8 lane >= 3.5x below the
    f32 lane and every modeled figure equal to an independent model."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_wire_under_test", os.path.join(repo, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    out_path = str(tmp_path / "gva.json")
    for k, v in (("BENCH_GVA_STEPS", "2"), ("BENCH_GVA_WARMUP", "1"),
                 ("BENCH_GVA_BATCH", "2"), ("BENCH_GVA_GA", "8"),
                 ("BENCH_GVA_OUT", out_path),
                 ("BENCH_GVA_WIRE", "f32,int8")):
        monkeypatch.setenv(k, v)
    out = bench.run_gossip_vs_ar()
    sweep = out["wire_sweep"]
    assert [e["wire_dtype"] for e in sweep] == ["f32", "int8"]
    f32e, i8e = sweep
    assert f32e["step_ms"] > 0 and i8e["step_ms"] > 0
    assert i8e["error_feedback"] is True and i8e["wire_block"] == 64
    ratio = (f32e["modeled_bytes_per_rank"]["gossip_wire"]
             / i8e["modeled_bytes_per_rank"]["gossip_wire"])
    assert ratio >= 3.5
    # artifact on disk carries the same sweep
    doc = json.load(open(out_path))
    assert doc["bench"]["wire_sweep"] == sweep
    # modeled figures equal an independently built CommModel
    from stochastic_gradient_push_tpu.models import TinyCNN
    params = TinyCNN(num_classes=10).init(
        jax.random.PRNGKey(0), jnp.zeros((2, 16, 16, 3)))["params"]
    codec = wire.Int8Codec(64)
    model = CommModel.from_schedule(
        build_schedule(RingGraph(WORLD, peers_per_itr=1)),
        encoded_payload_bytes(params, 1, codec),
        exact_bytes=tree_payload_bytes(params, 1),
        global_avg_every=8, codec=codec, error_feedback=True)
    want = model.totals(2, start=1)
    assert i8e["modeled_bytes_per_rank"]["gossip_wire"] \
        == want["gossip_wire"]
