"""bench.py cached-capture provenance (round-4 verdict, weakness #1).

The headline artifact may fall back to a recorded on-chip capture when
the tunnel is dead — but ONLY to a capture from the current round, with
its age stamped.  A prior round's capture must be refused loudly, never
silently re-reported.
"""

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _default_variant_env(monkeypatch):
    """_latest_tpu_capture matches on BENCH_NORM/BENCH_S2D; a stray
    export in the invoking shell must not flip these tests' config."""
    monkeypatch.delenv("BENCH_NORM", raising=False)
    monkeypatch.delenv("BENCH_S2D", raising=False)


@pytest.fixture(autouse=True)
def _no_round_marker(request, monkeypatch):
    """Pin the git round-marker lookup to 'unavailable' so the age rule
    is what these tests exercise; the round-marker test overrides."""
    if "bench_mod" in getattr(request, "fixturenames", ()):
        mod = request.getfixturevalue("bench_mod")
        monkeypatch.setattr(mod, "_round_start_epoch", lambda: None)


@pytest.fixture()
def bench_mod():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_capture(root, run_name, rec):
    d = root / run_name
    d.mkdir(parents=True)
    (d / "bench.jsonl").write_text(json.dumps(rec) + "\n")


LIVE_REC = {"metric": "resnet50_sgp_images_per_sec_per_chip",
            "value": 2600.0, "unit": "images/sec/chip",
            "platform": "tpu", "device": "TPU v5 lite"}


def test_fresh_capture_is_stamped(bench_mod, tmp_path):
    import datetime as dt

    now = dt.datetime.now(dt.timezone.utc)
    run = now.strftime("%Y%m%dT%H%M%S")
    _write_capture(tmp_path, run, LIVE_REC)
    rec = bench_mod._latest_tpu_capture(root=str(tmp_path))
    assert rec is not None
    assert rec["cached"] is True
    assert rec["cached_from"].endswith(run)
    assert rec["captured_at"] == run
    assert rec["capture_age_h"] < 1.0


def test_stale_capture_is_refused(bench_mod, tmp_path, capsys):
    _write_capture(tmp_path, "20260730T133755", LIVE_REC)  # a prior round
    rec = bench_mod._latest_tpu_capture(root=str(tmp_path))
    assert rec is None
    err = capsys.readouterr().err
    assert "REFUSED" in err and "20260730T133755" in err


def test_unparseable_run_name_is_refused(bench_mod, tmp_path):
    _write_capture(tmp_path, "not-a-timestamp", LIVE_REC)
    assert bench_mod._latest_tpu_capture(root=str(tmp_path)) is None


def test_cached_lines_never_recached(bench_mod, tmp_path):
    import datetime as dt

    run = dt.datetime.now(dt.timezone.utc).strftime("%Y%m%dT%H%M%S")
    _write_capture(tmp_path, run, dict(LIVE_REC, cached=True,
                                       cached_from="docs/tpu_runs/old"))
    assert bench_mod._latest_tpu_capture(root=str(tmp_path)) is None


def test_variant_capture_never_crosses_config(bench_mod, tmp_path,
                                              monkeypatch):
    """A cached record is only served to a run whose model-variant
    config (norm / s2d stem) matches the record's own stamped fields —
    a folded/s2d capture must not answer a default-config run, nor the
    reverse."""
    import datetime as dt

    run = dt.datetime.now(dt.timezone.utc).strftime("%Y%m%dT%H%M%S")
    _write_capture(tmp_path, run, dict(LIVE_REC, norm="folded"))
    monkeypatch.delenv("BENCH_NORM", raising=False)
    monkeypatch.delenv("BENCH_S2D", raising=False)
    # default run must refuse the folded capture
    assert bench_mod._latest_tpu_capture(root=str(tmp_path)) is None
    # the matching variant run gets it
    monkeypatch.setenv("BENCH_NORM", "folded")
    rec = bench_mod._latest_tpu_capture(root=str(tmp_path))
    assert rec is not None and rec["norm"] == "folded"
    # an s2d run must refuse it too (wrong variant)
    monkeypatch.setenv("BENCH_NORM", "bn")
    monkeypatch.setenv("BENCH_S2D", "1")
    assert bench_mod._latest_tpu_capture(root=str(tmp_path)) is None
    # a plain-bn capture (older, still fresh) answers the default run:
    # the non-matching folded run is skipped over, not fatal
    older = (dt.datetime.now(dt.timezone.utc)
             - dt.timedelta(minutes=1)).strftime("%Y%m%dT%H%M%S")
    _write_capture(tmp_path, older, LIVE_REC)
    monkeypatch.setenv("BENCH_S2D", "0")
    rec = bench_mod._latest_tpu_capture(root=str(tmp_path))
    assert rec is not None and rec.get("norm") is None


def test_round_marker_overrides_age(bench_mod, tmp_path, monkeypatch):
    """A capture past the age limit but newer than the round marker is
    still this round's — served (age-stamped).  Past 2x the limit, or
    older than the marker, it stays refused."""
    import datetime as dt

    old = (dt.datetime.now(dt.timezone.utc)
           - dt.timedelta(hours=14)).strftime("%Y%m%dT%H%M%S")
    _write_capture(tmp_path, old, LIVE_REC)
    cap_epoch = bench_mod._capture_epoch(old)
    # marker BEFORE the capture -> this round's -> served despite 14h
    monkeypatch.setattr(bench_mod, "_round_start_epoch",
                        lambda: cap_epoch - 3600)
    rec = bench_mod._latest_tpu_capture(root=str(tmp_path))
    assert rec is not None and 13.9 < rec["capture_age_h"] < 14.1
    # marker AFTER the capture -> prior round's -> refused
    monkeypatch.setattr(bench_mod, "_round_start_epoch",
                        lambda: cap_epoch + 3600)
    assert bench_mod._latest_tpu_capture(root=str(tmp_path)) is None
    # no marker available -> pure age rule -> refused
    monkeypatch.setattr(bench_mod, "_round_start_epoch", lambda: None)
    assert bench_mod._latest_tpu_capture(root=str(tmp_path)) is None
    # beyond the 2x backstop the marker cannot save it
    ancient = (dt.datetime.now(dt.timezone.utc)
               - dt.timedelta(hours=25)).strftime("%Y%m%dT%H%M%S")
    tmp2 = tmp_path / "b"
    _write_capture(tmp2, ancient, LIVE_REC)
    monkeypatch.setattr(
        bench_mod, "_round_start_epoch",
        lambda: bench_mod._capture_epoch(ancient) - 3600)
    assert bench_mod._latest_tpu_capture(root=str(tmp2)) is None


def test_age_override_env(bench_mod, tmp_path, monkeypatch):
    import datetime as dt

    old = (dt.datetime.now(dt.timezone.utc)
           - dt.timedelta(hours=2)).strftime("%Y%m%dT%H%M%S")
    _write_capture(tmp_path, old, LIVE_REC)
    monkeypatch.setenv("BENCH_MAX_CACHE_AGE_H", "1")
    assert bench_mod._latest_tpu_capture(root=str(tmp_path)) is None
    monkeypatch.setenv("BENCH_MAX_CACHE_AGE_H", "3")
    rec = bench_mod._latest_tpu_capture(root=str(tmp_path))
    assert rec is not None and 1.9 < rec["capture_age_h"] < 2.1
