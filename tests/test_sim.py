"""sim/: the priced-fabric fleet simulator.

Pins the package's three contracts: (1) the mixing algebra is EXACT —
the engine's fancy-index scatter is bit-identical to the dense
permutation-matrix oracle, and faults compose through the resilience
grammar's mass-conserving masks (column sums stay 1, the consensus
target never moves); (2) time is *modeled with the planner's own cost
vocabulary* — dropped edges ship nothing, DCN crossings carry the
premium, fused intra phases price as grouped allreduces; (3) the fleet
lane runs the REAL coordinator — a hello from a new host id produces
exactly one coordinated n → n′ upward reshard (grow-the-world
induction), here both in-process (simulated hosts) and, as a slow
test, through ``scripts/fleet.py --join`` with real supervisor
processes.  The sparse spectral-gap path and the cross-world grow
reshard (256→320, 1024→1536) ride along — they are what make the
simulator honest at world ≥ 1024.
"""

import os
import subprocess
import sys
import time

import flax.serialization
import numpy as np
import pytest

from stochastic_gradient_push_tpu.analysis import (
    SPARSE_GAP_WORLD_MIN,
    spectral_gap,
)
from stochastic_gradient_push_tpu.analysis.verifier import _sparse_gap
from stochastic_gradient_push_tpu.planner.interconnect import (
    InterconnectModel,
)
from stochastic_gradient_push_tpu.resilience import parse_fault_spec
from stochastic_gradient_push_tpu.sim import (
    FabricModel,
    SimState,
    cascading_slices_campaign,
    consensus_curve,
    coordinator_loss_campaign,
    gossip_tick,
    init_state,
    kill_slice_campaign,
    oracle_tick,
    payload_bytes_for,
    run_gossip,
    run_sim_fleet,
    sustained_churn_campaign,
    sweep_curves,
    time_to_error,
)
from stochastic_gradient_push_tpu.sim.fabric import (
    PHASE_LATENCY_S,
    SECONDS_PER_COST_BYTE,
)
from stochastic_gradient_push_tpu.supervise import (
    Coordinator,
    TornCheckpointError,
    consensus_mean,
    host_dir,
    load_world_checkpoint,
    reshard_checkpoints,
)
from stochastic_gradient_push_tpu.telemetry import (
    COORDINATOR_EVENTS_FILE,
    SUPERVISOR_EVENTS_FILE,
)
from stochastic_gradient_push_tpu.topology import TOPOLOGY_NAMES
from stochastic_gradient_push_tpu.topology.schedule import build_schedule

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _schedule(topology, world, ppi=1):
    return build_schedule(TOPOLOGY_NAMES[topology](world,
                                                   peers_per_itr=ppi))


# -- engine: exactness + mass conservation -----------------------------------


class TestEngine:
    @pytest.mark.parametrize("topology,world,ppi", [
        ("ring", 32, 1),
        ("exponential", 32, 2),
        ("linear", 16, 1),
        ("bipartite-exponential", 16, 1),
    ])
    def test_bit_exact_vs_dense_oracle(self, topology, world, ppi):
        # the core claim: the scatter engine IS the mixing matrix —
        # same float ops in the same order, so array_equal, not allclose
        sched = _schedule(topology, world, ppi)
        st = init_state(world, seed=2)
        oracle = SimState(params=st.params.copy(),
                          ps_weight=st.ps_weight.copy())
        for _ in range(2 * sched.num_phases + 1):
            st = gossip_tick(st, sched)
            oracle = oracle_tick(oracle, sched)
        assert np.array_equal(st.params, oracle.params)
        assert np.array_equal(st.ps_weight, oracle.ps_weight)

    def test_consensus_contracts_toward_initial_mean(self):
        sched = _schedule("exponential", 64)
        _, errs = run_gossip(sched, 24, seed=4)
        assert errs[-1] < errs[0] * 1e-3

    def test_init_state_is_world_size_invariant(self):
        # rank r's init never depends on the world, so a grown world's
        # incumbents keep their values (the hostsim stream family)
        big = init_state(6, seed=3)
        tail = init_state(2, seed=3, rank_offset=4)
        assert np.array_equal(big.params[4:], tail.params)

    def test_mass_conserved_under_sustained_churn(self):
        sched = _schedule("ring", 64)
        camp = sustained_churn_campaign(prob=0.5, at=2, duration=40,
                                        seed=9)
        plan = parse_fault_spec(camp.fault_spec)
        col0 = init_state(64, seed=6).params.sum(axis=0)
        st, errs = run_gossip(sched, 48, seed=6, fault_plan=plan)
        assert np.all(np.isfinite(st.params))
        np.testing.assert_allclose(st.params.sum(axis=0), col0,
                                   rtol=1e-11, atol=1e-11)
        assert abs(st.ps_weight.sum() - 64.0) < 1e-9
        assert errs[-1] < errs[0]

    def test_nan_corruption_poisons_wire_not_weight_lane(self):
        sched = _schedule("ring", 8)
        plan = parse_fault_spec("nan:3@0:4")
        st, _ = run_gossip(sched, 4, seed=1, fault_plan=plan)
        # rank 3's outgoing payloads were NaN: its neighbors' params are
        # poisoned, but the push-sum weight lane stays finite everywhere
        assert np.any(np.isnan(st.params))
        assert np.all(np.isfinite(st.ps_weight))


# -- satellite: sparse spectral-gap path -------------------------------------


class TestSparseSpectralGap:
    @pytest.mark.parametrize("topology,world", [
        ("ring", 16), ("ring", 64),
        ("exponential", 16), ("exponential", 64),
        ("linear", 32), ("bipartite-exponential", 32),
    ])
    def test_sparse_path_matches_dense_eig_below_threshold(
            self, topology, world):
        # below SPARSE_GAP_WORLD_MIN spectral_gap() takes the dense
        # eigensolve; the subspace-iteration path must agree on the
        # same schedules before we trust it alone at world >= 1024
        assert world < SPARSE_GAP_WORLD_MIN
        sched = _schedule(topology, world)
        dense = spectral_gap(sched)
        sparse = _sparse_gap(sched)
        assert abs(dense - sparse) <= 1e-8

    def test_sparse_path_is_the_dispatch_above_threshold(self):
        # at world 256 spectral_gap() IS the sparse path — pin the
        # dispatch and a planner-relevant ordering (exponential's
        # log-diameter cycle out-mixes the ring's)
        ring = spectral_gap(_schedule("ring", 256))
        expo = spectral_gap(_schedule("exponential", 256))
        assert ring == pytest.approx(_sparse_gap(_schedule("ring", 256)))
        assert 0.0 < ring < expo <= 1.0 + 1e-12


# -- fabric: modeled time in the planner's vocabulary ------------------------


class TestFabric:
    def test_payload_includes_push_sum_weight(self):
        from stochastic_gradient_push_tpu.telemetry.comm import (
            PS_WEIGHT_BYTES,
            encoded_payload_bytes,
        )

        tree = {"w": np.zeros((1, 16), np.float32)}
        assert payload_bytes_for(16) == (
            encoded_payload_bytes(tree, world=1) + PS_WEIGHT_BYTES)

    def test_dcn_crossings_carry_the_premium(self):
        sched = _schedule("ring", 64)
        pay = payload_bytes_for(16)
        uniform = FabricModel(sched, None, pay)
        sliced = FabricModel(
            sched, InterconnectModel(slice_size=32, dcn_cost=16.0), pay)
        assert sliced.cycle_time() > uniform.cycle_time()

    def test_dropped_edges_ship_nothing(self):
        # two slices: blacking one out removes EVERY cross-slice edge,
        # so the slowest surviving rank pays only the ICI hop
        sched = _schedule("ring", 64)
        camp = kill_slice_campaign(64, 32, at=0, duration=8)
        keep, _, _ = parse_fault_spec(camp.fault_spec).host_tables(sched)
        fm = FabricModel(
            sched, InterconnectModel(slice_size=32, dcn_cost=16.0),
            payload_bytes_for(16))
        assert fm.tick_time(0, keep_row=keep[0]) < fm.tick_time(0)

    def test_fused_intra_phase_prices_as_grouped_allreduce(self):
        sched = _schedule("hierarchical", 64)
        g = sched.slice_size
        fabric = InterconnectModel(slice_size=g, dcn_cost=16.0)
        pay = payload_bytes_for(16)
        fm = FabricModel(sched, fabric, pay)
        p = list(sched.phase_kinds).index("intra")
        want = PHASE_LATENCY_S + (pay * 2.0 * (g - 1) / g
                                  * fabric.ici_cost
                                  * SECONDS_PER_COST_BYTE)
        assert fm.tick_time(p) == pytest.approx(want)


# -- campaigns: the grammar they compile to ----------------------------------


class TestCampaigns:
    def test_kill_slice_compiles_and_validates(self):
        camp = kill_slice_campaign(1024, 128)
        assert camp.fault_spec.startswith("slice:896-1023@")
        assert camp.kill_hosts == (7,)
        parse_fault_spec(camp.fault_spec)   # grammar-valid
        with pytest.raises(ValueError):
            kill_slice_campaign(100, 32)    # not whole slices
        with pytest.raises(ValueError):
            kill_slice_campaign(64, 64)     # < 2 slices
        with pytest.raises(ValueError):
            kill_slice_campaign(64, 32, slice_idx=5)

    def test_cascade_staggers_inside_recovery_shadow(self):
        camp = cascading_slices_campaign(256, 32, count=3, at=100,
                                         stagger=50, duration=150)
        clauses = camp.fault_spec.split(";")
        assert len(clauses) == 3 and len(camp.kill_hosts) == 3
        starts = [int(c.split("@")[1].split(":")[0]) for c in clauses]
        ends = [int(c.split("@")[1].split(":")[1]) for c in clauses]
        # each loss lands while the previous one is still active
        assert all(s2 < e1 for s2, e1 in zip(starts[1:], ends))
        with pytest.raises(ValueError):
            cascading_slices_campaign(128, 32, count=4)

    def test_churn_and_coordinator_loss(self):
        camp = sustained_churn_campaign(prob=0.5, at=50, duration=1000,
                                        seed=3)
        assert "drop_random:0.5@50:1050" in camp.fault_spec
        assert "seed:3" in camp.fault_spec
        with pytest.raises(ValueError):
            sustained_churn_campaign(prob=1.5)
        loss = coordinator_loss_campaign(down_s=2.5)
        assert loss.fault_spec is None
        assert loss.coordinator_down_s == 2.5
        assert loss.kill_hosts == (-1,)     # fleet's last host
        assert "coordinator dark 2.5s" in loss.describe()


# -- curves: consensus against simulated wall-clock --------------------------


class TestCurves:
    def test_curve_shape_and_monotone_clock(self):
        sched = _schedule("exponential", 32)
        fabric = InterconnectModel(slice_size=16, dcn_cost=16.0)
        curve = consensus_curve(sched, 20, interconnect=fabric, seed=1)
        assert len(curve["time_s"]) == len(curve["error"]) == 20
        assert np.all(np.diff(curve["time_s"]) > 0)
        assert curve["cycle_time_s"] > 0
        assert curve["payload_bytes"] == payload_bytes_for(16)
        tte = time_to_error(curve, 1e-6)
        assert tte is not None
        first = int(np.argmax(np.asarray(curve["error"]) <= 1e-6))
        assert tte == curve["time_s"][first]
        assert time_to_error(curve, 0.0) is None

    def test_sweep_covers_the_grid(self):
        rows = sweep_curves(
            {"ring": lambda w: _schedule("ring", w),
             "exponential": lambda w: _schedule("exponential", w)},
            worlds=(16, 32), steps=12, seed=2)
        assert {(r["topology"], r["world"]) for r in rows} == {
            ("ring", 16), ("ring", 32),
            ("exponential", 16), ("exponential", 32)}
        for r in rows:
            assert r["final_error"] >= 0
            assert r["cycle_time_s"] > 0


# -- satellite: cross-world grow reshard -------------------------------------


def _world_state(n, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "params": {"w": rng.normal(size=(n, 8)).astype(np.float32)},
        "gossip": {
            # non-uniform push-sum weights: the consensus the reshard
            # must preserve is Σ params / Σ ps_weight, not a plain mean
            "ps_weight": rng.uniform(0.5, 1.5, n).astype(np.float32),
            "phase": np.zeros(n, np.int32)},
    }


def _write_rank_file(directory, tag, rank, world, state, rows):
    lo = rank * rows
    sliced = {
        "params": {"w": state["params"]["w"][lo:lo + rows]},
        "gossip": {
            "ps_weight": state["gossip"]["ps_weight"][lo:lo + rows],
            "phase": state["gossip"]["phase"][lo:lo + rows]},
    }
    path = os.path.join(directory,
                        f"{tag}checkpoint_r{rank}_n{world}.ckpt")
    with open(path, "wb") as f:
        f.write(flax.serialization.msgpack_serialize(
            {"state": sliced, "meta": {"epoch": 1, "itr": 0,
                                       "step": 7}}))
    return path


class TestReshardGrow:
    @pytest.mark.parametrize("old,new,rows", [
        (256, 320, 64),
        (1024, 1536, 128),
    ])
    def test_upward_reshard_preserves_consensus(self, tmp_path, old,
                                                new, rows):
        d = str(tmp_path)
        state = _world_state(old, seed=old)
        for r in range(old // rows):
            _write_rank_file(d, "", r, old, state, rows)
        m_old = consensus_mean(state)
        # every new-world host reshards its own disjoint shard
        # concurrently — same call the supervisor's fleet cycle makes
        for r in range(new // rows):
            rep = reshard_checkpoints(d, "", old, new, out_rank=r,
                                      out_rows=rows)
            assert rep.mean_drift <= 1e-6
        grown, meta, files = load_world_checkpoint(d, "", new)
        assert len(files) == new // rows
        m_new = consensus_mean(grown)
        for k in m_old:
            assert m_new[k].dtype == np.float64
            assert float(np.abs(m_old[k] - m_new[k]).max()) <= 1e-6
        ps = np.asarray(grown["gossip"]["ps_weight"])
        assert ps.shape == (new,) and np.all(ps == 1.0)
        assert np.all(np.asarray(grown["gossip"]["phase"]) == 0)

    def test_torn_grow_set_is_rejected(self, tmp_path):
        d = str(tmp_path)
        state = _world_state(256, seed=1)
        for r in range(4):
            _write_rank_file(d, "", r, 256, state, 64)
        # only 4 of the 5 world-320 shards land: rows don't cover the
        # world, and the loader must refuse the torn set
        for r in range(4):
            reshard_checkpoints(d, "", 256, 320, out_rank=r,
                                out_rows=64)
        with pytest.raises(TornCheckpointError):
            load_world_checkpoint(d, "", 320)


# -- fleet lane: grow-the-world induction ------------------------------------


class TestSimFleetGrow:
    def test_join_hello_grows_world_4_to_6(self, tmp_path):
        # 2-host world-4 fleet; a third simulated host says hello
        # mid-run and the REAL coordinator runs one grow cycle to a
        # 3-host world 6 (no replan: the assignment carries plan=None)
        rep = run_sim_fleet(str(tmp_path), {0: 2, 1: 2}, steps=40,
                            save_every=5, step_s=0.05, join_rows=2)
        assert rep.rc == 0
        assert rep.cycles == 1 and rep.gos == 1
        assert rep.prev_world == 4 and rep.world == 6
        assert rep.excluded == []
        assert rep.drift is not None and rep.drift <= 1e-6
        assert rep.ps_weight_reset is True
        assert rep.host_exit.get(2) == "complete"
        # exactly one coordinated cycle: nobody relaunched twice
        assert all(n <= 1 for n in rep.host_relaunches.values())


def _events(path):
    import json

    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


@pytest.mark.slow
def test_fleetcli_join_grows_world_4_to_6(tmp_path):
    """The subprocess version of grow-the-world: two real
    ``scripts/fleet.py`` host supervisors run hostsim children at
    world 4; a third launches with ``--join`` and no child; the
    in-process coordinator grows the fleet to world 6 in exactly one
    coordinated cycle and everyone trains to completion."""
    d = str(tmp_path)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    fleet_script = os.path.join(REPO, "scripts", "fleet.py")

    def host_cmd(h, join=False):
        sup = [sys.executable, fleet_script, "--host", str(h),
               "--fleet_dir", d, "--poll", "0.1",
               "--alive_interval", "0.5", "--drain_timeout", "30"]
        if join:
            sup.append("--join")
        return sup + [
            "--",
            sys.executable, "-m",
            "stochastic_gradient_push_tpu.supervise.hostsim",
            "--checkpoint_dir", d, "--trace_dir", host_dir(d, h),
            "--world_size", "4", "--num_processes", "2",
            "--process_id", str(min(h, 1)), "--rows", "2",
            "--rank_offset", str(h * 2), "--steps", "60",
            "--save_every", "5", "--step_s", "0.1"]

    sups = {h: subprocess.Popen(host_cmd(h), env=env) for h in (0, 1)}
    boundary = {}

    def on_cycle(assign):
        old, _, _ = load_world_checkpoint(d, "", 4)
        new, _, _ = load_world_checkpoint(d, "", 6)
        m_old, m_new = consensus_mean(old), consensus_mean(new)
        boundary["drift"] = max(
            float(np.abs(m_old[k] - m_new[k]).max()) for k in m_old)
        boundary["assign"] = assign
        boundary["ps"] = np.asarray(
            new["gossip"]["ps_weight"]).tolist()

    def chaos_join():
        deadline = time.time() + 60
        while time.time() < deadline:
            if all(os.path.isfile(os.path.join(
                    d, f"checkpoint_r{h}_n4.ckpt")) for h in (0, 1)):
                break
            time.sleep(0.2)
        sups[2] = subprocess.Popen(host_cmd(2, join=True), env=env)

    import threading

    joiner = threading.Thread(target=chaos_join, daemon=True)
    joiner.start()
    coord = Coordinator(
        d, {0: 2, 1: 2}, checkpoint_dir=d, tag="", gossip=False,
        deadline_s=5.0, host_timeout_s=10.0, hello_grace_s=60.0,
        ack_timeout_s=60.0, poll_interval_s=0.1, max_cycles=2,
        min_hosts=1, on_cycle=on_cycle)
    rc = coord.run()
    joiner.join(timeout=10)
    for p in sups.values():
        try:
            p.wait(timeout=60)
        except subprocess.TimeoutExpired:
            p.kill()
            raise

    assert rc == 0
    assert 2 in sups, "the joiner never launched"
    assert coord.world == 6 and coord.excluded == []
    assert boundary.get("drift") is not None, "no grow cycle ran"
    assert boundary["drift"] <= 1e-6
    assert all(w == 1.0 for w in boundary["ps"])
    assert boundary["assign"]["world"] == 6
    assert boundary["assign"]["prev_world"] == 4
    shards = boundary["assign"]["shards"]
    assert sorted((s["out_rank"], s["out_rows"])
                  for s in shards.values()) == [(0, 2), (1, 2), (2, 2)]

    coord_evs = _events(os.path.join(d, COORDINATOR_EVENTS_FILE))
    assigns = [e for e in coord_evs if e.get("kind") == "fleet"
               and e["data"].get("phase") == "assign"]
    gos = [e for e in coord_evs if e.get("kind") == "fleet"
           and e["data"].get("phase") == "go"]
    assert len(assigns) == 1 and len(gos) == 1
    # the joiner reported fleet-join and relaunched into world 6
    evs = _events(os.path.join(host_dir(d, 2), SUPERVISOR_EVENTS_FILE))
    assert any(e["data"].get("action") == "fleet-join"
               for e in evs if e.get("kind") == "supervisor")
    rel = [e for e in evs if e.get("kind") == "relaunch"]
    assert len(rel) == 1 and rel[0]["data"]["world"] == 6
    # the grown world trained through to the end, un-torn
    _, meta, files = load_world_checkpoint(d, "", 6)
    assert meta.get("step") == 60 and len(files) == 3
