"""Wall-clock-asynchronous AD-PSGD (train/async_bilat.py).

The executable counterpart of the reference's separate averaging process
(ad_psgd.py:120-133): averaging displacements are computed host-side
from step-k params and adopted at step k+δ with δ set by real timing.
"""

import numpy as np
import pytest

from stochastic_gradient_push_tpu.topology import (
    DynamicBipartiteExponentialGraph, build_pairing_schedule)
from stochastic_gradient_push_tpu.train.async_bilat import (
    AsyncBilateralAverager)


def _pairing(world=4):
    return build_pairing_schedule(
        DynamicBipartiteExponentialGraph(world, peers_per_itr=1))


def test_displacement_is_half_the_pair_gap():
    """One averaging round moves each rank halfway to its partner —
    the bilateral update x <- (x + x_partner)/2 (≙ ad_psgd.py:358-361),
    expressed as an additive displacement so intermediate SGD progress
    is never discarded."""
    import jax.numpy as jnp

    av = AsyncBilateralAverager(_pairing(4))
    params = {"w": jnp.asarray([[0.0], [2.0], [4.0], [6.0]])}
    av.start()
    try:
        av.publish(0, params)
        # wait for the thread's deposit
        for _ in range(500):
            new, adopted = av.maybe_adopt(3, params)
            if adopted:
                break
            import time
            time.sleep(0.01)
        assert adopted, "averaging thread never deposited"
    finally:
        av.stop()
    w = np.asarray(new["w"]).ravel()
    partner = av.pairing[0]
    expect = np.array([0.0, 2, 4, 6])
    expect = expect + (expect[partner] - expect) * 0.5
    np.testing.assert_allclose(w, expect)
    # the adoption was recorded with its true step gap
    s = av.staleness_summary()
    assert s["adoptions"] == 1 and s["staleness_max"] == 3


def test_mailbox_overwrites_not_queues():
    """Only the newest averaging result survives — like the reference's
    shared buffer, a slow consumer sees ONE (stale) displacement, not a
    backlog of superseded ones."""
    import time

    import jax.numpy as jnp

    av = AsyncBilateralAverager(_pairing(4))
    p1 = {"w": jnp.asarray([[0.0], [2.0], [4.0], [6.0]])}
    p2 = {"w": jnp.asarray([[10.0], [10.0], [10.0], [10.0]])}
    av.start()
    try:
        av.publish(0, p1)
        time.sleep(0.3)
        av.publish(1, p2)
        time.sleep(0.3)
        new, adopted = av.maybe_adopt(2, p2)
    finally:
        av.stop()
    assert adopted
    # consensus params -> zero displacement: proves the p2-round result
    # replaced the p1 one rather than queueing behind it
    np.testing.assert_allclose(np.asarray(new["w"]),
                               np.asarray(p2["w"]))


@pytest.mark.slow
def test_trainer_bilat_async_converges_replicas(tmp_path):
    """End-to-end through the Trainer: local-SGD compiled step + host
    averaging keeps replicas in consensus (spread far below a no-comm
    control) and records a staleness distribution."""
    import os

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from stochastic_gradient_push_tpu.algorithms.api import GossipAlgorithm
    from stochastic_gradient_push_tpu.data import (
        DistributedSampler, ShardedLoader, synthetic_classification)
    from stochastic_gradient_push_tpu.models import TinyCNN
    from stochastic_gradient_push_tpu.parallel import make_gossip_mesh
    from stochastic_gradient_push_tpu.train.loop import (
        Trainer, TrainerConfig)
    from stochastic_gradient_push_tpu.train.step import replica_spread

    world, batch, classes, img = 8, 4, 8, 12
    images, labels = synthetic_classification(
        world * batch * 6, num_classes=classes, image_size=img, seed=3)

    def run(bilat_async):
        cfg = TrainerConfig(
            push_sum=False, bilat=True, bilat_async=bilat_async,
            graph_class=DynamicBipartiteExponentialGraph,
            lr=0.1, warmup=False, lr_schedule={},
            batch_size=batch, num_epochs=3, num_itr_ignore=0,
            checkpoint_dir=str(tmp_path / f"async_{bilat_async}"),
            num_classes=classes, verbose=False, heartbeat_timeout=0,
            train_fast=True)
        if not bilat_async:
            # no-comm control: same config but bilateral averaging OFF
            cfg.bilat = False
            cfg.all_reduce = False
            cfg.push_sum = False
            cfg.graph_class = None

            class _Local(Trainer):
                def make_algorithm(self, ppi):
                    return GossipAlgorithm()
            trainer_cls = _Local
        else:
            trainer_cls = Trainer
        mesh = make_gossip_mesh(world)
        trainer = trainer_cls(cfg, TinyCNN(num_classes=classes), mesh,
                              sample_input_shape=(batch, img, img, 3))
        state = trainer.init_state()
        sampler = DistributedSampler(len(images), world)
        loader = ShardedLoader(images, labels, batch, sampler)
        state, result = trainer.fit(state, loader, sampler, None)
        spread = replica_spread(state, GossipAlgorithm())
        return spread["mean_spread"], result

    spread_async, result = run(True)
    spread_local, _ = run(False)

    stats = result["async_bilat"]
    assert stats["adoptions"] > 0, stats
    assert stats["staleness_mean"] >= 0.0
    # host averaging must hold replicas together vs the no-comm control
    assert spread_async < spread_local * 0.5, (spread_async, spread_local)
