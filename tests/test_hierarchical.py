"""Hierarchical (node, local) mesh: exact local averaging + node gossip
(≙ nprocs_per_node, distributed.py:62-78, 278-296, 551-562)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stochastic_gradient_push_tpu.algorithms import sgp
from stochastic_gradient_push_tpu.models import TinyMLP
from stochastic_gradient_push_tpu.parallel import (
    LOCAL_AXIS,
    NODE_AXIS,
    make_hierarchical_mesh,
)
from stochastic_gradient_push_tpu.topology import (
    DynamicDirectedExponentialGraph,
    build_schedule,
)
from stochastic_gradient_push_tpu.train import (
    LRSchedule,
    build_train_step,
    init_train_state,
    replicate_state,
    sgd,
    shard_train_step,
)

NODES, LOCAL = 4, 2
BATCH, IMG, CLASSES = 4, 8, 4


def test_hierarchical_mesh_training_step():
    mesh = make_hierarchical_mesh(LOCAL, NODES * LOCAL)
    assert mesh.shape == {NODE_AXIS: NODES, LOCAL_AXIS: LOCAL}

    model = TinyMLP(num_classes=CLASSES)
    sched = build_schedule(
        DynamicDirectedExponentialGraph(NODES, peers_per_itr=1))
    alg = sgp(sched, NODE_AXIS)
    tx = sgd(momentum=0.9, weight_decay=1e-4)
    lrs = LRSchedule(ref_lr=0.1, batch_size=BATCH, world_size=NODES * LOCAL)
    step = build_train_step(model, alg, tx, lrs, itr_per_epoch=10,
                            num_classes=CLASSES, local_axis=LOCAL_AXIS)
    train_fn = shard_train_step(step, mesh, NODE_AXIS, LOCAL_AXIS)

    state = replicate_state(
        init_train_state(model, jax.random.PRNGKey(0),
                         jnp.zeros((BATCH, IMG, IMG, 3)), tx, alg), NODES)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(NODES * LOCAL, BATCH, IMG, IMG, 3)).astype(np.float32)
    y = rng.integers(0, CLASSES, size=(NODES * LOCAL, BATCH)).astype(np.int32)

    losses = []
    for i in range(30):
        state, metrics = train_fn(state, x, y)
        jax.block_until_ready(state)
        losses.append(float(np.mean(np.asarray(metrics["loss"]))))

    # training works and state stays node-stacked
    assert losses[-1] < losses[0]
    assert np.asarray(state.step).shape == (NODES,)
    w = np.asarray(state.gossip.ps_weight)
    np.testing.assert_allclose(w, np.ones_like(w), atol=1e-4)


@pytest.mark.skipif(
    jax.__version_info__ < (0, 5),
    reason="collectives inside the differentiated forward (local-axis BN "
           "sync) transpose differently under the vma-less shard_map "
           "compat shim (check_rep=False) in jax<0.5, so hierarchical and "
           "flat grads legitimately disagree there")
def test_hierarchical_local_grads_match_wider_batch():
    """One hierarchical step (2 local devices x batch B) must equal a flat
    gossip step with per-rank batch 2B: exact local averaging is just a
    bigger effective batch."""
    from stochastic_gradient_push_tpu.parallel import (
        GOSSIP_AXIS, make_gossip_mesh)

    model = TinyMLP(num_classes=CLASSES)
    tx = sgd(momentum=0.9, weight_decay=1e-4)
    lrs = LRSchedule(ref_lr=0.1, batch_size=BATCH, world_size=NODES * LOCAL)
    sched = build_schedule(
        DynamicDirectedExponentialGraph(NODES, peers_per_itr=1))

    rng = np.random.default_rng(1)
    x = rng.normal(size=(NODES * LOCAL, BATCH, IMG, IMG, 3)).astype(np.float32)
    y = rng.integers(0, CLASSES, size=(NODES * LOCAL, BATCH)).astype(np.int32)

    # hierarchical: (4 nodes, 2 local)
    mesh_h = make_hierarchical_mesh(LOCAL, NODES * LOCAL)
    alg_h = sgp(sched, NODE_AXIS)
    step_h = build_train_step(model, alg_h, tx, lrs, itr_per_epoch=10,
                              num_classes=CLASSES, local_axis=LOCAL_AXIS)
    fn_h = shard_train_step(step_h, mesh_h, NODE_AXIS, LOCAL_AXIS)
    st_h = replicate_state(
        init_train_state(model, jax.random.PRNGKey(0),
                         jnp.zeros((BATCH, IMG, IMG, 3)), tx, alg_h), NODES)
    st_h, _ = fn_h(st_h, x, y)

    # flat: 4 ranks with the concatenated local batches
    mesh_f = make_gossip_mesh(NODES)
    alg_f = sgp(sched, GOSSIP_AXIS)
    step_f = build_train_step(model, alg_f, tx, lrs, itr_per_epoch=10,
                              num_classes=CLASSES)
    fn_f = shard_train_step(step_f, mesh_f, GOSSIP_AXIS)
    st_f = replicate_state(
        init_train_state(model, jax.random.PRNGKey(0),
                         jnp.zeros((BATCH * LOCAL, IMG, IMG, 3)), tx, alg_f),
        NODES)
    xf = x.reshape(NODES, LOCAL * BATCH, IMG, IMG, 3)
    yf = y.reshape(NODES, LOCAL * BATCH)
    st_f, _ = fn_f(st_f, xf, yf)

    for a, b in zip(jax.tree.leaves(st_h.params),
                    jax.tree.leaves(st_f.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_hierarchical_requires_matching_local_axis():
    from stochastic_gradient_push_tpu.train.loop import Trainer, TrainerConfig

    mesh = make_hierarchical_mesh(LOCAL, NODES * LOCAL)
    cfg = TrainerConfig(nprocs_per_node=4)  # wrong: mesh local axis is 2
    with pytest.raises(ValueError, match="hierarchical mesh"):
        Trainer(cfg, TinyMLP(num_classes=4), mesh, (4, 8, 8, 3))
