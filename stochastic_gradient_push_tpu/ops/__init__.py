"""Pallas TPU kernels for hot ops."""

from .flash_attention import (
    flash_attention,
    flash_attention_backward,
    flash_attention_forward,
)

__all__ = ["flash_attention", "flash_attention_forward",
           "flash_attention_backward"]
