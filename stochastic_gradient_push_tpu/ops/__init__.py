"""Pallas TPU kernels for hot ops."""

from .flash_attention import (
    flash_attention,
    flash_attention_backward,
    flash_attention_forward,
)
from .gossip_kernel import (
    GOSSIP_KERNELS,
    KernelBackendError,
    KernelLane,
    TransportHandle,
    empty_transport_handle,
    gossip_edge_axpy,
    gossip_edge_start,
    gossip_edge_wait,
    resolve_gossip_kernel,
    resolve_use_pallas,
)

__all__ = ["flash_attention", "flash_attention_forward",
           "flash_attention_backward", "GOSSIP_KERNELS",
           "KernelBackendError", "KernelLane", "TransportHandle",
           "empty_transport_handle", "gossip_edge_axpy",
           "gossip_edge_start", "gossip_edge_wait",
           "resolve_gossip_kernel", "resolve_use_pallas"]
