"""Pallas TPU flash-attention kernels: fused forward AND backward.

The transformer path's compute hot spot.  All three kernels share one
schedule shape: a 3-D grid whose two major dimensions are parallel
(batch·head and the output block) and whose MINOR dimension walks the
streamed axis with ``arbitrary`` semantics — so Pallas double-buffers the
streamed k/v (or q/do) block fetches behind the matmuls instead of
parking whole ``[seq, d]`` operands in VMEM per cell (the round-3 design,
whose dk/dv kernel lost to XLA 122.8 ms vs 68.6 ms at t=4096 —
docs/FLASH_TPU_RESULTS.txt).  Running state lives in fp32 VMEM scratch
that persists across the minor grid steps: the forward carries the
online-softmax ``(m, den, acc)`` triple, the backward kernels carry their
gradient accumulators, and outputs are written once on the last minor
step.  VMEM per cell is O(block²), independent of sequence length.

Backward (``jax.custom_vjp``) is the standard flash-attention-2
decomposition:

* dQ kernel, grid ``(bh, q-block, k-step)``: streams k/v, recomputes
  ``p = exp(s - lse)``, accumulates ``dq += ds @ k``.
* dK/dV kernel, grid ``(bh, k-block, q-step)``: streams q/do, accumulates
  ``dv += pᵀ @ do`` and ``dk += dsᵀ @ q``.

The per-row residuals travel in compact ``[rows, 1]`` layouts: the
forward's logsumexp and ``delta = rowsum(do · o)``, the latter computed
once outside the kernels (a fused XLA elementwise-reduce) so ``o`` is not
an operand of either backward kernel.  Causal runs skip the empty
triangle two ways: masked minor steps are compute-gated with ``pl.when``,
and their index maps clamp into the visible range so no new block is ever
fetched for a skipped step.

On non-TPU backends ``flash_attention`` transparently falls back to the
pure-JAX blockwise implementation
(parallel/ring_attention.py::blockwise_attention); Pallas interpret mode
exercises all three kernels in tests against that same oracle.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..parallel.ring_attention import blockwise_attention

__all__ = ["flash_attention", "flash_attention_forward",
           "flash_attention_backward"]

NEG_INF = -1e30

# Mosaic requires the last two block dims be (8·k, 128·k) or full-size.
# Per-row scalars (logsumexp, delta) ride as a [rows, 1] column — the last
# dim is the ARRAY's full size (1), which Mosaic accepts, so each residual
# costs t floats instead of the 128·t a lane-broadcast layout would.
SCALAR_COLS = 1

# fp32 running-state scratch keeps a full [rows, 128] lane so stores hit
# the native register layout; only column 0 is meaningful
_STATE_LANES = 128


def default_block(t: int) -> int:
    """Measured auto block size (TPU v5e): the LARGEST block that tiles
    the sequence wins at every measured length.  Step-level A/B on the
    full d768/L12 LM train step (scanned+fenced, the only timing that is
    trustworthy over the tunneled dev chip —
    docs/tpu_runs/20260731T072937_lmblock): at t=1024 block 512 runs the
    step at 64.0 ms vs 82.7 (block 256) vs 127.5 (block 128) — 2.0x —
    and block 512 also wins the kernel-level fenced sweeps at t=2048 and
    t=4096 (docs/tpu_runs/20260731T071733_retry/flashblocks.txt).  An
    earlier round's "128 best at t<=1024" rule came from UNFENCED
    micro-benchmarks that measured RPC-ack latency, not compute.
    The 3-D-grid schedule keeps VMEM at O(block^2), so 512 is safe."""
    for b in (512, 256, 128):
        if t % b == 0:
            return b
    return min(128, t)


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying the varying-mesh-axes type of ``like``
    — required for pallas_call outputs inside shard_map (check_vma), and
    the reason ``--attn flash`` can now compile in the sharded LM step."""
    vma = getattr(jax.typeof(like), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _compiler_params(interpret: bool):
    """Minor grid dim walks the streamed axis: revisited outputs/scratch
    require ``arbitrary``; the two major dims are parallel."""
    if interpret:
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))


def _causal_mask(s, qi, kj, block_q: int, block_k: int):
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 0)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 1)
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *rest, block_q: int,
                      block_k: int, causal: bool, return_lse: bool):
    """One (batch·head, q-block, k-step) cell.  Refs: q/o [block_q, d];
    k/v [block_k, d] (streamed); lse (when requested)
    [block_q, SCALAR_COLS]; scratch m/den [block_q, 128] and
    acc [block_q, d], all fp32, persistent across k-steps."""
    if return_lse:
        lse_ref, m_ref, den_ref, acc_ref = rest
    else:
        m_ref, den_ref, acc_ref = rest
    qi, kj = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        den_ref[:] = jnp.zeros_like(den_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    visible = (qi * block_q + block_q - 1 >= kj * block_k) if causal \
        else (kj >= 0)

    @pl.when(visible)
    def _compute():
        d = q_ref.shape[-1]
        q = q_ref[:].astype(jnp.float32) * (d ** -0.5)
        k = k_ref[:].astype(jnp.float32)
        v = v_ref[:].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k)
        m_prev = m_ref[:, :1]                              # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)                    # [bq, 1]
        den_new = den_ref[:, :1] * alpha + jnp.sum(p, -1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        den_ref[:] = jnp.broadcast_to(den_new, den_ref.shape)

    @pl.when(kj == nk - 1)
    def _finalize():
        den = den_ref[:, :1]
        o_ref[:] = (acc_ref[:] / den).astype(o_ref.dtype)
        if return_lse:
            lse_ref[:] = m_ref[:, :1] + jnp.log(den)


def flash_attention_forward(q, k, v, causal: bool = False,
                            block_q: int = 128, block_k: int = 128,
                            interpret: bool = False,
                            return_lse: bool = False):
    """Pallas forward.  q/k/v: ``[batch, heads, seq, head_dim]``.

    With ``return_lse`` also returns the row logsumexp ``[b, h, seq]``
    (float32), the residual the fused backward kernels consume.
    """
    b, h, t, d = q.shape
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q or t % block_k:
        raise ValueError(f"block sizes ({block_q}, {block_k}) must divide "
                         f"seq {t}")

    qf = q.reshape(b * h, t, d)
    kf = k.reshape(b * h, t, d)
    vf = v.reshape(b * h, t, d)

    def kv_map(bh, qi, kj):
        if causal:
            # masked steps re-point at the last visible block: same index
            # as the previous step ⇒ Pallas skips the fetch entirely
            kj = jnp.minimum(kj, (qi * block_q + block_q - 1) // block_k)
        return (bh, kj, 0)

    kernel = functools.partial(
        _flash_fwd_kernel, block_q=block_q, block_k=block_k,
        causal=causal, return_lse=return_lse)
    out_specs = [
        pl.BlockSpec((None, block_q, d), lambda bh, qi, kj: (bh, qi, 0)),
    ]
    out_shape = [_sds((b * h, t, d), q.dtype, qf)]
    if return_lse:
        out_specs.append(pl.BlockSpec((None, block_q, SCALAR_COLS),
                                      lambda bh, qi, kj: (bh, qi, 0)))
        out_shape.append(_sds((b * h, t, SCALAR_COLS), jnp.float32,
                              qf))
    results = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q, t // block_k),
        in_specs=[
            pl.BlockSpec((None, block_q, d),
                         lambda bh, qi, kj: (bh, qi, 0)),
            pl.BlockSpec((None, block_k, d), kv_map),
            pl.BlockSpec((None, block_k, d), kv_map),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, _STATE_LANES), jnp.float32),
            pltpu.VMEM((block_q, _STATE_LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(qf, kf, vf)
    if return_lse:
        out, lse = results
        return out.reshape(b, h, t, d), lse[..., 0].reshape(b, h, t)
    out, = results
    return out.reshape(b, h, t, d)


def _flash_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dq_ref, acc_ref, *, block_q: int, block_k: int,
                     causal: bool):
    """dQ cell (bh, q-block, k-step).  Refs: q/do/dq [block_q, d];
    k/v [block_k, d] (streamed); lse/delta [block_q, SCALAR_COLS];
    scratch acc [block_q, d] fp32."""
    qi, kj = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    visible = (qi * block_q + block_q - 1 >= kj * block_k) if causal \
        else (kj >= 0)

    @pl.when(visible)
    def _compute():
        d = q_ref.shape[-1]
        scale = d ** -0.5
        q = q_ref[:].astype(jnp.float32) * scale
        k = k_ref[:].astype(jnp.float32)
        v = v_ref[:].astype(jnp.float32)
        do = do_ref[:].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k)
        p = jnp.exp(s - lse_ref[:])                        # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        ds = p * (dp - delta_ref[:])
        acc_ref[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[:] = acc_ref[:].astype(dq_ref.dtype)


def _flash_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                      dk_ref, dv_ref, dk_acc, dv_acc, *, block_q: int,
                      block_k: int, causal: bool):
    """dK/dV cell (bh, k-block, q-step).  Refs: k/v/dk/dv [block_k, d];
    q/do [block_q, d] (streamed); lse/delta [block_q, SCALAR_COLS];
    scratch dk/dv accumulators [block_k, d] fp32."""
    kj, qi = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    visible = (qi * block_q + block_q - 1 >= kj * block_k) if causal \
        else (qi >= 0)

    @pl.when(visible)
    def _compute():
        d = k_ref.shape[-1]
        scale = d ** -0.5
        k = k_ref[:].astype(jnp.float32)
        v = v_ref[:].astype(jnp.float32)
        q = q_ref[:].astype(jnp.float32) * scale
        do = do_ref[:].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k)
        p = jnp.exp(s - lse_ref[:])                        # [bq, bk]
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        ds = p * (dp - delta_ref[:])
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bk, d]

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[:] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_acc[:].astype(dv_ref.dtype)


def flash_attention_backward(q, k, v, out, lse, do, causal: bool = False,
                             block_q: int = 128, block_k: int = 128,
                             interpret: bool = False):
    """Fused Pallas backward: returns ``(dq, dk, dv)``.

    ``lse`` is the forward's row logsumexp ``[b, h, seq]``; it and
    ``delta = rowsum(do · out)`` (computed here, once, as a fused XLA
    reduce) ship in the compact ``[rows, 1]`` layout, so no
    lane-broadcast scalar array ever exists in HBM and ``out`` is not an
    operand of either kernel.
    """
    b, h, t, d = q.shape
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q or t % block_k:
        raise ValueError(f"block sizes ({block_q}, {block_k}) must divide "
                         f"seq {t}")

    qf = q.reshape(b * h, t, d)
    kf = k.reshape(b * h, t, d)
    vf = v.reshape(b * h, t, d)
    dof = do.reshape(b * h, t, d)
    lsef = lse.reshape(b * h, t)[..., None]  # [b*h, t, SCALAR_COLS]
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(b * h, t)[..., None]

    def kv_map(bh, qi, kj):
        if causal:
            kj = jnp.minimum(kj, (qi * block_q + block_q - 1) // block_k)
        return (bh, kj, 0)

    q_row = pl.BlockSpec((None, block_q, d),
                         lambda bh, qi, kj: (bh, qi, 0))
    s_row = pl.BlockSpec((None, block_q, SCALAR_COLS),
                         lambda bh, qi, kj: (bh, qi, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, block_q=block_q,
                          block_k=block_k, causal=causal),
        grid=(b * h, t // block_q, t // block_k),
        in_specs=[
            q_row,                                          # q
            pl.BlockSpec((None, block_k, d), kv_map),       # k
            pl.BlockSpec((None, block_k, d), kv_map),       # v
            q_row,                                          # do
            s_row,                                          # lse
            s_row,                                          # delta
        ],
        out_specs=q_row,
        out_shape=_sds((b * h, t, d), q.dtype, qf),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, delta)

    def q_map(bh, kj, qi):
        if causal:
            # the first visible q-step for this k-block; earlier (masked)
            # steps alias it so no block is fetched for them
            qi = jnp.maximum(qi, (kj * block_k) // block_q)
        return (bh, qi, 0)

    k_col = pl.BlockSpec((None, block_k, d),
                         lambda bh, kj, qi: (bh, kj, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, block_q=block_q,
                          block_k=block_k, causal=causal),
        grid=(b * h, t // block_k, t // block_q),
        in_specs=[
            k_col,                                          # k
            k_col,                                          # v
            pl.BlockSpec((None, block_q, d), q_map),        # q
            pl.BlockSpec((None, block_q, d), q_map),        # do
            pl.BlockSpec((None, block_q, SCALAR_COLS), q_map),   # lse
            pl.BlockSpec((None, block_q, SCALAR_COLS), q_map),   # delta
        ],
        out_specs=[k_col, k_col],
        out_shape=[
            _sds((b * h, t, d), k.dtype, kf),
            _sds((b * h, t, d), v.dtype, vf),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(kf, vf, qf, dof, lsef, delta)
    return (dq.reshape(b, h, t, d), dk.reshape(b, h, t, d),
            dv.reshape(b, h, t, d))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, block_q, block_k):
    return flash_attention_forward(q, k, v, causal=causal,
                                   block_q=block_q, block_k=block_k)


def _flash_fwd(q, k, v, causal, block_q, block_k):
    out, lse = flash_attention_forward(q, k, v, causal=causal,
                                       block_q=block_q, block_k=block_k,
                                       return_lse=True)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, residuals, g):
    q, k, v, out, lse = residuals
    return flash_attention_backward(q, k, v, out, lse, g, causal=causal,
                                    block_q=block_q, block_k=block_k)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    block_q: int | None = None,
                    block_k: int | None = None):
    """Differentiable flash attention; Pallas on TPU, pure-JAX blockwise
    elsewhere.  ``block_q``/``block_k`` default to the measured
    :func:`default_block` rule for the sequence length."""
    t = q.shape[2]
    block_q = default_block(t) if block_q is None else block_q
    block_k = default_block(t) if block_k is None else block_k
    if jax.default_backend() != "tpu":
        return blockwise_attention(q, k, v, min(block_k, t),
                                   causal=causal)
    return _flash(q, k, v, causal, block_q, block_k)
