"""Pallas TPU flash-attention kernels: fused forward AND backward.

The transformer path's compute hot spot.  Forward: one grid cell per
(batch·head, q-block): the q block stays resident in VMEM while k/v blocks
stream through, accumulating with the online-softmax recurrence — O(block²)
VMEM instead of O(seq²) HBM, and causal upper-triangle blocks are skipped
entirely (≈2× fewer FLOPs at long sequence).  The forward also emits the
per-row logsumexp so the backward can recompute attention probabilities
without a second softmax reduction.

Backward (``jax.custom_vjp``): two fused kernels in the standard
flash-attention-2 decomposition —

* dQ kernel, grid over (batch·head, q-block): streams k/v blocks,
  recomputes ``p = exp(s - lse)``, accumulates ``dq += ds @ k``.
* dK/dV kernel, grid over (batch·head, k-block): streams q/do blocks,
  accumulates ``dv += pᵀ @ do`` and ``dk += dsᵀ @ q``.

Both use ``delta = rowsum(do · o)`` in place of materializing dP; it is
computed *inside* the kernels from the streamed ``o``/``do`` blocks (an
elementwise multiply-reduce, negligible next to the matmuls), so no delta
array ever exists in HBM.  The logsumexp residual travels in a compact
``[rows, 1]`` layout — a round-2 revision materialized lse and delta as
lane-broadcast ``[rows, 128]`` fp32 HBM operands (128× their logical
size; 2 MB of VMEM each per grid cell at t=4096, the likely cause of the
recorded dk/dv slowdown at long sequence — docs/FLASH_TPU_RESULTS.txt).
Causal runs skip the empty triangle blocks in both kernels.

On non-TPU backends ``flash_attention`` transparently falls back to the
pure-JAX blockwise implementation
(parallel/ring_attention.py::blockwise_attention); Pallas interpret mode
exercises both kernels in tests against that same oracle.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..parallel.ring_attention import blockwise_attention

__all__ = ["flash_attention", "flash_attention_forward",
           "flash_attention_backward"]

NEG_INF = -1e30

# Mosaic requires the last two block dims be (8·k, 128·k) or full-size.
# Per-row scalars (the logsumexp) ride as a [rows, 1] column — the last
# dim is the ARRAY's full size (1), which Mosaic accepts, so the residual
# costs t floats instead of the 128·t a lane-broadcast layout would.
SCALAR_COLS = 1


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *maybe_lse, block_q: int,
                  block_k: int, seq_len: int, causal: bool):
    """One (batch·head, q-block) cell.  Refs: q [block_q, d];
    k/v [seq, d]; o [block_q, d]; lse (when requested)
    [block_q, SCALAR_COLS]."""
    qi = pl.program_id(1)
    d = q_ref.shape[-1]
    q = q_ref[:].astype(jnp.float32) * (d ** -0.5)

    m = jnp.full((block_q,), NEG_INF, jnp.float32)
    den = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, d), jnp.float32)

    num_k_blocks = seq_len // block_k

    def body(kj, carry):
        m, den, acc = carry
        k_blk = k_ref[pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bq, bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        den = den * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, den, acc

    if causal:
        # skip blocks strictly above the diagonal
        last_block = qi * block_q // block_k + \
            (block_q + block_k - 1) // block_k
        upper = jnp.minimum(num_k_blocks, last_block)
    else:
        upper = num_k_blocks
    m, den, acc = jax.lax.fori_loop(0, upper, body, (m, den, acc))
    o_ref[:] = (acc / den[:, None]).astype(o_ref.dtype)
    if maybe_lse:
        # per-row logsumexp of the scaled scores — the backward's residual
        lse_ref, = maybe_lse
        lse_ref[:] = (m + jnp.log(den))[:, None]


def flash_attention_forward(q, k, v, causal: bool = False,
                            block_q: int = 128, block_k: int = 128,
                            interpret: bool = False,
                            return_lse: bool = False):
    """Pallas forward.  q/k/v: ``[batch, heads, seq, head_dim]``.

    With ``return_lse`` also returns the row logsumexp ``[b, h, seq]``
    (float32), the residual the fused backward kernels consume.
    """
    b, h, t, d = q.shape
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q or t % block_k:
        raise ValueError(f"block sizes ({block_q}, {block_k}) must divide "
                         f"seq {t}")

    qf = q.reshape(b * h, t, d)
    kf = k.reshape(b * h, t, d)
    vf = v.reshape(b * h, t, d)

    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, seq_len=t,
        causal=causal)
    out_specs = [
        pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
    ]
    out_shape = [jax.ShapeDtypeStruct((b * h, t, d), q.dtype)]
    if return_lse:
        out_specs.append(pl.BlockSpec((None, block_q, SCALAR_COLS),
                                      lambda bh, qi: (bh, qi, 0)))
        out_shape.append(jax.ShapeDtypeStruct((b * h, t, SCALAR_COLS),
                                              jnp.float32))
    results = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, t, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, t, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(qf, kf, vf)
    if return_lse:
        out, lse = results
        return out.reshape(b, h, t, d), lse[..., 0].reshape(b, h, t)
    out, = results
    return out.reshape(b, h, t, d)


def _flash_dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                     dq_ref, *, block_q: int, block_k: int, seq_len: int,
                     causal: bool):
    """dQ cell: one (batch·head, q-block); k/v stream through.
    Refs: q/o/do/dq [block_q, d]; k/v [seq, d]; lse
    [block_q, SCALAR_COLS].  ``delta = rowsum(do · o)`` is computed here
    rather than shipped as an operand."""
    qi = pl.program_id(1)
    d = q_ref.shape[-1]
    scale = d ** -0.5
    q = q_ref[:].astype(jnp.float32) * scale
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:][:, 0]
    delta = jnp.sum(do * o_ref[:].astype(jnp.float32), axis=-1)

    num_k_blocks = seq_len // block_k
    dq = jnp.zeros((block_q, d), jnp.float32)

    def body(kj, dq):
        k_blk = k_ref[pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                  # [bq, bk]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        ds = p * (dp - delta[:, None])
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    if causal:
        last_block = qi * block_q // block_k + \
            (block_q + block_k - 1) // block_k
        upper = jnp.minimum(num_k_blocks, last_block)
    else:
        upper = num_k_blocks
    dq = jax.lax.fori_loop(0, upper, body, dq)
    dq_ref[:] = (dq * scale).astype(dq_ref.dtype)


def _flash_dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                      dk_ref, dv_ref, *, block_q: int, block_k: int,
                      seq_len: int, causal: bool):
    """dK/dV cell: one (batch·head, k-block); q/o/do stream through.
    Refs: k/v/dk/dv [block_k, d]; q/o/do [seq, d]; lse
    [seq, SCALAR_COLS].  delta is recomputed per streamed q-block from
    ``do · o`` — an elementwise reduce per (k-block, q-block) pair,
    negligible next to the four matmuls in the same body."""
    kj = pl.program_id(1)
    d = k_ref.shape[-1]
    scale = d ** -0.5
    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)

    num_q_blocks = seq_len // block_q
    dk = jnp.zeros((block_k, d), jnp.float32)
    dv = jnp.zeros((block_k, d), jnp.float32)

    def body(qi, carry):
        dk, dv = carry
        q_blk = q_ref[pl.ds(qi * block_q, block_q), :].astype(
            jnp.float32) * scale
        do_blk = do_ref[pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse_blk = lse_ref[pl.ds(qi * block_q, block_q), :][:, 0]
        delta_blk = jnp.sum(
            do_blk * o_ref[pl.ds(qi * block_q, block_q), :].astype(
                jnp.float32), axis=-1)
        s = jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse_blk[:, None])              # [bq, bk]
        dv = dv + jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, d]
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        ds = p * (dp - delta_blk[:, None])
        dk = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, d]
        return dk, dv

    if causal:
        # the first q block whose rows can see this k block
        lower = (kj * block_k) // block_q
    else:
        lower = 0
    dk, dv = jax.lax.fori_loop(lower, num_q_blocks, body, (dk, dv))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def flash_attention_backward(q, k, v, out, lse, do, causal: bool = False,
                             block_q: int = 128, block_k: int = 128,
                             interpret: bool = False):
    """Fused Pallas backward: returns ``(dq, dk, dv)``.

    ``lse`` is the forward's row logsumexp ``[b, h, seq]``, shipped in the
    compact ``[rows, 1]`` layout; ``delta = rowsum(do · out)`` is computed
    inside the kernels from the streamed ``out``/``do`` blocks, so neither
    scalar family ever exists as a lane-broadcast HBM array.
    """
    b, h, t, d = q.shape
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q or t % block_k:
        raise ValueError(f"block sizes ({block_q}, {block_k}) must divide "
                         f"seq {t}")

    qf = q.reshape(b * h, t, d)
    kf = k.reshape(b * h, t, d)
    vf = v.reshape(b * h, t, d)
    of = out.reshape(b * h, t, d)
    dof = do.reshape(b * h, t, d)
    lsef = lse.reshape(b * h, t)[..., None]  # [b*h, t, SCALAR_COLS]

    row_specs = [
        pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),  # q
        pl.BlockSpec((None, t, d), lambda bh, qi: (bh, 0, 0)),         # k
        pl.BlockSpec((None, t, d), lambda bh, qi: (bh, 0, 0)),         # v
        pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),  # o
        pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),  # do
        pl.BlockSpec((None, block_q, SCALAR_COLS),
                     lambda bh, qi: (bh, qi, 0)),                      # lse
    ]
    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, block_q=block_q,
                          block_k=block_k, seq_len=t, causal=causal),
        grid=(b * h, t // block_q),
        in_specs=row_specs,
        out_specs=pl.BlockSpec((None, block_q, d),
                               lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, of, dof, lsef)

    col_specs = [
        pl.BlockSpec((None, t, d), lambda bh, kj: (bh, 0, 0)),         # q
        pl.BlockSpec((None, block_k, d), lambda bh, kj: (bh, kj, 0)),  # k
        pl.BlockSpec((None, block_k, d), lambda bh, kj: (bh, kj, 0)),  # v
        pl.BlockSpec((None, t, d), lambda bh, kj: (bh, 0, 0)),         # o
        pl.BlockSpec((None, t, d), lambda bh, kj: (bh, 0, 0)),         # do
        pl.BlockSpec((None, t, SCALAR_COLS),
                     lambda bh, kj: (bh, 0, 0)),                       # lse
    ]
    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, block_q=block_q,
                          block_k=block_k, seq_len=t, causal=causal),
        grid=(b * h, t // block_k),
        in_specs=col_specs,
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda bh, kj: (bh, kj, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, kj: (bh, kj, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, t, d), v.dtype),
        ],
        interpret=interpret,
    )(qf, kf, vf, of, dof, lsef)
    return (dq.reshape(b, h, t, d), dk.reshape(b, h, t, d),
            dv.reshape(b, h, t, d))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, block_q, block_k):
    return flash_attention_forward(q, k, v, causal=causal,
                                   block_q=block_q, block_k=block_k)


def _flash_fwd(q, k, v, causal, block_q, block_k):
    out, lse = flash_attention_forward(q, k, v, causal=causal,
                                       block_q=block_q, block_k=block_k,
                                       return_lse=True)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, residuals, g):
    q, k, v, out, lse = residuals
    return flash_attention_backward(q, k, v, out, lse, g, causal=causal,
                                    block_q=block_q, block_k=block_k)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False, block_q: int = 128,
                    block_k: int = 128):
    """Differentiable flash attention; Pallas on TPU, pure-JAX blockwise
    elsewhere."""
    if jax.default_backend() != "tpu":
        return blockwise_attention(q, k, v, min(block_k, q.shape[2]),
                                   causal=causal)
    return _flash(q, k, v, causal, block_q, block_k)
