"""Pallas TPU flash-attention forward kernel.

The transformer path's compute hot spot.  One grid cell per
(batch·head, q-block): the q block stays resident in VMEM while k/v blocks
stream through, accumulating with the online-softmax recurrence — O(block²)
VMEM instead of O(seq²) HBM, and causal upper-triangle blocks are skipped
entirely (≈2× fewer FLOPs at long sequence).

Differentiability: wrapped in ``jax.custom_vjp`` whose backward pass
replays the pure-JAX blockwise implementation
(parallel/ring_attention.py::blockwise_attention) under ``jax.vjp`` — the
forward gets the kernel, the backward gets XLA's fused recompute, and both
share one numerical reference that the tests pin down.

On non-TPU backends ``flash_attention`` transparently falls back to the
pure-JAX blockwise implementation (Pallas interpret mode exercises the
kernel in tests).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..parallel.ring_attention import blockwise_attention

__all__ = ["flash_attention", "flash_attention_forward"]

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q: int,
                  block_k: int, seq_len: int, causal: bool):
    """One (batch·head, q-block) cell.  Refs: q [block_q, d];
    k/v [seq, d]; o [block_q, d]."""
    qi = pl.program_id(1)
    d = q_ref.shape[-1]
    q = q_ref[:].astype(jnp.float32) * (d ** -0.5)

    m = jnp.full((block_q,), NEG_INF, jnp.float32)
    den = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, d), jnp.float32)

    num_k_blocks = seq_len // block_k

    def body(kj, carry):
        m, den, acc = carry
        k_blk = k_ref[pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bq, bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        den = den * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, den, acc

    if causal:
        # skip blocks strictly above the diagonal
        last_block = qi * block_q // block_k + \
            (block_q + block_k - 1) // block_k
        upper = jnp.minimum(num_k_blocks, last_block)
    else:
        upper = num_k_blocks
    m, den, acc = jax.lax.fori_loop(0, upper, body, (m, den, acc))
    o_ref[:] = (acc / den[:, None]).astype(o_ref.dtype)


def flash_attention_forward(q, k, v, causal: bool = False,
                            block_q: int = 128, block_k: int = 128,
                            interpret: bool = False):
    """Pallas forward.  q/k/v: ``[batch, heads, seq, head_dim]``."""
    b, h, t, d = q.shape
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q or t % block_k:
        raise ValueError(f"block sizes ({block_q}, {block_k}) must divide "
                         f"seq {t}")

    qf = q.reshape(b * h, t, d)
    kf = k.reshape(b * h, t, d)
    vf = v.reshape(b * h, t, d)

    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, seq_len=t,
        causal=causal)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, t, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, t, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d),
                               lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, t, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, block_q, block_k):
    return flash_attention_forward(q, k, v, causal=causal,
                                   block_q=block_q, block_k=block_k)


def _flash_fwd(q, k, v, causal, block_q, block_k):
    out = flash_attention_forward(q, k, v, causal=causal,
                                  block_q=block_q, block_k=block_k)
    return out, (q, k, v)


def _flash_bwd(causal, block_q, block_k, residuals, g):
    q, k, v = residuals
    block = min(block_k, q.shape[2])  # forward clamps too
    _, vjp = jax.vjp(
        lambda q, k, v: blockwise_attention(q, k, v, block,
                                            causal=causal), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False, block_q: int = 128,
                    block_k: int = 128):
    """Differentiable flash attention; Pallas on TPU, pure-JAX blockwise
    elsewhere."""
    if jax.default_backend() != "tpu":
        return blockwise_attention(q, k, v, min(block_k, q.shape[2]),
                                   causal=causal)
    return _flash(q, k, v, causal, block_q, block_k)
