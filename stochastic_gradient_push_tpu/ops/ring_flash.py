"""Ring attention with fused Pallas flash kernels per tick.

``parallel/ring_attention.py`` keeps the K/V rotation but computes each
tick's contribution with a materialized ``[block, block]`` fp32 score
matrix — fine at study scale, quadratic HBM at long context (a 16k-token
shard is a 1 GB score tensor per batch·head).  This module is the
long-context production path: the same ring schedule, but every tick's
block attention runs through the fused flash kernels
(ops/flash_attention.py), so per-device memory stays
O(flash_block²) regardless of shard length, and the MXU sees the same
tuned kernels the single-device path uses.

Two structural tricks make the composition exact:

* **LSE merging** (forward): each tick returns its block-normalized
  output plus the row logsumexp; ticks combine by
  ``lse ← logaddexp(lse, lse_t)`` with outputs reweighted by
  ``exp(lse_t − lse)`` — the online-softmax recurrence lifted to whole
  ticks.
* **Global-LSE backward**: flash-attention-2's backward needs only the
  FINAL row logsumexp and ``delta = rowsum(do · out)``; per-tick calls
  of the fused dq/dkv kernels with the merged lse yield exactly that
  tick's gradient contribution.  dq accumulates locally; dk/dv
  accumulators ride around the ring WITH their k/v blocks and arrive
  home after a full rotation.

Causality needs no position plumbing: a tick is either fully visible
(``causal=False`` kernels), the aligned diagonal block
(``causal=True`` kernels), or fully masked (skipped) — the three-way
``lax.switch`` below.

On non-TPU backends the per-tick compute falls back to a pure-JAX
blockwise tick (the oracle the tests pin against); ``interpret=True``
forces the Pallas kernels through the Pallas interpreter so CPU tests
exercise the real kernel path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .flash_attention import (
    flash_attention_backward,
    flash_attention_forward,
)
from .gossip_kernel import resolve_use_pallas

__all__ = ["ring_flash_attention"]

NEG_INF = -1e30

_FULL, _DIAG, _SKIP = 0, 1, 2


def _tick_fwd(q, k, v, causal: bool, use_pallas: bool, interpret: bool,
              block: int):
    """One tick's block attention → (normalized out, lse [b,h,t])."""
    if use_pallas:
        return flash_attention_forward(q, k, v, causal=causal,
                                       block_q=block, block_k=block,
                                       interpret=interpret,
                                       return_lse=True)
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * (d ** -0.5)
    if causal:
        t = q.shape[2]
        mask = jnp.tril(jnp.ones((t, t), bool))[None, None]
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    den = jnp.sum(p, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    out = (out / den[..., None]).astype(q.dtype)
    return out, m + jnp.log(den)


def _tick_bwd(q, k, v, out, lse, do, causal: bool, use_pallas: bool,
              interpret: bool, block: int):
    """One tick's (dq, dk, dv) under the GLOBAL lse/out (flash-2 rule)."""
    if use_pallas:
        return flash_attention_backward(q, k, v, out, lse, do,
                                        causal=causal, block_q=block,
                                        block_k=block,
                                        interpret=interpret)
    d = q.shape[-1]
    scale = d ** -0.5
    qf = q.astype(jnp.float32) * scale
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf,
                   preferred_element_type=jnp.float32)
    if causal:
        t = q.shape[2]
        mask = jnp.tril(jnp.ones((t, t), bool))[None, None]
        s = jnp.where(mask, s, NEG_INF)
    p = jnp.exp(s - lse[..., None])
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
    dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vf)
    ds = p * (dp - delta[..., None])
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kf) * scale
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _tick_mode(my_rank, owner, causal: bool):
    if not causal:
        return jnp.int32(_FULL)
    return jnp.where(owner == my_rank, _DIAG,
                     jnp.where(owner < my_rank, _FULL, _SKIP))


def _ring_forward(q, k, v, axis_name, causal, use_pallas, interpret,
                  block):
    world = lax.axis_size(axis_name)
    my_rank = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % world) for i in range(world)]
    tick = functools.partial(_tick_fwd, use_pallas=use_pallas,
                             interpret=interpret, block=block)

    def merge(acc, lse, mode, k_blk, v_blk):
        def visible(causal_tick):
            out_t, lse_t = tick(q, k_blk, v_blk, causal_tick)
            lse_new = jnp.logaddexp(lse, lse_t)
            w1 = jnp.exp(lse - lse_new)
            w2 = jnp.exp(lse_t - lse_new)
            return (acc * w1[..., None]
                    + out_t.astype(jnp.float32) * w2[..., None], lse_new)

        return lax.switch(mode, [lambda: visible(False),
                                 lambda: visible(True),
                                 lambda: (acc, lse)])

    zeros_bht = jnp.sum(q.astype(jnp.float32) * 0.0, axis=-1)
    acc = jnp.zeros_like(q, jnp.float32)
    lse = zeros_bht + NEG_INF

    def body(carry, step):
        acc, lse, k_blk, v_blk = carry
        nk = lax.ppermute(k_blk, axis_name, perm)
        nv = lax.ppermute(v_blk, axis_name, perm)
        mode = _tick_mode(my_rank, (my_rank - step) % world, causal)
        acc, lse = merge(acc, lse, mode, k_blk, v_blk)
        return (acc, lse, nk, nv), None

    if world > 1:
        (acc, lse, k_last, v_last), _ = lax.scan(
            body, (acc, lse, k, v), jnp.arange(world - 1))
        mode = _tick_mode(my_rank, (my_rank + 1) % world, causal)
        acc, lse = merge(acc, lse, mode, k_last, v_last)
    else:
        acc, lse = merge(acc, lse, jnp.int32(_DIAG if causal else _FULL),
                         k, v)
    return (acc).astype(q.dtype), lse


def _ring_backward(q, k, v, out, lse, do, axis_name, causal, use_pallas,
                   interpret, block):
    world = lax.axis_size(axis_name)
    my_rank = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % world) for i in range(world)]
    tick = functools.partial(_tick_bwd, use_pallas=use_pallas,
                             interpret=interpret, block=block)

    def contribute(dq_acc, dk_acc, dv_acc, mode, k_blk, v_blk):
        def visible(causal_tick):
            dq_t, dk_t, dv_t = tick(q, k_blk, v_blk, out, lse, do,
                                    causal_tick)
            return (dq_acc + dq_t.astype(jnp.float32),
                    dk_acc + dk_t.astype(jnp.float32),
                    dv_acc + dv_t.astype(jnp.float32))

        return lax.switch(mode, [lambda: visible(False),
                                 lambda: visible(True),
                                 lambda: (dq_acc, dk_acc, dv_acc)])

    dq_acc = jnp.zeros_like(q, jnp.float32)
    dk_acc = jnp.zeros_like(k, jnp.float32)
    dv_acc = jnp.zeros_like(v, jnp.float32)

    def body(carry, step):
        dq_acc, k_blk, v_blk, dk_acc, dv_acc = carry
        mode = _tick_mode(my_rank, (my_rank - step) % world, causal)
        dq_acc, dk_acc, dv_acc = contribute(dq_acc, dk_acc, dv_acc, mode,
                                            k_blk, v_blk)
        # the dk/dv accumulators travel WITH their block
        nk = lax.ppermute(k_blk, axis_name, perm)
        nv = lax.ppermute(v_blk, axis_name, perm)
        ndk = lax.ppermute(dk_acc, axis_name, perm)
        ndv = lax.ppermute(dv_acc, axis_name, perm)
        return (dq_acc, nk, nv, ndk, ndv), None

    if world > 1:
        (dq_acc, k_last, v_last, dk_acc, dv_acc), _ = lax.scan(
            body, (dq_acc, k, v, dk_acc, dv_acc), jnp.arange(world - 1))
        mode = _tick_mode(my_rank, (my_rank + 1) % world, causal)
        dq_acc, dk_acc, dv_acc = contribute(dq_acc, dk_acc, dv_acc, mode,
                                            k_last, v_last)
        # blocks sit one hop short of home after world-1 rotations; the
        # final hop returns each accumulator to its block's owner
        dk_acc = lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = lax.ppermute(dv_acc, axis_name, perm)
    else:
        mode = jnp.int32(_DIAG if causal else _FULL)
        dq_acc, dk_acc, dv_acc = contribute(dq_acc, dk_acc, dv_acc, mode,
                                            k, v)
    return (dq_acc.astype(q.dtype), dk_acc.astype(k.dtype),
            dv_acc.astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash(q, k, v, axis_name, causal, use_pallas, interpret, block):
    out, _ = _ring_forward(q, k, v, axis_name, causal, use_pallas,
                           interpret, block)
    return out


def _ring_flash_fwd(q, k, v, axis_name, causal, use_pallas, interpret,
                    block):
    out, lse = _ring_forward(q, k, v, axis_name, causal, use_pallas,
                             interpret, block)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, causal, use_pallas, interpret, block,
                    residuals, g):
    q, k, v, out, lse = residuals
    return _ring_backward(q, k, v, out, lse, g, axis_name, causal,
                          use_pallas, interpret, block)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_flash_attention(q, k, v, axis_name: str, causal: bool = False,
                         block: int | None = None, interpret: bool = False,
                         use_pallas: bool | None = None):
    """Exact ring attention with flash-kernel ticks.

    Args:
      q, k, v: per-rank sequence blocks ``[batch, heads, block_len,
        head_dim]``; must be called inside ``shard_map``.
      axis_name: mesh axis the sequence is sharded over.
      causal: causal masking consistent with contiguous block layout.
      block: flash kernel block size within each tick; None = the
        measured auto rule (flash_attention.default_block) on the local
        shard length.
      interpret: run the Pallas kernels through the interpreter
        (CPU tests of the real kernel path).
      use_pallas: force the kernel choice; default auto — Pallas on TPU
        (or when ``interpret``), pure-JAX blockwise tick elsewhere.  The
        auto rule is the shared
        :func:`~.gossip_kernel.resolve_use_pallas`, one convention for
        every Pallas lane in ops/.
    """
    use_pallas = resolve_use_pallas(use_pallas, interpret)
    if block is None:
        from .flash_attention import default_block

        block = default_block(q.shape[2])
    return _ring_flash(q, k, v, axis_name, causal, use_pallas, interpret,
                       block)
