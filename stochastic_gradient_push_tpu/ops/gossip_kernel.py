"""Fused Pallas gossip edge kernel: remote DMA + in-receive decode + axpy.

The schedule-level half of hiding the gossip exchange shipped with the
overlap phase schedule (``collectives.overlap_launch``); this module
closes the kernel-level half.  The XLA path round-trips every encoded
payload through HBM three times per edge: ``ppermute`` ships the wire
bytes, a separate decode pass materializes the full-precision payload,
and a separate axpy folds it into the accumulator.  Here one
``pl.pallas_call`` per (edge, leaf) does all three as a single fused op:

* **transport** — the flattened encoded payload is chunked over a grid;
  each grid step issues one ``pltpu.make_async_remote_copy`` per wire
  part (the int8 scale side-lane is its own part) straight from the
  sender's HBM into the destination rank's receive buffer, signalled by
  per-chunk send/recv DMA semaphores (the SNIPPETS.md [2] right-permute
  pattern, generalized to an arbitrary static destination table).  On
  grid step 0 — before the first RDMA — every rank runs an entry
  barrier with its destination AND its source (the barrier semaphore
  ``collective_id`` exists for): a fast sender must not write into the
  receiver's HBM receive buffers while the receiver has not yet entered
  the kernel and that scratch memory still belongs to a previous op.
  The barrier is emitted in compiled (Mosaic) mode only: the Pallas
  interpreter discharges each remote copy synchronously across the mesh
  axis, so no such race exists there (and its discharge rules do not
  implement remote semaphore signals);
* **in-receive decode** — the received chunk is DMA'd into VMEM and
  decoded there: f32 passthrough, bf16 widen, int8 per-block dequant
  against the scale side-lane (``parallel/wire.py`` owns the encode;
  the decode spec the codec exposes is interpreted here);
* **mixing axpy** — ``acc += w·decode(chunk)`` accumulates directly in
  VMEM (the mixing weight rides the sender multiply of the
  column-stochastic round, so the receive-side ``w`` is the identity),
  and only the updated accumulator block is written back.  The DECODED
  payload never materializes in HBM; the receive buffer holds encoded
  bytes only (~1 B/elem at int8 instead of 4).

Selection follows the ``ops/ring_flash.py`` convention through the
shared :func:`resolve_use_pallas` rule — Pallas on TPU (or under
``interpret=True``, which runs the identical kernel through the Pallas
interpreter so the world-8 CPU test mesh exercises the real remote-DMA
path), XLA ``ppermute`` everywhere else — and the XLA fallback stays
selectable at runtime (``--gossip_kernel xla``) and bit-compared in CI.
``resolve_gossip_kernel`` maps the CLI flag onto a :class:`KernelLane`
and rejects ``pallas`` on a backend that cannot lower Mosaic remote DMA
with a typed :class:`KernelBackendError` instead of a Mosaic crash.

Numerics: the kernel branch reuses the exact send pipeline of the XLA
path — the sender multiply, fault keep-masks, EF residual injection and
the codec ``encode`` all happen before the payload reaches the kernel,
so the error-feedback residual telescopes against the same sent bytes
— and the in-VMEM decode performs the same elementwise ops in the same
order as ``WireCodec.decode``, so interpret-mode output is bit-aligned
with the XLA path (pinned by tests and the wirecheck kernel lane).  The
push-sum weight lane (scalar leaves) never enters the kernel: it ships
exact f32 over ``lax.ppermute`` in both lanes, bit-identical by
construction.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["KernelBackendError", "KernelLane", "GOSSIP_KERNELS",
           "DEFAULT_CHUNK_ELEMS", "COLLECTIVE_ID_SLOTS",
           "resolve_use_pallas", "resolve_gossip_kernel",
           "gossip_edge_axpy", "main"]

# CLI vocabulary for --gossip_kernel
GOSSIP_KERNELS = ("auto", "pallas", "xla")

# elements of decoded payload per remote-copy chunk: 64k f32 elements is
# a 256 KB VMEM working set per buffered part — deep enough to amortize
# DMA issue cost, shallow enough to leave VMEM for the train step
DEFAULT_CHUNK_ELEMS = 64 * 1024

# ceiling on chunks per call (bounds the per-chunk DMA semaphore
# arrays); larger payloads get proportionally larger chunks
_MAX_CHUNKS = 256

# barrier-semaphore id pool the collective layer cycles per leaf slot:
# Mosaic keys barrier/collective state by collective_id, so two
# pallas_calls that could execute concurrently must not share one.
# Same-leaf calls are chained by their accumulator data dependency;
# distinct leaves get distinct ids from this pool (collectives.py
# passes collective_id = leaf_slot % COLLECTIVE_ID_SLOTS)
COLLECTIVE_ID_SLOTS = 16


class KernelBackendError(RuntimeError):
    """``--gossip_kernel pallas`` on a backend that cannot run it."""


def resolve_use_pallas(flag: bool | None, interpret: bool) -> bool:
    """The shared kernel-selection auto rule (ops/ring_flash.py and the
    gossip kernel resolve through this one function): an explicit flag
    wins; ``None`` means Pallas on TPU — or whenever ``interpret`` is
    set, which routes the identical kernel through the Pallas
    interpreter (the CPU test path) — and the non-kernel fallback
    elsewhere."""
    if flag is None:
        return bool(interpret) or jax.default_backend() == "tpu"
    return bool(flag)


@dataclasses.dataclass(frozen=True)
class KernelLane:
    """Resolved Pallas lane for the gossip collective: carried by the
    algorithm/collective layers wherever the kernel branch is active
    (absence — ``None`` — is the XLA ppermute lane)."""

    interpret: bool = False
    chunk_elems: int = DEFAULT_CHUNK_ELEMS

    @property
    def name(self) -> str:
        return "pallas"


def resolve_gossip_kernel(flag: str | None,
                          interpret: bool = False) -> KernelLane | None:
    """Map the ``--gossip_kernel`` flag onto a lane.

    ``"xla"``/``None`` → ``None`` (the ppermute path).  ``"auto"`` →
    a :class:`KernelLane` exactly when :func:`resolve_use_pallas` says
    the kernel can run (TPU, or ``interpret``).  ``"pallas"`` → a lane,
    or a typed :class:`KernelBackendError` on a backend where the
    Mosaic remote-DMA kernel cannot lower — failing at resolve time
    with a readable message instead of a Mosaic crash at first step.
    """
    if flag is None or flag == "xla":
        return None
    if flag == "auto":
        if resolve_use_pallas(None, interpret):
            return KernelLane(interpret=bool(interpret))
        return None
    if flag == "pallas":
        if not resolve_use_pallas(None, interpret):
            raise KernelBackendError(
                "gossip_kernel='pallas' needs a TPU backend: the fused "
                "gossip kernel's remote DMA only lowers through Mosaic "
                f"(current backend: {jax.default_backend()!r}).  Use "
                "gossip_kernel=auto for the XLA ppermute fallback, or "
                "interpret=True (tests) to run the kernel through the "
                "Pallas interpreter")
        return KernelLane(interpret=bool(interpret))
    raise ValueError(
        f"unknown gossip_kernel {flag!r}; one of {GOSSIP_KERNELS}")


# -- chunk layout -----------------------------------------------------------


def _chunk_layout(n_decoded: int, block: int | None, chunk_elems: int):
    """(chunk_rows R, elems per chunk C, num chunks NB) for a payload of
    ``n_decoded`` elements.  With an int8 ``block`` a chunk is a whole
    number of codec blocks so every scale stays chunk-local; the chunk
    target grows when the payload would otherwise exceed the semaphore
    ceiling."""
    blk = int(block) if block else 1
    rows_total = max(1, -(-n_decoded // blk))   # ceil: codec blocks
    # a chunk never exceeds the payload: padding is bounded by one
    # chunk's ragged tail, not by the chunk target
    rows_per_chunk = max(1, min(int(chunk_elems) // blk, rows_total))
    nb = -(-rows_total // rows_per_chunk)
    if nb > _MAX_CHUNKS:
        rows_per_chunk = -(-rows_total // _MAX_CHUNKS)
        nb = -(-rows_total // rows_per_chunk)
    return rows_per_chunk, rows_per_chunk * blk, nb


def _pad_rows(a, rows: int):
    """Zero-pad the leading dim to ``rows`` (symmetric codecs keep
    decode(0) == 0, so padding never leaks into the axpy)."""
    if a.shape[0] == rows:
        return a
    pad = [(0, rows - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
    return jnp.pad(a, pad)


# -- the kernel -------------------------------------------------------------


def _edge_axpy_kernel(kind: str, nparts: int, out_dtype, barrier: bool,
                      dst_ref, acc_ref, *refs):
    """One grid step: remote-copy this chunk of every wire part to the
    destination rank, pull the received chunk into VMEM, decode, and
    accumulate into the output block.

    Ref layout (after the SMEM ``[dst, src]`` rank pair and the
    pipelined accumulator block): ``refs = (*part_refs, out_ref,
    *recv_bufs, *vmem_bufs, *send_sems, *recv_sems, copy_sem)``.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    part_refs = refs[:nparts]
    out_ref = refs[nparts]
    scratch = refs[nparts + 1:]
    recv_bufs = scratch[:nparts]
    vmem_bufs = scratch[nparts:2 * nparts]
    send_sems = scratch[2 * nparts:3 * nparts]
    recv_sems = scratch[3 * nparts:4 * nparts]
    copy_sem = scratch[4 * nparts]

    k = pl.program_id(0)
    dst = dst_ref[0]

    if barrier:
        # entry barrier (compiled mode only — the interpreter's
        # discharge is synchronous and cannot signal remote
        # semaphores): before the FIRST remote copy, handshake with
        # the rank we write into (dst) and the rank that writes into
        # us (src, the permutation's inverse at this rank), so no
        # sender DMAs into recv_bufs before its receiver has entered
        # the kernel and owns that scratch memory.  Each rank receives
        # exactly two signals (from ITS src and dst) and waits the
        # semaphore back down to zero, per the Mosaic barrier contract.
        @pl.when(k == 0)
        def _entry_barrier():
            src = dst_ref[1]
            bsem = pltpu.get_barrier_semaphore()
            pltpu.semaphore_signal(
                bsem, inc=1, device_id=dst,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            pltpu.semaphore_signal(
                bsem, inc=1, device_id=src,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            pltpu.semaphore_wait(bsem, 2)

    # transport: chunk k of every part rides one remote DMA to the
    # destination; waiting the descriptor waits BOTH our send drain and
    # our own recv semaphore — signalled by whichever rank holds us as
    # its destination (the permutation is a bijection, so exactly one)
    rdmas = []
    for i in range(nparts):
        rdmas.append(pltpu.make_async_remote_copy(
            src_ref=part_refs[i].at[pl.ds(k, 1)],
            dst_ref=recv_bufs[i].at[pl.ds(k, 1)],
            send_sem=send_sems[i].at[k],
            recv_sem=recv_sems[i].at[k],
            device_id=dst,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        ))
    for r in rdmas:
        r.start()
    for r in rdmas:
        r.wait()

    # receive side: encoded chunk HBM -> VMEM (the only HBM residency of
    # the received payload is its ENCODED form in recv_bufs)
    for i in range(nparts):
        cp = pltpu.make_async_copy(recv_bufs[i].at[pl.ds(k, 1)],
                                   vmem_bufs[i], copy_sem)
        cp.start()
        cp.wait()

    # in-VMEM decode + mixing axpy; elementwise op order matches
    # WireCodec.decode exactly (bit parity with the XLA lane)
    if kind == "int8":
        q = vmem_bufs[0][0].astype(jnp.float32)        # [R, block]
        scale = vmem_bufs[1][0]                        # [R]
        dec = (q * scale[:, None]).reshape(1, -1).astype(out_dtype)
    else:  # "f32" passthrough / "bf16" widen — one astype covers both
        dec = vmem_bufs[0][0].reshape(1, -1).astype(out_dtype)
    out_ref[...] = acc_ref[...] + dec


def _edge_axpy_call(kind: str, interpret: bool, collective_id: int, dst,
                    acc_chunks, parts_chunks):
    """Build and invoke the pallas_call for one edge/leaf payload whose
    chunking is already laid out (acc ``[NB, C]``, each part
    ``[NB, ...]`` — the shapes alone carry the layout)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nb, c = acc_chunks.shape
    nparts = len(parts_chunks)
    # the entry barrier only lowers through Mosaic; the interpreter's
    # discharge rules run each remote copy synchronously (raceless) and
    # do not implement remote semaphore signals
    kernel = functools.partial(_edge_axpy_kernel, kind, nparts,
                               acc_chunks.dtype, not interpret)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(acc_chunks.shape, acc_chunks.dtype),
        grid=(nb,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] +
                 [pl.BlockSpec((1, c), lambda k: (k, 0),
                               memory_space=pltpu.VMEM)] +
                 [pl.BlockSpec(memory_space=pltpu.ANY)] * nparts,
        out_specs=pl.BlockSpec((1, c), lambda k: (k, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=(
            [pltpu.ANY(p.shape, p.dtype) for p in parts_chunks] +
            [pltpu.VMEM((1,) + p.shape[1:], p.dtype)
             for p in parts_chunks] +
            [pltpu.SemaphoreType.DMA((nb,))] * (2 * nparts) +
            [pltpu.SemaphoreType.DMA(())]),
        # the out block keeps the call live through DCE; collective_id
        # keys the entry-barrier semaphore and coordinates the
        # remote-DMA buffer addresses across the SPMD programs on a
        # real mesh.  Two calls that could execute concurrently must
        # not share an id (Mosaic keys barrier state by it): the
        # collective layer cycles ids per leaf slot
        # (COLLECTIVE_ID_SLOTS) — same-leaf calls are already ordered
        # by their accumulator data dependency, and TPU's single
        # compute stream executes custom calls sequentially in schedule
        # order, which backstops any id reuse across the pool boundary
        compiler_params=pltpu.TPUCompilerParams(
            collective_id=collective_id),
        interpret=interpret,
    )(dst, acc_chunks, *parts_chunks)


def gossip_edge_axpy(acc, parts, dests, axis_name: str, spec,
                     interpret: bool = False,
                     chunk_elems: int = DEFAULT_CHUNK_ELEMS, weight=None,
                     collective_id: int = 0):
    """``acc + w·decode(permute(parts))`` as one fused Pallas op.

    Drop-in replacement for the XLA seam
    ``acc + codec.decode(tuple(lax.ppermute(p, axis, pairs) for p in
    parts), like)`` inside :func:`..parallel.collectives._round_fn` —
    the encoded wire ``parts`` (from ``WireCodec.encode``; the sender
    multiply, fault masks and EF injection already applied upstream)
    are remote-copied chunk by chunk to the rank this rank's row of
    ``dests`` names, decoded in VMEM per ``spec`` (a
    :class:`~..parallel.wire.DecodeSpec`), and accumulated into ``acc``.

    ``weight`` is the receive-side axpy scalar; the column-stochastic
    round bakes the mixing weight into the sender multiply, so the
    default ``None`` (identity) is the production path.  Must be called
    inside ``shard_map`` with ``axis_name`` bound; all ranks execute
    the same program (the remote DMA is SPMD).

    ``collective_id`` keys the kernel's entry-barrier semaphore; call
    sites that could execute concurrently must pass distinct ids (the
    collective layer cycles ``leaf_slot % COLLECTIVE_ID_SLOTS``).
    """
    if spec is None:
        raise ValueError("codec exposes no in-kernel decode spec; the "
                         "caller must take the XLA ppermute path")
    kind = spec.kind
    if kind not in ("f32", "bf16", "int8"):
        raise ValueError(f"unknown decode spec kind {kind!r}")
    n = acc.size
    block = spec.block if kind == "int8" else None
    rows, c, nb = _chunk_layout(n, block, chunk_elems)

    # this rank's destination AND source from the static table, as an
    # SMEM [dst, src] pair: the entry barrier handshakes with both the
    # rank we write into and the rank that writes into us.  The source
    # is the permutation's inverse at this rank — which only exists
    # because the table is a bijection (SGPV101), so check it here
    # rather than ship garbage into the barrier
    table = np.asarray(dests, dtype=np.int32)
    if not np.array_equal(np.sort(table), np.arange(table.size)):
        raise ValueError(
            "dests must be a permutation of the axis ranks (every rank "
            f"receives exactly one stream); got {table.tolist()}")
    inv = np.empty_like(table)
    inv[table] = np.arange(table.size, dtype=np.int32)
    both = jnp.asarray(np.stack([table, inv], axis=1), jnp.int32)
    dst = both[jax.lax.axis_index(axis_name)]

    acc_flat = _pad_rows(acc.reshape(-1), nb * c).reshape(nb, c)
    if kind == "int8":
        q, scale = parts
        q_chunks = _pad_rows(q, nb * rows).reshape(nb, rows, q.shape[1])
        s_chunks = _pad_rows(scale, nb * rows).reshape(nb, rows)
        parts_chunks = (q_chunks, s_chunks)
    else:
        (w,) = parts
        parts_chunks = (_pad_rows(w.reshape(-1), nb * c).reshape(nb, c),)

    out = _edge_axpy_call(kind, interpret, int(collective_id), dst,
                          acc_flat, parts_chunks)
    out = out.reshape(-1)[:n].reshape(acc.shape)
    if weight is not None:
        out = acc + (out - acc) * jnp.asarray(weight, acc.dtype)
    return out


# -- CI selftest (scripts/gossipkernel.py) ----------------------------------


def _selftest() -> int:
    """Interpret-mode kernel acceptance on the world-8 virtual CPU mesh:
    the fused kernel must match the XLA decode+axpy bit-for-bit on the
    f32 passthrough and to f32 tolerance on int8, including a chunked
    (multi-grid-step) payload with a ragged tail."""
    import sys

    from jax.sharding import PartitionSpec as P

    from ..parallel import wire
    from ..parallel.mesh import GOSSIP_AXIS, make_gossip_mesh

    world = 8
    if jax.device_count() < world:
        print(f"gossip-kernel selftest FAILED: needs {world} devices, "
              f"have {jax.device_count()} (run via "
              "scripts/gossipkernel.py)", file=sys.stderr)
        return 1
    failures: list[str] = []
    mesh = make_gossip_mesh(world)
    dests = np.asarray([(r + 1) % world for r in range(world)])
    rng = np.random.default_rng(0)
    # ragged: 3 chunks at chunk_elems=128 with a 44-element tail
    n = 300
    x = rng.normal(size=(world, n)).astype(np.float32)
    codec = wire.Int8Codec(64)

    def both_lanes(xr):
        xr = xr.reshape(-1)
        acc = xr * 0.25
        pairs = [(s, int(dests[s])) for s in range(world)]
        # f32 passthrough lane
        k_f32 = gossip_edge_axpy(acc, (xr,), dests, GOSSIP_AXIS,
                                 wire.F32.kernel_spec(), interpret=True,
                                 chunk_elems=128)
        x_f32 = acc + jax.lax.ppermute(xr, GOSSIP_AXIS, pairs)
        # int8 lane (shared encode, in-kernel vs XLA decode)
        parts = codec.encode(xr)
        k_i8 = gossip_edge_axpy(acc, parts, dests, GOSSIP_AXIS,
                                codec.kernel_spec(), interpret=True,
                                chunk_elems=128)
        x_i8 = acc + codec.decode(
            tuple(jax.lax.ppermute(p, GOSSIP_AXIS, pairs)
                  for p in parts), xr)
        return tuple(t[None] for t in (k_f32, x_f32, k_i8, x_i8))

    fn = jax.jit(jax.shard_map(both_lanes, mesh=mesh,
                               in_specs=P(GOSSIP_AXIS),
                               out_specs=(P(GOSSIP_AXIS),) * 4))
    k_f32, x_f32, k_i8, x_i8 = map(np.asarray, fn(x))
    if not np.array_equal(k_f32, x_f32):
        failures.append(
            f"f32 passthrough lane diverged from XLA ppermute "
            f"(max |d| {np.abs(k_f32 - x_f32).max():.2e}); the fused "
            "transport must be bit-identical")
    d8 = np.abs(k_i8 - x_i8).max()
    if d8 > 1e-6:
        failures.append(
            f"int8 in-kernel dequant drifted {d8:.2e} from the XLA "
            "decode (same scales, same op order — should be aligned)")
    # resolver contract: typed rejection instead of a Mosaic crash
    try:
        resolve_gossip_kernel("pallas", interpret=False)
        if jax.default_backend() != "tpu":
            failures.append("resolve_gossip_kernel('pallas') on a "
                            "non-TPU backend did not raise")
    except KernelBackendError:
        pass
    if resolve_gossip_kernel("auto", interpret=True) is None:
        failures.append("auto+interpret must resolve to the kernel lane")
    if resolve_gossip_kernel("xla") is not None:
        failures.append("'xla' must resolve to the ppermute lane")

    if failures:
        for f in failures:
            print(f"gossip-kernel selftest FAILED: {f}", file=sys.stderr)
        return 1
    print(f"gossip-kernel selftest: OK (world {world}, payload {n} over "
          f"3 chunks: f32 lane bit-identical, int8 lane max |d| "
          f"{d8:.1e}; pallas-on-cpu rejected with a typed error)")
    return 0


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="gossipkernel",
        description="Fused Pallas gossip kernel: CI selftest")
    ap.add_argument("--selftest", action="store_true",
                    help="run the interpret-mode kernel self-check")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    ap.error("choose --selftest")
    return 2
