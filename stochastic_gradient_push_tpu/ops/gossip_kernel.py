"""Split Pallas gossip transport: start (remote DMA) / wait (decode+axpy).

The schedule-level half of hiding the gossip exchange shipped with the
overlap phase schedule (``collectives.overlap_launch``); this module
closes the kernel-level half.  The XLA path round-trips every encoded
payload through HBM three times per edge: ``ppermute`` ships the wire
bytes, a separate decode pass materializes the full-precision payload,
and a separate axpy folds it into the accumulator.  The original fused
kernel (PR 15) collapsed those into one ``pallas_call`` — but starting
AND waiting the remote DMA inside one op meant overlap launches could
never ride it (the transport the overlap schedule hides behind compute
was serialized inside the kernel).  This revision splits the op:

* :func:`gossip_edge_start` — one ``pallas_call`` serving ALL edges of
  a payload (the per-edge messages ride a leading ``E`` axis; one
  program, ``E × num_chunks`` grid steps): grid step 0 runs the entry
  barrier with every destination AND source on the ``collective_id``-
  keyed barrier semaphore, then each step issues one
  ``pltpu.make_async_remote_copy`` per wire part (the int8 scale
  side-lane is its own part) straight from the sender's HBM into the
  destination rank's landing buffer, *pipelined depth-2*: the DMA for
  chunk ``g+1`` is issued before chunk ``g`` is waited, so the wire
  stays busy while completions drain.  The call returns an opaque
  :class:`TransportHandle` carrying the landed ENCODED buffers — the
  cross-call data dependency XLA schedules around;
* :func:`gossip_edge_wait` — a purely local ``pallas_call`` (no axis,
  no barrier, no collective_id) that pulls each landed chunk into VMEM,
  decodes it there (f32 passthrough, bf16 widen, int8 per-block dequant
  against the scale side-lane), and accumulates ``acc += decode(chunk)``
  across all ``E`` edges into the output block.  Mosaic's automatic
  grid pipeline double-buffers the decode against the next chunk's
  HBM→VMEM fetch.  The DECODED payload never materializes in HBM.

**Handle contract (compiled mode, honestly stated).**  Mosaic in this
jax version keys DMA semaphores to kernel scratch — they must drain
before a ``pallas_call`` returns, and no semaphore can cross a call
boundary.  So the start op completes its own transfers internally (the
depth-2 chunk pipeline above is where the wire overlap inside the op
lives) and the handle's "semaphore state" is definitionally drained at
hand-off: what crosses the call boundary is the landed encoded buffer
state.  The async win is scheduling-level and real — ``overlap_launch``
issues the start at the TOP of the step, XLA hoists it behind the
forward/backward compute, and ``post_step`` consumes the handle via the
wait at the bottom — exactly the start/done split the XLA lane's
collective-permute pair gets, now with in-VMEM decode on the landing
side.  On the interpret CI mesh the Pallas interpreter discharges each
remote copy synchronously, so split and fused numerics are identical.
A live-TPU capture of the compiled pipeline is the carried ROADMAP
item.

:func:`gossip_edge_axpy` remains as the fused convenience spelling —
now literally ``gossip_edge_wait(gossip_edge_start(...), acc)`` — so
single-shot callers and the parity suite exercise the same two kernels
the split path runs.

Selection follows the ``ops/ring_flash.py`` convention through the
shared :func:`resolve_use_pallas` rule — Pallas on TPU (or under
``interpret=True``, which runs the identical kernels through the Pallas
interpreter so the world-8 CPU test mesh exercises the real remote-DMA
path), XLA ``ppermute`` everywhere else — and the XLA fallback stays
selectable at runtime (``--gossip_kernel xla``) and bit-compared in CI.
``resolve_gossip_kernel`` maps the CLI flag onto a :class:`KernelLane`
and rejects ``pallas`` on a backend that cannot lower Mosaic remote DMA
with a typed :class:`KernelBackendError` instead of a Mosaic crash.

Numerics: the kernel branch reuses the exact send pipeline of the XLA
path — the sender multiply, fault keep-masks, EF residual injection and
the codec ``encode`` all happen before the payload reaches the kernel,
so the error-feedback residual telescopes against the same sent bytes
— and the in-VMEM decode performs the same elementwise ops in the same
order as ``WireCodec.decode``, so interpret-mode output is bit-aligned
with the XLA path (pinned by tests and the wirecheck kernel lane).  The
push-sum weight lane (scalar leaves) never enters the kernel: it ships
exact f32 over ``lax.ppermute`` in both lanes, bit-identical by
construction.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["KernelBackendError", "KernelLane", "GOSSIP_KERNELS",
           "DEFAULT_CHUNK_ELEMS", "COLLECTIVE_ID_SLOTS",
           "TransportHandle", "empty_transport_handle",
           "resolve_use_pallas", "resolve_gossip_kernel",
           "gossip_edge_start", "gossip_edge_wait",
           "gossip_edge_axpy", "main"]

# CLI vocabulary for --gossip_kernel
GOSSIP_KERNELS = ("auto", "pallas", "xla")

# elements of decoded payload per remote-copy chunk: 64k f32 elements is
# a 256 KB VMEM working set per buffered part — deep enough to amortize
# DMA issue cost, shallow enough to leave VMEM for the train step
DEFAULT_CHUNK_ELEMS = 64 * 1024

# ceiling on chunks per call (bounds the per-chunk DMA semaphore
# arrays); larger payloads get proportionally larger chunks
_MAX_CHUNKS = 256

# barrier-semaphore id pool the collective layer cycles per transport
# bucket: Mosaic keys barrier/collective state by collective_id, so two
# pallas_calls that could execute concurrently must not share one.
# Buckets launched in the same round are deliberately concurrent (that
# is the pipelining), so each bucket gets its own id from this pool
# (collectives.py passes collective_id = bucket_index %
# COLLECTIVE_ID_SLOTS); successive rounds of the SAME bucket are
# ordered by their handle data dependency
COLLECTIVE_ID_SLOTS = 16


class KernelBackendError(RuntimeError):
    """``--gossip_kernel pallas`` on a backend that cannot run it."""


def resolve_use_pallas(flag: bool | None, interpret: bool) -> bool:
    """The shared kernel-selection auto rule (ops/ring_flash.py and the
    gossip kernel resolve through this one function): an explicit flag
    wins; ``None`` means Pallas on TPU — or whenever ``interpret`` is
    set, which routes the identical kernel through the Pallas
    interpreter (the CPU test path) — and the non-kernel fallback
    elsewhere."""
    if flag is None:
        return bool(interpret) or jax.default_backend() == "tpu"
    return bool(flag)


@dataclasses.dataclass(frozen=True)
class KernelLane:
    """Resolved Pallas lane for the gossip collective: carried by the
    algorithm/collective layers wherever the kernel branch is active
    (absence — ``None`` — is the XLA ppermute lane)."""

    interpret: bool = False
    chunk_elems: int = DEFAULT_CHUNK_ELEMS

    @property
    def name(self) -> str:
        return "pallas"


def resolve_gossip_kernel(flag: str | None,
                          interpret: bool = False) -> KernelLane | None:
    """Map the ``--gossip_kernel`` flag onto a lane.

    ``"xla"``/``None`` → ``None`` (the ppermute path).  ``"auto"`` →
    a :class:`KernelLane` exactly when :func:`resolve_use_pallas` says
    the kernel can run (TPU, or ``interpret``).  ``"pallas"`` → a lane,
    or a typed :class:`KernelBackendError` on a backend where the
    Mosaic remote-DMA kernel cannot lower — failing at resolve time
    with a readable message instead of a Mosaic crash at first step.
    """
    if flag is None or flag == "xla":
        return None
    if flag == "auto":
        if resolve_use_pallas(None, interpret):
            return KernelLane(interpret=bool(interpret))
        return None
    if flag == "pallas":
        if not resolve_use_pallas(None, interpret):
            raise KernelBackendError(
                "gossip_kernel='pallas' needs a TPU backend: the fused "
                "gossip kernel's remote DMA only lowers through Mosaic "
                f"(current backend: {jax.default_backend()!r}).  Use "
                "gossip_kernel=auto for the XLA ppermute fallback, or "
                "interpret=True (tests) to run the kernel through the "
                "Pallas interpreter")
        return KernelLane(interpret=bool(interpret))
    raise ValueError(
        f"unknown gossip_kernel {flag!r}; one of {GOSSIP_KERNELS}")


# -- chunk layout -----------------------------------------------------------


def _chunk_layout(n_decoded: int, block: int | None, chunk_elems: int):
    """(chunk_rows R, elems per chunk C, num chunks NB) for a payload of
    ``n_decoded`` elements.  With an int8 ``block`` a chunk is a whole
    number of codec blocks so every scale stays chunk-local; the chunk
    target grows when the payload would otherwise exceed the semaphore
    ceiling."""
    if int(n_decoded) < 1:
        raise ValueError(
            f"payload must have at least one element, got {n_decoded} "
            "(scalar/empty leaves take the exact-f32 ppermute lane, "
            "never the kernel)")
    if int(chunk_elems) < 1:
        raise ValueError(f"chunk_elems must be >= 1, got {chunk_elems}")
    blk = int(block) if block else 1
    rows_total = max(1, -(-n_decoded // blk))   # ceil: codec blocks
    # a chunk never exceeds the payload: padding is bounded by one
    # chunk's ragged tail, not by the chunk target
    rows_per_chunk = max(1, min(int(chunk_elems) // blk, rows_total))
    nb = -(-rows_total // rows_per_chunk)
    if nb > _MAX_CHUNKS:
        rows_per_chunk = -(-rows_total // _MAX_CHUNKS)
        nb = -(-rows_total // rows_per_chunk)
    return rows_per_chunk, rows_per_chunk * blk, nb


def _pad_rows(a, rows: int):
    """Zero-pad the leading dim to ``rows`` (symmetric codecs keep
    decode(0) == 0, so padding never leaks into the axpy)."""
    if a.shape[0] == rows:
        return a
    pad = [(0, rows - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
    return jnp.pad(a, pad)


# -- the transport handle ---------------------------------------------------


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class TransportHandle:
    """Opaque result of :func:`gossip_edge_start`: the landed encoded
    receive buffers (each ``[E, NB, ...]``) plus the static layout the
    wait side needs to pull, decode and fold them.  A pytree, so it
    rides FIFO slots, ``lax.cond`` branches and jit boundaries; between
    a start and its wait the buffers hold WIRE bytes — nothing outside
    :func:`gossip_edge_wait` / :meth:`decode_edges` may interpret them.

    ``meta`` = (kind, n_decoded, rows, chunk_elems, num_chunks,
    num_edges, interpret) — all static, so handles from different
    schedule phases of one round are structurally identical (required
    for the phase ``lax.switch``)."""

    recv: tuple
    meta: tuple

    def tree_flatten(self):
        return (tuple(self.recv),), self.meta

    @classmethod
    def tree_unflatten(cls, meta, children):
        (recv,) = children
        return cls(recv=tuple(recv), meta=meta)

    @property
    def num_edges(self) -> int:
        return self.meta[5]

    @property
    def n_decoded(self) -> int:
        return self.meta[1]

    def decode_edges(self):
        """Per-edge decoded payload ``[E, n]`` in f32 — the pure-jnp
        twin of the wait kernel's in-VMEM decode, same elementwise op
        order, for landing sites that cannot (or need not) run the
        kernel: drains, health views, interpret-mode checks.  Fold the
        edges sequentially (``for e: acc += dec[e]``) to stay
        bit-aligned with the kernel's per-edge accumulation."""
        kind, n, rows, _c, nb, ne, _interp = self.meta
        if kind == "int8":
            q, scale = self.recv
            qf = q.astype(jnp.float32).reshape(ne, nb * rows, -1)
            s = scale.reshape(ne, nb * rows)
            return (qf * s[:, :, None]).reshape(ne, -1)[:, :n]
        return self.recv[0].reshape(ne, -1)[:, :n].astype(jnp.float32)


def empty_transport_handle(spec, n_decoded: int, num_edges: int,
                           interpret: bool = False,
                           chunk_elems: int = DEFAULT_CHUNK_ELEMS
                           ) -> TransportHandle:
    """A zero handle with exactly the structure a matching
    :func:`gossip_edge_start` call would return — the thinning skip
    branch's ``lax.cond`` arm must hand back the same pytree as the
    launch arm, and waiting a zero handle lands a zero contribution
    (decode(0) == 0 for every codec)."""
    kind = spec.kind
    block = spec.block if kind == "int8" else None
    rows, c, nb = _chunk_layout(n_decoded, block, chunk_elems)
    if kind == "int8":
        recv = (jnp.zeros((num_edges, nb, rows, int(block)), jnp.int8),
                jnp.zeros((num_edges, nb, rows), jnp.float32))
    elif kind == "bf16":
        recv = (jnp.zeros((num_edges, nb, c), jnp.bfloat16),)
    else:
        recv = (jnp.zeros((num_edges, nb, c), jnp.float32),)
    return TransportHandle(
        recv=recv, meta=(kind, int(n_decoded), rows, c, nb,
                         int(num_edges), bool(interpret)))


# -- the start kernel (transport only) --------------------------------------


def _edge_start_kernel(nparts: int, nb: int, ne: int, compiled: bool,
                      tbl_ref, *refs):
    """Transport program over a flat ``E*NB`` grid: grid step ``g``
    covers chunk ``g % NB`` of edge ``g // NB``.

    Ref layout: ``refs = (*part_refs, *out_refs, *send_sems,
    *recv_sems)`` — parts and outs full-shape in ANY (the kernel only
    touches them through DMA), semaphores per (edge, chunk).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    part_refs = refs[:nparts]
    out_refs = refs[nparts:2 * nparts]
    send_sems = refs[2 * nparts:3 * nparts]
    recv_sems = refs[3 * nparts:4 * nparts]

    g = pl.program_id(0)
    total = ne * nb

    def chunk_dmas(gg):
        # descriptors for flat step gg; remaking the same descriptor to
        # wait it is the Mosaic idiom (the semaphores carry identity)
        e = gg // nb
        k = gg - e * nb
        dmas = []
        for i in range(nparts):
            dmas.append(pltpu.make_async_remote_copy(
                src_ref=part_refs[i].at[pl.ds(e, 1), pl.ds(k, 1)],
                dst_ref=out_refs[i].at[pl.ds(e, 1), pl.ds(k, 1)],
                send_sem=send_sems[i].at[e, k],
                recv_sem=recv_sems[i].at[e, k],
                device_id=tbl_ref[e, 0],
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            ))
        return dmas

    if compiled:
        # entry barrier (compiled mode only — the interpreter's
        # discharge is synchronous and cannot signal remote
        # semaphores): before the FIRST remote copy, handshake with
        # every rank we write into (dst_e) and every rank that writes
        # into us (src_e, each permutation's inverse at this rank), so
        # no sender DMAs into landing buffers before its receiver has
        # entered the kernel and owns that memory.  Each rank receives
        # exactly 2E signals (from ITS src and dst per edge) and waits
        # the semaphore back down to zero, per the Mosaic barrier
        # contract.
        @pl.when(g == 0)
        def _entry_barrier():
            bsem = pltpu.get_barrier_semaphore()
            for e in range(ne):
                pltpu.semaphore_signal(
                    bsem, inc=1, device_id=tbl_ref[e, 0],
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
                pltpu.semaphore_signal(
                    bsem, inc=1, device_id=tbl_ref[e, 1],
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
            pltpu.semaphore_wait(bsem, 2 * ne)

        # depth-2 chunk pipeline: step g waits chunk g but has already
        # issued chunk g+1, so one transfer is always in flight while
        # the previous drains (the Mosaic depth of the ROADMAP item)
        @pl.when(g == 0)
        def _prime():
            for dma in chunk_dmas(g):
                dma.start()

        @pl.when(g + 1 < total)
        def _issue_ahead():
            for dma in chunk_dmas(g + 1):
                dma.start()
    else:
        # interpret mode: discharge is synchronous (start performs the
        # copy), so the pipeline shape is irrelevant — issue the step's
        # own chunk and fall through to the shared wait
        for dma in chunk_dmas(g):
            dma.start()

    # both modes drain chunk g here — remade descriptors wait via
    # semaphore identity, so this tail pairs with whichever branch
    # issued the start
    dmas = chunk_dmas(g)
    for dma in dmas:
        dma.wait()


def _edge_start_call(interpret: bool, collective_id: int, ne: int,
                     nb: int, tbl, parts_chunks):
    """Build and invoke the transport pallas_call: inputs are the
    per-edge chunked parts (each ``[E, NB, ...]``), outputs the landed
    encoded buffers of identical shape on the destination ranks."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nparts = len(parts_chunks)
    kernel = functools.partial(_edge_start_kernel, nparts, nb, ne,
                               not interpret)
    return pl.pallas_call(
        kernel,
        out_shape=tuple(jax.ShapeDtypeStruct(p.shape, p.dtype)
                        for p in parts_chunks),
        grid=(ne * nb,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] +
                 [pl.BlockSpec(memory_space=pltpu.ANY)] * nparts,
        out_specs=tuple([pl.BlockSpec(memory_space=pltpu.ANY)] * nparts),
        scratch_shapes=(
            [pltpu.SemaphoreType.DMA((ne, nb))] * (2 * nparts)),
        # collective_id keys the entry-barrier semaphore and
        # coordinates the remote-DMA buffer addresses across the SPMD
        # programs on a real mesh.  Two calls that could execute
        # concurrently must not share an id (Mosaic keys barrier state
        # by it): the collective layer cycles ids per transport bucket
        # (COLLECTIVE_ID_SLOTS) — same-bucket rounds are already
        # ordered by their handle data dependency, and TPU's single
        # compute stream executes custom calls sequentially in schedule
        # order, which backstops any id reuse across the pool boundary
        compiler_params=pltpu.TPUCompilerParams(
            collective_id=collective_id),
        interpret=interpret,
    )(tbl, *parts_chunks)


def gossip_edge_start(parts, dests, axis_name: str, spec,
                      n_decoded: int | None = None,
                      interpret: bool = False,
                      chunk_elems: int = DEFAULT_CHUNK_ELEMS,
                      collective_id: int = 0) -> TransportHandle:
    """Issue the transport for every edge of one payload; returns the
    :class:`TransportHandle` whose wait decodes and accumulates.

    ``parts`` are the encoded wire parts (from ``WireCodec.encode``;
    the sender multiply, fault masks and EF injection already applied
    upstream), each stacked over a leading edge axis ``E`` — one
    pallas_call serves all ``peers_per_itr`` edges.  ``dests`` is the
    matching ``[E, world]`` static destination table (each row a
    permutation; a single ``[world]`` row means ``E == 1``).
    ``n_decoded`` is the decoded payload length the wait side trims to
    (defaults to the encoded capacity).  Must be called inside
    ``shard_map`` with ``axis_name`` bound; all ranks execute the same
    program (the remote DMA is SPMD).

    ``collective_id`` keys the kernel's entry-barrier semaphore; call
    sites that could execute concurrently must pass distinct ids (the
    collective layer cycles ``bucket_index % COLLECTIVE_ID_SLOTS``).
    """
    if spec is None:
        raise ValueError("codec exposes no in-kernel decode spec; the "
                         "caller must take the XLA ppermute path")
    kind = spec.kind
    if kind not in ("f32", "bf16", "int8"):
        raise ValueError(f"unknown decode spec kind {kind!r}")

    table = np.asarray(dests, dtype=np.int32)
    if table.ndim == 1:
        table = table[None]
    ne = table.shape[0]
    # normalize single-edge parts to the stacked [E=1, ...] convention
    expect_ndim = {"int8": (3, 2)}.get(kind, (2,))
    norm = []
    for i, p in enumerate(parts):
        want = expect_ndim[i] if i < len(expect_ndim) else expect_ndim[-1]
        norm.append(p[None] if p.ndim == want - 1 else p)
    parts = tuple(norm)
    if any(p.shape[0] != ne for p in parts):
        raise ValueError(
            f"parts lead with {[p.shape[0] for p in parts]} edges but "
            f"dests has {ne} rows — every part must stack one message "
            "per edge")

    # every row must be a permutation: the barrier handshakes with each
    # permutation's inverse at this rank, which only exists for a
    # bijection (SGPV101, re-checked at the call boundary)
    world = table.shape[1]
    full = np.empty((ne, world, 2), dtype=np.int32)
    for e in range(ne):
        row = table[e]
        if not np.array_equal(np.sort(row), np.arange(world)):
            raise ValueError(
                "dests must be a permutation of the axis ranks (every "
                f"rank receives exactly one stream); got {row.tolist()}")
        inv = np.empty_like(row)
        inv[row] = np.arange(world, dtype=np.int32)
        full[e] = np.stack([row, inv], axis=1)
    # this rank's [E, 2] (dst_e, src_e) table, into SMEM
    tbl = jnp.asarray(np.transpose(full, (1, 0, 2)),
                      jnp.int32)[jax.lax.axis_index(axis_name)]

    if kind == "int8":
        q, scale = parts
        n = int(n_decoded) if n_decoded is not None \
            else q.shape[1] * q.shape[2]
        rows, c, nb = _chunk_layout(n, spec.block, chunk_elems)
        q_chunks = jax.vmap(
            lambda a: _pad_rows(a, nb * rows).reshape(nb, rows,
                                                      a.shape[1]))(q)
        s_chunks = jax.vmap(
            lambda a: _pad_rows(a, nb * rows).reshape(nb, rows))(scale)
        parts_chunks = (q_chunks, s_chunks)
    else:
        (w,) = parts
        n = int(n_decoded) if n_decoded is not None else w.shape[1]
        rows, c, nb = _chunk_layout(n, None, chunk_elems)
        parts_chunks = (jax.vmap(
            lambda a: _pad_rows(a.reshape(-1), nb * c).reshape(nb, c))(w),)

    recv = _edge_start_call(interpret, int(collective_id), ne, nb, tbl,
                            parts_chunks)
    if not isinstance(recv, (tuple, list)):
        recv = (recv,)
    return TransportHandle(
        recv=tuple(recv),
        meta=(kind, n, rows, c, nb, ne, bool(interpret)))


# -- the wait kernel (decode + axpy, purely local) --------------------------


def _edge_wait_kernel(kind: str, ne: int, out_dtype, acc_ref, *refs):
    """One grid step (k, e): decode edge e's chunk k in VMEM and fold it
    into output block k.  The e axis is minormost, so the output block
    stays resident across its E revisits; Mosaic's grid pipeline
    double-buffers each chunk fetch against the previous decode."""
    from jax.experimental import pallas as pl

    e = pl.program_id(1)
    part_refs = refs[:-1]
    out_ref = refs[-1]

    # in-VMEM decode; elementwise op order matches WireCodec.decode
    # exactly (bit parity with the XLA lane)
    if kind == "int8":
        q = part_refs[0][0, 0].astype(jnp.float32)     # [R, block]
        scale = part_refs[1][0, 0]                     # [R]
        dec = (q * scale[:, None]).reshape(1, -1).astype(out_dtype)
    else:  # "f32" passthrough / "bf16" widen — one astype covers both
        dec = part_refs[0][0, 0].reshape(1, -1).astype(out_dtype)

    @pl.when(e == 0)
    def _init():
        out_ref[...] = acc_ref[...] + dec

    if ne > 1:
        @pl.when(e > 0)
        def _fold():
            out_ref[...] = out_ref[...] + dec


def _edge_wait_call(kind: str, interpret: bool, acc_chunks, recv, ne: int):
    """Build and invoke the landing pallas_call: purely local (HBM→VMEM
    pulls of landed chunks + decode + axpy), no collective semantics."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nb, c = acc_chunks.shape
    kernel = functools.partial(_edge_wait_kernel, kind, ne,
                               acc_chunks.dtype)
    if kind == "int8":
        in_specs = [
            pl.BlockSpec((1, c), lambda k, e: (k, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1) + recv[0].shape[2:],
                         lambda k, e: (e, k, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1) + recv[1].shape[2:],
                         lambda k, e: (e, k, 0),
                         memory_space=pltpu.VMEM),
        ]
    else:
        in_specs = [
            pl.BlockSpec((1, c), lambda k, e: (k, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, c), lambda k, e: (e, k, 0),
                         memory_space=pltpu.VMEM),
        ]
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(acc_chunks.shape,
                                       acc_chunks.dtype),
        grid=(nb, ne),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, c), lambda k, e: (k, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(acc_chunks, *recv)


def gossip_edge_wait(handle: TransportHandle, acc, weight=None):
    """Land a started transport: ``acc + Σ_e w·decode(recv[e])`` as one
    local pallas_call over the handle's chunks × edges.

    Purely local — no axis name, no barrier, no collective_id: the
    remote transfers completed inside :func:`gossip_edge_start`; this
    op owns the HBM→VMEM pull, the in-VMEM decode and the mixing axpy.
    ``weight`` is the receive-side axpy scalar; the column-stochastic
    round bakes the mixing weight into the sender multiply, so the
    default ``None`` (identity) is the production path."""
    kind, n, _rows, c, nb, ne, interpret = handle.meta
    if acc.size != n:
        raise ValueError(
            f"accumulator has {acc.size} elements but the transport "
            f"handle landed a {n}-element payload")
    acc_chunks = _pad_rows(acc.reshape(-1), nb * c).reshape(nb, c)
    out = _edge_wait_call(kind, interpret, acc_chunks, handle.recv, ne)
    out = out.reshape(-1)[:n].reshape(acc.shape)
    if weight is not None:
        out = acc + (out - acc) * jnp.asarray(weight, acc.dtype)
    return out


def gossip_edge_axpy(acc, parts, dests, axis_name: str, spec,
                     interpret: bool = False,
                     chunk_elems: int = DEFAULT_CHUNK_ELEMS, weight=None,
                     collective_id: int = 0):
    """``acc + w·decode(permute(parts))`` — the fused spelling: a
    :func:`gossip_edge_start` immediately consumed by its
    :func:`gossip_edge_wait`.

    Drop-in replacement for the XLA seam
    ``acc + codec.decode(tuple(lax.ppermute(p, axis, pairs) for p in
    parts), like)`` inside :func:`..parallel.collectives._round_fn` —
    synchronous callers (and the parity suite) exercise exactly the two
    kernels the split overlap path runs, so one pin covers both.
    """
    if spec is not None and spec.kind in ("f32", "bf16"):
        # single-edge parts may be leaf-shaped (the f32 lane ships the
        # message as-is; bf16 encode keeps the leaf shape): flatten to
        # the stacked [E=1, n] transport convention
        parts = tuple(p.reshape(1, -1) for p in parts)
    handle = gossip_edge_start(parts, dests, axis_name, spec,
                               n_decoded=acc.size, interpret=interpret,
                               chunk_elems=chunk_elems,
                               collective_id=collective_id)
    return gossip_edge_wait(handle, acc, weight=weight)


# -- CI selftest (scripts/gossipkernel.py) ----------------------------------


def _selftest() -> int:
    """Interpret-mode kernel acceptance on the world-8 virtual CPU mesh:
    the fused spelling must match the XLA decode+axpy bit-for-bit on
    the f32 passthrough and to f32 tolerance on int8, including a
    chunked (multi-grid-step) payload with a ragged tail; the split
    start/wait pair must equal the fused spelling bit-for-bit; and one
    edge-folded (E=2) call must equal two sequential single-edge calls.
    """
    import sys

    from jax.sharding import PartitionSpec as P

    from ..parallel import wire
    from ..parallel.mesh import GOSSIP_AXIS, make_gossip_mesh

    world = 8
    if jax.device_count() < world:
        print(f"gossip-kernel selftest FAILED: needs {world} devices, "
              f"have {jax.device_count()} (run via "
              "scripts/gossipkernel.py)", file=sys.stderr)
        return 1
    failures: list[str] = []
    mesh = make_gossip_mesh(world)
    dests = np.asarray([(r + 1) % world for r in range(world)])
    dests2 = np.asarray([(r + 3) % world for r in range(world)])
    rng = np.random.default_rng(0)
    # ragged: 3 chunks at chunk_elems=128 with a 44-element tail
    n = 300
    x = rng.normal(size=(world, n)).astype(np.float32)
    codec = wire.Int8Codec(64)

    def both_lanes(xr):
        xr = xr.reshape(-1)
        acc = xr * 0.25
        pairs = [(s, int(dests[s])) for s in range(world)]
        # f32 passthrough lane
        k_f32 = gossip_edge_axpy(acc, (xr,), dests, GOSSIP_AXIS,
                                 wire.F32.kernel_spec(), interpret=True,
                                 chunk_elems=128)
        x_f32 = acc + jax.lax.ppermute(xr, GOSSIP_AXIS, pairs)
        # int8 lane (shared encode, in-kernel vs XLA decode)
        parts = codec.encode(xr)
        k_i8 = gossip_edge_axpy(acc, parts, dests, GOSSIP_AXIS,
                                codec.kernel_spec(), interpret=True,
                                chunk_elems=128)
        x_i8 = acc + codec.decode(
            tuple(jax.lax.ppermute(p, GOSSIP_AXIS, pairs)
                  for p in parts), xr)
        # split lane: start at the "top", wait at the "bottom" — must
        # equal the fused spelling bit-for-bit (it IS the same pair of
        # kernels, handed off through the TransportHandle)
        h = gossip_edge_start((xr,), dests, GOSSIP_AXIS,
                              wire.F32.kernel_spec(), n_decoded=n,
                              interpret=True, chunk_elems=128,
                              collective_id=1)
        s_f32 = gossip_edge_wait(h, acc)
        # bucketed/edge-folded lane: ONE kernel program serving two
        # edges vs two sequential single-edge calls
        stacked = jnp.stack([xr, xr * 0.5])
        h2 = gossip_edge_start((stacked,), np.stack([dests, dests2]),
                               GOSSIP_AXIS, wire.F32.kernel_spec(),
                               n_decoded=n, interpret=True,
                               chunk_elems=128, collective_id=2)
        folded = gossip_edge_wait(h2, acc)
        seq = gossip_edge_axpy(acc, (xr,), dests, GOSSIP_AXIS,
                               wire.F32.kernel_spec(), interpret=True,
                               chunk_elems=128, collective_id=3)
        seq = gossip_edge_axpy(seq, (xr * 0.5,), dests2, GOSSIP_AXIS,
                               wire.F32.kernel_spec(), interpret=True,
                               chunk_elems=128, collective_id=4)
        return tuple(t[None] for t in (k_f32, x_f32, k_i8, x_i8,
                                       s_f32, folded, seq))

    fn = jax.jit(jax.shard_map(both_lanes, mesh=mesh,
                               in_specs=P(GOSSIP_AXIS),
                               out_specs=(P(GOSSIP_AXIS),) * 7))
    k_f32, x_f32, k_i8, x_i8, s_f32, folded, seq = map(
        np.asarray, jax.block_until_ready(fn(x)))
    if not np.array_equal(k_f32, x_f32):
        failures.append(
            f"f32 passthrough lane diverged from XLA ppermute "
            f"(max |d| {np.abs(k_f32 - x_f32).max():.2e}); the fused "
            "transport must be bit-identical")
    d8 = np.abs(k_i8 - x_i8).max()
    if d8 > 1e-6:
        failures.append(
            f"int8 in-kernel dequant drifted {d8:.2e} from the XLA "
            "decode (same scales, same op order — should be aligned)")
    if not np.array_equal(s_f32, k_f32):
        failures.append(
            "split start/wait diverged from the fused spelling (max |d| "
            f"{np.abs(s_f32 - k_f32).max():.2e}); the handle hand-off "
            "must be a pure re-association of the same two kernels")
    d_fold = np.abs(folded - seq).max()
    if d_fold > 1e-6:
        failures.append(
            f"edge-folded (E=2) call drifted {d_fold:.2e} from two "
            "sequential single-edge calls — the fold must accumulate "
            "edges in order")
    # a zero handle lands a zero contribution (the thinning skip branch)
    zero_h = empty_transport_handle(codec.kernel_spec(), n, 1,
                                    interpret=True, chunk_elems=128)
    z = np.asarray(gossip_edge_wait(zero_h, jnp.asarray(x[0])))
    if not np.array_equal(z, x[0]):
        failures.append("waiting an empty_transport_handle must be the "
                        "identity on the accumulator")
    # resolver contract: typed rejection instead of a Mosaic crash
    try:
        resolve_gossip_kernel("pallas", interpret=False)
        if jax.default_backend() != "tpu":
            failures.append("resolve_gossip_kernel('pallas') on a "
                            "non-TPU backend did not raise")
    except KernelBackendError:
        pass
    if resolve_gossip_kernel("auto", interpret=True) is None:
        failures.append("auto+interpret must resolve to the kernel lane")
    if resolve_gossip_kernel("xla") is not None:
        failures.append("'xla' must resolve to the ppermute lane")

    if failures:
        for f in failures:
            print(f"gossip-kernel selftest FAILED: {f}", file=sys.stderr)
        return 1
    print(f"gossip-kernel selftest: OK (world {world}, payload {n} over "
          f"3 chunks: f32 lane bit-identical, int8 lane max |d| "
          f"{d8:.1e}; split start/wait == fused, E=2 fold == sequential "
          f"(|d| {d_fold:.1e}), zero-handle wait is identity; "
          "pallas-on-cpu rejected with a typed error)")
    return 0


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="gossipkernel",
        description="Split Pallas gossip transport: CI selftest")
    ap.add_argument("--selftest", action="store_true",
                    help="run the interpret-mode kernel self-check")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    ap.error("choose --selftest")
    return 2
