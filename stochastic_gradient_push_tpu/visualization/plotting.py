"""Post-hoc log parsing and paper-style figures.

Port of ``visualization/plotting.py`` (reference :26-362): parses the
per-rank CSV logs the trainer emits (identical schema, so logs from either
implementation parse here) and produces the paper's figure families —
train/val error vs wall-clock time, time-per-iteration scaling across node
counts, and transformer NLL curves from fairseq-style logs.

Matplotlib is imported lazily with the Agg backend so the module works on
headless TPU hosts.
"""

from __future__ import annotations

import os
import re

import pandas as pd

__all__ = ["parse_csv", "parse_epochs", "parse_lm_csv",
           "parse_transformer_out", "plot_error_vs_time", "plot_itrs",
           "plot_lm", "plot_scaling", "plot_transformer",
           "ITERATIONS_PER_EPOCH"]

# iterations per epoch at batch 256/node on ImageNet
# (≙ plotting.py:196-197)
ITERATIONS_PER_EPOCH = {4: 1251, 8: 625, 16: 312, 32: 156}


def _plt():
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    return plt


def parse_csv(fpath: str) -> tuple[pd.DataFrame, pd.DataFrame]:
    """Parse one rank's training CSV into (train_rows, val_rows).

    ≙ plotting.py:195-228: skips the 4 preamble lines, splits on the
    ``itr == -1`` validation marker rows, and reconstructs elapsed time from
    the cumulative batch-time average.
    """
    df = pd.read_csv(fpath, skiprows=4)
    df.columns = [c.strip() for c in df.columns]
    val = df[df["itr"] == -1].copy()
    train = df[df["itr"] != -1].copy()
    # elapsed wall-clock estimate: cumulative mean batch time × global
    # iteration number (the itr column is sampled every print_freq rows, so
    # use the logged iteration numbers, not row indices)
    itr_per_epoch = train["itr"].max() + 1
    train["elapsed"] = train["avg:BT(s)"] * (
        train["Epoch"] * itr_per_epoch + train["itr"] + 1)
    return train, val


def parse_epochs(directory: str, world_size: int,
                 tag: str = "") -> pd.DataFrame:
    """Per-epoch cross-rank summary for the error-vs-time figures
    (≙ plotting.py:195-228 ``parse_csv``): one row per epoch with

    - ``train_mean``: 100 − mean over ranks of the end-of-epoch
      ``avg:Prec@1`` (the epoch's cumulative training accuracy),
    - ``val_mean``: mean over ranks of the validation rows' top-1 error,
    - ``time``: elapsed seconds — epoch-end global iteration × the mean
      cumulative batch time (the reference's estimate, plotting.py:226),
    - ``itr``: cumulative iteration count at each epoch end.
    """
    frames, itr_per_epoch = [], 0
    for f in _gather_rank_files(directory, world_size, tag):
        train, val = parse_csv(f)
        # last logged row of each epoch carries the cumulative epoch stats
        ends = train.groupby("Epoch").tail(1).set_index("Epoch")
        frame = pd.DataFrame({"train_mean": 100 - ends["avg:Prec@1"],
                              "time_mean": ends["avg:BT(s)"]})
        if len(val):
            # align on Epoch, not position: a run killed mid-epoch has an
            # epoch-end train row without a matching validation row
            frame["val_mean"] = 100 - val.set_index("Epoch")["val"]
        frames.append(frame)
        itr_per_epoch = max(itr_per_epoch, train["itr"].max() + 1)
    if not frames:
        raise FileNotFoundError(
            f"no {tag}out_r*_n{world_size}.csv under {directory}")
    # cross-rank mean per epoch (NaN-skipping, so ranks with fewer logged
    # epochs or missing validation rows average over what exists)
    pdf = pd.concat(frames).groupby(level=0).mean()
    pdf["itr"] = (pdf.index + 1) * itr_per_epoch
    pdf["time"] = pdf["itr"] * pdf["time_mean"].iloc[-1]
    return pdf.reset_index()


def plot_error_vs_time(runs: dict[str, str], world_size: int,
                       tag: str = "", val: bool = False,
                       out_path: str | None = None):
    """The paper's headline figure: train (or validation) error against
    elapsed wall-clock seconds, mean across ranks, one curve per labelled
    run directory (≙ plotting.py:255-292 ``plot_itrs`` with
    ``x='time'``)."""
    plt = _plt()
    fig, ax = plt.subplots(figsize=(6, 4))
    col = "val_mean" if val else "train_mean"
    for label, directory in runs.items():
        pdf = parse_epochs(directory, world_size, tag)
        if col not in pdf:
            continue
        ax.plot(pdf["time"], pdf[col], "o-", label=label)
    ax.set_xlabel("Time (s)")
    ax.set_ylabel(("Validation" if val else "Training") + " Error (%)")
    ax.grid(which="both", alpha=0.4)
    ax.legend()
    fig.tight_layout()
    if out_path:
        fig.savefig(out_path, dpi=120, bbox_inches="tight")
    return fig


def _gather_rank_files(directory: str, world_size: int,
                       tag: str = "") -> list[str]:
    files = []
    for rank in range(world_size):
        f = os.path.join(directory, f"{tag}out_r{rank}_n{world_size}.csv")
        if os.path.isfile(f):
            files.append(f)
    return files


def plot_itrs(directory: str, world_size: int, tag: str = "",
              out_path: str | None = None, metric: str = "avg:Loss"):
    """Training metric vs iteration for every rank (≙ plotting.py:255-292)."""
    plt = _plt()
    fig, ax = plt.subplots(figsize=(8, 5))
    for f in _gather_rank_files(directory, world_size, tag):
        train, _ = parse_csv(f)
        rank = re.search(r"out_r(\d+)_", f).group(1)
        x = train["Epoch"] * (train["itr"].max() + 1) + train["itr"]
        ax.plot(x, train[metric], alpha=0.6, label=f"rank {rank}")
    ax.set_xlabel("iteration")
    ax.set_ylabel(metric)
    ax.legend(fontsize=7, ncol=4)
    if out_path:
        fig.savefig(out_path, dpi=120, bbox_inches="tight")
    return fig


def plot_scaling(results: dict[int, float], baseline: dict[int, float]
                 | None = None, out_path: str | None = None,
                 ylabel: str = "time per iteration (s)"):
    """Time-per-iteration across node counts (≙ plotting.py:295-343).

    ``results``/``baseline``: {num_nodes: time_per_itr}.
    """
    plt = _plt()
    fig, ax = plt.subplots(figsize=(6, 4))
    nodes = sorted(results)
    ax.plot(nodes, [results[n] for n in nodes], "o-", label="SGP")
    if baseline:
        bn = sorted(baseline)
        ax.plot(bn, [baseline[n] for n in bn], "s--", label="AR")
    ax.set_xscale("log", base=2)
    ax.set_xticks(nodes)
    ax.set_xticklabels(nodes)
    ax.set_xlabel("nodes")
    ax.set_ylabel(ylabel)
    ax.legend()
    if out_path:
        fig.savefig(out_path, dpi=120, bbox_inches="tight")
    return fig


_TRANSFORMER_RE = re.compile(
    r"epoch (\d+).*?loss ([\d.]+).*?wall ([\d.]+)")


def parse_transformer_out(fpath: str) -> pd.DataFrame:
    """Parse fairseq-style transformer logs (≙ plotting.py:137-192):
    extracts (epoch, loss, wall) triples from train-summary lines."""
    rows = []
    with open(fpath) as f:
        for line in f:
            m = _TRANSFORMER_RE.search(line)
            if m:
                rows.append({"epoch": int(m.group(1)),
                             "loss": float(m.group(2)),
                             "wall": float(m.group(3))})
    return pd.DataFrame(rows)


def plot_transformer(fpaths: dict[str, str], out_path: str | None = None):
    """NLL vs wall-clock for labelled transformer runs
    (≙ plotting.py:231-252)."""
    plt = _plt()
    fig, ax = plt.subplots(figsize=(6, 4))
    for label, fpath in fpaths.items():
        df = parse_transformer_out(fpath)
        if len(df):
            ax.plot(df["wall"] / 3600.0, df["loss"], label=label)
    ax.set_xlabel("wall time (h)")
    ax.set_ylabel("NLL")
    ax.legend()
    if out_path:
        fig.savefig(out_path, dpi=120, bbox_inches="tight")
    return fig


def parse_lm_csv(fpath: str) -> "pd.DataFrame":
    """Parse an LM harness CSV (run/gossip_lm.py: header
    ``step,loss,ppl,lr,tokens_per_sec,grad_norm[,moe_dropped]
    [,val_loss,val_ppl]``).

    The reference had no in-repo LM harness (its transformer runs lived in
    an external fairseq fork, parsed by :func:`parse_transformer_out`);
    this parses the native LM family's logs instead.  Validation columns,
    when present, are populated only on validation rows.
    """
    df = pd.read_csv(fpath)
    df.columns = [c.strip() for c in df.columns]
    return df


def plot_lm(fpaths: dict[str, str], out_path: str | None = None,
            x: str = "step"):
    """Train (and, when logged, validation) loss curves for labelled LM
    runs — the in-repo counterpart of :func:`plot_transformer`."""
    plt = _plt()
    fig, ax = plt.subplots(figsize=(6, 4))
    for label, fpath in fpaths.items():
        df = parse_lm_csv(fpath)
        if not len(df):
            continue
        ax.plot(df[x], df["loss"], label=label)
        if "val_loss" in df.columns:
            val = df.dropna(subset=["val_loss"])
            if len(val):
                ax.plot(val[x], val["val_loss"], linestyle="--",
                        label=f"{label} (val)")
    ax.set_xlabel(x)
    ax.set_ylabel("loss (nats/token)")
    ax.legend()
    if out_path:
        fig.savefig(out_path, dpi=120, bbox_inches="tight")
    return fig
