"""Log parsing and figure generation."""

from .plotting import (
    ITERATIONS_PER_EPOCH,
    parse_csv,
    parse_epochs,
    parse_lm_csv,
    parse_transformer_out,
    plot_error_vs_time,
    plot_itrs,
    plot_lm,
    plot_scaling,
    plot_transformer,
)

__all__ = [
    "ITERATIONS_PER_EPOCH",
    "parse_csv",
    "parse_epochs",
    "parse_lm_csv",
    "parse_transformer_out",
    "plot_error_vs_time",
    "plot_itrs",
    "plot_lm",
    "plot_scaling",
    "plot_transformer",
]
