"""Compatibility shims for the span of JAX releases this package runs on.

The code targets the modern JAX surface (``jax.shard_map``,
``lax.axis_size``, ``lax.pvary``/``lax.pcast``); older releases (0.4.x)
spell these ``jax.experimental.shard_map.shard_map`` (with ``auto=`` instead
of ``axis_names=`` and ``check_rep=`` instead of vma tracking) or lack them
entirely.  :func:`ensure_jax_compat` installs the missing aliases once, at
package import, so every module and test can use the modern names
unconditionally.

Shim semantics on 0.4.x:

* ``jax.shard_map(f, mesh=, in_specs=, out_specs=, axis_names=)`` — the
  ``axis_names`` manual set is translated to its complement ``auto=`` set;
  replication checking is disabled (``check_rep=False``) because the vma
  rules the code is written against do not exist, and the old rep analysis
  rejects valid programs that rely on them.
* ``lax.axis_size(name)`` — ``lax.psum(1, name)``, which constant-folds to
  the static axis size inside ``shard_map``.
* ``jax.typeof(x)`` — the raw aval; it has no ``vma`` attribute, which
  callers already treat as "no varying axes tracked".
* ``lax.pvary`` / ``lax.pcast(..., to="varying")`` — identity.  Without vma
  tracking every value is already implicitly varying, so marking is a no-op.
"""

from __future__ import annotations

import functools

__all__ = ["ensure_jax_compat"]

_INSTALLED = False


def ensure_jax_compat() -> None:
    """Install modern-JAX aliases on older releases (idempotent)."""
    global _INSTALLED
    if _INSTALLED:
        return
    _INSTALLED = True

    import jax
    from jax import lax

    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        @functools.wraps(_shard_map)
        def shard_map_compat(f, *, mesh, in_specs, out_specs,
                             axis_names=None, check_vma=None, **kwargs):
            if axis_names is not None:
                auto = frozenset(mesh.axis_names) - frozenset(axis_names)
                if auto:
                    kwargs["auto"] = auto
            kwargs.setdefault("check_rep", False)
            return _shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kwargs)

        jax.shard_map = shard_map_compat

    if not hasattr(jax, "typeof"):
        def typeof(x):
            return jax.core.get_aval(x)

        jax.typeof = typeof

    if not hasattr(lax, "axis_size"):
        def axis_size(axis_name):
            return lax.psum(1, axis_name)

        lax.axis_size = axis_size

    if not hasattr(lax, "pvary"):
        def pvary(x, axis_names):
            return x

        lax.pvary = pvary

    if not hasattr(lax, "pcast"):
        def pcast(x, axis_names, *, to):
            return x

        lax.pcast = pcast
