"""Small models for tests and smoke runs (SURVEY.md §7 minimum slice)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class TinyCNN(nn.Module):
    """A few conv blocks + dense head; CIFAR-sized inputs.

    Used by the end-to-end smoke tests the reference enables via
    ``--num_iterations_per_training_epoch`` (gossip_sgd.py:83-88) but never
    ships a model for.
    """

    num_classes: int = 10
    width: int = 16
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = jnp.asarray(x, self.dtype)
        for i in range(3):
            x = nn.Conv(self.width * 2 ** i, (3, 3), use_bias=False,
                        dtype=self.dtype)(x)
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     kernel_init=nn.initializers.normal(stddev=0.01))(x)
        return jnp.asarray(x, jnp.float32)


class TinyMLP(nn.Module):
    """Minimal MLP for the fastest possible distributed smoke tests."""

    num_classes: int = 10
    width: int = 32

    @nn.compact
    def __call__(self, x, train: bool = True):
        del train
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.width)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes)(x)
        return x
