"""Pipeline-stage transformer LM: one shard's slice of the layer stack.

Pairs with ``parallel/pipeline.py`` (the tick schedule) and ``train/pp.py``
(mesh/init/step).  Each pipe shard holds

* ``embed`` / ``ln_f`` / ``lm_head`` — replicated over the pipe axis; only
  stage 0 (embed) and the last stage (head) produce live outputs, and their
  gradients are shared with a ``psum`` in the train step;
* ``stack`` — ``n_local_layers`` transformer blocks stacked on a leading
  axis (``nn.scan``), *stage-local*: shard ``s`` holds layers
  ``[s·L/S, (s+1)·L/S)``.  Globally the stacked leaf is sharded over the
  pipe axis, so a gathered checkpoint holds the full ``L``-layer model.

The block itself is the shared ``_Block`` from models/transformer.py —
pipeline parallelism changes the layout, not the math.  Ring attention
composes (pp × sp): the tick's ppermute moves activations over ``pipe``
while each block's ring rotation moves KV over ``seq`` — different manual
axes, both uniform collectives inside the scanned tick body, so they
nest cleanly (tests/test_pipeline.py pins parity with the stacked ring
model).  MoE composes too (``moe_every=1`` so the scanned stack stays
uniform; tokens route per microbatch inside the ticks) — replicated
experts, expert-sharded dispatch over an ``ep`` axis (the all_to_all is
uniform across ticks), per-block routing under ``seq`` sharding, and
the full 4-D pp × ep × sp mesh.  The only constraint left is
structural: MoE requires ``moe_every=1`` (composition matrix,
ARCHITECTURE.md).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from .transformer import TransformerConfig, _Block

__all__ = ["PipelineStageLM"]


class _ScanBlock(nn.Module):
    """Carry-style wrapper so ``nn.scan`` stacks block params on axis 0."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions):
        use_moe = self.cfg.moe_experts > 0
        return _Block(self.cfg, use_moe=use_moe,
                      name="block")(x, positions), None


class PipelineStageLM(nn.Module):
    """One pipeline stage of a decoder-only LM.

    ``n_local_layers`` is ``cfg.n_layers // n_stages`` — the model object
    never references the mesh; stage identity comes entirely from which
    parameter values the shard holds (train/pp.py initializes each shard's
    stack with a pipe-index-folded RNG).
    """

    cfg: TransformerConfig
    n_local_layers: int

    def setup(self):
        cfg = self.cfg
        if cfg.moe_experts > 0 and cfg.moe_every != 1:
            raise ValueError(
                "MoE × pipeline requires moe_every=1: the stage stack is "
                "one uniform nn.scan, so every layer must share the block "
                "structure — see ARCHITECTURE.md composition matrix")
        self.embed = nn.Embed(cfg.vocab_size, cfg.d_model,
                              embedding_init=nn.initializers.normal(0.02),
                              dtype=cfg.dtype)
        target = _ScanBlock
        if cfg.remat:
            target = nn.remat(target, prevent_cse=False)
        # sown MoE collections ("losses"/"moe_metrics") stack per-layer on
        # axis 0 like the params; harmless when nothing is sown
        self.stack = nn.scan(
            target,
            variable_axes={"params": 0, "losses": 0, "moe_metrics": 0},
            split_rngs={"params": True},
            in_axes=nn.broadcast,
            length=self.n_local_layers)(cfg)
        self.ln_f = nn.LayerNorm(dtype=jnp.float32)
        self.lm_head = nn.Dense(cfg.vocab_size, use_bias=False,
                                dtype=cfg.dtype)

    def embed_tokens(self, tokens: jnp.ndarray) -> jnp.ndarray:
        """``[..., T] -> [..., T, D]`` — applied to all microbatches."""
        return self.embed(tokens)

    def blocks(self, x: jnp.ndarray, positions: jnp.ndarray) -> jnp.ndarray:
        """This stage's slice of the layer stack (the pipeline tick body)."""
        x, _ = self.stack(x, positions)
        return x

    def head(self, x: jnp.ndarray) -> jnp.ndarray:
        """Final LN + logits in fp32."""
        return jnp.asarray(self.lm_head(self.ln_f(x)), jnp.float32)

    def __call__(self, tokens: jnp.ndarray, train: bool = True):
        """Init/reference path: embed → local stack → head.

        This is NOT the pipelined forward (that lives in train/pp.py —
        it interleaves ``blocks`` with ``ppermute``); calling it exercises
        every parameter group once so ``init`` builds the full tree.
        """
        del train
        tokens = tokens.reshape(-1, tokens.shape[-1])  # merge microbatch dims
        positions = jnp.arange(tokens.shape[-1])
        if self.cfg.seq_axis is not None:
            # ring attention: this shard holds one contiguous block; its
            # global positions start at the block offset
            from jax import lax
            positions = positions + lax.axis_index(
                self.cfg.seq_axis) * tokens.shape[-1]
        x = self.embed_tokens(tokens)
        x = self.blocks(x, positions)
        return self.head(x)
