"""ResNet family in flax, initialized per the reference recipe.

The reference trains torchvision's ResNet-50 with the "ImageNet in 1hr"
initialization (gossip_sgd.py:693-707):

* batch-norm EMA decay 0.9
* final fully-connected weights ~ N(0, 0.01)
* the last batch-norm (gamma) of every residual bottleneck zero-initialized

This implementation is TPU-first rather than a torchvision translation:
NHWC layout (XLA's native convolution layout on TPU), optional bfloat16
compute with float32 parameters and batch statistics, and compiler-friendly
static shapes throughout.  Structure matches torchvision's
resnet{18,34,50,101,152} so parameter counts and accuracy recipes carry over.
"""

from __future__ import annotations

import typing as tp
from functools import partial

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152", "RESNETS", "space_to_depth", "s2d_stem_kernel",
           "ProbeBatchNorm"]


def space_to_depth(x: jnp.ndarray, block: int = 2) -> jnp.ndarray:
    """NHWC space-to-depth: pack each ``block x block`` spatial tile into
    channels — ``[N, H, W, C] -> [N, H/b, W/b, b*b*C]`` with (dy, dx, c)
    packing order (matched by :func:`s2d_stem_kernel`)."""
    n, h, w, c = x.shape
    if h % block or w % block:
        raise ValueError(
            f"stem_s2d requires spatial dims divisible by {block}, got "
            f"{h}x{w} — use the standard stem for odd image sizes")
    x = x.reshape(n, h // block, block, w // block, block, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        n, h // block, w // block, block * block * c)


def s2d_stem_kernel(k7: jnp.ndarray) -> jnp.ndarray:
    """Transform the standard ``[7, 7, C, F]`` stride-2 stem kernel into
    the mathematically equivalent ``[4, 4, 4C, F]`` stride-1 kernel over
    space-to-depth input (the MLPerf TPU ResNet trick).

    Derivation: ``out[i] = Σ_u k[u] x[2i - 3 + u]``.  Zero-padding the
    kernel at the FRONT to 8 taps gives ``out[i] = Σ_u k8[u] x[2i-4+u]``
    — a 4-tap convolution over 2-pixel blocks at stride 1 with block-space
    padding (2, 1).  The 7x7 stem's skinny 147-deep contraction becomes a
    dense 192-deep one, which tiles the 128x128 MXU far better than the
    strided original.
    """
    kh, kw, c, f = k7.shape
    assert kh == 7 and kw == 7, "stem transform is specific to 7x7/2"
    k8 = jnp.pad(k7, ((1, 0), (1, 0), (0, 0), (0, 0)))
    # [8, 8, C, F] -> [4, dy, 4, dx, C, F] -> [4, 4, dy, dx, C, F]
    k4 = k8.reshape(4, 2, 4, 2, c, f).transpose(0, 2, 1, 3, 4, 5)
    return k4.reshape(4, 4, 4 * c, f)

ModuleDef = tp.Any


class ProbeBatchNorm(nn.Module):
    """BatchNorm with the two MFU-experiment knobs docs/MFU_ANALYSIS.md
    names for the bandwidth-bound backward phase:

    * ``stats_dtype=bfloat16`` — compute the batch mean/variance
      reductions in the compute dtype instead of flax's always-float32
      promotion, halving the statistics' HBM read traffic and removing
      the fp32 materialization between conv fusions.  The running-stat
      EMA stays float32.
    * ``frozen=True`` — normalize with the *running* statistics even in
      training (per-channel affine only: no batch reductions forward, no
      statistics term backward).  Not a training configuration — it is
      the BN-*folded* benchmark variant whose step-time delta ATTRIBUTES
      the cost of BN's reduction passes.

    Per-layer variables ("scale"/"bias" params, "mean"/"var"
    batch_stats, float32) match ``nn.BatchNorm``, so train-state
    plumbing and replication are unchanged; the ``frozen`` mode
    self-assigns the running stats so the ``batch_stats`` collection is
    still mutated and the train step's state threading (train/step.py)
    needs no special case.  Flax auto-names embed the class name
    (``ProbeBatchNorm_0`` vs ``BatchNorm_0``), so checkpoints do NOT
    interchange across ``norm_variant`` — same caveat as ``stem_s2d``.
    """

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: tp.Any = jnp.float32
    stats_dtype: tp.Any = None  # None -> float32 (flax semantics)
    frozen: bool = False
    scale_init: tp.Callable = nn.initializers.ones
    bias_init: tp.Callable = nn.initializers.zeros

    @nn.compact
    def __call__(self, x):
        feat = x.shape[-1]
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((feat,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((feat,), jnp.float32))
        scale = self.param("scale", self.scale_init, (feat,), jnp.float32)
        bias = self.param("bias", self.bias_init, (feat,), jnp.float32)

        if self.use_running_average or self.frozen:
            mean, var = ra_mean.value, ra_var.value
            if self.frozen and not self.use_running_average \
                    and not self.is_initializing():
                ra_mean.value = ra_mean.value  # keep collection mutated
                ra_var.value = ra_var.value
        else:
            sdt = self.stats_dtype or jnp.float32
            xs = x.astype(sdt)
            axes = tuple(range(x.ndim - 1))
            mean = jnp.mean(xs, axes)
            # fast variance (E[x^2] - E[x]^2), as flax's default; the
            # cancellation can go NEGATIVE in bf16 (8-bit mantissa), and
            # rsqrt of a negative is NaN — clamp
            var = jnp.maximum(
                jnp.mean(jnp.square(xs), axes) - jnp.square(mean), 0)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = (m * ra_mean.value
                                 + (1 - m) * mean.astype(jnp.float32))
                ra_var.value = (m * ra_var.value
                                + (1 - m) * var.astype(jnp.float32))
        cdt = self.dtype
        inv = lax.rsqrt(var.astype(cdt) + jnp.asarray(
            self.epsilon, cdt)) * scale.astype(cdt)
        return (x.astype(cdt) - mean.astype(cdt)) * inv + bias.astype(cdt)


class BasicBlock(nn.Module):
    """Two 3x3 convs (resnet18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: tp.Callable
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        # NOTE: the reference zero-inits gamma only in Bottleneck blocks
        # (isinstance check, gossip_sgd.py:701-704); BasicBlock keeps 1s
        y = self.norm()(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class Bottleneck(nn.Module):
    """1x1 → 3x3 → 1x1 with 4x expansion (resnet50/101/152)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: tp.Callable
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # zero-init gamma on bn3 (gossip_sgd.py:701-704)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """ImageNet-style ResNet, NHWC, bf16-compute friendly.

    Args:
      stage_sizes: blocks per stage, e.g. ``[3, 4, 6, 3]`` for resnet50.
      block_cls: :class:`BasicBlock` or :class:`Bottleneck`.
      num_classes: classifier width (1000 for ImageNet).
      num_filters: stem width.
      dtype: compute dtype (params and BN stats stay float32).
      bn_momentum: EMA decay of batch statistics — 0.9 per the reference
        (gossip_sgd.py:695-697), not flax's 0.99 default.
      small_images: CIFAR-style stem (3x3/1 conv, no max-pool) for tests.
    """

    stage_sizes: tp.Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: tp.Any = jnp.float32
    bn_momentum: float = 0.9
    small_images: bool = False
    # space-to-depth stem (MLPerf TPU trick): mathematically equivalent
    # 4x4/1 conv over 2x2-packed input in place of the 7x7/2 stem; the
    # stem kernel is drawn as 7x7 with the reference init then
    # transformed, so the init DISTRIBUTION matches exactly.  Changes the
    # stem parameter shape — checkpoints don't interchange across the
    # flag (expected: it is an architecture-layout choice).
    stem_s2d: bool = False
    # MFU-experiment norm variants (docs/MFU_ANALYSIS.md): "bn" is flax
    # BatchNorm (fp32 stats); "bn16" computes batch stats in the compute
    # dtype (ProbeBatchNorm stats_dtype); "folded" normalizes with the
    # running stats even in training — a benchmark-only variant that
    # attributes BN's reduction cost, NOT a training configuration.
    norm_variant: str = "bn"

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       kernel_init=nn.initializers.variance_scaling(
                           2.0, "fan_out", "normal"))
        if self.norm_variant == "bn":
            norm = partial(nn.BatchNorm, use_running_average=not train,
                           momentum=self.bn_momentum, epsilon=1e-5,
                           dtype=self.dtype)
        elif self.norm_variant == "bn16":
            norm = partial(ProbeBatchNorm, use_running_average=not train,
                           momentum=self.bn_momentum, epsilon=1e-5,
                           dtype=self.dtype, stats_dtype=self.dtype)
        elif self.norm_variant == "folded":
            norm = partial(ProbeBatchNorm, use_running_average=not train,
                           momentum=self.bn_momentum, epsilon=1e-5,
                           dtype=self.dtype, frozen=True)
        else:
            raise ValueError(f"unknown norm_variant {self.norm_variant!r}")

        x = jnp.asarray(x, self.dtype)
        if self.small_images:
            x = conv(self.num_filters, (3, 3), name="conv_init")(x)
        elif self.stem_s2d:
            def s2d_init(key, shape, dtype=jnp.float32):
                c = shape[2] // 4
                base = nn.initializers.variance_scaling(
                    2.0, "fan_out", "normal")(key, (7, 7, c, shape[3]),
                                              dtype)
                return s2d_stem_kernel(base)

            x = space_to_depth(x, 2)
            x = nn.Conv(self.num_filters, (4, 4), (1, 1),
                        padding=[(2, 1), (2, 1)], use_bias=False,
                        dtype=self.dtype, kernel_init=s2d_init,
                        name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2),
                     padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        if not self.small_images:
            x = nn.max_pool(x, (3, 3), strides=(2, 2),
                            padding=[(1, 1), (1, 1)])
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i,
                                   strides=strides, conv=conv, norm=norm,
                                   act=nn.relu)(x)
        x = jnp.mean(x, axis=(1, 2))
        # fc ~ N(0, 0.01) (gossip_sgd.py:705)
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     kernel_init=nn.initializers.normal(stddev=0.01),
                     name="fc")(x)
        return jnp.asarray(x, jnp.float32)


resnet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
resnet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
resnet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=Bottleneck)
resnet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=Bottleneck)
resnet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=Bottleneck)

RESNETS = {
    "resnet18": resnet18,
    "resnet34": resnet34,
    "resnet50": resnet50,
    "resnet101": resnet101,
    "resnet152": resnet152,
}
