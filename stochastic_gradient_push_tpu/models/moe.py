"""Mixture-of-experts FFN with expert parallelism over a mesh axis.

Completes the parallelism alphabet (dp × sp × tp × **ep**): experts shard
over a manual ``ep`` mesh axis, tokens route top-1 (switch style) with a
capacity limit, and two ``lax.all_to_all`` collectives move token slots to
their experts' shards and back.  Each shard computes only its local experts
over only the tokens routed to them — the compute- and memory-efficient
formulation, not a masked dense mixture.

Functional layer (explicit weights) so it slots into the same
shard_map-based step structure as everything else:

    y, aux = switch_moe_ffn(x, router_w, w1, w2, ep_axis="ep")

``w1``/``w2`` carry the *local* expert slices (global ``[E, ...]`` arrays
sharded over ``ep`` via ``in_specs=P("ep")``).  With ``ep_axis=None`` the
same code runs single-shard with all experts — the numerical reference the
tests pin the sharded version against.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["switch_moe_ffn", "moe_capacity"]


def moe_capacity(num_tokens: int, num_experts: int,
                 capacity_factor: float = 1.25) -> int:
    """Per-expert token slots per source shard."""
    return max(1, int(num_tokens * capacity_factor / num_experts))


def switch_moe_ffn(x, router_w, w1, w2, ep_axis: str | None = None,
                   capacity_factor: float = 1.25):
    """Top-1 switch MoE feed-forward.

    Args:
      x: ``[T, D]`` tokens (this shard's tokens when ``ep_axis`` is set).
      router_w: ``[D, E]`` router weights (replicated; E = total experts).
      w1: ``[E_local, D, F]`` up-projections (local expert slice).
      w2: ``[E_local, F, D]`` down-projections.
      ep_axis: mesh axis experts are sharded over (None = single shard).
      capacity_factor: slots per expert = T·cf/E per source shard; tokens
        over capacity receive zero expert output — callers supply the
        residual connection that makes them pass through (standard switch
        usage).

    Returns ``(y [T, D], aux)`` where aux carries the load-balancing loss
    (Switch Transformer's fraction·probability dot product) and the
    fraction of dropped tokens.
    """
    t, d = x.shape
    e_local = w1.shape[0]
    ep = lax.axis_size(ep_axis) if ep_axis is not None else 1
    e_total = e_local * ep
    if router_w.shape[-1] != e_total:
        raise ValueError(
            f"router is over {router_w.shape[-1]} experts but weights "
            f"provide {e_total} ({e_local} × {ep} shards)")
    cap = moe_capacity(t, e_total, capacity_factor)

    logits = x @ router_w                                    # [T, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                  # [T]
    top_prob = jnp.take_along_axis(
        probs, expert_idx[:, None], axis=-1)[:, 0]           # [T]

    onehot = jax.nn.one_hot(expert_idx, e_total,
                            dtype=jnp.float32)               # [T, E]
    # position of each token within its chosen expert's queue
    cum = jnp.cumsum(onehot.astype(jnp.int32), axis=0)       # [T, E]
    pos = jnp.take_along_axis(
        cum, expert_idx[:, None], axis=-1)[:, 0] - 1         # [T] int32
    kept = pos < cap
    # out-of-capacity tokens index slot == cap → one_hot gives all-zeros
    slot = jax.nn.one_hot(jnp.where(kept, pos, cap), cap,
                          dtype=jnp.float32)                 # [T, C]
    dispatch = onehot[:, :, None] * slot[:, None, :]         # [T, E, C]

    x_slots = jnp.einsum("tec,td->ecd", dispatch,
                         x.astype(jnp.float32))              # [E, C, D]

    if ep_axis is not None:
        # [E, C, D] → this shard's experts with every shard's slots:
        # [E_local, ep·C, D]
        x_slots = lax.all_to_all(x_slots, ep_axis, split_axis=0,
                                 concat_axis=1, tiled=True)

    h = jnp.einsum("ecd,edf->ecf", x_slots, w1.astype(jnp.float32))
    h = jax.nn.gelu(h)
    y_slots = jnp.einsum("ecf,efd->ecd", h, w2.astype(jnp.float32))

    if ep_axis is not None:
        y_slots = lax.all_to_all(y_slots, ep_axis, split_axis=1,
                                 concat_axis=0, tiled=True)  # [E, C, D]

    combine = dispatch * top_prob[:, None, None]             # [T, E, C]
    y = jnp.einsum("tec,ecd->td", combine, y_slots)

    # Switch load-balancing loss: E · Σ_e (token fraction)·(mean prob)
    frac = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = {
        "load_balance_loss": e_total * jnp.sum(frac * mean_prob),
        "dropped_fraction": 1.0 - jnp.mean(kept.astype(jnp.float32)),
    }
    return y.astype(x.dtype), aux
