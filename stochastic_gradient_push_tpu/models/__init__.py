"""Model zoo: ResNet family (flagship: resnet50) and small test models."""

from .resnet import (
    RESNETS,
    ResNet,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
)
from .moe import moe_capacity, switch_moe_ffn
from .small import TinyCNN, TinyMLP
from .pipeline import PipelineStageLM
from .transformer import TransformerConfig, TransformerLM

__all__ = [
    "ResNet",
    "RESNETS",
    "resnet18",
    "resnet34",
    "resnet50",
    "resnet101",
    "resnet152",
    "TinyCNN",
    "TinyMLP",
    "PipelineStageLM",
    "TransformerLM",
    "TransformerConfig",
    "switch_moe_ffn",
    "moe_capacity",
]
