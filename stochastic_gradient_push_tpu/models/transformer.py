"""Decoder-only transformer LM with optional ring-attention sequence
parallelism.

The reference's transformer experiments (WMT16, paper §5) ran in an external
fairseq fork — the repo itself ships only the log parser
(visualization/plotting.py:137-192).  This module makes the transformer a
first-class in-repo model family, built TPU-first:

* pre-norm blocks, bf16-friendly compute with fp32 LN/softmax
* rotary position embeddings (no learned position table to shard)
* attention backends: ``full`` (plain causal), ``blockwise``
  (O(block²) memory, single device), or ``ring`` — exact attention over a
  sequence-sharded mesh axis (parallel/ring_attention.py), with every rank
  holding ``seq/world`` tokens
* pointwise sublayers (embedding, LN, MLP, logits) act per-token, so under
  sequence sharding they need no communication at all
* optional switch-MoE feed-forward blocks with experts sharded over an
  ``ep`` mesh axis (models/moe.py): set ``moe_experts > 0`` and every
  ``moe_every``-th block routes tokens to experts via all_to_all
"""

from __future__ import annotations

import typing as tp

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from ..parallel.ring_attention import blockwise_attention, ring_attention

__all__ = ["TransformerLM", "TransformerConfig"]


def _rope(x: jnp.ndarray, positions: jnp.ndarray,
          base: float = 10000.0) -> jnp.ndarray:
    """Rotary embeddings. x: [B, H, T, D]; positions: [T] global indices."""
    d = x.shape[-1]
    half = d // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]
    cos = jnp.cos(angles)[None, None]      # [1,1,T,half]
    sin = jnp.sin(angles)[None, None]
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rotated.astype(x.dtype)


class TransformerConfig(tp.NamedTuple):
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 6
    n_heads: int = 8
    d_ff: int = 2048
    max_len: int = 2048
    dtype: tp.Any = jnp.float32
    attn_impl: str = "full"     # full | blockwise | flash | ring | ring_flash
    # block size for blockwise/flash/ring_flash; None = the measured
    # auto rule (ops.flash_attention.default_block) on the local length
    attn_block_size: int | None = None
    # flash only: a different K/V-side block (None = attn_block_size).
    # The fenced kernel sweep found asymmetric (bq 512, bk 256) best for
    # the t=1024 backward (docs/tpu_runs/20260731T071733_retry)
    attn_block_k: int | None = None
    seq_axis: str | None = None       # mesh axis for ring attention
    remat: bool = False               # jax.checkpoint each block
    moe_experts: int = 0              # total experts (0 = dense FFN)
    moe_every: int = 2                # every k-th block uses MoE
    ep_axis: str | None = None        # mesh axis experts shard over
    moe_capacity_factor: float = 1.25


class _Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        head_dim = cfg.d_model // cfg.n_heads
        dense = lambda name: nn.Dense(
            cfg.d_model, use_bias=False, dtype=cfg.dtype, name=name)
        q = dense("q")(x)
        k = dense("k")(x)
        v = dense("v")(x)

        def split(t):  # [B,T,E] → [B,H,T,D]
            b, s, _ = t.shape
            return t.reshape(b, s, cfg.n_heads, head_dim).transpose(
                0, 2, 1, 3)

        q, k, v = split(q), split(k), split(v)
        q = _rope(q, positions)
        k = _rope(k, positions)

        if cfg.attn_impl == "ring":
            if cfg.seq_axis is None:
                raise ValueError("ring attention requires seq_axis")
            out = ring_attention(q, k, v, cfg.seq_axis, causal=True)
        elif cfg.attn_impl == "ring_flash":
            # flash-kernel ticks: O(attn_block_size²) memory per device
            # regardless of shard length — the long-context production
            # path (ops/ring_flash.py)
            if cfg.seq_axis is None:
                raise ValueError("ring attention requires seq_axis")
            from ..ops.flash_attention import default_block
            from ..ops.ring_flash import ring_flash_attention
            out = ring_flash_attention(
                q, k, v, cfg.seq_axis, causal=True,
                block=cfg.attn_block_size or default_block(q.shape[2]))
        elif cfg.attn_impl == "flash":
            from ..ops.flash_attention import flash_attention
            out = flash_attention(
                q, k, v, causal=True,
                block_q=cfg.attn_block_size,
                block_k=cfg.attn_block_k or cfg.attn_block_size)
        elif cfg.attn_impl == "blockwise":
            out = blockwise_attention(
                q, k, v, min(cfg.attn_block_size or 128, q.shape[2]),
                causal=True)
        elif cfg.attn_impl == "full":
            t = q.shape[2]
            mask = jnp.tril(jnp.ones((t, t), bool))[None, None]
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                           preferred_element_type=jnp.float32)
            s = s * head_dim ** -0.5
            s = jnp.where(mask, s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            out = jnp.einsum("bhqk,bhkd->bhqd", p,
                             v.astype(jnp.float32)).astype(cfg.dtype)
        else:
            raise ValueError(f"unknown attn_impl {cfg.attn_impl}")

        b, h, s, d = out.shape
        out = out.transpose(0, 2, 1, 3).reshape(b, s, cfg.d_model)
        return nn.Dense(cfg.d_model, use_bias=False, dtype=cfg.dtype,
                        name="o")(out)


class _MoEFFN(nn.Module):
    """Switch-MoE feed-forward (models/moe.py) as a flax module.

    Expert weights carry the *local* slice when ``ep_axis`` is set — the
    state layout shards the expert dimension over ``ep`` (see
    ``train/lm.py::ep_state_specs``); the router is replicated.
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        from .moe import switch_moe_ffn

        cfg = self.cfg
        ep = 1
        if cfg.ep_axis is not None:
            ep = lax.axis_size(cfg.ep_axis)
        if cfg.moe_experts % ep:
            raise ValueError(
                f"moe_experts {cfg.moe_experts} not divisible by ep {ep}")
        e_local = cfg.moe_experts // ep
        router = self.param(
            "router", nn.initializers.normal(0.02),
            (cfg.d_model, cfg.moe_experts), jnp.float32)
        w1 = self.param("experts_up", nn.initializers.lecun_normal(),
                        (e_local, cfg.d_model, cfg.d_ff), jnp.float32)
        w2 = self.param("experts_down", nn.initializers.lecun_normal(),
                        (e_local, cfg.d_ff, cfg.d_model), jnp.float32)

        b, t, d = x.shape
        flat = x.reshape(b * t, d)
        y, aux = switch_moe_ffn(
            flat, router, w1, w2, ep_axis=cfg.ep_axis,
            capacity_factor=cfg.moe_capacity_factor)
        self.sow("losses", "load_balance", aux["load_balance_loss"])
        self.sow("moe_metrics", "dropped_fraction",
                 aux["dropped_fraction"])
        return y.reshape(b, t, d)


class _Block(nn.Module):
    cfg: TransformerConfig
    use_moe: bool = False

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        ln = lambda name: nn.LayerNorm(dtype=jnp.float32, name=name)
        x = x + _Attention(cfg, name="attn")(ln("ln1")(x), positions)
        h = ln("ln2")(x)
        if self.use_moe:
            # dropped (over-capacity) tokens contribute zero here and ride
            # the residual connection through unchanged
            return x + _MoEFFN(cfg, name="moe")(h)
        h = nn.Dense(cfg.d_ff, dtype=cfg.dtype, name="up")(h)
        h = nn.gelu(h)
        h = nn.Dense(cfg.d_model, dtype=cfg.dtype, name="down")(h)
        return x + h


class TransformerLM(nn.Module):
    """Causal LM.  ``__call__(tokens, train)`` → logits ``[B, T, vocab]``.

    Under sequence sharding (``attn_impl='ring'``), ``tokens`` is this
    rank's contiguous block and global positions are derived from the
    rank's position on the sequence axis.
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, train: bool = True):
        del train  # no dropout in the base recipe
        cfg = self.cfg
        if cfg.moe_experts > 0 and cfg.moe_every < 1:
            raise ValueError("moe_every must be >= 1 when moe_experts > 0")
        b, t = tokens.shape
        if cfg.attn_impl in ("ring", "ring_flash"):
            offset = lax.axis_index(cfg.seq_axis) * t
        else:
            offset = 0
        positions = offset + jnp.arange(t)

        x = nn.Embed(cfg.vocab_size, cfg.d_model,
                     embedding_init=nn.initializers.normal(0.02),
                     dtype=cfg.dtype, name="embed")(tokens)
        block = _Block
        if cfg.remat:
            block = nn.remat(_Block)
        for i in range(cfg.n_layers):
            use_moe = (cfg.moe_experts > 0
                       and i % cfg.moe_every == cfg.moe_every - 1)
            x = block(cfg, use_moe=use_moe, name=f"block_{i}")(x, positions)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=False,
                          dtype=cfg.dtype, name="lm_head")(x)
        return jnp.asarray(logits, jnp.float32)
