"""Streaming input pipeline: background decode for real datasets.

The eager loaders hold everything in host memory — fine for validation and
smoke runs, impossible for ImageNet training.  This loader streams: a
thread pool decodes/augments the next batches (PIL releases the GIL for
image decode) while the TPU computes, and the host never holds more than
``prefetch`` global batches.

Fills the role of the reference's ``torch.utils.data.DataLoader`` with
``num_workers`` forked decoders (gossip_sgd.py:563-567) — without the
torchvision dependency this image lacks — and yields world-stacked batches
``(world, batch, H, W, C)`` that the sharded train step consumes directly.
Same iteration contract as :class:`~.pipeline.ShardedLoader` (``len``,
``set_epoch``, ``fast_forward``) so the Trainer can use either.
"""

from __future__ import annotations

import concurrent.futures
import typing as tp

import numpy as np

from .imagefolder import ImageFolderDataset
from .pipeline import DistributedSampler

__all__ = ["StreamingImageFolder"]


class StreamingImageFolder:
    """World-stacked streaming loader over an ImageFolder directory."""

    def __init__(self, root: str, split: str, world_size: int,
                 batch_size: int, image_size: int = 224, train: bool = True,
                 num_workers: int = 8, prefetch: int = 4, seed: int = 0,
                 ranks: tp.Sequence[int] | None = None):
        self.dataset = ImageFolderDataset(
            f"{root}/{split}" if split else root,
            image_size=image_size, train=train, seed=seed)
        self.world_size = world_size
        self.batch_size = batch_size
        self.num_workers = max(num_workers, 1)
        self.prefetch = max(prefetch, 1)
        self.sampler = DistributedSampler(len(self.dataset), world_size)
        # multi-host: decode only this process's rank rows
        self.ranks = None if ranks is None else list(ranks)
        self.start_itr = 0

    @property
    def classes(self) -> list[str]:
        return self.dataset.classes

    def __len__(self) -> int:
        return self.sampler.num_samples // self.batch_size

    def set_epoch(self, epoch: int) -> None:
        self.sampler.set_epoch(epoch)
        self.dataset.set_epoch(epoch)

    def fast_forward(self, itr: int) -> None:
        self.start_itr = int(itr)

    def _load_batch(self, idx_block: np.ndarray
                    ) -> tuple[np.ndarray, np.ndarray]:
        """Decode one batch block: idx_block is (rows, batch) indices."""
        flat = idx_block.reshape(-1)
        images = np.stack([self.dataset[i][0] for i in flat])
        labels = np.asarray([self.dataset.labels[i] for i in flat],
                            np.int32)
        s = self.dataset.image_size
        rows = idx_block.shape[0]
        return (images.reshape(rows, self.batch_size, s, s, 3),
                labels.reshape(rows, self.batch_size))

    def __iter__(self) -> tp.Iterator[tuple[np.ndarray, np.ndarray]]:
        n_batches = len(self)
        table = self.sampler.all_indices()  # (world, num_samples)
        if self.ranks is not None:
            table = table[self.ranks]
        start = self.start_itr
        self.start_itr = 0
        blocks = [table[:, b * self.batch_size:(b + 1) * self.batch_size]
                  for b in range(start, n_batches)]
        if not blocks:
            return
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=self.num_workers) as pool:
            window: list = []
            block_iter = iter(blocks)
            for blk in block_iter:
                window.append(pool.submit(self._load_batch, blk))
                if len(window) >= self.prefetch:
                    break
            for blk in block_iter:
                done = window.pop(0)
                window.append(pool.submit(self._load_batch, blk))
                yield done.result()
            for fut in window:
                yield fut.result()
