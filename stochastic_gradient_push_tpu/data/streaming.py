"""Streaming input pipeline: background decode for real datasets.

The eager loaders hold everything in host memory — fine for validation and
smoke runs, impossible for ImageNet training.  This loader streams: a
thread pool decodes/augments the next batches (PIL releases the GIL for
image decode) while the TPU computes, and the host never holds more than
``prefetch`` global batches.

Fills the role of the reference's ``torch.utils.data.DataLoader`` with
``num_workers`` forked decoders (gossip_sgd.py:563-567) — without the
torchvision dependency this image lacks — and yields world-stacked batches
``(world, batch, H, W, C)`` that the sharded train step consumes directly.
Same iteration contract as :class:`~.pipeline.ShardedLoader` (``len``,
``set_epoch``, ``fast_forward``) so the Trainer can use either.

By default the per-image decode runs through the native C++ pipeline
(data/native.py: libjpeg decode + Pillow-compatible resample + normalize
on a GIL-free std::thread pool — the counterpart of the reference's C++
DataLoader worker machinery); ``backend="pil"`` forces the pure-Python
path.  Both backends draw the same augmentation stream (crop boxes and
flips) by construction; pixel values agree to ~1 uint8 LSB with
``max_denom=1`` and may differ more (still a faithful antialiased
downscale) under the default DCT-domain fast decode.
"""

from __future__ import annotations

import concurrent.futures
import typing as tp

import numpy as np

from .imagefolder import ImageFolderDataset
from .pipeline import DistributedSampler

__all__ = ["StreamingImageFolder"]


class StreamingImageFolder:
    """World-stacked streaming loader over an ImageFolder directory."""

    def __init__(self, root: str, split: str, world_size: int,
                 batch_size: int, image_size: int = 224, train: bool = True,
                 num_workers: int = 8, prefetch: int = 4, seed: int = 0,
                 ranks: tp.Sequence[int] | None = None,
                 backend: str = "auto", max_denom: int = 8,
                 output: str = "f32"):
        self.dataset = ImageFolderDataset(
            f"{root}/{split}" if split else root,
            image_size=image_size, train=train, seed=seed)
        self.world_size = world_size
        self.batch_size = batch_size
        self.num_workers = max(num_workers, 1)
        self.prefetch = max(prefetch, 1)
        self.sampler = DistributedSampler(len(self.dataset), world_size)
        # multi-host: decode only this process's rank rows
        self.ranks = None if ranks is None else list(ranks)
        self.start_itr = 0
        # backend: "native" = the C++ pipeline (data/native.py; libjpeg
        # decode + resample + normalize on a GIL-free std::thread pool),
        # "pil" = pure Python, "auto" = native when it builds.  The native
        # decoder replays the dataset's exact per-(seed, epoch, index)
        # augmentation rng (same crops/flips); pixel values match PIL to
        # ~1 uint8 LSB at max_denom=1, while the default max_denom=8
        # allows DCT-domain downscaled decodes on large images — visually
        # equivalent but not LSB-close (tested bound: within a few LSB on
        # average).  Pass max_denom=1 for strict parity.
        if backend not in ("auto", "native", "pil"):
            raise ValueError(f"unknown backend {backend!r}")
        # output: "f32" = ImageNet-normalized float32; "uint8" = raw
        # pixels, 4x smaller host->device, normalized ON DEVICE by the
        # train/eval steps (dtype-triggered; train/step.py)
        if output not in ("f32", "uint8"):
            raise ValueError(f"unknown output {output!r}")
        self.output = output
        self.decoder = None
        if backend != "pil":
            from .native import NativeDecoder
            dec = NativeDecoder(self.dataset.paths, image_size, train,
                                seed=seed, threads=self.num_workers,
                                max_denom=max_denom)
            if dec.available:
                self.decoder = dec
            elif backend == "native":
                import os as _os
                hint = ""
                if _os.environ.get("SGP_NATIVE_LOADER", "1").lower() in (
                        "0", "off", "false"):
                    hint = (" (SGP_NATIVE_LOADER="
                            f"{_os.environ['SGP_NATIVE_LOADER']!r} disables "
                            "it — unset the env var)")
                raise RuntimeError("backend='native' but the native loader "
                                   f"is unavailable{hint}")

    @property
    def classes(self) -> list[str]:
        return self.dataset.classes

    def __len__(self) -> int:
        return self.sampler.num_samples // self.batch_size

    def set_epoch(self, epoch: int) -> None:
        self.sampler.set_epoch(epoch)
        self.dataset.set_epoch(epoch)
        if self.decoder is not None:
            self.decoder.set_epoch(epoch)

    def fast_forward(self, itr: int) -> None:
        self.start_itr = int(itr)

    def _load_batch(self, idx_block: np.ndarray
                    ) -> tuple[np.ndarray, np.ndarray]:
        """Decode one batch block: idx_block is (rows, batch) indices."""
        flat = idx_block.reshape(-1)
        if self.decoder is not None:
            images = self.decoder.decode(flat, output=self.output)
        else:
            images = np.stack([
                self.dataset.decode(i, raw=self.output == "uint8")
                for i in flat])
        labels = np.asarray([self.dataset.labels[i] for i in flat],
                            np.int32)
        s = self.dataset.image_size
        rows = idx_block.shape[0]
        return (images.reshape(rows, self.batch_size, s, s, 3),
                labels.reshape(rows, self.batch_size))

    def __iter__(self) -> tp.Iterator[tuple[np.ndarray, np.ndarray]]:
        n_batches = len(self)
        table = self.sampler.all_indices()  # (world, num_samples)
        if self.ranks is not None:
            table = table[self.ranks]
        start = self.start_itr
        self.start_itr = 0
        blocks = [table[:, b * self.batch_size:(b + 1) * self.batch_size]
                  for b in range(start, n_batches)]
        if not blocks:
            return
        # native decode parallelizes WITHIN a batch (C++ pool of
        # num_workers threads), so the outer executor only needs enough
        # workers to overlap produce with consume; the PIL path gets all
        # its parallelism from the outer pool instead.
        outer = 2 if self.decoder is not None else self.num_workers
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=outer) as pool:
            window: list = []
            block_iter = iter(blocks)
            for blk in block_iter:
                window.append(pool.submit(self._load_batch, blk))
                if len(window) >= self.prefetch:
                    break
            for blk in block_iter:
                done = window.pop(0)
                window.append(pool.submit(self._load_batch, blk))
                yield done.result()
            for fut in window:
                yield fut.result()
