"""Host->device batch prefetch: overlap the transfer with compute.

The train loop dispatches a step and blocks until it completes; the next
batch's host->device copy then runs in the gap.  On a tunneled dev box
that copy crosses the tunnel and can rival the step itself (bench.py
pins its data for exactly this reason); even locally it serializes PCIe
traffic behind compute.  :class:`DevicePrefetcher` wraps any
``(images, labels)`` loader and device_puts batches on a background
thread with a small queue, so batch k+1's transfer rides inside step k's
compute window (``device_put`` is async; the queue depth bounds host
memory).

Scope (ROADMAP's deferred "chunk-level device-put prefetch", now behind
a flag): single-process meshes, non-scanned path (``scan_steps == 1`` —
scan chunks are host-stacked before transfer, which would force the
arrays back to host).  The Trainer enables it via
``TrainerConfig.prefetch``; measured on-chip before being defaulted
(docs/MFU_ANALYSIS.md round-5 section).
"""

from __future__ import annotations

import queue
import threading
import typing as tp

import jax
from jax.sharding import NamedSharding

__all__ = ["DevicePrefetcher"]

_STOP = object()


class DevicePrefetcher:
    """Iterate ``loader``, device_putting each ``(x, y)`` ``depth`` ahead.

    Delegates ``len``/``set_epoch``/``fast_forward`` so it can stand in
    for the wrapped loader anywhere in the train loop.  Iteration errors
    on the worker thread re-raise on the consumer.
    """

    def __init__(self, loader, mesh, spec, depth: int = 2):
        self.loader = loader
        self.sharding = NamedSharding(mesh, spec)
        self.depth = max(1, int(depth))

    def __len__(self) -> int:
        return len(self.loader)

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.loader, "set_epoch"):
            self.loader.set_epoch(epoch)

    def fast_forward(self, n: int) -> None:
        if hasattr(self.loader, "fast_forward"):
            self.loader.fast_forward(n)

    def __iter__(self) -> tp.Iterator:
        q: queue.Queue = queue.Queue(maxsize=self.depth)
        stop = threading.Event()

        def put(item) -> bool:
            # bounded put: an abandoned consumer (epoch cap) sets `stop`
            # from the generator's finally, so the worker exits instead
            # of blocking on a full queue forever
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def work():
            try:
                for x, y in self.loader:
                    if not put((jax.device_put(x, self.sharding),
                                jax.device_put(y, self.sharding))):
                        return
            except BaseException as e:  # sgplint: disable=SGPL007
                # (deliberate transport: surfaces on the consumer side,
                # which re-raises it — see the isinstance check below)
                put(e)
                return
            put(_STOP)

        t = threading.Thread(target=work, daemon=True,
                             name="device-prefetch")
        t.start()
        try:
            while True:
                item = q.get()
                if item is _STOP:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
