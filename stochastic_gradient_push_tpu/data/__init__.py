"""Input pipelines."""

from .pipeline import (
    DistributedSampler,
    ShardedLoader,
    imagefolder_arrays,
    synthetic_classification,
)

__all__ = [
    "DistributedSampler",
    "ShardedLoader",
    "synthetic_classification",
    "imagefolder_arrays",
]
