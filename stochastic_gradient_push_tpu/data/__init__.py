"""Input pipelines."""

from .lm import lm_batches, synthetic_lm_corpus
from .pipeline import (
    DistributedSampler,
    ShardedLoader,
    imagefolder_arrays,
    synthetic_classification,
)

__all__ = [
    "DistributedSampler",
    "ShardedLoader",
    "synthetic_classification",
    "imagefolder_arrays",
    "synthetic_lm_corpus",
    "lm_batches",
]
