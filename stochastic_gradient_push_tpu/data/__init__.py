"""Input pipelines."""

from .imagefolder import ImageFolderDataset, load_image, scan_image_folder
from .lm import lm_batches, synthetic_lm_corpus
from .native import NativeDecoder
from .streaming import StreamingImageFolder
from .pipeline import (
    DistributedSampler,
    ShardedLoader,
    imagefolder_arrays,
    synthetic_classification,
    translated_patch_classification,
)

__all__ = [
    "DistributedSampler",
    "ShardedLoader",
    "synthetic_classification",
    "translated_patch_classification",
    "imagefolder_arrays",
    "synthetic_lm_corpus",
    "lm_batches",
    "ImageFolderDataset",
    "NativeDecoder",
    "StreamingImageFolder",
    "scan_image_folder",
    "load_image",
]
