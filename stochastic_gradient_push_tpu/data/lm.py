"""Language-model data: synthetic + file corpora, (dp, sp)-sharded batching."""

from __future__ import annotations

import numpy as np

__all__ = ["synthetic_lm_corpus", "load_corpus", "lm_batches"]


def load_corpus(path: str, vocab_size: int) -> np.ndarray:
    """Load a real corpus for the LM harness.

    ``.npy``/``.npz`` files are taken as pre-tokenized integer arrays
    (validated against ``vocab_size``); anything else is read as raw
    bytes — a byte-level LM (requires ``vocab_size >= 256``).
    """
    if path.endswith((".npy", ".npz")):
        arr = np.load(path)
        if hasattr(arr, "files"):  # npz: single array expected
            names = list(arr.files)
            if len(names) != 1:
                raise ValueError(f"{path}: expected one array, "
                                 f"found {names}")
            arr = arr[names[0]]
        arr = np.asarray(arr).reshape(-1)
        if not np.issubdtype(arr.dtype, np.integer):
            raise ValueError(f"{path}: token array must be integer, "
                             f"got {arr.dtype}")
        arr = arr.astype(np.int32)
        if arr.size and (arr.min() < 0 or arr.max() >= vocab_size):
            raise ValueError(
                f"{path}: token ids span [{arr.min()}, {arr.max()}] — "
                f"outside vocab_size {vocab_size}")
        return arr
    with open(path, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    if vocab_size < 256:
        raise ValueError(
            f"byte-level corpus needs vocab_size >= 256, got {vocab_size}")
    return data.astype(np.int32)


def synthetic_lm_corpus(n_tokens: int, vocab_size: int = 256,
                        order: int = 2, seed: int = 0) -> np.ndarray:
    """A learnable Markov corpus: each token depends on the previous
    ``order`` tokens through a fixed random table, so a causal LM can drive
    the loss well below the unigram entropy."""
    g = np.random.default_rng(seed)
    table = g.integers(0, vocab_size,
                       size=(vocab_size,) * order).astype(np.int32)
    noise = g.random(n_tokens)
    toks = np.empty(n_tokens, np.int32)
    toks[:order] = g.integers(0, vocab_size, size=order)
    for i in range(order, n_tokens):
        if noise[i] < 0.9:  # mostly deterministic, some noise
            toks[i] = table[tuple(toks[i - order:i])]
        else:
            toks[i] = g.integers(0, vocab_size)
    return toks


def lm_batches(corpus: np.ndarray, dp: int, sp: int, batch: int,
               seq_len: int, seed: int = 0):
    """Yield ``(tokens, targets)`` of shape ``[dp, sp, batch, seq_len/sp]``.

    Each (dp, batch) sequence is contiguous; its target is the sequence
    shifted by one token (computed globally *before* sharding, so sequence
    shards need no cross-shard shift).  The sp dimension holds contiguous
    blocks of each sequence, matching ring attention's block layout.
    """
    if seq_len % sp:
        raise ValueError(f"seq_len {seq_len} not divisible by sp {sp}")
    block = seq_len // sp
    span = seq_len + 1
    n_seqs = (len(corpus) - 1) // seq_len
    if n_seqs < dp * batch:
        raise ValueError("corpus too small for one batch")
    g = np.random.default_rng(seed)
    starts_all = np.arange(n_seqs) * seq_len
    g.shuffle(starts_all)
    for i in range(0, len(starts_all) - dp * batch + 1, dp * batch):
        starts = starts_all[i:i + dp * batch]
        seqs = np.stack([corpus[s:s + span] for s in starts])  # [dp*b, L+1]
        tokens = seqs[:, :-1].reshape(dp, batch, sp, block)
        targets = seqs[:, 1:].reshape(dp, batch, sp, block)
        # [dp, batch, sp, block] → [dp, sp, batch, block]
        yield (np.ascontiguousarray(tokens.transpose(0, 2, 1, 3)),
               np.ascontiguousarray(targets.transpose(0, 2, 1, 3)))
