"""Self-contained ImageFolder pipeline (PIL + numpy, no torchvision).

Implements the reference's exact input transforms (gossip_sgd.py:546-581)
without the torchvision dependency this image lacks:

* train: RandomResizedCrop(size, scale=(0.08, 1.0), ratio=(3/4, 4/3)) +
  RandomHorizontalFlip — the "ImageNet in 1hr" augmentation
* eval: Resize(size·256/224) + CenterCrop(size)
* both: float32, ImageNet mean/std normalization, NHWC

Directory layout is torchvision's ImageFolder contract: ``root/split/
class_name/*.{png,jpg,...}``, classes indexed in sorted order.
"""

from __future__ import annotations

import math
import os

import numpy as np

__all__ = ["scan_image_folder", "load_image", "ImageFolderDataset",
           "augmentation_rng"]

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)
_EXTENSIONS = {".png", ".jpg", ".jpeg", ".bmp", ".webp"}


def scan_image_folder(root: str) -> tuple[list[str], np.ndarray, list[str]]:
    """→ (paths, labels, class_names); classes indexed in sorted order."""
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    if not classes:
        raise FileNotFoundError(f"no class directories under {root}")
    paths, labels = [], []
    for idx, cls in enumerate(classes):
        cdir = os.path.join(root, cls)
        for fname in sorted(os.listdir(cdir)):
            if os.path.splitext(fname)[1].lower() in _EXTENSIONS:
                paths.append(os.path.join(cdir, fname))
                labels.append(idx)
    if not paths:
        raise FileNotFoundError(f"no images under {root}")
    return paths, np.asarray(labels, np.int32), classes


def augmentation_rng(seed: int, epoch: int, idx: int) -> np.random.Generator:
    """The per-(seed, epoch, sample) augmentation stream: deterministic but
    fresh crops every epoch.  ONE derivation shared by the PIL path and the
    native decoder (data/native.py) — backend interchangeability depends on
    both drawing from the identical stream."""
    return np.random.default_rng(
        (seed * 1_000_003 + epoch) * 10_000_019 + int(idx))


def _random_resized_crop_box(w: int, h: int, rng: np.random.Generator,
                             scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
    """Torch-style RandomResizedCrop box sampling (10 tries, center
    fallback)."""
    area = w * h
    log_ratio = (math.log(ratio[0]), math.log(ratio[1]))
    for _ in range(10):
        target_area = area * rng.uniform(*scale)
        aspect = math.exp(rng.uniform(*log_ratio))
        cw = int(round(math.sqrt(target_area * aspect)))
        ch = int(round(math.sqrt(target_area / aspect)))
        if 0 < cw <= w and 0 < ch <= h:
            left = int(rng.integers(0, w - cw + 1))
            top = int(rng.integers(0, h - ch + 1))
            return left, top, cw, ch
    # fallback: largest center crop within the ratio bounds
    in_ratio = w / h
    if in_ratio < ratio[0]:
        cw, ch = w, int(round(w / ratio[0]))
    elif in_ratio > ratio[1]:
        cw, ch = int(round(h * ratio[1])), h
    else:
        cw, ch = w, h
    return (w - cw) // 2, (h - ch) // 2, cw, ch


def load_image(path: str, image_size: int, train: bool,
               rng: np.random.Generator | None = None,
               raw: bool = False) -> np.ndarray:
    """Decode + transform one image → float32 HWC (normalized), or the
    pre-normalization uint8 pixels when ``raw`` (the device-side-normalize
    pipeline; see train/step.py)."""
    from PIL import Image

    with Image.open(path) as img:
        img = img.convert("RGB")
        w, h = img.size
        if train:
            rng = rng or np.random.default_rng()
            left, top, cw, ch = _random_resized_crop_box(w, h, rng)
            img = img.resize((image_size, image_size), Image.BILINEAR,
                             box=(left, top, left + cw, top + ch))
            if rng.random() < 0.5:
                img = img.transpose(Image.FLIP_LEFT_RIGHT)
        else:
            short = int(image_size * 256 / 224)
            if w <= h:
                nw, nh = short, max(short, int(round(short * h / w)))
            else:
                nh, nw = short, max(short, int(round(short * w / h)))
            img = img.resize((nw, nh), Image.BILINEAR)
            left = (nw - image_size) // 2
            top = (nh - image_size) // 2
            img = img.crop((left, top, left + image_size,
                            top + image_size))
        if raw:
            return np.asarray(img, np.uint8)
        arr = np.asarray(img, np.float32) / 255.0
    return (arr - IMAGENET_MEAN) / IMAGENET_STD


class ImageFolderDataset:
    """Indexable decoded dataset over an ImageFolder directory."""

    def __init__(self, root: str, image_size: int = 224,
                 train: bool = True, seed: int = 0):
        self.paths, self.labels, self.classes = scan_image_folder(root)
        self.image_size = image_size
        self.train = train
        self.seed = seed
        self.epoch = 0

    def __len__(self) -> int:
        return len(self.paths)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)

    def __getitem__(self, idx: int) -> tuple[np.ndarray, np.int32]:
        return (self.decode(idx), self.labels[idx])

    def decode(self, idx: int, raw: bool = False) -> np.ndarray:
        """One decoded image: normalized float32, or pre-normalization
        uint8 pixels when ``raw`` (the device-side-normalize pipeline).
        The ONLY place the per-sample rng meets the transform — every
        backend/output variant routes through here or replays the same
        :func:`augmentation_rng` stream."""
        rng = (augmentation_rng(self.seed, self.epoch, idx)
               if self.train else None)
        return load_image(self.paths[idx], self.image_size, self.train,
                          rng, raw=raw)
