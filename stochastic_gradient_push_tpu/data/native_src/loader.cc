// Native host-side image pipeline: the TPU-framework counterpart of the
// reference's C++ decode path (torch's DataLoader workers + torchvision's
// libjpeg-backed PIL decode, driven from gossip_sgd.py:546-583).
//
// The reference feeds each GPU from forked C++ DataLoader workers; a TPU
// chip at the measured 2600 img/s/chip (BASELINE.md) outruns a Python/PIL
// decode loop by an order of magnitude, so the host pipeline must be
// native too.  This module is a CPython extension (no pybind11 in the
// image — raw C API + buffer protocol, no numpy C API) that does, per
// image, entirely in C++ with the GIL released:
//
//   JPEG decode (libjpeg)  ->  crop  ->  separable triangle-filter
//   resample (Pillow-compatible BILINEAR, antialiased on downscale)
//   ->  horizontal flip  ->  output as float32 ImageNet-normalized,
//   float32 raw [0,1], or uint8 (4x smaller host->device transfer;
//   the train step normalizes uint8 inputs on device)
//
// Both transform orders of data/imagefolder.py are reproduced exactly:
//   train:  crop(box) -> resize(S,S) -> optional flip      (load_image)
//   eval:   resize(short->256S/224) -> center-crop(S)      (load_image)
// Crop boxes and flips are SAMPLED IN PYTHON (imagefolder.py keeps its
// per-(epoch,sample) rng) and passed in, so native and PIL paths see
// identical augmentation streams and differ only in resampling rounding.
//
// Batch API: decode_batch() fans a list of file paths over an internal
// std::thread pool and writes straight into a caller-provided float32
// buffer (world, batch, S, S, 3)-shaped by the Python wrapper.  Images
// that libjpeg cannot handle (PNG, CMYK/YCCK, truncated files) are
// reported back by index and re-decoded through the PIL fallback —
// correctness never depends on this module.
//
// Build: scripts/build_native.sh or data/native.py:ensure_built()
// (g++ -O3 -shared -fPIC loader.cc -ljpeg).

#include <Python.h>

#include <jpeglib.h>

#include <algorithm>
#include <atomic>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// JPEG decode (libjpeg, error-trampoline instead of exit())
// ---------------------------------------------------------------------------

struct JpegErrorMgr {
  jpeg_error_mgr pub;
  std::jmp_buf jump;
};

void jpeg_error_exit(j_common_ptr cinfo) {
  JpegErrorMgr* err = reinterpret_cast<JpegErrorMgr*>(cinfo->err);
  std::longjmp(err->jump, 1);
}

// Decoded image: tightly packed RGB, uint8.  full_w/full_h are the
// original (pre-scale_denom) dimensions straight from the header —
// libjpeg rounds scaled output dims UP, so w * denom may overshoot.
struct Image {
  int w = 0, h = 0;
  int full_w = 0, full_h = 0;
  std::vector<uint8_t> rgb;  // h * w * 3
  bool ok = false;
};

// One libjpeg session: read the header, let ``pick_denom`` choose the
// DCT-domain downscale (1, 2, 4, 8 — the cheap 1/scale_denom decode) from
// the full-size dims, then decompress.  ``denom_out`` reports the choice.
// CMYK / YCCK (which PIL converts via ImageCms) and non-3-component
// outputs are routed to the Python fallback.
template <typename PickDenom>
Image decode_jpeg(const uint8_t* data, size_t len, PickDenom pick_denom,
                  int* denom_out) {
  Image img;
  jpeg_decompress_struct cinfo;
  JpegErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_error_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    img.ok = false;
    return img;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return img;
  }
  if (cinfo.jpeg_color_space == JCS_CMYK ||
      cinfo.jpeg_color_space == JCS_YCCK) {
    jpeg_destroy_decompress(&cinfo);
    return img;
  }
  const int denom = pick_denom(static_cast<int>(cinfo.image_width),
                               static_cast<int>(cinfo.image_height));
  *denom_out = denom;
  img.full_w = static_cast<int>(cinfo.image_width);
  img.full_h = static_cast<int>(cinfo.image_height);
  cinfo.out_color_space = JCS_RGB;
  cinfo.scale_num = 1;
  cinfo.scale_denom = static_cast<unsigned>(denom);
  cinfo.dct_method = JDCT_ISLOW;  // match PIL's default quality
  jpeg_start_decompress(&cinfo);
  img.w = static_cast<int>(cinfo.output_width);
  img.h = static_cast<int>(cinfo.output_height);
  if (cinfo.output_components != 3) {
    jpeg_destroy_decompress(&cinfo);
    return img;
  }
  img.rgb.resize(static_cast<size_t>(img.w) * img.h * 3);
  const size_t stride = static_cast<size_t>(img.w) * 3;
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = img.rgb.data() + cinfo.output_scanline * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  img.ok = true;
  return img;
}

// ---------------------------------------------------------------------------
// Pillow-compatible separable resampling (BILINEAR == triangle filter,
// antialiased when downscaling: support scales with in/out ratio).
// Matches Pillow's ResampleHorizontal/Vertical coefficient construction;
// we keep float32 throughout (Pillow quantizes to int16 fixed point, so
// outputs differ by <=1-2 LSB — parity-tested in
// tests/test_native_loader.py).
// ---------------------------------------------------------------------------

struct FilterTable {
  int ksize = 0;                 // max taps per output pixel
  std::vector<int> bounds;       // 2 * out: (xmin, xcount)
  std::vector<float> coeffs;     // out * ksize
};

FilterTable triangle_coeffs(int in_size, int out_size, double box_start,
                            double box_size) {
  FilterTable ft;
  const double scale = box_size / out_size;
  const double filterscale = std::max(scale, 1.0);
  const double support = 1.0 * filterscale;  // bilinear support = 1.0
  ft.ksize = static_cast<int>(std::ceil(support)) * 2 + 1;
  ft.bounds.resize(2 * out_size);
  ft.coeffs.assign(static_cast<size_t>(out_size) * ft.ksize, 0.0f);
  const double ss = 1.0 / filterscale;
  for (int xx = 0; xx < out_size; ++xx) {
    const double center = box_start + (xx + 0.5) * scale;
    int xmin = static_cast<int>(center - support + 0.5);
    if (xmin < 0) xmin = 0;
    int xmax = static_cast<int>(center + support + 0.5);
    if (xmax > in_size) xmax = in_size;
    xmax -= xmin;
    double wsum = 0.0;
    std::vector<double> w(static_cast<size_t>(std::max(xmax, 1)));
    for (int x = 0; x < xmax; ++x) {
      const double arg = (xmin + x - center + 0.5) * ss;
      const double v = (arg >= -1.0 && arg <= 1.0)
                           ? (arg < 0 ? 1.0 + arg : 1.0 - arg)
                           : 0.0;
      w[static_cast<size_t>(x)] = v;
      wsum += v;
    }
    for (int x = 0; x < xmax; ++x) {
      ft.coeffs[static_cast<size_t>(xx) * ft.ksize + x] =
          wsum != 0.0 ? static_cast<float>(w[static_cast<size_t>(x)] / wsum)
                      : 0.0f;
    }
    ft.bounds[2 * xx] = xmin;
    ft.bounds[2 * xx + 1] = xmax;
  }
  return ft;
}

// Output modes: float32 ImageNet-normalized (the classic contract),
// float32 raw [0,1], or uint8 — the latter shrinks the host->device
// transfer 4x and lets the compiled train step fuse the normalize into
// the stem convolution (train/step.py normalizes uint8 inputs on device).
enum class OutMode : int { kF32Norm = 0, kF32Raw = 1, kU8 = 2 };

// Finalization applied as each output row completes: clamp to [0, 255],
// round to the uint8 grid PIL materializes, optional horizontal flip,
// then write in the requested output mode.
struct Finalize {
  bool flip = false;
  OutMode mode = OutMode::kF32Norm;
  int out_w = 0;       // row width of dst
  void* dst = nullptr;  // float* or uint8_t* per mode
};

constexpr float kMean[3] = {0.485f, 0.456f, 0.406f};
constexpr float kStd[3] = {0.229f, 0.224f, 0.225f};

// Resample a (h, w, 3) uint8 image to the conceptual (out_h, out_w) grid,
// but only materialize the output window [x0, x1) x [y0, y1) — EXACT:
// every produced pixel reads the same source taps it would in a full
// resample, so a windowed eval (resize-short then center-crop) is
// bit-identical to resize-then-crop.  box_* give the source rectangle in
// decoded coords (train crop / full image).  Each finished row runs
// through ``fin`` straight into the caller's buffer; nothing the window
// doesn't need is ever computed.
void resample_window(const uint8_t* src, int w, int h, double box_l,
                     double box_t, double box_w, double box_h, int out_w,
                     int out_h, int x0, int x1, int y0, int y1,
                     const Finalize& fin) {
  const FilterTable fx = triangle_coeffs(w, out_w, box_l, box_w);
  const FilterTable fy = triangle_coeffs(h, out_h, box_t, box_h);
  const int ww = x1 - x0;
  // source rows the vertical pass will touch:
  const int row_lo = fy.bounds[2 * y0];
  const int row_hi = fy.bounds[2 * (y1 - 1)] + fy.bounds[2 * (y1 - 1) + 1];
  const int nrows = row_hi - row_lo;
  // horizontal pass over just those rows and just the window's columns
  std::vector<float> tmp(static_cast<size_t>(nrows) * ww * 3);
  for (int y = 0; y < nrows; ++y) {
    const uint8_t* row = src + static_cast<size_t>(row_lo + y) * w * 3;
    float* trow = tmp.data() + static_cast<size_t>(y) * ww * 3;
    for (int xx = x0; xx < x1; ++xx) {
      const int xmin = fx.bounds[2 * xx];
      const int xcount = fx.bounds[2 * xx + 1];
      const float* cf = fx.coeffs.data() + static_cast<size_t>(xx) * fx.ksize;
      float r = 0, g = 0, b = 0;
      for (int x = 0; x < xcount; ++x) {
        const float c = cf[x];
        const uint8_t* px = row + static_cast<size_t>(xmin + x) * 3;
        r += c * px[0];
        g += c * px[1];
        b += c * px[2];
      }
      float* o = trow + static_cast<size_t>(xx - x0) * 3;
      o[0] = r;
      o[1] = g;
      o[2] = b;
    }
  }
  // vertical pass + fused finalize, one output row at a time
  std::vector<float> acc(static_cast<size_t>(ww) * 3);
  for (int yy = y0; yy < y1; ++yy) {
    const int ymin = fy.bounds[2 * yy];
    const int ycount = fy.bounds[2 * yy + 1];
    const float* cf = fy.coeffs.data() + static_cast<size_t>(yy) * fy.ksize;
    std::memset(acc.data(), 0, sizeof(float) * ww * 3);
    for (int y = 0; y < ycount; ++y) {
      const float c = cf[y];
      const float* trow =
          tmp.data() + static_cast<size_t>(ymin - row_lo + y) * ww * 3;
      for (int x = 0; x < ww * 3; ++x) acc[static_cast<size_t>(x)] += c * trow[x];
    }
    const size_t row_off = static_cast<size_t>(yy - y0) * fin.out_w * 3;
    float* frow = static_cast<float*>(fin.dst) + row_off;
    uint8_t* urow = static_cast<uint8_t*>(fin.dst) + row_off;
    for (int x = 0; x < ww; ++x) {
      const int sx = fin.flip ? (ww - 1 - x) : x;
      for (int c = 0; c < 3; ++c) {
        float v = acc[static_cast<size_t>(sx) * 3 + c];
        v = std::min(std::max(v, 0.0f), 255.0f);
        v = std::nearbyintf(v);  // PIL's uint8 quantization
        switch (fin.mode) {
          case OutMode::kF32Norm:
            frow[3 * x + c] = (v / 255.0f - kMean[c]) / kStd[c];
            break;
          case OutMode::kF32Raw:
            frow[3 * x + c] = v / 255.0f;
            break;
          case OutMode::kU8:
            urow[3 * x + c] = static_cast<uint8_t>(v);
            break;
        }
      }
    }
  }
}

// ---------------------------------------------------------------------------
// Per-image pipeline
// ---------------------------------------------------------------------------

bool read_file(const char* path, std::vector<uint8_t>& buf) {
  std::FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  const long n = std::ftell(f);
  if (n < 0) {
    std::fclose(f);
    return false;
  }
  std::fseek(f, 0, SEEK_SET);
  buf.resize(static_cast<size_t>(n));
  const size_t got = n ? std::fread(buf.data(), 1, static_cast<size_t>(n), f)
                       : 0;
  std::fclose(f);
  return got == static_cast<size_t>(n);
}

struct Task {
  const char* path;
  // train: crop box in ORIGINAL image coords (from Python's rng);
  // negative box_w means eval mode (resize-short + center-crop).
  int box_l, box_t, box_w, box_h;
  int flip;       // train only
  int out_size;   // S
  int max_denom;  // cap on the DCT-domain downscale (1 disables)
  void* dst;      // S*S*3, float32 or uint8 per mode
};

bool run_task(const Task& t, OutMode mode) {
  std::vector<uint8_t> raw;
  if (!read_file(t.path, raw)) return false;
  // JPEG magic; everything else goes to the Python fallback.
  if (raw.size() < 3 || raw[0] != 0xFF || raw[1] != 0xD8) return false;

  const bool train = t.box_w >= 0;

  // DCT-domain scale_denom choice: decoding at 1/2 or 1/4 is far cheaper
  // and stays lossless for the filter as long as the decoded source
  // region never drops below the resample target (the triangle filter
  // then still strictly downscales, so antialiasing stays intact).
  int denom = 1;
  auto pick = [&](int w, int h) {
    const int src_min =
        train ? std::min(t.box_w, t.box_h) : std::min(w, h);
    const int target = train
        ? t.out_size
        : (t.out_size * 256 + 223) / 224;  // eval short-side target
    int d = 1;
    for (int cand = 2; cand <= t.max_denom; cand *= 2) {
      if (src_min / cand >= target) d = cand;
    }
    return d;
  };
  Image img = decode_jpeg(raw.data(), raw.size(), pick, &denom);
  if (!img.ok) return false;
  const double ds = 1.0 / denom;  // original -> decoded coord scale

  const int S = t.out_size;
  Finalize fin;
  fin.mode = mode;
  fin.out_w = S;
  fin.dst = t.dst;
  if (train) {
    fin.flip = t.flip != 0;
    resample_window(img.rgb.data(), img.w, img.h, t.box_l * ds, t.box_t * ds,
                    t.box_w * ds, t.box_h * ds, S, S, 0, S, 0, S, fin);
  } else {
    // Resize short side to round(256/224*S) keeping aspect (exactly
    // imagefolder.py:88-94), then center-crop SxS — windowed, so only the
    // crop region (plus filter support) is ever resampled.
    const int short_target = static_cast<int>(S * 256.0 / 224.0);
    int nw, nh;
    // NOTE: imagefolder.py computes from ORIGINAL dims; use the header's
    // full_w/full_h (w * denom would overshoot — libjpeg ceils scaled
    // dims), then map the resample onto the 1/denom-scaled decode.
    // nearbyint under the default FE_TONEAREST mode rounds half-to-even,
    // matching Python's round() in imagefolder.py:91-93 for exact .5s
    const int ow = img.full_w, oh = img.full_h;
    if (ow <= oh) {
      nw = short_target;
      nh = std::max(short_target,
                    static_cast<int>(std::nearbyint(
                        static_cast<double>(short_target) * oh / ow)));
    } else {
      nh = short_target;
      nw = std::max(short_target,
                    static_cast<int>(std::nearbyint(
                        static_cast<double>(short_target) * ow / oh)));
    }
    const int left = (nw - S) / 2, top = (nh - S) / 2;
    resample_window(img.rgb.data(), img.w, img.h, 0.0, 0.0,
                    static_cast<double>(img.w), static_cast<double>(img.h),
                    nw, nh, left, left + S, top, top + S, fin);
  }
  return true;
}

// ---------------------------------------------------------------------------
// Python bindings (raw C API, buffer protocol only)
// ---------------------------------------------------------------------------

struct BufferGuard {
  Py_buffer view{};
  bool held = false;
  ~BufferGuard() {
    if (held) PyBuffer_Release(&view);
  }
};

bool get_buffer(PyObject* obj, BufferGuard& g, int flags, const char* name) {
  if (PyObject_GetBuffer(obj, &g.view, flags) != 0) {
    PyErr_Format(PyExc_TypeError, "%s must support the buffer protocol",
                 name);
    return false;
  }
  g.held = true;
  if (!PyBuffer_IsContiguous(&g.view, 'C')) {
    PyErr_Format(PyExc_ValueError, "%s must be C-contiguous", name);
    return false;
  }
  return true;
}

// decode_batch(paths: list[bytes], boxes: int32 buffer (n, 5) =
//   (box_l, box_t, box_w, box_h, flip) with box_w < 0 => eval,
//   out: buffer (n * S * S * 3; float32 for modes 0/1, uint8 for 2),
//   out_size: int, threads: int, mode: int {0: f32 normalized,
//   1: f32 raw, 2: uint8}) -> list[int] (indices for the PIL fallback)
PyObject* py_decode_batch(PyObject*, PyObject* args) {
  PyObject* paths_obj;
  PyObject* boxes_obj;
  PyObject* out_obj;
  int out_size, threads, mode_i, max_denom = 8;
  if (!PyArg_ParseTuple(args, "OOOiii|i", &paths_obj, &boxes_obj, &out_obj,
                        &out_size, &threads, &mode_i, &max_denom)) {
    return nullptr;
  }
  if (mode_i < 0 || mode_i > 2) {
    PyErr_SetString(PyExc_ValueError, "mode must be 0, 1 or 2");
    return nullptr;
  }
  const OutMode mode = static_cast<OutMode>(mode_i);
  if (!PyList_Check(paths_obj)) {
    PyErr_SetString(PyExc_TypeError, "paths must be a list of bytes");
    return nullptr;
  }
  const Py_ssize_t n = PyList_GET_SIZE(paths_obj);

  // hold the path bytes (borrowed refs stay alive via the list)
  std::vector<const char*> paths(static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PyList_GET_ITEM(paths_obj, i);
    if (!PyBytes_Check(item)) {
      PyErr_SetString(PyExc_TypeError, "paths must be a list of bytes");
      return nullptr;
    }
    paths[static_cast<size_t>(i)] = PyBytes_AS_STRING(item);
  }

  BufferGuard boxes_g, out_g;
  if (!get_buffer(boxes_obj, boxes_g, PyBUF_C_CONTIGUOUS | PyBUF_FORMAT,
                  "boxes"))
    return nullptr;
  if (!get_buffer(out_obj, out_g,
                  PyBUF_C_CONTIGUOUS | PyBUF_WRITABLE | PyBUF_FORMAT, "out"))
    return nullptr;
  // itemsize (and format when exported) pin the element type: byte-length
  // alone would let e.g. an int64 boxes array of sufficient size be
  // silently reinterpreted as int32 garbage crop boxes
  if (boxes_g.view.itemsize != static_cast<Py_ssize_t>(sizeof(int32_t)) ||
      (boxes_g.view.format != nullptr &&
       strcmp(boxes_g.view.format, "i") != 0 &&
       strcmp(boxes_g.view.format, "l") != 0)) {
    PyErr_Format(PyExc_TypeError,
                 "boxes must be int32 (itemsize %zd, format %s)",
                 boxes_g.view.itemsize,
                 boxes_g.view.format ? boxes_g.view.format : "?");
    return nullptr;
  }
  if (boxes_g.view.len < static_cast<Py_ssize_t>(n * 5 * sizeof(int32_t))) {
    PyErr_SetString(PyExc_ValueError, "boxes buffer too small (need n*5 i32)");
    return nullptr;
  }
  const size_t per_img = static_cast<size_t>(out_size) * out_size * 3;
  const size_t elem = mode == OutMode::kU8 ? 1 : sizeof(float);
  const char* want_fmt = mode == OutMode::kU8 ? "B" : "f";
  if (out_g.view.itemsize != static_cast<Py_ssize_t>(elem) ||
      (out_g.view.format != nullptr &&
       strcmp(out_g.view.format, want_fmt) != 0)) {
    PyErr_Format(PyExc_TypeError,
                 "out must be %s for this mode (itemsize %zd, format %s)",
                 mode == OutMode::kU8 ? "uint8" : "float32",
                 out_g.view.itemsize,
                 out_g.view.format ? out_g.view.format : "?");
    return nullptr;
  }
  if (out_g.view.len < static_cast<Py_ssize_t>(n * per_img * elem)) {
    PyErr_SetString(PyExc_ValueError, "out buffer too small");
    return nullptr;
  }
  const int32_t* boxes = static_cast<const int32_t*>(boxes_g.view.buf);
  uint8_t* out = static_cast<uint8_t*>(out_g.view.buf);

  std::vector<uint8_t> failed(static_cast<size_t>(n), 0);
  {
    // the whole batch decodes without the GIL
    Py_BEGIN_ALLOW_THREADS;
    const int nthreads =
        std::max(1, std::min<int>(threads, static_cast<int>(n)));
    std::atomic<Py_ssize_t> next{0};
    auto worker = [&]() {
      for (;;) {
        const Py_ssize_t i = next.fetch_add(1);
        if (i >= n) break;
        const int32_t* b = boxes + i * 5;
        Task t{paths[static_cast<size_t>(i)], b[0], b[1], b[2], b[3],
               static_cast<int>(b[4]), out_size, max_denom,
               out + i * per_img * elem};
        if (!run_task(t, mode)) failed[static_cast<size_t>(i)] = 1;
      }
    };
    if (nthreads == 1) {
      worker();
    } else {
      std::vector<std::thread> pool;
      pool.reserve(static_cast<size_t>(nthreads));
      for (int k = 0; k < nthreads; ++k) pool.emplace_back(worker);
      for (auto& th : pool) th.join();
    }
    Py_END_ALLOW_THREADS;
  }

  PyObject* fails = PyList_New(0);
  if (!fails) return nullptr;
  for (Py_ssize_t i = 0; i < n; ++i) {
    if (failed[static_cast<size_t>(i)]) {
      PyObject* idx = PyLong_FromSsize_t(i);
      if (!idx || PyList_Append(fails, idx) != 0) {
        Py_XDECREF(idx);
        Py_DECREF(fails);
        return nullptr;
      }
      Py_DECREF(idx);
    }
  }
  return fails;
}

// decode_one(path: bytes, box: (l, t, w, h, flip), out_size, mode)
//   -> bytes (S*S*3 of float32 or uint8 per mode) | None — single-image
//   probe, used by tests.
PyObject* py_decode_one(PyObject*, PyObject* args) {
  const char* path;
  int l, t, w, h, flip, out_size, mode_i, max_denom = 8;
  if (!PyArg_ParseTuple(args, "y(iiiii)ii|i", &path, &l, &t, &w, &h, &flip,
                        &out_size, &mode_i, &max_denom)) {
    return nullptr;
  }
  if (mode_i < 0 || mode_i > 2) {
    PyErr_SetString(PyExc_ValueError, "mode must be 0, 1 or 2");
    return nullptr;
  }
  const OutMode mode = static_cast<OutMode>(mode_i);
  const size_t per_img = static_cast<size_t>(out_size) * out_size * 3;
  const size_t elem = mode == OutMode::kU8 ? 1 : sizeof(float);
  std::vector<uint8_t> buf(per_img * elem);
  Task task{path, l, t, w, h, flip, out_size, max_denom, buf.data()};
  bool ok;
  Py_BEGIN_ALLOW_THREADS;
  ok = run_task(task, mode);
  Py_END_ALLOW_THREADS;
  if (!ok) Py_RETURN_NONE;
  return PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(buf.data()),
      static_cast<Py_ssize_t>(buf.size()));
}

PyMethodDef kMethods[] = {
    {"decode_batch", py_decode_batch, METH_VARARGS,
     "decode_batch(paths, boxes_i32_n5, out, out_size, threads, "
     "mode{0:f32norm,1:f32raw,2:u8}, max_denom=8) -> failed indices"},
    {"decode_one", py_decode_one, METH_VARARGS,
     "decode_one(path, (l, t, w, h, flip), out_size, "
     "mode{0:f32norm,1:f32raw,2:u8}, max_denom=8) -> bytes or None"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "_nativeloader",
    "libjpeg decode + Pillow-compatible resample + augment, multithreaded",
    -1, kMethods,
};

}  // namespace

PyMODINIT_FUNC PyInit__nativeloader(void) {
  return PyModule_Create(&kModule);
}
