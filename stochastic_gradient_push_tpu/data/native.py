"""Python face of the native C++ image pipeline (data/native_src/loader.cc).

The reference feeds each worker from torch's C++ DataLoader machinery
(gossip_sgd.py:563-567, ``num_workers`` forked decoders); the TPU framework's
counterpart is a CPython extension that decodes, resamples and normalizes
whole batches with the GIL released on a std::thread pool.  This module:

* builds the extension on demand (``g++ -O3 -shared``, cached next to the
  source; no pybind11 — the image doesn't have it);
* samples the augmentation stream IN PYTHON, with exactly the per-
  ``(seed, epoch, index)`` rng of :class:`~.imagefolder.ImageFolderDataset`,
  so the native and PIL paths see identical crops/flips; pixel values
  match PIL to ~1 uint8 LSB at ``max_denom=1`` (parity-tested), while the
  default ``max_denom=8`` trades that for DCT-domain fast decodes on
  large images — a faithful antialiased downscale, not LSB-identical;
* decodes anything the C++ side rejects (PNG, CMYK, truncated files)
  through the PIL fallback, per image, so correctness never depends on the
  native path being available.

``SGP_NATIVE_LOADER=0`` disables the extension entirely (the streaming
loader then uses pure PIL); ``=require`` turns a missing toolchain into an
error instead of a silent fallback.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sys
import sysconfig
import threading
import typing as tp

import numpy as np

from .imagefolder import (_random_resized_crop_box, augmentation_rng,
                          load_image)

__all__ = ["ensure_built", "get_native", "NativeDecoder"]

_DATA_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DATA_DIR, "native_src", "loader.cc")
# the interpreter's cache tag in the filename forces a rebuild after a
# Python upgrade — mtime-vs-source alone can't see an ABI change
_TAG = getattr(sys.implementation, "cache_tag", None) or "py"
_SO = os.path.join(_DATA_DIR, f"_nativeloader.{_TAG}.so")
_LOCK = threading.Lock()
_MODULE: tp.Any = None
_TRIED = False


def ensure_built(verbose: bool = False) -> str | None:
    """Compile the extension if missing/stale; return the .so path or None."""
    if os.path.exists(_SO):
        # a shipped prebuilt .so without the source tree is fine as-is
        if not os.path.exists(_SRC) or \
                os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
            return _SO
    if not os.path.exists(_SRC):
        return None
    include = sysconfig.get_paths()["include"]
    tmp = _SO + f".tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", f"-I{include}",
           _SRC, "-o", tmp, "-ljpeg", "-pthread"]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:  # no g++ / hang
        if verbose:
            print(f"native loader build unavailable: {e}", file=sys.stderr)
        try:
            os.unlink(tmp)  # a timed-out g++ may leave a partial object
        except OSError:
            pass
        return None
    if proc.returncode != 0:
        if verbose:
            print(f"native loader build failed:\n{proc.stderr}",
                  file=sys.stderr)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    os.replace(tmp, _SO)  # atomic: concurrent builders race harmlessly
    return _SO


def get_native() -> tp.Any | None:
    """Import (building if needed) the `_nativeloader` module, else None."""
    global _MODULE, _TRIED
    with _LOCK:
        if _MODULE is not None or _TRIED:
            return _MODULE
        _TRIED = True
        mode = os.environ.get("SGP_NATIVE_LOADER", "1").lower()
        if mode in ("0", "off", "false"):
            return None
        so = ensure_built(verbose=(mode == "require"))
        if so is None:
            if mode == "require":
                raise RuntimeError(
                    "SGP_NATIVE_LOADER=require but the native loader could "
                    "not be built (g++/libjpeg missing?)")
            return None
        spec = importlib.util.spec_from_file_location("_nativeloader", so)
        assert spec and spec.loader
        mod = importlib.util.module_from_spec(spec)
        try:
            spec.loader.exec_module(mod)
        except (ImportError, OSError, SystemError) as e:
            # a corrupt or foreign-ABI cached extension degrades to PIL
            # (as documented) instead of crashing the loader; drop the
            # bad .so so the next process rebuilds it
            try:
                os.unlink(so)
            except OSError:
                pass
            if mode == "require":
                raise RuntimeError(
                    f"SGP_NATIVE_LOADER=require but the built extension "
                    f"failed to import: {e}") from e
            return None
        _MODULE = mod
        return _MODULE


class NativeDecoder:
    """Batch decoder with the exact augmentation stream of
    :class:`~.imagefolder.ImageFolderDataset`.

    Crop boxes / flips are sampled here (numpy rng, per ``(seed, epoch,
    index)``) against header-only image dimensions (cached after first
    touch — no pixel decode), then the C++ pool does decode + resample +
    normalize straight into the output buffer.  Failed indices fall back
    to :func:`~.imagefolder.load_image`.
    """

    def __init__(self, paths: tp.Sequence[str], image_size: int,
                 train: bool, seed: int = 0,
                 threads: int | None = None, max_denom: int = 8):
        self.paths = list(paths)
        self.image_size = int(image_size)
        self.train = bool(train)
        self.seed = int(seed)
        self.epoch = 0
        self.threads = threads or min(16, os.cpu_count() or 1)
        # DCT-domain downscale cap; 1 disables (exact-parity mode for tests)
        self.max_denom = int(max_denom)
        # header dims cache: (n, 2) int32, -1 = not yet read (a dict of
        # tuples would cost hundreds of MB at ImageNet scale)
        self._dims = np.full((len(self.paths), 2), -1, np.int32)
        self._native = get_native()

    @property
    def available(self) -> bool:
        return self._native is not None

    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)

    def _dims_for(self, idx: int) -> tuple[int, int]:
        w, h = self._dims[idx]
        if w < 0:
            from PIL import Image
            with Image.open(self.paths[idx]) as im:  # header only, no decode
                w, h = im.size
            self._dims[idx] = (w, h)
        return int(w), int(h)

    def _rng(self, idx: int) -> np.random.Generator:
        # identical stream to ImageFolderDataset.__getitem__
        return augmentation_rng(self.seed, self.epoch, idx)

    def sample_boxes(self, indices: np.ndarray) -> np.ndarray:
        """(n, 5) int32 (l, t, w, h, flip); eval rows are the sentinel."""
        n = len(indices)
        boxes = np.empty((n, 5), np.int32)
        if not self.train:
            boxes[:] = (-1, -1, -1, -1, 0)
            return boxes
        for j, idx in enumerate(indices):
            w, h = self._dims_for(int(idx))
            rng = self._rng(int(idx))
            l, t, cw, ch = _random_resized_crop_box(w, h, rng)
            boxes[j] = (l, t, cw, ch, 1 if rng.random() < 0.5 else 0)
        return boxes

    def decode(self, indices: np.ndarray, out: np.ndarray | None = None,
               output: str = "f32") -> np.ndarray:
        """Decode ``indices`` -> (n, S, S, 3).

        ``output="f32"`` yields ImageNet-normalized float32 (the classic
        contract); ``"uint8"`` yields raw pixels — 4x smaller to ship to
        the device, where the train step normalizes (train/step.py).
        """
        if output not in ("f32", "uint8"):
            raise ValueError(f"unknown output {output!r}")
        dtype = np.float32 if output == "f32" else np.uint8
        mode = 0 if output == "f32" else 2
        indices = np.asarray(indices).reshape(-1)
        n, S = len(indices), self.image_size
        if out is None:
            out = np.empty((n, S, S, 3), dtype)
        assert out.shape == (n, S, S, 3) and out.dtype == dtype
        if self._native is None:
            self._pil_many(indices, range(len(indices)), out)
            return out
        boxes = self.sample_boxes(indices)
        paths = [os.fsencode(self.paths[int(i)]) for i in indices]
        failed = self._native.decode_batch(paths, boxes, out, S,
                                           self.threads, mode,
                                           self.max_denom)
        # anything libjpeg rejected (PNG/webp/CMYK/truncated) decodes via
        # PIL — threaded, so a mostly-non-JPEG dataset keeps its decode
        # parallelism instead of collapsing to a serial loop
        self._pil_many(indices, failed, out)
        return out

    def _pil_many(self, indices: np.ndarray, slots: tp.Iterable[int],
                  out: np.ndarray) -> None:
        slots = list(slots)
        if len(slots) <= 1 or self.threads == 1:
            for j in slots:
                out[j] = self._pil_one(int(indices[j]), out.dtype)
            return
        import concurrent.futures

        with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(self.threads, len(slots))) as pool:
            for j, img in zip(slots, pool.map(
                    lambda j: self._pil_one(int(indices[j]), out.dtype),
                    slots)):
                out[j] = img

    def _pil_one(self, idx: int, dtype=np.float32) -> np.ndarray:
        return load_image(self.paths[idx], self.image_size, self.train,
                          self._rng(idx) if self.train else None,
                          raw=(dtype == np.uint8))
