"""Input pipeline: sharded samplers and loaders for decentralized DP.

Mirrors the reference's data plumbing (gossip_sgd.py:539-583):

* :class:`DistributedSampler` — same contract as
  ``torch.utils.data.distributed.DistributedSampler``: per-epoch seeded
  shuffle (``set_epoch``, seeded ``epoch + seed*90`` by the caller,
  gossip_sgd.py:289), padding to a multiple of world size, strided shard
  per rank.
* :class:`ShardedLoader` — batches every rank's shard and stacks them into
  one global ``(world, per_rank_batch, ...)`` array, the layout the sharded
  train step consumes.  Under multi-host execution each process constructs
  it with ``ranks=`` (its ``parallel.multihost.owned_batch_rows``) and gets only
  its local rows, ready for ``jax.make_array_from_process_local_data``.
  ``fast_forward`` reproduces the reference's checkpoint-resume sampler
  spoofing (gossip_sgd.py:356-364) without loading and discarding data.
* :func:`synthetic_classification` — a deterministic, learnable synthetic
  dataset (class-dependent means + noise) used by smoke tests and
  benchmarks; the reference has no equivalent (its only testing affordance
  is early-exit, SURVEY.md §4).
* :func:`imagefolder_arrays` — eager ImageNet-style directory loading
  (PIL decode, see imagefolder.py) for accuracy-parity runs.
"""

from __future__ import annotations

import typing as tp

import numpy as np

__all__ = ["DistributedSampler", "ShardedLoader",
           "synthetic_classification", "imagefolder_arrays"]


class DistributedSampler:
    """Deterministic per-rank index sampler.

    Same semantics as torch's ``DistributedSampler(shuffle=True)``: shuffle
    ``range(n)`` with ``seed = epoch`` (callers pass ``epoch + seed*90``
    like gossip_sgd.py:289), pad by wrapping so every rank gets
    ``ceil(n / world)`` samples, then stride by rank.
    """

    def __init__(self, dataset_len: int, world_size: int, rank: int | None = None):
        if dataset_len < 1:
            raise ValueError("dataset_len must be >= 1")
        self.n = int(dataset_len)
        self.world_size = int(world_size)
        self.rank = rank
        self.epoch = 0
        self.num_samples = -(-self.n // self.world_size)  # ceil
        self.total_size = self.num_samples * self.world_size

    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)

    def indices_for_rank(self, rank: int | None = None) -> np.ndarray:
        rank = self.rank if rank is None else rank
        if rank is None:
            raise ValueError("no rank given and none set at construction")
        g = np.random.default_rng(self.epoch)
        idx = g.permutation(self.n)
        if self.total_size > self.n:
            idx = np.concatenate([idx, idx[: self.total_size - self.n]])
        return idx[rank::self.world_size]

    def all_indices(self) -> np.ndarray:
        """(world_size, num_samples) index table for stacked loading."""
        return np.stack([self.indices_for_rank(r)
                         for r in range(self.world_size)])


class ShardedLoader:
    """Iterates global batches stacked over the world dimension.

    Yields ``(images, labels)`` with shapes ``(world, batch, ...)`` /
    ``(world, batch)`` — ready for a ``P('gossip')``-sharded train step.
    Incomplete trailing batches are dropped (torch drops them per-rank when
    ``drop_last``; with the stacked layout a ragged last batch would change
    shapes and trigger recompilation, so dropping is the XLA-friendly
    default).
    """

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 batch_size: int, sampler: DistributedSampler,
                 ranks: tp.Sequence[int] | None = None):
        if len(images) != len(labels):
            raise ValueError("images and labels length mismatch")
        self.images = images
        self.labels = labels
        self.batch_size = int(batch_size)
        self.sampler = sampler
        self.ranks = None if ranks is None else list(ranks)
        self.start_itr = 0

    def __len__(self) -> int:
        return self.sampler.num_samples // self.batch_size

    def fast_forward(self, itr: int) -> None:
        """Resume mid-epoch: skip the first ``itr`` batches
        (≙ the sampler spoof at gossip_sgd.py:356-364)."""
        self.start_itr = int(itr)

    def __iter__(self):
        table = self.sampler.all_indices()
        if self.ranks is not None:
            table = table[self.ranks]
        n_batches = len(self)
        for b in range(self.start_itr, n_batches):
            sel = table[:, b * self.batch_size:(b + 1) * self.batch_size]
            yield self.images[sel], self.labels[sel]
        self.start_itr = 0


def synthetic_classification(n: int, num_classes: int = 10,
                             image_size: int = 16, channels: int = 3,
                             seed: int = 0, noise: float = 0.5,
                             dtype=np.float32) -> tuple[np.ndarray, np.ndarray]:
    """Learnable synthetic image classification data.

    Each class has a fixed random mean image; samples are mean + noise, so a
    small model can fit them and smoke tests can assert loss decrease.
    ``noise`` sets the per-pixel noise scale (class means have scale 1.0) —
    raise it to make the task genuinely hard for convergence studies.
    """
    g = np.random.default_rng(seed)
    means = g.normal(scale=1.0,
                     size=(num_classes, image_size, image_size, channels))
    labels = g.integers(0, num_classes, size=(n,))
    images = means[labels] + g.normal(
        scale=noise, size=(n, image_size, image_size, channels))
    return images.astype(dtype), labels.astype(np.int32)


def translated_patch_classification(
        n: int, num_classes: int = 16, image_size: int = 24,
        patch_size: int = 8, channels: int = 3, seed: int = 0,
        noise: float = 1.0, dtype=np.float32
        ) -> tuple[np.ndarray, np.ndarray]:
    """Harder synthetic task for non-toy convergence studies.

    Each class is a fixed random ``patch_size``² pattern placed at a
    RANDOM position on a noise background, so the label is not linearly
    separable in pixel space — a model must learn translation-robust
    (convolutional) features, unlike :func:`synthetic_classification`
    whose class means a linear probe separates.  Used by
    examples/convergence_resnet.py for the D3-style acceptance
    methodology (BASELINE.md) on ResNet-18.
    """
    g = np.random.default_rng(seed)
    patches = g.normal(scale=1.5,
                       size=(num_classes, patch_size, patch_size, channels))
    labels = g.integers(0, num_classes, size=(n,))
    images = g.normal(scale=noise,
                      size=(n, image_size, image_size, channels))
    span = image_size - patch_size + 1
    rows = g.integers(0, span, size=(n,))
    cols = g.integers(0, span, size=(n,))
    for i in range(n):
        images[i, rows[i]:rows[i] + patch_size,
               cols[i]:cols[i] + patch_size] += patches[labels[i]]
    return images.astype(dtype), labels.astype(np.int32)


def imagefolder_arrays(root: str, split: str, image_size: int = 224,
                       train: bool = True,
                       limit: int | None = None) -> tuple[np.ndarray, np.ndarray]:
    """Eagerly load an ImageNet-style folder (PIL decode, no torchvision).

    Transform parity with gossip_sgd.py:546-581: train = RandomResizedCrop +
    horizontal flip; val = Resize(256·size/224) + CenterCrop; both
    normalized with the ImageNet mean/std.  Returns NHWC float32 arrays.

    Intended for validation sets and smoke runs; use
    :class:`~.streaming.StreamingImageFolder` for large training sets.
    """
    from .imagefolder import ImageFolderDataset

    ds = ImageFolderDataset(f"{root}/{split}" if split else root,
                            image_size=image_size, train=train)
    idx = np.arange(len(ds))
    if limit is not None and limit < len(ds):
        # directory order is class-grouped; subsample uniformly so a
        # limited load still covers all classes instead of the first few
        idx = np.linspace(0, len(ds) - 1, limit).astype(np.int64)
    images = np.stack([ds[int(i)][0] for i in idx])
    labels = ds.labels[idx]
    return images.astype(np.float32), labels.astype(np.int32)
