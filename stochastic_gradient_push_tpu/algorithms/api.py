"""Decentralized-averaging algorithms as pure state transforms.

The reference wraps models in stateful ``nn.Module`` subclasses whose behavior
is spread across forward-pre hooks, backward hooks, a background gossip
thread, and bias/de-bias flags (``GossipDataParallel``, distributed.py:39-589;
``BilatGossipDataParallel``, ad_psgd.py:36-418).  Here each algorithm is four
pure functions over an explicit :class:`GossipState`, slotted into the train
step at fixed points:

```
params, gstate = alg.pre_step(params, gstate)        # overlap: LAUNCH round t
z              = alg.eval_params(params, gstate)     # de-biased params for fwd
grads          = alg.reduce_grads(grads)             # exact averaging (AR/local)
params, gstate = alg.post_step(params, gstate)       # sync: gossip round;
                                                     # overlap: consume round
                                                     # t−staleness+1
```

This is the hook dance of distributed.py:512-589 made explicit: ``pre_step``
≙ the forward-pre hook's ``transfer_params`` (overlap launches at the top of
the step so the collective hides behind backprop), ``eval_params`` ≙
``unbias`` (distributed.py:307-314), ``reduce_grads`` ≙ the backward hook's
intra-node reduction (distributed.py:520-562), ``post_step`` ≙ the gossip
thread's ``mix`` / ``_query_gossip_queue`` consume (distributed.py:336-434,
459-510).  The ``is_ps_numerator`` flag, heartbeat timeouts, poison values,
and lock protocol all disappear: state is explicit and the collective is
part of the compiled step.
"""

from __future__ import annotations

import typing as tp

import flax.struct
import jax.numpy as jnp

Params = tp.Any  # arbitrary pytree of arrays


@flax.struct.dataclass
class GossipState:
    """Per-rank algorithm state carried through the train step.

    Attributes:
      phase: int32 rotation counter — replaces ``GraphManager``'s mutable
        ``_group_indices`` (graph_manager.py:128-133).
      ps_weight: float32 scalar push-sum weight (distributed.py:134-136).
        Stays exactly 1.0 for synchronous regular mixing; deviates between
        launch and consume in overlap mode.
      in_flight: pytree of pending peer contributions (overlap mode), the
        compiled analogue of the gossip thread's receive buffer
        (distributed.py:149-155); ``None`` for synchronous algorithms.
      ef_residual: params-shaped pytree of pending quantization error
        (error-feedback wire compression, parallel/wire.py): round t's
        residual is re-injected into round t+1's send so compression
        noise stays a bounded perturbation of the network mean instead
        of a bias.  ``None`` unless the algorithm runs a lossy wire
        codec with ``error_feedback=True``.
    """

    phase: jnp.ndarray
    ps_weight: jnp.ndarray
    in_flight: tp.Any = None
    ef_residual: tp.Any = None


class GossipAlgorithm:
    """Base algorithm: exact data parallelism (no gossip).

    Subclasses override the four slots.  The base class doubles as the
    AllReduce baseline when constructed via :func:`~.algorithms.all_reduce`.
    """

    name: str = "base"

    def init(self, params: Params) -> GossipState:
        del params
        return GossipState(phase=jnp.int32(0), ps_weight=jnp.float32(1.0))

    def pre_step(self, params: Params, state: GossipState
                 ) -> tuple[Params, GossipState]:
        return params, state

    def eval_params(self, params: Params, state: GossipState) -> Params:
        """De-biased parameter estimate used for forward/eval
        (≙ ``unbias``, distributed.py:307-314)."""
        return params

    def val_params(self, params: Params, state: GossipState) -> Params:
        """Parameters for VALIDATION/metrics.  Like :meth:`eval_params`,
        but overlap algorithms additionally DRAIN in-flight gossip the
        way the reference's ``model.eval()`` does before validating
        (``_query_gossip_queue`` final drain, distributed.py:322-327) —
        the training trajectory never sees this; it is an eval-time
        view.  Default: identical to ``eval_params``."""
        return self.eval_params(params, state)

    def reduce_grads(self, grads: Params) -> Params:
        return grads

    def post_step(self, params: Params, state: GossipState
                  ) -> tuple[Params, GossipState]:
        return params, state
