"""The five training algorithms: AR, SGP, OSGP, D-PSGD, AD-PSGD.

Selection matrix (mirrors the reference CLI semantics, gossip_sgd.py:179-190):

| reference flags                    | here                          |
|------------------------------------|-------------------------------|
| ``--all_reduce True``              | :func:`all_reduce`            |
| ``--push_sum True``                | :func:`sgp` (overlap=False)   |
| ``--push_sum True --overlap True`` | :func:`sgp` (overlap=True)    |
| ``--push_sum False``               | :func:`dpsgd`                 |
| ``gossip_sgd_adpsgd.py``           | :func:`adpsgd`                |
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel import collectives
from ..parallel.collectives import as_scalar
from ..topology.schedule import GossipSchedule
from .api import GossipAlgorithm, GossipState, Params

__all__ = ["all_reduce", "sgp", "osgp", "dpsgd", "adpsgd",
           "AllReduce", "PushSumGossip", "PushPullGossip", "BilateralGossip"]


class AllReduce(GossipAlgorithm):
    """Exact AllReduce-SGD baseline (≙ DistributedDataParallel,
    gossip_sgd.py:179-180): average gradients with ``psum`` every step."""

    name = "ar"

    def __init__(self, axis_name: str):
        self.axis_name = axis_name

    def reduce_grads(self, grads: Params) -> Params:
        return collectives.allreduce_mean(grads, self.axis_name)


class PushSumGossip(GossipAlgorithm):
    """Stochastic Gradient Push — synchronous or overlap (SGP / OSGP).

    Synchronous (overlap=False, ≙ ``GossipDataParallel(push_sum=True,
    overlap=False)``): after the optimizer step, run one complete push-sum
    round — parameters and push-sum weight mixed jointly
    (distributed.py:389-434 + gossiper.py:176-219 collapsed into one
    collective).

    Overlap (overlap=True, ≙ OSGP, distributed.py:571-588): ``post_step``
    keeps only the local share ``lo·x`` and stores the peers' contributions
    in ``state.in_flight``; ``pre_step`` of a *later* iteration adds them —
    the same staleness the reference gets from its gossip thread, except
    the "thread" is XLA's collective scheduler overlapping the ppermute
    with backprop compute.

    ``staleness`` bounds how many steps an incoming share may ride in
    flight (≙ ``synch_freq``: the reference polls non-blocking for up to N
    steps before forcing a wait, distributed.py:127-129, :578, so its max
    staleness is ``synch_freq+1``; here the bound is exact rather than
    comm-speed-dependent).  ``in_flight`` becomes a FIFO of ``staleness``
    slots: ``pre_step`` consumes the oldest, ``post_step`` appends the
    round just launched.  Memory cost: ``staleness`` extra parameter
    copies.  Every launched share is consumed exactly once, so push-sum
    mass conservation is preserved for any staleness.

    ``wire`` (a :class:`~..parallel.wire.WireCodec`) compresses gossip
    payloads on the ppermute boundary — bf16 or per-block int8; the
    push-sum weight lane always ships exact f32.  ``error_feedback``
    adds the per-rank residual accumulator (``GossipState.ef_residual``)
    that re-injects each round's quantization error into the next send,
    bounding the compression perturbation (parallel/collectives.py
    module docstring).  Synchronous mode only; composes with
    ``gossip_every`` thinning (the residual waits out non-firing steps),
    with fault injection (dropped edges carry their residual), and with
    hierarchical schedules (the codec rides the delegate DCN lane; the
    intra-slice psum stays exact).  The residual deliberately SURVIVES
    exact global averages: it is sender-local pending correction, and
    re-injecting it later loses nothing the average computed.

    ``global_avg_every`` interleaves an *exact* global average every k-th
    step (periodic global averaging, Chen et al.): after the gossip
    round, ``x ← Σ x / Σ w`` via one allreduce and the push-sum weight
    resets to 1.  The consensus value of push-sum is exactly that ratio,
    so the operation preserves the mean for any mixing (uniform or
    irregular) while snapping all ranks to consensus — the planner's
    recovery for topologies whose spectral gap is below the floor at the
    requested world size.  Synchronous mode only (an in-flight overlap
    share would be double-counted by the average).
    """

    name = "sgp"

    def __init__(self, schedule: GossipSchedule, axis_name: str,
                 overlap: bool = False, track_weight: bool = True,
                 gossip_every: int = 1, comm_dtype=None,
                 staleness: int = 1, global_avg_every: int = 0,
                 faults=None, wire=None, error_feedback: bool = False):
        self.schedule = schedule
        self.axis_name = axis_name
        self.overlap = overlap
        from ..topology.hierarchical import HierarchicalSchedule

        if isinstance(schedule, HierarchicalSchedule):
            # two-level rounds compile to leader ppermute + grouped psum
            # (collectives._hier_round_fn); neither the overlap split nor
            # per-edge fault masks decompose across that psum
            if overlap:
                raise ValueError(
                    "overlap mode is not supported on hierarchical "
                    "schedules: the intra-slice exact average cannot be "
                    "deferred as an in-flight share")
            if faults is not None:
                raise ValueError(
                    "inject_faults is not supported on hierarchical "
                    "schedules: the intra-slice psum has no per-edge "
                    "mask (use a flat topology for fault drills)")
        # deterministic fault injection (resilience/faults.py FaultMasks):
        # the mixing boundary applies the plan's keep/corrupt masks with
        # mass-conserving reabsorption.  Synchronous mode only — an
        # overlap share launched under one fault state and consumed under
        # another would decouple the mask from the wire it describes.
        if faults is not None and overlap:
            raise ValueError(
                "inject_faults is a synchronous-mode feature: overlap "
                "in-flight shares would straddle fault windows")
        if faults is not None and faults.gossip_every != gossip_every:
            # phase-dependent masks are resolved against the rotation
            # actually active at each tick, which depends on thinning
            raise ValueError(
                f"fault masks were compiled for gossip_every="
                f"{faults.gossip_every} but the algorithm runs "
                f"gossip_every={gossip_every}; rebuild the masks with "
                "the matching thinning factor")
        self.faults = faults
        if staleness < 1:
            raise ValueError("staleness must be >= 1")
        if staleness > 1 and not overlap:
            raise ValueError("staleness is an overlap-mode knob")
        self.staleness = staleness
        # push-pull (D-PSGD) reuses this machinery with no ps-weight
        self.track_weight = track_weight
        # communication thinning: gossip on every k-th step only (the
        # compiled counterpart of the reference's synch_freq intent —
        # fewer communications per optimization step)
        if gossip_every < 1:
            raise ValueError("gossip_every must be >= 1")
        if gossip_every > 1 and overlap:
            raise ValueError(
                "gossip_every > 1 is a synchronous-mode knob; overlap "
                "already hides the collective behind compute")
        self.gossip_every = gossip_every
        # periodic exact global averaging every k-th step (0 = off);
        # see the class docstring
        if global_avg_every < 0:
            raise ValueError("global_avg_every must be >= 0")
        if global_avg_every and overlap:
            raise ValueError(
                "global_avg_every is a synchronous-mode knob: averaging "
                "around in-flight overlap shares would double-count them")
        self.global_avg_every = global_avg_every
        # wire codec for gossip payloads (parallel/wire.py); comm_dtype
        # is the deprecated bf16-only alias — both resolve to one codec,
        # and a lossless codec compiles to the uncompressed path
        from ..parallel import wire as wire_mod

        if wire is not None and comm_dtype is not None:
            raise ValueError("pass either wire (a WireCodec) or the "
                             "deprecated comm_dtype, not both")
        if wire is None and comm_dtype is not None:
            wire = wire_mod.from_comm_dtype(comm_dtype)
        self.wire = wire
        self.comm_dtype = comm_dtype  # kept for introspection only
        # per-rank error-feedback residual accumulators (wire.py module
        # docstring): quantization error from round t re-injected into
        # round t+1's send — requires a lossy codec to have any error,
        # and synchronous mode (an overlap in-flight share would
        # straddle residual windows the same way it straddles faults)
        if error_feedback:
            if wire is None or not wire.lossy:
                raise ValueError(
                    "error_feedback needs a lossy wire codec "
                    "(wire_dtype bf16/int8); exact wires have no "
                    "quantization error to feed back")
            if overlap:
                raise ValueError(
                    "error_feedback is a synchronous-mode feature: "
                    "overlap in-flight shares would straddle residual "
                    "windows")
            if not track_weight:
                raise ValueError(
                    "error_feedback rides the push-sum wire "
                    "(track_weight=True); the push-pull path carries "
                    "no residual state")
        self.error_feedback = bool(error_feedback)

    # -- helpers -----------------------------------------------------------

    def _zeros_like_params(self, params: Params):
        return jax.tree.map(jnp.zeros_like, params)

    def _mix(self, params, ps_weight, phase, tick=None, residual=None):
        """One wire round; returns ``(params, ps_weight, residual)`` —
        residual is None unless error feedback is active."""
        if self.track_weight:
            out = collectives.mix_push_sum(
                params, ps_weight, phase, self.schedule, self.axis_name,
                codec=self.wire, faults=self.faults, tick=tick,
                ef_residual=residual)
            if residual is None:
                return out[0], out[1], None
            return out
        return (collectives.mix_push_pull(
            params, phase, self.schedule, self.axis_name,
            codec=self.wire), ps_weight, None)

    def _split_round(self, params, ps_weight, phase):
        """One round split into (local share, incoming share).

        local = lo·x; incoming = Σ_i w_i·ppermute(x) — their sum is exactly
        the synchronous round, so overlap mode differs from sync only in
        *when* the incoming share is applied.
        """
        tree = (params, ps_weight)
        mixed = collectives.gossip_round(
            tree, phase, self.schedule, self.axis_name,
            codec=self.wire)
        # local share is a cheap rescale; recover incoming by subtraction
        # would lose precision — instead compute local share directly and
        # subtract from the mixed total.
        num_phases = self.schedule.num_phases
        lo_table = jnp.asarray(self.schedule.self_weight, jnp.float32)
        my_rank = jax.lax.axis_index(self.axis_name)
        lo = lo_table[as_scalar(phase) % num_phases, my_rank]
        local = jax.tree.map(lambda a: a * lo.astype(a.dtype), tree)
        incoming = jax.tree.map(jnp.subtract, mixed, local)
        return local, incoming

    # -- algorithm slots ---------------------------------------------------

    def init(self, params: Params) -> GossipState:
        state = GossipState(phase=jnp.int32(0), ps_weight=jnp.float32(1.0))
        if self.error_feedback:
            # pending quantization error starts at zero; the structure
            # mirrors params (the compressed lanes), never the ps-weight
            state = state.replace(
                ef_residual=self._zeros_like_params(params))
        if self.overlap:
            # FIFO of `staleness` (params, weight) slots, each holding one
            # round's incoming share.  A tuple of slots (static pytree
            # structure) rather than a stacked axis keeps the algorithm
            # agnostic to how callers batch/shard the state leaves.
            slot = lambda: (self._zeros_like_params(params),
                            jnp.float32(0.0))
            state = state.replace(
                in_flight=tuple(slot() for _ in range(self.staleness)))
        return state

    def pre_step(self, params, state):
        if not self.overlap:
            return params, state
        # consume the OLDEST in-flight round (≙ _query_gossip_queue,
        # distributed.py:336-387: p += r; ps_weight += gossip_ps_weight),
        # then shift the FIFO; post_step fills the freed last slot
        in_params, in_w = state.in_flight[0]
        params = jax.tree.map(lambda p, b: p + b.astype(p.dtype),
                              params, in_params)
        ps_weight = state.ps_weight + jnp.reshape(
            in_w, jnp.shape(state.ps_weight))
        empty = (self._zeros_like_params(in_params),
                 jnp.zeros_like(in_w))
        in_flight = state.in_flight[1:] + (empty,)
        return params, state.replace(ps_weight=ps_weight,
                                     in_flight=in_flight)

    def eval_params(self, params, state):
        if not self.track_weight:
            return params
        w = as_scalar(state.ps_weight)
        return jax.tree.map(lambda p: p / w.astype(p.dtype), params)

    def val_params(self, params, state):
        """Validation view: drain every in-flight share first (≙ the
        reference's ``model.eval()`` blocking drain before validation,
        distributed.py:322-327), then de-bias.  At staleness 1 this
        makes OSGP validation numerically IDENTICAL to sync SGP — the
        local+incoming split is exact, so between-step params differ
        from the synchronous trajectory only by the not-yet-applied
        incoming share this method adds back.  The training state is
        untouched (pure eval-time view)."""
        if not self.overlap:
            return self.eval_params(params, state)
        ps_weight = state.ps_weight
        for in_p, in_w in state.in_flight:
            params = jax.tree.map(lambda p, b: p + b.astype(p.dtype),
                                  params, in_p)
            ps_weight = ps_weight + jnp.reshape(in_w,
                                                jnp.shape(ps_weight))
        if not self.track_weight:
            return params
        w = as_scalar(ps_weight)
        return jax.tree.map(lambda p: p / w.astype(p.dtype), params)

    def post_step(self, params, state):
        phase = state.phase
        if not self.overlap:
            if self.gossip_every > 1:
                return self._thinned_post_step(params, state)
            params, ps_weight, residual = self._mix(
                params, state.ps_weight, phase,
                residual=state.ef_residual)
            ps_weight = jnp.reshape(jnp.asarray(ps_weight, jnp.float32),
                                    jnp.shape(state.ps_weight))
            params, ps_weight = self._maybe_global_average(
                params, ps_weight, phase + 1)
            return params, state.replace(phase=phase + 1,
                                         ps_weight=ps_weight,
                                         ef_residual=residual)
        # overlap: keep local share now, stash incoming for next pre_step
        (local_p, local_w), incoming = self._split_round(
            params, state.ps_weight, phase)
        return self._finish_overlap(local_p, local_w, incoming, state,
                                    phase)

    def _thinned_post_step(self, params, state):
        """Gossip on every ``gossip_every``-th call; the rotation phase
        advances only when a round actually fires, so the graph cycles
        through the same peer sequence as un-thinned gossip."""
        tick = collectives.as_scalar(state.phase)
        fire = (tick % self.gossip_every) == 0
        rotation = tick // self.gossip_every

        def mix_branch(operand):
            p, w, r = operand
            # faults are indexed by the step clock (tick), not the slower
            # rotation counter — a fault window means wall steps
            p, w, r = self._mix(p, w, rotation, tick=tick, residual=r)
            return (p, jnp.reshape(jnp.asarray(w, jnp.float32),
                                   jnp.shape(state.ps_weight)), r)

        # on non-firing steps the residual rides through unchanged —
        # pending error waits for the next wire round
        params, ps_weight, residual = jax.lax.cond(
            fire, mix_branch, lambda o: o,
            (params, state.ps_weight, state.ef_residual))
        params, ps_weight = self._maybe_global_average(
            params, ps_weight, tick + 1)
        return params, state.replace(phase=state.phase + 1,
                                     ps_weight=ps_weight,
                                     ef_residual=residual)

    def global_average(self, params, ps_weight):
        """Exact push-sum consensus NOW: ``x ← Σ params / Σ ps_weight``
        (one allreduce) and the weight resets to 1.  Mass conservation
        makes that ratio the true parameter average under any
        column-stochastic mixing — including faulted mixing with
        mass-conserving drops — so the trajectory mean is untouched while
        consensus error snaps to zero.  Called per-rank inside
        shard_map; the periodic schedule (:meth:`_maybe_global_average`)
        and the resilience recovery path (resilience/recovery.py) both
        route through here."""
        tot_p, tot_w = collectives.allreduce_sum((params, ps_weight),
                                                 self.axis_name)
        tw = as_scalar(tot_w)
        params = jax.tree.map(lambda a: (a / tw.astype(a.dtype)), tot_p)
        return params, jnp.ones_like(ps_weight)

    def _maybe_global_average(self, params, ps_weight, tick_next):
        """Every ``global_avg_every`` steps: fire :meth:`global_average`
        (periodic global averaging, Chen et al.)."""
        if self.global_avg_every <= 0:
            return params, ps_weight
        fire = (as_scalar(tick_next) % self.global_avg_every) == 0

        def avg_branch(operand):
            return self.global_average(*operand)

        return jax.lax.cond(fire, avg_branch, lambda o: o,
                            (params, ps_weight))

    def _finish_overlap(self, local_p, local_w, incoming, state, phase):
        local_w = jnp.reshape(jnp.asarray(local_w, jnp.float32),
                              jnp.shape(state.ps_weight))
        # the just-launched round takes the FIFO's freed last slot
        in_flight = state.in_flight[:-1] + (incoming,)
        return local_p, state.replace(phase=phase + 1,
                                      ps_weight=local_w,
                                      in_flight=in_flight)


class PushPullGossip(PushSumGossip):
    """D-PSGD: doubly-stochastic gossip
    (≙ ``GossipDataParallel(push_sum=False)`` → ``PushPull.mix``,
    gossiper.py:222-275).

    Synchronous mode needs no push-sum weight: a complete doubly-stochastic
    round preserves the mean directly.  Overlap mode *must* track it — the
    parameters are scaled by ``lo`` between launching a round and consuming
    it, and the de-bias division is what keeps gradients evaluated at the
    right point (the reference's ps-weight machinery likewise stays active
    for PushPull, gossiper.py:160-169 with distributed.py:298-314).
    """

    name = "dpsgd"

    def __init__(self, schedule: GossipSchedule, axis_name: str,
                 overlap: bool = False, staleness: int = 1,
                 global_avg_every: int = 0, faults=None):
        if not schedule.regular:
            raise ValueError("D-PSGD requires a regular schedule "
                             "(doubly-stochastic mixing)")
        if faults is not None:
            # a dropped edge breaks ROW-stochasticity even with sender
            # reabsorption, and without a ps-weight there is no mass
            # accounting to absorb the asymmetry — the exact failure mode
            # push-sum exists to survive (Assran et al. 2018, §1)
            raise ValueError(
                "inject_faults requires push-sum: D-PSGD's "
                "doubly-stochastic invariant does not survive dropped "
                "edges (use --push_sum True)")
        super().__init__(schedule, axis_name, overlap=overlap,
                         track_weight=overlap, staleness=staleness,
                         global_avg_every=global_avg_every)


class BilateralGossip(GossipAlgorithm):
    """AD-PSGD in its synchronous perfect-matching formulation.

    The reference runs bilateral averaging in a separate OS process with its
    own optimizer, shipping gradients through shared memory
    (ad_psgd.py:120-133, 252-366) — host-side asynchrony that cannot (and
    should not) live inside one SPMD program.  The TPU-native counterpart:
    every step, each rank averages parameters with one rotating partner,
    ``x ← (x + x_partner)/2`` (≙ ad_psgd.py:358-361), with the matching
    schedule derived from the same communication graph.  See SURVEY.md §7
    "Hard parts" #4 for the staleness-distribution caveat.
    """

    name = "adpsgd"

    def __init__(self, pairing: np.ndarray, axis_name: str):
        self.pairing = pairing
        self.axis_name = axis_name

    def post_step(self, params, state):
        params = collectives.mix_bilat(
            params, state.phase, self.pairing, self.axis_name)
        return params, state.replace(phase=state.phase + 1)


# -- factory helpers matching the reference's flag surface -------------------

def all_reduce(axis_name: str) -> AllReduce:
    return AllReduce(axis_name)


def sgp(schedule: GossipSchedule, axis_name: str,
        overlap: bool = False, gossip_every: int = 1,
        comm_dtype=None, staleness: int = 1,
        global_avg_every: int = 0, faults=None, wire=None,
        error_feedback: bool = False) -> PushSumGossip:
    return PushSumGossip(schedule, axis_name, overlap=overlap,
                         gossip_every=gossip_every, comm_dtype=comm_dtype,
                         staleness=staleness,
                         global_avg_every=global_avg_every, faults=faults,
                         wire=wire, error_feedback=error_feedback)


def osgp(schedule: GossipSchedule, axis_name: str,
         staleness: int = 1) -> PushSumGossip:
    return PushSumGossip(schedule, axis_name, overlap=True,
                         staleness=staleness)


def dpsgd(schedule: GossipSchedule, axis_name: str,
          overlap: bool = False, staleness: int = 1,
          global_avg_every: int = 0, faults=None) -> PushPullGossip:
    return PushPullGossip(schedule, axis_name, overlap=overlap,
                          staleness=staleness,
                          global_avg_every=global_avg_every, faults=faults)


def adpsgd(pairing: np.ndarray, axis_name: str) -> BilateralGossip:
    return BilateralGossip(pairing, axis_name)
